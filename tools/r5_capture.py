"""Round-5 on-chip capture sequence — run when the tunnel is healthy.

Runs each pending on-chip measurement as its OWN subprocess (the
single-client tunnel tolerates exactly one attached process at a time;
a fresh process per phase also keeps one phase's wedge from losing the
others), in priority order, committing artifacts as it goes:

  1. bench.py                 -> BENCH line incl. hll_groupby_p50_ms
  2. hll_northstar -paths     -> ladder rows/s (sort lowering) + aux
  3. filter_matrix            -> FILTER_MATRIX_r5.json
  4. serving_curve            -> SERVING_CURVE_TPU_r5.json

Each phase gets a deadline; on timeout/failure the runner records the
failure and moves on (a wedge mid-sequence still leaves the earlier
artifacts on disk).  Usage:  python tools/r5_capture.py [--skip N ...]
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PHASES = [
    {
        "name": "bench",
        "cmd": [sys.executable, "bench.py"],
        "deadline_s": 2500,
        "log": "/tmp/r5cap_bench.log",
    },
    {
        "name": "northstar",
        "cmd": [
            sys.executable,
            "-m",
            "pinot_tpu.tools.hll_northstar",
            "-rows",
            "134217728",
            "-paths",
            "-out",
            os.path.join(REPO, "NORTHSTAR_HLL_r5.json"),
        ],
        "deadline_s": 3600,
        "log": "/tmp/r5cap_northstar.log",
    },
    {
        "name": "filter_matrix",
        "cmd": [
            sys.executable,
            "-m",
            "pinot_tpu.tools.filter_matrix",
            "-out",
            os.path.join(REPO, "FILTER_MATRIX_r5.json"),
        ],
        "deadline_s": 3600,
        "log": "/tmp/r5cap_matrix.log",
    },
    {
        "name": "serving_curve",
        "cmd": [
            sys.executable,
            "-m",
            "pinot_tpu.tools.serving_curve",
            "-qps",
            "1,2,4,8,16,32",
            "-duration",
            "20",
            "-out",
            os.path.join(REPO, "SERVING_CURVE_TPU_r5.json"),
        ],
        "deadline_s": 3600,
        "log": "/tmp/r5cap_curve.log",
    },
]


def main() -> None:
    skip = set()
    args = sys.argv[1:]
    if args and args[0] == "--skip":
        skip = set(args[1:])
    manifest = []
    for phase in PHASES:
        if phase["name"] in skip:
            continue
        t0 = time.time()
        print(f"== {phase['name']} (deadline {phase['deadline_s']}s)", flush=True)
        with open(phase["log"], "w") as log:
            proc = subprocess.Popen(
                phase["cmd"], cwd=REPO, stdout=log, stderr=subprocess.STDOUT
            )
            try:
                rc = proc.wait(timeout=phase["deadline_s"])
            except subprocess.TimeoutExpired:
                # NEVER SIGKILL a chip-attached process (a kill mid-
                # transfer wedges the single-client tunnel lease for
                # hours) — SIGTERM and wait patiently
                proc.terminate()
                try:
                    rc = proc.wait(timeout=300)
                except subprocess.TimeoutExpired:
                    print(
                        f"!! {phase['name']} ignored SIGTERM; leaving it to "
                        "exit on its own (no SIGKILL near the tunnel)",
                        flush=True,
                    )
                    rc = -2
                else:
                    rc = -1
        entry = {
            "phase": phase["name"],
            "rc": rc,
            "seconds": round(time.time() - t0, 1),
            "log": phase["log"],
        }
        manifest.append(entry)
        print(json.dumps(entry), flush=True)
        if rc == -2:
            # the stuck process may still hold the single-client tunnel;
            # a next phase would silently fall back to CPU — stop here
            print("!! aborting sequence: previous phase still running", flush=True)
            break
        if rc != 0:
            print(f"!! {phase['name']} failed (rc={rc}); continuing", flush=True)
    with open("/tmp/r5cap_manifest.json", "w") as f:
        json.dump(manifest, f, indent=1)


if __name__ == "__main__":
    main()
