"""Sweep: why is the factored one-hot contraction 25x off its FLOP floor?

Variants at K=16384, N=134M:
  - chunk size sweep
  - unrolled scan
  - vmap-then-sum instead of scan
  - one_hot on the K1 axis directly (no transpose in dot)
  - presence without weight multiply
  - m1 contraction K sweep (512..16384) to find the cliff
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

N = 1 << 27


def _fetch(out):
    leaf = out
    while isinstance(leaf, (tuple, list)):
        leaf = leaf[0]
    np.asarray(leaf.ravel()[:1])


def timeit(fn, *args, iters=3):
    _fetch(fn(*args))
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        _fetch(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def report(name, secs):
    print(json.dumps({"probe": name, "ms": round(secs * 1e3, 2), "ns_per_row": round(secs / N * 1e9, 3)}), flush=True)


def factored(idx, K, chunk, dtype, unroll=1):
    K1 = K // 128
    nb = idx.shape[0] // chunk

    def body(acc, b):
        i_c = jax.lax.dynamic_slice_in_dim(idx, b * chunk, chunk)
        hi = jax.nn.one_hot(i_c // 128, K1, dtype=dtype)
        lo = jax.nn.one_hot(i_c % 128, 128, dtype=dtype)
        acc = acc + jax.lax.dot_general(
            hi, lo, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc, None

    acc, _ = jax.lax.scan(body, jnp.zeros((K1, 128), jnp.float32), jnp.arange(nb), unroll=unroll)
    return acc


def factored_vmap(idx, K, chunk, dtype):
    K1 = K // 128
    nb = idx.shape[0] // chunk
    blocks = idx.reshape(nb, chunk)

    def per_block(i_c):
        hi = jax.nn.one_hot(i_c // 128, K1, dtype=dtype)
        lo = jax.nn.one_hot(i_c % 128, 128, dtype=dtype)
        return jax.lax.dot_general(
            hi, lo, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    return jnp.sum(jax.vmap(per_block)(blocks), axis=0)


def batched_dot(idx, K, chunk, dtype):
    """One batched dot_general over the block axis: [nb,chunk,K1]x[nb,chunk,128]
    -> [nb,K1,128], contraction over chunk, then sum over nb."""
    K1 = K // 128
    nb = idx.shape[0] // chunk
    blocks = idx.reshape(nb, chunk)
    hi = jax.nn.one_hot(blocks // 128, K1, dtype=dtype)
    lo = jax.nn.one_hot(blocks % 128, 128, dtype=dtype)
    out = jax.lax.dot_general(
        hi, lo, (((1,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
    )
    return jnp.sum(out, axis=0)


def m1(idx, K, chunk):
    nb = idx.shape[0] // chunk

    def body(acc, b):
        i_c = jax.lax.dynamic_slice_in_dim(idx, b * chunk, chunk)
        onehot = jax.nn.one_hot(i_c, K, dtype=jnp.float32)
        return acc + (jnp.ones((1, chunk), jnp.float32) @ onehot), None

    acc, _ = jax.lax.scan(body, jnp.zeros((1, K), jnp.float32), jnp.arange(nb))
    return acc


def main():
    rng = np.random.default_rng(0)
    dev = jax.devices()[0]
    idx = jax.device_put(jnp.asarray(rng.integers(0, 16384, size=N).astype(np.int32)), dev)

    for K in (512, 2048, 8192, 16384):
        f = jax.jit(lambda i, K=K: m1(jnp.minimum(i, K - 1), K, 1 << 18))
        report(f"m1_K{K}", timeit(f, idx))

    for chunk_log in (18, 20, 22):
        f = jax.jit(lambda i, c=1 << chunk_log: factored(i, 16384, c, jnp.bfloat16))
        report(f"factored_bf16_chunk2e{chunk_log}", timeit(f, idx))

    f = jax.jit(lambda i: factored(i, 16384, 1 << 18, jnp.bfloat16, unroll=4))
    report("factored_bf16_unroll4", timeit(f, idx))

    f = jax.jit(lambda i: factored_vmap(i, 16384, 1 << 18, jnp.bfloat16))
    report("factored_vmap_bf16", timeit(f, idx))

    f = jax.jit(lambda i: batched_dot(i, 16384, 1 << 18, jnp.bfloat16))
    report("batched_dot_bf16", timeit(f, idx))

    f = jax.jit(lambda i: batched_dot(i, 16384, 1 << 15, jnp.bfloat16))
    report("batched_dot_bf16_chunk2e15", timeit(f, idx))


if __name__ == "__main__":
    main()
