"""On-chip probe: candidate lowerings for grouped HLL / presence.

Measures, at the round-4 bench and north-star shapes:
  a. current M=1 one-hot contraction  [1,chunk]@[chunk,K]
  b. factored outer-product           [K/128,chunk]@[chunk,128]
  c. factored in bf16 (f32 accumulate)
  d. masked scatter-max (all dropped) vs live scatter-max
  e. jax.lax.sort throughput (1- and 2-operand)
  f. int8 factored contraction (int32 accumulate)

One JSON line per measurement on stdout.
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

N = 1 << 27  # 134M rows
CHUNK = 1 << 18


def _fetch(out):
    leaf = out
    while isinstance(leaf, (tuple, list)):
        leaf = leaf[0]
    np.asarray(leaf.ravel()[:1])


def timeit(fn, *args, iters=3):
    _fetch(fn(*args))
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        _fetch(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def report(name, secs, rows=N):
    print(
        json.dumps(
            {"probe": name, "ms": round(secs * 1e3, 2), "ns_per_row": round(secs / rows * 1e9, 3)}
        ),
        flush=True,
    )


def contraction_m1(idx, w, K):
    nb = idx.shape[0] // CHUNK

    def body(acc, b):
        i_c = jax.lax.dynamic_slice_in_dim(idx, b * CHUNK, CHUNK)
        w_c = jax.lax.dynamic_slice_in_dim(w, b * CHUNK, CHUNK)
        onehot = jax.nn.one_hot(i_c, K, dtype=jnp.float32)
        return acc + (w_c[None, :] @ onehot), None

    acc, _ = jax.lax.scan(body, jnp.zeros((1, K), jnp.float32), jnp.arange(nb))
    return acc


def contraction_factored(idx, w, K, dtype=jnp.float32):
    K1 = K // 128
    nb = idx.shape[0] // CHUNK

    def body(acc, b):
        i_c = jax.lax.dynamic_slice_in_dim(idx, b * CHUNK, CHUNK)
        w_c = jax.lax.dynamic_slice_in_dim(w, b * CHUNK, CHUNK).astype(dtype)
        hi = jax.nn.one_hot(i_c // 128, K1, dtype=dtype)  # [chunk, K1]
        lo = jax.nn.one_hot(i_c % 128, 128, dtype=dtype)  # [chunk, 128]
        acc = acc + jax.lax.dot_general(
            hi * w_c[:, None],
            lo,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc, None

    acc, _ = jax.lax.scan(body, jnp.zeros((K1, 128), jnp.float32), jnp.arange(nb))
    return acc


def contraction_int8(idx, w8, K):
    K1 = K // 128
    nb = idx.shape[0] // CHUNK

    def body(acc, b):
        i_c = jax.lax.dynamic_slice_in_dim(idx, b * CHUNK, CHUNK)
        w_c = jax.lax.dynamic_slice_in_dim(w8, b * CHUNK, CHUNK)
        hi = jax.nn.one_hot(i_c // 128, K1, dtype=jnp.int8)
        lo = jax.nn.one_hot(i_c % 128, 128, dtype=jnp.int8)
        acc = acc + jax.lax.dot_general(
            hi * w_c[:, None],
            lo,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        return acc, None

    acc, _ = jax.lax.scan(body, jnp.zeros((K1, 128), jnp.int32), jnp.arange(nb))
    return acc


def main():
    rng = np.random.default_rng(0)
    dev = jax.devices()[0]
    print(json.dumps({"probe": "platform", "dev": str(dev)}), flush=True)

    idx_np = rng.integers(0, 16384, size=N).astype(np.int32)
    idx = jax.device_put(jnp.asarray(idx_np), dev)
    w = jax.device_put(jnp.ones(N, jnp.float32), dev)
    w8 = jax.device_put(jnp.ones(N, jnp.int8), dev)

    K = 16384  # bench shape: cap 4 x gcard_pad 4096
    f_m1 = jax.jit(lambda i, ww: contraction_m1(i, ww, K))
    report("m1_onehot_K16384", timeit(f_m1, idx, w))
    f_fac = jax.jit(lambda i, ww: contraction_factored(i, ww, K))
    report("factored_f32_K16384", timeit(f_fac, idx, w))
    f_bf = jax.jit(lambda i, ww: contraction_factored(i, ww, K, jnp.bfloat16))
    report("factored_bf16_K16384", timeit(f_bf, idx, w))
    f_i8 = jax.jit(lambda i, ww: contraction_int8(i, ww, K))
    report("factored_int8_K16384", timeit(f_i8, idx, w))

    # north-star presence shape: K = 1024 groups x 256 buckets
    K2 = 1024 * 256
    f_fac2 = jax.jit(lambda i, ww: contraction_factored(i, ww, K2, jnp.bfloat16))
    idx2 = jax.device_put(jnp.asarray(rng.integers(0, K2, size=N).astype(np.int32)), dev)
    report("factored_bf16_K262144", timeit(f_fac2, idx2, w))
    f_i82 = jax.jit(lambda i, ww: contraction_int8(i, ww, K2))
    report("factored_int8_K262144", timeit(f_i82, idx2, w))

    # scatter-max: live vs fully-dropped
    rho = jax.device_put(jnp.asarray(rng.integers(1, 40, size=N).astype(np.uint8)), dev)

    def scat(i, r):
        holder = jnp.zeros(K2, jnp.uint8)
        return holder.at[i].max(r, mode="drop")

    f_scat = jax.jit(scat)
    report("scatter_max_live", timeit(f_scat, idx2, rho))
    idx_dropped = jax.device_put(jnp.full(N, K2, jnp.int32), dev)
    report("scatter_max_all_dropped", timeit(f_scat, idx_dropped, rho))

    # sort throughput
    f_sort1 = jax.jit(lambda x: jax.lax.sort(x))
    report("sort_1op_134M_int32", timeit(f_sort1, idx2))
    f_sort2 = jax.jit(lambda x, y: jax.lax.sort((x, y), num_keys=1))
    report("sort_2op_134M_int32", timeit(f_sort2, idx2, idx))

    # cumsum
    f_cum = jax.jit(lambda x: jnp.cumsum(x))
    report("cumsum_134M_int32", timeit(f_cum, idx))


if __name__ == "__main__":
    main()
