"""End-to-end probes for the two round-5 HLL lowerings.

1. hll_sort: packed (slot*256+bucket)*64+rho int32 -> single-op sort ->
   searchsorted run-max extraction -> dense [cap, 256] registers.
   North-star shape: N=134M, cap=1024.  Correctness vs numpy scatter-max.
2. batched-dot factored contraction at K = 262144 / 2^20 (to re-gate
   _MATMUL_VALUE_CAP).
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

N = 1 << 27
CAP = 1024
M = 256


def _fetch(out):
    leaf = out
    while isinstance(leaf, (tuple, list)):
        leaf = leaf[0]
    np.asarray(leaf.ravel()[:1])


def timeit(fn, *args, iters=3):
    _fetch(fn(*args))
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        _fetch(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def report(name, secs, extra=None):
    print(
        json.dumps(
            {
                "probe": name,
                "ms": round(secs * 1e3, 2),
                "ns_per_row": round(secs / N * 1e9, 3),
                **(extra or {}),
            }
        ),
        flush=True,
    )


def hll_sort_registers(packed):
    """packed int32 [N]: (cell << 6) | rho, sentinel int32 max for invalid.
    Returns uint8 [CAP, M] registers."""
    s = jax.lax.sort(packed)
    ncells = CAP * M
    # run-max per cell: the largest packed value with the cell prefix is
    # at position searchsorted(s, (cell+1)<<6) - 1
    bounds = (jnp.arange(ncells, dtype=jnp.int32) + 1) << 6
    pos = jnp.searchsorted(s, bounds) - 1
    v = s[jnp.maximum(pos, 0)]
    regs = jnp.where((pos >= 0) & ((v >> 6) == jnp.arange(ncells)), v & 63, 0)
    return regs.reshape(CAP, M).astype(jnp.uint8)


def main():
    rng = np.random.default_rng(1)
    dev = jax.devices()[0]

    gid = rng.integers(0, CAP, size=N).astype(np.int32)
    bucket = rng.integers(0, M, size=N).astype(np.int32)
    # geometric-ish rho in [1, 40]
    rho = np.minimum(1 + rng.geometric(0.5, size=N), 40).astype(np.int32)
    packed_np = ((gid * M + bucket) << 6) | rho
    # ~1% masked rows
    invalid = rng.random(N) < 0.01
    packed_np[invalid] = np.iinfo(np.int32).max
    packed = jax.device_put(jnp.asarray(packed_np), dev)

    f = jax.jit(hll_sort_registers)
    t = timeit(f, packed)
    # correctness vs numpy scatter-max
    live = ~invalid
    cells = gid[live] * M + bucket[live]
    expect = np.zeros(CAP * M, np.uint8)
    np.maximum.at(expect, cells, rho[live].astype(np.uint8))
    got = np.asarray(f(packed)).reshape(-1)
    ok = bool((got == expect).all())
    report("hll_sort_registers_134M_cap1024", t, {"bit_identical": ok})

    # current flat uint8 scatter-max for the same shape (baseline)
    flat_np = np.where(invalid, CAP * M, gid * M + bucket).astype(np.int32)
    flat = jax.device_put(jnp.asarray(flat_np), dev)
    rho_u8 = jax.device_put(jnp.asarray(rho.astype(np.uint8)), dev)

    def scat(i, r):
        return jnp.zeros(CAP * M, jnp.uint8).at[i].max(r, mode="drop").reshape(CAP, M)

    f2 = jax.jit(scat)
    t2 = timeit(f2, flat, rho_u8)
    got2 = np.asarray(f2(flat, rho_u8)).reshape(-1)
    report("hll_scatter_baseline", t2, {"bit_identical": bool((got2 == expect).all())})

    # batched-dot factored contraction at bigger K
    def batched_dot(idx, K, chunk=1 << 18):
        K1 = K // 128
        nb = idx.shape[0] // chunk
        blocks = idx.reshape(nb, chunk)
        hi = jax.nn.one_hot(blocks // 128, K1, dtype=jnp.bfloat16)
        lo = jax.nn.one_hot(blocks % 128, 128, dtype=jnp.bfloat16)
        out = jax.lax.dot_general(
            hi, lo, (((1,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
        )
        return jnp.sum(out, axis=0)

    for Klog in (18, 20):
        K = 1 << Klog
        idx = jax.device_put(
            jnp.asarray(rng.integers(0, K, size=N).astype(np.int32)), dev
        )
        fK = jax.jit(lambda i, K=K: batched_dot(i, K))
        report(f"batched_dot_bf16_K2e{Klog}", timeit(fK, idx))


if __name__ == "__main__":
    main()
