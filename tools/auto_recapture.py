#!/usr/bin/env python
"""Auto-recapture: keep trying to land a non-degraded on-chip bench
record (VERDICT r3 weak #6 / next #1 — "a wedge can never again leave
only a degraded committed record").

Loop: probe the device tunnel out-of-process; when it answers, run the
full ``bench.py`` (serialized — this script is the only chip client it
starts) and, if the result is on-chip and non-degraded, append it to
the captures file and exit 0.  While the tunnel is down, sleep and
re-probe, up to ``--max-hours``.

Run it in the background near round end:
    nohup python tools/auto_recapture.py --out BENCH_TPU_CAPTURES_r4.json &
It is safe to leave running: one capture, then exit.  Exit codes:
0 = capture landed, 2 = gave up (tunnel never healthy), 3 = bench kept
failing while the tunnel probed healthy.
"""
from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def probe(timeout_s: float = 90.0) -> bool:
    sys.path.insert(0, REPO)
    from pinot_tpu.utils.platform import probe_device  # the ONE probe impl

    return probe_device(timeout_s)


def run_bench(deadline_s: int) -> dict | None:
    env = dict(os.environ)
    env["PINOT_TPU_BENCH_DEADLINE_S"] = str(deadline_s)
    try:
        r = subprocess.run(
            [sys.executable, "bench.py"],
            timeout=deadline_s + 600,
            capture_output=True,
            text=True,
            cwd=REPO,
            env=env,
        )
    except subprocess.TimeoutExpired:
        return None
    for line in reversed((r.stdout or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="BENCH_TPU_CAPTURES_r4.json")
    ap.add_argument("--probe-interval-s", type=int, default=300)
    ap.add_argument("--max-hours", type=float, default=10.0)
    ap.add_argument("--bench-deadline-s", type=int, default=3000)
    args = ap.parse_args()

    out_path = os.path.join(REPO, args.out)
    stop_at = time.time() + args.max_hours * 3600
    bench_failures = 0
    while time.time() < stop_at:
        if not probe():
            print(f"{datetime.datetime.now():%H:%M:%S} tunnel down; sleeping", flush=True)
            time.sleep(args.probe_interval_s)
            continue
        print(f"{datetime.datetime.now():%H:%M:%S} tunnel up; running bench", flush=True)
        result = run_bench(args.bench_deadline_s)
        if result and not result.get("degraded"):
            caps = {"note": "auto-recaptured on-chip bench runs", "runs": []}
            if os.path.exists(out_path):
                with open(out_path) as f:
                    caps = json.load(f)
            # an existing file without a runs list must not crash the
            # append AFTER the expensive bench run succeeded
            caps.setdefault("runs", []).append(
                {
                    "when": f"{datetime.datetime.now():%Y-%m-%d %H:%M:%S} (auto_recapture)",
                    "result": result,
                }
            )
            tmp = out_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(caps, f, indent=1)
            os.replace(tmp, out_path)
            print(f"capture landed: {result.get('value')} {result.get('unit')}", flush=True)
            return 0
        bench_failures += 1
        print(
            f"{datetime.datetime.now():%H:%M:%S} bench degraded/failed "
            f"({bench_failures}); re-probing",
            flush=True,
        )
        if bench_failures >= 5:
            return 3
        time.sleep(args.probe_interval_s)
    return 2


if __name__ == "__main__":
    sys.exit(main())
