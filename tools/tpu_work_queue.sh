#!/bin/bash
# Serial on-chip work queue: waits for the axon tunnel, then runs each
# step once, logging to /tmp/tpu_runs/. Never uses kill -9 (a SIGKILL
# mid-transfer wedges the tunnel lease for hours).
cd /root/repo
LOG=/tmp/tpu_runs
mkdir -p "$LOG"
probe() { timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; }
echo "$(date +%T) queue start" > $LOG/status.txt
for i in $(seq 1 400); do
  if probe; then echo "$(date +%T) tunnel UP (probe $i)" >> $LOG/status.txt; break; fi
  echo "$(date +%T) probe $i down" >> $LOG/status.txt
  sleep 45
done
if ! probe; then echo "$(date +%T) GAVE UP" >> $LOG/status.txt; exit 1; fi

echo "$(date +%T) step1 tpu gate" >> $LOG/status.txt
PINOT_TPU_TESTS=tpu timeout 2400 python -m pytest tests/test_tpu_platform.py -m tpu -q > $LOG/step1_gate.log 2>&1
echo "$(date +%T) step1 exit=$?" >> $LOG/status.txt

echo "$(date +%T) step2 bench" >> $LOG/status.txt
timeout 3600 python bench.py > $LOG/step2_bench.log 2> $LOG/step2_bench.err
echo "$(date +%T) step2 exit=$?" >> $LOG/status.txt

echo "$(date +%T) step3 hll northstar 536M" >> $LOG/status.txt
timeout 3000 python -m pinot_tpu.tools.hll_northstar -rows 536870912 -iters 3 > $LOG/step3_ns.log 2>&1
echo "$(date +%T) step3 exit=$?" >> $LOG/status.txt

echo "$(date +%T) step4 auto-recapture insurance (foreground: chip work stays serialized)" >> $LOG/status.txt
python tools/auto_recapture.py --out BENCH_TPU_CAPTURES_r4.json --max-hours 2 > $LOG/step4_recapture.log 2>&1
echo "$(date +%T) step4 exit=$?" >> $LOG/status.txt
echo "$(date +%T) ALL DONE" >> $LOG/status.txt

# Provenance: round-4 serialization of on-chip validation (gate ->
# bench -> north-star -> recapture insurance) behind a tunnel-recovery
# probe. Chip work MUST be serialized: the tunnel is single-client, and
# SIGKILLing a client mid-transfer wedges the lease for hours (see
# .claude/skills/verify/SKILL.md).
