#!/bin/bash
# Serial on-chip work queue: waits for the axon tunnel, then runs each
# step once, logging to /tmp/tpu_runs/. Never uses kill -9 (a SIGKILL
# mid-transfer wedges the tunnel lease for hours).
cd /root/repo
LOG=/tmp/tpu_runs
mkdir -p "$LOG"
probe() { timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; }
echo "$(date +%T) queue start" > $LOG/status.txt
for i in $(seq 1 400); do
  if probe; then echo "$(date +%T) tunnel UP (probe $i)" >> $LOG/status.txt; break; fi
  echo "$(date +%T) probe $i down" >> $LOG/status.txt
  sleep 45
done
if ! probe; then echo "$(date +%T) GAVE UP" >> $LOG/status.txt; exit 1; fi

echo "$(date +%T) step1 tpu gate" >> $LOG/status.txt
PINOT_TPU_TESTS=tpu timeout 2400 python -m pytest tests/test_tpu_platform.py -m tpu -q > $LOG/step1_gate.log 2>&1
echo "$(date +%T) step1 exit=$?" >> $LOG/status.txt

echo "$(date +%T) step2 two-server quickstart repro" >> $LOG/status.txt
if [ -f /tmp/repro2srv.py ]; then
  PYTHONPATH=/root/repo timeout 900 python -u /tmp/repro2srv.py > $LOG/step2_repro.log 2>&1
  echo "$(date +%T) step2 exit=$?" >> $LOG/status.txt
else
  echo "$(date +%T) step2 SKIPPED (/tmp/repro2srv.py not present)" >> $LOG/status.txt
fi

echo "$(date +%T) step3 bench" >> $LOG/status.txt
timeout 3600 python bench.py > $LOG/step3_bench.log 2> $LOG/step3_bench.err
echo "$(date +%T) step3 exit=$?" >> $LOG/status.txt

echo "$(date +%T) step4 pallas microbench" >> $LOG/status.txt
timeout 1800 python -m pinot_tpu.tools.microbench pallas_ab -rows 8388608 > $LOG/step4_pallas.log 2>&1
echo "$(date +%T) step4 exit=$?" >> $LOG/status.txt
echo "$(date +%T) ALL DONE" >> $LOG/status.txt

# Provenance: used in round 3 to serialize all on-chip validation
# (gate -> demo repro -> bench capture -> pallas A/B) behind a tunnel-
# recovery probe. Chip work MUST be serialized: the tunnel is single-
# client, and SIGKILLing a client mid-transfer wedges the lease for
# hours (see .claude/skills/verify/SKILL.md).
