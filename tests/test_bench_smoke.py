"""bench.py end-to-end smoke at tiny scale: the driver runs bench.py on
real hardware at round end — a bitrotted bench means no recorded
number, so the harness itself is regression-tested here (CPU, tiny
config, all phases: kernel marginal, broker latencies, the selective
path matrix, and the extra workload shapes)."""
import json
import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_bench_end_to_end_smoke(tmp_path):
    env = dict(os.environ)
    env.update(
        PINOT_TPU_BENCH_SEGMENTS="1",
        PINOT_TPU_BENCH_ROWS_PER_SEGMENT="50000",
        PINOT_TPU_BENCH_ITERS="2",
        # force CPU deterministically (the bench's own probe would try
        # the tunnel first and burn its timeout when the tunnel is down)
        PINOT_TPU_BENCH_FORCE_CPU="1",
    )
    out = subprocess.run(
        [sys.executable, "bench.py"],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    line = out.stdout.strip().splitlines()[-1]
    j = json.loads(line)
    assert j["metric"] == "tpch_q1_rows_scanned_per_sec_per_chip"
    assert j["value"] > 0
    assert j["degraded"] is True  # CPU run must self-mark
    d = j["detail"]
    for key in (
        "broker_p50_ms",
        "broker_p99_ms",
        "sel_clustered_p50_ms_invindex",
        "sel_clustered_p50_ms_zonemap",
        "sel_clustered_p50_ms_fullscan",
        "sel_shuffled_p50_ms_invindex",
        "sel_shuffled_p50_ms_fullscan",
        "q6_p50_ms",
        "hll_groupby_p50_ms",
    ):
        assert key in d and d[key] > 0, key
    # the degraded record must point at an EXISTING committed capture
    # file (the judge follows this reference when the tunnel is down)
    ref = j["tpu_capture_ref"]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert os.path.exists(os.path.join(repo, ref)), ref

    # perf regression gate (tools/perf_gate.py) on the fresh output:
    # vs itself the bands must hold trivially (pass), and vs the
    # committed full-scale capture the gate must detect the workload
    # config mismatch and SKIP rather than compare apples to oranges
    from pinot_tpu.tools.perf_gate import compare, load_bench

    fresh = load_bench(j)
    assert compare(fresh, fresh)["verdict"] == "pass"
    committed = load_bench(os.path.join(repo, "BENCH_r05.json"))
    gated = compare(committed, fresh)
    assert gated["verdict"] == "skipped"  # tiny smoke config != capture
    assert "detail.total_rows" in gated["configMismatch"]
