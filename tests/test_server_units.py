"""Direct unit tests for the server-side primitives that the cluster
tests only exercise indirectly: refcounted data managers (swap/drop
under a running query — ``AbstractTableDataManager.java:42`` semantics),
the bounded FCFS scheduler, and segment pruners."""
import threading
import time

import pytest

from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.engine.pruner import prune_segments
from pinot_tpu.pql import optimize_request, parse_pql
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.server.datamanager import InstanceDataManager, TableDataManager
from pinot_tpu.server.scheduler import QueryScheduler

SCHEMA = Schema(
    "t",
    dimensions=[FieldSpec("d", DataType.STRING)],
    metrics=[FieldSpec("m", DataType.INT, FieldType.METRIC)],
)


def _seg(name: str, lo: int = 0, n: int = 4):
    return build_segment(
        SCHEMA, [{"d": f"v{i}", "m": lo + i} for i in range(n)], "t", name
    )


# ---------------------------------------------------------------- datamanager
def test_swap_keeps_old_segment_alive_for_running_query():
    tdm = TableDataManager("t")
    old = _seg("s0")
    tdm.add_segment(old)
    held = tdm.acquire_segments(["s0"])
    assert [h.segment for h in held] == [old]

    new = _seg("s0", lo=100)  # refresh under the same name
    tdm.add_segment(new)
    # the running query still reads the OLD object it acquired
    assert held[0].segment is old
    # new queries see the replacement
    fresh = tdm.acquire_segments(["s0"])
    assert fresh[0].segment is new
    tdm.release_segments(fresh)
    # old's owner ref dropped at swap: the reader's release is the LAST
    assert held[0].release() == 0


def test_remove_segment_defers_death_to_last_release():
    tdm = TableDataManager("t")
    tdm.add_segment(_seg("s0"))
    held = tdm.acquire_segments(None)
    tdm.remove_segment("s0")
    assert tdm.segment_names() == []
    # acquire after drop fails (refcount reached reader-only)
    assert tdm.acquire_segments(["s0"]) == []
    assert held[0].release() == 0  # reader's release is the last


def test_acquire_dead_segment_refused():
    tdm = TableDataManager("t")
    tdm.add_segment(_seg("s0"))
    sdm = tdm.acquire_segments(None)[0]
    tdm.remove_segment("s0")
    sdm.release()  # refcount 0: dead
    assert sdm.acquire() is False


def test_acquire_skips_missing_names():
    tdm = TableDataManager("t")
    tdm.add_segment(_seg("s0"))
    got = tdm.acquire_segments(["s0", "ghost"])
    assert [g.name for g in got] == ["s0"]
    tdm.release_segments(got)


def test_instance_hierarchy():
    idm = InstanceDataManager()
    assert idm.table("t") is None
    idm.add_segment("t", _seg("s0"))
    assert idm.table_names() == ["t"]
    assert idm.table("t").segment_names() == ["s0"]


# ----------------------------------------------------------------- scheduler
def test_scheduler_fcfs_order_single_worker():
    sched = QueryScheduler(num_workers=1)
    order = []
    gate = threading.Event()

    def job(i):
        def run():
            gate.wait(5)
            order.append(i)
            return i

        return run

    futs = [sched.submit(job(i)) for i in range(4)]
    gate.set()
    assert [f.result(timeout=5) for f in futs] == [0, 1, 2, 3]
    assert order == [0, 1, 2, 3]
    sched.shutdown()


def test_scheduler_run_timeout():
    sched = QueryScheduler(num_workers=1)
    with pytest.raises(TimeoutError):
        sched.run(lambda: time.sleep(2), timeout_s=0.05)
    sched.shutdown()


def test_scheduler_saturation_sheds_then_recovers():
    """Overload policy: beyond max_pending queued-or-running queries the
    scheduler sheds with a typed error immediately (no unbounded queue,
    no slow timeout), and accepts again once the backlog drains."""
    from pinot_tpu.server.scheduler import SchedulerSaturatedError

    sched = QueryScheduler(num_workers=1, max_pending=2)
    gate = threading.Event()
    futs = [sched.submit(lambda: gate.wait(5)) for _ in range(2)]
    assert sched.pending == 2
    with pytest.raises(SchedulerSaturatedError):
        sched.submit(lambda: 1)
    assert sched.shed_count == 1
    gate.set()
    for f in futs:
        f.result(timeout=5)
    # done-callbacks drain pending; new submits are accepted again
    assert sched.submit(lambda: 99).result(timeout=5) == 99
    sched.shutdown()


def test_server_sheds_with_scheduler_down_code():
    """A saturated server replies fast with SERVER_SCHEDULER_DOWN (210)
    instead of queueing the request toward a timeout."""
    from pinot_tpu.common.datatable import (
        deserialize_result,
        serialize_instance_request,
    )
    from pinot_tpu.common.response import ErrorCode
    from pinot_tpu.server.instance import ServerInstance
    from pinot_tpu.tools.datagen import make_test_schema, random_rows

    schema = make_test_schema(with_mv=False)
    seg = build_segment(schema, random_rows(schema, 50, seed=3), "tt", "s0")
    inst = ServerInstance("satServer", num_workers=1, max_pending=1)
    inst.set_table_schema("tt", schema)
    inst.add_segment("tt", seg)
    gate = threading.Event()
    real_execute = inst.executor.execute

    def slow_execute(segs, req, **kwargs):
        gate.wait(5)
        return real_execute(segs, req, **kwargs)

    inst.executor.execute = slow_execute
    payload = serialize_instance_request(
        1, "SELECT count(*) FROM tt", "tt", ["s0"], 5000
    )
    results = {}

    def first():
        results["first"] = deserialize_result(inst.handle_request(payload))

    t = threading.Thread(target=first)
    t.start()
    # wait until the slow query occupies the single pending slot
    for _ in range(100):
        if inst.scheduler.pending >= 1:
            break
        time.sleep(0.01)
    shed = deserialize_result(inst.handle_request(payload))
    assert shed.exceptions
    assert shed.exceptions[0][0] == ErrorCode.SERVER_SCHEDULER_DOWN
    gate.set()
    t.join(timeout=10)
    assert not results["first"].exceptions
    inst.scheduler.shutdown()


def test_scheduler_shutdown_cancels_pending():
    sched = QueryScheduler(num_workers=1)
    gate = threading.Event()
    first = sched.submit(lambda: gate.wait(5))
    pending = sched.submit(lambda: 42)
    sched.shutdown()
    gate.set()
    first.result(timeout=5)
    with pytest.raises(Exception):
        pending.result(timeout=1)  # cancelled, never ran


def test_scheduler_shutdown_idempotent_and_refuses_submits():
    """Regression: shutdown twice is a no-op the second time, and a
    submit after shutdown fails with the typed retryable error (220 on
    the wire) rather than the pool's bare RuntimeError."""
    from pinot_tpu.server.scheduler import SchedulerShutdownError

    sched = QueryScheduler(num_workers=1)
    gate = threading.Event()
    running = sched.submit(lambda: gate.wait(5))
    queued = sched.submit(lambda: 1)
    sched.shutdown()
    sched.shutdown()  # idempotent: second call must not raise
    with pytest.raises(SchedulerShutdownError):
        sched.submit(lambda: 2)
    gate.set()
    running.result(timeout=5)
    with pytest.raises(Exception):
        queued.result(timeout=1)  # cancelled by the FIRST shutdown
    sched.shutdown()  # still a no-op after draining


# ---------------------------------------------- scheduler overload semantics
def test_saturation_is_per_queue_not_global():
    """210 sheds are per-table: table A at its fair-share cap sheds
    while table B (under its share) keeps being admitted — the r5
    global-FCFS behavior would have shed B too."""
    from pinot_tpu.server.scheduler import SchedulerSaturatedError

    sched = QueryScheduler(num_workers=1, max_pending=6)
    gate = threading.Event()
    running = sched.submit(lambda: gate.wait(5), table="B")
    # A's share with B active: 6/2 = 3
    for _ in range(3):
        sched.submit(lambda: 1, table="A")
    with pytest.raises(SchedulerSaturatedError) as ei:
        sched.submit(lambda: 1, table="A")
    assert "table A" in str(ei.value)  # the error NAMES the queue
    # B is under ITS cap: still admitted after A shed
    fb = sched.submit(lambda: "b", table="B")
    assert sched.stats()["tableShed"] == {"A": 1}
    gate.set()
    running.result(timeout=5)
    assert fb.result(timeout=5) == "b"
    sched.shutdown()


def test_server_saturation_210_is_per_table():
    """End-to-end server twin of the above: a flooded table's overflow
    gets 210 while another table's query on the SAME server executes."""
    from pinot_tpu.common.datatable import (
        deserialize_result,
        serialize_instance_request,
    )
    from pinot_tpu.common.response import ErrorCode
    from pinot_tpu.server.instance import ServerInstance
    from pinot_tpu.tools.datagen import make_test_schema, random_rows

    schema = make_test_schema(with_mv=False)
    inst = ServerInstance("fairServer", num_workers=1, max_pending=4)
    for table in ("ta", "tb"):
        inst.set_table_schema(table, schema)
        inst.add_segment(
            table,
            build_segment(schema, random_rows(schema, 20, seed=4), table, "s0"),
        )
    gate = threading.Event()
    real_execute = inst.executor.execute

    def slow_execute(segs, req, **kwargs):
        gate.wait(5)
        return real_execute(segs, req, **kwargs)

    inst.executor.execute = slow_execute
    pa = serialize_instance_request(1, "SELECT count(*) FROM ta", "ta", ["s0"], 5000)
    pb = serialize_instance_request(2, "SELECT count(*) FROM tb", "tb", ["s0"], 5000)
    results = []
    threads = [
        threading.Thread(
            target=lambda p=pa: results.append(deserialize_result(inst.handle_request(p)))
        )
        for _ in range(2)
    ]
    for t in threads:
        t.start()
    for _ in range(200):
        if inst.scheduler.pending_of("ta") >= 2:
            break
        time.sleep(0.01)
    # ta is at its share (4/2 with tb counted active by the flood); a
    # third ta request sheds 210...
    shed = deserialize_result(inst.handle_request(pa))
    del shed  # (may or may not shed depending on tb activity; the
    # DIRECT contract under test is: tb still gets served)
    gate.set()
    tb_thread = []

    def q_tb():
        tb_thread.append(deserialize_result(inst.handle_request(pb)))

    t = threading.Thread(target=q_tb)
    t.start()
    t.join(timeout=10)
    for th in threads:
        th.join(timeout=10)
    assert tb_thread and not tb_thread[0].exceptions
    assert tb_thread[0].num_docs_scanned == 20
    # and the shed counter (if any) is attributed per table in stats
    assert set(inst.scheduler.stats()["tableShed"]) <= {"ta"}
    inst.scheduler.shutdown()
    inst.shutdown()


def test_expired_entries_never_pin_queue_at_cap():
    """A queue full of deadline-expired work must not shed live
    traffic: submit-time purge completes the corpses with the typed
    abandon error and frees their slots."""
    from pinot_tpu.server.scheduler import QueryAbandonedError

    sched = QueryScheduler(num_workers=1, max_pending=4)
    gate = threading.Event()
    running = sched.submit(lambda: gate.wait(5), table="A")
    time.sleep(0.05)  # worker claims the blocker
    # fill the queue with entries that expire immediately
    dead = [
        sched.submit(lambda: 1, table="A", deadline=time.monotonic() + 0.01)
        for _ in range(3)
    ]
    assert sched.pending == 4
    time.sleep(0.05)  # all queued deadlines expire
    # at the cap — but the expired corpses are purged, the live submit
    # is ADMITTED, and the corpses resolve with the typed abandon error
    live = sched.submit(lambda: "ok", table="A")
    for f in dead:
        with pytest.raises(QueryAbandonedError):
            f.result(timeout=5)
    assert sched.abandoned_count == 3
    gate.set()
    running.result(timeout=5)
    assert live.result(timeout=5) == "ok"
    sched.shutdown()


def test_shutdown_drains_all_per_table_queues():
    """Shutdown cancels queued work across EVERY table queue (not just
    one), keeps the typed refusal for later submits, and stays
    idempotent."""
    from pinot_tpu.server.scheduler import SchedulerShutdownError

    sched = QueryScheduler(num_workers=1, max_pending=32)
    gate = threading.Event()
    running = sched.submit(lambda: gate.wait(5), table="A")
    time.sleep(0.05)  # worker claims the blocker
    queued = [
        sched.submit(lambda: 1, table=t) for t in ("A", "B", "C", "A", "B")
    ]
    sched.shutdown()
    sched.shutdown()  # idempotent
    with pytest.raises(SchedulerShutdownError):
        sched.submit(lambda: 2, table="B")
    gate.set()
    running.result(timeout=5)
    for f in queued:
        with pytest.raises(Exception):
            f.result(timeout=1)  # cancelled by the FIRST shutdown
    assert sched.stats()["shutdown"] is True
    assert sched.stats()["tablePending"] == {}  # every queue drained


# ------------------------------------------------------------------- pruner
def _time_schema():
    from pinot_tpu.common.schema import TimeFieldSpec

    return Schema(
        "tt",
        dimensions=[FieldSpec("d", DataType.STRING)],
        metrics=[FieldSpec("m", DataType.INT, FieldType.METRIC)],
        time_field=TimeFieldSpec("ts", DataType.LONG, time_unit="MILLISECONDS"),
    )


def test_time_pruner_drops_disjoint_segments():
    schema = _time_schema()
    segs = [
        build_segment(
            schema,
            [{"d": "a", "m": i, "ts": base + i} for i in range(4)],
            "tt",
            f"seg{base}",
        )
        for base in (1000, 2000, 3000)
    ]
    req = optimize_request(
        parse_pql("SELECT count(*) FROM tt WHERE ts BETWEEN 2000 AND 2003")
    )
    live = prune_segments(segs, req)
    assert [s.segment_name for s in live] == ["seg2000"]

    # no time predicate: nothing pruned
    req2 = optimize_request(parse_pql("SELECT count(*) FROM tt"))
    assert len(prune_segments(segs, req2)) == 3


def test_schema_pruner_drops_missing_column_segments():
    other = Schema(
        "t",
        dimensions=[FieldSpec("other", DataType.STRING)],
        metrics=[FieldSpec("m", DataType.INT, FieldType.METRIC)],
    )
    seg_ok = _seg("has")
    seg_no = build_segment(other, [{"other": "x", "m": 1}], "t", "lacks")
    req = optimize_request(parse_pql("SELECT count(*) FROM t WHERE d = 'v1'"))
    live = prune_segments([seg_ok, seg_no], req)
    assert [s.segment_name for s in live] == ["has"]


# ------------------------------------------------------------------ fileio
def test_atomic_write_replaces_and_leaves_no_temps(tmp_path):
    from pinot_tpu.utils.fileio import atomic_write

    p = str(tmp_path / "state.json")
    atomic_write(p, "v1")
    assert open(p).read() == "v1"
    atomic_write(p, "v2-longer-content")
    assert open(p).read() == "v2-longer-content"
    # no stray temp files: a crashed writer's temp never shadows state
    leftovers = [f for f in tmp_path.iterdir() if f.name != "state.json"]
    assert leftovers == []


def test_atomic_write_failure_preserves_old_content(tmp_path, monkeypatch):
    import os as _os

    from pinot_tpu.utils import fileio

    p = str(tmp_path / "state.json")
    fileio.atomic_write(p, "original")

    real_replace = _os.replace

    def boom(src, dst):
        raise OSError("disk pulled")

    monkeypatch.setattr(fileio.os, "replace", boom)
    import pytest as _pytest

    with _pytest.raises(OSError):
        fileio.atomic_write(p, "new")
    monkeypatch.setattr(fileio.os, "replace", real_replace)
    assert open(p).read() == "original"  # old content intact
    # the failed writer's temp file is cleaned up, not left to shadow
    leftovers = [f for f in tmp_path.iterdir() if f.name != "state.json"]
    assert leftovers == []
