"""Engine edge cases: host-fallback group-by at huge key spaces,
MV order-by selection, offsets, empty segments, trace spans."""
import pytest

from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.engine.executor import QueryExecutor
from pinot_tpu.engine.reduce import reduce_to_response
from pinot_tpu.pql import optimize_request, parse_pql
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.tools.datagen import make_test_schema, random_rows
from pinot_tpu.tools.scan_engine import ScanQueryProcessor

EX = QueryExecutor()


def run_both(schema, rows, segments, pql):
    req_e = optimize_request(parse_pql(pql))
    req_o = optimize_request(parse_pql(pql))
    got = reduce_to_response(req_e, [EX.execute(segments, req_e)]).to_json()
    want = ScanQueryProcessor(schema, rows).execute(req_o).to_json()
    for k in ("timeUsedMs", "cost", "numEntriesScannedInFilter", "numEntriesScannedPostFilter",
              "numSegmentsQueried", "numServersQueried", "numServersResponded"):
        got.pop(k, None)
        want.pop(k, None)
    return got, want


def test_host_fallback_huge_keyspace():
    """Group-by key space above MAX_GROUP_CAPACITY routes to the host
    hash path (the LONG_MAP_BASED analog) and stays correct."""
    schema = Schema(
        "big",
        dimensions=[
            FieldSpec("a", DataType.INT),
            FieldSpec("b", DataType.INT),
            FieldSpec("c", DataType.INT),
        ],
        metrics=[FieldSpec("m", DataType.INT, FieldType.METRIC)],
    )
    # 150^3 = 3.4M > 2^20 capacity cap
    rows = random_rows(schema, 800, seed=3, cardinality=150)
    seg = build_segment(schema, rows, "big", "bigseg")

    from pinot_tpu.engine.context import get_table_context
    from pinot_tpu.engine.device import get_staged
    from pinot_tpu.engine.plan import build_static_plan

    req = parse_pql("SELECT sum(m) FROM big GROUP BY a, b, c TOP 10")
    ctx = get_table_context([seg])
    staged = get_staged([seg], ["a", "b", "c", "m"])
    plan = build_static_plan(req, ctx, staged)
    assert not plan.on_device  # confirms the fallback triggers

    got, want = run_both(schema, rows, [seg], "SELECT sum(m) FROM big GROUP BY a, b, c TOP 10")
    assert got == want


def test_wide_key_order_by_stays_on_device(monkeypatch):
    """ORDER BY whose composite-key radix product overflows the key dtype
    uses the multi-operand lexicographic lax.sort path on device (no host
    fallback), and matches the oracle exactly."""
    from pinot_tpu.engine import config
    from pinot_tpu.engine.context import get_table_context
    from pinot_tpu.engine.device import get_staged
    from pinot_tpu.engine.plan import build_static_plan

    monkeypatch.setattr(config, "max_key_space", lambda: 10)

    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 400, seed=77)
    seg = build_segment(schema, rows, "testTable", "wideksel")

    pql = "SELECT dimStr, metInt FROM testTable ORDER BY dimInt, metInt DESC LIMIT 12"
    req = parse_pql(pql)
    ctx = get_table_context([seg])
    staged = get_staged([seg], ["dimStr", "metInt", "dimInt"])
    plan = build_static_plan(req, ctx, staged)
    assert plan.on_device
    assert plan.selection is not None and not plan.selection.packed

    got, want = run_both(schema, rows, [seg], pql)
    assert got == want


def test_mv_order_by_selection():
    schema = make_test_schema()
    rows = random_rows(schema, 300, seed=21)
    seg = build_segment(schema, rows, "testTable", "mvsel")
    got, want = run_both(
        schema, rows, [seg], "SELECT dimStr FROM testTable ORDER BY dimStrMV LIMIT 10"
    )
    assert got == want


def test_selection_offset_window():
    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 200, seed=33)
    seg = build_segment(schema, rows, "testTable", "offsel")
    got, want = run_both(
        schema, rows, [seg],
        "SELECT dimInt FROM testTable ORDER BY metInt DESC LIMIT 15, 10",
    )
    assert got == want


def test_empty_segment_pruned():
    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 100, seed=4)
    seg = build_segment(schema, rows, "testTable", "full")
    empty = build_segment(schema, [], "testTable", "empty")
    req = parse_pql("SELECT count(*) FROM testTable")
    resp = reduce_to_response(req, [EX.execute([seg, empty], req)])
    assert resp.num_docs_scanned == 100
    assert resp.total_docs == 100


def test_time_pruning_skips_segments():
    from pinot_tpu.common.schema import TimeFieldSpec

    schema = Schema(
        "tp",
        metrics=[FieldSpec("m", DataType.INT, FieldType.METRIC)],
        time_field=TimeFieldSpec("days", DataType.INT, time_unit="DAYS"),
    )
    seg_old = build_segment(schema, [{"m": 1, "days": d} for d in range(100, 110)], "tp", "old")
    seg_new = build_segment(schema, [{"m": 2, "days": d} for d in range(200, 210)], "tp", "new")
    req = parse_pql("SELECT count(*) FROM tp WHERE days BETWEEN 200 AND 205")
    res = EX.execute([seg_old, seg_new], req)
    assert res.num_segments_queried == 1  # old segment pruned by time range
    assert res.num_docs_scanned == 6


def test_trace_spans_attached():
    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 50, seed=6)
    seg = build_segment(schema, rows, "testTable", "traceseg")
    from pinot_tpu.server.instance import ServerInstance
    from pinot_tpu.common.datatable import serialize_instance_request, deserialize_result

    server = ServerInstance("traceServer")
    server.add_segment("testTable", seg)
    payload = serialize_instance_request(
        1, "SELECT count(*) FROM testTable", "testTable", [], 10_000, trace=True
    )
    res = deserialize_result(server.handle_request(payload))
    assert "traceServer" in res.trace
    assert any(s["span"] == "planAndExecute" for s in res.trace["traceServer"])


def test_host_fallback_vectorized_matches_row_path():
    """The vectorized numpy hash group-by (LONG_MAP_BASED fast-path
    analog) produces the same response as the row-wise accumulator path
    over multiple segments, filters, and every vectorizable agg."""
    import pinot_tpu.engine.host_fallback as hf

    schema = Schema(
        "big",
        dimensions=[
            FieldSpec("a", DataType.INT),
            FieldSpec("b", DataType.STRING),
            FieldSpec("c", DataType.INT),
        ],
        metrics=[FieldSpec("m", DataType.INT, FieldType.METRIC),
                 FieldSpec("f", DataType.DOUBLE, FieldType.METRIC)],
    )
    rows = random_rows(schema, 1200, seed=9, cardinality=130)
    segs = [
        build_segment(schema, rows[:600], "big", "vseg0"),
        build_segment(schema, rows[600:], "big", "vseg1"),
    ]
    pql = (
        "SELECT count(*), sum(m), min(f), max(m), avg(f), minmaxrange(m) "
        "FROM big WHERE a > 100 GROUP BY a, b, c TOP 12"
    )

    from pinot_tpu.engine.context import get_table_context

    req = optimize_request(parse_pql(pql))
    ctx = get_table_context(segs)
    assert hf._vectorizable_groupby(req, segs, ctx)

    got, want = run_both(schema, rows, segs, pql)
    assert got == want

    # row path forced: MV group column is not vectorizable
    schema_mv = make_test_schema()
    req_mv = optimize_request(
        parse_pql("SELECT count(*) FROM testTable GROUP BY dimStrMV TOP 5")
    )
    rows_mv = random_rows(schema_mv, 50, seed=2)
    seg_mv = build_segment(schema_mv, rows_mv, "testTable", "mvseg")
    assert not hf._vectorizable_groupby(req_mv, [seg_mv], get_table_context([seg_mv]))


def test_host_fallback_vectorized_scale():
    """~300k rows x ~1M-key group-by completes through the vectorized
    fallback quickly (the row path takes minutes at this scale)."""
    import time

    import numpy as np

    from pinot_tpu.segment.dictionary import Dictionary
    from pinot_tpu.segment.immutable import (
        ColumnData,
        ColumnMetadata,
        ImmutableSegment,
        SegmentMetadata,
    )
    from pinot_tpu.common.schema import DataType as DT

    n = 300_000
    rng = np.random.default_rng(0)
    cols = {}
    for name, card in (("a", 1250), ("b", 1250), ("m", 500)):  # 1.56M keys > 2^20 cap
        d = Dictionary(DT.INT, np.arange(card))
        fwd = rng.integers(0, card, n).astype(np.int32)
        meta = ColumnMetadata(
            name=name, data_type=DT.INT,
            field_type=FieldType.METRIC if name == "m" else FieldType.DIMENSION,
            single_value=True, cardinality=card, total_docs=n,
            is_sorted=False, total_number_of_entries=n,
            min_value=0, max_value=card - 1,
        )
        cols[name] = ColumnData(metadata=meta, dictionary=d, fwd=fwd)
    smeta = SegmentMetadata(
        segment_name="huge", table_name="big", num_docs=n,
        columns={c.metadata.name: c.metadata for c in cols.values()},
    )
    seg = ImmutableSegment(metadata=smeta, columns=cols)
    smeta.crc = 1

    req = optimize_request(
        parse_pql("SELECT sum(m), count(*) FROM big GROUP BY a, b TOP 10")
    )
    t0 = time.perf_counter()
    res = EX.execute([seg], req)
    took = time.perf_counter() - t0
    assert res.num_docs_scanned == n
    resp = reduce_to_response(req, [res])
    top = resp.to_json()["aggregationResults"][0]["groupByResult"]
    assert len(top) == 10
    assert took < 10.0, f"vectorized fallback too slow: {took:.1f}s"


def test_chunked_kernel_matches_unchunked(monkeypatch):
    """Segment-axis chunking (PINOT_TPU_CHUNK_ROWS) combines chunk
    outputs into bit-identical results — the capacity path for tables
    whose per-row kernel temporaries exceed HBM in one dispatch."""
    import json

    from pinot_tpu.engine.executor import QueryExecutor
    from pinot_tpu.engine.reduce import reduce_to_response
    from pinot_tpu.pql import optimize_request, parse_pql
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment

    segs = [synthetic_lineitem_segment(4096, seed=41 + i, name=f"ck{i}") for i in range(6)]
    queries = [
        "SELECT sum(l_quantity), count(*), min(l_discount), max(l_tax) FROM lineitem "
        "WHERE l_shipdate <= '1998-09-02' GROUP BY l_returnflag TOP 10",
        "SELECT avg(l_extendedprice) FROM lineitem",
        "SELECT distinctcounthll(l_shipdate) FROM lineitem GROUP BY l_linestatus TOP 10",
    ]
    for pql in queries:
        req = optimize_request(parse_pql(pql))
        outs = {}
        for chunk_rows in ("0", "8192"):  # off vs 2-segment chunks
            monkeypatch.setenv("PINOT_TPU_CHUNK_ROWS", chunk_rows)
            r = reduce_to_response(req, [QueryExecutor().execute(segs, req)])
            outs[chunk_rows] = json.dumps(
                r.to_json()["aggregationResults"], sort_keys=True
            )
        assert outs["0"] == outs["8192"], pql


def test_host_fallback_vectorized_distinct_matches_oracle():
    """Beyond-capacity group-bys with distinctcount/distinctcounthll
    take the vectorized (group, gid) pair-dedup host path (the per-row
    Python loop took ~30 min at 134M rows); results must match the
    scan oracle exactly."""
    import json

    from pinot_tpu.engine import config as _config
    from pinot_tpu.engine.executor import QueryExecutor
    from pinot_tpu.engine.reduce import reduce_to_response
    from pinot_tpu.pql import optimize_request, parse_pql
    from pinot_tpu.tools.datagen import lineitem_schema, synthetic_lineitem_segment
    from pinot_tpu.tools.scan_engine import ScanQueryProcessor

    segs = [synthetic_lineitem_segment(6000, seed=61 + i, name=f"hf{i}") for i in range(3)]
    oracle = ScanQueryProcessor(lineitem_schema(), [r for s in segs for r in s.rows()])
    queries = [
        "SELECT distinctcount(l_shipdate), count(*) FROM lineitem GROUP BY l_extendedprice TOP 10",
        "SELECT distinctcounthll(l_quantity) FROM lineitem GROUP BY l_extendedprice TOP 10",
        "SELECT distinctcount(l_shipmode) FROM lineitem "
        "WHERE l_returnflag = 'R' GROUP BY l_extendedprice TOP 5",
    ]
    saved = _config.MAX_GROUP_CAPACITY
    _config.MAX_GROUP_CAPACITY = 64  # force the host fallback
    try:
        for pql in queries:
            req = optimize_request(parse_pql(pql))
            got = reduce_to_response(req, [QueryExecutor().execute(segs, req)])
            want = oracle.execute(parse_pql(pql))
            assert json.dumps(got.to_json()["aggregationResults"], sort_keys=True) == \
                json.dumps(want.to_json()["aggregationResults"], sort_keys=True), pql
    finally:
        _config.MAX_GROUP_CAPACITY = saved


def test_docrange_filter_on_group_column_skips_base_correctly():
    """Regression for the skip_base x docrange interplay: a sorted
    column filtered by RANGE and ALSO used as the group key stages only
    its gfwd stream (base fwd/dict skipped), the leaf resolves via doc
    bounds, and results match the oracle."""
    import json

    from pinot_tpu.engine.executor import QueryExecutor
    from pinot_tpu.engine.reduce import reduce_to_response
    from pinot_tpu.pql import optimize_request, parse_pql
    from pinot_tpu.tools.datagen import lineitem_schema, synthetic_lineitem_segment
    from pinot_tpu.tools.scan_engine import ScanQueryProcessor

    segs = [synthetic_lineitem_segment(5000, seed=81 + i, name=f"dr{i}") for i in range(2)]
    oracle = ScanQueryProcessor(lineitem_schema(), [r for s in segs for r in s.rows()])
    # l_shipdate is sorted in every synthetic segment -> docrange leaf;
    # grouping by the same column forces the gfwd role stream
    pql = (
        "SELECT count(*), sum(l_quantity) FROM lineitem "
        "WHERE l_shipdate >= '1995-01-01' GROUP BY l_shipdate TOP 7"
    )
    req = optimize_request(parse_pql(pql))
    got = reduce_to_response(req, [QueryExecutor().execute(segs, req)])
    want = oracle.execute(parse_pql(pql))
    assert json.dumps(got.to_json()["aggregationResults"], sort_keys=True) == \
        json.dumps(want.to_json()["aggregationResults"], sort_keys=True)


def test_host_fallback_factorization_branches_match_oracle(monkeypatch):
    """Both group-key factorization branches of the vectorized host path
    (peak-RSS satellite): the DENSE presence+cumsum-rank branch engages
    only when the key space is small relative to the matched rows (its
    space-sized transients are now bool + int32, not two int64 arrays);
    a SPARSE key space takes np.unique whose footprint scales with rows.
    Responses must match the scan oracle on both."""
    from pinot_tpu.engine import config

    monkeypatch.setattr(config, "MAX_GROUP_CAPACITY", 64)  # force host path

    schema = Schema(
        "big",
        dimensions=[
            FieldSpec("a", DataType.INT),
            FieldSpec("b", DataType.INT),
            FieldSpec("c", DataType.INT),
        ],
        metrics=[FieldSpec("m", DataType.INT, FieldType.METRIC)],
    )
    rows = random_rows(schema, 1600, seed=13, cardinality=20)
    seg = build_segment(schema, rows, "big", "fseg")

    # dense: space = 20*20 = 400 <= 8 * ~1600 matched rows
    got, want = run_both(
        schema, rows, [seg],
        "SELECT count(*), sum(m) FROM big GROUP BY a, b TOP 10",
    )
    assert got == want

    # sparse: space = 20^3 = 8000 > 8 * (few matched rows)
    needle = rows[0]["a"]
    got, want = run_both(
        schema, rows, [seg],
        f"SELECT count(*), sum(m) FROM big WHERE a = {needle} GROUP BY a, b, c TOP 10",
    )
    assert got == want
