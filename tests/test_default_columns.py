"""Schema-evolution default columns (VERDICT r3 #5).

Reference behavior: when a schema grows, segments built before the new
column get a synthesized default-value column at load time
(pinot-core ``segment/index/loader/defaultcolumn/
BaseDefaultColumnHandler.java:18``), so old rows keep answering —
with default-null semantics — instead of the segment being pruned.
"""
import numpy as np

from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema, TimeFieldSpec
from pinot_tpu.pql import parse_pql
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.segment.default_column import inject_default_columns, make_default_column
from pinot_tpu.server.instance import ServerInstance
from pinot_tpu.tools.cluster_harness import InProcessCluster
from pinot_tpu.tools.datagen import make_test_schema, random_rows


def _grown_schema(base: Schema) -> Schema:
    """base + a new string dimension, MV int dimension, and a metric."""
    return Schema(
        base.schema_name,
        dimensions=list(base.dimensions)
        + [
            FieldSpec("newDim", DataType.STRING, FieldType.DIMENSION),
            FieldSpec(
                "newMV", DataType.INT_ARRAY, FieldType.DIMENSION, single_value=False
            ),
        ],
        metrics=list(base.metrics)
        + [FieldSpec("newMet", DataType.DOUBLE, FieldType.METRIC)],
        time_field=base.time_field,
    )


# ---------------------------------------------------------------- unit
def test_make_default_column_sv_string():
    spec = FieldSpec("d", DataType.STRING, FieldType.DIMENSION)
    col = make_default_column(spec, 7)
    assert col.metadata.cardinality == 1
    assert col.metadata.is_sorted
    assert col.dictionary.get(0) == "null"
    np.testing.assert_array_equal(col.fwd, np.zeros(7, dtype=np.int32))
    assert col.values_for_doc(3) == "null"


def test_make_default_column_metric_and_mv():
    met = make_default_column(FieldSpec("m", DataType.DOUBLE, FieldType.METRIC), 4)
    assert met.values_for_doc(0) == 0.0  # metric default null is additive identity
    mv = make_default_column(
        FieldSpec("mv", DataType.INT_ARRAY, FieldType.DIMENSION, single_value=False), 4
    )
    assert not mv.is_single_value
    assert mv.values_for_doc(2) == [-(2**31)]  # INT dimension null


def test_inject_skips_existing_and_time():
    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 20, seed=5)
    seg = build_segment(schema, rows, "t", "s0")
    grown = _grown_schema(schema)
    assert inject_default_columns(seg, grown) == 3
    assert seg.has_column("newDim") and seg.has_column("newMet")
    # metadata stays consistent with the live column set (converters
    # and persistence iterate metadata.columns)
    assert "newDim" in seg.metadata.columns and "newMet" in seg.metadata.columns
    # idempotent; never resynthesizes present columns or the time column
    assert inject_default_columns(seg, grown) == 0
    # a schema whose time column is absent from the segment: not injected
    other = Schema(
        "t2",
        dimensions=[FieldSpec("dimStr", DataType.STRING, FieldType.DIMENSION)],
        time_field=TimeFieldSpec("otherTime", DataType.INT, time_unit="DAYS"),
    )
    seg2 = build_segment(schema, rows, "t", "s1")
    inject_default_columns(seg2, other)
    assert not seg2.has_column("otherTime")


# ------------------------------------------------------ server instance
def test_server_retro_patches_loaded_segments():
    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 50, seed=7)
    old_seg = build_segment(schema, rows, "testTable_OFFLINE", "old")
    server = ServerInstance("s0")
    server.add_segment("testTable_OFFLINE", old_seg)  # loaded pre-evolution

    grown = _grown_schema(schema)
    server.set_table_schema("testTable_OFFLINE", grown)  # evolve: retro-patch
    assert old_seg.has_column("newDim")

    new_rows = [dict(r, newDim="x", newMV=[1, 2], newMet=2.5) for r in rows]
    new_seg = build_segment(grown, new_rows, "testTable_OFFLINE", "new")
    server.add_segment("testTable_OFFLINE", new_seg)  # future loads auto-patch
    assert new_seg.has_column("newDim")


# --------------------------------------------------------- end-to-end
def test_mixed_age_segments_answer_with_defaults(tmp_path):
    cluster = InProcessCluster(num_servers=2, data_dir=str(tmp_path))
    schema = make_test_schema(with_mv=False)
    physical = cluster.add_offline_table(schema)
    rows = random_rows(schema, 120, seed=9)
    cluster.upload(physical, build_segment(schema, rows[:60], physical, "oldSeg"))

    # grow the schema, then upload a segment built against it
    grown = _grown_schema(schema)
    cluster.controller.add_schema(grown)
    new_rows = [dict(r, newDim="fresh", newMV=[3], newMet=1.0) for r in rows[60:]]
    cluster.upload(physical, build_segment(grown, new_rows, physical, "newSeg"))

    # old segment participates: all 120 rows scanned, not 60
    resp = cluster.query("SELECT count(*) FROM testTable GROUP BY newDim TOP 10")
    groups = {
        tuple(g.group): g.value for g in resp.aggregation_results[0].group_by_result
    }
    assert groups == {("fresh",): 60.0, ("null",): 60.0}

    # metric default is 0: sum over all rows == sum over new rows only
    resp2 = cluster.query("SELECT sum(newMet) FROM testTable")
    assert resp2.num_docs_scanned == 120
    assert resp2.aggregation_results[0].value == 60.0

    # filter on the default value selects exactly the old rows
    resp3 = cluster.query("SELECT count(*) FROM testTable WHERE newDim = 'null'")
    assert resp3.aggregation_results[0].value == 60.0


def test_realtime_rollover_picks_up_evolved_schema(tmp_path):
    """Schema evolution on a live realtime table: the next segment
    rollover consumes the new column's real streamed values; sealed
    pre-evolution segments answer with defaults."""
    from pinot_tpu.realtime.llc import RESP_KEEP, make_segment_name
    from pinot_tpu.realtime.stream import MemoryStreamProvider

    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path))
    base = Schema(
        "meetupRsvp",
        dimensions=[FieldSpec("venue_name", DataType.STRING)],
        metrics=[FieldSpec("rsvp_count", DataType.INT, FieldType.METRIC)],
        time_field=TimeFieldSpec("mtime", DataType.LONG, time_unit="MILLISECONDS"),
    )
    stream = MemoryStreamProvider(num_partitions=1)
    physical = cluster.add_realtime_table(base, stream, rows_per_segment=50)
    for i in range(50):
        stream.produce({"venue_name": f"v{i % 3}", "rsvp_count": 1, "mtime": 1000 + i})

    seg0 = make_segment_name(physical, 0, 0)
    dm0 = cluster.controller.realtime_manager.consumers_of(seg0)[0]
    dm0.consume_step(max_rows=1000)  # fills segment 0 with old-schema rows

    # evolve while segment 0 is still consuming: the evolution applies
    # to segments created from here on (the reference's semantics — a
    # consuming segment keeps the schema it was created with)
    grown = Schema(
        base.schema_name,
        dimensions=list(base.dimensions),
        metrics=list(base.metrics)
        + [FieldSpec("guests", DataType.INT, FieldType.METRIC)],
        time_field=base.time_field,
    )
    cluster.controller.add_schema(grown)
    assert dm0.try_commit() == RESP_KEEP  # seals; rollover creates seg1 post-evolution

    # rows with the new column stream into the post-evolution segment
    for i in range(50):
        stream.produce(
            {"venue_name": "v9", "rsvp_count": 1, "guests": 2, "mtime": 2000 + i}
        )
    seg1 = make_segment_name(physical, 0, 1)
    dm1 = cluster.controller.realtime_manager.consumers_of(seg1)[0]
    dm1.consume_step(max_rows=1000)
    assert dm1.try_commit() == RESP_KEEP

    # old rows: guests = 0 (metric default); new rows: real value 2
    resp = cluster.query("SELECT sum(guests) FROM meetupRsvp")
    assert resp.num_docs_scanned == 100
    assert resp.aggregation_results[0].value == 100.0  # 50 rows x 2 guests
