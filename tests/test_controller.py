"""Control-plane tests: ideal/external state, segment lifecycle,
replication + failover, retention/validation managers, REST API."""
import json
import time
import urllib.request

import pytest

from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema, TimeFieldSpec
from pinot_tpu.common.tableconfig import RetentionConfig, TableConfig
from pinot_tpu.controller.controller import Controller, ControllerHttpServer
from pinot_tpu.pql import parse_pql
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.tools.cluster_harness import InProcessCluster
from pinot_tpu.tools.datagen import make_test_schema, random_rows
from pinot_tpu.tools.scan_engine import ScanQueryProcessor


def make_cluster(num_servers=2, replication=1, tmp=None, http=False):
    cluster = InProcessCluster(num_servers=num_servers, data_dir=tmp, http=http)
    schema = make_test_schema(with_mv=False)
    physical = cluster.add_offline_table(schema, replication=replication)
    return cluster, schema, physical


def test_upload_and_query(tmp_path):
    cluster, schema, physical = make_cluster(tmp=str(tmp_path))
    rows = random_rows(schema, 300, seed=1)
    seg1 = build_segment(schema, rows[:150], physical, "s1")
    seg2 = build_segment(schema, rows[150:], physical, "s2")
    cluster.upload(physical, seg1)
    cluster.upload(physical, seg2)

    resp = cluster.query("SELECT count(*) FROM testTable")
    assert resp.num_docs_scanned == 300
    # logical name resolves to the _OFFLINE physical table
    oracle = ScanQueryProcessor(schema, rows)
    want = oracle.execute(parse_pql("SELECT sum(metInt) FROM testTable"))
    got = cluster.query("SELECT sum(metInt) FROM testTable")
    assert got.aggregation_results[0].value == want.aggregation_results[0].value

    # ideal state == external view, one replica each
    ideal = cluster.controller.resources.get_ideal_state(physical)
    view = cluster.controller.resources.get_external_view(physical)
    assert set(ideal) == {"s1", "s2"}
    assert ideal == view


def test_balanced_assignment(tmp_path):
    cluster, schema, physical = make_cluster(num_servers=2, tmp=str(tmp_path))
    rows = random_rows(schema, 100, seed=2)
    for i in range(4):
        cluster.upload(physical, build_segment(schema, rows, physical, f"seg{i}"))
    ideal = cluster.controller.resources.get_ideal_state(physical)
    counts = {}
    for seg, replicas in ideal.items():
        for server in replicas:
            counts[server] = counts.get(server, 0) + 1
    assert counts == {"server0": 2, "server1": 2}  # round-robin balance


def test_replication_and_failover(tmp_path):
    cluster, schema, physical = make_cluster(num_servers=2, replication=2, tmp=str(tmp_path))
    rows = random_rows(schema, 200, seed=3)
    cluster.upload(physical, build_segment(schema, rows, physical, "rseg"))
    ideal = cluster.controller.resources.get_ideal_state(physical)
    assert len(ideal["rseg"]) == 2  # two replicas

    assert cluster.query("SELECT count(*) FROM testTable").num_docs_scanned == 200

    # kill server0: routing must fail over to the surviving replica
    cluster.controller.resources.set_instance_alive("server0", False)
    resp = cluster.query("SELECT count(*) FROM testTable")
    assert resp.num_docs_scanned == 200
    assert not resp.exceptions

    # restart: reconcile reloads and both replicas serve again
    cluster.controller.resources.set_instance_alive("server0", True)
    assert cluster.query("SELECT count(*) FROM testTable").num_docs_scanned == 200


def test_delete_segment_and_table(tmp_path):
    cluster, schema, physical = make_cluster(tmp=str(tmp_path))
    rows = random_rows(schema, 80, seed=4)
    cluster.upload(physical, build_segment(schema, rows, physical, "d1"))
    cluster.upload(physical, build_segment(schema, rows, physical, "d2"))
    assert cluster.query("SELECT count(*) FROM testTable").num_docs_scanned == 160

    cluster.controller.delete_segment(physical, "d1")
    assert cluster.query("SELECT count(*) FROM testTable").num_docs_scanned == 80
    assert not cluster.controller.store.exists(physical, "d1")

    cluster.controller.delete_table(physical)
    resp = cluster.query("SELECT count(*) FROM testTable")
    assert resp.exceptions  # routing gone


def test_retention_manager(tmp_path):
    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path))
    schema = Schema(
        "rt",
        metrics=[FieldSpec("m", DataType.INT, FieldType.METRIC)],
        time_field=TimeFieldSpec("days", DataType.INT, time_unit="DAYS"),
    )
    cluster.controller.add_schema(schema)
    physical = cluster.controller.add_table(
        TableConfig(
            table_name="rt",
            retention=RetentionConfig(retention_time_unit="DAYS", retention_time_value=30),
        )
    )
    now_days = int(time.time() // 86400)
    old = build_segment(schema, [{"m": 1, "days": now_days - 100}], physical, "old")
    fresh = build_segment(schema, [{"m": 2, "days": now_days}], physical, "fresh")
    cluster.upload(physical, old)
    cluster.upload(physical, fresh)
    assert cluster.query("SELECT count(*) FROM rt").num_docs_scanned == 2

    cluster.controller.retention_manager.run_once()
    assert cluster.controller.resources.segments_of(physical) == ["fresh"]
    assert cluster.query("SELECT count(*) FROM rt").num_docs_scanned == 1


def test_validation_manager_repairs(tmp_path):
    cluster, schema, physical = make_cluster(num_servers=1, tmp=str(tmp_path))
    rows = random_rows(schema, 50, seed=6)
    cluster.upload(physical, build_segment(schema, rows, physical, "v1"))

    # simulate a server that lost the segment (e.g. restart without disk)
    cluster.servers[0].remove_segment(physical, "v1")
    view = cluster.controller.resources.external_views[physical]
    view["v1"]["server0"] = "OFFLINE"
    cluster.controller.validation_manager.run_once()
    assert cluster.controller.resources.get_external_view(physical)["v1"]["server0"] == "ONLINE"
    assert cluster.query("SELECT count(*) FROM testTable").num_docs_scanned == 50


def test_status_checker(tmp_path):
    cluster, schema, physical = make_cluster(num_servers=1, tmp=str(tmp_path))
    rows = random_rows(schema, 10, seed=7)
    cluster.upload(physical, build_segment(schema, rows, physical, "sc1"))
    cluster.controller.status_checker.run_once()
    snap = cluster.controller.status_checker.metrics.snapshot()
    assert snap["gauges"][f"{physical}.percentSegmentsAvailable"] == 100.0
    assert snap["gauges"][f"{physical}.segmentCount"] == 1


def test_schema_required_before_table(tmp_path):
    controller = Controller(str(tmp_path))
    with pytest.raises(ValueError):
        controller.add_table(TableConfig(table_name="nope"))


def test_controller_http(tmp_path):
    controller = Controller(str(tmp_path))
    http = ControllerHttpServer(controller)
    http.start()
    base = f"http://127.0.0.1:{http.port}"
    try:
        schema = make_test_schema(with_mv=False)
        req = urllib.request.Request(
            base + "/schemas",
            data=json.dumps(schema.to_json()).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=5) as r:
            assert json.loads(r.read())["status"] == "ok"

        req = urllib.request.Request(
            base + "/tables",
            data=json.dumps(TableConfig("testTable").to_json()).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=5) as r:
            assert json.loads(r.read())["table"] == "testTable_OFFLINE"

        with urllib.request.urlopen(base + "/tables", timeout=5) as r:
            assert json.loads(r.read())["tables"] == ["testTable_OFFLINE"]

        with urllib.request.urlopen(base + "/schemas/testTable", timeout=5) as r:
            assert json.loads(r.read())["schemaName"] == "testTable"

        with urllib.request.urlopen(base + "/tables/testTable_OFFLINE/segments", timeout=5) as r:
            assert json.loads(r.read())["segments"] == []
    finally:
        http.stop()


def test_dashboard_pages_and_pql_proxy(tmp_path):
    """Ops UI (pinot-dashboard analog): home, per-table, query-console
    pages, and the PqlQueryResource-style /pql proxy to a live broker."""
    cluster, schema, physical = make_cluster(tmp=str(tmp_path / "ctrl"), http=True)
    rows = random_rows(schema, 30, seed=8)
    cluster.upload(physical, build_segment(schema, rows, physical, "dash1"))
    http = ControllerHttpServer(cluster.controller)
    http.start()
    base = f"http://127.0.0.1:{http.port}"
    try:
        with urllib.request.urlopen(base + "/", timeout=5) as r:
            home = r.read().decode()
        assert "pinot_tpu cluster" in home
        assert physical in home and "server0" in home

        with urllib.request.urlopen(
            base + f"/dashboard/table/{physical}", timeout=5
        ) as r:
            table_page = r.read().decode()
        assert "dash1" in table_page
        assert "dimStr" in table_page  # schema rendered

        with urllib.request.urlopen(base + "/dashboard/query", timeout=5) as r:
            assert "Query console" in r.read().decode()

        req = urllib.request.Request(
            base + "/pql",
            data=json.dumps({"pql": "SELECT count(*) FROM testTable"}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        assert out["numDocsScanned"] == 30, out
    finally:
        http.stop()
        cluster.stop()


def test_upload_refresh_replaces_segment(tmp_path):
    """Re-uploading a segment with the same name refreshes data
    (UploadRefreshDeleteIntegrationTest analog; CRC changes force reload)."""
    cluster, schema, physical = make_cluster(num_servers=1, tmp=str(tmp_path))
    rows_v1 = random_rows(schema, 60, seed=10)
    cluster.upload(physical, build_segment(schema, rows_v1, physical, "refresh_me"))
    assert cluster.query("SELECT count(*) FROM testTable").num_docs_scanned == 60

    rows_v2 = random_rows(schema, 90, seed=11)
    cluster.upload(physical, build_segment(schema, rows_v2, physical, "refresh_me"))
    assert cluster.query("SELECT count(*) FROM testTable").num_docs_scanned == 90

    cluster.controller.delete_segment(physical, "refresh_me")
    assert cluster.query("SELECT count(*) FROM testTable").num_docs_scanned == 0


def test_http_path_traversal_rejected(tmp_path):
    """Percent-encoded '/' or '..' in path segments must not reach the
    segment store as filesystem paths."""
    cluster, schema, physical = make_cluster(tmp=str(tmp_path))
    http = ControllerHttpServer(cluster.controller)
    http.start()
    base = f"http://127.0.0.1:{http.port}"
    try:
        for path in (
            "/segments/..%2F..%2Fetc/x/file",
            "/tables/..%2F..",
            "/dashboard/table/..",
        ):
            code = None
            try:
                urllib.request.urlopen(base + path, timeout=5)
            except urllib.error.HTTPError as e:
                code = e.code
            assert code in (400, 404), (path, code)
    finally:
        http.stop()
        cluster.stop()
