"""Controller metadata durability: schemas, table configs, ideal
states, and segment metadata (incl. LLC offset checkpoints) survive a
controller restart via the on-disk property store — the ZK
property-store role (``PinotHelixResourceManager.java:103``).  A fresh
controller over the same data dir recovers the cluster; re-registering
servers replay ideal state and reload segments; realtime consumption
resumes from the committed offsets."""
import json

import pytest

from pinot_tpu.common.datatable import deserialize_result, serialize_instance_request
from pinot_tpu.common.tableconfig import StreamConfig, TableConfig
from pinot_tpu.controller.controller import Controller
from pinot_tpu.pql import parse_pql
from pinot_tpu.realtime.llc import RESP_KEEP, make_segment_name
from pinot_tpu.realtime.stream import FileBasedStreamProvider
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.server.instance import ServerInstance
from pinot_tpu.server.starter import ServerStarter
from pinot_tpu.tools.datagen import make_test_schema, random_rows

TABLE = "testTable"


def _count_docs(server: ServerInstance, physical: str) -> int:
    payload = serialize_instance_request(
        1, f"SELECT count(*) FROM {physical}", physical, [], 10_000
    )
    res = deserialize_result(server.handle_request(payload))
    return res.num_docs_scanned


def test_offline_state_survives_controller_restart(tmp_path):
    data_dir = str(tmp_path / "ctl")
    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 300, seed=17)

    c1 = Controller(data_dir)
    server = ServerInstance("srvA")
    ServerStarter(server, c1.resources).start()
    c1.add_schema(schema)
    physical = c1.add_table(TableConfig(table_name=TABLE, table_type="OFFLINE"))
    for i in range(2):
        seg = build_segment(schema, rows[i * 150 : (i + 1) * 150], physical, f"d{i}")
        c1.upload_segment(physical, seg)
    ideal_before = c1.resources.get_ideal_state(physical)
    assert _count_docs(server, physical) == 300
    del c1, server  # crash: nothing survives but the data dir

    c2 = Controller(data_dir)
    # metadata recovered
    assert c2.resources.get_schema(TABLE) is not None
    assert physical in c2.resources.tables()
    assert c2.resources.get_ideal_state(physical) == ideal_before
    info = c2.resources.get_segment_metadata(physical, "d0")
    assert info is not None and info["metadata"].num_docs == 150
    assert info["dir"]
    # external views start empty until participants re-register
    assert c2.resources.get_external_view(physical) == {}

    # a re-registering server replays ideal state and reloads from store
    server2 = ServerInstance("srvA")
    ServerStarter(server2, c2.resources).start()
    view = c2.resources.get_external_view(physical)
    assert view == {"d0": {"srvA": "ONLINE"}, "d1": {"srvA": "ONLINE"}}
    assert _count_docs(server2, physical) == 300


def test_realtime_offsets_survive_controller_restart(tmp_path):
    data_dir = str(tmp_path / "ctl")
    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 100, seed=23)
    stream_file = tmp_path / "p0.jsonl"
    with open(stream_file, "w") as f:
        for r in rows[:75]:
            f.write(json.dumps(r) + "\n")
    stream = FileBasedStreamProvider([str(stream_file)])

    c1 = Controller(data_dir)
    server = ServerInstance("srvA")
    ServerStarter(server, c1.resources).start()
    c1.add_schema(schema)
    config = TableConfig(
        table_name=TABLE,
        table_type="REALTIME",
        stream=StreamConfig(rows_per_segment=50),
    )
    physical = c1.add_realtime_table(config, stream)
    seg0 = make_segment_name(physical, 0, 0)
    seg1 = make_segment_name(physical, 0, 1)

    # consume 75 rows: seg0 seals at 50, seg1 consuming holds 25
    dm0 = c1.realtime_manager.consumers_of(seg0)[0]
    dm0.consume_step(max_rows=1000)
    assert dm0.try_commit() == RESP_KEEP
    dm1 = c1.realtime_manager.consumers_of(seg1)[0]
    dm1.consume_step(max_rows=1000)
    assert _count_docs(server, physical) == 75
    committed = c1.resources.get_segment_metadata(physical, seg0)
    assert committed["metadata"].custom["endOffset"] == 50
    del c1, server, dm0, dm1  # crash

    # restart: offsets + stream descriptor recovered from disk
    c2 = Controller(data_dir)
    info = c2.resources.get_segment_metadata(physical, seg0)
    assert info["metadata"].custom["endOffset"] == 50
    ideal = c2.resources.get_ideal_state(physical)
    assert ideal[seg0] == {"srvA": "ONLINE"}
    assert ideal[seg1] == {"srvA": "CONSUMING"}

    server2 = ServerInstance("srvA")
    ServerStarter(server2, c2.resources).start()
    # sealed segment reloaded from the store; consumer resumed at the
    # committed offset (uncommitted rows re-consumed, as the reference)
    dm1b = c2.realtime_manager.consumers_of(seg1)[0]
    assert dm1b.offset == 50
    dm1b.consume_step(max_rows=1000)
    assert _count_docs(server2, physical) == 75

    # stream keeps flowing after the restart: 25 more rows seal seg1
    with open(stream_file, "a") as f:
        for r in rows[75:]:
            f.write(json.dumps(r) + "\n")
    dm1b.consume_step(max_rows=1000)
    assert dm1b.try_commit() == RESP_KEEP
    seg2 = make_segment_name(physical, 0, 2)
    assert c2.realtime_manager.consumers_of(seg2), "rollover consumer missing"
    assert _count_docs(server2, physical) == 100


def test_delete_table_clears_property_store(tmp_path):
    data_dir = str(tmp_path / "ctl")
    schema = make_test_schema(with_mv=False)
    c1 = Controller(data_dir)
    server = ServerInstance("srvA")
    ServerStarter(server, c1.resources).start()
    c1.add_schema(schema)
    physical = c1.add_table(TableConfig(table_name=TABLE, table_type="OFFLINE"))
    seg = build_segment(schema, random_rows(schema, 50, seed=3), physical, "d0")
    c1.upload_segment(physical, seg)
    c1.delete_table(physical)
    del c1, server

    c2 = Controller(data_dir)
    assert physical not in c2.resources.tables()
    assert c2.resources.get_segment_metadata(physical, "d0") is None
