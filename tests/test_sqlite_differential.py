"""Randomized differential testing against SQLite.

The reference establishes cluster-level correctness by loading the same
data into H2 and asserting result equality over thousands of generated
PQL/SQL pairs (pinot-integration-tests BaseClusterIntegrationTest.runQuery
:224, QueryGenerator.generateH2Sql :311-426).  SQLite plays H2's role
here: an INDEPENDENT engine, so a shared misunderstanding between our
TPU engine and our scan oracle cannot hide.

Queries go through the full in-process cluster (broker scatter-gather
over multiple servers/segments), not the engine directly.
"""
import math
import sqlite3

import pytest

from pinot_tpu.common.request import group_sort_ascending
from pinot_tpu.common.schema import DataType
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.tools.cluster_harness import InProcessCluster
from pinot_tpu.tools.datagen import make_test_schema, random_rows
from pinot_tpu.tools.query_gen import SqlDiffQueryGenerator

REL_TOL = 1e-4


def _norm(v):
    """Normalize a cell for cross-engine comparison: numeric if possible."""
    if isinstance(v, (int, float)):
        return float(v)
    try:
        return float(v)
    except (TypeError, ValueError):
        return str(v)


def _close(a, b):
    a, b = _norm(a), _norm(b)
    if isinstance(a, float) and isinstance(b, float):
        return math.isclose(a, b, rel_tol=REL_TOL, abs_tol=1e-6)
    return a == b


def _sqlite_type(dt: DataType) -> str:
    if dt == DataType.STRING:
        return "TEXT"
    if dt in (DataType.FLOAT, DataType.DOUBLE):
        return "REAL"
    return "INTEGER"


def _load_sqlite(schema, rows):
    conn = sqlite3.connect(":memory:")
    # regexp_like(col, pat) with the ENGINE's exact semantics
    # (re.search over str(value) — plan.py match_table REGEX), so
    # generated where-clauses run verbatim in both dialects
    import re as _re

    conn.create_function(
        "regexp_like", 2, lambda v, p: _re.search(p, str(v)) is not None
    )
    fields = [s for s in schema.all_fields() if s.single_value]
    cols = ", ".join(f"{s.name} {_sqlite_type(s.data_type)}" for s in fields)
    conn.execute(f"CREATE TABLE testTable ({cols})")
    names = [s.name for s in fields]
    ph = ", ".join("?" * len(names))
    conn.executemany(
        f"INSERT INTO testTable VALUES ({ph})",
        [[r[n] for n in names] for r in rows],
    )
    conn.commit()
    return conn


def _check_agg(q, resp, conn, errs):
    row = conn.execute(
        f"SELECT COUNT(*), {', '.join(q.agg_sql_exprs())} FROM testTable{q.where}"
    ).fetchone()
    matched = row[0]
    if matched == 0:
        # engines differ legitimately on empty-set aggregates (NULL vs
        # identity); assert only that ours also saw zero docs
        if resp.num_docs_scanned != 0:
            errs.append((q.pql, "expected 0 docs", resp.num_docs_scanned))
        return
    for i, want in enumerate(row[1:]):
        got = resp.aggregation_results[i].value
        if not _close(got, want):
            errs.append((q.pql, f"agg[{i}] got {got}", f"want {want}"))


def _check_group_by(q, resp, conn, errs, single_server):
    gcols = ", ".join(q.group_cols)
    rows = conn.execute(
        f"SELECT {gcols}, {', '.join(q.agg_sql_exprs())} "
        f"FROM testTable{q.where} GROUP BY {gcols}"
    ).fetchall()
    k = len(q.group_cols)
    # group key -> per-agg values; keys normalized like the engine renders
    table = {tuple(str(v) for v in r[:k]): r[k:] for r in rows}
    expect_n = min(q.top, len(table))
    # Distributed group-by is approximate by design once a server trims
    # its candidate set to topN*5 (reference semantics:
    # AggregationGroupByOperatorService.java:76 _trimSize = minTrimSize*5;
    # a group split across servers can lose low partials).  Values and
    # membership are exact only when no server can have trimmed.
    exact = single_server or len(table) <= max(q.top * 5, 100)
    for i, (func, _col) in enumerate(q.aggs):
        result = resp.aggregation_results[i].group_by_result
        if len(result) != expect_n:
            errs.append((q.pql, f"agg[{i}] {len(result)} groups", f"want {expect_n}"))
            continue
        for g in result:
            key = tuple(g.group)
            if key not in table:
                errs.append((q.pql, f"agg[{i}] ghost group {key}", "absent in sqlite"))
            elif exact and not _close(g.value, table[key][i]):
                errs.append(
                    (q.pql, f"agg[{i}] group {key} got {g.value}", f"want {table[key][i]}")
                )
        if not exact:
            continue
        # the returned groups must be a valid top-N by value (ascending
        # for min-style functions, descending otherwise, matching
        # BrokerReduceService trim semantics); compare value multisets
        # so tie-boundary group swaps don't false-positive
        asc = group_sort_ascending(func)
        all_vals = sorted((float(v[i]) for v in table.values()), reverse=not asc)
        want_vals = all_vals[:expect_n]
        got_vals = [float(_norm(g.value)) for g in result]
        for gv, wv in zip(sorted(got_vals, reverse=not asc), want_vals):
            if not math.isclose(gv, wv, rel_tol=REL_TOL, abs_tol=1e-6):
                errs.append((q.pql, f"agg[{i}] top values {got_vals}", f"want {want_vals}"))
                break


def _check_selection(q, resp, conn, errs):
    cols = ", ".join(q.select_cols)
    rows = conn.execute(f"SELECT {cols} FROM testTable{q.where}").fetchall()
    got_rows = resp.selection_results.rows if resp.selection_results else []
    expect_n = min(q.limit, len(rows))
    if len(got_rows) != expect_n:
        errs.append((q.pql, f"{len(got_rows)} rows", f"want {expect_n}"))
        return
    universe = {}
    for r in rows:
        key = tuple(_norm(v) for v in r)
        universe[key] = universe.get(key, 0) + 1
    for r in got_rows:
        key = tuple(_norm(v) for v in r)
        if universe.get(key, 0) <= 0:
            errs.append((q.pql, f"row {key}", "not in sqlite result (or overused)"))
        else:
            universe[key] -= 1
    if q.order_by:
        # ordered prefix of sort KEYS must match exactly (tie rows may
        # differ, but tied keys are equal so the key sequence is stable)
        idx = [q.select_cols.index(c) for c, _asc in q.order_by]
        ordered = sorted(
            (tuple(_norm(v) for v in r) for r in rows),
            key=lambda t: tuple(
                _SortKey(t[j], asc) for j, (_c, asc) in zip(idx, q.order_by)
            ),
        )
        want_keys = [tuple(t[j] for j in idx) for t in ordered[:expect_n]]
        got_keys = [tuple(_norm(r[j]) for j in idx) for r in got_rows]
        if got_keys != want_keys:
            errs.append((q.pql, f"order keys {got_keys[:5]}", f"want {want_keys[:5]}"))


class _SortKey:
    """Direction-aware sort key for mixed str/float columns."""

    __slots__ = ("v", "asc")

    def __init__(self, v, asc):
        self.v = v
        self.asc = asc

    def __lt__(self, other):
        if self.v == other.v:
            return False
        lt = self.v < other.v
        return lt if self.asc else not lt

    def __eq__(self, other):
        return self.v == other.v


def _run(seed, num_queries=120, num_servers=2, num_segments=4):
    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 600, seed=seed)
    cluster = InProcessCluster(num_servers=num_servers)
    physical = cluster.add_offline_table(schema)
    chunk = len(rows) // num_segments
    for i in range(num_segments):
        part = rows[i * chunk : (i + 1) * chunk if i < num_segments - 1 else len(rows)]
        cluster.upload(physical, build_segment(schema, part, physical, f"sqd{i}"))
    conn = _load_sqlite(schema, rows)
    gen = SqlDiffQueryGenerator(schema, rows, seed=seed)
    errs = []
    try:
        for _ in range(num_queries):
            q = gen.next_diff()
            resp = cluster.query(q.pql)
            assert not resp.exceptions, (q.pql, resp.exceptions)
            if q.kind == "agg":
                _check_agg(q, resp, conn, errs)
            elif q.kind == "groupby":
                _check_group_by(q, resp, conn, errs, num_servers == 1)
            else:
                _check_selection(q, resp, conn, errs)
    finally:
        conn.close()
        cluster.stop()
    assert not errs, f"{len(errs)} mismatches vs sqlite; first 3: {errs[:3]}"


def test_sqlite_differential_seed1():
    _run(seed=101)


def test_sqlite_differential_seed2():
    _run(seed=202)


def test_sqlite_differential_many_segments():
    _run(seed=303, num_queries=60, num_servers=3, num_segments=7)


def test_sqlite_differential_single_server_exact():
    """One server sees every segment, so even huge group key spaces are
    exact (the regime the reference's H2 cluster tests run in)."""
    _run(seed=404, num_queries=60, num_servers=1, num_segments=4)


def test_having_matches_sqlite():
    """HAVING (broker-reduce group filter, beyond-reference PQL
    feature) vs SQLite's HAVING on single-agg group-bys, where the
    semantics map one-to-one. Single server so trims are exact."""
    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 600, seed=5)
    cluster = InProcessCluster(num_servers=1)
    physical = cluster.add_offline_table(schema)
    cluster.upload(physical, build_segment(schema, rows, physical, "hav0"))
    conn = _load_sqlite(schema, rows)
    cases = [
        ("SELECT sum(metInt) FROM testTable GROUP BY dimStr HAVING sum(metInt) > {t} TOP 500",
         "SELECT dimStr, SUM(metInt) FROM testTable GROUP BY dimStr HAVING SUM(metInt) > {t}"),
        ("SELECT count(*) FROM testTable GROUP BY dimStr HAVING count(*) >= {t} TOP 500",
         "SELECT dimStr, COUNT(*) FROM testTable GROUP BY dimStr HAVING COUNT(*) >= {t}"),
        ("SELECT avg(metDouble) FROM testTable WHERE metInt > 0 GROUP BY dimStr "
         "HAVING avg(metDouble) < {t} TOP 500",
         "SELECT dimStr, AVG(metDouble) FROM testTable WHERE metInt > 0 GROUP BY dimStr "
         "HAVING AVG(metDouble) < {t}"),
    ]
    errs = []
    try:
        # thresholds sit at the MIDPOINT between two adjacent distinct
        # aggregate values so no group's membership hinges on bitwise
        # float equality between engines, and each case provably
        # filters some groups and keeps some
        for pql_t, sql_t in cases:
            base_sql = sql_t.split(" HAVING")[0]
            vals = sorted({r[1] for r in conn.execute(base_sql).fetchall()})
            assert len(vals) >= 2, f"degenerate distribution for {base_sql}"
            mid = len(vals) // 2
            t = (vals[mid - 1] + vals[mid]) / 2
            want = {
                str(r[0]): r[1] for r in conn.execute(sql_t.format(t=t)).fetchall()
            }
            assert want, f"threshold {t} filtered everything: bad case"
            n_groups = conn.execute(
                f"SELECT COUNT(*) FROM ({base_sql})"
            ).fetchone()[0]
            assert len(want) < n_groups, f"threshold {t} filtered nothing: bad case"
            resp = cluster.query(pql_t.format(t=t))
            assert not resp.exceptions, resp.exceptions
            got = {
                g.group[0]: g.value
                for g in resp.aggregation_results[0].group_by_result
            }
            if set(got) != set(want):
                errs.append((pql_t.format(t=t), sorted(set(got) ^ set(want))[:5]))
                continue
            for k, v in got.items():
                if not _close(v, want[k]):
                    errs.append((pql_t.format(t=t), k, v, want[k]))
    finally:
        conn.close()
        cluster.stop()
    assert not errs, errs


def test_having_filters_all_agg_lists():
    """SQL semantics: a group failing HAVING disappears from EVERY
    aggregation's result list, not only the one the predicate names."""
    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 400, seed=9)
    cluster = InProcessCluster(num_servers=1)
    physical = cluster.add_offline_table(schema)
    cluster.upload(physical, build_segment(schema, rows, physical, "hav1"))
    conn = _load_sqlite(schema, rows)
    try:
        base = "SELECT dimStr, SUM(metInt), COUNT(*) FROM testTable GROUP BY dimStr"
        vals = sorted({r[1] for r in conn.execute(base).fetchall()})
        assert len(vals) >= 2, "degenerate SUM distribution: bad seed"
        t = (vals[len(vals) // 2 - 1] + vals[len(vals) // 2]) / 2
        want = {
            str(r[0]): (r[1], r[2])
            for r in conn.execute(base + f" HAVING SUM(metInt) > {t}").fetchall()
        }
        n_groups = conn.execute(f"SELECT COUNT(*) FROM ({base})").fetchone()[0]
        assert 0 < len(want) < n_groups, "threshold must split the groups"
        resp = cluster.query(
            f"SELECT sum(metInt), count(*) FROM testTable GROUP BY dimStr "
            f"HAVING sum(metInt) > {t} TOP 500"
        )
        assert not resp.exceptions, resp.exceptions
        for i in range(2):  # BOTH agg lists carry only passing groups
            got = {
                g.group[0]: g.value
                for g in resp.aggregation_results[i].group_by_result
            }
            assert set(got) == set(want), (i, sorted(set(got) ^ set(want)))
            for k, v in got.items():
                assert _close(v, want[k][i]), (i, k, v, want[k][i])
    finally:
        conn.close()
        cluster.stop()
