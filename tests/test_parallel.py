"""Multi-chip (8-device virtual CPU mesh) execution tests.

Validates the shard_map path: segment axis sharded over the mesh,
psum/pmin/pmax merge over the mesh axis, results identical to the
single-device vmapped path and to the scan oracle.
"""
import jax
import pytest

from pinot_tpu.engine.executor import QueryExecutor
from pinot_tpu.engine.reduce import reduce_to_response
from pinot_tpu.parallel import default_mesh
from pinot_tpu.pql import parse_pql, optimize_request
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.tools.datagen import make_test_schema, random_rows
from pinot_tpu.tools.scan_engine import ScanQueryProcessor

NUM_SEGMENTS = 6  # deliberately not divisible by 8 -> exercises padding


@pytest.fixture(scope="module")
def setup():
    schema = make_test_schema()
    rows = random_rows(schema, 900, seed=5, cardinality=12)
    chunk = len(rows) // NUM_SEGMENTS
    segments = [
        build_segment(
            schema,
            rows[i * chunk : (i + 1) * chunk if i < NUM_SEGMENTS - 1 else len(rows)],
            "testTable",
            f"pseg{i}",
        )
        for i in range(NUM_SEGMENTS)
    ]
    mesh = default_mesh()
    return schema, rows, segments, mesh


QUERIES = [
    "SELECT count(*) FROM testTable",
    "SELECT sum(metInt), min(metDouble), max(metDouble), avg(metFloat) FROM testTable",
    "SELECT count(*) FROM testTable WHERE dimStr <> 'zz' AND metInt > 2000",
    "SELECT sum(metInt) FROM testTable GROUP BY dimStr TOP 5",
    "SELECT min(metDouble), count(*) FROM testTable GROUP BY dimStr, dimInt TOP 7",
    "SELECT distinctcount(dimInt), percentile90(metInt) FROM testTable",
    "SELECT distinctcounthll(dimLong) FROM testTable",
    "SELECT countmv(dimStrMV) FROM testTable GROUP BY dimStrMV TOP 5",
    "SELECT dimStr, metInt FROM testTable ORDER BY metInt DESC LIMIT 7",
    "SELECT dimInt FROM testTable WHERE dimStr > 'm' LIMIT 12",
]


def test_mesh_has_8_devices(setup):
    _, _, _, mesh = setup
    assert mesh.devices.size == 8


@pytest.mark.parametrize("pql", QUERIES)
def test_sharded_matches_oracle(setup, pql):
    schema, rows, segments, mesh = setup
    oracle = ScanQueryProcessor(schema, rows)
    req_s = optimize_request(parse_pql(pql))
    req_o = optimize_request(parse_pql(pql))
    sharded = reduce_to_response(req_s, [QueryExecutor(mesh=mesh).execute(segments, req_s)])
    want = oracle.execute(req_o)
    gj, wj = sharded.to_json(), want.to_json()
    for k in ("timeUsedMs", "cost", "numEntriesScannedInFilter", "numEntriesScannedPostFilter",
              "numSegmentsQueried", "numServersQueried", "numServersResponded"):
        gj.pop(k, None)
        wj.pop(k, None)
    assert gj == wj


@pytest.mark.parametrize("pql", QUERIES[:6])
def test_sharded_matches_single_device(setup, pql):
    _, _, segments, mesh = setup
    req_a = optimize_request(parse_pql(pql))
    req_b = optimize_request(parse_pql(pql))
    a = reduce_to_response(req_a, [QueryExecutor(mesh=mesh).execute(segments, req_a)])
    b = reduce_to_response(req_b, [QueryExecutor().execute(segments, req_b)])
    aj, bj = a.to_json(), b.to_json()
    aj.pop("cost", None); bj.pop("cost", None)  # timing is path-dependent
    # filter-work accounting is tier-dependent: the single-device path
    # may take the bit-sliced tier (counts plane words) while the mesh
    # path scans rows — results stay exact either way
    aj.pop("numEntriesScannedInFilter", None); bj.pop("numEntriesScannedInFilter", None)
    assert aj == bj


def test_multihost_mesh_shapes(setup):
    """2-D (hosts, chips) mesh construction + flattening (structural
    validation of the DCN/ICI layering; single-process here)."""
    from pinot_tpu.parallel.multihost import flatten_to_segment_mesh, make_multihost_mesh

    mesh = make_multihost_mesh()
    assert mesh.devices.size == 8
    assert mesh.axis_names[-1] == "segments"
    flat = flatten_to_segment_mesh(mesh)
    assert flat.devices.shape == (8,)

    # the query kernel runs on the flattened mesh unchanged
    _, _, segments, _ = setup
    from pinot_tpu.engine.executor import QueryExecutor
    from pinot_tpu.engine.reduce import reduce_to_response
    from pinot_tpu.pql import parse_pql

    req = parse_pql("SELECT count(*) FROM testTable")
    resp = reduce_to_response(req, [QueryExecutor(mesh=flat).execute(segments, req)])
    assert resp.num_docs_scanned == 900


@pytest.mark.parametrize(
    "pql",
    [
        "SELECT count(*) FROM testTable",
        "SELECT sum(metInt), min(metDouble) FROM testTable GROUP BY dimStr TOP 5",
        "SELECT distinctcounthll(dimLong) FROM testTable",
    ],
)
def test_query_executes_on_2d_hosts_chips_mesh(setup, pql):
    """The full query kernel runs SPMD over a (hosts, chips) mesh: the
    segment axis shards over both axes and the merge collective names
    both, i.e. the reduction XLA lowers is the hierarchical ICI-then-DCN
    one described in multihost.py (simulated 2x4 here)."""
    from pinot_tpu.parallel.multihost import simulated_multihost_mesh

    schema, rows, segments, _ = setup
    mesh2d = simulated_multihost_mesh(2)
    assert mesh2d.devices.shape == (2, 4)
    assert mesh2d.axis_names == ("hosts", "segments")

    req = optimize_request(parse_pql(pql))
    req1 = optimize_request(parse_pql(pql))
    got = reduce_to_response(req, [QueryExecutor(mesh=mesh2d).execute(segments, req)])
    want = ScanQueryProcessor(schema, rows).execute(req1)
    gj, wj = got.to_json(), want.to_json()
    for k in ("timeUsedMs", "cost", "numEntriesScannedInFilter", "numEntriesScannedPostFilter",
              "numSegmentsQueried", "numServersQueried", "numServersResponded"):
        gj.pop(k, None)
        wj.pop(k, None)
    assert gj == wj


def test_phase_timers_recorded(setup):
    from pinot_tpu.engine.executor import QueryExecutor
    from pinot_tpu.pql import parse_pql
    from pinot_tpu.utils.metrics import ServerMetrics

    _, _, segments, _ = setup
    metrics = ServerMetrics("phased")
    ex = QueryExecutor(metrics=metrics)
    ex.execute(segments, parse_pql("SELECT sum(metInt) FROM testTable GROUP BY dimStr"))
    snap = metrics.snapshot()
    for phase in ("phase.staging", "phase.planBuild", "phase.planExec", "phase.finalize"):
        assert snap["timers"][phase]["count"] >= 1


def test_sharded_chunked_matches_unchunked(setup, monkeypatch):
    """Segment-axis chunking on the MESH path (per-device row budget,
    multiples of the device count per dispatch) combines into
    bit-identical results — the pod-scale analog of the single-chip
    capacity path.  24 segments over 8 devices with a 1-row budget
    splits into 3 chunked dispatches, so the cross-chunk
    combine_reduced path genuinely executes."""
    schema, rows, _, mesh = setup
    n_seg = 24  # 3 chunks of 8 under the tiny budget below
    per = max(1, len(rows) // n_seg)
    segments = [
        build_segment(schema, rows[i * per : (i + 1) * per], "testTable", f"ck{i}")
        for i in range(n_seg)
    ]
    from pinot_tpu.engine.kernel import _pick_chunk

    assert _pick_chunk(n_seg, 1024, 1 * 8, granularity=8) == 8  # really splits
    pql = (
        "SELECT sum(metInt), count(*), distinctcounthll(dimLong) "
        "FROM testTable GROUP BY dimStr TOP 5"
    )
    req = optimize_request(parse_pql(pql))
    monkeypatch.setenv("PINOT_TPU_CHUNK_ROWS", "0")
    plain = reduce_to_response(
        req, [QueryExecutor(mesh=mesh).execute(segments, req)]
    ).to_json()
    monkeypatch.setenv("PINOT_TPU_CHUNK_ROWS", "1")
    chunked = reduce_to_response(
        req, [QueryExecutor(mesh=mesh).execute(segments, req)]
    ).to_json()
    for k in ("timeUsedMs", "cost"):
        plain.pop(k, None)
        chunked.pop(k, None)
    assert plain == chunked


def test_northstar_config_chunked_sharded(monkeypatch):
    """The 1B-row north-star configuration (adevents, high-cardinality
    distinctcounthll GROUP BY campaign_id) at scaled-down shapes through
    make_chunked_sharded_kernel on the 8-device mesh: the chunk budget
    forces multiple mesh dispatches and the grouped-HLL register states
    (packed-sort lowering) must combine bit-identically across chunks
    AND devices, matching the unchunked single-mesh run."""
    from pinot_tpu.tools.datagen import synthetic_adevents_segment

    mesh = default_mesh()
    n_seg = 16  # 2 chunked dispatches of 8 under the budget below
    segments = [
        synthetic_adevents_segment(
            512,
            seed=300 + i,
            name=f"ns{i}",
            campaign_card=32,
            site_card=8,
            user_card=4096,
            user_universe=1 << 14,
        )
        for i in range(n_seg)
    ]
    pql = (
        "SELECT distinctcounthll(user_id), count(*) FROM adevents "
        "GROUP BY campaign_id TOP 10"
    )
    req = optimize_request(parse_pql(pql))
    monkeypatch.setenv("PINOT_TPU_CHUNK_ROWS", "0")
    plain = reduce_to_response(
        req, [QueryExecutor(mesh=mesh).execute(segments, req)]
    ).to_json()
    # budget = 1 row/device forces ceil(16/8) = 2 dispatches
    monkeypatch.setenv("PINOT_TPU_CHUNK_ROWS", "1")
    req2 = optimize_request(parse_pql(pql))
    chunked = reduce_to_response(
        req2, [QueryExecutor(mesh=mesh).execute(segments, req2)]
    ).to_json()
    for k in ("timeUsedMs", "cost"):
        plain.pop(k, None)
        chunked.pop(k, None)
    assert plain == chunked
    # the HLL estimates are real (non-zero distinct per campaign)
    aggs = [a for a in plain["aggregationResults"] if a["function"].startswith("distinctcounthll")]
    assert aggs and all(float(g["value"]) > 0 for g in aggs[0]["groupByResult"])
