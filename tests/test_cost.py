"""Cost-accounting plane (PR 6): per-query cost vector wire + merge
invariants (broker totals == sum of server totals, under failover /
hedging / partial responses / kill-server chaos), device-vs-host cost
consistency, HBM staging-ledger byte accuracy, ingest lag draining, the
perf regression gate, and pre-registered series."""
import json
import math
import os
import struct
import time

import pytest

from pinot_tpu.common.datatable import MAGIC, deserialize_result, serialize_result
from pinot_tpu.engine.results import IntermediateResult
from pinot_tpu.pql import parse_pql
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.tools.cluster_harness import InProcessCluster, single_server_broker
from pinot_tpu.tools.datagen import make_test_schema, random_rows

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------ wire
def test_cost_vector_wire_roundtrip_and_additive_merge():
    a = IntermediateResult(
        num_docs_scanned=5,
        cost={"bytesScanned": 100, "deviceMs": 1.5, "segmentsFullScan": 2},
    )
    b = deserialize_result(serialize_result(a))
    assert b.cost == a.cost
    b.merge(
        IntermediateResult(cost={"bytesScanned": 11, "hostMs": 2.0, "segmentsHost": 1})
    )
    assert b.cost == {
        "bytesScanned": 111,
        "deviceMs": 1.5,
        "hostMs": 2.0,
        "segmentsFullScan": 2,
        "segmentsHost": 1,
    }


def test_cost_wire_backward_compat_old_payload_without_cost():
    """A payload from a pre-cost peer (no trailing cost field) must
    still deserialize — mixed-version operation."""
    data = serialize_result(IntermediateResult(num_docs_scanned=7))
    # the trailing optional fields are empty cost dict (b"d"+i64(0) = 9
    # bytes), empty backpressure dict (9), empty plan-info list (9), the
    # join-payload None (b"N" = 1) and the freshness None (1); chop all
    # five and fix the length header to emulate the pre-cost wire format
    payload = data[16:-29]
    old = MAGIC + struct.pack("<Q", len(payload)) + payload
    res = deserialize_result(old)
    assert res.num_docs_scanned == 7
    assert res.cost == {}


# ------------------------------------------ invariant: broker == Σ servers
class _SpyTransport:
    """Wraps a transport, recording every successful reply's bytes (a
    raised attempt never delivered data, so it cannot count)."""

    def __init__(self, inner, delay_for=None, delay_s=0.0):
        self.inner = inner
        self.replies = []
        self.delay_for = delay_for
        self.delay_s = delay_s

    def request(self, address, payload, timeout=15.0):
        if self.delay_for is not None and address == self.delay_for:
            time.sleep(self.delay_s)
        reply = self.inner.request(address, payload, timeout)
        self.replies.append(reply)
        return reply

    def __getattr__(self, name):
        return getattr(self.inner, name)


def _sum_replies(replies):
    docs, cost = 0, {}
    for raw in replies:
        res = deserialize_result(raw)
        docs += res.num_docs_scanned
        for k, v in res.cost.items():
            cost[k] = cost.get(k, 0) + v
    return docs, cost


def _assert_invariant(resp, replies):
    docs, cost = _sum_replies(replies)
    assert resp.num_docs_scanned == docs
    assert set(resp.cost) == set(cost)
    for k, v in cost.items():
        assert math.isclose(resp.cost[k], v, rel_tol=1e-9), (k, resp.cost[k], v)
    # served-tier counts partition the queried segments exactly
    tiers = sum(
        resp.cost.get(k, 0)
        for k in (
            "segmentsPostings",
            "segmentsBitsliced",
            "segmentsZonemap",
            "segmentsFullScan",
            "segmentsHost",
            "segmentsStarTree",
        )
    )
    assert tiers == resp.num_segments_queried


@pytest.fixture(scope="module")
def cost_cluster():
    cluster = InProcessCluster(num_servers=2)
    schema = make_test_schema(with_mv=False)
    physical = cluster.add_offline_table(schema, replication=2)
    rows = random_rows(schema, 2400, seed=13)
    total = 0
    for i in range(4):
        seg = rows[i * 600 : (i + 1) * 600]
        cluster.upload(physical, build_segment(schema, seg, physical, f"cseg{i}"))
        total += len(seg)
    spy = _SpyTransport(cluster.transport)
    cluster.broker.transport = spy
    yield cluster, spy, total
    cluster.broker.transport = spy.inner
    cluster.stop()


COST_QUERIES = [
    "SELECT count(*) FROM testTable",
    "SELECT sum(metInt), max(metFloat) FROM testTable WHERE dimInt > 40",
    "SELECT sum(metInt) FROM testTable GROUP BY dimStr TOP 5",
    "SELECT dimStr, metInt FROM testTable ORDER BY metInt DESC LIMIT 5",
]


@pytest.mark.parametrize("pql", COST_QUERIES)
def test_broker_cost_equals_sum_of_server_costs(cost_cluster, pql):
    cluster, spy, total = cost_cluster
    spy.replies.clear()
    resp = cluster.query(pql)
    assert not resp.exceptions
    _assert_invariant(resp, spy.replies)
    assert resp.cost.get("bytesScanned", 0) > 0
    assert len(spy.replies) >= 2  # genuinely scattered across servers


def test_cost_invariant_under_replica_failover(cost_cluster):
    """A dead replica's attempts raise (no data): the broker re-covers
    on the alternate and the invariant holds over the merged replies."""
    cluster, spy, total = cost_cluster
    victim = cluster.servers[0].name
    spy.inner.set_down((victim, 0))
    try:
        spy.replies.clear()
        resp = cluster.query("SELECT count(*) FROM testTable")
        assert not resp.exceptions
        assert resp.num_retries >= 1
        assert not resp.partial_response
        assert resp.num_docs_scanned == total
        _assert_invariant(resp, spy.replies)
    finally:
        spy.inner.set_down((victim, 0), down=False)


def _sum_node_actuals(resp):
    summed = {}
    for node in resp.explain["servers"]:
        for k, v in (node.get("actualCost") or {}).items():
            summed[k] = summed.get(k, 0) + v
    return summed


def test_explain_analyze_actuals_sum_to_merged_cost(cost_cluster):
    """EXPLAIN ANALYZE per-server plan-node actuals sum EXACTLY to the
    merged BrokerResponse.cost (the introspection plane's core honesty
    invariant, sibling of the broker == Σ servers cost invariant)."""
    cluster, spy, total = cost_cluster
    resp = cluster.query("EXPLAIN ANALYZE SELECT count(*) FROM testTable")
    assert not resp.exceptions
    assert resp.explain["mode"] == "analyze"
    summed = _sum_node_actuals(resp)
    assert set(summed) == set(resp.cost)
    for k, v in resp.cost.items():
        assert math.isclose(summed[k], v, rel_tol=1e-9), k
    assert resp.explain["actualDocsScanned"] == resp.num_docs_scanned == total


def test_explain_analyze_actuals_sum_under_replica_failover(cost_cluster):
    """A dead replica's attempts deliver no data (and no plan node):
    after failover only the MERGED replies' nodes survive, so the
    actuals still sum exactly to the merged cost."""
    cluster, spy, total = cost_cluster
    victim = cluster.servers[0].name
    spy.inner.set_down((victim, 0))
    try:
        spy.replies.clear()
        resp = cluster.query("EXPLAIN ANALYZE SELECT count(*) FROM testTable")
        assert not resp.exceptions
        assert resp.num_retries >= 1 and not resp.partial_response
        summed = _sum_node_actuals(resp)
        assert set(summed) == set(resp.cost)
        for k, v in resp.cost.items():
            assert math.isclose(summed[k], v, rel_tol=1e-9), k
        assert resp.explain["actualDocsScanned"] == total
        # exactly the merged replies carry nodes: no phantom/duplicate
        # attribution from the failed attempts
        assert len(resp.explain["servers"]) == len(spy.replies)
    finally:
        spy.inner.set_down((victim, 0), down=False)


def test_explain_analyze_actuals_sum_under_partial_response(tmp_path):
    """Replication=1 with a dead server: the response degrades honestly
    AND the surviving servers' plan-node actuals still equal the merged
    cost — unserved segments attribute to nobody."""
    cluster = InProcessCluster(num_servers=2, data_dir=str(tmp_path))
    try:
        schema = make_test_schema(with_mv=False)
        physical = cluster.add_offline_table(schema, replication=1)
        rows = random_rows(schema, 1200, seed=17)
        for i in range(4):
            cluster.upload(
                physical,
                build_segment(
                    schema, rows[i * 300 : (i + 1) * 300], physical, f"xseg{i}"
                ),
            )
        spy = _SpyTransport(cluster.transport)
        cluster.broker.transport = spy
        victim = cluster.servers[0].name
        spy.inner.set_down((victim, 0))
        resp = cluster.query("EXPLAIN ANALYZE SELECT count(*) FROM testTable")
        assert resp.partial_response and resp.num_segments_unserved > 0
        summed = _sum_node_actuals(resp)
        assert set(summed) == set(resp.cost)
        for k, v in resp.cost.items():
            assert math.isclose(summed[k], v, rel_tol=1e-9), k
        assert 0 < resp.explain["actualDocsScanned"] < 1200
    finally:
        cluster.stop()


def test_cost_invariant_under_hedging(cost_cluster):
    """A hedged attempt's winner covers the identical segment set: the
    response cost must match the steady-state answer exactly for the
    integer components (a hedge must never double-count)."""
    cluster, spy, total = cost_cluster
    baseline = cluster.query("SELECT count(*) FROM testTable")
    broker = cluster.broker
    old_delay = broker.hedge_delay_ms
    victim = cluster.servers[0].name
    spy.delay_for, spy.delay_s = (victim, 0), 0.25
    broker.hedge_delay_ms = 30.0
    try:
        resp = cluster.query("SELECT count(*) FROM testTable")
        assert not resp.exceptions
        assert resp.num_hedges >= 1
        assert resp.num_docs_scanned == baseline.num_docs_scanned == total
        for k in ("segmentsPostings", "segmentsBitsliced", "segmentsZonemap",
                  "segmentsFullScan", "segmentsHost", "segmentsStarTree",
                  "segmentsPruned"):
            assert resp.cost.get(k, 0) == baseline.cost.get(k, 0), k
        assert resp.num_segments_queried == baseline.num_segments_queried
    finally:
        broker.hedge_delay_ms = old_delay
        spy.delay_for, spy.delay_s = None, 0.0


def test_cost_invariant_under_partial_response(tmp_path):
    """Replication=1 and a dead server: the response degrades honestly
    AND its cost equals the sum of what the surviving servers served."""
    cluster = InProcessCluster(num_servers=2, data_dir=str(tmp_path))
    try:
        schema = make_test_schema(with_mv=False)
        physical = cluster.add_offline_table(schema, replication=1)
        rows = random_rows(schema, 1200, seed=17)
        for i in range(4):
            cluster.upload(
                physical,
                build_segment(
                    schema, rows[i * 300 : (i + 1) * 300], physical, f"pseg{i}"
                ),
            )
        spy = _SpyTransport(cluster.transport)
        cluster.broker.transport = spy
        victim = cluster.servers[0].name
        spy.inner.set_down((victim, 0))
        spy.replies.clear()
        resp = cluster.query("SELECT count(*) FROM testTable")
        assert resp.partial_response and resp.num_segments_unserved > 0
        _assert_invariant(resp, spy.replies)
        assert 0 < resp.num_docs_scanned < 1200
    finally:
        cluster.stop()


@pytest.mark.chaos
def test_cost_invariant_under_kill_server_chaos(tmp_path):
    """Acceptance: the merge invariant holds through the kill-server
    scenario — a server dies, the stabilizer re-replicates, and every
    post-heal response's cost still equals the sum of its server
    replies with zero docs lost."""
    cluster = InProcessCluster(num_servers=3, data_dir=str(tmp_path))
    try:
        cluster.controller.stabilizer.grace_s = 0.0
        schema = make_test_schema(with_mv=False)
        physical = cluster.add_offline_table(schema, replication=2)
        rows = random_rows(schema, 1500, seed=23)
        total = 0
        for i in range(5):
            seg = rows[i * 300 : (i + 1) * 300]
            cluster.upload(physical, build_segment(schema, seg, physical, f"kseg{i}"))
            total += len(seg)
        spy = _SpyTransport(cluster.transport)
        cluster.broker.transport = spy

        victim = cluster.servers[0].name
        spy.inner.set_down((victim, 0))
        cluster.controller.resources.set_instance_alive(victim, False)
        for _ in range(2):
            cluster.controller.stabilizer.run_once()

        for pql in COST_QUERIES:
            spy.replies.clear()
            resp = cluster.query(pql)
            assert not resp.exceptions, (pql, resp.exceptions)
            assert not resp.partial_response
            _assert_invariant(resp, spy.replies)
        final = cluster.query("SELECT count(*) FROM testTable")
        assert final.num_docs_scanned == total
    finally:
        cluster.stop()


# ------------------------------------------- device vs host consistency
@pytest.mark.chaos
def test_host_failover_cost_consistent_with_device_path():
    """The same query served via host failover reports the same docs
    and result payload as the device run; only the tier/timing parts of
    the cost vector move (device -> host)."""
    from pinot_tpu.common.faults import DeviceFaultInjector

    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 2000, seed=31)
    segs = [
        build_segment(schema, rows[:1000], "costHeal", "ch0"),
        build_segment(schema, rows[1000:], "costHeal", "ch1"),
    ]
    inj = DeviceFaultInjector(seed=7)
    broker = single_server_broker(
        "costHeal", segs, pipeline=True, device_fault_injector=inj
    )
    try:
        pql = "SELECT sum(metInt) FROM costHeal GROUP BY dimStr TOP 5"
        healthy = broker.handle_pql(pql)
        assert not healthy.exceptions
        assert healthy.cost.get("segmentsFullScan", 0) + healthy.cost.get(
            "segmentsZonemap", 0
        ) == len(segs)
        assert healthy.cost.get("deviceMs", 0) > 0
        assert "segmentsHost" not in healthy.cost

        digest = inj.launches[-1].digest
        assert digest is not None
        inj.poison_plan(digest)
        failed_over = broker.handle_pql(pql)
        assert not failed_over.exceptions
        assert failed_over.cost.get("segmentsHost", 0) == len(segs)
        assert failed_over.cost.get("hostMs", 0) > 0
        # identical answer + docs accounting, path-independent
        assert failed_over.num_docs_scanned == healthy.num_docs_scanned
        hj, fj = healthy.to_json(), failed_over.to_json()
        for k in ("timeUsedMs", "requestId", "cost",
                  "numEntriesScannedInFilter", "numEntriesScannedPostFilter"):
            hj.pop(k, None)
            fj.pop(k, None)
        assert hj == fj
    finally:
        broker.local_servers[0].shutdown()


# ------------------------------------------------------- HBM ledger
def _independent_staged_bytes(staged) -> int:
    """Re-derive a staged table's device bytes straight off its arrays
    (independent of the ledger's own measurement helper)."""
    total = int(staged.num_docs_arr.nbytes)
    if staged._valid is not None:
        total += int(staged._valid.nbytes)
    for sc in staged.columns.values():
        for attr in ("fwd", "mv", "mv_counts", "dict_vals", "raw", "gfwd",
                     "hll_bucket", "hll_rho", "mv_raw", "bsi", "bsiv"):
            arr = getattr(sc, attr)
            if arr is not None:
                total += int(arr.nbytes)
    return total


def test_hbm_ledger_matches_staged_array_bytes_within_1pct():
    from pinot_tpu.engine import device as device_mod
    from pinot_tpu.engine.executor import QueryExecutor
    from pinot_tpu.pql import optimize_request

    device_mod.clear_staging_cache()
    assert device_mod.LEDGER.total_bytes() == 0

    schema = make_test_schema(with_mv=True)
    rows = random_rows(schema, 1500, seed=41)
    segs = [
        build_segment(schema, rows[:750], "ledgerTable", "ls0"),
        build_segment(schema, rows[750:], "ledgerTable", "ls1"),
    ]
    ex = QueryExecutor()
    for pql in (
        "SELECT count(*) FROM ledgerTable WHERE dimInt > 10",
        "SELECT sum(metInt) FROM ledgerTable GROUP BY dimStr TOP 5",
    ):
        req = optimize_request(parse_pql(pql))
        ex.execute(segs, req)

    expected = sum(
        _independent_staged_bytes(st) for st in device_mod._stage_cache.values()
    )
    got = device_mod.LEDGER.total_bytes()
    assert expected > 0
    assert abs(got - expected) <= 0.01 * expected, (got, expected)

    snap = device_mod.LEDGER.snapshot()
    assert snap["stagedBytes"] == got
    assert snap["highWatermarkBytes"] >= got
    assert "ledgerTable" in snap["byTable"]
    assert snap["byTable"]["ledgerTable"] == got  # only table staged
    assert snap["stagedTables"] == len(device_mod._stage_cache)
    assert sum(snap["byRole"].values()) == got

    # eviction visibility: quarantining a segment releases its bytes
    ev0, evb0 = snap["evictions"], snap["evictedBytes"]
    dropped = device_mod.evict_staged_segment("ls0")
    assert dropped >= 1
    snap2 = device_mod.LEDGER.snapshot()
    assert snap2["stagedBytes"] < got
    assert snap2["evictions"] > ev0
    assert snap2["evictedBytes"] > evb0
    device_mod.clear_staging_cache()
    assert device_mod.LEDGER.total_bytes() == 0


# ------------------------------------------------------- ingest lag
def test_ingest_lag_drains_to_zero_after_commit(tmp_path):
    from pinot_tpu.realtime.llc import make_segment_name
    from pinot_tpu.realtime.stream import MemoryStreamProvider

    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path))
    try:
        schema = _rsvp_schema()
        stream = MemoryStreamProvider(num_partitions=1)
        physical = cluster.add_realtime_table(schema, stream, rows_per_segment=50)
        server = cluster.servers[0]
        gauge = server.metrics.gauge(f"ingest.lag.{physical}.p0")

        for i in range(70):
            stream.produce(_rsvp_row(i))
        # nothing consumed yet: lag = full backlog (live set_fn read)
        assert gauge.value == 70

        seg0 = make_segment_name(physical, 0, 0)
        dm = cluster.controller.realtime_manager.consumers_of(seg0)[0]
        dm.consume_step(max_rows=1000)  # seals at the 50-row threshold
        assert gauge.value == 20
        assert dm.try_commit() == "KEEP"

        # post-commit: the rollover consumer owns the gauge; catching up
        # provably drains the lag to 0
        seg1 = make_segment_name(physical, 0, 1)
        dm1 = cluster.controller.realtime_manager.consumers_of(seg1)[0]
        assert dm1.offset == 50
        dm1.consume_step(max_rows=1000)
        assert gauge.value == 0

        assert server.metrics.meter("ingest.rowsConsumed").count == 70
        assert server.metrics.timer("ingest.commitMs").count >= 1
        assert cluster.controller.metrics.meter("segmentCommits").count == 1
        assert cluster.controller.metrics.timer("segmentCommitMs").count == 1

        # a STOPPED consumer detaches its gauge: its frozen offset must
        # not keep reporting phantom lag as producers write on
        cluster.controller.realtime_manager.release_segment_consumers(seg1)
        for i in range(70, 80):
            stream.produce(_rsvp_row(i))
        assert gauge.value == 0
    finally:
        cluster.stop()


def _rsvp_schema():
    from pinot_tpu.common.schema import (
        DataType, FieldSpec, FieldType, Schema, TimeFieldSpec,
    )

    return Schema(
        "costRsvp",
        dimensions=[FieldSpec("venue", DataType.STRING)],
        metrics=[FieldSpec("n", DataType.INT, FieldType.METRIC)],
        time_field=TimeFieldSpec("ts", DataType.LONG, time_unit="MILLISECONDS"),
    )


def _rsvp_row(i):
    return {"venue": f"v{i % 3}", "n": i % 5, "ts": 1_000_000 + i}


# ----------------------------------------------- pre-registered series
def test_cost_and_hbm_series_preregistered_at_zero():
    from pinot_tpu.broker.broker import BrokerRequestHandler
    from pinot_tpu.server.instance import ServerInstance
    from pinot_tpu.transport.local import LocalTransport
    from pinot_tpu.utils.metrics import prometheus_text

    server = ServerInstance("freshServer")
    try:
        text = server.metrics_text()
        for needle in (
            "cost_docsScanned_total",
            "cost_bytesScanned_total",
            "hbm_stagedBytes",
            "hbm_highWatermarkBytes",
            "hbm_qinputCacheBytes",
            "ingest_rowsConsumed_total",
            "cost_deviceMs_ms_count",
            "ingest_commitMs_ms_count",
        ):
            assert needle in text, needle
    finally:
        server.shutdown()

    broker = BrokerRequestHandler(LocalTransport(), {}, name="freshBroker")
    text = prometheus_text(broker.metrics)
    for needle in ("cost_docsScanned_total", "cost_bytesScanned_total",
                   "cost_hostMs_ms_count"):
        assert needle in text, needle


# ------------------------------------------------- slow-query log + dump
def test_querylog_and_trace_dump_render_cost(cost_cluster):
    from pinot_tpu.broker.querylog import SlowQueryLog
    from pinot_tpu.tools.trace_dump import render_cost, render_waterfall

    cluster, spy, total = cost_cluster
    broker = cluster.broker
    old_log = broker.querylog
    broker.querylog = SlowQueryLog(threshold_ms=0.0)  # record everything
    try:
        resp = cluster.query("SELECT count(*) FROM testTable", trace=True)
        entry = broker.querylog.entries()[0]
        assert entry["numDocsScanned"] == total
        assert entry["cost"].get("bytesScanned", 0) > 0
    finally:
        broker.querylog = old_log

    j = resp.to_json()
    out = render_waterfall(j["traceInfo"]) + render_cost(j)
    assert f"docs={total}" in out
    assert "bytes=" in out
    # device or host ms: whichever path served, the split is rendered
    assert ("deviceMs=" in out) or ("hostMs=" in out)


# ------------------------------------------------- capacity rollup
def test_debug_capacity_rollup_and_dashboard(tmp_path):
    """Controller /debug/capacity aggregates server HBM ledgers +
    ingest lag and broker per-table cost rates cluster-wide; the
    dashboard page renders it."""
    import urllib.request

    from pinot_tpu.controller.controller import (
        ControllerHttpServer,
        collect_capacity,
    )
    from pinot_tpu.server.network_starter import ServerAdminHttpServer

    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path), http=True)
    admin = None
    http = None
    try:
        schema = make_test_schema(with_mv=False)
        physical = cluster.add_offline_table(schema)
        rows = random_rows(schema, 600, seed=19)
        cluster.upload(physical, build_segment(schema, rows, physical, "capseg0"))
        for _ in range(2):
            resp = cluster.query("SELECT sum(metInt) FROM testTable WHERE dimInt > 5")
            assert not resp.exceptions

        # give the in-process server an admin HTTP surface and register
        # it as the instance url, the way the networked starter does
        admin = ServerAdminHttpServer(cluster.servers[0])
        admin.start()
        cluster.controller.resources.instances["server0"].url = admin.url

        cap = collect_capacity(cluster.controller)
        assert "server0" in cap["servers"]
        hbm = cap["servers"]["server0"]["hbm"]
        assert hbm["stagedBytes"] > 0
        # ledger attributes by PHYSICAL table (what is actually staged);
        # broker cost rates attribute by logical table (what was asked)
        assert physical in hbm["byTable"]
        assert cap["totals"]["stagedBytes"] == hbm["stagedBytes"]
        t = cap["tables"]["testTable"]
        assert t["docsScanned"] > 0 and t["bytesScanned"] > 0

        http = ControllerHttpServer(cluster.controller)
        http.start()
        base = f"http://127.0.0.1:{http.port}"
        with urllib.request.urlopen(base + "/debug/capacity", timeout=10) as r:
            over_http = json.loads(r.read())
        assert over_http["servers"]["server0"]["hbm"]["stagedBytes"] > 0
        with urllib.request.urlopen(base + "/dashboard/capacity", timeout=10) as r:
            page = r.read().decode()
        assert "Capacity" in page and "testTable" in page and "server0" in page
    finally:
        if http is not None:
            http.stop()
        if admin is not None:
            admin.stop()
        cluster.stop()


# --------------------------------------------------------- perf gate
def _bench_doc():
    from pinot_tpu.tools.perf_gate import load_bench

    return load_bench(os.path.join(REPO, "BENCH_r05.json"))


def test_perf_gate_identical_run_passes():
    from pinot_tpu.tools.perf_gate import compare

    base = _bench_doc()
    out = compare(base, json.loads(json.dumps(base)))
    assert out["verdict"] == "pass"
    assert out["compared"] >= 8
    assert all(m["ok"] for m in out["metrics"])


def test_perf_gate_fails_on_latency_and_throughput_regressions():
    from pinot_tpu.tools.perf_gate import compare

    base = _bench_doc()
    slow = json.loads(json.dumps(base))
    slow["detail"]["broker_p50_ms"] = base["detail"]["broker_p50_ms"] * 10
    out = compare(base, slow)
    assert out["verdict"] == "fail"
    bad = [m for m in out["metrics"] if not m["ok"]]
    assert [m["metric"] for m in bad] == ["detail.broker_p50_ms"]

    dead = json.loads(json.dumps(base))
    dead["value"] = base["value"] * 0.05
    out = compare(base, dead)
    assert out["verdict"] == "fail"
    assert any(m["metric"] == "value" for m in out["metrics"] if not m["ok"])

    # a wider tolerance scale can absorb a borderline regression
    mild = json.loads(json.dumps(base))
    mild["detail"]["broker_p50_ms"] = base["detail"]["broker_p50_ms"] * 2.8
    assert compare(base, mild)["verdict"] == "fail"
    assert compare(base, mild, tolerance_scale=2.0)["verdict"] == "pass"


def test_perf_gate_skips_on_config_mismatch():
    from pinot_tpu.tools.perf_gate import compare

    base = _bench_doc()
    other = json.loads(json.dumps(base))
    other["detail"]["total_rows"] = base["detail"]["total_rows"] * 8
    other["detail"]["broker_p50_ms"] = base["detail"]["broker_p50_ms"] * 50
    out = compare(base, other)
    assert out["verdict"] == "skipped"
    assert "detail.total_rows" in out["configMismatch"]
    # forced comparison still works for exploration
    assert compare(base, other, allow_config_mismatch=True)["verdict"] == "fail"


def test_perf_gate_cli_passes_against_committed_capture():
    """The tier-1 smoke: the gate binary runs clean against the
    committed capture compared with itself (same run => pass)."""
    from pinot_tpu.tools.perf_gate import main

    path = os.path.join(REPO, "BENCH_r05.json")
    assert main([path, "--baseline", path]) == 0
