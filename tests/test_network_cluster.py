"""Networked control plane: controller, servers, broker as real OS
processes coordinated over HTTP (the multi-JVM ClusterTest analog —
``pinot-integration-tests/.../ClusterTest.java:62`` — but with actual
process boundaries instead of one JVM).

Covers: instance registration + heartbeats, transition messages +
acks (segment download with local cache), broker cluster-state polling
for routing, liveness-based failover when a server is SIGKILLed.
"""
import json
import os
import select
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from pinot_tpu.common.tableconfig import TableConfig
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.segment.format import SEGMENT_FILE_NAME, write_segment
from pinot_tpu.tools.datagen import make_test_schema, random_rows

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TABLE = "netTable"
PHYSICAL = "netTable_OFFLINE"


def _admin_env():
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""  # no TPU tunnel in child processes
    env["JAX_PLATFORMS"] = "cpu"
    env["PINOT_TPU_FORCE_CPU"] = "1"
    if os.environ.get("PINOT_TPU_LOGLEVEL"):
        env["PINOT_TPU_LOGLEVEL"] = os.environ["PINOT_TPU_LOGLEVEL"]
    return env


def _spawn(args, ready_prefix="READY"):
    # PINOT_TPU_TEST_LOGDIR=<dir> tees each child's stderr to a file —
    # the only way to see why a spawned role stalled in a flaky run
    log_dir = os.environ.get("PINOT_TPU_TEST_LOGDIR")
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        name = "_".join(a.lstrip("-") for a in args[:3]).replace("/", "_")
        stderr = open(os.path.join(log_dir, f"{name}_{time.time():.0f}.err"), "w")
    else:
        stderr = subprocess.DEVNULL
    proc = subprocess.Popen(
        [sys.executable, "-m", "pinot_tpu.tools.admin", *args],
        cwd=REPO_ROOT,
        env=_admin_env(),
        stdout=subprocess.PIPE,
        stderr=stderr,
        text=True,
    )
    deadline = time.time() + 90
    while time.time() < deadline:
        # select so a child that hangs without printing can't block
        # readline() forever past the deadline
        ready, _, _ = select.select([proc.stdout], [], [], 1.0)
        if ready:
            line = proc.stdout.readline()
            if line.startswith(ready_prefix):
                return proc, line.split()[-1]
        if proc.poll() is not None:
            raise RuntimeError(f"process exited early: {args}")
    proc.kill()
    raise RuntimeError(f"no READY from {args}")


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def _post_json(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), headers={"Content-Type": "application/json"}
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _wait_for(cond, timeout=30, interval=0.25, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")



def _bring_up_cluster(tmp_path, ctrl_url, procs, schema, rows):
    """Spawn 2 servers + broker against a running controller, create the
    schema/table, upload two 200-row segments; returns broker_url."""
    for name in ("s0", "s1"):
        p, _addr = _spawn(
            ["StartServer", "-controller", ctrl_url, "-name", name,
             "-data-dir", str(tmp_path / f"cache_{name}")]
        )
        procs.append(p)
    broker_proc, broker_url = _spawn(
        ["StartBroker", "-controller", ctrl_url, "-port", "0"]
    )
    procs.append(broker_proc)

    _post_json(ctrl_url + "/schemas", schema.to_json())
    config = TableConfig(table_name=TABLE, table_type="OFFLINE", replication=2)
    _post_json(ctrl_url + "/tables", config.to_json())
    for i in range(2):
        seg = build_segment(schema, rows[i * 200 : (i + 1) * 200], PHYSICAL, f"net_{i}")
        d = str(tmp_path / f"build_{i}")
        write_segment(seg, d)
        with open(os.path.join(d, SEGMENT_FILE_NAME), "rb") as f:
            data = f.read()
        req = urllib.request.Request(
            ctrl_url + f"/segments/{PHYSICAL}", data=data,
            headers={"Content-Type": "application/octet-stream"},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            assert json.loads(r.read())["status"] == "ok"
    return broker_url


@pytest.mark.slow
def test_networked_cluster_end_to_end(tmp_path):
    schema = make_test_schema(with_mv=False)
    schema.schema_name = TABLE
    rows = random_rows(schema, 400, seed=29)

    procs = []
    try:
        ctrl_proc, ctrl_url = _spawn(
            ["StartController", "-port", "0", "-data-dir", str(tmp_path / "store"),
             "-heartbeat-timeout", "2.0"]
        )
        procs.append(ctrl_proc)

        broker_url = _bring_up_cluster(tmp_path, ctrl_url, procs, schema, rows)
        # srv procs are procs[1:3] in spawn order (s0, s1)
        srv_procs = {"s0": procs[1], "s1": procs[2]}

        # transitions are async messages: wait until both replicas report ONLINE
        def _all_online():
            view = _get(ctrl_url + f"/tables/{PHYSICAL}/externalview")
            return (
                len(view) == 2
                and all(
                    set(replicas) == {"s0", "s1"}
                    and all(st == "ONLINE" for st in replicas.values())
                    for replicas in view.values()
                )
            )

        _wait_for(_all_online, timeout=60, what="segments ONLINE on both servers")

        # broker picked the view up by polling cluster state
        def _query(pql):
            return _post_json(broker_url + "/query", {"pql": pql})

        def _full_count():
            resp = _query(f"SELECT count(*) FROM {TABLE}")
            return resp.get("numDocsScanned") == 400 and not resp.get("exceptions")

        _wait_for(_full_count, timeout=60, what="broker routing serving all segments")

        expected_sum = sum(r["metInt"] for r in rows)
        resp = _query(f"SELECT sum(metInt) FROM {TABLE}")
        assert not resp["exceptions"]
        got = float(resp["aggregationResults"][0]["value"])
        assert got == pytest.approx(expected_sum, rel=1e-6)

        # SIGKILL one server: heartbeats stop, controller marks it dead,
        # broker reroutes to the surviving replica -> still full results
        srv_procs["s0"].send_signal(signal.SIGKILL)
        srv_procs["s0"].wait(timeout=10)

        def _s0_dead():
            state = _get(ctrl_url + "/clusterstate")
            return "s0" not in state["servers"]

        _wait_for(_s0_dead, timeout=20, what="controller declaring s0 dead")

        def _failover_ok():
            resp = _query(f"SELECT count(*) FROM {TABLE}")
            return resp.get("numDocsScanned") == 400 and not resp.get("exceptions")

        _wait_for(_failover_ok, timeout=30, what="failover to surviving replica")

        # restart s0 under the same name + cache dir: re-registration must
        # reconcile (replay ideal state) and reload from the local cache
        p, _addr = _spawn(
            ["StartServer", "-controller", ctrl_url, "-name", "s0",
             "-data-dir", str(tmp_path / "cache_s0")]
        )
        procs.append(p)
        _wait_for(_all_online, timeout=60, what="restarted s0 back ONLINE")
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()


@pytest.mark.slow
def test_controller_sigkill_restart_recovers_cluster(tmp_path):
    """SIGKILL the controller process and restart it over the same data
    dir: metadata recovers from the property store, servers re-register
    and replay ideal state, the broker resumes routing — and while the
    controller is down, already-routed queries keep serving (the
    ZK-outage-tolerance analog)."""
    import socket

    schema = make_test_schema(with_mv=False)
    schema.schema_name = TABLE
    rows = random_rows(schema, 400, seed=31)

    # fixed controller port so restarted process is reachable at the
    # same URL the servers/brokers hold
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    ctrl_port = s.getsockname()[1]
    s.close()
    data_dir = str(tmp_path / "store")

    def start_controller():
        return _spawn(
            ["StartController", "-port", str(ctrl_port), "-data-dir", data_dir,
             "-heartbeat-timeout", "2.0"]
        )

    procs = []
    try:
        ctrl_proc, ctrl_url = start_controller()
        procs.append(ctrl_proc)

        broker_url = _bring_up_cluster(tmp_path, ctrl_url, procs, schema, rows)

        def _query(pql):
            return _post_json(broker_url + "/query", {"pql": pql})

        def _full_count():
            resp = _query(f"SELECT count(*) FROM {TABLE}")
            return resp.get("numDocsScanned") == 400 and not resp.get("exceptions")

        _wait_for(_full_count, timeout=60, what="cluster serving all segments")

        # --- SIGKILL the controller ---
        ctrl_proc.send_signal(signal.SIGKILL)
        ctrl_proc.wait(timeout=10)

        # data plane survives the control-plane outage: the broker keeps
        # its last routing table and servers keep serving
        time.sleep(1.0)
        assert _full_count(), "queries must keep serving while controller is down"

        # --- restart controller over the same data dir ---
        ctrl_proc2, ctrl_url2 = start_controller()
        procs.append(ctrl_proc2)
        assert ctrl_url2 == ctrl_url

        # recovered metadata visible immediately from the property store
        tables = _get(ctrl_url + "/tables")
        assert PHYSICAL in tables["tables"]
        ideal = _get(ctrl_url + f"/tables/{PHYSICAL}/idealstate")
        assert set(ideal) == {"net_0", "net_1"}

        # servers re-register via heartbeat 'reregister', replay ideal
        # state, external view refills, broker routing resumes
        def _view_refilled():
            view = _get(ctrl_url + f"/tables/{PHYSICAL}/externalview")
            return len(view) == 2 and all(
                st == "ONLINE"
                for replicas in view.values()
                for st in replicas.values()
            ) and all(len(r) == 2 for r in view.values())

        _wait_for(_view_refilled, timeout=60, what="external view refilled after restart")
        _wait_for(_full_count, timeout=30, what="queries after controller restart")

        expected_sum = sum(r["metInt"] for r in rows)
        resp = _query(f"SELECT sum(metInt) FROM {TABLE}")
        assert not resp["exceptions"]
        assert float(resp["aggregationResults"][0]["value"]) == pytest.approx(
            expected_sum, rel=1e-6
        )
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
