"""Segment build / persist / reload tests (codec + builder + format)."""
import numpy as np
import pytest

from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.segment.bitpack import bits_required, pack_bits, unpack_bits
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.segment.dictionary import Dictionary
from pinot_tpu.segment.format import read_segment, write_segment
from pinot_tpu.tools.datagen import random_rows, make_test_schema


# ---------------------------------------------------------------- bitpack
@pytest.mark.parametrize("card", [1, 2, 3, 7, 8, 255, 256, 100_000])
def test_bitpack_roundtrip(card):
    rng = np.random.default_rng(card)
    vals = rng.integers(0, card, size=1013).astype(np.int64)
    nbits = bits_required(card)
    packed = pack_bits(vals, nbits)
    out = unpack_bits(packed, nbits, len(vals))
    np.testing.assert_array_equal(out, vals.astype(np.int32))
    # size bound: packed uses exactly ceil(n*nbits/8) bytes
    assert packed.size == (len(vals) * nbits + 7) // 8


def test_bits_required():
    assert bits_required(1) == 1
    assert bits_required(2) == 1
    assert bits_required(3) == 2
    assert bits_required(256) == 8
    assert bits_required(257) == 9


# ------------------------------------------------------------- dictionary
def test_numeric_dictionary_sorted_lookup():
    d = Dictionary.build(DataType.INT, [5, 3, 5, 1, 9])
    assert list(d.values) == [1, 3, 5, 9]
    assert d.index_of(5) == 2
    assert d.index_of(4) == -1
    assert d.insertion_index(4) == 2  # first >= 4
    assert d.min_value == 1 and d.max_value == 9


def test_string_dictionary():
    d = Dictionary.build(DataType.STRING, ["b", "a", "c", "a"])
    assert d.values == ["a", "b", "c"]
    assert d.index_of("b") == 1
    assert d.index_of("zz") == -1


# ---------------------------------------------------------------- builder
def test_build_simple_segment():
    schema = Schema(
        "t",
        dimensions=[FieldSpec("d", DataType.STRING)],
        metrics=[FieldSpec("m", DataType.INT, FieldType.METRIC)],
    )
    rows = [{"d": "x", "m": 1}, {"d": "y", "m": 2}, {"d": "x", "m": 3}]
    seg = build_segment(schema, rows, "t", "seg0")
    assert seg.num_docs == 3
    d = seg.column("d")
    assert d.dictionary.values == ["x", "y"]
    np.testing.assert_array_equal(d.fwd, [0, 1, 0])
    m = seg.column("m")
    assert m.metadata.cardinality == 3
    assert m.metadata.min_value == 1 and m.metadata.max_value == 3
    # rows roundtrip
    assert seg.row(2) == {"d": "x", "m": 3}


def test_build_sorted_flag():
    schema = Schema("t", metrics=[FieldSpec("m", DataType.INT, FieldType.METRIC)])
    seg = build_segment(schema, [{"m": v} for v in [1, 2, 2, 5]], "t")
    assert seg.column("m").metadata.is_sorted
    seg2 = build_segment(schema, [{"m": v} for v in [1, 5, 2]], "t")
    assert not seg2.column("m").metadata.is_sorted


def test_build_mv_column():
    schema = Schema(
        "t",
        dimensions=[FieldSpec("tags", DataType.STRING_ARRAY, single_value=False)],
    )
    rows = [{"tags": ["a", "b"]}, {"tags": ["c"]}, {"tags": ["b", "c", "a"]}]
    seg = build_segment(schema, rows, "t")
    col = seg.column("tags")
    assert col.metadata.max_num_multi_values == 3
    assert col.metadata.total_number_of_entries == 6
    np.testing.assert_array_equal(col.mv_offsets, [0, 2, 3, 6])
    assert seg.row(2) == {"tags": ["b", "c", "a"]}


def test_missing_values_get_defaults():
    schema = Schema(
        "t",
        dimensions=[FieldSpec("d", DataType.STRING)],
        metrics=[FieldSpec("m", DataType.INT, FieldType.METRIC)],
    )
    seg = build_segment(schema, [{"d": "x"}, {"m": 7}], "t")
    assert seg.row(0) == {"d": "x", "m": 0}  # metric null = 0
    assert seg.row(1) == {"d": "null", "m": 7}  # dim null = "null"


def test_time_column_range():
    from pinot_tpu.common.schema import TimeFieldSpec

    schema = Schema(
        "t",
        metrics=[FieldSpec("m", DataType.INT, FieldType.METRIC)],
        time_field=TimeFieldSpec("days", DataType.INT, time_unit="DAYS"),
    )
    seg = build_segment(schema, [{"m": 1, "days": 100}, {"m": 2, "days": 90}], "t")
    assert seg.metadata.start_time == 90
    assert seg.metadata.end_time == 100
    assert seg.metadata.time_column == "days"


# ----------------------------------------------------------------- format
def test_segment_disk_roundtrip(tmp_path):
    schema = make_test_schema()
    rows = random_rows(schema, 500, seed=3)
    seg = build_segment(schema, rows, "t", "seg_rt")
    write_segment(seg, str(tmp_path / "seg_rt"))
    loaded = read_segment(str(tmp_path / "seg_rt"))

    assert loaded.metadata.segment_name == "seg_rt"
    assert loaded.num_docs == 500
    assert loaded.metadata.crc == seg.metadata.crc
    assert loaded.compute_crc() == seg.compute_crc()
    for name, col in seg.columns.items():
        lcol = loaded.column(name)
        if col.fwd is not None:
            np.testing.assert_array_equal(lcol.fwd, col.fwd)
        if col.mv_values is not None:
            np.testing.assert_array_equal(lcol.mv_values, col.mv_values)
            np.testing.assert_array_equal(lcol.mv_offsets, col.mv_offsets)
    # spot-check row materialization equality
    for i in (0, 123, 499):
        assert loaded.row(i) == seg.row(i)


def test_readers_csv_jsonl(tmp_path):
    schema = Schema(
        "t",
        dimensions=[
            FieldSpec("d", DataType.STRING),
            FieldSpec("tags", DataType.STRING_ARRAY, single_value=False),
        ],
        metrics=[FieldSpec("m", DataType.INT, FieldType.METRIC)],
    )
    csv_path = tmp_path / "data.csv"
    csv_path.write_text("d,tags,m\nx,a;b,1\ny,c,2\n")
    from pinot_tpu.segment.readers import read_csv, read_jsonl

    rows = read_csv(str(csv_path), schema)
    assert rows == [
        {"d": "x", "tags": ["a", "b"], "m": 1},
        {"d": "y", "tags": ["c"], "m": 2},
    ]

    jl = tmp_path / "data.jsonl"
    jl.write_text('{"d": "x", "tags": ["a"], "m": 3}\n{"d": "z", "m": 4}\n')
    rows = read_jsonl(str(jl), schema)
    assert rows[0] == {"d": "x", "tags": ["a"], "m": 3}
    assert rows[1] == {"d": "z", "tags": ["null"], "m": 4}


def test_schema_json_reference_nested_time_spec():
    """Reference-format schema JSON (nested incomingGranularitySpec,
    common/data/TimeFieldSpec.java as in sample_data/*.schema) loads
    as-is, alongside this package's flat form."""
    from pinot_tpu.common.schema import DataType, Schema

    d = {
        "schemaName": "meetupRsvp",
        "dimensionFieldSpecs": [{"name": "venue", "dataType": "STRING"}],
        "metricFieldSpecs": [{"name": "rsvp_count", "dataType": "INT"}],
        "timeFieldSpec": {
            "incomingGranularitySpec": {
                "timeType": "MILLISECONDS",
                "dataType": "LONG",
                "name": "mtime",
            }
        },
    }
    schema = Schema.from_json(d)
    assert schema.time_field is not None
    assert schema.time_field.name == "mtime"
    assert schema.time_field.data_type == DataType.LONG
    assert schema.time_field.time_unit == "MILLISECONDS"
    # round-trips through our flat form
    again = Schema.from_json(schema.to_json())
    assert again.time_field.time_unit == "MILLISECONDS"
