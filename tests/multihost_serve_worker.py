"""Worker for the multi-host SERVING test
(tests/test_multihost_process.py::test_broker_pql_through_multihost_mesh):
each OS process is one host of a 2-host mesh-serving group
(server/mesh_server.py).  The lead (pid 0) serves the framework's query
protocol; the test process points a real BrokerRequestHandler at it.

Run as: python tests/multihost_serve_worker.py <coordinator> <nprocs>
        <pid> <serve_port> [<follower_port>...]
"""
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
)
os.environ.setdefault("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    coordinator, num_procs, pid, serve_port = (
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
        int(sys.argv[4]),
    )
    follower_ports = [int(p) for p in sys.argv[5:]]

    from pinot_tpu.server.mesh_server import MultihostQueryServer
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment

    # deterministic seeds: every host builds the same global segment
    # view (XLA partitions the stacked arrays across the mesh)
    segments = [
        synthetic_lineitem_segment(512, seed=100 + i, name=f"mh{i}") for i in range(8)
    ]
    server = MultihostQueryServer(
        "lineitem",
        segments,
        coordinator_address=coordinator,
        num_processes=num_procs,
        process_id=pid,
        port=serve_port,
    )
    if not server.is_lead and os.environ.get("PINOT_TPU_MESH_TEST_EXIT_ON_QUERY") == "1":
        # failure injection for the mid-query death test: this follower
        # answers liveness pings normally, then dies the moment it
        # starts PROCESSING a forwarded query — after the lead's
        # preflight, before collective entry
        server.server.handle_request = lambda payload: os._exit(17)
    if server.is_lead:
        server.connect_followers([("127.0.0.1", p) for p in follower_ports])
    print(f"SERVING pid={pid} port={server.address[1]}", flush=True)

    import time

    time.sleep(600)  # the test kills us when done


if __name__ == "__main__":
    main()
