"""Mesh execution plane (ISSUE 12): pod-scale multichip serving with
per-chip-group lanes, on the forced 8-device CPU host (conftest).

Covers: topology construction from env, shape-hashed lane routing,
byte-identical payloads sharded vs single-lane across the bench query
mix, lane-group coalesce/shed/heal units, chaos (one poisoned plan on
one lane heals via host fallback while other lanes keep serving),
sharded staging-ledger accounting + eviction, per-lane utilization
attribution with sum-consistent rollups, and the EXPLAIN mesh node
whose phantom digest matches real sharded execution exactly.
"""
import json
import threading
import time

import jax
import pytest

from pinot_tpu.engine.mesh import (
    ChipGroup,
    MeshTopology,
    build_topology,
    collective_names,
)

NUM_SEGMENTS = 6  # not divisible by 4 or 8 -> exercises mesh padding


def _segments(n=NUM_SEGMENTS, rows=2500, prefix="msh"):
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment

    return [
        synthetic_lineitem_segment(rows, seed=31 + i, name=f"{prefix}{i}")
        for i in range(n)
    ]


def _strip(resp) -> str:
    """Canonical payload for the byte-identity differential (bench.py
    _strip_timing semantics: timing, request identity, and the
    path-dependent cost vector excluded)."""
    return json.dumps(
        {
            k: v
            for k, v in resp.to_json().items()
            if k not in ("timeUsedMs", "requestId", "cost")
        },
        sort_keys=True,
    )


@pytest.fixture(scope="module")
def lineitem_segments():
    return _segments()


@pytest.fixture(scope="module")
def mesh_broker(lineitem_segments):
    """One server carved into 2 lanes x 4 chips over the 8 virtual CPU
    devices, behind an in-process broker."""
    from pinot_tpu.tools.cluster_harness import single_server_broker

    topo = build_topology(jax.devices(), 2, 4)
    broker = single_server_broker(
        "lineitem", lineitem_segments, topology=topo
    )
    yield broker
    broker.local_servers[0].shutdown()


# ---------------------------------------------------------------------------
# topology construction
# ---------------------------------------------------------------------------


def test_default_topology_is_trivial_single_lane(monkeypatch):
    monkeypatch.delenv("PINOT_TPU_MESH_SHAPE", raising=False)
    monkeypatch.delenv("PINOT_TPU_LANES", raising=False)
    topo = MeshTopology.from_env()
    assert topo.trivial
    assert topo.num_lanes == 1
    assert topo.primary_mesh is None
    snap = topo.snapshot()
    assert snap["shape"] == "1x1" and snap["shardAxis"] is None


@pytest.mark.parametrize(
    "shape,lanes,want",
    [
        ("2x4", None, (2, 4)),
        ("8", None, (1, 8)),
        (None, "4", (4, 2)),
        (None, "2", (2, 4)),
        ("4x2", "4", (4, 2)),
        ("junk", None, (1, 8)),  # junk shape degrades, never raises
        ("64x64", None, (8, 1)),  # impossible request clamps to devices
    ],
)
def test_topology_env_parsing(monkeypatch, shape, lanes, want):
    monkeypatch.delenv("PINOT_TPU_MESH_SHAPE", raising=False)
    monkeypatch.delenv("PINOT_TPU_LANES", raising=False)
    if shape is not None:
        monkeypatch.setenv("PINOT_TPU_MESH_SHAPE", shape)
    if lanes is not None:
        monkeypatch.setenv("PINOT_TPU_LANES", lanes)
    topo = MeshTopology.from_env()
    assert (topo.num_lanes, topo.devices_per_lane) == want
    # groups own disjoint devices and each carries its own mesh
    seen = set()
    for g in topo.groups:
        ids = {d.id for d in g.devices}
        assert not ids & seen
        seen |= ids
        assert g.mesh is not None and int(g.mesh.devices.size) == g.size


def test_from_mesh_legacy_adapter():
    from pinot_tpu.parallel import default_mesh

    topo = MeshTopology.from_mesh(default_mesh())
    assert topo.num_lanes == 1 and not topo.trivial
    assert int(topo.primary_mesh.devices.size) == 8
    assert MeshTopology.from_mesh(None).trivial


def test_collective_names_reflect_plan_reducers(lineitem_segments):
    from pinot_tpu.engine.context import get_table_context
    from pinot_tpu.engine.explain import _phantom_staged
    from pinot_tpu.engine.plan import build_static_plan
    from pinot_tpu.pql import optimize_request, parse_pql

    req = optimize_request(
        parse_pql("SELECT sum(l_quantity), min(l_quantity) FROM lineitem")
    )
    ctx = get_table_context(lineitem_segments)
    phantom = _phantom_staged(
        lineitem_segments, ["l_quantity"], ("l_quantity",), (), ()
    )
    plan = build_static_plan(req, ctx, phantom)
    names = collective_names(plan)
    assert "psum" in names and "pmin" in names


# ---------------------------------------------------------------------------
# lane-group units: routing, coalesce, shed, heal
# ---------------------------------------------------------------------------


def _bare_group(n=4, metrics=None, **kwargs):
    from pinot_tpu.engine.dispatch import LaneGroup

    topo = MeshTopology(
        groups=tuple(ChipGroup(index=i) for i in range(n)), source="env"
    )
    return LaneGroup(topo, metrics=metrics, **kwargs)


def test_lane_selection_is_stable_and_spread():
    lg = _bare_group(4)
    try:
        idx = {f"shape{i}": lg.lane_index(f"shape{i}") for i in range(256)}
        # deterministic: same key always lands on the same lane
        for k, v in idx.items():
            assert lg.lane_index(k) == v
            assert lg.select(k).index == v
            assert lg.select(k).group is lg.topology.groups[v]
        # and shapes actually spread across the group
        assert len(set(idx.values())) == 4
    finally:
        lg.close()


def test_lane_group_coalesces_identical_dispatches():
    lg = _bare_group(2)
    try:
        release = threading.Event()

        def slow_launch():
            release.wait(5.0)
            return {"v": 1}

        sel = lg.select("shapeA")
        t1 = sel.lane.submit(("k", 1), slow_launch, pending=lambda v: False)
        t2 = sel.lane.submit(("k", 1), slow_launch, pending=lambda v: False)
        release.set()
        assert t1.result(time.monotonic() + 10) == {"v": 1}
        assert t2.result(time.monotonic() + 10) == {"v": 1}
        assert t2.coalesced  # rode the identical in-flight dispatch
        stats = lg.stats()
        assert stats["coalesceHits"] >= 1
        assert stats["lanes"][sel.index]["coalesceHits"] >= 1
    finally:
        lg.close()


def test_lane_group_sheds_expired_waiters_per_lane():
    from pinot_tpu.server.scheduler import QueryAbandonedError

    lg = _bare_group(2)
    try:
        sel = lg.select("shapeB")
        expired = time.monotonic() - 1.0
        ticket = sel.lane.submit(("dead", 1), lambda: {"v": 2}, deadline=expired)
        with pytest.raises(QueryAbandonedError):
            ticket.result(time.monotonic() + 5)
        assert lg.stats()["shed"] >= 1
        assert lg.stats()["lanes"][sel.index]["shed"] >= 1
    finally:
        lg.close()


def test_lane_group_rollup_sums_per_lane_stats():
    lg = _bare_group(3)
    try:
        for key in ("a", "b", "c", "d", "e"):
            sel = lg.select(key)
            sel.lane.submit((key, 1), lambda: {"v": key}, pending=lambda v: False
                            ).result(time.monotonic() + 5)
        stats = lg.stats()
        per_lane = stats["lanes"]
        assert len(per_lane) == 3
        for field in ("dispatches", "shed", "coalesceHits", "deviceFailures"):
            assert stats[field] == sum(l[field] for l in per_lane)
        assert stats["dispatches"] == 5
    finally:
        lg.close()


def test_single_group_lane_is_premesh_shape():
    """A single-group LaneGroup is byte-compatible with the pre-mesh
    single lane: verbatim stats (no "lanes" key), unprefixed metrics."""
    from pinot_tpu.utils.metrics import ServerMetrics

    m = ServerMetrics("premesh")
    lg = _bare_group(1, metrics=m)
    try:
        assert lg.primary is lg.lanes[0]
        assert lg.lanes[0].index is None
        stats = lg.stats()
        assert "lanes" not in stats
        assert lg.select("anything").index == 0
        snap = m.snapshot()
        assert "lane.depth" in snap["gauges"]
        assert not any(g.startswith("lane.0.") for g in snap["gauges"])
    finally:
        lg.close()


# ---------------------------------------------------------------------------
# serving: byte-identical payloads sharded vs single-lane
# ---------------------------------------------------------------------------


def test_sharded_payloads_byte_identical_to_single_lane(
    lineitem_segments, mesh_broker
):
    """The bench query mix (plus COUNT(*) and a selection) through a
    2x4 lane-group server serves byte-identical payloads to the
    single-lane server — the mesh is a pure execution-plane change."""
    from pinot_tpu.tools.cluster_harness import single_server_broker
    from pinot_tpu.tools.serving_curve import mixed_workload

    single = single_server_broker("lineitem", lineitem_segments)
    try:
        queries = mixed_workload(lineitem_segments) + [
            "SELECT count(*) FROM lineitem",
            "SELECT l_returnflag, l_quantity FROM lineitem "
            "ORDER BY l_quantity DESC LIMIT 7",
        ]
        for pql in queries:
            a = single.handle_pql(pql)
            b = mesh_broker.handle_pql(pql)
            assert not a.exceptions, (pql, a.exceptions)
            assert not b.exceptions, (pql, b.exceptions)
            assert _strip(a) == _strip(b), pql
        # the mesh server really executed on device lanes (no silent
        # host healing — the regression the shard_map kwarg fix covers)
        server = mesh_broker.local_servers[0]
        heal = server.executor.healing_stats()
        assert heal["hostFailovers"] == 0 and heal["deviceFailures"] == 0
        assert server.lanes.stats()["dispatches"] >= 1
    finally:
        single.local_servers[0].shutdown()


def test_mesh_status_reports_topology_and_lanes(mesh_broker):
    server = mesh_broker.local_servers[0]
    status = server.status()
    assert status["mesh"]["lanes"] == 2
    assert status["mesh"]["devicesPerLane"] == 4
    assert status["mesh"]["shardAxis"] == "segments"
    assert len(status["lane"]["lanes"]) == 2
    snap = status["metrics"]
    assert snap["gauges"]["mesh.lanes"] == 2
    assert "lane.0.depth" in snap["gauges"] and "lane.1.depth" in snap["gauges"]


# ---------------------------------------------------------------------------
# chaos: one poisoned plan on one lane heals via host fallback while
# the other lanes keep serving from their chips
# ---------------------------------------------------------------------------


def _strip_heal(resp) -> str:
    """Payload canonicalization across the device/host tiers: the
    entries-scanned counters are tier-dependent by design (zone maps /
    postings scan fewer entries; the host path counts differently —
    test_selfheal strips the same two), the DATA must match exactly."""
    return json.dumps(
        {
            k: v
            for k, v in resp.to_json().items()
            if k
            not in (
                "timeUsedMs",
                "requestId",
                "cost",
                "numEntriesScannedInFilter",
                "numEntriesScannedPostFilter",
            )
        },
        sort_keys=True,
    )


def test_poisoned_plan_on_one_lane_heals_while_others_serve(lineitem_segments):
    from pinot_tpu.common.faults import DeviceFaultInjector
    from pinot_tpu.tools.cluster_harness import single_server_broker

    inj = DeviceFaultInjector(seed=7)
    topo = build_topology(jax.devices(), 2, 4)
    broker = single_server_broker(
        "lineitem",
        lineitem_segments,
        topology=topo,
        device_fault_injector=inj,
    )
    server = broker.local_servers[0]
    try:
        victim_q = "SELECT sum(l_quantity) FROM lineitem GROUP BY l_returnflag TOP 5"
        healthy_q = "SELECT count(*) FROM lineitem"
        # learn the device-plan digest and lane WITHOUT serving: EXPLAIN
        dev = broker.handle_pql("EXPLAIN " + victim_q).explain["servers"][0]["device"]
        victim_digest = dev["planDigest"]
        victim_lane = dev["mesh"]["laneIndex"]
        # sanity: the two shapes route to different lanes (chosen so)
        healthy_dev = broker.handle_pql("EXPLAIN " + healthy_q).explain[
            "servers"
        ][0]["device"]
        baseline = _strip_heal(broker.handle_pql(victim_q))

        inj.poison_plan(victim_digest)
        poisoned = broker.handle_pql(victim_q)
        assert not poisoned.exceptions
        # healed via host fallback, byte-identical answer
        assert _strip_heal(poisoned) == baseline
        heal = server.executor.healing_stats()
        assert heal["hostFailovers"] >= 1
        assert heal["poisonedPlans"] >= 1

        # the OTHER lanes keep serving on device: a healthy shape still
        # dispatches and adds zero new failures
        before = server.lanes.stats()["dispatches"]
        ok = broker.handle_pql(healthy_q)
        assert not ok.exceptions
        if healthy_dev["mesh"]["laneIndex"] != victim_lane:
            assert server.lanes.stats()["dispatches"] >= before
        assert server.executor.healing_stats()["deviceFailures"] == heal[
            "deviceFailures"
        ]

        # repeat offenders skip the device entirely (quarantine), still
        # byte-identical
        again = broker.handle_pql(victim_q)
        assert not again.exceptions and _strip_heal(again) == baseline
        assert server.executor.healing_stats()["poisonSkips"] >= 1
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# sharded staging ledger + staging-token invariant
# ---------------------------------------------------------------------------


def test_ledger_attributes_sharded_staging_per_device():
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pinot_tpu.engine.device import (
        LEDGER,
        evict_staged_segment,
        get_staged,
    )

    segs = _segments(n=4, rows=600, prefix="led")
    topo = build_topology(jax.devices(), 2, 4)
    group = topo.groups[1]  # devices 4-7: distinguishable from default
    sh = NamedSharding(group.mesh, P("segments"))
    st = get_staged(segs, ["l_quantity", "l_shipdate"], pad_segments_to=4, sharding=sh)
    try:
        snap = LEDGER.snapshot()
        entry = next(
            e for e in snap["entries"] if set(e["segments"]) == {s.segment_name for s in segs}
        )
        # per-device attribution: every chip of the group holds its
        # shard, and the per-device bytes sum EXACTLY to the entry total
        ids = {f"cpu:{d.id}" for d in group.devices}
        assert set(entry["devices"]) == ids
        assert sum(entry["devices"].values()) == entry["bytes"]
        assert set(snap["byDevice"]).issuperset(ids)

        # same segments on a DIFFERENT placement = a distinct staged
        # copy with its own token (no stale alias across chip groups)
        sh0 = NamedSharding(topo.groups[0].mesh, P("segments"))
        st0 = get_staged(
            segs, ["l_quantity", "l_shipdate"], pad_segments_to=4, sharding=sh0
        )
        assert st0.token != st.token

        # eviction drops EVERY placement holding the segment, and a
        # re-stage mints a fresh token (the PR 3 invariant, sharded)
        dropped = evict_staged_segment(segs[0].segment_name)
        assert dropped >= 2
        st2 = get_staged(
            segs, ["l_quantity", "l_shipdate"], pad_segments_to=4, sharding=sh
        )
        assert st2.token not in (st.token, st0.token)
    finally:
        evict_staged_segment(segs[0].segment_name)


# ---------------------------------------------------------------------------
# per-lane utilization attribution + rollup consistency
# ---------------------------------------------------------------------------


def test_per_lane_utilization_rollup_equals_sum_of_lane_snapshots(mesh_broker):
    from pinot_tpu.tools.serving_curve import mixed_workload

    server = mesh_broker.local_servers[0]
    segs = mesh_broker.local_servers[0].data_manager.table("lineitem_OFFLINE")
    for pql in mixed_workload(_segments()):  # drive some device work
        mesh_broker.handle_pql(pql)
    du = server.device_utilization()
    assert du["mesh"]["lanes"] == 2

    recent = du["recent"]
    lanes = recent["lanes"]
    assert len(lanes) == 2
    # rollup totals equal the sum of the per-lane snapshots EXACTLY
    assert recent["queries"] == sum(l["queries"] for l in lanes)
    assert recent["deviceBytes"] == sum(l["deviceBytes"] for l in lanes)
    assert recent["achievedBytesPerSec"] == sum(
        l["achievedBytesPerSec"] for l in lanes
    )
    assert recent["achievedFlopsPerSec"] == sum(
        l["achievedFlopsPerSec"] for l in lanes
    )
    assert recent["queries"] >= 1  # device work actually attributed

    occ = du["occupancy"]
    occ_lanes = occ["lanes"]
    assert len(occ_lanes) == 2
    assert occ["depth"] == sum(l["depth"] for l in occ_lanes)
    assert occ["busyFraction"] == round(
        sum(l["busyFraction"] for l in occ_lanes), 6
    )


# ---------------------------------------------------------------------------
# EXPLAIN mesh node: decision reported, phantom digest matches real
# sharded execution exactly
# ---------------------------------------------------------------------------


def test_explain_reports_mesh_decision_and_digest_matches(mesh_broker):
    q = "SELECT sum(l_extendedprice), count(*) FROM lineitem GROUP BY l_linestatus TOP 5"
    pre = mesh_broker.handle_pql("EXPLAIN " + q)
    dev = pre.explain["servers"][0]["device"]
    mesh_node = dev["mesh"]
    assert mesh_node["shape"] == "2x4"
    assert mesh_node["lanes"] == 2
    assert mesh_node["shardAxis"] == "segments"
    assert "psum" in mesh_node["collective"]
    assert mesh_node["laneIndex"] in (0, 1)

    # real sharded execution compiles the IDENTICAL plan digest on the
    # lane EXPLAIN predicted
    resp = mesh_broker.handle_pql(q)
    assert not resp.exceptions
    server = mesh_broker.local_servers[0]
    lane = server.lanes.lanes[mesh_node["laneIndex"]]
    assert lane.compile_info(dev["planDigest"]) is not None
    post = mesh_broker.handle_pql("EXPLAIN " + q)
    post_dev = post.explain["servers"][0]["device"]
    assert post_dev["planDigest"] == dev["planDigest"]
    assert post_dev["compile"]["state"] == "warm"


# ---------------------------------------------------------------------------
# perf-gate: multichip-mode documents gate their own namespace
# ---------------------------------------------------------------------------


def test_perf_gate_multichip_kind():
    from pinot_tpu.tools.perf_gate import compare

    doc = {
        "metric": "multichip_serving_ladder_rows_per_sec",
        "platform": "cpu",
        "n_devices": 8,
        "num_segments": 8,
        "total_rows": 1000,
        "rows_per_sec": {"single_lane": 100.0, "sharded": 320.0, "lane_group": 300.0},
        "sharded_vs_single": 3.2,
        "lane_group_vs_single": 3.0,
        "utilization": {
            "sharded": {"achievedBytesPerSec": 1000.0},
            "lane_group": {"achievedBytesPerSec": 900.0},
        },
    }
    # identical docs pass and compare the multichip namespace
    out = compare(doc, doc)
    assert out["verdict"] == "pass"
    assert {r["metric"] for r in out["metrics"]} >= {
        "rows_per_sec.sharded",
        "sharded_vs_single",
        "utilization.lane_group.achievedBytesPerSec",
    }
    # a collapsed speedup fails the direction-aware band
    worse = json.loads(json.dumps(doc))
    worse["rows_per_sec"]["sharded"] = 110.0
    worse["sharded_vs_single"] = 1.1
    out = compare(doc, worse)
    assert out["verdict"] == "fail"
    # config mismatch SKIPs (different device count is a different run)
    other = json.loads(json.dumps(doc))
    other["n_devices"] = 4
    assert compare(doc, other)["verdict"] == "skipped"
    # mixed kinds SKIP outright
    assert (
        compare({"metric": "tpch_q1_rows_scanned_per_sec_per_chip"}, doc)["verdict"]
        == "skipped"
    )


# ---------------------------------------------------------------------------
# acceptance (slow): sharded execution beats a single lane by >= 3x on
# the scan-heavy shapes — measured by bench's multichip mode on real
# hardware; here gated as a slow test so tier-1 stays deterministic
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_sharded_speedup_over_single_lane():
    import os

    import numpy as np

    if (os.cpu_count() or 1) < 8:
        pytest.skip(
            "virtual CPU devices share host cores: a host with fewer "
            "cores than mesh devices cannot express the parallel "
            "speedup this test measures (wall-clock is core-bound, "
            "not device-bound) — run on an 8+-core host or real chips"
        )

    from pinot_tpu.engine.context import get_table_context
    from pinot_tpu.engine.device import get_staged, segment_arrays
    from pinot_tpu.engine.kernel import make_table_kernel
    from pinot_tpu.engine.plan import build_query_inputs, build_static_plan
    from pinot_tpu.parallel import default_mesh
    from pinot_tpu.parallel.multichip import make_sharded_table_kernel
    from pinot_tpu.pql import optimize_request, parse_pql

    segs = _segments(n=8, rows=120_000, prefix="spd")
    req = optimize_request(
        parse_pql(
            "SELECT sum(l_quantity), sum(l_extendedprice), count(*) "
            "FROM lineitem GROUP BY l_returnflag TOP 5"
        )
    )
    ctx = get_table_context(segs)
    needed = sorted(set(req.referenced_columns()))

    def bench(kernel, staged):
        q = build_query_inputs(req, build_static_plan(req, ctx, staged), ctx, staged)
        arrays = segment_arrays(staged, needed)
        outs = kernel(arrays, q)
        np.asarray(next(iter(outs.values()))[0] if isinstance(next(iter(outs.values())), tuple) else next(iter(outs.values())))
        t0 = time.perf_counter()
        for _ in range(8):
            outs = kernel(arrays, q)
        leaf = next(iter(outs.values()))
        while isinstance(leaf, (tuple, list)):
            leaf = leaf[0]
        np.asarray(leaf)
        return time.perf_counter() - t0

    staged1 = get_staged(segs, needed, gfwd_columns=("l_returnflag",), ctx=ctx)
    plan1 = build_static_plan(req, ctx, staged1)
    t_single = bench(make_table_kernel(plan1), staged1)

    mesh = default_mesh()
    from jax.sharding import NamedSharding, PartitionSpec as P

    staged8 = get_staged(
        segs,
        needed,
        pad_segments_to=8,
        gfwd_columns=("l_returnflag",),
        ctx=ctx,
        sharding=NamedSharding(mesh, P("segments")),
    )
    plan8 = build_static_plan(req, ctx, staged8)
    t_mesh = bench(make_sharded_table_kernel(plan8, mesh), staged8)
    assert t_single / max(t_mesh, 1e-9) >= 3.0, (t_single, t_mesh)
