"""SLO & tail-latency attribution plane (ISSUE 11): the history
recorder ring, multi-window burn rates, tail-based trace sampling with
its zero-overhead contract, the flight recorder, and the doctor.

Covers the acceptance bar: burn-rate math evaluates over exactly the
recorded history (deterministic, injected clocks — no wall-clock
sleeps for window math); a not-retained query does ZERO retained-entry
work; in a chaos scenario the SLO burn gauge crosses, a flight-recorder
bundle lands on disk, ``/debug/tails`` attributes the victim table's
tail to a phase, and ``tools/doctor.py`` collects all of it into one
parseable bundle.
"""
import json
import os
import time
import urllib.request

import pytest

from pinot_tpu.segment.builder import build_segment
from pinot_tpu.tools.datagen import make_test_schema, random_rows
from pinot_tpu.utils.metrics import MetricsRegistry
from pinot_tpu.utils.timeseries import HistoryRecorder, leaked_recorder_threads

TABLE = "testTable"


# ------------------------------------------------------- history recorder
def test_history_recorder_ring_and_window_delta():
    reg = MetricsRegistry("t")
    clk = [1000.0]
    rec = HistoryRecorder(
        reg, interval_s=5, capacity=4, clock=lambda: clk[0], start=False
    )
    reg.meter("m").mark(10)
    reg.gauge("g").set(2)
    reg.gauge("flag").set(True)  # bool gauges flatten to 1.0/0.0
    reg.gauge("label").set("not-a-number")  # non-numeric: skipped
    reg.timer("ph").update(5.0)
    rec.tick()
    clk[0] += 5
    reg.meter("m").mark(5)
    rec.tick()
    assert rec.sample_count() == 2
    assert rec.latest("m.count") == 15
    assert rec.latest("flag") == 1.0
    assert rec.latest("label") is None
    assert rec.latest("ph.p99Ms") == 5.0
    # exact window: base is the newest sample at least window_s old
    assert rec.window_delta("m.count", 5) == (5, 5.0)
    # window longer than the ring: partial figure from the oldest sample
    assert rec.window_delta("m.count", 600) == (5, 5.0)
    assert rec.window_delta("nope", 5) is None
    # capacity bound: the ring never exceeds 4 samples
    for _ in range(6):
        clk[0] += 5
        rec.tick()
    assert rec.sample_count() == 4
    q = rec.query(series=["m."], window_s=10)
    assert set(q["series"]) == {"m.count", "m.rate1m"}
    assert q["samples"] == 4
    # windowS filter: only the trailing 10s of samples ride out
    assert len(q["series"]["m.count"]) == 3  # ts in [now-10, now]


def test_history_recorder_providers_hooks_and_thread_lifecycle():
    reg = MetricsRegistry("t")
    rec = HistoryRecorder(reg, interval_s=0.02, capacity=8, metrics=reg)
    try:
        seen = []
        rec.register_provider(lambda: {"extra.series": 7.0})
        rec.register_provider(lambda: 1 / 0)  # sick provider: tolerated
        rec.add_tick_hook(seen.append)
        rec.add_tick_hook(lambda now: 1 / 0)  # sick hook: tolerated
        deadline = time.monotonic() + 5
        while rec.sample_count() < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert rec.sample_count() >= 2, "recorder thread never ticked"
        assert rec.latest("extra.series") == 7.0
        assert rec.latest("history.ticks.count") >= 1
        assert seen and all(isinstance(t, float) for t in seen)
    finally:
        rec.stop()
    assert rec.stopped
    assert leaked_recorder_threads(grace_s=2.0) == []
    rec.start()  # restartable after stop
    rec.stop()
    assert leaked_recorder_threads(grace_s=2.0) == []


# ------------------------------------------------------------- SLO burn
def _slo_rig(fast=10.0, slow=100.0):
    from pinot_tpu.utils.slo import SloTracker

    reg = MetricsRegistry("t")
    clk = [0.0]
    hist = HistoryRecorder(
        reg, interval_s=5, capacity=64, clock=lambda: clk[0], start=False
    )
    slo = SloTracker(
        history=hist,
        metrics=reg,
        fast_window_s=fast,
        slow_window_s=slow,
        burn_threshold=1.0,
    )
    hist.register_provider(slo.series)
    return reg, clk, hist, slo


def test_slo_burn_rate_math_and_crossing():
    reg, clk, hist, slo = _slo_rig()
    for _ in range(100):
        slo.observe(TABLE, 10.0, failed=False)
    hist.tick()
    clk[0] += 10.0
    for i in range(100):
        slo.observe(TABLE, 10.0, failed=(i < 50))
    hist.tick()
    # a read-only poll between ticks (/debug/slo, fleet rollup, doctor,
    # flight-recorder source) must NOT consume the crossing edge the
    # sloBurn trigger depends on
    assert slo.snapshot()["burningTables"] == [TABLE]
    ev = slo.evaluate()
    t = ev["tables"][TABLE]
    # availability: 50 bad / 100 over the fast window, budget 1-0.999
    av = t["windows"]["burnRate5m"]["availability"]
    assert av["queries"] == 100 and av["bad"] == 50
    assert av["badFraction"] == pytest.approx(0.5)
    assert av["burnRate"] == pytest.approx(0.5 / 0.001, rel=1e-3)
    # slow window is younger than 100s: partial figure from the oldest
    # sample — same delta here, so both windows burn and the table
    # CROSSES into burning exactly once
    assert t["burning"] and ev["crossed"] == [TABLE]
    assert ev["burningTables"] == [TABLE]
    assert ev["worstBurning"][0] == TABLE
    assert reg.gauge("slo.burning").value == 1
    assert reg.gauge("slo.worstBurnRate5m").value > 1.0
    ev2 = slo.evaluate()
    assert ev2["crossed"] == []  # still burning, but no new crossing
    # snapshot() is evaluate() without the edge-trigger field
    assert "crossed" not in slo.snapshot()


def test_slo_multi_window_guard_fast_spike_does_not_page():
    """A burst that burns the FAST window while the slow window is
    healthy must not mark the table burning (multi-window practice)."""
    reg, clk, hist, slo = _slo_rig(fast=10.0, slow=100.0)
    # generous latency budget (target 0.5) so slow-window burn stays <1
    slo.set_objective(TABLE, {"latencyMs": 5.0, "latencyTarget": 0.5})
    for ts in (0.0, 5.0, 10.0, 15.0):
        clk[0] = ts
        for _ in range(75):
            slo.observe(TABLE, 1.0, failed=False)  # under the 5ms bar
        hist.tick()
    clk[0] = 25.0
    for _ in range(10):
        slo.observe(TABLE, 50.0, failed=False)  # every one breaches
    hist.tick()
    ev = slo.evaluate()
    t = ev["tables"][TABLE]
    # fast window (base = sample@15): 10/10 breaches, burn = 1/0.5 = 2
    assert t["burnRate5m"] == pytest.approx(2.0, rel=1e-3)
    # slow window (base = sample@0): 10/310 breaches, burn ~ 0.065
    assert t["burnRate1h"] < 1.0
    assert not t["burning"] and ev["burningTables"] == []


def test_slo_objectives_override_and_clear(monkeypatch):
    from pinot_tpu.utils.slo import SloTracker, default_objective

    monkeypatch.setenv("PINOT_TPU_SLO_LATENCY_MS", "400")
    assert default_objective()["latencyMs"] == 400.0
    slo = SloTracker()
    # partial override: unset fields fall back per-field to env defaults
    slo.set_objective(TABLE, {"latencyTarget": 0.9})
    obj = slo.objective(TABLE)
    assert obj["latencyTarget"] == 0.9 and obj["latencyMs"] == 400.0
    slo.set_objective(TABLE, None)
    assert slo.objective(TABLE) == default_objective()
    # a failed query counts against BOTH availability and latency
    slo.observe(TABLE, 1.0, failed=True)
    s = slo.series()
    assert s[f"slo.{TABLE}.failures"] == 1
    assert s[f"slo.{TABLE}.latencyBreaches"] == 1


# ------------------------------------------------------- tail sampling
def test_tail_sampler_decisions_and_zero_overhead():
    import pinot_tpu.utils.tailsample as ts_mod
    from pinot_tpu.utils.tailsample import TailSampler

    t = TailSampler(enabled=True, slow_ms=100.0, sample_n=4, capacity=3)
    assert t.decide(50.0, failed=True, partial=False) == "failed"
    assert t.decide(50.0, failed=False, partial=True) == "partial"
    assert t.decide(150.0, failed=False, partial=False) == "slow"
    # 4th decide() call: the 1-in-N sample fires even for a fast query
    assert t.decide(1.0, failed=False, partial=False) == "sampled"
    assert t.decide(1.0, failed=False, partial=False) is None

    # zero-overhead contract: a not-retained observe() never calls the
    # scopes builder and never builds a retained entry
    before = ts_mod.TAIL_ALLOCATIONS

    def boom():
        raise AssertionError("scopes built on the not-retained path")

    assert t.observe("r0", 1.0, False, False, boom) is None
    assert ts_mod.TAIL_ALLOCATIONS == before

    # retained path: scopes_fn runs once, entry lands in the ring
    scopes = {
        "brk": [
            {"id": "1", "parent": None, "span": "query", "ms": 100.0},
            {"id": "2", "parent": "1", "span": "laneWait", "ms": 70.0},
        ]
    }
    reason = t.observe(
        "r1", 500.0, False, False, lambda: scopes,
        table=TABLE, plan_digest="d1", summary="SELECT ...",
    )
    assert reason == "slow"
    assert ts_mod.TAIL_ALLOCATIONS == before + 1
    got = t.get("r1")
    assert got is not None and got["reason"] == "slow"
    # self time: the 100ms parent holding a 70ms child splits 30/70
    assert got["phaseSelfMs"] == {"query": 30.0, "laneWait": 70.0}
    # ring bound: capacity 3 evicts the oldest
    for i in range(4):
        t.retain(f"rr{i}", "slow", 300.0, {})
    assert t.get("r1") is None
    snap = t.snapshot()
    assert snap["retained"] == 3 and len(snap["entries"]) == 3
    # span trees are elided from the listing unless asked
    assert all("scopes" not in e for e in snap["entries"])
    assert all("scopes" in e for e in t.snapshot(include_traces=True)["entries"])


def test_tail_phase_self_time_never_double_counts():
    from pinot_tpu.utils.tailsample import phase_self_ms

    # concurrent children overlapping the parent: self floors at 0
    scopes = {
        "s": [
            {"id": "p", "parent": None, "span": "serverQuery", "ms": 100.0},
            {"id": "a", "parent": "p", "span": "stageA", "ms": 80.0},
            {"id": "b", "parent": "p", "span": "stageB", "ms": 60.0},
        ]
    }
    out = phase_self_ms(scopes)
    assert "serverQuery" not in out  # 100 - 140 floors at 0, dropped
    assert out == {"stageA": 80.0, "stageB": 60.0}
    assert phase_self_ms({}) == {}


def test_tail_digest_attribution_fractions():
    from pinot_tpu.utils.tailsample import TailSampler

    t = TailSampler(enabled=True, slow_ms=100.0, sample_n=0, capacity=8)
    scopes = {
        "b": [
            {"id": "1", "parent": None, "span": "query", "ms": 100.0},
            {"id": "2", "parent": "1", "span": "laneWait", "ms": 75.0},
        ]
    }
    for i in range(6):
        t.retain(f"r{i}", "slow", 200.0 + i, scopes, plan_digest="dig",
                 table=TABLE, summary="shape")
    agg = t.snapshot()["byDigest"][0]
    assert agg["digest"] == "dig" and agg["tails"] == 6
    assert agg["topPhase"] == "laneWait"
    assert agg["attribution"]["laneWait"] == pytest.approx(0.75)
    assert sum(agg["attribution"].values()) == pytest.approx(1.0)
    assert agg["latencyMs"]["p50"] <= agg["latencyMs"]["p99"]


def test_tail_env_opt_out(monkeypatch):
    from pinot_tpu.utils.tailsample import TailSampler

    monkeypatch.setenv("PINOT_TPU_TAIL_TRACE", "0")
    assert TailSampler().armed is False
    monkeypatch.delenv("PINOT_TPU_TAIL_TRACE")
    assert TailSampler().armed is True


# ------------------------------------------------------ flight recorder
def test_flight_recorder_dump_prune_rate_limit(tmp_path):
    from pinot_tpu.utils.flightrec import FlightRecorder

    # disabled without a directory: dumps are free no-ops
    off = FlightRecorder("broker", "b0")
    assert not off.enabled and off.maybe_dump("x") is None

    clk = [100.0]
    rec = FlightRecorder(
        "broker", "b0",
        sources={"ok": lambda: {"v": 1}, "sick": lambda: 1 / 0},
        directory=str(tmp_path), max_bundles=2, min_interval_s=30.0,
        clock=lambda: clk[0],
    )
    path = rec.maybe_dump("sloBurn", {"table": TABLE})
    assert path is not None and os.path.exists(path)
    doc = json.loads(open(path).read())
    assert doc["reason"] == "sloBurn" and doc["detail"]["table"] == TABLE
    assert doc["sources"]["ok"] == {"v": 1}
    assert "ZeroDivisionError" in doc["sources"]["sick"]["error"]
    # rate limit: a second dump inside the window is suppressed
    assert rec.maybe_dump("sloBurn") is None
    # bounded: oldest pruned BEFORE writing, never the fresh bundle
    written = [path]
    for i in range(3):
        clk[0] += 31.0
        p = rec.maybe_dump(f"r{i}")
        assert p is not None
        written.append(p)
    files = rec.bundle_files()
    assert len(files) == 2 and files[-1] == written[-1]
    snap = rec.snapshot()
    assert snap["enabled"] and len(snap["bundles"]) == 2
    assert snap["dir"] == str(tmp_path)


def test_tableconfig_slo_roundtrip():
    from pinot_tpu.common.tableconfig import SloConfig, TableConfig

    cfg = TableConfig(
        table_name=TABLE, table_type="OFFLINE",
        slo=SloConfig(latency_ms=250.0, latency_target=0.95),
    )
    d = cfg.to_json()
    assert d["slo"] == {
        "latencyMs": 250.0, "latencyTarget": 0.95, "availabilityTarget": None,
    }
    back = TableConfig.from_json(d)
    assert back.slo is not None and back.slo.latency_ms == 250.0
    # absent block stays absent
    plain = TableConfig(table_name=TABLE, table_type="OFFLINE")
    assert "slo" not in plain.to_json()
    assert TableConfig.from_json(plain.to_json()).slo is None


# --------------------------------------------------- broker integration
@pytest.fixture(scope="module")
def served():
    from pinot_tpu.tools.cluster_harness import single_server_broker

    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 300, seed=9)
    seg = build_segment(schema, rows, TABLE, "tailSeg")
    broker = single_server_broker(TABLE, [seg])
    for _ in range(2):  # warm staging + compile
        r = broker.handle_pql(f"SELECT count(*) FROM {TABLE}")
        assert not r.exceptions
    yield broker
    broker.shutdown()


def test_broker_tail_retention_links_querylog(served, monkeypatch):
    broker = served
    monkeypatch.setattr(broker.tail, "slow_ms", 0.001)  # retain everything
    monkeypatch.setattr(broker.querylog, "threshold_ms", 0.0)
    resp = broker.handle_pql(f"SELECT sum(metInt) FROM {TABLE}")
    assert not resp.exceptions
    # the client did not ask for a trace: even though tail arming traced
    # the query internally (and retained it), the RESPONSE must stay
    # byte-identical to the sampling-off payload — no traceInfo
    assert resp.trace_info == {}
    got = broker.tail.get(resp.request_id)
    assert got is not None and got["reason"] == "slow"
    assert got["table"] == TABLE and got["planDigest"]
    assert got["phaseSelfMs"], "no phase attribution on the retained tail"
    # querylog cross-link, both directions
    entry = next(
        e
        for e in broker.querylog.snapshot()["entries"]
        if e["requestId"] == resp.request_id
    )
    assert entry["traceRetained"] is True
    assert entry["traceRef"] == f"/debug/tails?requestId={resp.request_id}"
    assert broker.metrics.meter("tails.retained").count > 0


def test_broker_not_retained_path_is_zero_overhead(served, monkeypatch):
    import pinot_tpu.utils.tailsample as ts_mod

    broker = served
    monkeypatch.setattr(broker.tail, "slow_ms", 1e9)
    monkeypatch.setattr(broker.tail, "sample_n", 0)
    broker.handle_pql(f"SELECT count(*) FROM {TABLE}")  # warm this config
    before_alloc = ts_mod.TAIL_ALLOCATIONS
    before_obs = broker.metrics.meter("tails.observed").count
    resp = broker.handle_pql(f"SELECT count(*) FROM {TABLE}")
    assert not resp.exceptions
    assert ts_mod.TAIL_ALLOCATIONS == before_alloc, (
        "not-retained query built a tail entry"
    )
    assert resp.trace_info == {}  # armed-but-untraced: no traceInfo leak
    assert broker.metrics.meter("tails.observed").count == before_obs + 1
    # an explicitly traced query still gets its waterfall back even when
    # the tail verdict is drop
    resp = broker.handle_pql(f"SELECT count(*) FROM {TABLE}", trace=True)
    assert resp.trace_info["scopes"]


def test_broker_shed_not_retained_as_tail(served, monkeypatch):
    """A 429 shed is a typed overload verdict, not a failure worth a
    span tree: retaining sheds would do the MOST tail-sampling work
    exactly during a shed storm and flood the bounded ring.  SLO
    availability still counts them."""
    import pinot_tpu.utils.tailsample as ts_mod
    from pinot_tpu.common.response import ErrorCode

    broker = served
    monkeypatch.setattr(broker.tail, "slow_ms", 1e9)
    monkeypatch.setattr(broker.tail, "sample_n", 0)
    broker.quota.set_quota(TABLE, 0.001)  # one initial token, then shed
    try:
        before = ts_mod.TAIL_ALLOCATIONS
        fail0 = broker.slo.series().get(f"slo.{TABLE}.failures", 0)
        sheds = 0
        for _ in range(3):
            resp = broker.handle_pql(f"SELECT count(*) FROM {TABLE}")
            if resp.exceptions:
                assert resp.exceptions[0].error_code == ErrorCode.TOO_MANY_REQUESTS
                sheds += 1
                assert broker.tail.get(resp.request_id) is None
        assert sheds >= 2, "quota never shed"
        assert ts_mod.TAIL_ALLOCATIONS == before, "shed retained as a tail"
        assert broker.slo.series()[f"slo.{TABLE}.failures"] == fail0 + sheds
    finally:
        broker.quota.set_quota(TABLE, None)


def test_broker_http_history_slo_tails_flightrec(served, monkeypatch):
    from pinot_tpu.broker.broker import BrokerHttpServer

    broker = served
    monkeypatch.setattr(broker.tail, "slow_ms", 0.001)
    resp = broker.handle_pql(f"SELECT count(*) FROM {TABLE}")
    broker.history.tick()
    http = BrokerHttpServer(broker)
    http.start()
    try:
        base = f"http://127.0.0.1:{http.port}"

        def get(path, status=200):
            try:
                with urllib.request.urlopen(base + path, timeout=10) as r:
                    assert r.status == status
                    return json.loads(r.read())
            except urllib.error.HTTPError as e:
                assert e.code == status, (path, e.code)
                return json.loads(e.read())

        hist = get("/debug/history?series=queries,slo.&windowS=600")
        assert hist["samples"] >= 1 and hist["windowS"] == 600.0
        assert any(k.startswith("queries") for k in hist["series"])
        assert any(k.startswith("slo.") for k in hist["series"])
        slo = get("/debug/slo")
        assert TABLE in slo["tables"] and "burningTables" in slo
        tails = get("/debug/tails?top=5")
        assert tails["retained"] >= 1 and tails["byDigest"]
        one = get(f"/debug/tails?requestId={resp.request_id}")
        assert one["scopes"], "per-request fetch must include the tree"
        assert get("/debug/tails?requestId=nope", status=404)["error"]
        frec = get("/debug/flightrec")
        assert frec["enabled"] is False  # env not set in this test
    finally:
        http.stop()


def test_role_series_preregistered_at_construction():
    """Metric hygiene: every history.*/slo.*/tails.*/flightrec.* series
    exists (zero-valued) from construction, before any traffic."""
    from pinot_tpu.broker.broker import BrokerRequestHandler
    from pinot_tpu.server.instance import ServerInstance
    from pinot_tpu.transport.local import LocalTransport

    broker = BrokerRequestHandler(LocalTransport(), {}, name="hygBrk")
    snap = broker.metrics.snapshot()
    for m in ("history.ticks", "tails.observed", "tails.retained",
              "flightrec.dumps"):
        assert m in snap["meters"], m
    for g in ("history.series", "slo.burning", "slo.worstBurnRate5m",
              "slo.worstBurnRate1h", "tails.ring", "flightrec.bundles"):
        assert g in snap["gauges"], g
    broker.shutdown()

    server = ServerInstance("hygSrv")
    snap = server.metrics.snapshot()
    for m in ("history.ticks", "flightrec.dumps"):
        assert m in snap["meters"], m
    for g in ("history.series", "flightrec.bundles"):
        assert g in snap["gauges"], g
    server.shutdown()


# ----------------------------------------------- controller + dashboard
def test_controller_history_slo_flightrec_endpoints(tmp_path):
    from pinot_tpu.controller.controller import Controller, ControllerHttpServer

    ctrl = Controller(str(tmp_path))
    http = ControllerHttpServer(ctrl)
    http.start()
    try:
        base = f"http://{http.host}:{http.port}"
        ctrl.history.tick()
        hist = json.loads(
            urllib.request.urlopen(base + "/debug/history?windowS=60", timeout=10).read()
        )
        assert hist["samples"] >= 1
        # controller + stabilizer registries ride the same recorder
        assert any(k.startswith("stabilizer.") for k in hist["series"])
        slo = json.loads(
            urllib.request.urlopen(base + "/debug/slo", timeout=10).read()
        )
        assert slo["brokers"] == 0 and slo["tables"] == {}
        frec = json.loads(
            urllib.request.urlopen(base + "/debug/flightrec", timeout=10).read()
        )
        assert frec["enabled"] is False
        page = urllib.request.urlopen(base + "/dashboard/slo", timeout=10).read()
        assert b"SLO burn rates" in page and b"no table burning" in page
    finally:
        http.stop()
        ctrl.stop()


# ------------------------------------------------------- chaos scenarios
def test_chaos_slo_burn_crossing_tails_and_flight_bundle(tmp_path, monkeypatch):
    """Kill the only server under a warmed table: the SLO burn gauge
    crosses, sloBurn + failedQuery flight bundles land on disk, and
    /debug/tails attributes the victim table's tail latency."""
    from pinot_tpu.common.tableconfig import SloConfig
    from pinot_tpu.tools.cluster_harness import InProcessCluster

    frec = tmp_path / "frec"
    monkeypatch.setenv("PINOT_TPU_FLIGHTREC_DIR", str(frec))
    monkeypatch.setenv("PINOT_TPU_FLIGHTREC_MIN_INTERVAL_S", "0")
    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path / "data"))
    try:
        schema = make_test_schema(with_mv=False)
        physical = cluster.add_offline_table(
            schema, slo=SloConfig(latency_ms=123.0)
        )
        rows = random_rows(schema, 120, seed=3)
        cluster.upload(physical, build_segment(schema, rows, physical, "s0"))
        broker = cluster.broker
        # the table-config SLO block landed via the starter path
        assert broker.slo.objective(TABLE)["latencyMs"] == 123.0
        broker.tail.slow_ms = 0.001  # retain the healthy-path tails too
        for _ in range(3):
            r = cluster.query(f"SELECT sum(metInt) FROM {TABLE}")
            assert not r.exceptions
        broker.history.tick()  # baseline sample: healthy traffic

        cluster.transport.set_down(("server0", 0))  # kill the only server
        for _ in range(8):
            r = cluster.query(f"SELECT count(*) FROM {TABLE}")
            assert r.exceptions, "query must fail with the server dead"
        time.sleep(0.02)
        broker.history.tick()  # burn evaluation + flight trigger fire here

        assert broker.metrics.gauge("slo.burning").value >= 1
        assert broker.metrics.gauge("slo.worstBurnRate5m").value > 1.0
        names = os.listdir(frec)
        assert any("-sloBurn-" in f for f in names), names
        assert any("-failedQuery-" in f for f in names), names
        bundle = json.loads(
            open(frec / next(f for f in names if "-sloBurn-" in f)).read()
        )
        assert bundle["detail"]["table"] == TABLE
        assert bundle["detail"]["burnRate5m"] > 1.0
        for source in ("history", "slowQueries", "tails", "slo"):
            assert source in bundle["sources"], source

        # tails attribute the victim table: healthy tails carry server-
        # side phases, the post-kill failures are retained as "failed"
        snap = broker.tail.snapshot()
        aggs = [a for a in snap["byDigest"] if a["table"] == TABLE]
        assert aggs and aggs[0]["topPhase"], aggs
        assert any(e["reason"] == "failed" for e in snap["entries"])
    finally:
        cluster.stop()


def test_chaos_kill_server_leaves_controller_flight_bundle(tmp_path, monkeypatch):
    """The kill-server chaos shape (satellite): a server death + heal
    round spotted on the controller's history cadence dumps a
    controller flight-recorder bundle."""
    from pinot_tpu.tools.cluster_harness import _build_scenario_cluster

    frec = tmp_path / "frec"
    monkeypatch.setenv("PINOT_TPU_FLIGHTREC_DIR", str(frec))
    monkeypatch.setenv("PINOT_TPU_FLIGHTREC_MIN_INTERVAL_S", "0")
    cluster, physical, total = _build_scenario_cluster(
        3, 2, 4, data_dir=str(tmp_path / "data")
    )
    try:
        cluster.transport.set_down(("server0", 0))
        cluster.controller.resources.set_instance_alive("server0", False)
        cluster.controller.stabilizer.run_once()  # re-replication = heal
        cluster.controller.history.tick()  # deterministic trigger point
        files = [f for f in os.listdir(frec) if "-controller-" in f]
        assert files, os.listdir(frec)
        bundle = json.loads(open(frec / files[-1]).read())
        assert bundle["reason"] == "serverDeathOrHeal"
        assert bundle["detail"]["notableEventsThisTick"] > 0
        for source in ("history", "metrics", "stabilizer"):
            assert source in bundle["sources"], source
        # the serving bar of the scenario still holds
        final = cluster.query(f"SELECT count(*) FROM {TABLE}")
        assert final.num_docs_scanned == total and not final.exceptions
    finally:
        cluster.stop()


# ------------------------------------------------------------- doctor
def test_doctor_bundle_and_tail_report(tmp_path, monkeypatch):
    """Tier-1 doctor smoke (satellite): against a networked in-process
    cluster under closed-loop load, the doctor produces one parseable
    bundle carrying every role's debug surfaces, inlined flight
    bundles, and retained tails — and tail_report renders it."""
    from pinot_tpu.tools import doctor, tail_report
    from pinot_tpu.tools.cluster_harness import (
        ClosedLoopLoad,
        _build_partition_cluster,
    )

    monkeypatch.setenv("PINOT_TPU_FLIGHTREC_DIR", str(tmp_path / "frec"))
    monkeypatch.setenv("PINOT_TPU_FLIGHTREC_MIN_INTERVAL_S", "0")
    monkeypatch.setenv("PINOT_TPU_TAIL_SLOW_MS", "0.001")
    cluster, physical, total = _build_partition_cluster(
        2, 2, 3, data_dir=str(tmp_path / "data")
    )
    try:
        load = ClosedLoopLoad(
            cluster, f"SELECT count(*) FROM {TABLE}", total, clients=2
        ).start()
        time.sleep(0.4)
        summary = load.stop()
        assert summary["okQueries"] > 0
        cluster.query("SELECT count(*) FROM nosuchtable")  # -> flight bundle
        cluster.broker.history.tick()
        for s in cluster.server_starters:
            s.server.history.tick()
        cluster.controller.history.tick()

        bundle = doctor.collect(cluster.url, timeout_s=10)
        json.dumps(bundle)  # parseable end to end
        roles = bundle["summary"]["instances"]
        assert roles.get("broker") == 1 and roles.get("server") == 2
        assert bundle["summary"]["fetchErrors"] == 0, bundle["summary"]
        assert bundle["summary"]["retainedTails"] > 0
        assert bundle["summary"]["flightBundles"] >= 1
        # the controller's fleet SLO rollup saw the loaded table
        assert TABLE in bundle["controller"]["/debug/slo"]["tables"]
        brk = next(
            e for e in bundle["instances"].values() if e["role"] == "broker"
        )
        assert brk["endpoints"]["/debug/history"]["series"]
        assert TABLE in brk["endpoints"]["/debug/slo"]["tables"]
        assert brk["flightBundles"], "failedQuery bundle not inlined"
        srv = next(
            e for e in bundle["instances"].values() if e["role"] == "server"
        )
        assert srv["endpoints"]["/debug/history"]["series"]

        # CLI path writes the same bundle to disk
        out = tmp_path / "doctor.json"
        assert doctor.main([cluster.url, "--out", str(out)]) == 0
        assert json.loads(out.read_text())["summary"]["retainedTails"] > 0

        # tail_report digs the tails payloads out of the doctor bundle
        payloads = tail_report._find_tails_payloads(bundle)
        assert payloads
        text = tail_report.render_report(tail_report._merge(payloads))
        assert "retained" in text and "top phase" in text

        # live SLO objective propagation over the network poll path
        # (update_table_slo bumps the clusterstate version — a silent
        # config mutation would never reach a polling broker)
        from pinot_tpu.common.tableconfig import SloConfig

        cluster.controller.resources.update_table_slo(
            physical, SloConfig(latency_ms=150.0)
        )
        cluster.wait(
            lambda: cluster.broker.slo.objective(TABLE)["latencyMs"] == 150.0,
            what="slo objective propagation",
        )
        cluster.controller.resources.update_table_slo(physical, None)
        cluster.wait(
            lambda: cluster.broker.slo.objective(TABLE)["latencyMs"] != 150.0,
            what="slo objective clearing",
        )
    finally:
        cluster.stop()


def test_tail_report_and_doctor_pure_renderers():
    from pinot_tpu.tools import doctor, tail_report

    empty = tail_report.render_report({"observed": 0, "retained": 0})
    assert "no retained tails" in empty
    snap = {
        "observed": 100, "retained": 2, "slowMs": 250.0, "sampleN": 128,
        "entries": [
            {"requestId": "b-1", "reason": "slow", "timeUsedMs": 400.0,
             "table": TABLE, "planDigest": "deadbeef", "ts": 2.0},
        ],
        "byDigest": [
            {"digest": "deadbeef", "summary": "SELECT ...", "table": TABLE,
             "tails": 2, "windowTails": 2,
             "latencyMs": {"p50": 300.0, "p99": 400.0},
             "phaseMs": {"laneWait": 70.0, "query": 30.0},
             "attribution": {"laneWait": 0.7, "query": 0.3},
             "topPhase": "laneWait"},
        ],
    }
    text = tail_report.render_report(snap)
    assert "deadbeef" in text and "laneWait (70.0%)" in text
    assert "b-1" in text

    summary = doctor.summarize(
        {
            "controller": {"/debug/slo": {"burningTables": [TABLE]}},
            "instances": {
                "b0": {"role": "broker",
                       "endpoints": {"/debug/tails?traces=true": {"retained": 3}}},
                "s0": {"role": "server", "error": "no HTTP surface registered"},
            },
        }
    )
    assert summary["burningTables"] == [TABLE]
    assert summary["retainedTails"] == 3
    assert summary["instances"] == {"broker": 1, "server": 1}
    assert summary["fetchErrors"] == 1
