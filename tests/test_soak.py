"""Opt-in high-volume differential soak (PINOT_TPU_SOAK=1).

The default differential tests run ~120 generated queries per suite at
small scale; this soak runs 1600 at 4k rows with high-cardinality
group-bys — the regime that surfaces tie-boundary trims and f32
cancellation. Ran clean on 2026-07-30 (45/1600 raw diffs, all
classified benign: float accumulation + tie ordering, 0 real).

The comparator encodes the engine's accuracy CONTRACT, not bit
equality:
- group VALUE sequences agree within rel 1e-4 OR abs 2e-3 (f32 sums
  under cancellation lose relative precision — production accumulates
  f32 for MXU/HBM throughput where the reference uses f64;
  BASELINE.md's own tolerance is rtol 1e-4 at bench scale),
- common groups agree to the same tolerance,
- groups present in only one engine sit AT the TOP-N boundary value
  (any tie order is a correct answer).
"""
import math
import os

import pytest

if os.environ.get("PINOT_TPU_SOAK") != "1":
    pytest.skip("soak runs via PINOT_TPU_SOAK=1", allow_module_level=True)

from pinot_tpu.engine.executor import QueryExecutor
from pinot_tpu.engine.reduce import reduce_to_response
from pinot_tpu.pql import optimize_request, parse_pql
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.tools.datagen import make_test_schema, random_rows
from pinot_tpu.tools.query_gen import QueryGenerator
from pinot_tpu.tools.scan_engine import ScanQueryProcessor

REL, ABS = 1e-4, 2e-3


def _close(a, b):
    try:
        fa, fb = float(a), float(b)
    except (TypeError, ValueError):
        return a == b
    if math.isinf(fa) or math.isinf(fb):
        return fa == fb
    return abs(fa - fb) <= max(ABS, REL * max(1.0, abs(fa), abs(fb)))


def _groupby_ok(g_res, w_res):
    gv = [float(r["value"]) for r in g_res]
    wv = [float(r["value"]) for r in w_res]
    if len(gv) != len(wv) or not all(_close(a, b) for a, b in zip(gv, wv)):
        return False
    gm = {tuple(r["group"]): r["value"] for r in g_res}
    wm = {tuple(r["group"]): r["value"] for r in w_res}
    if not all(_close(gm[k], wm[k]) for k in gm.keys() & wm.keys()):
        return False
    boundary = min(gv, default=0.0)
    return all(
        _close(float(v), boundary)
        for k in gm.keys() ^ wm.keys()
        for v in (gm.get(k, wm.get(k)),)
    )


def _result_ok(got, want, request):
    ga, wa = got.get("aggregationResults", []), want.get("aggregationResults", [])
    if len(ga) != len(wa):
        return False
    for g1, w1 in zip(ga, wa):
        if "groupByResult" in g1 or "groupByResult" in w1:
            if not _groupby_ok(
                g1.get("groupByResult", []), w1.get("groupByResult", [])
            ):
                return False
        elif not _close(g1.get("value"), w1.get("value")):
            return False
    return _selection_ok(got, want, request)


def _selection_ok(got, want, request):
    """Order-aware selection compare: exact rows, else LIMIT-tie-tolerant.

    With ORDER BY, the ordered key SEQUENCE must match exactly; rows
    whose key is strictly inside the cut line must match as a multiset,
    and only boundary-key rows may differ (any tie order is correct).
    Without ORDER BY, any LIMIT-sized subset of matching rows is a
    correct answer, so equal row counts plus a multiset check against
    the union is the strongest portable assertion."""
    g, w = got.get("selectionResults", {}), want.get("selectionResults", {})
    if g.get("columns") != w.get("columns"):
        return False
    gr = [tuple(r) for r in g.get("results", [])]
    wr = [tuple(r) for r in w.get("results", [])]
    if sorted(gr) == sorted(wr):
        return True
    if len(gr) != len(wr):
        return False
    sel = getattr(request, "selection", None)
    sorts = list(getattr(sel, "sorts", []) or []) if sel is not None else []
    if not sorts:
        return False  # same count, different unordered rows: suspicious
    cols = g.get("columns", [])
    try:
        key_idx = [cols.index(s.column) for s in sorts]
    except ValueError:
        return False
    gk = [tuple(r[i] for i in key_idx) for r in gr]
    wk = [tuple(r[i] for i in key_idx) for r in wr]
    if gk != wk:
        return False  # ordered key sequences must agree exactly
    boundary = gk[-1]
    g_in = sorted(r for r, k in zip(gr, gk) if k != boundary)
    w_in = sorted(r for r, k in zip(wr, wk) if k != boundary)
    return g_in == w_in


def test_soak_1600_queries():
    schema = make_test_schema()
    rows = random_rows(schema, 4000, seed=7)
    chunk = len(rows) // 3
    segments = [
        build_segment(
            schema,
            rows[i * chunk : (i + 1) * chunk if i < 2 else len(rows)],
            "testTable",
            f"s{i}",
        )
        for i in range(3)
    ]
    oracle = ScanQueryProcessor(schema, rows)
    ex = QueryExecutor()
    bad = []
    for seed in (101, 202, 303, 404):
        gen = QueryGenerator(schema, rows, seed=seed)
        for _ in range(400):
            pql = gen.next_query()
            req_e = optimize_request(parse_pql(pql))
            req_o = optimize_request(parse_pql(pql))
            got = reduce_to_response(req_e, [ex.execute(segments, req_e)]).to_json()
            want = oracle.execute(req_o).to_json()
            if not _result_ok(got, want, req_e):
                bad.append(pql)
    assert not bad, f"{len(bad)} real mismatches; first: {bad[0]}"
