"""Workload introspection plane (PR 8): EXPLAIN / EXPLAIN ANALYZE,
the per-plan-digest stats registry, and the compile timeline.

Tier-1 guards: the EXPLAIN JSON top-level schema is golden (clients
script against it), plain EXPLAIN performs ZERO device work (no lane
submissions, no cost meters marked — safe to call in production), a
poisoned plan's EXPLAIN reports the host tier it will ACTUALLY serve
from, and /debug/plans tier mixes reconcile exactly with the
cost-vector tier counters after a mixed workload."""
import json
import math
import struct

import pytest

from pinot_tpu.common.datatable import MAGIC, deserialize_result, serialize_result
from pinot_tpu.engine.plandigest import plan_shape_digest, plan_shape_summary
from pinot_tpu.engine.results import IntermediateResult
from pinot_tpu.pql import parse_pql, optimize_request
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.tools.cluster_harness import InProcessCluster, single_server_broker
from pinot_tpu.tools.datagen import make_test_schema, random_rows


# --------------------------------------------------------------- parser
def test_explain_parser_prefix_variants():
    assert parse_pql("EXPLAIN SELECT count(*) FROM t").explain == "plan"
    assert parse_pql("EXPLAIN PLAN FOR SELECT count(*) FROM t").explain == "plan"
    assert parse_pql("EXPLAIN ANALYZE SELECT count(*) FROM t").explain == "analyze"
    assert parse_pql("SELECT count(*) FROM t").explain is None
    # the inner query still parses fully (filters, group by...)
    req = parse_pql("EXPLAIN SELECT sum(m) FROM t WHERE a > 5 GROUP BY b TOP 3")
    assert req.explain == "plan" and req.is_group_by
    # a broken inner query still raises a parse error
    from pinot_tpu.pql import PqlParseError

    with pytest.raises(PqlParseError):
        parse_pql("EXPLAIN SELECT FROM t")


# --------------------------------------------------------------- digest
def test_plan_shape_digest_erases_literals_not_shape():
    def dig(pql):
        return plan_shape_digest(optimize_request(parse_pql(pql)))

    # literals erased: same shape, different constants -> same digest
    assert dig("SELECT sum(m) FROM t WHERE a > 5") == dig(
        "SELECT sum(m) FROM t WHERE a > 999"
    )
    assert dig("SELECT count(*) FROM t WHERE a IN (1, 2)") == dig(
        "SELECT count(*) FROM t WHERE a IN (7, 8)"
    )
    # physical suffix stripped: broker (logical) and server (physical)
    # key the same series
    assert dig("SELECT sum(m) FROM t WHERE a > 5") == dig(
        "SELECT sum(m) FROM t_OFFLINE WHERE a > 5"
    )
    # shape changes change the digest
    assert dig("SELECT sum(m) FROM t WHERE a > 5") != dig(
        "SELECT sum(m) FROM t WHERE b > 5"
    )
    assert dig("SELECT sum(m) FROM t") != dig("SELECT max(m) FROM t")
    assert dig("SELECT sum(m) FROM t GROUP BY a") != dig(
        "SELECT sum(m) FROM t GROUP BY b"
    )
    # the EXPLAIN prefix itself does not change the shape
    assert dig("EXPLAIN SELECT sum(m) FROM t WHERE a > 5") == dig(
        "SELECT sum(m) FROM t WHERE a > 5"
    )
    s = plan_shape_summary(optimize_request(parse_pql(
        "SELECT sum(m) FROM t WHERE a > 5 GROUP BY b"
    )))
    assert "sum_m" in s and "from t" in s


# ----------------------------------------------------------------- wire
def test_plan_info_wire_roundtrip_and_backward_compat():
    res = IntermediateResult(plan_info=[{"server": "s0", "tierCounts": {"segmentsHost": 1}}])
    out = deserialize_result(serialize_result(res))
    assert out.plan_info == res.plan_info
    # a payload from a pre-introspection peer (no trailing plan list)
    # must still deserialize: chop the trailing empty list (b"l"+i64(0))
    # plus the later join-payload None (b"N") and freshness None (b"N")
    data = serialize_result(IntermediateResult(num_docs_scanned=3))
    payload = data[16:-11]
    old = MAGIC + struct.pack("<Q", len(payload)) + payload
    back = deserialize_result(old)
    assert back.num_docs_scanned == 3 and back.plan_info == []


# --------------------------------------------------- golden shape guard
EXPLAIN_TOP_KEYS = {
    "mode", "planDigest", "summary", "numServers", "tierCounts",
    "estimatedCost", "servers",
}
NODE_REQUIRED_KEYS = {
    "server", "table", "planDigest", "summary", "numSegments", "totalDocs",
    "tierCounts", "segments", "staged", "estimatedCost",
}


_FIXTURE_SEQ = __import__("itertools").count()


@pytest.fixture()
def explain_broker():
    # unique segment names per instantiation: the HBM ledger is
    # process-global and keys entries by segment name, so reused names
    # from an earlier test's staging would pollute the zero-staged guard
    n = next(_FIXTURE_SEQ)
    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 1600, seed=5)
    segs = [
        build_segment(schema, rows[:800], "expTable", f"xg{n}a"),
        build_segment(schema, rows[800:], "expTable", f"xg{n}b"),
    ]
    broker = single_server_broker("expTable", segs, pipeline=True)
    broker.test_seg_names = [s.segment_name for s in segs]
    yield broker
    broker.local_servers[0].shutdown()


def test_explain_golden_schema_and_zero_device_work(explain_broker):
    """Schema-stability guard: the EXPLAIN JSON top level is golden,
    and plain EXPLAIN launches nothing — zero lane submissions, zero
    cost meters marked — on a COLD server (nothing ever staged)."""
    broker = explain_broker
    server = broker.local_servers[0]
    resp = broker.handle_pql(
        "EXPLAIN SELECT sum(metInt) FROM expTable WHERE dimInt > 40"
    )
    assert not resp.exceptions, resp.exceptions

    j = resp.to_json()
    assert set(j["explain"].keys()) == EXPLAIN_TOP_KEYS
    assert j["explain"]["mode"] == "plan"
    assert j["planDigest"] == j["explain"]["planDigest"]
    # EXPLAIN returns the plan INSTEAD of results
    assert "aggregationResults" not in j and "selectionResults" not in j

    node = j["explain"]["servers"][0]
    assert NODE_REQUIRED_KEYS.issubset(node.keys())
    assert node["tierCounts"] and sum(node["tierCounts"].values()) == 2
    for seg in node["segments"]:
        assert {"segment", "tier", "reason"}.issubset(seg.keys())

    # ZERO device work: no lane submission happened, no cost marked,
    # nothing got staged into HBM on this query's behalf
    lane = server.lane.stats()
    assert lane["dispatches"] == 0 and lane["depth"] == 0
    assert lane["coalesceHits"] == 0 and lane["shed"] == 0
    assert server.metrics.meter("cost.docsScanned").count == 0
    assert server.metrics.meter("cost.bytesScanned").count == 0
    for k in server._TIER_KEYS:
        assert server.metrics.meter(f"cost.tier.{k}").count == 0, k
    assert node["staged"]["hbmBytes"] == 0  # nothing staged by EXPLAIN
    # and the plan-stats registry did NOT count it as an execution
    assert server.plan_stats.snapshot()["plans"] == []
    assert server.metrics.meter("plan.explains").count == 1


def test_explain_device_digest_matches_real_execution(explain_broker):
    """The phantom-staged StaticPlan digest must equal the digest the
    real execution hands the lane — else the compile registry and the
    poison-honesty lookup would silently miss."""
    broker = explain_broker
    server = broker.local_servers[0]
    pql = "SELECT sum(metInt) FROM expTable WHERE dimInt > 40"
    pre = broker.handle_pql("EXPLAIN " + pql)
    dev = pre.explain["servers"][0]["device"]
    assert dev["compile"]["state"] == "cold"  # never launched here

    real = broker.handle_pql(pql)
    assert not real.exceptions
    assert server.lane.stats()["compiledPlans"] >= 1
    assert server.lane.compile_info(dev["planDigest"]) is not None, (
        "phantom plan digest diverged from the real staged plan"
    )
    post = broker.handle_pql("EXPLAIN " + pql)
    comp = post.explain["servers"][0]["device"]["compile"]
    assert comp["state"] == "warm" and comp["firstCallMs"] > 0


def test_compile_timeline_cold_then_warm(explain_broker):
    broker = explain_broker
    server = broker.local_servers[0]
    pql = "SELECT max(metFloat) FROM expTable WHERE dimInt > 10"
    broker.handle_pql(pql)
    cold0 = server.metrics.meter("compile.cold").count
    assert cold0 >= 1
    assert server.metrics.timer("compile.firstCallMs").count == cold0
    broker.handle_pql(pql)
    assert server.metrics.meter("compile.cold").count == cold0  # no re-compile
    assert server.metrics.meter("compile.warm").count >= 1


def test_explain_analyze_actuals_match_cost(explain_broker):
    broker = explain_broker
    pql = "SELECT sum(metInt) FROM expTable GROUP BY dimStr TOP 5"
    resp = broker.handle_pql("EXPLAIN ANALYZE " + pql)
    assert not resp.exceptions
    ex = resp.explain
    assert ex["mode"] == "analyze"
    # results ARE returned for analyze (it executed)
    assert resp.aggregation_results is not None
    # node actuals sum exactly to the merged BrokerResponse.cost
    summed = {}
    for node in ex["servers"]:
        for k, v in node["actualCost"].items():
            summed[k] = summed.get(k, 0) + v
    assert set(summed) == set(resp.cost)
    for k, v in resp.cost.items():
        assert math.isclose(summed[k], v, rel_tol=1e-9), k
    assert ex["actualDocsScanned"] == resp.num_docs_scanned


# ----------------------------------------------- honesty under healing
@pytest.mark.chaos
def test_explain_honest_about_poison_quarantine():
    """A poisoned (quarantined) plan's EXPLAIN must report the host
    tier it will ACTUALLY serve from — not the device tier it would
    have picked — and flip back after clear_poisoned()."""
    from pinot_tpu.common.faults import DeviceFaultInjector

    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 2000, seed=31)
    segs = [
        build_segment(schema, rows[:1000], "poisT", "q0"),
        build_segment(schema, rows[1000:], "poisT", "q1"),
    ]
    inj = DeviceFaultInjector(seed=7)
    broker = single_server_broker(
        "poisT", segs, pipeline=True, device_fault_injector=inj
    )
    server = broker.local_servers[0]
    try:
        pql = "SELECT sum(metInt) FROM poisT GROUP BY dimStr TOP 5"
        assert not broker.handle_pql(pql).exceptions
        pre = broker.handle_pql("EXPLAIN " + pql).explain["servers"][0]
        assert "segmentsHost" not in pre["tierCounts"]
        device_digest = pre["device"]["planDigest"]
        assert device_digest == inj.launches[-1].digest

        inj.poison_plan(device_digest)
        failed_over = broker.handle_pql(pql)  # quarantines + host-serves
        assert not failed_over.exceptions
        assert failed_over.cost.get("segmentsHost") == 2

        post = broker.handle_pql("EXPLAIN " + pql).explain["servers"][0]
        assert post["tierCounts"] == {"segmentsHost": 2}, post["tierCounts"]
        assert post["device"]["quarantined"] is True
        assert all(
            s["tier"] == "host" and "quarantined" in s["reason"]
            for s in post["segments"]
        )

        # re-admission: EXPLAIN flips back to the device tier
        inj.heal()
        server.executor.clear_poisoned()
        cleared = broker.handle_pql("EXPLAIN " + pql).explain["servers"][0]
        assert "segmentsHost" not in cleared["tierCounts"]
        assert cleared["device"]["quarantined"] is False
    finally:
        server.shutdown()


# ------------------------------------------ stats registry reconciliation
MIXED_WORKLOAD = [
    "SELECT count(*) FROM testTable",
    "SELECT count(*) FROM testTable",
    "SELECT sum(metInt), max(metFloat) FROM testTable WHERE dimInt > 40",
    "SELECT sum(metInt) FROM testTable GROUP BY dimStr TOP 5",
    "SELECT dimStr, metInt FROM testTable ORDER BY metInt DESC LIMIT 5",
    "SELECT sum(metInt), max(metFloat) FROM testTable WHERE dimInt > 80",
]


def test_plan_stats_reconcile_with_cost_tier_counters(tmp_path):
    """Acceptance: after a mixed workload, /debug/plans per-digest exec
    counts and tier mixes reconcile exactly with the cost-vector tier
    counters (cost.tier.* meters) and with the summed responses."""
    cluster = InProcessCluster(num_servers=2, data_dir=str(tmp_path))
    try:
        schema = make_test_schema(with_mv=False)
        physical = cluster.add_offline_table(schema, replication=2)
        rows = random_rows(schema, 2400, seed=13)
        for i in range(4):
            cluster.upload(
                physical,
                build_segment(
                    schema, rows[i * 600 : (i + 1) * 600], physical, f"w{i}"
                ),
            )
        expected_cost = {}
        for pql in MIXED_WORKLOAD:
            resp = cluster.query(pql)
            assert not resp.exceptions, (pql, resp.exceptions)
            for k, v in resp.cost.items():
                expected_cost[k] = expected_cost.get(k, 0) + v

        tier_keys = (
            "segmentsPruned", "segmentsPostings", "segmentsZonemap",
            "segmentsFullScan", "segmentsHost", "segmentsStarTree",
        )
        # per-server: plan-stats tier mixes == cost.tier.* meters
        for server in cluster.servers:
            snap = server.plan_stats.snapshot(top=50)
            assert snap["digests"] >= 1
            mix_sum = {}
            execs = 0
            for plan in snap["plans"]:
                execs += plan["count"]
                for k, v in plan["tierMix"].items():
                    mix_sum[k] = mix_sum.get(k, 0) + v
            assert execs == server.metrics.meter("plan.recorded").count
            for k in tier_keys:
                assert mix_sum.get(k, 0) == server.metrics.meter(
                    f"cost.tier.{k}"
                ).count, k
        # cluster-wide: server tier meters sum to the responses' tiers
        for k in tier_keys:
            total = sum(
                s.metrics.meter(f"cost.tier.{k}").count for s in cluster.servers
            )
            assert total == expected_cost.get(k, 0), k

        # broker workload roll-up: distinct shapes, counts, both orders
        wl = cluster.broker.workload_snapshot()
        distinct = len({plan_shape_digest(optimize_request(parse_pql(p)))
                        for p in MIXED_WORKLOAD})
        assert wl["digests"] == distinct
        assert sum(p["count"] for p in wl["topByCount"]) == len(MIXED_WORKLOAD)
        top = wl["topByCount"][0]
        assert top["count"] == 2  # the repeated count(*) leads by frequency
        assert {p["digest"] for p in wl["topByCost"]} == {
            p["digest"] for p in wl["topByCount"]
        }

        # querylog cross-link: entries carry the digest of their shape
        from pinot_tpu.broker.querylog import SlowQueryLog

        old_log = cluster.broker.querylog
        cluster.broker.querylog = SlowQueryLog(threshold_ms=0.0)
        try:
            resp = cluster.query(MIXED_WORKLOAD[0], trace=True)
            entry = cluster.broker.querylog.entries()[0]
            assert entry["planDigest"] == resp.plan_digest
            assert any(
                p["digest"] == entry["planDigest"] for p in wl["topByCount"]
            )
            # trace_dump footer renders the tier decisions + the digest
            from pinot_tpu.tools.trace_dump import render_tiers

            footer = render_tiers(resp.to_json())
            assert f"planDigest={resp.plan_digest}" in footer
            assert "=" in footer and footer.startswith("tiers: ")
        finally:
            cluster.broker.querylog = old_log
    finally:
        cluster.stop()


# --------------------------------------------------- endpoints + pages
def test_workload_endpoints_and_dashboard(tmp_path):
    import urllib.request

    from pinot_tpu.controller.controller import (
        ControllerHttpServer,
        collect_workload,
    )
    from pinot_tpu.server.network_starter import ServerAdminHttpServer

    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path), http=True)
    admin = None
    http = None
    try:
        schema = make_test_schema(with_mv=False)
        physical = cluster.add_offline_table(schema)
        rows = random_rows(schema, 600, seed=19)
        cluster.upload(physical, build_segment(schema, rows, physical, "wd0"))
        for _ in range(3):
            assert not cluster.query(
                "SELECT sum(metInt) FROM testTable WHERE dimInt > 5"
            ).exceptions

        # broker /debug/workload over HTTP
        base = f"http://{cluster.http.host}:{cluster.http.port}"
        with urllib.request.urlopen(base + "/debug/workload", timeout=10) as r:
            wl = json.loads(r.read())
        assert wl["digests"] == 1 and wl["topByCount"][0]["count"] == 3
        assert wl["topByCount"][0]["cost"]["bytesScanned"] > 0

        # server /debug/plans over the admin surface
        admin = ServerAdminHttpServer(cluster.servers[0])
        admin.start()
        with urllib.request.urlopen(admin.url + "/debug/plans", timeout=10) as r:
            plans = json.loads(r.read())
        assert plans["digests"] == 1
        assert plans["plans"][0]["count"] == 3
        assert plans["plans"][0]["tierMix"]
        with urllib.request.urlopen(
            admin.url + "/debug/plans?by=cost", timeout=10
        ) as r:
            assert json.loads(r.read())["orderedBy"] == "cost"
        # and in status() for in-process harnesses
        assert cluster.servers[0].status()["plans"]["digests"] == 1

        # controller roll-up + dashboard page
        wl2 = collect_workload(cluster.controller)
        assert wl2["brokers"] == 1 and wl2["digests"] == 1
        assert wl2["topByCount"][0]["count"] == 3
        http = ControllerHttpServer(cluster.controller)
        http.start()
        cbase = f"http://127.0.0.1:{http.port}"
        with urllib.request.urlopen(cbase + "/debug/workload", timeout=10) as r:
            over = json.loads(r.read())
        assert over["digests"] == 1
        with urllib.request.urlopen(cbase + "/dashboard/workload", timeout=10) as r:
            page = r.read().decode()
        assert "Workload" in page and over["topByCount"][0]["digest"] in page
    finally:
        if http is not None:
            http.stop()
        if admin is not None:
            admin.stop()
        cluster.stop()


# -------------------------------------------------------- explain_dump
def test_explain_dump_renders_plan_and_analyze(explain_broker):
    from pinot_tpu.tools.explain_dump import render_explain

    broker = explain_broker
    pql = "SELECT sum(metInt) FROM expTable WHERE dimInt > 40"
    plan = broker.handle_pql("EXPLAIN " + pql)
    out = render_explain(plan.to_json())
    assert out.startswith("EXPLAIN ")
    assert "digest=" in out and "server benchServer" in out
    for name in broker.test_seg_names:
        assert name in out

    analyze = broker.handle_pql("EXPLAIN ANALYZE " + pql)
    out2 = render_explain(analyze.to_json())
    assert "EXPLAIN ANALYZE" in out2
    assert "actual:" in out2 and "est=" in out2 and "x)" in out2

    # graceful on a non-explain response
    assert render_explain({"numDocsScanned": 5}).startswith("(no explain tree")
