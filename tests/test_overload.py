"""Multi-tenant overload protection: broker adaptive admission, server
fair-share scheduling, ingest backpressure — unit tiers plus the
tier-1 noisy-neighbor chaos acceptance (ISSUE 7).

The scenario functions live in ``tools/cluster_harness.py`` so the SAME
code drives manual CLI chaos runs and these deterministic tests."""
import threading
import time

import pytest

from pinot_tpu.broker.admission import AdmissionController
from pinot_tpu.broker.quota import QueryQuotaManager
from pinot_tpu.realtime.backpressure import IngestBackpressure
from pinot_tpu.server.scheduler import QueryScheduler, SchedulerSaturatedError


# ------------------------------------------------------------ admission units
def test_admission_quota_tier_shed():
    quota = QueryQuotaManager()
    quota.set_quota("t", 1.0)
    adm = AdmissionController(quota=quota)
    d1 = adm.try_admit("t")
    assert d1.admitted
    adm.release("t")
    d2 = adm.try_admit("t")  # bucket (capacity 1) drained
    assert not d2.admitted and d2.tier == "quota"
    assert "quota" in d2.message


def test_admission_concurrency_tier_shed_and_release():
    adm = AdmissionController(max_inflight_per_table=2)
    assert adm.try_admit("t").admitted
    assert adm.try_admit("t").admitted
    d = adm.try_admit("t")
    assert not d.admitted and d.tier == "concurrency"
    # other tables are unaffected — the cap is per table
    assert adm.try_admit("other").admitted
    adm.release("t")
    assert adm.try_admit("t").admitted  # slot freed
    for _ in range(2):
        adm.release("t")
    adm.release("other")
    assert adm.table_inflight("t") == 0


def test_admission_aimd_window_decrease_and_recovery():
    adm = AdmissionController(initial_window=8, min_window=1, max_window=16)
    # saturation evidence (210 reply / transport failure) halves the window
    adm.on_attempt_start("s1")
    adm.on_attempt_done("s1", saturated=True)
    assert adm.window_of("s1") == 4.0
    adm.on_attempt_start("s1")
    adm.on_attempt_done("s1", saturated=True)
    assert adm.window_of("s1") == 2.0
    # healthy replies grow it back additively
    for _ in range(4):
        adm.on_attempt_start("s1")
        adm.on_attempt_done("s1", saturated=False)
    assert adm.window_of("s1") == 4.0
    # the floor holds
    for _ in range(10):
        adm.on_attempt_start("s1")
        adm.on_attempt_done("s1", saturated=True)
    assert adm.window_of("s1") == 1.0


def test_admission_backpressure_snapshot_counts_as_saturation():
    """A healthy (non-210) reply whose backpressure snapshot shows the
    scheduler past the high-water fraction decreases the window — the
    broker backs off BEFORE the server has to shed."""
    adm = AdmissionController(initial_window=8, pending_high_water=0.8)
    adm.on_attempt_start("s1")
    adm.on_attempt_done(
        "s1", saturated=False, backpressure={"pending": 60, "maxPending": 64}
    )
    assert adm.window_of("s1") == 4.0
    # below the high water: additive increase
    adm.on_attempt_start("s1")
    adm.on_attempt_done(
        "s1", saturated=False, backpressure={"pending": 3, "maxPending": 64}
    )
    assert adm.window_of("s1") == 4.5


def test_admission_check_cover_sheds_only_when_all_windows_full():
    adm = AdmissionController(initial_window=1)
    adm.on_attempt_start("s1")  # s1 now at its window
    assert adm.check_cover("t", ["s1", "s2"]).admitted  # s2 has headroom
    adm.on_attempt_start("s2")
    d = adm.check_cover("t", ["s1", "s2"])
    assert not d.admitted and d.tier == "overload"
    adm.on_attempt_cancelled("s1")
    assert adm.check_cover("t", ["s1", "s2"]).admitted


# ----------------------------------------------------- fair-share scheduler
def test_fairshare_flooder_cannot_fill_queue_when_others_wait():
    """Per-table pending caps: alone, a table may use the whole queue;
    once another table holds pending work the flooder's submits shed at
    its weighted share while the other table keeps being admitted."""
    sched = QueryScheduler(num_workers=1, max_pending=8)
    gate = threading.Event()
    futs = []
    # worker occupied by the first entry; A fills the rest of the queue
    futs.append(sched.submit(lambda: gate.wait(5), table="A"))
    for _ in range(7):
        futs.append(sched.submit(lambda: 1, table="A"))
    assert sched.pending == 8
    with pytest.raises(SchedulerSaturatedError):
        sched.submit(lambda: 1, table="A")  # global cap
    # B was idle so far: A's flood cannot lock B out — B's first submit
    # is admitted ONLY after A's backlog drains below the global cap,
    # so release the gate and let capacity free up
    gate.set()
    for f in futs:
        f.result(timeout=5)
    fb = sched.submit(lambda: "b", table="B")
    assert fb.result(timeout=5) == "b"
    sched.shutdown()


def test_fairshare_share_cap_with_other_table_waiting():
    """With B pending, A is capped at its share (max_pending/2 for two
    equal-weight tables) instead of the whole queue."""
    sched = QueryScheduler(num_workers=1, max_pending=8)
    gate = threading.Event()
    running = sched.submit(lambda: gate.wait(5), table="B")  # occupies worker
    # B holds pending work; A's fair share is 8/2 = 4
    admitted = 0
    shed_at = None
    for i in range(8):
        try:
            sched.submit(lambda: 1, table="A")
            admitted += 1
        except SchedulerSaturatedError as e:
            shed_at = i
            assert "fair-share" in str(e) and "table A" in str(e)
            break
    assert admitted == 4 and shed_at == 4
    # B itself is still admitted (it is under ITS share)
    fb = sched.submit(lambda: "b", table="B")
    gate.set()
    running.result(timeout=5)
    assert fb.result(timeout=5) == "b"
    sched.shutdown()


def test_fairshare_drr_interleaves_starved_table():
    """DRR dequeue: a table with ONE query behind a 6-deep flood queue
    is served on the next DRR cycle, not after the whole flood."""
    sched = QueryScheduler(num_workers=1, max_pending=32)
    order = []
    gate = threading.Event()

    def job(tag):
        def run():
            gate.wait(5)
            order.append(tag)

        return run

    blocker = sched.submit(job("warm"), table="A")
    time.sleep(0.05)  # let the worker claim the blocker
    futs = [sched.submit(job(f"A{i}"), table="A") for i in range(6)]
    fb = sched.submit(job("B0"), table="B")
    gate.set()
    fb.result(timeout=5)
    for f in futs:
        f.result(timeout=5)
    blocker.result(timeout=5)
    # B0 ran among the FIRST queued entries (DRR alternates A/B), never
    # last; FCFS would have run it after all six A entries
    assert order.index("B0") <= 2, order
    sched.shutdown()


def test_fairshare_weights_skew_share():
    sched = QueryScheduler(num_workers=1, max_pending=9)
    sched.set_weight("A", 2.0)
    gate = threading.Event()
    running = sched.submit(lambda: gate.wait(5), table="B")
    # active tables: A (w=2), B (w=1) -> A's share = 9 * 2/3 = 6
    admitted = 0
    for _ in range(9):
        try:
            sched.submit(lambda: 1, table="A")
            admitted += 1
        except SchedulerSaturatedError:
            break
    assert admitted == 6
    gate.set()
    running.result(timeout=5)
    sched.shutdown()


# --------------------------------------------------- ingest governor units
def test_ingest_governor_hysteresis_latch():
    reading = {"hbm": 0.0}
    gov = IngestBackpressure(
        hbm_high_bytes=100.0,
        hbm_low_bytes=50.0,
        hbm_bytes_fn=lambda: reading["hbm"],
        poll_interval_s=0.0,
    )
    assert gov.consume_allowed()
    reading["hbm"] = 150.0
    assert not gov.consume_allowed() and gov.paused
    assert "high watermark" in gov.reason
    # between low and high: STAYS paused (no flapping at the boundary)
    reading["hbm"] = 80.0
    assert not gov.consume_allowed()
    reading["hbm"] = 40.0
    assert gov.consume_allowed() and not gov.paused
    snap = gov.snapshot()
    assert snap["pauses"] == 1 and snap["resumes"] == 1
    assert [e["event"] for e in snap["events"]] == ["pause", "resume"]


def test_ingest_governor_mutable_watermark_and_batch_clamp():
    reading = {"mut": 0.0}
    gov = IngestBackpressure(
        mutable_high_bytes=1000.0,
        mutable_low_bytes=500.0,
        hbm_bytes_fn=lambda: 0.0,
        mutable_bytes_fn=lambda: reading["mut"],
        poll_interval_s=0.0,
        max_batch_rows=64,
    )
    assert gov.clamp_batch(10_000) == 64
    reading["mut"] = 2000.0
    assert not gov.consume_allowed()
    reading["mut"] = 100.0
    assert gov.consume_allowed()


def test_ingest_governor_disabled_and_fail_open():
    # no watermarks configured -> never pauses, never polls
    gov = IngestBackpressure(hbm_high_bytes=0.0, mutable_high_bytes=0.0)
    assert not gov.enabled and gov.consume_allowed()

    # a broken probe fails OPEN: ingest must not wedge on a bad gauge
    def boom():
        raise RuntimeError("probe broken")

    gov2 = IngestBackpressure(
        hbm_high_bytes=10.0, hbm_bytes_fn=boom, poll_interval_s=0.0
    )
    assert gov2.consume_allowed()


# -------------------------------------------------------- wire compatibility
def test_backpressure_rides_result_wire_and_old_payloads_still_read():
    from pinot_tpu.common.datatable import deserialize_result, serialize_result
    from pinot_tpu.engine.results import IntermediateResult

    res = IntermediateResult(num_docs_scanned=7)
    res.cost = {"bytesScanned": 42}
    res.backpressure = {"pending": 3, "maxPending": 64, "laneDepth": 1}
    data = serialize_result(res)
    out = deserialize_result(data)
    assert out.backpressure == {"pending": 3, "maxPending": 64, "laneDepth": 1}
    assert out.cost == {"bytesScanned": 42}

    # an old-format payload (no backpressure trailer) still deserializes
    res2 = IntermediateResult(num_docs_scanned=1)
    data2 = serialize_result(res2)
    out2 = deserialize_result(data2)
    assert out2.backpressure == {}


# --------------------------------------------------- end-to-end shed typing
def test_broker_concurrency_cap_sheds_typed_429():
    """A tenant flooding with SLOW queries is capped by in-flight
    concurrency (not QPS): overflow comes back as a typed 429."""
    from pinot_tpu.common.response import ErrorCode
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.tools.cluster_harness import single_server_broker
    from pinot_tpu.tools.datagen import make_test_schema, random_rows

    schema = make_test_schema(with_mv=False)
    seg = build_segment(schema, random_rows(schema, 30, seed=2), "tt", "s0")
    broker = single_server_broker("tt", [seg])
    broker.admission.max_inflight_per_table = 2
    server = broker.local_servers[0]
    gate = threading.Event()
    real_execute = server.executor.execute

    def slow_execute(segs, req, **kwargs):
        gate.wait(5)
        return real_execute(segs, req, **kwargs)

    server.executor.execute = slow_execute
    results = {}

    def q(i):
        results[i] = broker.handle_pql("SELECT count(*) FROM tt")

    threads = [threading.Thread(target=q, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for _ in range(100):
        if broker.admission.table_inflight("tt") >= 2:
            break
        time.sleep(0.01)
    shed = broker.handle_pql("SELECT count(*) FROM tt")
    assert shed.exceptions
    assert shed.exceptions[0].error_code == ErrorCode.TOO_MANY_REQUESTS
    assert "in flight" in shed.exceptions[0].message
    gate.set()
    for t in threads:
        t.join(timeout=10)
    for r in results.values():
        assert not r.exceptions
    assert broker.admission.table_inflight("tt") == 0
    server.shutdown()


def test_broker_aimd_shed_recovers_after_server_drains():
    """check_cover opens back up once windows regrow: AIMD shedding is
    adaptive, not a latched circuit."""
    adm = AdmissionController(initial_window=2, min_window=1)
    for _ in range(3):
        adm.on_attempt_start("s1")
        adm.on_attempt_done("s1", saturated=True)
    assert adm.window_of("s1") == 1.0
    adm.on_attempt_start("s1")
    assert not adm.check_cover("t", ["s1"]).admitted
    # the inflight attempt completes healthy -> window grows, cover opens
    adm.on_attempt_done("s1", saturated=False)
    assert adm.check_cover("t", ["s1"]).admitted


# ------------------------------------------------------- chaos acceptance
@pytest.mark.chaos
def test_noisy_neighbor_tenant_isolation(tmp_path):
    """ISSUE 7 acceptance: tenant A flooding at >=10x its quota cannot
    fail a single tenant-B query; B's p99 stays within a bounded
    multiple of its unloaded baseline (floored); every bit of A's
    overflow is shed with typed 429/210 — no client-visible timeouts.

    The timing bar is measured against a baseline captured moments
    earlier in the SAME process, but on a 2-core box under full-suite
    load the two phases can land in windows of very different scheduler
    pressure (the r12 flake: one 3x miss under a transient CPU spike).
    Functional assertions stay strict on the first run; only a
    timing-bar-only miss re-runs the scenario once with a wider,
    CPU-contention-floored bar — a genuine isolation regression fails
    BOTH runs, noise passes the second."""
    from pinot_tpu.tools.cluster_harness import run_noisy_neighbor_scenario

    def check_functional(out):
        assert out["tenantB"]["failedQueries"] == 0, out["tenantB"]
        assert out["offeredMultiple"] >= 10.0, out
        assert out["sheddingTyped"], out["tenantA"]
        assert out["tenantA"]["timeouts"] == 0
        shed = out["tenantA"]["shed429"] + out["tenantA"]["shed210"]
        assert shed > 0  # the flood actually overflowed and was shed
        assert out["failedQueries"] == 0

    out = run_noisy_neighbor_scenario(
        num_servers=2,
        baseline_s=0.7,
        flood_s=1.5,
        data_dir=str(tmp_path / "r1"),
    )
    check_functional(out)
    if not out["tenantBP99Within"]:
        # timing only: one retry with the contention-hardened bar
        out = run_noisy_neighbor_scenario(
            num_servers=2,
            baseline_s=0.7,
            flood_s=1.5,
            data_dir=str(tmp_path / "r2"),
            p99_floor_ms=50.0,
            p99_multiple=4.0,
        )
        check_functional(out)
    assert out["tenantBP99Within"], (
        out["tenantBLoadedP99Ms"],
        out["tenantBP99LimitMs"],
    )


@pytest.mark.chaos
def test_ingest_backpressure_pauses_and_drains(tmp_path):
    """ISSUE 7 acceptance: consumers provably pause when the HBM ledger
    crosses the high watermark (offset frozen, lag visible, zero rows
    consumed while held) and drain lag to 0 after resume."""
    from pinot_tpu.tools.cluster_harness import run_ingest_backpressure_scenario

    out = run_ingest_backpressure_scenario(data_dir=str(tmp_path))
    assert out["paused"], out
    assert out["offsetFrozen"], out
    assert out["consumedWhilePaused"] == 0
    assert out["lagWhilePaused"] > 0
    assert out["resumed"] and out["finalLag"] == 0, out
    assert out["governor"]["pauses"] == 1 and out["governor"]["resumes"] == 1
    assert out["failedQueries"] == 0


@pytest.mark.chaos
def test_join_under_flood_tenant_isolation(tmp_path):
    """ISSUE 14 chaos: tenant A flooding two-table JOINs at >=10x its
    quota — multi-phase scatter traffic per admitted query — cannot
    fail a single tenant-B scan, and B's p99 holds within the bounded
    multiple.  Same contention-hardened retry contract as the
    noisy-neighbor test: functional assertions strict on both runs,
    only a timing-bar-only miss re-runs once with the wider bar."""
    from pinot_tpu.tools.cluster_harness import run_join_under_flood_scenario

    def check_functional(out):
        assert out["tenantB"]["failedQueries"] == 0, out["tenantB"]
        assert out["offeredMultiple"] >= 10.0, out
        assert out["sheddingTyped"], out["tenantA"]
        assert out["tenantA"]["timeouts"] == 0
        shed = out["tenantA"]["shed429"] + out["tenantA"]["shed210"]
        assert shed > 0
        assert out["failedQueries"] == 0
        # joins genuinely executed through the join plane while flooded
        assert out["joinMeters"]["join.queries"] > 0

    out = run_join_under_flood_scenario(
        num_servers=2,
        baseline_s=0.7,
        flood_s=1.5,
        data_dir=str(tmp_path / "r1"),
    )
    check_functional(out)
    if not out["tenantBP99Within"]:
        out = run_join_under_flood_scenario(
            num_servers=2,
            baseline_s=0.7,
            flood_s=1.5,
            data_dir=str(tmp_path / "r2"),
            p99_floor_ms=50.0,
            p99_multiple=4.0,
        )
        check_functional(out)
    assert out["tenantBP99Within"], (
        out["tenantBLoadedP99Ms"],
        out["tenantBP99LimitMs"],
    )
