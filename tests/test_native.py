"""Native codec tests: C++ pack/unpack must agree bit-for-bit with the
numpy fallback (and with itself round-trip)."""
import numpy as np
import pytest

from pinot_tpu.segment import native
from pinot_tpu.segment.bitpack import bits_required


def test_native_builds_and_loads():
    assert native.available(), "native codec should build with the baked-in g++"


@pytest.mark.parametrize("card", [2, 3, 17, 255, 256, 4097, 1_000_000])
def test_native_matches_numpy(card):
    rng = np.random.default_rng(card)
    n = 10_000
    vals = rng.integers(0, card, size=n).astype(np.int32)
    nbits = bits_required(card)

    packed_native = native.pack_bits(vals, nbits)
    assert packed_native is not None

    # numpy reference encoding (force the fallback path with small slices)
    from pinot_tpu.segment.bitpack import pack_bits as pb, unpack_bits as ub

    import pinot_tpu.segment.bitpack as bp

    # fallback encoding computed manually
    values = vals.astype(np.uint64)
    shifts = np.arange(nbits, dtype=np.uint64)
    bits = ((values[:, None] >> shifts[None, :]) & 1).astype(np.uint8).reshape(-1)
    pad = (-bits.size) % 8
    if pad:
        bits = np.concatenate([bits, np.zeros(pad, dtype=np.uint8)])
    packed_numpy = np.packbits(bits.reshape(-1, 8)[:, ::-1], axis=1).reshape(-1)

    np.testing.assert_array_equal(packed_native, packed_numpy)

    out = native.unpack_bits(packed_native, nbits, n)
    np.testing.assert_array_equal(out, vals)

    # public API roundtrip (dispatches to native for n >= 4096)
    np.testing.assert_array_equal(ub(pb(vals, nbits), nbits, n), vals)


# ---------------------------------------------------------------------------
# Native CSV -> columnar build path
# ---------------------------------------------------------------------------

from pinot_tpu.segment.builder import build_segment
from pinot_tpu.segment.columnar import build_segment_from_csv, read_csv_columnar
from pinot_tpu.segment.readers import MV_DELIMITER, read_csv
from pinot_tpu.tools.datagen import make_test_schema, random_rows


def _write_csv(path, schema, rows):
    names = [s.name for s in schema.all_fields()]
    with open(path, "w") as f:
        f.write(",".join(names) + "\n")
        for row in rows:
            cells = []
            for n in names:
                v = row[n]
                if isinstance(v, list):
                    cells.append(MV_DELIMITER.join(str(x) for x in v))
                else:
                    cells.append(str(v))
            f.write(",".join(cells) + "\n")


def _assert_segments_equal(a, b):
    assert a.num_docs == b.num_docs
    assert set(a.columns) == set(b.columns)
    for name, ca in a.columns.items():
        cb = b.columns[name]
        ma, mb = ca.metadata, cb.metadata
        for attr in (
            "cardinality",
            "is_sorted",
            "max_num_multi_values",
            "total_number_of_entries",
            "min_value",
            "max_value",
        ):
            assert getattr(ma, attr) == getattr(mb, attr), (name, attr)
        if ca.dictionary.is_string:
            assert list(ca.dictionary.values) == list(cb.dictionary.values)
        else:
            np.testing.assert_array_equal(ca.dictionary.values, cb.dictionary.values)
        if ca.fwd is not None:
            np.testing.assert_array_equal(ca.fwd, cb.fwd)
        else:
            np.testing.assert_array_equal(ca.mv_values, cb.mv_values)
            np.testing.assert_array_equal(ca.mv_offsets, cb.mv_offsets)
    assert a.compute_crc() == b.compute_crc()


def test_columnar_csv_build_matches_row_build(tmp_path):
    """The native columnar CSV path must produce a segment identical to
    the row-wise Python path (same dictionaries, fwd indexes, metadata,
    CRC)."""
    schema = make_test_schema()  # includes MV columns
    rows = random_rows(schema, 500, seed=13)
    path = str(tmp_path / "data.csv")
    _write_csv(path, schema, rows)

    cols, n = read_csv_columnar(path, schema)
    assert cols is not None, "native fast path should engage on plain CSV"
    assert n == 500

    seg_columnar = build_segment_from_csv(schema, path, "t", "seg_c")
    seg_rows = build_segment(schema, read_csv(path, schema), "t", "seg_c")
    _assert_segments_equal(seg_columnar, seg_rows)


def test_columnar_csv_missing_cells_and_blank_lines(tmp_path):
    schema = make_test_schema(with_mv=False)
    path = str(tmp_path / "gaps.csv")
    names = [s.name for s in schema.all_fields()]
    with open(path, "w") as f:
        f.write(",".join(names) + "\n")
        f.write("alpha,1,2,3.5,4.5,100\n")
        f.write("\n")  # blank line skipped
        f.write("beta,7\n")  # missing trailing cells -> defaults
        f.write("gamma,,,,,200\n")  # empty numeric cells -> defaults

    seg_columnar = build_segment_from_csv(schema, path, "t", "g1")
    seg_rows = build_segment(schema, read_csv(path, schema), "t", "g1")
    _assert_segments_equal(seg_columnar, seg_rows)


def test_columnar_csv_quoted_falls_back(tmp_path):
    """Quoted CSV routes to the Python csv module and still builds."""
    schema = make_test_schema(with_mv=False)
    path = str(tmp_path / "quoted.csv")
    names = [s.name for s in schema.all_fields()]
    with open(path, "w") as f:
        f.write(",".join(names) + "\n")
        f.write('"hello, world",1,2,3.5,4.5,100\n')

    cols, _ = read_csv_columnar(path, schema)
    assert cols is None
    seg = build_segment_from_csv(schema, path, "t", "q1")
    assert seg.num_docs == 1
    assert seg.columns["dimStr"].dictionary.values[0] == "hello, world"


def test_columnar_csv_nan_cells_match_row_path(tmp_path):
    """'nan' in a float column maps to the default null value on both
    paths (the row builder's isnan -> default rule)."""
    schema = make_test_schema(with_mv=False)
    path = str(tmp_path / "nan.csv")
    names = [s.name for s in schema.all_fields()]
    with open(path, "w") as f:
        f.write(",".join(names) + "\n")
        f.write("a,1,2,3,nan,nan,100\n")
        f.write("b,3,4,5,1.5,2.5,200\n")

    seg_columnar = build_segment_from_csv(schema, path, "t", "n1")
    seg_rows = build_segment(schema, read_csv(path, schema), "t", "n1")
    _assert_segments_equal(seg_columnar, seg_rows)


def test_columnar_csv_int_overflow_is_loud(tmp_path):
    """Out-of-range INT cells raise on the columnar path just like the
    row-wise np.asarray(int32) does — no silent wraparound."""
    schema = make_test_schema(with_mv=False)
    path = str(tmp_path / "ovf.csv")
    names = [s.name for s in schema.all_fields()]
    with open(path, "w") as f:
        f.write(",".join(names) + "\n")
        f.write("a,3000000000,2,1.0,1.0,100\n")

    with pytest.raises(OverflowError):
        build_segment_from_csv(schema, path, "t", "o1")


def test_columnar_csv_extra_columns_skipped(tmp_path):
    """Header columns not in the schema are tokenized but discarded
    (skip type), matching DictReader's ignore-extra-keys behavior."""
    schema = make_test_schema(with_mv=False)
    path = str(tmp_path / "extra.csv")
    names = [s.name for s in schema.all_fields()]
    with open(path, "w") as f:
        f.write("junk1," + ",".join(names) + ",junk2\n")
        f.write("x,a,1,2,3,1.5,2.5,100,y\n")
        f.write("x,b,4,5,6,3.5,4.5,200,y\n")

    cols, n = read_csv_columnar(path, schema)
    assert cols is not None and n == 2
    seg_columnar = build_segment_from_csv(schema, path, "t", "e1")
    seg_rows = build_segment(schema, read_csv(path, schema), "t", "e1")
    _assert_segments_equal(seg_columnar, seg_rows)


def test_columnar_csv_lone_cr_falls_back(tmp_path):
    """A bare \\r (row separator for python csv, cell data for the
    native parser) routes to the python path so both agree."""
    schema = make_test_schema(with_mv=False)
    path = str(tmp_path / "cr.csv")
    names = [s.name for s in schema.all_fields()]
    with open(path, "wb") as f:
        f.write((",".join(names) + "\n").encode())
        f.write(b"a\rb,1,2,3,1.0,1.0,100\n")

    cols, _ = read_csv_columnar(path, schema)
    assert cols is None
    seg = build_segment_from_csv(schema, path, "t", "cr1")
    seg_rows = build_segment(schema, read_csv(path, schema), "t", "cr1")
    _assert_segments_equal(seg, seg_rows)
