"""Native codec tests: C++ pack/unpack must agree bit-for-bit with the
numpy fallback (and with itself round-trip)."""
import numpy as np
import pytest

from pinot_tpu.segment import native
from pinot_tpu.segment.bitpack import bits_required


def test_native_builds_and_loads():
    assert native.available(), "native codec should build with the baked-in g++"


@pytest.mark.parametrize("card", [2, 3, 17, 255, 256, 4097, 1_000_000])
def test_native_matches_numpy(card):
    rng = np.random.default_rng(card)
    n = 10_000
    vals = rng.integers(0, card, size=n).astype(np.int32)
    nbits = bits_required(card)

    packed_native = native.pack_bits(vals, nbits)
    assert packed_native is not None

    # numpy reference encoding (force the fallback path with small slices)
    from pinot_tpu.segment.bitpack import pack_bits as pb, unpack_bits as ub

    import pinot_tpu.segment.bitpack as bp

    # fallback encoding computed manually
    values = vals.astype(np.uint64)
    shifts = np.arange(nbits, dtype=np.uint64)
    bits = ((values[:, None] >> shifts[None, :]) & 1).astype(np.uint8).reshape(-1)
    pad = (-bits.size) % 8
    if pad:
        bits = np.concatenate([bits, np.zeros(pad, dtype=np.uint8)])
    packed_numpy = np.packbits(bits.reshape(-1, 8)[:, ::-1], axis=1).reshape(-1)

    np.testing.assert_array_equal(packed_native, packed_numpy)

    out = native.unpack_bits(packed_native, nbits, n)
    np.testing.assert_array_equal(out, vals)

    # public API roundtrip (dispatches to native for n >= 4096)
    np.testing.assert_array_equal(ub(pb(vals, nbits), nbits, n), vals)
