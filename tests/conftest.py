"""Test config: force an 8-device virtual CPU mesh and 64-bit mode.

Multi-chip sharding is validated on a virtual CPU mesh
(``xla_force_host_platform_device_count=8``) since only one real TPU
chip is reachable; x64 is enabled so CPU test runs reproduce the
reference's double-precision aggregation semantics exactly.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_enable_x64", True)
