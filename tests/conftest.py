"""Test config: force an 8-device virtual CPU mesh and 64-bit mode.

Multi-chip sharding is validated on a virtual CPU mesh
(``xla_force_host_platform_device_count=8``) since only one real TPU
chip is reachable; x64 is enabled so CPU test runs reproduce the
reference's double-precision aggregation semantics exactly.

The container's sitecustomize force-registers the experimental 'axon'
TPU backend (tunnel to the real chip) before conftest runs; its PJRT
client init can block, so ``force_cpu_mesh`` updates the jax config
(not just the env) before first backend init — tests are CPU-only by
design.
"""
import os

if os.environ.get("PINOT_TPU_TESTS") == "tpu":
    # on-device gate (pytest -m tpu): keep the real TPU backend and its
    # native float32 semantics — tolerance assertions live in the tests
    import jax  # noqa: F401
else:
    from pinot_tpu.utils.platform import force_cpu_mesh

    if not force_cpu_mesh(8):  # not an assert: must survive PYTHONOPTIMIZE
        raise RuntimeError(
            "jax backends initialized before conftest; tests must come up on a "
            "virtual 8-device CPU mesh, not the axon TPU tunnel"
        )

    import jax

    jax.config.update("jax_enable_x64", True)


# ---------------------------------------------------------------------------
# Thread-leak guard for the device-lane supervision path: a watchdog
# restart abandons the wedged lane thread, and a bug there would leak
# one thread per wedge.  After every test, any lane that was CLOSED must
# have no surviving lane/watchdog threads (lanes left open by
# module-scoped fixtures are exempt — they are still serving).
# ---------------------------------------------------------------------------
import pytest


@pytest.fixture(autouse=True)
def _no_leaked_lane_threads():
    yield
    from pinot_tpu.engine.dispatch import leaked_lane_threads

    leaked = leaked_lane_threads(grace_s=2.0)
    assert not leaked, (
        f"device-lane threads leaked past lane close: "
        f"{[t.name for t in leaked]}"
    )


@pytest.fixture(autouse=True)
def _no_leaked_scheduler_threads():
    """Fair-share scheduler workers (server/scheduler.py): a shut-down
    scheduler's workers must drain their queues and exit — this guard
    catches any worker that survived shutdown().  Workers of schedulers
    still serving (module fixtures) are exempt."""
    yield
    from pinot_tpu.server.scheduler import leaked_scheduler_threads

    # grace covers a worker still draining a query whose client already
    # timed out (e.g. the 2s sleep in test_scheduler_run_timeout)
    leaked = leaked_scheduler_threads(grace_s=4.0)
    assert not leaked, (
        f"scheduler worker threads leaked past shutdown(): "
        f"{[t.name for t in leaked]}"
    )


@pytest.fixture(autouse=True)
def _no_leaked_recorder_threads():
    """History recorders (utils/timeseries.py): one daemon thread per
    role snapshots metrics on a cadence; ``stop()`` must actually end
    it.  Recorders still running (module fixtures, live roles) are
    exempt — a STOPPED recorder whose thread survives is the leak."""
    yield
    from pinot_tpu.utils.timeseries import leaked_recorder_threads

    leaked = leaked_recorder_threads(grace_s=2.0)
    assert not leaked, (
        f"history-recorder threads leaked past stop(): "
        f"{[t.name for t in leaked]}"
    )


@pytest.fixture(autouse=True)
def _no_leaked_ingest_pool_threads():
    """Ingest consumer pools (realtime/pool.py): bounded workers
    multiplexing realtime consumers; ``stop()`` must end every worker.
    Pools still running (live servers) are exempt — a STOPPED pool
    whose workers survive is the leak."""
    yield
    from pinot_tpu.realtime.pool import leaked_pool_threads

    leaked = leaked_pool_threads(grace_s=2.0)
    assert not leaked, (
        f"ingest-pool worker threads leaked past stop(): "
        f"{[t.name for t in leaked]}"
    )


@pytest.fixture(autouse=True)
def _no_leaked_prewarm_threads():
    """Prewarm workers (server/prewarm.py): the background compile
    driver is one daemon thread per server, started lazily on the
    first prewarm request; ``stop()`` (via ``ServerInstance.shutdown``)
    must actually end it.  Workers still serving (live servers held by
    fixtures) are exempt — a STOPPED worker whose thread survives is
    the leak."""
    yield
    from pinot_tpu.server.prewarm import leaked_prewarm_threads

    leaked = leaked_prewarm_threads(grace_s=2.0)
    assert not leaked, (
        f"prewarm worker threads leaked past stop(): {leaked}"
    )


@pytest.fixture(autouse=True)
def _no_leaked_manager_threads():
    """Controller periodic managers (retention/validation/status/
    stabilizer): a stopped manager's worker must actually exit —
    ``_PeriodicManager.stop()`` joins it with a bounded timeout, and
    this guard catches any manager loop that shrugged off the stop
    event.  Still-running managers (module fixtures) are exempt."""
    yield
    from pinot_tpu.controller.managers import leaked_manager_threads

    leaked = leaked_manager_threads(grace_s=2.0)
    assert not leaked, (
        f"controller-manager threads leaked past stop(): "
        f"{[t.name for t in leaked]}"
    )


@pytest.fixture(autouse=True)
def _no_leaked_audit_threads():
    """Audit samplers (utils/audit.py): shadow/replica auditor workers
    are lazy daemon threads started on the first enqueued sample;
    ``stop()`` (via ServerInstance.shutdown / Broker.shutdown) must
    actually end them.  Still-enabled auditors on live fixtures are
    exempt — a STOPPED auditor whose worker survives is the leak."""
    yield
    from pinot_tpu.utils.audit import leaked_audit_threads

    leaked = leaked_audit_threads(grace_s=2.0)
    assert not leaked, (
        f"audit worker threads leaked past stop(): {leaked}"
    )
