"""Test config: force an 8-device virtual CPU mesh and 64-bit mode.

Multi-chip sharding is validated on a virtual CPU mesh
(``xla_force_host_platform_device_count=8``) since only one real TPU
chip is reachable; x64 is enabled so CPU test runs reproduce the
reference's double-precision aggregation semantics exactly.

The container's sitecustomize force-registers the experimental 'axon'
TPU backend (tunnel to the real chip) before conftest runs; its PJRT
client init can block, so the factory is dropped here — tests are
CPU-only by design.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# The sitecustomize force-sets JAX_PLATFORMS=axon before conftest runs;
# updating the config (not just the env) keeps backend init CPU-only so
# the axon PJRT client (TPU tunnel) is never dialed. The axon factory
# stays *registered* — pallas and mlir need the platform names known.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
