"""Single-transfer output fetch (engine/packing.py): bit-exact pytree
round trip through the packed uint8 buffer for every dtype the kernels
emit, and layout-cache correctness across shape changes."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pinot_tpu.engine.packing import make_packed_kernel


def test_packed_round_trip_mixed_tree():
    def fn(a, b):
        return {
            "f32": a * 2.0,
            "pair": (a.sum(), b + 1),
            "i8": b.astype(jnp.int8),
            "u16": b.astype(jnp.uint16),
            "bool": a > 0.5,
            "scalar": jnp.float32(3.25),
            "empty": jnp.zeros((0, 4), jnp.float32),
        }

    a = np.linspace(0, 1, 37, dtype=np.float32)
    b = np.arange(37, dtype=np.int32)
    packed = make_packed_kernel(fn)
    got = packed(jnp.asarray(a), jnp.asarray(b))
    want = jax.tree_util.tree_map(np.asarray, fn(jnp.asarray(a), jnp.asarray(b)))

    assert set(got) == set(want)
    for k in want:
        g, w = got[k], want[k]
        if isinstance(w, tuple):
            for gg, ww in zip(g, w):
                np.testing.assert_array_equal(np.asarray(gg), np.asarray(ww))
        else:
            assert np.asarray(g).dtype == np.asarray(w).dtype, k
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def test_packed_layout_cache_shape_change():
    def fn(x):
        return {"sum": x.sum(axis=0), "sq": x * x}

    packed = make_packed_kernel(fn)
    for n in (8, 16, 8):  # revisit the first shape: cache hit must hold
        x = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
        got = packed(jnp.asarray(x))
        np.testing.assert_allclose(got["sum"], x.sum(axis=0), rtol=1e-6)
        np.testing.assert_allclose(got["sq"], x * x, rtol=1e-6)
        assert isinstance(got["sum"], np.ndarray)


def test_packed_f64_under_x64():
    if not jax.config.read("jax_enable_x64"):
        pytest.skip("x64 disabled")

    def fn(x):
        return {"d": x.astype(jnp.float64) / 3.0}

    x = np.arange(11, dtype=np.float64)
    got = make_packed_kernel(fn)(jnp.asarray(x))
    assert got["d"].dtype == np.float64
    np.testing.assert_allclose(got["d"], x / 3.0)


def test_npgroup_matches_ufunc_at():
    """utils/npgroup sorted-reduceat primitives are drop-in equivalents
    of np.maximum.at (property check over random shapes)."""
    import numpy as np

    from pinot_tpu.utils.npgroup import group_max_rows, scatter_max_2d

    rng = np.random.default_rng(7)
    for _ in range(5):
        R, G, M = int(rng.integers(1, 400)), int(rng.integers(1, 12)), 16
        inverse = rng.integers(0, G, R)
        vals2d = rng.integers(0, 60, (R, M)).astype(np.uint8)
        want = np.zeros((G, M), np.uint8)
        np.maximum.at(want, inverse, vals2d)
        # group_max_rows only defined for groups with >=1 row: compare
        # on non-empty groups
        got = group_max_rows(inverse, G, vals2d)
        present = np.unique(inverse)
        np.testing.assert_array_equal(got[present], want[present])

        cols = rng.integers(0, M, R)
        vals = rng.integers(0, 60, R).astype(np.uint8)
        want2 = np.zeros((G, M), np.uint8)
        np.maximum.at(want2, (inverse, cols), vals)
        np.testing.assert_array_equal(scatter_max_2d(inverse, G, cols, vals, M), want2)
    # empty input
    np.testing.assert_array_equal(
        scatter_max_2d(np.zeros(0, np.int64), 3, np.zeros(0, np.int64), np.zeros(0, np.uint8), 4),
        np.zeros((3, 4), np.uint8),
    )
