"""Multiprocess batch segment build + push (pinot-hadoop analog,
``SegmentCreationJob.java`` / ``SegmentTarPushJob.java``)."""
import csv
import json
import urllib.request

import pytest

from pinot_tpu.common.schema import Schema
from pinot_tpu.controller.controller import ControllerHttpServer
from pinot_tpu.tools.batch_build import BatchBuildSpec, run_batch_build
from pinot_tpu.tools.datagen import make_test_schema, random_rows


def _write_inputs(tmp_path, schema: Schema, shards: int, rows_per: int):
    paths = []
    cols = [f.name for f in schema.all_fields()]
    for i in range(shards):
        rows = random_rows(schema, rows_per, seed=100 + i)
        p = tmp_path / f"shard{i}.csv"
        with open(p, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(cols)
            for r in rows:
                w.writerow([r[c] for c in cols])
        paths.append(str(p))
    return paths


@pytest.fixture()
def schema_file(tmp_path):
    schema = make_test_schema(with_mv=False)
    p = tmp_path / "schema.json"
    p.write_text(json.dumps(schema.to_json()))
    return schema, str(p)


def test_batch_build_multiprocess(tmp_path, schema_file):
    schema, schema_path = schema_file
    inputs = _write_inputs(tmp_path, schema, shards=3, rows_per=40)
    spec = BatchBuildSpec(
        schema_file=schema_path,
        table="bb",
        input_files=inputs,
        out_dir=str(tmp_path / "out"),
    )
    results = run_batch_build(spec, workers=3)
    assert [r["segment"] for r in results] == ["bb_0", "bb_1", "bb_2"]
    assert all(r["docs"] == 40 and not r["pushed"] for r in results)

    from pinot_tpu.segment.format import read_segment

    for r in results:
        seg = read_segment(r["path"])
        assert seg.num_docs == 40


def test_batch_build_and_push_to_controller(tmp_path, schema_file):
    from pinot_tpu.tools.cluster_harness import InProcessCluster

    schema, schema_path = schema_file
    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path / "ctrl"))
    physical = cluster.add_offline_table(schema)
    http = ControllerHttpServer(cluster.controller)
    http.start()
    try:
        inputs = _write_inputs(tmp_path, schema, shards=2, rows_per=30)
        spec = BatchBuildSpec(
            schema_file=schema_path,
            table=physical,
            input_files=inputs,
            out_dir=str(tmp_path / "out"),
            controller=f"http://127.0.0.1:{http.port}",
        )
        # workers=1 keeps the push in-process (the pool path is covered
        # above; pushes go through the same HTTP client either way)
        results = run_batch_build(spec, workers=1)
        assert all(r["pushed"] for r in results)
        assert cluster.query("SELECT count(*) FROM testTable").num_docs_scanned == 60
    finally:
        http.stop()
        cluster.stop()
