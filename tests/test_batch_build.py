"""Multiprocess batch segment build + push (pinot-hadoop analog,
``SegmentCreationJob.java`` / ``SegmentTarPushJob.java``)."""
import csv
import json
import urllib.request

import pytest

from pinot_tpu.common.schema import Schema
from pinot_tpu.controller.controller import ControllerHttpServer
from pinot_tpu.tools.batch_build import BatchBuildSpec, run_batch_build
from pinot_tpu.tools.datagen import make_test_schema, random_rows


def _write_inputs(tmp_path, schema: Schema, shards: int, rows_per: int):
    paths = []
    cols = [f.name for f in schema.all_fields()]
    for i in range(shards):
        rows = random_rows(schema, rows_per, seed=100 + i)
        p = tmp_path / f"shard{i}.csv"
        with open(p, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(cols)
            for r in rows:
                w.writerow([r[c] for c in cols])
        paths.append(str(p))
    return paths


@pytest.fixture()
def schema_file(tmp_path):
    schema = make_test_schema(with_mv=False)
    p = tmp_path / "schema.json"
    p.write_text(json.dumps(schema.to_json()))
    return schema, str(p)


def test_batch_build_multiprocess(tmp_path, schema_file):
    schema, schema_path = schema_file
    inputs = _write_inputs(tmp_path, schema, shards=3, rows_per=40)
    spec = BatchBuildSpec(
        schema_file=schema_path,
        table="bb",
        input_files=inputs,
        out_dir=str(tmp_path / "out"),
    )
    results = run_batch_build(spec, workers=3)
    assert [r["segment"] for r in results] == ["bb_0", "bb_1", "bb_2"]
    assert all(r["docs"] == 40 and not r["pushed"] for r in results)

    from pinot_tpu.segment.format import read_segment

    for r in results:
        seg = read_segment(r["path"])
        assert seg.num_docs == 40


def test_batch_build_and_push_to_controller(tmp_path, schema_file):
    from pinot_tpu.tools.cluster_harness import InProcessCluster

    schema, schema_path = schema_file
    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path / "ctrl"))
    physical = cluster.add_offline_table(schema)
    http = ControllerHttpServer(cluster.controller)
    http.start()
    try:
        inputs = _write_inputs(tmp_path, schema, shards=2, rows_per=30)
        spec = BatchBuildSpec(
            schema_file=schema_path,
            table=physical,
            input_files=inputs,
            out_dir=str(tmp_path / "out"),
            controller=f"http://127.0.0.1:{http.port}",
        )
        # workers=1 keeps the push in-process (the pool path is covered
        # above; pushes go through the same HTTP client either way)
        results = run_batch_build(spec, workers=1)
        assert all(r["pushed"] for r in results)
        assert cluster.query("SELECT count(*) FROM testTable").num_docs_scanned == 60
    finally:
        http.stop()
        cluster.stop()


# -- cross-machine fan-out (VERDICT r3 #2: SegmentCreationJob parity) ---


def _spawn_worker(tmp_path, name):
    """A build worker as a real OS process; returns (proc, port)."""
    import subprocess
    import sys
    import time

    import os

    script = tmp_path / f"{name}.py"
    port_file = tmp_path / f"{name}.port"
    script.write_text(
        "import sys, time\n"
        "from pinot_tpu.tools.batch_build import serve_build_worker\n"
        "srv = serve_build_worker(host='127.0.0.1', port=0)\n"
        f"open({str(port_file)!r}, 'w').write(str(srv.port))\n"
        "time.sleep(600)\n"
    )
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, str(script)],
        env={
            **os.environ,
            "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", ""),
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
        },
    )
    for _ in range(100):
        if port_file.exists() and port_file.read_text().strip():
            return proc, int(port_file.read_text())
        time.sleep(0.1)
    proc.kill()
    raise RuntimeError(f"worker {name} did not start")


def test_distributed_build_two_process_workers_and_push(tmp_path, schema_file):
    """N shards across 2 real OS-process workers, pushed to a live
    controller, queryable after — plus per-shard retry when one worker
    dies mid-run."""
    from pinot_tpu.tools.batch_build import run_distributed_build
    from pinot_tpu.tools.cluster_harness import InProcessCluster

    schema, schema_path = schema_file
    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path / "ctrl"))
    physical = cluster.add_offline_table(schema)
    http = ControllerHttpServer(cluster.controller)
    http.start()
    w1 = w2 = None
    try:
        w1, p1 = _spawn_worker(tmp_path, "w1")
        w2, p2 = _spawn_worker(tmp_path, "w2")
        inputs = _write_inputs(tmp_path, schema, shards=4, rows_per=25)
        spec = BatchBuildSpec(
            schema_file=schema_path,
            table=physical,
            input_files=inputs,
            out_dir=str(tmp_path / "out"),
            controller=f"http://127.0.0.1:{http.port}",
        )
        results = run_distributed_build(
            spec, [("127.0.0.1", p1), ("127.0.0.1", p2)], timeout_s=120.0
        )
        assert [r["segment"] for r in results] == [f"{physical}_{i}" for i in range(4)]
        assert all(r["pushed"] for r in results)
        assert cluster.query("SELECT count(*) FROM testTable").num_docs_scanned == 100

        # kill one worker: every shard still completes via retry on the
        # survivor (Hadoop mapper re-execution analog)
        w1.terminate()
        w1.wait(timeout=30)
        spec2 = BatchBuildSpec(
            schema_file=schema_path,
            table=physical,
            input_files=inputs[:2],
            out_dir=str(tmp_path / "out2"),
            segment_name_prefix="bb2",
        )
        results2 = run_distributed_build(
            spec2, [("127.0.0.1", p1), ("127.0.0.1", p2)], timeout_s=120.0
        )
        assert [r["segment"] for r in results2] == ["bb2_0", "bb2_1"]
    finally:
        for w in (w1, w2):
            if w is not None:
                w.terminate()
        http.stop()
        cluster.stop()
