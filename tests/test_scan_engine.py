"""Sentinel tests for the scan oracle — exact golden values on a tiny
hand-written dataset (the QueriesSentinelTest analog at oracle level)."""
import math

from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.pql import parse_pql
from pinot_tpu.tools.scan_engine import ScanQueryProcessor

SCHEMA = Schema(
    "t",
    dimensions=[
        FieldSpec("city", DataType.STRING),
        FieldSpec("tags", DataType.STRING_ARRAY, single_value=False),
    ],
    metrics=[
        FieldSpec("sales", DataType.INT, FieldType.METRIC),
        FieldSpec("price", DataType.DOUBLE, FieldType.METRIC),
    ],
)

ROWS = [
    {"city": "sf", "tags": ["a", "b"], "sales": 10, "price": 1.5},
    {"city": "sf", "tags": ["b"], "sales": 20, "price": 2.5},
    {"city": "ny", "tags": ["a"], "sales": 30, "price": 3.5},
    {"city": "la", "tags": ["c", "a"], "sales": 40, "price": 4.5},
    {"city": "ny", "tags": ["b", "c"], "sales": 50, "price": 5.5},
]

ENGINE = ScanQueryProcessor(SCHEMA, ROWS)


def run(pql):
    return ENGINE.execute(parse_pql(pql))


def agg_values(resp):
    return [a.value for a in resp.aggregation_results]


def test_count_star():
    assert agg_values(run("SELECT count(*) FROM t")) == [5]


def test_sum_min_max_avg():
    resp = run("SELECT sum(sales), min(sales), max(sales), avg(sales), minmaxrange(sales) FROM t")
    assert agg_values(resp) == [150.0, 10.0, 50.0, 30.0, 40.0]


def test_filter_equality():
    resp = run("SELECT count(*), sum(sales) FROM t WHERE city = 'sf'")
    assert agg_values(resp) == [2, 30.0]
    assert resp.num_docs_scanned == 2
    assert resp.total_docs == 5


def test_filter_in_and_range():
    assert agg_values(run("SELECT count(*) FROM t WHERE city IN ('sf','ny')")) == [4]
    assert agg_values(run("SELECT count(*) FROM t WHERE sales > 20")) == [3]
    assert agg_values(run("SELECT count(*) FROM t WHERE sales BETWEEN 20 AND 40")) == [3]
    assert agg_values(run("SELECT count(*) FROM t WHERE sales >= 20 AND sales < 50")) == [3]


def test_filter_not_and_or():
    assert agg_values(run("SELECT count(*) FROM t WHERE city <> 'sf'")) == [3]
    assert agg_values(run("SELECT count(*) FROM t WHERE city = 'sf' OR sales = 40")) == [3]
    assert agg_values(run("SELECT count(*) FROM t WHERE city NOT IN ('sf','la')")) == [2]


def test_mv_predicate_any_semantics():
    # tags contains 'a' in rows 0, 2, 3
    assert agg_values(run("SELECT count(*) FROM t WHERE tags = 'a'")) == [3]
    # NOT on MV: no value equals 'a' -> rows 1, 4
    assert agg_values(run("SELECT count(*) FROM t WHERE tags <> 'a'")) == [2]


def test_distinctcount():
    assert agg_values(run("SELECT distinctcount(city) FROM t")) == [3]
    assert agg_values(run("SELECT distinctcountmv(tags) FROM t")) == [3]


def test_percentile_exact_formula():
    # sales sorted: [10,20,30,40,50]; p50 idx = int(5*0.5)=2 -> 30
    assert agg_values(run("SELECT percentile50(sales) FROM t")) == [30.0]
    # p90 idx = int(4.5)=4 -> 50
    assert agg_values(run("SELECT percentile90(sales) FROM t")) == [50.0]
    assert agg_values(run("SELECT percentileest50(sales) FROM t")) == [30.0]


def test_group_by_desc_order_and_top():
    resp = run("SELECT sum(sales) FROM t GROUP BY city TOP 2")
    gr = resp.aggregation_results[0].group_by_result
    assert [(g.group, g.value) for g in gr] == [(["ny"], 80.0), (["la"], 40.0)]


def test_group_by_min_ascending():
    resp = run("SELECT min(sales) FROM t GROUP BY city")
    gr = resp.aggregation_results[0].group_by_result
    # min sorts ascending (reference quirk: startswith("min"))
    assert [(g.group[0], g.value) for g in gr] == [("sf", 10.0), ("ny", 30.0), ("la", 40.0)]


def test_group_by_mv_explodes():
    resp = run("SELECT count(*) FROM t GROUP BY tags")
    gr = {g.group[0]: g.value for g in resp.aggregation_results[0].group_by_result}
    assert gr == {"a": 3, "b": 3, "c": 2}


def test_group_by_multi_column():
    resp = run("SELECT sum(sales) FROM t GROUP BY city, tags TOP 100")
    gr = {tuple(g.group): g.value for g in resp.aggregation_results[0].group_by_result}
    assert gr[("sf", "b")] == 30.0
    assert gr[("ny", "c")] == 50.0


def test_selection_basic():
    resp = run("SELECT city, sales FROM t LIMIT 3")
    s = resp.selection_results
    assert s.columns == ["city", "sales"]
    assert s.rows == [["sf", 10], ["sf", 20], ["ny", 30]]


def test_selection_order_by():
    resp = run("SELECT city FROM t ORDER BY sales DESC LIMIT 2")
    assert resp.selection_results.rows == [["ny"], ["la"]]


def test_selection_star_order():
    resp = run("SELECT * FROM t LIMIT 1")
    assert resp.selection_results.columns == ["city", "tags", "sales", "price"]


def test_mv_aggregation():
    # countMV counts every value: 2+1+1+2+2 = 8
    assert agg_values(run("SELECT countmv(tags) FROM t")) == [8]


def test_empty_result_defaults():
    resp = run("SELECT count(*), sum(sales), min(sales), max(sales) FROM t WHERE city = 'zz'")
    vals = agg_values(resp)
    assert vals[0] == 0 and vals[1] == 0.0
    assert vals[2] == math.inf and vals[3] == -math.inf


def test_hll_close_to_exact():
    resp = run("SELECT distinctcounthll(sales) FROM t")
    # tiny cardinality -> linear counting is exact
    assert agg_values(resp) == [5]
