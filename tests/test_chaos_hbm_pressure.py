"""Tier-1 twin of ``cluster_harness --scenario hbm-pressure`` (ISSUE 18):
addressable data is pinned far above the HBM cap under closed-loop mixed
load — the hot set keeps answering, cold tables cycle through warm/disk
and back (demotion AND promotion counters move), and an injected device
allocation failure heals as ``resourceExhausted`` without a host
failover or a poisoned plan.  Zero failed queries end to end."""
import pytest

from pinot_tpu.tools.cluster_harness import run_hbm_pressure_scenario


@pytest.mark.chaos
def test_hbm_pressure_scenario_cycles_tiers_with_zero_failures(tmp_path):
    out = run_hbm_pressure_scenario(
        num_tables=8,
        rows_per_table=64,
        clients=2,
        baseline_s=0.6,
        load_s=2.0,
        data_dir=str(tmp_path),
        seed=421,
    )

    # the headline: nothing failed while addressable >> HBM cap
    assert out["failedQueries"] == 0, out
    assert out["sweepErrors"] == [], out["sweepErrors"][:3]
    assert out["addressable_over_cap"] >= 4.0
    assert out["addressableBytes"] > out["hbmCapBytes"]

    # tiers actually cycled: victims left HBM AND came back
    assert out["demotions"] > 0
    assert out["promotions"] > 0
    assert out["cold_loads"] > 0
    assert out["coldSweeps"] > 0

    # the hot set stayed bounded — generous band, this is a CI box
    assert out["hotLoad"]["okQueries"] > 0
    assert out["hot_p99_ms"] <= 10.0 * max(out["baseline_p99_ms"], 25.0)

    # OOM healed as its own class: answered on device, never poisoned
    assert out["oomHealed"] is True
    heal = out["selfHealing"]
    assert heal["resourceExhausted"] >= 1
    assert heal["poisonedPlans"] == 0
