"""Serving-topology tests: DataTable wire roundtrip, in-process
broker/server cluster vs oracle, real TCP transport, partial failure.

The in-process multi-node harness mirrors the reference's
``ClusterTest`` approach (everything in one process, SURVEY §4).
"""
import json
import math

import numpy as np
import pytest

from pinot_tpu.broker.broker import BrokerHttpServer, BrokerRequestHandler
from pinot_tpu.broker.routing import RoutingTableProvider
from pinot_tpu.common.datatable import deserialize_result, serialize_result
from pinot_tpu.engine.results import (
    AvgPartial,
    CountPartial,
    DistinctPartial,
    HistogramPartial,
    HllPartial,
    IntermediateResult,
    MinPartial,
    SumPartial,
)
from pinot_tpu.pql import parse_pql, optimize_request
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.server.instance import ServerInstance
from pinot_tpu.tools.datagen import make_test_schema, random_rows
from pinot_tpu.tools.scan_engine import ScanQueryProcessor
from pinot_tpu.transport.local import LocalTransport
from pinot_tpu.transport.tcp import TcpServer, TcpTransport

TABLE = "testTable"


# ------------------------------------------------------------ datatable
def test_datatable_roundtrip():
    res = IntermediateResult(
        aggregations=[
            CountPartial(5),
            SumPartial(1.5),
            MinPartial(-2.0),
            AvgPartial(10.0, 4.0),
            DistinctPartial({"a", "b", 3}),
            HllPartial(np.arange(256, dtype=np.uint8)),
            HistogramPartial({1.0: 3, 2.5: 7}, percentile=90),
        ],
        num_docs_scanned=42,
        total_docs=100,
        num_segments_queried=3,
        trace={"server0": [{"span": "x", "ms": 1.5}]},
        exceptions=[(200, "boom")],
    )
    out = deserialize_result(serialize_result(res))
    assert out.num_docs_scanned == 42
    assert out.total_docs == 100
    assert out.exceptions == [(200, "boom")]
    assert out.trace == res.trace
    assert [type(p).__name__ for p in out.aggregations] == [
        type(p).__name__ for p in res.aggregations
    ]
    assert out.aggregations[0].count == 5
    assert out.aggregations[4].values == {"a", "b", 3}
    np.testing.assert_array_equal(out.aggregations[5].registers, res.aggregations[5].registers)
    assert out.aggregations[6].counts == {1.0: 3, 2.5: 7}
    assert out.aggregations[6].percentile == 90


def test_datatable_groups_and_selection():
    res = IntermediateResult(
        groups={("a", "1"): [SumPartial(2.0)], ("b", "2"): [SumPartial(3.0)]},
        num_docs_scanned=2,
    )
    out = deserialize_result(serialize_result(res))
    assert out.groups[("a", "1")][0].total == 2.0

    res2 = IntermediateResult(
        selection_rows=[([1, "x"], ["x", 1, [1, 2]]), ([2, "y"], ["y", 2, [3]])],
        selection_columns=["d", "m", "mv"],
    )
    out2 = deserialize_result(serialize_result(res2))
    assert out2.selection_columns == ["d", "m", "mv"]
    assert out2.selection_rows == [([1, "x"], ["x", 1, [1, 2]]), ([2, "y"], ["y", 2, [3]])]


# ----------------------------------------------------------- cluster
@pytest.fixture(scope="module")
def cluster():
    schema = make_test_schema()
    rows = random_rows(schema, 800, seed=9, cardinality=12)
    half = len(rows) // 2
    seg_a1 = build_segment(schema, rows[:200], TABLE, "segA1")
    seg_a2 = build_segment(schema, rows[200:half], TABLE, "segA2")
    seg_b1 = build_segment(schema, rows[half:600], TABLE, "segB1")
    seg_b2 = build_segment(schema, rows[600:], TABLE, "segB2")

    server_a = ServerInstance("serverA")
    server_a.add_segment(TABLE, seg_a1)
    server_a.add_segment(TABLE, seg_a2)
    server_b = ServerInstance("serverB")
    server_b.add_segment(TABLE, seg_b1)
    server_b.add_segment(TABLE, seg_b2)

    transport = LocalTransport()
    transport.register(("serverA", 0), server_a.handle_request)
    transport.register(("serverB", 0), server_b.handle_request)

    routing = RoutingTableProvider()
    routing.update(
        TABLE,
        {
            "segA1": {"serverA": "ONLINE"},
            "segA2": {"serverA": "ONLINE"},
            "segB1": {"serverB": "ONLINE"},
            "segB2": {"serverB": "ONLINE"},
        },
    )
    broker = BrokerRequestHandler(
        transport,
        {"serverA": ("serverA", 0), "serverB": ("serverB", 0)},
        routing=routing,
        timeout_ms=30_000,
    )
    oracle = ScanQueryProcessor(schema, rows)
    return broker, oracle, transport


CLUSTER_QUERIES = [
    "SELECT count(*) FROM testTable",
    "SELECT sum(metInt), avg(metDouble) FROM testTable WHERE dimInt > 1000",
    "SELECT sum(metInt) FROM testTable GROUP BY dimStr TOP 5",
    "SELECT distinctcount(dimLong) FROM testTable",
    "SELECT percentile90(metInt) FROM testTable",
    "SELECT min(metFloat) FROM testTable GROUP BY dimStr, dimInt TOP 10",
    "SELECT dimStr, metInt FROM testTable ORDER BY metInt DESC LIMIT 8",
    "SELECT distinctcounthll(dimInt) FROM testTable WHERE dimStr <> 'qq'",
]


@pytest.mark.parametrize("pql", CLUSTER_QUERIES)
def test_cluster_matches_oracle(cluster, pql):
    broker, oracle, _ = cluster
    got = broker.handle_pql(pql).to_json()
    want = oracle.execute(optimize_request(parse_pql(pql))).to_json()
    # requestId/planDigest are broker-assigned (the oracle issues
    # neither); cost is path-dependent execution accounting
    for k in ("timeUsedMs", "requestId", "planDigest", "cost",
              "freshnessMs",  # wall-clock-relative event-time staleness
              "numEntriesScannedInFilter",
              "numEntriesScannedPostFilter", "numSegmentsQueried",
              "numServersQueried", "numServersResponded"):
        got.pop(k, None)
        want.pop(k, None)
    assert got == want


def test_cluster_stats(cluster):
    broker, _, _ = cluster
    resp = broker.handle_pql("SELECT count(*) FROM testTable")
    assert resp.num_servers_queried == 2
    assert resp.num_servers_responded == 2
    assert resp.total_docs == 800


def test_partial_failure(cluster):
    broker, _, transport = cluster
    transport.set_down(("serverB", 0))
    try:
        resp = broker.handle_pql("SELECT count(*) FROM testTable")
        # serverA's partial results still reduce; serverB surfaces an exception
        assert resp.num_servers_responded == 1
        assert len(resp.exceptions) == 1
        assert resp.num_docs_scanned == 400
    finally:
        transport.set_down(("serverB", 0), down=False)


def test_bad_pql_returns_exception(cluster):
    broker, _, _ = cluster
    resp = broker.handle_pql("SELEC nope")
    assert resp.exceptions and resp.exceptions[0].error_code == 150


def test_unknown_table(cluster):
    broker, _, _ = cluster
    resp = broker.handle_pql("SELECT count(*) FROM nosuchtable")
    assert resp.exceptions and resp.exceptions[0].error_code == 410


def test_trace_rides_back(cluster):
    broker, _, _ = cluster
    resp = broker.handle_pql("SELECT count(*) FROM testTable", trace=True)
    assert resp.trace_info  # per-server span lists


# ---------------------------------------------------------------- tcp
def test_tcp_roundtrip():
    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 100, seed=2)
    seg = build_segment(schema, rows, TABLE, "tcpseg")
    server = ServerInstance("tcpServer")
    server.add_segment(TABLE, seg)

    tcp_server = TcpServer(server.handle_request)
    tcp_server.start()
    try:
        transport = TcpTransport()
        routing = RoutingTableProvider()
        routing.update(TABLE, {"tcpseg": {"tcpServer": "ONLINE"}})
        broker = BrokerRequestHandler(
            transport, {"tcpServer": tcp_server.address}, routing=routing
        )
        resp = broker.handle_pql("SELECT count(*) FROM testTable")
        assert resp.num_docs_scanned == 100
        oracle = ScanQueryProcessor(schema, rows)
        want = oracle.execute(parse_pql("SELECT sum(metInt) FROM testTable"))
        got = broker.handle_pql("SELECT sum(metInt) FROM testTable")
        assert got.aggregation_results[0].value == want.aggregation_results[0].value
    finally:
        tcp_server.stop()


def test_http_endpoint():
    import urllib.request

    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 50, seed=4)
    seg = build_segment(schema, rows, TABLE, "httpseg")
    server = ServerInstance("httpServer")
    server.add_segment(TABLE, seg)
    transport = LocalTransport()
    transport.register(("httpServer", 0), server.handle_request)
    routing = RoutingTableProvider()
    routing.update(TABLE, {"httpseg": {"httpServer": "ONLINE"}})
    broker = BrokerRequestHandler(transport, {"httpServer": ("httpServer", 0)}, routing=routing)
    http = BrokerHttpServer(broker)
    http.start()
    try:
        url = f"http://127.0.0.1:{http.port}/query"
        body = json.dumps({"pql": "SELECT count(*) FROM testTable"}).encode()
        req = urllib.request.Request(url, data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            payload = json.loads(r.read())
        assert payload["numDocsScanned"] == 50
        assert payload["aggregationResults"][0]["value"] == "50"
        # GET variant
        get_url = url + "?pql=" + urllib.parse.quote("SELECT count(*) FROM testTable")
        with urllib.request.urlopen(get_url, timeout=10) as r:
            payload = json.loads(r.read())
        assert payload["numDocsScanned"] == 50
    finally:
        http.stop()


def test_debug_options_reach_servers():
    """optimizationFlags ride the InstanceRequest wire format so the
    server-side re-parse applies the same optimizer toggles as the
    broker (OptimizationFlags.java semantics, end to end)."""
    from pinot_tpu.tools.cluster_harness import InProcessCluster
    from pinot_tpu.tools.datagen import make_test_schema, random_rows
    from pinot_tpu.segment.builder import build_segment

    cluster = InProcessCluster(num_servers=1)
    schema = make_test_schema(with_mv=False)
    physical = cluster.add_offline_table(schema)
    rows = random_rows(schema, 200, seed=12)
    cluster.upload(physical, build_segment(schema, rows, physical, "dbg1"))
    try:
        pql = "SELECT count(*) FROM testTable WHERE dimInt = 1 OR dimInt = 2"
        want = cluster.broker.handle_pql(pql).to_json()
        got = cluster.broker.handle_pql(
            pql, debug_options={"optimizationFlags": "-multipleOrEqualitiesToInClause"}
        ).to_json()
        assert not got["exceptions"]
        assert got["aggregationResults"] == want["aggregationResults"]

        bad = cluster.broker.handle_pql(
            pql, debug_options={"optimizationFlags": "bogus"}
        ).to_json()
        assert bad["exceptions"]
    finally:
        cluster.stop()


def test_per_query_timeout_override():
    """A client can SHORTEN the timeout per query (reference timeoutMs
    request parameter) but never extend past the broker ceiling; a
    too-short timeout yields a clean gather error, not a hang."""
    import time as _time
    import urllib.request

    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 50, seed=4)
    seg = build_segment(schema, rows, TABLE, "toseg")
    server = ServerInstance("toServer")

    slow_calls = {"n": 0}
    real = server.handle_request

    def slow(req_bytes):
        slow_calls["n"] += 1
        if slow_calls["n"] > 1:  # warm query passes, then delay
            _time.sleep(0.8)
        return real(req_bytes)

    server.add_segment(TABLE, seg)
    transport = LocalTransport()
    transport.register(("toServer", 0), slow)
    routing = RoutingTableProvider()
    routing.update(TABLE, {"toseg": {"toServer": "ONLINE"}})
    broker = BrokerRequestHandler(
        transport, {"toServer": ("toServer", 0)}, routing=routing, timeout_ms=15_000
    )
    http = BrokerHttpServer(broker)
    http.start()
    try:
        url = f"http://127.0.0.1:{http.port}/query"

        def post(payload):
            req = urllib.request.Request(
                url, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=10) as r:
                return json.loads(r.read())

        pql = "SELECT count(*) FROM testTable"
        assert post({"pql": pql})["numDocsScanned"] == 50  # warm
        t0 = _time.perf_counter()
        out = post({"pql": pql, "timeoutMs": 100})
        took = _time.perf_counter() - t0
        assert out["exceptions"], "100ms budget must beat the 800ms server"
        assert took < 5, f"short timeout honored, took {took:.2f}s"
        # a huge request value clamps to the broker ceiling (and works)
        out = post({"pql": pql, "timeoutMs": 10_000_000})
        assert not out["exceptions"] and out["numDocsScanned"] == 50
        # junk timeouts are REJECTED with a validation error (strings,
        # booleans — float(True)==1.0 — and non-positive numbers): a
        # silently ignored override would leave the client believing a
        # budget it never got
        for junk in ("soon", True, -5, 0):
            out = post({"pql": pql, "timeoutMs": junk})
            assert out["exceptions"], junk
            assert out["exceptions"][0]["errorCode"] == 160, junk
        # absent override still means "broker default", not an error
        out = post({"pql": pql, "timeoutMs": None})
        assert not out["exceptions"] and out["numDocsScanned"] == 50
    finally:
        http.stop()
