"""Correctness & freshness audit plane (ISSUE 19): the differential
comparator, shadow-audit eligibility + quarantine, the replica/CRC
sweeps, event-time freshness watermarks end to end, and the seeded
wrong-answer chaos twin."""
import json
import time

import pytest

from pinot_tpu.common.schema import (
    DataType,
    FieldSpec,
    FieldType,
    Schema,
    TimeFieldSpec,
)
from pinot_tpu.realtime.llc import make_segment_name
from pinot_tpu.realtime.stream import MemoryStreamProvider
from pinot_tpu.tools.cluster_harness import InProcessCluster
from pinot_tpu.tools.datagen import make_test_schema, random_rows
from pinot_tpu.utils.audit import (
    ACCOUNTING_FIELDS,
    SamplerBudget,
    ShadowAuditor,
    payloads_equivalent,
    strip_accounting,
)


# ------------------------------------------------------- comparator
def test_payloads_equivalent_absorbs_float32_noise():
    """The device float32 / host float64 accumulation wobble must NOT
    read as divergence: last-printed-digit noise and sqrt(n)-scaled
    relative error both sit far inside the tolerance band."""
    a = {"aggregationResults": [{"function": "sum_m", "value": "118.37801"}]}
    b = {"aggregationResults": [{"function": "sum_m", "value": "118.37800"}]}
    assert payloads_equivalent(a, b)
    # 1M-row Q1-scale sum: ~1e-4 relative tree-reduction error is honest
    assert payloads_equivalent(
        {"v": "3578694016.00000"}, {"v": "3578694400.00000"}
    )


def test_payloads_equivalent_catches_real_divergence():
    """A genuinely wrong answer (corrupted partial, dropped rows) is
    orders of magnitude outside the band and must fail."""
    good = {"aggregationResults": [{"function": "sum_m", "value": "2048.00000"}]}
    bad = {"aggregationResults": [{"function": "sum_m", "value": "2148.00000"}]}
    assert not payloads_equivalent(good, bad)
    # counts are exact: off-by-one on an integer aggregate diverges
    assert not payloads_equivalent({"numDocs": 300}, {"numDocs": 301})


def test_payloads_equivalent_structure_is_exact():
    """Only numeric LEAVES get tolerance: keys, list lengths, group
    labels, and non-numeric strings remain byte-exact."""
    assert not payloads_equivalent({"a": 1}, {"a": 1, "b": 2})
    assert not payloads_equivalent([1, 2], [1, 2, 3])
    assert not payloads_equivalent({"group": ["x"]}, {"group": ["y"]})
    assert payloads_equivalent(
        {"g": [["k1"], "5.00000"]}, {"g": [["k1"], "5.00000"]}
    )


def test_unstripped_field_difference_still_fails():
    """Negative differential guard (satellite 1): stripping accounting
    must not widen the contract — two payloads differing in any
    NON-stripped field still compare unequal after the strip."""
    a = {"totalDocs": 300, "numDocsScanned": 300, "freshnessMs": 11.0}
    b = {"totalDocs": 299, "numDocsScanned": 250, "freshnessMs": 99.0}
    sa, sb = strip_accounting(a), strip_accounting(b)
    # the accounting fields (incl. freshnessMs) are gone ...
    assert "freshnessMs" in ACCOUNTING_FIELDS
    assert "freshnessMs" not in sa and "numDocsScanned" not in sa
    # ... but the surviving totalDocs difference still fails the check
    assert not payloads_equivalent(sa, sb)


def test_bench_strip_timing_excludes_freshness_only():
    """bench.py's byte-identity differential must ignore freshnessMs
    (wall-clock-relative) while any other field difference still
    breaks identity."""
    import bench

    class _Resp:
        def __init__(self, d):
            self._d = d

        def to_json(self):
            return dict(self._d)

    base = {"totalDocs": 10, "aggregationResults": [], "freshnessMs": 5.0}
    fresher = dict(base, freshnessMs=900.0)
    wrong = dict(base, totalDocs=11)
    assert bench._strip_timing(_Resp(base)) == bench._strip_timing(_Resp(fresher))
    assert bench._strip_timing(_Resp(base)) != bench._strip_timing(_Resp(wrong))


# -------------------------------------------- shadow-audit sampling
class _StubResult:
    def __init__(self, tier="device"):
        self.exceptions = []
        self._served_tier = tier


class _StubRequest:
    explain = False
    join = None


def _stub_instance():
    from pinot_tpu.utils.metrics import ServerMetrics

    class _Exec:
        @staticmethod
        def audit_quarantined_snapshot():
            return []

    class _Inst:
        name = "stub"
        metrics = ServerMetrics("stub-audit-test")
        executor = _Exec()

    return _Inst()


def test_shadow_offer_eligibility_and_budget():
    inst = _stub_instance()
    auditor = ShadowAuditor(inst, sample_n=1, budget=SamplerBudget(per_s=0.0))
    try:
        req = {"requestId": "r1", "table": "t"}
        # host-served replies ARE the oracle: never sampled
        assert not auditor.offer(req, _StubRequest(), [], _StubResult("host"))
        # eligible tier but an exhausted budget -> dropped, not queued
        assert not auditor.offer(req, _StubRequest(), [], _StubResult("device"))
        assert inst.metrics.meter("audit.dropped").count >= 1
        # sampling counter: 1-in-N means N-1 of N offers are free no-ops
        auditor.sample_n = 1000
        auditor._count = 0
        assert not auditor.offer(req, _StubRequest(), [], _StubResult("device"))
    finally:
        auditor.stop()


def test_shadow_auditor_disabled_when_sample_n_zero():
    inst = _stub_instance()
    auditor = ShadowAuditor(inst, sample_n=0)
    try:
        assert not auditor.enabled
        assert not auditor.offer({}, _StubRequest(), [], _StubResult("device"))
        snap = auditor.snapshot()
        assert snap["enabled"] is False and snap["samples"] == 0
    finally:
        auditor.stop()


def test_sampler_budget_refills():
    b = SamplerBudget(per_s=1000.0, burst=2.0)
    assert b.take() and b.take()
    assert not b.take()  # burst exhausted
    time.sleep(0.01)  # 1000/s refills ~10 tokens in 10ms
    assert b.take()


# ------------------------------------------------- chaos twin (e2e)
def test_audit_divergence_scenario_chaos_twin(tmp_path):
    """Tier-1 twin of ``--scenario audit-divergence``: a seeded device
    fault injector corrupts served aggregates under closed-loop load;
    the shadow auditor must detect within budget, quarantine the
    (shape, tier), and the cluster must serve byte-correct answers
    after — with ZERO failed queries throughout."""
    from pinot_tpu.tools.cluster_harness import run_audit_divergence_scenario

    res = run_audit_divergence_scenario(
        load_s=1.0, detect_budget_s=20.0, data_dir=str(tmp_path)
    )
    assert res["detected"], res
    assert res["quarantined"] and res["quarantined"][0]["tier"] == "device"
    assert res["failedQueries"] == 0
    assert res["postQuarantineMismatches"] == 0
    assert res["divergences"] >= 1


# --------------------------------------------------- freshness plane
def _fresh_schema(name: str) -> Schema:
    return Schema(
        name,
        dimensions=[FieldSpec("d", DataType.STRING)],
        metrics=[FieldSpec("m", DataType.INT, FieldType.METRIC)],
        time_field=TimeFieldSpec("ts", DataType.LONG, time_unit="MILLISECONDS"),
    )


def test_freshness_ms_monotone_consistent_with_watermarks(tmp_path):
    """BrokerResponse.freshnessMs must equal (reduce-time now) − the
    table's MIN partition watermark — bounded by wall clocks read
    around the query — and must shrink when fresher events land."""
    from pinot_tpu.broker.freshness import WATERMARKS, now_ms

    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path))
    schema = _fresh_schema("freshT")
    stream = MemoryStreamProvider(num_partitions=1)
    physical = cluster.add_realtime_table(schema, stream, rows_per_segment=500)
    try:
        t0 = now_ms()
        for i in range(20):
            stream.produce({"d": f"a{i % 3}", "m": i, "ts": int(t0 - 60_000 + i)})
        dm = cluster.controller.realtime_manager.consumers_of(
            make_segment_name(physical, 0, 0)
        )[0]
        dm.consume_step(max_rows=100)

        wm = WATERMARKS.table_min_ms(physical)
        assert wm == int(t0 - 60_000 + 19)  # max event-time consumed

        before = now_ms()
        resp = cluster.query("SELECT count(*) FROM freshT")
        after = now_ms()
        assert not resp.exceptions
        assert resp.freshness_ms is not None
        # consistency band: computed between the two wall-clock reads
        assert before - wm - 1e-6 <= resp.freshness_ms <= after - wm + 1e-6
        assert resp.to_json()["freshnessMs"] == round(resp.freshness_ms, 3)

        # fresher events -> watermark advances -> freshnessMs shrinks
        stream.produce({"d": "z", "m": 1, "ts": int(now_ms() - 2_000)})
        dm.consume_step(max_rows=100)
        wm2 = WATERMARKS.table_min_ms(physical)
        assert wm2 > wm
        resp2 = cluster.query("SELECT count(*) FROM freshT")
        assert resp2.freshness_ms < resp.freshness_ms

        # the watermark itself is monotone: a stale replay cannot
        # regress it (so freshnessMs can never lie fresher->staler
        # without wall time passing)
        WATERMARKS.advance(physical, 0, wm2 - 50_000)
        assert WATERMARKS.get(physical, 0) == wm2

        # offline-only replies carry NO freshness stamp
        schema_off = make_test_schema(with_mv=False)
        from pinot_tpu.segment.builder import build_segment

        off = cluster.add_offline_table(schema_off, replication=1)
        cluster.upload(
            off, build_segment(schema_off, random_rows(schema_off, 50, seed=3), off, "s0")
        )
        resp_off = cluster.query("SELECT count(*) FROM testTable")
        assert resp_off.freshness_ms is None
        assert "freshnessMs" not in resp_off.to_json()
    finally:
        cluster.stop()
        WATERMARKS.drop_table(physical)


def test_freshness_gauge_survives_rollover_and_pool_resize(tmp_path):
    """The per-(table, partition) freshness.lag gauge is a continuous
    series: segment rollover hands it to the successor consumer, and
    an ingest-pool resize must not detach it."""
    from pinot_tpu.broker.freshness import WATERMARKS, now_ms
    from pinot_tpu.realtime.pool import IngestConsumerPool

    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path))
    schema = _fresh_schema("freshRoll")
    stream = MemoryStreamProvider(num_partitions=1)
    physical = cluster.add_realtime_table(schema, stream, rows_per_segment=50)
    pool = IngestConsumerPool(workers=2, name="auditFreshPool")
    try:
        t0 = now_ms()
        for i in range(60):
            stream.produce({"d": "x", "m": i, "ts": int(t0 - 30_000 + i)})
        dm = cluster.controller.realtime_manager.consumers_of(
            make_segment_name(physical, 0, 0)
        )[0]
        dm.consume_step(max_rows=1000)
        gauge = cluster.servers[0].metrics.gauge(f"freshness.lag.{physical}.p0")
        v_before = gauge.value
        assert isinstance(v_before, (int, float)) and v_before > 0

        # rollover: seq 0 commits, seq 1 consumes — same series name,
        # successor re-registers, predecessor's detach is a no-op
        assert dm.threshold_reached
        dm.try_commit()
        dm1 = cluster.controller.realtime_manager.consumers_of(
            make_segment_name(physical, 0, 1)
        )[0]
        v_after_roll = gauge.value
        assert isinstance(v_after_roll, (int, float)) and v_after_roll > 0

        # drive the successor through the shared pool, then resize it:
        # the watermark keeps advancing and the gauge stays attached
        pool.add(dm1, key=("freshRoll", 0))
        stream.produce({"d": "y", "m": 1, "ts": int(now_ms() - 3_000)})
        pool.kick()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            w = WATERMARKS.get(physical, 0)
            if w is not None and w >= t0 - 4_000:
                break
            time.sleep(0.02)
        assert WATERMARKS.get(physical, 0) >= t0 - 4_000

        pool.resize(1)
        stream.produce({"d": "y", "m": 2, "ts": int(now_ms() - 1_000)})
        pool.kick()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            w = WATERMARKS.get(physical, 0)
            if w is not None and w >= t0 - 2_000:
                break
            time.sleep(0.02)
        assert WATERMARKS.get(physical, 0) >= t0 - 2_000
        v_final = gauge.value
        # gauge live and reporting the (small) fresh lag
        assert isinstance(v_final, (int, float)) and 0 < v_final < 60_000
    finally:
        pool.stop()
        cluster.stop()
        WATERMARKS.drop_table(physical)


def test_datatable_freshness_roundtrip_and_mixed_version():
    """The freshness stamp rides a TRAILING optional DataTable field:
    round-trips when present, tolerates None, and a payload truncated
    to the pre-audit wire shape still deserializes (older peer)."""
    from pinot_tpu.common.datatable import deserialize_result, serialize_result
    from pinot_tpu.engine.results import IntermediateResult

    res = IntermediateResult()
    res.num_docs_scanned = 7
    res.total_docs = 7
    res.freshness = {"minEventMs": 1234.5}
    back = deserialize_result(serialize_result(res))
    assert back.freshness == {"minEventMs": 1234.5}
    assert back.num_docs_scanned == 7

    res2 = IntermediateResult()
    assert deserialize_result(serialize_result(res2)).freshness is None


def test_results_merge_min_combines_freshness():
    """An answer is only as fresh as its STALEST contributing
    partition: merge takes the min watermark, and a None side never
    clobbers a stamped one."""
    from pinot_tpu.engine.results import IntermediateResult

    a, b, c = IntermediateResult(), IntermediateResult(), IntermediateResult()
    b.freshness = {"minEventMs": 5_000.0}
    c.freshness = {"minEventMs": 2_000.0}
    a.merge(b)
    assert a.freshness == {"minEventMs": 5_000.0}
    a.merge(c)
    assert a.freshness["minEventMs"] == 2_000.0
    a.merge(IntermediateResult())  # unstamped (offline) side: no-op
    assert a.freshness["minEventMs"] == 2_000.0


def test_worst_freshness_tables_ranking():
    from pinot_tpu.broker.freshness import worst_freshness_tables

    snap = {
        "tables": {
            "a_REALTIME": {"lagMs": 100.0},
            "b_REALTIME": {"lagMs": 90_000.0},
            "c_REALTIME": {"lagMs": 7_000.0},
        }
    }
    ranked = worst_freshness_tables(snap, top=2)
    assert [r["table"] for r in ranked] == ["b_REALTIME", "c_REALTIME"]


# ------------------------------------------------------ freshness SLO
def test_slo_freshness_objective_burn():
    """freshnessMs rides the SLO burn machinery as a third objective:
    breaches count only when a threshold is set, and evaluate() emits
    a freshness burn entry alongside latency/availability."""
    from pinot_tpu.utils.metrics import MetricsRegistry
    from pinot_tpu.utils.slo import SloTracker
    from pinot_tpu.utils.timeseries import HistoryRecorder

    reg = MetricsRegistry("slo-fresh-test")
    clk = [0.0]
    hist = HistoryRecorder(
        reg, interval_s=5, capacity=64, clock=lambda: clk[0], start=False
    )
    slo = SloTracker(history=hist, metrics=reg,
                     fast_window_s=10.0, slow_window_s=100.0)
    hist.register_provider(slo.series)
    slo.set_objective("t", {"latencyMs": 1e9,
                            "freshnessMs": 1000.0, "freshnessTarget": 0.9})
    # baseline sample: window deltas need a pre-window tick to diff from
    slo.observe("t", 1.0, False, freshness_ms=50.0)
    hist.tick()
    clk[0] += 10.0
    for _ in range(8):
        slo.observe("t", 1.0, False, freshness_ms=50.0)  # fresh: no breach
    for _ in range(2):
        slo.observe("t", 1.0, False, freshness_ms=5_000.0)  # stale: breach
    hist.tick()
    assert slo.series()["slo.t.freshnessBreaches"] == 2
    ev = slo.evaluate(consume_crossings=False)
    fresh = ev["tables"]["t"]["windows"]["burnRate5m"]["freshness"]
    assert fresh["bad"] == 2 and fresh["queries"] == 10
    assert fresh["burnRate"] == pytest.approx(0.2 / 0.1, rel=1e-3)

    # threshold 0 (offline fleet): freshness never breaches, and
    # evaluate() contributes NO freshness entry (budget zeroed)
    slo.set_objective("u", {"latencyMs": 1e9})
    slo.observe("u", 1.0, False, freshness_ms=1e12)
    hist.tick()
    assert slo.series()["slo.u.freshnessBreaches"] == 0
    ev2 = slo.evaluate(consume_crossings=False)
    assert ev2["tables"]["u"]["windows"]["burnRate5m"]["freshness"] is None


# --------------------------------------------------- querylog x-link
def test_querylog_freshness_and_audit_ref_annotation():
    from pinot_tpu.broker.querylog import SlowQueryLog

    log = SlowQueryLog(threshold_ms=0.0)
    log.observe({"requestId": "rq-1", "table": "t", "timeUsedMs": 5.0,
                 "freshnessMs": 123.4})
    assert log.annotate("rq-1", auditRef="audit-rq-1")
    assert not log.annotate("rq-missing", auditRef="x")
    entry = [e for e in log.entries() if e["requestId"] == "rq-1"][0]
    assert entry["freshnessMs"] == 123.4
    assert entry["auditRef"] == "audit-rq-1"


# --------------------------------------------------- CRC sweep plane
def test_crc_audit_manager_detects_replica_divergence(tmp_path):
    """The controller sweep compares every replica's claimed segment
    CRC against the other replicas AND the property-store metadata: a
    clean cluster sweeps zero mismatches; one corrupted replica claim
    is flagged with the full evidence row."""
    from pinot_tpu.controller.managers import CrcAuditManager
    from pinot_tpu.segment.builder import build_segment

    cluster = InProcessCluster(num_servers=2, data_dir=str(tmp_path))
    schema = make_test_schema(with_mv=False)
    physical = cluster.add_offline_table(schema, replication=2)
    rows = random_rows(schema, 120, seed=7)
    cluster.upload(physical, build_segment(schema, rows[:60], physical, "s1"))
    cluster.upload(physical, build_segment(schema, rows[60:], physical, "s2"))
    try:
        by_name = {s.name: s for s in cluster.servers}
        # in-process servers register no admin URL; give the sweep one
        for name, inst in cluster.controller.resources.instances.items():
            if inst.role == "server":
                inst.url = f"inproc://{name}"

        claims = {
            name: dict(srv.segment_crcs()["segments"])
            for name, srv in by_name.items()
        }
        mgr = CrcAuditManager(
            cluster.controller.resources,
            crc_fn=lambda name, url: claims[name],
        )
        mgr.run_once()
        snap = mgr.snapshot()
        assert snap["mismatches"] == [] and snap["segmentsChecked"] == 2

        # corrupt ONE replica's claim for s1: flagged with evidence
        victim = next(
            n for n, c in claims.items() if c.get(physical, {}).get("s1")
        )
        claims[victim] = {physical: dict(claims[victim][physical], s1=0xBAD)}
        mgr.run_once()
        snap = mgr.snapshot()
        assert len(snap["mismatches"]) == 1
        row = snap["mismatches"][0]
        assert row["segment"] == "s1"
        assert row["replicaCrcs"][victim] == 0xBAD
        assert row["expectedCrc"] is not None
        assert mgr.metrics.gauge("audit.crcMismatches").value == 1
        mgr.stop()
    finally:
        cluster.stop()


# --------------------------------------------------- debug surfaces
def test_server_and_controller_audit_debug_surfaces(tmp_path):
    """/debug/audit answers on every role, pre-registered with zeros
    before any sample — the doctor's rollup sources."""
    from pinot_tpu.segment.builder import build_segment

    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path))
    schema = make_test_schema(with_mv=False)
    physical = cluster.add_offline_table(schema, replication=1)
    cluster.upload(
        physical,
        build_segment(schema, random_rows(schema, 40, seed=5), physical, "s0"),
    )
    try:
        s = cluster.servers[0]
        snap = s.auditor.snapshot()
        assert snap["samples"] == 0 and snap["divergences"] == 0
        assert snap["quarantined"] == []
        ctrl_snap = cluster.controller.crc_audit.snapshot()
        assert "mismatches" in ctrl_snap and "intervalS" in ctrl_snap
        rep = cluster.broker.replica_audit.snapshot()
        assert rep["divergences"] == 0
    finally:
        cluster.stop()
