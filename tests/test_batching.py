"""Cross-query vectorized batching + ingest-aware result cache (ISSUE 13).

Covers the acceptance contracts:

- batched execution is byte-identical to unbatched across the bench
  shape mix (same-plan distinct-literal queries stacked into one
  vmapped launch);
- batch window close/fill semantics (idle close, cap fill, member cap);
- a result-cache hit returns the identical payload with ZERO device
  work in the cost vector;
- a cached realtime entry is dropped the moment the covering LLC
  consume offset advances (stale answer impossible);
- a deadline-expired query sheds out of a forming batch without
  poisoning its batchmates;
- a poisoned batched plan host-heals EVERY member byte-identically.
"""
import json
import threading
import time

import numpy as np
import pytest

from pinot_tpu.engine.dispatch import BatchSpec, DeviceLane
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.server.scheduler import QueryAbandonedError
from pinot_tpu.tools.cluster_harness import single_server_broker
from pinot_tpu.tools.datagen import make_test_schema, random_rows
from pinot_tpu.utils.metrics import ServerMetrics


def _payload(resp) -> str:
    """Canonical payload for differentials: everything except wall
    clock, the broker-assigned requestId, and the (path-dependent)
    cost vector — the same exclusions every differential suite uses."""
    return json.dumps(
        {
            k: v
            for k, v in resp.to_json().items()
            if k not in ("timeUsedMs", "requestId", "cost", "freshnessMs")
        },
        sort_keys=True,
    )


def _build_stack(pipeline: bool = True, **kwargs):
    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 4000, seed=9)
    segs = [
        build_segment(schema, rows[:2000], "testTable", "bt0"),
        build_segment(schema, rows[2000:], "testTable", "bt1"),
    ]
    return single_server_broker("testTable", segs, pipeline=pipeline, **kwargs)


# dimInt values span ~240..9300 at cardinality 20 (datagen), so these
# literals genuinely partition the data — distinct inputs, one plan
def _literal_ladder(shape: str):
    return [shape.format(t=t) for t in (1000, 2300, 4800, 6500)]


# the bench shape mix, parameterized by a literal each: filtered
# scalar aggs, filtered group-by, distinct-count group-by, selection
BATCH_SHAPES = [
    "SELECT sum(metInt), count(*) FROM testTable WHERE dimInt > {t}",
    "SELECT sum(metFloat), max(metInt) FROM testTable WHERE dimInt > {t} GROUP BY dimStr TOP 5",
    "SELECT distinctcount(dimLong) FROM testTable WHERE dimInt > {t} GROUP BY dimStr TOP 5",
    "SELECT dimStr, metInt FROM testTable WHERE dimInt > {t} ORDER BY metInt DESC LIMIT 7",
]


def _run_concurrently_batched(broker, queries, settle_s: float = 0.8):
    """Fire ``queries`` concurrently while the lane is blocked so they
    queue as distinct same-plan dispatches, then release — the lane's
    dequeue gathers them into batched launches."""
    server = broker.local_servers[0]
    gate = threading.Event()
    server.lane.submit(("blocker", time.monotonic()), lambda: gate.wait(15))
    time.sleep(0.05)
    results = {}
    errs = []

    def run(q):
        try:
            results[q] = broker.handle_pql(q)
        except Exception as e:  # pragma: no cover - fail loudly below
            errs.append((q, e))

    threads = [threading.Thread(target=run, args=(q,)) for q in queries]
    for t in threads:
        t.start()
    time.sleep(settle_s)  # let every PREP finish and queue on the lane
    gate.set()
    for t in threads:
        t.join()
    assert not errs, errs[:1]
    return results


@pytest.mark.parametrize("shape", BATCH_SHAPES, ids=["agg", "groupby", "distinct", "select"])
def test_batched_matches_unbatched_payloads(shape, monkeypatch):
    """Byte-identity differential: same-plan distinct-literal queries
    forced through one batched launch serve payloads identical to the
    serial (unbatched, no-lane) executor — and batches actually
    formed (the counters prove it, not just absence of errors)."""
    # the scalar-agg shape would otherwise take the bit-sliced tier and
    # never queue a scan plan on the lane — this suite exercises the
    # batch-formation machinery itself
    monkeypatch.setenv("PINOT_TPU_BITSLICED", "0")
    serial = _build_stack(pipeline=False)
    pipelined = _build_stack(pipeline=True)
    queries = _literal_ladder(shape)
    # warm staging + compile on one literal so formation isn't skewed
    # by a cold compile holding the lane
    for b in (serial, pipelined):
        r = b.handle_pql(queries[0])
        assert not r.exceptions, r.exceptions

    results = _run_concurrently_batched(pipelined, queries)
    server = pipelined.local_servers[0]
    stats = server.lane.stats()
    assert stats["batchLaunches"] >= 1, stats
    assert stats["batchedQueries"] >= 2, stats
    batched_hits = 0
    for q in queries:
        resp = results[q]
        assert not resp.exceptions, (q, resp.exceptions)
        assert _payload(serial.handle_pql(q)) == _payload(resp), q
        batched_hits += int(resp.cost.get("batchHits", 0))
    assert batched_hits >= 2  # the differential exercised real batches


def test_distinct_literals_produce_distinct_results():
    """Guard against the batching tier ever collapsing distinct
    literals into one answer: the ladder's results must differ."""
    pipelined = _build_stack(pipeline=True)
    queries = _literal_ladder(BATCH_SHAPES[0])
    r = pipelined.handle_pql(queries[0])
    assert not r.exceptions
    results = _run_concurrently_batched(pipelined, queries)
    answers = {
        json.dumps(results[q].to_json().get("aggregationResults"), sort_keys=True)
        for q in queries
    }
    assert len(answers) == len(queries)


# ------------------------------------------------------ lane-unit tier
def _fake_spec(key, val, calls=None):
    """BatchSpec whose batched launch doubles each member's value —
    members must each get THEIR value back, doubled."""

    def launch_batched(inputs_list):
        if calls is not None:
            calls.append([x["v"] for x in inputs_list])
        arr = np.array([x["v"] for x in inputs_list], dtype=np.int64)

        def fetch(handle, count_transfer=True):
            return {"v": arr * 2}

        return fetch, object()

    return BatchSpec(key, {"v": val}, launch_batched)


def _member_result(ticket, deadline=None):
    fetch, handle = ticket.result(deadline)
    return fetch(handle)["v"]


def test_batch_fills_queued_peers_and_respects_cap():
    """All queued same-key dispatches stack into one launch up to the
    member cap; overflow launches as the NEXT batch — and each member
    receives its own sliced output."""
    lane = DeviceLane(metrics=ServerMetrics("t"))
    lane.batch_max = 3
    lane.batch_window_s = 0.0
    calls = []
    gate = threading.Event()
    lane.submit(("blocker",), lambda: gate.wait(10))
    time.sleep(0.05)
    tickets = [
        lane.submit(
            ("q", i),
            lambda i=i: ("unbatched", i),
            batch=_fake_spec("K", i, calls),
        )
        for i in range(5)
    ]
    gate.set()
    vals = [_member_result(t, time.monotonic() + 10) for t in tickets]
    assert vals == [0, 2, 4, 6, 8]
    assert [len(c) for c in calls] == [3, 2]  # cap fill, then remainder
    assert lane.batch_launches == 2
    assert lane.batched_queries == 5
    assert lane.batch_window_full >= 1
    assert all(t.batch_size in (2, 3) for t in tickets)
    lane.close()


def test_single_batchable_dispatch_closes_idle_without_batching():
    """An idle lane launches a lone batchable dispatch immediately via
    its own (unbatched) launch — batching never adds latency or a
    vmapped recompile to a quiet server."""
    lane = DeviceLane()
    t = lane.submit(("q", 0), lambda: "direct", batch=_fake_spec("K", 0))
    assert t.result(time.monotonic() + 10) == "direct"
    assert lane.batch_launches == 0
    assert t.batch_size == 1
    lane.close()


def test_batch_keys_partition_batches():
    """Different batch keys never stack: two shapes queued together
    launch as two batches (or singles), each member correct."""
    lane = DeviceLane()
    lane.batch_window_s = 0.0
    gate = threading.Event()
    lane.submit(("blocker",), lambda: gate.wait(10))
    time.sleep(0.05)
    ta = [
        lane.submit(("a", i), lambda i=i: ("un", i), batch=_fake_spec("KA", i))
        for i in range(2)
    ]
    tb = [
        lane.submit(("b", i), lambda i=i: ("un", i), batch=_fake_spec("KB", 10 + i))
        for i in range(2)
    ]
    gate.set()
    assert [_member_result(t, time.monotonic() + 10) for t in ta] == [0, 2]
    assert [_member_result(t, time.monotonic() + 10) for t in tb] == [20, 22]
    assert lane.batch_launches == 2
    lane.close()


def test_deadline_expired_member_sheds_without_poisoning_batchmates():
    """ISSUE 13 satellite: a member whose deadline drained while its
    batch formed sheds with QueryAbandonedError; its batchmates launch
    and complete normally."""
    lane = DeviceLane()
    lane.batch_window_s = 0.0
    gate = threading.Event()
    lane.submit(("blocker",), lambda: gate.wait(10))
    time.sleep(0.05)
    doomed = lane.submit(
        ("q", 0),
        lambda: ("un", 0),
        deadline=time.monotonic() + 0.05,
        batch=_fake_spec("K", 0),
    )
    survivors = [
        lane.submit(
            ("q", i),
            lambda i=i: ("un", i),
            deadline=time.monotonic() + 30,
            batch=_fake_spec("K", i),
        )
        for i in (1, 2)
    ]
    time.sleep(0.2)  # doomed expires while the blocker holds the lane
    gate.set()
    with pytest.raises(QueryAbandonedError):
        doomed.result(time.monotonic() + 5)
    assert [_member_result(t, time.monotonic() + 10) for t in survivors] == [2, 4]
    assert lane.shed_count == 1
    assert lane.batch_launches == 1  # the two survivors still batched
    assert lane.batched_queries == 2
    lane.close()


def test_batched_launch_error_fans_out_to_every_member():
    """A failing batched launch delivers the SAME typed error to every
    member's waiters (each then heals independently upstream)."""
    from pinot_tpu.engine.dispatch import DeviceExecutionError

    lane = DeviceLane()
    lane.batch_window_s = 0.0

    def bad_launch(inputs_list):
        raise ValueError("trace-time type error")  # deterministic: poison

    gate = threading.Event()
    lane.submit(("blocker",), lambda: gate.wait(10))
    time.sleep(0.05)
    tickets = [
        lane.submit(
            ("q", i), lambda i=i: ("un", i), batch=BatchSpec("K", {"v": i}, bad_launch)
        )
        for i in range(3)
    ]
    gate.set()
    errs = []
    for t in tickets:
        with pytest.raises(DeviceExecutionError) as ei:
            t.result(time.monotonic() + 10)
        errs.append(ei.value)
    assert all(not e.retryable for e in errs)
    assert lane.device_failure_count == 1  # one launch, fanned out
    lane.close()


def test_poisoned_batched_plan_host_heals_every_member(monkeypatch):
    """ISSUE 13 satellite: a plan the injector poisons fails its
    batched launch once, and EVERY member transparently host-heals to
    the payload the serial path serves."""
    from pinot_tpu.common.faults import DeviceFaultInjector

    monkeypatch.setenv("PINOT_TPU_BITSLICED", "0")  # exercise the scan batch tier

    inj = DeviceFaultInjector(seed=3)
    serial = _build_stack(pipeline=False)
    pipelined = _build_stack(pipeline=True, device_fault_injector=inj)
    server = pipelined.local_servers[0]
    queries = _literal_ladder(BATCH_SHAPES[0])
    warm = pipelined.handle_pql(queries[0])
    assert not warm.exceptions, warm.exceptions
    # poison the device plan the whole ladder shares (one StaticPlan)
    digest = inj.launches[-1].digest
    assert digest is not None
    server.executor.clear_poisoned()
    inj.poison_plan(digest)

    def heal_payload(resp) -> str:
        # PR 3 convention: result fields are exact across heal paths,
        # but entries-scanned WORK accounting is path-dependent (the
        # host path and the device path count filter work differently)
        return json.dumps(
            {
                k: v
                for k, v in resp.to_json().items()
                if k
                not in (
                    "timeUsedMs",
                    "requestId",
                    "cost",
                    "numEntriesScannedInFilter",
                    "numEntriesScannedPostFilter",
                )
            },
            sort_keys=True,
        )

    results = _run_concurrently_batched(pipelined, queries)
    for q in queries:
        resp = results[q]
        assert not resp.exceptions, (q, resp.exceptions)
        assert heal_payload(serial.handle_pql(q)) == heal_payload(resp), q
    heal = server.executor.healing_stats()
    assert heal["hostFailovers"] >= len(queries), heal
    assert heal["poisonedPlans"] >= 1, heal


# --------------------------------------------------- result-cache tier
def test_cache_hit_identical_payload_and_zero_device_work(monkeypatch):
    """A hit serves the byte-identical payload, marks rescacheHits=1 as
    its ENTIRE cost vector (zero device work — the acceptance bar), and
    performs no lane dispatch."""
    monkeypatch.setenv("PINOT_TPU_RESULT_CACHE", "1")
    broker = _build_stack(pipeline=True)
    server = broker.local_servers[0]
    q = "SELECT sum(metInt), count(*) FROM testTable WHERE dimInt > 4800"
    r1 = broker.handle_pql(q)
    assert not r1.exceptions, r1.exceptions
    d1 = server.lane.dispatch_count
    r2 = broker.handle_pql(q)
    assert not r2.exceptions
    assert _payload(r1) == _payload(r2)
    assert r2.cost == {"rescacheHits": 1}, r2.cost
    assert server.lane.dispatch_count == d1  # zero device work
    snap = server.result_cache.snapshot()
    assert snap["hits"] == 1 and snap["puts"] >= 1
    # distinct literals are distinct entries — never cross-served
    r3 = broker.handle_pql("SELECT sum(metInt), count(*) FROM testTable WHERE dimInt > 1000")
    assert "rescacheHits" not in r3.cost


def test_cache_disabled_by_default():
    broker = _build_stack(pipeline=True)
    server = broker.local_servers[0]
    q = "SELECT count(*) FROM testTable"
    for _ in range(2):
        assert not broker.handle_pql(q).exceptions
    assert server.result_cache.snapshot()["puts"] == 0


def test_segment_set_change_invalidates_cache(monkeypatch):
    monkeypatch.setenv("PINOT_TPU_RESULT_CACHE", "1")
    broker = _build_stack(pipeline=True)
    server = broker.local_servers[0]
    q = "SELECT count(*) FROM testTable"
    r1 = broker.handle_pql(q)
    assert not r1.exceptions
    assert server.result_cache.entry_count() == 1
    schema = make_test_schema(with_mv=False)
    extra = build_segment(schema, random_rows(schema, 50, seed=4), "testTable", "btX")
    server.add_segment("testTable_OFFLINE", extra)
    assert server.result_cache.entry_count() == 0  # staleness fence
    # the next query re-executes (no hit) even though the broker still
    # routes the original cover — the fence dropped the entry eagerly
    r2 = broker.handle_pql(q)
    assert "rescacheHits" not in r2.cost
    assert r2.num_docs_scanned == r1.num_docs_scanned


def test_cache_invalidated_by_llc_offset_advance(monkeypatch, tmp_path):
    """ISSUE 13 acceptance: a cached realtime answer is dropped the
    moment the covering LLC consume offset advances — a stale answer is
    impossible, and the follow-up query sees the new rows."""
    from pinot_tpu.realtime.stream import MemoryStreamProvider
    from pinot_tpu.tools.cluster_harness import InProcessCluster

    from tests.test_realtime import make_row, rsvp_schema

    monkeypatch.setenv("PINOT_TPU_RESULT_CACHE", "1")
    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path))
    try:
        schema = rsvp_schema()
        stream = MemoryStreamProvider(num_partitions=1)
        physical = cluster.add_realtime_table(schema, stream, rows_per_segment=500)
        for i in range(120):
            stream.produce(make_row(i))
        from pinot_tpu.realtime.llc import make_segment_name

        seg0 = make_segment_name(physical, 0, 0)
        (dm,) = cluster.controller.realtime_manager.consumers_of(seg0)
        dm.consume_step(max_rows=30)

        q = "SELECT count(*) FROM meetupRsvp"
        server = cluster.servers[0]
        r1 = cluster.query(q)
        assert r1.num_docs_scanned == 30
        r2 = cluster.query(q)
        assert r2.num_docs_scanned == 30
        assert r2.cost.get("rescacheHits") == 1, r2.cost
        assert server.result_cache.entry_count() >= 1

        # the LLC offset advances -> the cached entry is DROPPED (not
        # merely unreachable), and the next query answers fresh
        evicted_before = server.result_cache.snapshot()["staleEvictions"]
        dm.consume_step(max_rows=20)
        snap = server.result_cache.snapshot()
        assert snap["entries"] == 0
        assert snap["staleEvictions"] > evicted_before
        r3 = cluster.query(q)
        assert "rescacheHits" not in r3.cost
        assert r3.num_docs_scanned == 50  # the fresh watermark, never stale
    finally:
        cluster.stop()


def test_explain_reports_batching_decision(monkeypatch):
    """EXPLAIN's device node carries the batching decision (batched /
    batchMax / windowMs / cacheHit), and EXPLAIN ANALYZE annotates the
    actuals off its own execution."""
    monkeypatch.setenv("PINOT_TPU_RESULT_CACHE", "1")
    monkeypatch.setenv("PINOT_TPU_BITSLICED", "0")  # pin the scan tier so the batching node appears
    broker = _build_stack(pipeline=True)
    q = "SELECT sum(metInt), count(*) FROM testTable WHERE dimInt > 4800"
    plain = broker.handle_pql("EXPLAIN " + q)
    assert not plain.exceptions, plain.exceptions
    dev = plain.explain["servers"][0].get("device")
    assert dev is not None and "batching" in dev, plain.explain
    b = dev["batching"]
    assert b["batched"] is True
    assert b["batchMax"] > 1
    assert b["windowMs"] >= 0
    assert b["cacheHit"] is False  # nothing executed yet
    # execute (fills the cache) + hit it once, then EXPLAIN sees the
    # entry standing by
    assert not broker.handle_pql(q).exceptions
    hit = broker.handle_pql(q)
    assert hit.cost.get("rescacheHits") == 1, hit.cost
    again = broker.handle_pql("EXPLAIN " + q)
    assert again.explain["servers"][0]["device"]["batching"]["cacheHit"] is True
    analyze = broker.handle_pql("EXPLAIN ANALYZE " + q)
    ab = analyze.explain["servers"][0]["device"]["batching"]
    assert ab["actualBatchSize"] >= 1
    assert "actualCacheHit" not in ab  # ANALYZE always executes; the
    # standing-entry `cacheHit` probe is the cache signal
    # /debug/plans carries the per-shape batch/cache view
    server = broker.local_servers[0]
    plans = server.plan_stats.snapshot(top=10)["plans"]
    assert all("batching" in p for p in plans)
    assert any(p["batching"]["cacheHits"] >= 1 for p in plans), plans
