"""Kafka binary wire protocol (realtime/kafka.py): codec round trips,
client vs the protocol-compat shim over real sockets, and LLC ingestion
through the Kafka-protocol client.

Reference parity: ``SimpleConsumerWrapper.java`` (Metadata/ListOffsets/
Fetch against a real broker's wire protocol) — here implemented from
the protocol spec, tested against the shim serving the same bytes a
Kafka 0.8+ broker would."""
import json

import pytest

from pinot_tpu.realtime.kafka import (
    EARLIEST,
    LATEST,
    KafkaProtocolShim,
    KafkaStreamProvider,
    KafkaWireClient,
    decode_message_set,
    encode_message,
)
from pinot_tpu.realtime.netstream import NetworkStreamProvider, StreamBrokerServer


# -- codec level -------------------------------------------------------


def test_message_set_round_trip():
    data = b"".join(
        encode_message(i, json.dumps({"i": i}).encode()) for i in range(5)
    )
    out = decode_message_set(data)
    assert [o for o, _, _ in out] == list(range(5))
    assert json.loads(out[3][2]) == {"i": 3}


def test_message_set_truncated_tail_dropped():
    data = b"".join(encode_message(i, b"x" * 100) for i in range(3))
    out = decode_message_set(data[:-30])  # cut mid-message
    assert [o for o, _, _ in out] == [0, 1]


def test_message_set_crc_checked():
    data = bytearray(encode_message(0, b"payload"))
    data[-2] ^= 0xFF  # corrupt the value
    with pytest.raises(ValueError, match="CRC"):
        decode_message_set(bytes(data))


# -- client vs shim over real sockets ---------------------------------


@pytest.fixture()
def kafka_stack():
    sb = StreamBrokerServer()
    sb.start()
    host, port = sb.address
    producer = NetworkStreamProvider(host, port, "ktopic")
    producer.create_topic(2)
    shim = KafkaProtocolShim(sb).start()
    try:
        yield sb, producer, shim
    finally:
        shim.stop()
        sb.stop()


def test_metadata_list_offsets_fetch(kafka_stack):
    sb, producer, shim = kafka_stack
    for i in range(10):
        producer.produce({"i": i}, partition=i % 2)

    host, port = shim.address
    client = KafkaWireClient(host, port)
    meta = client.metadata(["ktopic"])
    assert len(meta["topics"]["ktopic"]["partitions"]) == 2
    assert meta["brokers"][0]["port"] == port

    assert client.list_offsets("ktopic", 0, EARLIEST) == [0]
    assert client.list_offsets("ktopic", 0, LATEST) == [5]

    msgs = client.fetch("ktopic", 0, 0)
    assert [o for o, _, _ in msgs] == list(range(5))
    assert json.loads(msgs[0][2]) == {"i": 0}

    # fetch from a mid offset
    msgs = client.fetch("ktopic", 1, 3)
    assert [o for o, _, _ in msgs] == [3, 4]

    # out of range
    with pytest.raises(IndexError):
        client.fetch("ktopic", 0, 99)
    client.close()


def test_fetch_respects_max_bytes(kafka_stack):
    sb, producer, shim = kafka_stack
    for i in range(20):
        producer.produce({"pad": "z" * 200, "i": i}, partition=0)
    host, port = shim.address
    client = KafkaWireClient(host, port)
    msgs = client.fetch("ktopic", 0, 0, max_bytes=700)
    assert 0 < len(msgs) < 20  # bounded batch, no truncated-garbage rows
    assert msgs[0][0] == 0
    client.close()


def test_stream_provider_interface(kafka_stack):
    sb, producer, shim = kafka_stack
    for i in range(7):
        producer.produce({"i": i}, partition=i % 2)
    host, port = shim.address
    sp = KafkaStreamProvider(host, port, "ktopic")
    assert sp.partition_count() == 2
    rows, nxt = sp.fetch(0, 0, max_rows=100)
    assert [r["i"] for r in rows] == [0, 2, 4, 6]
    assert nxt == 4
    assert sp.latest_offset(1) == 3
    # descriptor round trip (controller recovery path)
    from pinot_tpu.realtime.stream import describe_stream, stream_from_descriptor

    desc = describe_stream(sp)
    assert desc["type"] == "kafka"
    sp2 = stream_from_descriptor(desc)
    assert sp2.latest_offset(0) == 4


# -- LLC ingestion through the wire client ----------------------------


def test_llc_consumes_through_kafka_protocol(kafka_stack, tmp_path):
    from pinot_tpu.tools.cluster_harness import InProcessCluster
    from pinot_tpu.realtime.llc import RESP_KEEP, make_segment_name
    from tests.test_realtime import make_row, rsvp_schema

    sb, producer, shim = kafka_stack
    host, port = shim.address

    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path))
    schema = rsvp_schema()
    stream = KafkaStreamProvider(host, port, "ktopic")
    physical = cluster.add_realtime_table(schema, stream, rows_per_segment=50)

    for i in range(70):
        producer.produce(make_row(i), partition=i % 2)

    seg0 = make_segment_name(physical, 0, 0)
    dm = cluster.controller.realtime_manager.consumers_of(seg0)[0]
    dm.consume_step(max_rows=1000)
    seg1 = make_segment_name(physical, 1, 0)
    dm1 = cluster.controller.realtime_manager.consumers_of(seg1)[0]
    dm1.consume_step(max_rows=1000)

    resp = cluster.query("SELECT count(*) FROM meetupRsvp")
    assert resp.num_docs_scanned == 70

    # partition 0 sealed at the 35-row... below threshold: force another
    # round of production to cross the 50-row threshold and commit
    for i in range(70, 140):
        producer.produce(make_row(i), partition=i % 2)
    dm.consume_step(max_rows=1000)
    assert dm.threshold_reached
    assert dm.try_commit() == RESP_KEEP

    # committed offsets recorded from the Kafka-protocol stream
    info = cluster.controller.resources.get_segment_metadata(physical, seg0)
    assert info["metadata"].custom["startOffset"] == 0
    assert info["metadata"].custom["endOffset"] == 50


def test_oversized_message_grows_and_progresses(kafka_stack):
    """A message larger than the fetch max_bytes must not livelock the
    consumer: the truncated empty MessageSet triggers max_bytes growth
    and retry (real-broker SimpleConsumer behavior)."""
    sb, producer, shim = kafka_stack
    producer.produce({"big": "x" * 50_000}, partition=0)
    producer.produce({"i": 1}, partition=0)
    host, port = shim.address
    client = KafkaWireClient(host, port)
    msgs = client.fetch("ktopic", 0, 0, max_bytes=1024)  # << message size
    assert msgs and msgs[0][0] == 0
    assert len(json.loads(msgs[0][2])["big"]) == 50_000
    client.close()

    sp = KafkaStreamProvider(host, port, "ktopic")
    rows, nxt = sp.fetch(0, 0, max_rows=10)
    assert len(rows) == 2 and nxt == 2


def test_gzip_compressed_message_set():
    """A gzip wrapper message (attrs codec=1) decodes to its inner
    messages — what a real 0.8 broker returns for a gzip producer."""
    import gzip as _gzip
    import struct

    from pinot_tpu.realtime.kafka import _signed_crc

    inner = b"".join(encode_message(i, json.dumps({"i": i}).encode()) for i in range(3))
    compressed = _gzip.compress(inner)
    body = struct.pack(">bb", 0, 1) + struct.pack(">i", -1) + struct.pack(
        ">i", len(compressed)
    ) + compressed
    msg = struct.pack(">i", _signed_crc(body)) + body
    wrapper = struct.pack(">qi", 2, len(msg)) + msg
    out = decode_message_set(wrapper)
    assert [o for o, _, _ in out] == [0, 1, 2]
    assert json.loads(out[2][2]) == {"i": 2}

    # unsupported codecs fail loudly, not with a row-decoder crash
    body2 = struct.pack(">bb", 0, 4) + struct.pack(">i", -1) + struct.pack(">i", 1) + b"x"
    msg2 = struct.pack(">i", _signed_crc(body2)) + body2
    with pytest.raises(ValueError, match="compression codec 4"):
        decode_message_set(struct.pack(">qi", 0, len(msg2)) + msg2)


# -- consumer-group protocol (0.9+ coordinator APIs) -------------------


def test_group_protocol_join_sync_heartbeat(kafka_stack):
    from pinot_tpu.realtime.kafka_group import KafkaGroupConsumer

    sb, producer, shim = kafka_stack
    for i in range(20):
        producer.produce({"i": i}, partition=i % 2)
    host, port = shim.address

    c1 = KafkaGroupConsumer(host, port, "ktopic", group="g1", consumer_id="a")
    a1 = c1.join()
    assert a1 == [0, 1]  # sole member owns everything

    rows = c1.poll()
    assert len(rows) == 20
    assert c1.commit()
    assert c1.committed_offsets() == {0: 10, 1: 10}

    # second member joins: first member's next poll sees the rebalance,
    # revoke-commits, rejoins; the range assignment splits partitions
    c2 = KafkaGroupConsumer(host, port, "ktopic", group="g1", consumer_id="b")
    import threading

    a2_box = {}
    t = threading.Thread(target=lambda: a2_box.update(a=c2.join()))
    t.start()
    # keep polling: c1's heartbeat sees REBALANCE_IN_PROGRESS once c2's
    # join registers, revoke-commits, and rejoins through the barrier
    import time as _time

    for _ in range(100):
        c1.poll()
        if not t.is_alive():
            break
        _time.sleep(0.05)
    t.join(timeout=10)
    assert not t.is_alive()
    both = sorted(c1.assignment + a2_box["a"])
    assert both == [0, 1]
    assert len(c1.assignment) == 1 and len(a2_box["a"]) == 1
    c1.close()
    c2.close()


def test_group_offsets_survive_membership(kafka_stack):
    from pinot_tpu.realtime.kafka_group import KafkaGroupConsumer

    sb, producer, shim = kafka_stack
    for i in range(10):
        producer.produce({"i": i}, partition=0)
    host, port = shim.address
    c = KafkaGroupConsumer(host, port, "ktopic", group="g2", consumer_id="a")
    c.join()
    c.poll()
    assert c.commit()
    c.close()
    # a fresh member resumes from the committed offsets
    c2 = KafkaGroupConsumer(host, port, "ktopic", group="g2", consumer_id="b")
    c2.join()
    assert c2.positions.get(0) == 10
    assert c2.poll() == []
    c2.close()


def test_hlc_through_kafka_group_protocol(kafka_stack):
    """The full HLC ingestion mode over the Kafka wire protocol: the
    quickstart's multi-process cluster consumes with consumer groups
    coordinated by JoinGroup/SyncGroup/Heartbeat."""
    from pinot_tpu.tools.quickstart import run_network_realtime_quickstart

    count = run_network_realtime_quickstart(
        num_events=300,
        verbose=False,
        consumer_type="highlevel",
        stream_protocol="kafka",
    )
    assert count >= 300


def test_snappy_codec_round_trip():
    """Snappy-compressed wrapper messages (codec=2, incl. snappy-java
    xerial framing) decode — the common 0.8-era producer default."""
    import struct

    from pinot_tpu.realtime.kafka import _signed_crc
    from pinot_tpu.utils.snappy import compress, decompress

    # pure codec round trips, incl. back-references from a real encoder
    # shape (literal-only encoding is valid snappy)
    for payload in (b"", b"abc", b"x" * 100000, bytes(range(256)) * 300):
        assert decompress(compress(payload)) == payload
    # hand-built copy tags: literal "abcd" + 1-byte-offset copy len 4
    blob = bytes([8, (4 - 1) << 2]) + b"abcd" + bytes([1, 4])
    assert decompress(blob) == b"abcdabcd"

    inner = b"".join(encode_message(i, json.dumps({"i": i}).encode()) for i in range(4))
    compressed = compress(inner)
    # xerial framing variant
    xerial = b"\x82SNAPPY\x00" + struct.pack(">ii", 1, 1) + struct.pack(">i", len(compressed)) + compressed
    for wire in (compressed, xerial):
        body = struct.pack(">bb", 0, 2) + struct.pack(">i", -1) + struct.pack(">i", len(wire)) + wire
        msg = struct.pack(">i", _signed_crc(body)) + body
        out = decode_message_set(struct.pack(">qi", 3, len(msg)) + msg)
        assert [o for o, _, _ in out] == [0, 1, 2, 3]
        assert json.loads(out[3][2]) == {"i": 3}


def test_lz4_codec_round_trip():
    """LZ4 wrapper messages (codec=3, standard frame format incl. the
    KAFKA-3160 unverifiable header checksum) decode to inner messages."""
    import struct

    from pinot_tpu.realtime.kafka import _signed_crc
    from pinot_tpu.utils import lz4

    # block round trips through the greedy compressor: empty, short
    # (literal-only), RLE (overlapping match), structured repeats, and
    # incompressible bytes
    rng = __import__("random").Random(7)
    payloads = [
        b"",
        b"abc",
        b"x" * 100000,
        bytes(range(256)) * 300,
        b"the quick brown fox " * 4000,
        bytes(rng.randrange(256) for _ in range(5000)),
    ]
    for payload in payloads:
        assert lz4.decompress_block(lz4.compress_block(payload)) == payload
        assert lz4.decompress(lz4.compress_frame(payload)) == payload

    # hand-built block with a known shape: 4 literals then an
    # overlapping offset-4 match of length 8 -> "abcd" * 3, ending in a
    # >=5-byte literal tail per the spec's end conditions
    blob = bytes([0x44]) + b"abcd" + bytes([0x04, 0x00]) + bytes([0x50]) + b"abcde"
    assert lz4.decompress_block(blob) == b"abcd" * 3 + b"abcde"

    # corrupt inputs fail loudly
    with pytest.raises(ValueError, match="zero match offset"):
        lz4.decompress_block(bytes([0x14]) + b"a" + bytes([0x00, 0x00]))
    with pytest.raises(ValueError, match="outside window"):
        lz4.decompress_block(bytes([0x14]) + b"a" + bytes([0x09, 0x00]))
    with pytest.raises(ValueError, match="bad frame magic"):
        lz4.decompress_frame(b"\x00\x00\x00\x00rest")

    # a skippable frame before the real one is skipped
    skip = struct.pack("<II", 0x184D2A50, 3) + b"pad"
    assert lz4.decompress(skip + lz4.compress_frame(b"hello world!" * 10)) == b"hello world!" * 10

    # wrapper MessageSet through the Kafka decoder
    inner = b"".join(encode_message(i, json.dumps({"i": i}).encode()) for i in range(4))
    wire = lz4.compress_frame(inner)
    body = struct.pack(">bb", 0, 3) + struct.pack(">i", -1) + struct.pack(">i", len(wire)) + wire
    msg = struct.pack(">i", _signed_crc(body)) + body
    out = decode_message_set(struct.pack(">qi", 3, len(msg)) + msg)
    assert [o for o, _, _ in out] == [0, 1, 2, 3]
    assert json.loads(out[3][2]) == {"i": 3}


def test_lz4_xxh32_and_header_checksum():
    """xxh32 matches the published reference vectors, and emitted
    frames carry the spec-correct header checksum byte."""
    import struct

    from pinot_tpu.utils import lz4

    assert lz4.xxh32(b"") == 0x02CC5D05
    assert lz4.xxh32(b"a") == 0x550D7456
    assert lz4.xxh32(b"abc") == 0x32D153FF
    assert lz4.xxh32(b"a" * 100) == lz4.xxh32(b"a" * 100)  # deterministic
    assert lz4.xxh32(b"abc", seed=1) != lz4.xxh32(b"abc")

    frame = lz4.compress_frame(b"payload bytes " * 50)
    flg = frame[4]
    hdr_len = 2 + (8 if flg & 0x08 else 0)
    descriptor = frame[4 : 4 + hdr_len]
    assert frame[4 + hdr_len] == (lz4.xxh32(descriptor) >> 8) & 0xFF


def test_lz4_linked_blocks_and_bounds():
    """Linked-block frames (librdkafka's LZ4F default) back-reference
    prior blocks' output; bounds trip BEFORE any oversized copy runs."""
    import struct

    from pinot_tpu.utils import lz4

    # hand-built 2-block frame: block 2's first match reaches 8 bytes
    # back into block 1's output (legal only in linked mode)
    blk2 = bytes([0x04, 0x08, 0x00, 0x20]) + b"XY"
    body = (
        struct.pack("<I", 0x80000008) + b"abcdefgh"
        + struct.pack("<I", len(blk2)) + blk2
        + struct.pack("<I", 0)
    )

    def frame(flg):
        return struct.pack("<I", lz4.FRAME_MAGIC) + bytes([flg, 0x40, 0]) + body

    assert lz4.decompress_frame(frame(0x40)) == b"abcdefghabcdefghXY"  # linked
    with pytest.raises(ValueError, match="outside window"):
        lz4.decompress_frame(frame(0x60))  # independent: offset invalid

    # a declared 2GB overlapping match trips the bound before copying
    ext = b"\xff" * 8000 + b"\x00"  # ~2M extra match length
    bomb = bytes([0x1F]) + b"a" + bytes([0x01, 0x00]) + ext
    with pytest.raises(ValueError, match="exceeds declared size"):
        lz4.decompress_block(bomb, max_output=1000)
    # same shape without the cap decodes (offset-1 RLE), sized right
    n = 4 + 15 + 255 * 8000
    assert lz4.decompress_block(bomb) == b"a" * (1 + n)


@pytest.mark.parametrize("codec", ["gzip", "snappy", "lz4"])
def test_compressed_fetch_end_to_end(codec):
    """Full consume path over real sockets with the shim serving
    producer-style COMPRESSED wrapper batches: every codec a 0.8/0.9
    producer can emit decodes through KafkaStreamProvider."""
    sb = StreamBrokerServer()
    sb.start()
    try:
        host, port = sb.address
        producer = NetworkStreamProvider(host, port, "ctopic")
        producer.create_topic(1)
        for i in range(25):
            producer.produce({"i": i}, partition=0)
        shim = KafkaProtocolShim(sb, compression=codec).start()
        try:
            k_host, k_port = shim.address
            sp = KafkaStreamProvider(k_host, k_port, "ctopic")
            rows, nxt = sp.fetch(0, 0, max_rows=100)
            assert [r["i"] for r in rows] == list(range(25))
            assert nxt == 25
            # mid-stream offset: wrapper decode must resume exactly
            rows2, nxt2 = sp.fetch(0, 10, max_rows=100)
            assert [r["i"] for r in rows2] == list(range(10, 25))
            assert nxt2 == 25
        finally:
            shim.stop()
    finally:
        sb.stop()


def test_compressed_wrapper_respects_max_bytes():
    """An over-budget compressed wrapper is cut at max_bytes like the
    raw path, so the client's grow+retry loop engages instead of the
    shim overrunning the consumer's stated budget."""
    sb = StreamBrokerServer()
    sb.start()
    try:
        host, port = sb.address
        producer = NetworkStreamProvider(host, port, "btopic")
        producer.create_topic(1)
        for i in range(5):
            producer.produce({"i": i, "pad": "x" * 200}, partition=0)
        shim = KafkaProtocolShim(sb, compression="gzip").start()
        try:
            k_host, k_port = shim.address
            c = KafkaWireClient(k_host, k_port)
            # tiny budget: one roundtrip returns only cut bytes, no
            # decodable message — the grow trigger
            msgs, raw_len, _ = c._fetch_once("btopic", 0, 0, 40)
            assert msgs == [] and 0 < raw_len <= 40
            # the provider's grow+retry still lands every row
            sp = KafkaStreamProvider(k_host, k_port, "btopic")
            rows, nxt = sp.fetch(0, 0, max_rows=100)
            assert [r["i"] for r in rows] == list(range(5)) and nxt == 5
        finally:
            shim.stop()
    finally:
        sb.stop()


def test_real_broker_wrapper_below_offset_filtered():
    """A REAL 0.8/0.9 broker serves stored compressed wrappers whose
    inner set can start BEFORE the requested offset; the client must
    skip those inner messages or they re-ingest as duplicates."""
    import struct

    from pinot_tpu.realtime.kafka import _Reader, compress_message_set

    inner = b"".join(encode_message(i, json.dumps({"i": i}).encode()) for i in range(5))
    wrapper = encode_message(4, compress_message_set(inner, "gzip"), codec=1)

    class FakeClient(KafkaWireClient):
        def _roundtrip(self, api, body):
            resp = (
                struct.pack(">i", 1)
                + struct.pack(">h", len(b"wtopic")) + b"wtopic"
                + struct.pack(">i", 1)
                + struct.pack(">i", 0)       # partition
                + struct.pack(">h", 0)       # err
                + struct.pack(">q", 5)       # high watermark
                + struct.pack(">i", len(wrapper)) + wrapper
            )
            return _Reader(resp)

    c = FakeClient("nohost", 0)
    msgs, raw_len, decoded_any = c._fetch_once("wtopic", 0, 2, 1 << 20)
    assert [o for o, _, _ in msgs] == [2, 3, 4]  # 0 and 1 filtered
    assert raw_len == len(wrapper) and decoded_any


# -- columnar partitions over the row protocol ------------------------


def test_columnar_partition_fetch_is_typed_error(kafka_stack):
    """A populated columnar partition must reject Kafka-protocol reads
    with the typed columnar error (mirroring the netstream broker's
    rejection), NOT silently report high-watermark 0 — consumers would
    idle forever believing the partition empty."""
    import numpy as np

    from pinot_tpu.realtime.kafka import ColumnarPartitionError

    sb, producer, shim = kafka_stack
    producer.produce_columns({"i": np.arange(8, dtype=np.int64)}, partition=0)
    producer.produce({"i": 99}, partition=1)  # row partition, same topic

    host, port = shim.address
    client = KafkaWireClient(host, port)
    with pytest.raises(ColumnarPartitionError, match="columnar partition"):
        client.fetch("ktopic", 0, 0)
    with pytest.raises(ColumnarPartitionError, match="columnar partition"):
        client.list_offsets("ktopic", 0, LATEST)
    # the row-mode partition of the SAME topic keeps serving normally
    assert client.list_offsets("ktopic", 1, LATEST) == [1]
    assert [o for o, _, _ in client.fetch("ktopic", 1, 0)] == [0]
