"""End-to-end query observability: distributed trace trees, unified
metrics exposition, the slow-query log, and the metric-name lint.

Covers the PR-4 acceptance bar: a trace=true query returns ONE merged
span tree in traceInfo (broker phases + per-server scheduler/lane/
device phases) on both the in-process and networked cluster paths; a
failover query's trace carries the retry + failover spans; /metrics on
broker, server, and controller serves valid Prometheus text; the
slow-query ring rolls over; and the disabled-trace path allocates zero
spans.
"""
import json
import re
import threading
import time
import urllib.request

import pytest

from pinot_tpu.broker.broker import BrokerHttpServer, BrokerRequestHandler
from pinot_tpu.broker.routing import RoutingTableProvider
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.server.instance import ServerInstance
from pinot_tpu.tools.datagen import make_test_schema, random_rows
from pinot_tpu.transport.local import LocalTransport

TABLE = "testTable"


def _spans(trace_info):
    """Flatten {scopes: {scope: [span...]}} -> [(scope, span)]."""
    out = []
    for scope, spans in trace_info.get("scopes", {}).items():
        for s in spans:
            out.append((scope, s))
    return out


def _span_names(trace_info, scope_prefix=""):
    return {
        s["span"]
        for scope, s in _spans(trace_info)
        if scope.startswith(scope_prefix)
    }


def _assert_single_tree(trace_info):
    """Every span's parent resolves and every root chain reaches the
    broker's root query span — one connected tree, not islands."""
    by_id = {s["id"]: s for _, s in _spans(trace_info)}
    roots = [s for _, s in _spans(trace_info) if s["parent"] is None]
    assert len(roots) == 1, f"expected one root, got {roots}"
    for _, s in _spans(trace_info):
        if s["parent"] is not None:
            assert s["parent"] in by_id, f"dangling parent on {s}"
        # chain terminates at the root (cycle-free)
        seen, cur = set(), s
        while cur["parent"] is not None:
            assert cur["id"] not in seen
            seen.add(cur["id"])
            cur = by_id[cur["parent"]]
        assert cur is roots[0]


# ---------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def cluster():
    """2 servers, every segment replicated on both (failover-capable)."""
    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 400, seed=21, cardinality=8)
    seg1 = build_segment(schema, rows[:200], TABLE, "obsSeg1")
    seg2 = build_segment(schema, rows[200:], TABLE, "obsSeg2")

    servers = {}
    transport = LocalTransport()
    for name in ("obsA", "obsB"):
        s = ServerInstance(name)
        s.add_segment(TABLE, seg1)
        s.add_segment(TABLE, seg2)
        transport.register((name, 0), s.handle_request)
        servers[name] = s
    routing = RoutingTableProvider()
    routing.update(
        TABLE,
        {
            "obsSeg1": {"obsA": "ONLINE", "obsB": "ONLINE"},
            "obsSeg2": {"obsA": "ONLINE", "obsB": "ONLINE"},
        },
    )
    broker = BrokerRequestHandler(
        transport,
        {"obsA": ("obsA", 0), "obsB": ("obsB", 0)},
        routing=routing,
        timeout_ms=30_000,
        retry_attempts=2,
        retry_backoff_ms=1.0,
    )
    return broker, servers, transport


# ------------------------------------------------------------- trace trees
def test_trace_tree_in_process(cluster):
    broker, servers, _ = cluster
    resp = broker.handle_pql(f"SELECT sum(metInt) FROM {TABLE}", trace=True)
    assert not resp.exceptions
    ti = resp.trace_info
    assert ti["traceId"] == resp.request_id
    _assert_single_tree(ti)
    # broker phases present
    broker_spans = _span_names(ti, broker.name)
    assert {"query", "parse", "route", "scatterGather", "serverAttempt", "reduce"} <= broker_spans
    # per-server scheduler + executor phases present, nested under the
    # attempt spans (single-tree assertion above proves the nesting)
    for sname in servers:
        names = _span_names(ti, sname)
        assert {"serverQuery", "queueWait", "planAndExecute", "finalize"} <= names, (
            sname, names,
        )
    # the server spans carry the broker's requestId tag
    tagged = [
        s for scope, s in _spans(ti)
        if s["span"] == "serverQuery"
    ]
    assert tagged and all(
        s["tags"]["requestId"] == resp.request_id for s in tagged
    )


def test_trace_disabled_allocates_no_spans(cluster, monkeypatch):
    """With tail sampling opted out (the PINOT_TPU_TAIL_TRACE=0
    contract), an untraced query allocates zero spans — the original
    PR 4 bar.  The always-on default's own zero-overhead contract (no
    retained-entry work on the not-retained path) lives in
    test_slo_tails.py."""
    broker, _, _ = cluster
    import pinot_tpu.utils.trace as trace_mod

    monkeypatch.setattr(broker.tail, "enabled", False)
    broker.handle_pql(f"SELECT count(*) FROM {TABLE}")  # warm
    before = trace_mod.SPAN_ALLOCATIONS
    resp = broker.handle_pql(f"SELECT count(*) FROM {TABLE}")
    assert not resp.exceptions
    assert trace_mod.SPAN_ALLOCATIONS == before, (
        "untraced handle-request path allocated spans"
    )
    assert resp.trace_info == {}


def test_trace_shows_retry_and_failover(cluster):
    """A downed replica's attempt fails, the broker fails over, and the
    merged trace shows BOTH: the error attempt and the failover event
    plus the replacement attempt that succeeded."""
    broker, _, transport = cluster
    transport.set_down(("obsA", 0))
    try:
        # routing picks replicas randomly: retry until a batch actually
        # landed on the downed server (usually the first query)
        for _ in range(20):
            resp = broker.handle_pql(f"SELECT count(*) FROM {TABLE}", trace=True)
            if resp.num_retries >= 1:
                break
    finally:
        transport.set_down(("obsA", 0), down=False)
        broker.health.mark_alive("obsA")
    assert not resp.partial_response and resp.num_docs_scanned == 400
    assert resp.num_retries >= 1
    ti = resp.trace_info
    _assert_single_tree(ti)
    attempts = [s for _, s in _spans(ti) if s["span"] == "serverAttempt"]
    statuses = {s["tags"]["status"] for s in attempts}
    assert "error" in statuses and "ok" in statuses, attempts
    events = [s for _, s in _spans(ti) if s["span"] == "failover"]
    assert events and events[0]["tags"]["fromServer"] == "obsA"
    # reissued attempts are tagged with their reissue count
    assert any(s["tags"]["reissues"] >= 1 for s in attempts if s["tags"]["status"] == "ok")


def test_trace_shows_device_host_failover():
    """A transient device fault heals transparently (PR 3) and the
    traced query shows the deviceFailures/deviceRetries events."""
    from pinot_tpu.common.faults import DeviceFaultInjector
    from pinot_tpu.tools.cluster_harness import single_server_broker

    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 300, seed=3)
    seg = build_segment(schema, rows, TABLE, "healTraceSeg")
    inj = DeviceFaultInjector(seed=7)
    broker = single_server_broker(TABLE, [seg], device_fault_injector=inj)
    try:
        pql = f"SELECT sum(metInt) FROM {TABLE}"
        want = broker.handle_pql(pql)
        assert not want.exceptions
        inj.fail_next(1, retryable=True)
        resp = broker.handle_pql(pql, trace=True)
        assert not resp.exceptions
        names = _span_names(resp.trace_info)
        assert "deviceFailures" in names and "deviceRetries" in names, names
        _assert_single_tree(resp.trace_info)
    finally:
        broker.local_servers[0].shutdown()


def test_request_id_globally_unique_and_echoed(cluster):
    broker, _, _ = cluster
    other = BrokerRequestHandler(
        LocalTransport(), {}, name=broker.name  # same display name!
    )
    r1 = broker.handle_pql(f"SELECT count(*) FROM {TABLE}")
    r2 = broker.handle_pql(f"SELECT count(*) FROM {TABLE}")
    r3 = other.handle_pql("SELECT count(*) FROM nosuchtable")
    ids = {r1.request_id, r2.request_id, r3.request_id}
    assert len(ids) == 3
    assert all(i.startswith(broker.name + "-") for i in ids)
    assert r1.to_json()["requestId"] == r1.request_id
    # error responses echo the id too (correlation with /debug/queries)
    assert r3.to_json()["requestId"] == r3.request_id


# ----------------------------------------------------------- exposition
# one metric sample line: name{labels} value
_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*\})? [^ ]+$"
)


def _assert_valid_prometheus(text: str, required_substrings=()):
    assert text.endswith("\n")
    families = set()
    for line in text.rstrip("\n").split("\n"):
        if line.startswith("# TYPE "):
            name = line.split()[2]
            assert name not in families, f"duplicate TYPE for {name}"
            families.add(name)
            continue
        if line.startswith("#"):
            continue
        assert _PROM_SAMPLE.match(line), f"bad exposition line: {line!r}"
    for sub in required_substrings:
        assert sub in text, f"{sub} missing from exposition"


def test_prometheus_text_valid_and_covers_key_series(cluster):
    broker, servers, _ = cluster
    from pinot_tpu.utils.metrics import prometheus_text

    broker.handle_pql(f"SELECT count(*) FROM {TABLE}")
    btext = prometheus_text(broker.metrics)
    _assert_valid_prometheus(
        btext,
        required_substrings=[
            "pinot_tpu_broker_queries_total",
            "pinot_tpu_broker_scatterGather_ms",
        ],
    )
    server = next(iter(servers.values()))
    text = server.metrics_text()
    _assert_valid_prometheus(
        text,
        required_substrings=[
            "pinot_tpu_server_queries_total",
            "pinot_tpu_server_lane_depth",  # lane depth gauge
            "pinot_tpu_server_phase_schedulerWait_ms",
        ],
    )
    # every timer summary family carries _count and _sum samples, so an
    # external scraper can do rate x latency math (ISSUE 11 satellite)
    for exposition in (btext, text):
        summaries = [
            line.split()[2]
            for line in exposition.splitlines()
            if line.startswith("# TYPE ") and line.endswith(" summary")
        ]
        assert summaries, "no timer families in exposition"
        for fam in summaries:
            assert f"{fam}_count{{" in exposition, f"{fam} missing _count"
            assert f"{fam}_sum{{" in exposition, f"{fam} missing _sum"
            assert f'{fam}{{scope="' in exposition  # quantile samples


def test_meter_windowed_rate_and_timer_interpolation():
    from pinot_tpu.utils.metrics import Meter, Timer, Gauge

    m = Meter()
    m.mark(100)
    assert m.count == 100
    assert m.rate > 0
    assert m.rate_1m >= 0  # pre-first-tick instantaneous estimate
    # after a simulated idle minute the EWMA decays instead of
    # reporting the lifetime average forever
    m._last_tick -= 120.0
    m.mark(0)
    decayed = m.rate_1m
    m._last_tick -= 600.0
    assert m.rate_1m <= decayed + 1e-9

    t = Timer()
    for v in (10.0, 20.0, 30.0, 40.0):
        t.update(v)
    # interpolated median of [10,20,30,40] = 25 (nearest-rank gave 30)
    assert t.percentile(50) == pytest.approx(25.0)
    assert t.percentile(0) == 10.0 and t.percentile(100) == 40.0
    p50, p95 = t.percentiles((50, 95))
    assert p50 == pytest.approx(25.0) and p95 == pytest.approx(38.5)

    g = Gauge()
    g.set(7)
    assert g.value == 7
    g.set_fn(lambda: 42)
    assert g.value == 42


def test_gauge_snapshot_thread_safety():
    from pinot_tpu.utils.metrics import MetricsRegistry

    reg = MetricsRegistry("t")
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            reg.gauge("g").set(i)
            reg.meter("m").mark()
            i += 1

    t = threading.Thread(target=writer)
    t.start()
    try:
        for _ in range(50):
            snap = reg.snapshot()
            assert isinstance(snap["gauges"]["g"], int)
    finally:
        stop.set()
        t.join()


def test_trace_survives_misrouted_table():
    """A traced query for a table the server doesn't host still returns
    its span tree next to the error — stale routing is exactly when an
    operator needs the server-side view."""
    from pinot_tpu.common.datatable import (
        deserialize_result,
        serialize_instance_request,
    )

    server = ServerInstance("misServer")
    payload = serialize_instance_request(
        "rid-1", "SELECT count(*) FROM ghostTable", "ghostTable", [], 10_000,
        trace=True,
    )
    res = deserialize_result(server.handle_request(payload))
    assert res.exceptions
    names = {s["span"] for s in res.trace["misServer"]}
    assert {"serverQuery", "tableNotHosted"} <= names
    server.shutdown()


# ------------------------------------------------------------ slow log
def test_slow_query_log_ring_and_threshold(monkeypatch):
    from pinot_tpu.broker.querylog import SlowQueryLog

    log = SlowQueryLog(capacity=3, threshold_ms=100.0)
    assert not log.observe({"requestId": "a", "timeUsedMs": 5.0})
    assert log.observe({"requestId": "b", "timeUsedMs": 500.0})
    assert log.observe({"requestId": "c", "timeUsedMs": 1.0, "exceptions": [200]})
    assert log.observe({"requestId": "d", "timeUsedMs": 1.0, "partialResponse": True})
    assert log.observe({"requestId": "e", "timeUsedMs": 150.0})
    snap = log.snapshot()
    assert snap["totalQueries"] == 5 and snap["totalRecorded"] == 4
    # ring holds the LAST 3, newest first
    assert [e["requestId"] for e in snap["entries"]] == ["e", "d", "c"]
    # env-var construction path
    monkeypatch.setenv("PINOT_TPU_SLOW_QUERY_MS", "7")
    monkeypatch.setenv("PINOT_TPU_SLOW_QUERY_LOG_N", "2")
    log2 = SlowQueryLog()
    assert log2.threshold_ms == 7.0 and log2.capacity == 2


def test_broker_http_debug_endpoints(cluster):
    """/metrics (Prometheus), /debug/metrics (JSON), /debug/queries on
    the broker HTTP surface; a failed query lands in the slow log with
    its requestId."""
    broker, _, _ = cluster
    http = BrokerHttpServer(broker)
    http.start()
    try:
        base = f"http://127.0.0.1:{http.port}"
        bad = json.loads(
            urllib.request.urlopen(
                base + "/query?pql=" + urllib.parse.quote("SELECT count(*) FROM nosuchtable"),
                timeout=10,
            ).read()
        )
        assert bad["exceptions"]
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            _assert_valid_prometheus(
                r.read().decode(), ["pinot_tpu_broker_queries_total"]
            )
        dbg = json.loads(urllib.request.urlopen(base + "/debug/metrics", timeout=10).read())
        assert dbg["scope"] == broker.name and "meters" in dbg
        queries = json.loads(urllib.request.urlopen(base + "/debug/queries", timeout=10).read())
        assert any(
            e["requestId"] == bad["requestId"] for e in queries["entries"]
        ), queries
    finally:
        http.stop()


# ------------------------------------------------------- networked path
def test_networked_cluster_trace_and_metrics(tmp_path):
    """Controller + networked server + networked broker as real HTTP/TCP
    endpoints (in one process): trace trees merge across the TCP
    transport, and all three roles serve Prometheus /metrics — including
    lane/selfHealing series on the server."""
    from pinot_tpu.controller.controller import Controller, ControllerHttpServer
    from pinot_tpu.broker.network_starter import NetworkedBrokerStarter
    from pinot_tpu.server.network_starter import NetworkedServerStarter

    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 300, seed=11)

    ctrl = Controller(str(tmp_path / "ctl"))
    chttp = ControllerHttpServer(ctrl)
    chttp.start()
    ctrl_url = f"http://127.0.0.1:{chttp.port}"
    server = NetworkedServerStarter(
        ctrl_url, "netObsSrv", data_dir=str(tmp_path / "srv"), poll_interval_s=0.1
    )
    broker = NetworkedBrokerStarter(ctrl_url, "netObsBrk", poll_interval_s=0.1)
    try:
        server.start()
        broker.start()
        ctrl.add_schema(schema)
        from pinot_tpu.common.tableconfig import TableConfig

        physical = ctrl.add_table(TableConfig(table_name=TABLE, table_type="OFFLINE"))
        ctrl.upload_segment(physical, build_segment(schema, rows, physical, "netObs1"))

        def _query(trace=False):
            req = urllib.request.Request(
                f"http://127.0.0.1:{broker.http.port}/query",
                data=json.dumps(
                    {"pql": f"SELECT sum(metInt) FROM {TABLE}", "trace": trace}
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())

        deadline = time.time() + 30
        out = None
        while time.time() < deadline:
            out = _query()
            if not out.get("exceptions") and out.get("numDocsScanned") == 300:
                break
            time.sleep(0.2)
        assert out and out.get("numDocsScanned") == 300, out

        out = _query(trace=True)
        ti = out["traceInfo"]
        assert ti["traceId"] == out["requestId"]
        _assert_single_tree(ti)
        assert "netObsSrv" in ti["scopes"]
        assert {"serverQuery", "planAndExecute"} <= _span_names(ti, "netObsSrv")
        assert "serverAttempt" in _span_names(ti, "netObsBrk")
        # the waterfall renders the merged tree
        from pinot_tpu.tools.trace_dump import render_waterfall

        art = render_waterfall(ti)
        assert "netObsSrv:planAndExecute" in art and "netObsBrk:query" in art

        # all three roles expose Prometheus text
        for url, needles in (
            (f"http://127.0.0.1:{broker.http.port}/metrics",
             ["pinot_tpu_broker_queries_total"]),
            (f"{server.admin.url}/metrics",
             ["pinot_tpu_server_queries_total", "pinot_tpu_server_lane_depth",
              "pinot_tpu_server_heal_", "pinot_tpu_server_lane_coalesced"]),
            (f"{ctrl_url}/metrics",
             ["pinot_tpu_controller_heartbeats_total",
              "pinot_tpu_controller_aliveServers"]),
        ):
            with urllib.request.urlopen(url, timeout=10) as r:
                text = r.read().decode()
            _assert_valid_prometheus(text)
            for n in needles:
                assert n in text, (url, n, text[:2000])

        # controller-side cluster aggregation sees broker AND server
        agg = json.loads(
            urllib.request.urlopen(ctrl_url + "/debug/clustermetrics", timeout=10).read()
        )
        assert "netObsSrv" in agg["instances"] and "netObsBrk" in agg["instances"]
        srv_entry = agg["instances"]["netObsSrv"]
        assert "selfHealing" in srv_entry["metrics"], srv_entry
        # the dashboard metrics page renders it
        with urllib.request.urlopen(ctrl_url + "/dashboard/metrics", timeout=10) as r:
            html = r.read().decode()
        assert "netObsSrv" in html and "netObsBrk" in html
    finally:
        broker.stop()
        server.stop()
        chttp.stop()
        ctrl.stop()
        server.server.shutdown()


# ------------------------------------------------------------ trace dump
def test_trace_dump_waterfall_pure():
    from pinot_tpu.tools.trace_dump import render_waterfall

    ti = {
        "traceId": "b-1",
        "scopes": {
            "b": [
                {"span": "query", "id": "b:1", "parent": None, "startMs": 0.0, "ms": 10.0},
                {"span": "scatter", "id": "b:2", "parent": "b:1", "startMs": 1.0, "ms": 8.0},
            ],
            "s": [
                {"span": "serverQuery", "id": "s:1", "parent": "b:2",
                 "startMs": 2.0, "ms": 6.0, "tags": {"requestId": "b-1"}},
            ],
        },
    }
    art = render_waterfall(ti, width=20)
    lines = art.splitlines()
    assert "total 10.000ms" in lines[0]
    assert lines[1].lstrip().startswith("b:query")
    # depth-indented child chain b:query > b:scatter > s:serverQuery
    assert lines[2].startswith("  b:scatter")
    assert lines[3].startswith("    s:serverQuery")
    assert "requestId=b-1" in lines[3]
    assert render_waterfall({"scopes": {}}) == "(empty trace)\n"


# ------------------------------------------------------------- the lint
def test_metrics_lint():
    """Tier-1 guard: every metric name used in pinot_tpu appears in the
    per-role catalogs — a typo cannot silently fork a series."""
    from pinot_tpu.tools.metrics_lint import run_lint

    problems = run_lint()
    assert problems == []


def test_metrics_lint_catches_unknown_name(tmp_path):
    pkg = tmp_path / "fakepkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        'def f(reg):\n    reg.meter("definitelyNotCatalogued").mark()\n'
    )
    from pinot_tpu.tools.metrics_lint import run_lint

    problems = run_lint(str(pkg))
    assert len(problems) == 1 and "definitelyNotCatalogued" in problems[0]
