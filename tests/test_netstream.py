"""Network stream ingestion: TCP stream broker + NetworkStreamProvider
+ LLC consumption in separate server processes, with consumer restart
resuming from committed offsets.  (Reference roles:
``SimpleConsumerWrapper.java`` / ``LLRealtimeSegmentDataManager.java:68``
— Kafka replaced by the built-in stream-broker process.)"""
import json
import os
import signal
import time

import pytest

from pinot_tpu.realtime.netstream import NetworkStreamProvider, StreamBrokerServer
from pinot_tpu.realtime.stream import describe_stream, stream_from_descriptor


def test_stream_broker_roundtrip(tmp_path):
    broker = StreamBrokerServer(log_dir=str(tmp_path / "log"))
    broker.start()
    try:
        host, port = broker.address
        p = NetworkStreamProvider(host, port, "events")
        p.create_topic(2)
        assert p.partition_count() == 2
        for i in range(10):
            p.produce({"i": i}, partition=i % 2)
        assert p.latest_offset(0) == 5
        rows, nxt = p.fetch(0, 2, 100)
        assert nxt == 5 and [r["i"] for r in rows] == [4, 6, 8]
        # descriptor roundtrip (property-store recovery path)
        d = describe_stream(p)
        p2 = stream_from_descriptor(d)
        assert p2.latest_offset(1) == 5
    finally:
        broker.stop()

    # broker restart over the same log dir: offsets survive
    broker2 = StreamBrokerServer(log_dir=str(tmp_path / "log"))
    broker2.start()
    try:
        p3 = NetworkStreamProvider(broker2.address[0], broker2.address[1], "events")
        assert p3.latest_offset(0) == 5
        rows, _ = p3.fetch(1, 0, 100)
        assert [r["i"] for r in rows] == [1, 3, 5, 7, 9]
    finally:
        broker2.stop()


# ---------------------------------------------------------------------------
# full networked realtime path: real OS processes
# ---------------------------------------------------------------------------

from tests.test_network_cluster import (  # noqa: E402
    _get,
    _post_json,
    _spawn,
    _wait_for,
)
from pinot_tpu.common.tableconfig import StreamConfig, TableConfig  # noqa: E402
from pinot_tpu.tools.datagen import make_test_schema  # noqa: E402

RTABLE = "netRt"
RPHYSICAL = "netRt_REALTIME"


@pytest.mark.slow
def test_networked_realtime_ingestion_and_restart(tmp_path):
    schema = make_test_schema(with_mv=False)
    schema.schema_name = RTABLE

    procs = []
    stream_broker = StreamBrokerServer(log_dir=str(tmp_path / "streamlog"))
    stream_broker.start()
    try:
        host, port = stream_broker.address
        producer = NetworkStreamProvider(host, port, "rtopic")
        producer.create_topic(1)

        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ctrl_port = s.getsockname()[1]
        s.close()

        def start_controller():
            return _spawn(
                ["StartController", "-port", str(ctrl_port),
                 "-data-dir", str(tmp_path / "store"), "-heartbeat-timeout", "3.0"]
            )

        ctrl_proc, ctrl_url = start_controller()
        procs.append(ctrl_proc)
        srv_proc, _ = _spawn(
            ["StartServer", "-controller", ctrl_url, "-name", "rs0",
             "-data-dir", str(tmp_path / "cache_rs0")]
        )
        procs.append(srv_proc)
        broker_proc, broker_url = _spawn(
            ["StartBroker", "-controller", ctrl_url, "-port", "0"]
        )
        procs.append(broker_proc)

        _post_json(ctrl_url + "/schemas", schema.to_json())
        config = TableConfig(
            table_name=RTABLE,
            table_type="REALTIME",
            stream=StreamConfig(
                stream_type="network",
                topic="rtopic",
                rows_per_segment=50,
                properties={"host": host, "port": port},
            ),
        )
        _post_json(ctrl_url + "/tables", config.to_json())

        def _query(pql):
            resp = _post_json(broker_url + "/query", {"pql": pql})
            assert "error" not in resp, resp
            return resp

        def _wait_sum(expected):
            # transient no-servers windows during failover surface as
            # exceptions (retriable); converge like the count waits do
            def check():
                resp = _query(f"SELECT sum(metInt) FROM {RTABLE}")
                if resp.get("exceptions") or "aggregationResults" not in resp:
                    return False
                return float(resp["aggregationResults"][0]["value"]) == expected
            return check

        def make_row(i):
            return {
                "dimStr": f"v{i % 5}",
                "dimInt": i % 7,
                "dimLong": i,
                "metInt": i,
                "metFloat": 0.5 * i,
                "metDouble": 0.25 * i,
                "daysSinceEpoch": 17000 + i,
            }

        # produce 75 rows: seg0 (50) commits, seg1 keeps consuming 25
        producer.produce_batch([make_row(i) for i in range(75)])

        def _count_is(n):
            def check():
                resp = _query(f"SELECT count(*) FROM {RTABLE}")
                return not resp.get("exceptions") and resp.get("numDocsScanned") == n
            return check

        _wait_for(_count_is(75), timeout=90, what="75 rows visible via broker")

        # seg0 committed with exact offsets
        def _seg0_committed():
            view = _get(ctrl_url + f"/tables/{RPHYSICAL}/externalview")
            return view.get(f"{RPHYSICAL}__0__0", {}).get("rs0") == "ONLINE"

        _wait_for(_seg0_committed, timeout=60, what="segment 0 committed -> ONLINE")

        # correctness through the full path
        _wait_for(_wait_sum(sum(range(75))), timeout=30, what="sum over 75 rows")

        # SIGKILL the consuming server; restart -> consumption resumes
        # from the committed offset (seg1 re-consumes its 25 rows)
        srv_proc.send_signal(signal.SIGKILL)
        srv_proc.wait(timeout=10)
        srv_proc2, _ = _spawn(
            ["StartServer", "-controller", ctrl_url, "-name", "rs0",
             "-data-dir", str(tmp_path / "cache_rs0")]
        )
        procs.append(srv_proc2)

        _wait_for(_count_is(75), timeout=90, what="rows visible after server restart")

        # keep producing: 25 more rows seal seg1 and roll to seg2
        producer.produce_batch([make_row(i) for i in range(75, 100)])
        _wait_for(_count_is(100), timeout=90, what="100 rows after restart")

        def _seg1_committed():
            view = _get(ctrl_url + f"/tables/{RPHYSICAL}/externalview")
            return view.get(f"{RPHYSICAL}__0__1", {}).get("rs0") == "ONLINE"

        _wait_for(_seg1_committed, timeout=60, what="segment 1 committed after restart")
        _wait_for(_wait_sum(sum(range(100))), timeout=30, what="sum over 100 rows")

        # --- SIGKILL the CONTROLLER mid-consumption and restart it ---
        # the consuming table must resume: server re-registers, the
        # recovered completion FSM accepts the next commit
        ctrl_proc.send_signal(signal.SIGKILL)
        ctrl_proc.wait(timeout=10)
        # 50 rows: enough to seal seg2, whose commit needs the restarted
        # controller's recovered completion FSM
        producer.produce_batch([make_row(i) for i in range(100, 150)])
        ctrl_proc2, _ = start_controller()
        procs.append(ctrl_proc2)

        _wait_for(_count_is(150), timeout=120, what="150 rows after controller restart")

        def _seg2_committed():
            view = _get(ctrl_url + f"/tables/{RPHYSICAL}/externalview")
            return view.get(f"{RPHYSICAL}__0__2", {}).get("rs0") == "ONLINE"

        _wait_for(
            _seg2_committed, timeout=90,
            what="segment 2 committed by recovered controller",
        )
        _wait_for(_wait_sum(sum(range(150))), timeout=30, what="sum over 150 rows")
    finally:
        stream_broker.stop()
        for proc in procs:
            if proc.poll() is None:
                proc.kill()


def test_partition_log_torn_tail_recovery(tmp_path):
    """A SIGKILL mid-append leaves a partial JSON line; broker restart
    must truncate the torn tail and come up (Kafka log recovery
    semantics), not crash — and corruption mid-log must still raise."""
    import json as _json

    from pinot_tpu.realtime.netstream import _Topic

    log = tmp_path / "p0.jsonl"
    log.write_text('{"i": 1}\n{"i": 2}\n{"i": 3, "x"')
    t = _Topic(1, [str(log)])
    assert [r["i"] for r in t.rows[0]] == [1, 2]
    t.append(0, [{"i": 4}])
    t.close()
    # the torn line was truncated before re-appending
    t2 = _Topic(1, [str(log)])
    assert [r["i"] for r in t2.rows[0]] == [1, 2, 4]
    t2.close()

    bad = tmp_path / "p1.jsonl"
    bad.write_text('{"i": 1}\nnot-json\n{"i": 2}\n')
    try:
        _Topic(1, [str(bad)])
        raise AssertionError("mid-log corruption must raise")
    except _json.JSONDecodeError:
        pass


def test_consumer_group_rebalance_and_offsets(tmp_path):
    """HLC analog: partitions split across group members, rebalance on
    join/leave, committed offsets durable across broker restart, and a
    stale member's commit rejected after rebalance."""
    from pinot_tpu.realtime.netstream import HLConsumer, NetworkStreamProvider, StreamBrokerServer

    log_dir = str(tmp_path / "stream")
    broker = StreamBrokerServer(log_dir=log_dir)
    broker.start()
    host, port = broker.address
    try:
        prod = NetworkStreamProvider(host, port, "events")
        prod.create_topic(4)
        for p in range(4):
            prod.produce_batch([{"p": p, "i": i} for i in range(10)], partition=p)

        c1 = HLConsumer(host, port, "events", "g1", "c1")
        assert sorted(c1.join()) == [0, 1, 2, 3]  # sole member owns all

        c2 = HLConsumer(host, port, "events", "g1", "c2")
        a2 = c2.join()
        # c1 discovers the rebalance on its next poll and drops to half
        rows1 = c1.poll()
        assert sorted(c1.assignment + a2) == [0, 1, 2, 3]
        assert not (set(c1.assignment) & set(a2))

        # drain + commit both members
        c1.poll()
        c2.poll()
        assert c1.commit() and c2.commit()
        committed = c1.committed_offsets()
        assert committed == {0: 10, 1: 10, 2: 10, 3: 10}

        # c2 leaves -> c1 takes everything back on next poll
        c2.close()
        c1.poll()
        assert sorted(c1.assignment) == [0, 1, 2, 3]
        # a stale-generation commit from the departed member is refused
        assert not c2.commit()

        # restart the broker: group offsets survive, a fresh member
        # resumes from committed positions (no replay of drained rows)
        broker.stop()
        broker2 = StreamBrokerServer(log_dir=log_dir)
        broker2.start()
        try:
            h2, p2_ = broker2.address
            c3 = HLConsumer(h2, p2_, "events", "g1", "c3")
            c3.join()
            assert c3.positions == {0: 10, 1: 10, 2: 10, 3: 10}
            assert c3.poll() == []  # nothing new
            NetworkStreamProvider(h2, p2_, "events").produce({"p": 0, "i": 99}, partition=0)
            polled = c3.poll()
            assert [(p, r["i"]) for p, r in polled] == [(0, 99)]
        finally:
            broker2.stop()
    finally:
        broker.stop()


def test_consumer_group_session_expiry(tmp_path):
    """A member that stops heartbeating is expired and its partitions
    reassigned to the survivors."""
    import time as _time

    from pinot_tpu.realtime.netstream import HLConsumer, NetworkStreamProvider, StreamBrokerServer

    broker = StreamBrokerServer()
    broker.start()
    host, port = broker.address
    try:
        NetworkStreamProvider(host, port, "t").create_topic(2)
        c1 = HLConsumer(host, port, "t", "g", "c1", session_timeout=0.3)
        c2 = HLConsumer(host, port, "t", "g", "c2", session_timeout=0.3)
        c1.join()
        c2.join()
        c1.poll()
        assert len(c1.assignment) == 1 and len(c2.assignment) == 1
        _time.sleep(0.5)  # c2 goes silent past the session timeout
        c1.poll()  # heartbeat triggers expiry + rebalance + rejoin
        assert sorted(c1.assignment) == [0, 1]
    finally:
        broker.stop()


def test_columnar_produce_fetch_roundtrip():
    """Columnar blocks store verbatim broker-side and decode back to
    the exact arrays; row ops on a columnar partition error; produce
    modes cannot mix within a partition."""
    import numpy as np
    import pytest as _pytest

    from pinot_tpu.realtime.netstream import NetworkStreamProvider, StreamBrokerServer

    srv = StreamBrokerServer()
    srv.start()
    try:
        srv.create_topic("colt", 2)
        prov = NetworkStreamProvider(*srv.address, "colt")
        cols = {
            "a": np.arange(100, dtype=np.int64),
            "b": np.linspace(0, 1, 100),
        }
        first = prov.produce_columns(cols, partition=0)
        assert first == 0
        assert prov.produce_columns(cols, partition=0) == 100
        got, n, nxt = prov.fetch_columns(0, 0)
        assert n == 100 and nxt == 100
        assert np.array_equal(got["a"], cols["a"])
        assert np.array_equal(got["b"], cols["b"])
        got2, n2, nxt2 = prov.fetch_columns(0, 100)
        assert n2 == 100 and nxt2 == 200
        # end of log: empty block at the latest offset
        _, n3, nxt3 = prov.fetch_columns(0, 200)
        assert n3 == 0 and nxt3 == 200
        assert prov.latest_offset(0) == 200
        # row fetch on a columnar partition is a typed error
        with _pytest.raises(RuntimeError, match="columnar"):
            prov.fetch(0, 0, 10)
        # row produce on a columnar partition refused; and vice versa
        with _pytest.raises(RuntimeError, match="columnar-mode"):
            prov.produce({"a": 1, "b": 2.0}, partition=0)
        prov.produce({"a": 1, "b": 2.0}, partition=1)
        with _pytest.raises(RuntimeError, match="row-mode"):
            prov.produce_columns(cols, partition=1)
    finally:
        srv.stop()


def test_columnar_index_matches_row_path():
    """index_columns and index_batch produce identical snapshots (same
    dictionaries after sort, same decoded rows) — the columnar fast
    path is a codec, not different semantics."""
    import numpy as np

    from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema, TimeFieldSpec
    from pinot_tpu.realtime.mutable import MutableSegment

    schema = Schema(
        "ct",
        dimensions=[
            FieldSpec("d", DataType.LONG, FieldType.DIMENSION),
            FieldSpec("s", DataType.STRING, FieldType.DIMENSION),
        ],
        metrics=[FieldSpec("m", DataType.FLOAT, FieldType.METRIC)],
        time_field=TimeFieldSpec("t", DataType.LONG, time_unit="MILLISECONDS"),
    )
    rng = np.random.default_rng(4)
    n = 5000
    cols = {
        "d": rng.integers(0, 700, n),
        "s": np.asarray([f"s{int(v)}" for v in rng.integers(0, 40, n)], dtype=object),
        "m": np.round(rng.random(n) * 5, 3).astype(np.float32),
        "t": 1_700_000_000_000 + np.arange(n),
    }
    rows = [
        {"d": int(cols["d"][i]), "s": str(cols["s"][i]), "m": float(cols["m"][i]), "t": int(cols["t"][i])}
        for i in range(n)
    ]
    seg_c = MutableSegment(schema, "c0", "ct")
    # two appends exercise dictionary growth across columnar batches
    seg_c.index_columns({c: a[: n // 2] for c, a in cols.items()})
    seg_c.index_columns({c: a[n // 2 :] for c, a in cols.items()})
    seg_r = MutableSegment(schema, "r0", "ct")
    seg_r.index_batch(rows)
    snap_c, snap_r = seg_c.snapshot(), seg_r.snapshot()
    assert snap_c.num_docs == snap_r.num_docs == n
    for name in ("d", "s", "m", "t"):
        cc, cr = snap_c.column(name), snap_r.column(name)
        assert list(cc.dictionary.values) == list(cr.dictionary.values)
        assert np.array_equal(cc.fwd, cr.fwd), name
    # scalar _id_of after array encodes (lazy value_to_id rebuild)
    mc = seg_c._columns["d"]
    known = mc.id_to_value[0]
    assert mc._id_of(known) == 0


def test_llc_consumer_takes_columnar_path():
    """The production LLC consumer prefers columnar blocks when the
    stream provider serves them: vectorized decode + encode, mid-block
    budget caps resume at the right offset, and the snapshot equals a
    row-path ingest of the same data."""
    import numpy as np

    from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema, TimeFieldSpec
    from pinot_tpu.realtime.llc import RealtimeSegmentDataManager
    from pinot_tpu.realtime.netstream import NetworkStreamProvider, StreamBrokerServer

    schema = Schema(
        "ct",
        dimensions=[FieldSpec("d", DataType.LONG, FieldType.DIMENSION)],
        metrics=[FieldSpec("m", DataType.INT, FieldType.METRIC)],
        time_field=TimeFieldSpec("t", DataType.LONG, time_unit="MILLISECONDS"),
    )
    srv = StreamBrokerServer()
    srv.start()
    try:
        srv.create_topic("colllc", 1)
        prov = NetworkStreamProvider(*srv.address, "colllc")
        rng = np.random.default_rng(6)
        n = 900
        cols = {
            "d": rng.integers(0, 50, n),
            "m": rng.integers(0, 9, n),
            "t": 1_700_000_000_000 + np.arange(n),
        }
        # three blocks of 300
        for i in range(0, n, 300):
            prov.produce_columns({c: a[i : i + 300] for c, a in cols.items()})

        dm = RealtimeSegmentDataManager(
            server=None,
            manager=None,
            table="ct",
            segment_name="ct__0__0",
            schema=schema,
            stream=prov,
            partition=0,
            start_offset=0,
            rows_per_segment=1000,
        )
        # budget forces a MID-block cap on the first fetch (250 < 300)
        assert dm.consume_step(max_rows=250) == 250
        assert dm._columnar is True and dm.offset == 250
        while dm.consume_step(max_rows=400):
            pass
        assert dm.mutable.num_docs == n and dm.offset == n
        snap = dm.mutable.snapshot()
        got = snap.column("m").dictionary.value_array()[
            np.asarray(snap.column("m").fwd)
        ]
        assert np.array_equal(np.sort(got), np.sort(cols["m"]))
        # per-row alignment: (d, m) pairs survive the columnar path
        gd = snap.column("d").dictionary.value_array()[np.asarray(snap.column("d").fwd)]
        want = sorted(zip(cols["d"].tolist(), cols["m"].tolist()))
        assert sorted(zip(gd.tolist(), got.tolist())) == want
    finally:
        srv.stop()
