"""Avro object container codec: roundtrip (null + deflate), union
nulls, MV arrays, schema derivation, reader->builder->query integration,
and segment->Avro export. (Reference role:
core/data/readers/AvroRecordReader.java, AvroUtils schema mapping,
pinot-tools segment converters.)"""
import gzip
import io
import json
import os

import pytest

from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.segment.avro import (
    AvroContainerReader,
    avro_to_pinot_schema,
    pinot_to_avro_schema,
    read_avro,
    write_avro,
)
from pinot_tpu.segment.builder import build_segment

AVRO_SCHEMA = {
    "type": "record",
    "name": "LineItem",
    "fields": [
        {"name": "flag", "type": "string"},
        {"name": "qty", "type": "int"},
        {"name": "price", "type": ["null", "double"]},
        {"name": "tags", "type": {"type": "array", "items": "string"}},
        {"name": "big", "type": "long"},
        {"name": "ratio", "type": "float"},
        {"name": "ok", "type": "boolean"},
    ],
}

RECORDS = [
    {"flag": "R", "qty": 5, "price": 10.25, "tags": ["a", "b"], "big": 1 << 40, "ratio": 0.5, "ok": True},
    {"flag": "N", "qty": -3, "price": None, "tags": [], "big": -(1 << 33), "ratio": -2.0, "ok": False},
    {"flag": "A", "qty": 0, "price": 99.0, "tags": ["x"], "big": 0, "ratio": 1.5, "ok": True},
] * 7  # multiple of nothing, spans block boundaries at small block size


@pytest.mark.parametrize("codec", ["null", "deflate"])
def test_container_roundtrip(tmp_path, codec):
    path = str(tmp_path / f"data_{codec}.avro")
    write_avro(path, AVRO_SCHEMA, RECORDS, codec=codec, records_per_block=4)
    reader = AvroContainerReader(path)
    assert reader.codec == codec
    got = list(reader)
    assert len(got) == len(RECORDS)
    assert got[0]["flag"] == "R"
    assert got[0]["big"] == 1 << 40
    assert got[1]["price"] is None
    assert got[1]["qty"] == -3
    assert got[2]["tags"] == ["x"]
    assert abs(got[0]["ratio"] - 0.5) < 1e-6


def test_gzip_wrapped_container(tmp_path):
    """.gz-wrapped Avro files open transparently (AvroRecordReader.java:75)."""
    plain = str(tmp_path / "d.avro")
    write_avro(plain, AVRO_SCHEMA, RECORDS[:3])
    gz = str(tmp_path / "d.avro.gz")
    with open(plain, "rb") as f, gzip.open(gz, "wb") as g:
        g.write(f.read())
    assert len(list(AvroContainerReader(gz))) == 3


def test_schema_derivation(tmp_path):
    path = str(tmp_path / "d.avro")
    write_avro(path, AVRO_SCHEMA, RECORDS[:3])
    schema = avro_to_pinot_schema(path, "lineitem", metrics=("qty", "price"))
    assert schema.schema_name == "lineitem"
    f = {s.name: s for s in schema.all_fields()}
    assert f["qty"].field_type == FieldType.METRIC
    assert f["qty"].data_type == DataType.INT
    assert f["price"].data_type == DataType.DOUBLE  # union [null, double]
    assert f["tags"].data_type == DataType.STRING_ARRAY and not f["tags"].single_value
    assert f["big"].data_type == DataType.LONG
    assert f["flag"].field_type == FieldType.DIMENSION


def test_read_avro_into_segment_and_query(tmp_path):
    """Avro file -> rows -> segment -> query, end to end."""
    from pinot_tpu.engine.executor import QueryExecutor
    from pinot_tpu.engine.reduce import reduce_to_response
    from pinot_tpu.pql import parse_pql

    path = str(tmp_path / "d.avro")
    write_avro(path, AVRO_SCHEMA, RECORDS, codec="deflate")
    schema = avro_to_pinot_schema(path, "lineitem", metrics=("qty",))
    rows = read_avro(path, schema)
    assert len(rows) == len(RECORDS)
    # union-null price defaulted, MV flattened
    assert rows[1]["price"] == schema.field("price").get_default_null_value()
    assert rows[0]["tags"] == ["a", "b"]

    seg = build_segment(schema, rows, "lineitem", "avroseg")
    req = parse_pql("SELECT sum(qty) FROM lineitem WHERE flag = 'R'")
    resp = reduce_to_response(req, [QueryExecutor().execute([seg], req)])
    want = sum(r["qty"] for r in RECORDS if r["flag"] == "R")
    got = float(resp.to_json()["aggregationResults"][0]["value"])
    assert got == want


def test_segment_to_avro_export(tmp_path):
    """Segment -> Avro converter roundtrips rows (pinot-tools parity)."""
    from pinot_tpu.tools.converters import segment_to_avro

    schema = Schema(
        "t",
        dimensions=[
            FieldSpec("d", DataType.STRING),
            FieldSpec("mv", DataType.INT_ARRAY, single_value=False),
        ],
        metrics=[FieldSpec("m", DataType.DOUBLE, FieldType.METRIC)],
    )
    rows = [
        {"d": "x", "mv": [1, 2], "m": 1.5},
        {"d": "y", "mv": [3], "m": -0.25},
    ]
    seg = build_segment(schema, rows, "t", "s0")
    out = str(tmp_path / "out.avro")
    n = segment_to_avro(seg, out)
    assert n == 2
    back = {rec["d"]: rec for rec in AvroContainerReader(out)}
    assert back["x"]["mv"] == [1, 2]
    assert back["y"]["m"] == -0.25


def test_reader_is_reiterable_and_bytes_decode(tmp_path):
    schema_avro = {
        "type": "record",
        "name": "B",
        "fields": [{"name": "raw", "type": "bytes"}, {"name": "k", "type": "string"}],
    }
    path = str(tmp_path / "b.avro")
    write_avro(path, schema_avro, [{"raw": b"abc", "k": "x"}])
    reader = AvroContainerReader(path)
    assert [r["raw"] for r in reader] == [b"abc"]
    assert [r["raw"] for r in reader] == [b"abc"]  # re-iterable

    schema = Schema("b", dimensions=[FieldSpec("raw", DataType.STRING), FieldSpec("k", DataType.STRING)])
    rows = read_avro(path, schema)
    assert rows[0]["raw"] == "abc"  # decoded content, not repr
