"""Distributed join subsystem (ISSUE 14): PQL grammar edge cases, the
engine's device-vs-host differential, skew-aware shuffle partitioning,
and the three broker strategies end-to-end — byte-identical results
across every strategy and execution tier, under replica failover, with
a poisoned join plan healing transparently, and with the result-cache /
batching interop guards held.
"""
from __future__ import annotations

import json

import numpy as np
import pytest

from pinot_tpu.common.datatable import (
    deserialize_instance_request,
    deserialize_result,
    serialize_instance_request,
    serialize_result,
)
from pinot_tpu.common.request import FilterOperator
from pinot_tpu.common.response import ErrorCode
from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.common.tableconfig import PartitionConfig
from pinot_tpu.engine import join as jm
from pinot_tpu.engine.plandigest import plan_shape_digest
from pinot_tpu.engine.results import IntermediateResult
from pinot_tpu.pql import PqlParseError, parse_pql
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.tools.cluster_harness import InProcessCluster

# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------


def test_parse_join_with_aliases_and_reversed_on():
    r1 = parse_pql(
        "SELECT sum(f.v) FROM fact f JOIN dim AS d ON f.k = d.dk WHERE d.cat = 'x'"
    )
    r2 = parse_pql(
        "SELECT sum(x.v) FROM fact x JOIN dim y ON y.dk = x.k WHERE y.cat = 'x'"
    )
    for r in (r1, r2):
        assert r.join is not None
        assert r.join.right_table == "dim"
        assert r.join.left_key == "k" and r.join.right_key == "dk"
        # right-side refs canonicalize to the TABLE name, not the alias
        leaves = [n for n in r.filter.walk() if n.is_leaf]
        assert leaves[0].column == "dim.cat"
    # alias spelling does not fork the plan shape
    assert plan_shape_digest(r1) == plan_shape_digest(r2)
    # ...but a joined scan is a different shape from a plain scan
    assert plan_shape_digest(r1) != plan_shape_digest(
        parse_pql("SELECT sum(v) FROM fact WHERE cat = 'x'")
    )


def test_parse_join_group_order_top():
    r = parse_pql(
        "SELECT sum(f.v), count(*) FROM fact f JOIN dim d ON f.k = d.k "
        "WHERE f.v > 3 GROUP BY d.cat, f.g ORDER BY d.cat TOP 7"
    )
    assert r.group_by.columns == ["dim.cat", "g"]
    assert r.group_by.top_n == 7
    assert r.aggregations[0].column == "v"


@pytest.mark.parametrize(
    "pql,needle",
    [
        ("SELECT a.x FROM a, b", "cross join"),
        ("SELECT a.x FROM a CROSS JOIN b ON a.k = b.k", "cross join"),
        ("SELECT a.x FROM a LEFT JOIN b ON a.k = b.k", "INNER equi-join"),
        ("SELECT a.x FROM a JOIN b ON a.k < b.k", "equi-join"),
        ("SELECT a.x FROM a JOIN b ON a.k = a.j", "EACH side"),
        ("SELECT a.x FROM a JOIN b ON a.k = b.k JOIN c ON a.k = c.k", "two tables"),
        ("SELECT a.x FROM a JOIN b ON a.k = b.k AND a.j = b.j", "compound ON"),
        ("SELECT x FROM a JOIN b ON a.k = b.k", "qualified"),
        ("SELECT * FROM a JOIN b ON a.k = b.k", "name the"),
        ("SELECT q.x FROM a JOIN b ON a.k = b.k", "unknown table alias"),
        ("SELECT a.b FROM plain", "only valid in a join"),
        ("SELECT a.x FROM a INNER b", "expected JOIN"),
        ("SELECT a.x FROM a JOIN b ON k = b.k", "qualified"),
    ],
)
def test_parse_join_typed_errors(pql, needle):
    with pytest.raises(PqlParseError) as ei:
        parse_pql(pql)
    assert needle.lower() in str(ei.value).lower()


def test_parse_errors_surface_as_4xx_not_crash():
    """Through the whole broker front door: a join parse error is a
    typed 150, never an unhandled exception."""
    from pinot_tpu.broker.broker import BrokerRequestHandler
    from pinot_tpu.transport.local import LocalTransport

    broker = BrokerRequestHandler(LocalTransport(), {}, name="jerr")
    try:
        resp = broker.handle_pql("SELECT a.x FROM a CROSS JOIN b")
        assert [e.error_code for e in resp.exceptions] == [ErrorCode.PQL_PARSING]
    finally:
        broker.shutdown()


# ---------------------------------------------------------------------------
# engine units
# ---------------------------------------------------------------------------


def _mk_side(keys, stored=DataType.LONG, **cols):
    out_cols = {}
    for name, (vals, st) in cols.items():
        out_cols[name] = jm._dict_encode(np.asarray(vals, dtype=object if st == DataType.STRING else None), st)
    return jm.SideRows(
        n=len(keys), key=jm._dict_encode(np.asarray(keys), stored), cols=out_cols
    )


def test_side_rows_wire_roundtrip_with_strings():
    side = _mk_side(
        [3, 1, 3, 9],
        cols_num=([1, 2, 3, 4], DataType.INT),
        cols_str=(["a", "b", "a", "c"], DataType.STRING),
    )
    back = jm.decode_side(
        deserialize_instance_request(
            serialize_instance_request(
                "rid", "pql", "t", [], 100.0, join={"x": jm.encode_side(side)}
            )
        )["join"]["x"]
    )
    assert back.n == side.n
    assert np.array_equal(back.key.ids, side.key.ids)
    assert list(back.cols["cols_str"].values) == ["a", "b", "c"]
    # join payload on the result wire too
    res = IntermediateResult(num_docs_scanned=1)
    res.join_payload = jm.encode_side(side)
    rt = deserialize_result(serialize_result(res))
    assert np.array_equal(
        jm.decode_side(rt.join_payload).key.ids, side.key.ids
    )


def test_split_join_filter_sides_and_mixed_rejection():
    r = parse_pql(
        "SELECT count(*) FROM f JOIN d ON f.k = d.k "
        "WHERE f.a > 1 AND d.b = 2 AND (f.c = 3 OR f.e = 4)"
    )
    left, right = jm.split_join_filter(r)
    assert {n.column for n in left.walk() if n.is_leaf} == {"a", "c", "e"}
    assert [n.column for n in right.walk() if n.is_leaf] == ["b"]  # stripped
    bad = parse_pql(
        "SELECT count(*) FROM f JOIN d ON f.k = d.k WHERE f.a = 1 OR d.b = 2"
    )
    with pytest.raises(jm.JoinValidationError):
        jm.split_join_filter(bad)


def test_host_join_matches_bruteforce_with_duplicate_keys():
    rng = np.random.default_rng(5)
    pk = rng.integers(0, 20, 400)
    pv = rng.integers(0, 50, 400)
    bk = rng.integers(0, 25, 60)  # duplicate build keys: M:N join
    bw = rng.integers(0, 9, 60)
    probe = _mk_side(pk, cols_v=(pv, DataType.INT))
    probe.cols["v"] = probe.cols.pop("cols_v")
    build = _mk_side(bk, cols_w=(bw, DataType.INT))
    build.cols["d.w"] = build.cols.pop("cols_w")
    req = parse_pql("SELECT count(*), sum(f.v), sum(d.w) FROM f JOIN d ON f.k = d.k")
    res = jm.host_join(req, build, probe)
    exp_cnt = exp_sv = exp_sw = 0
    for k, v in zip(pk, pv):
        for k2, w in zip(bk, bw):
            if k == k2:
                exp_cnt += 1
                exp_sv += v
                exp_sw += w
    vals = [p.finalize() for p in res.aggregations]
    assert vals == [exp_cnt, float(exp_sv), float(exp_sw)]
    assert res.num_docs_scanned == exp_cnt


def test_device_join_differential_vs_host():
    """The device hash-join kernel must match the exact host join for
    every eligible shape — scalar aggs, probe-side groups, build-side
    groups (unique keys), string join keys."""
    from pinot_tpu.engine.executor import QueryExecutor

    rng = np.random.default_rng(0)
    N, B = 4000, 400
    pk = rng.integers(0, 300, N)
    pv = rng.integers(0, 100, N)
    pg = np.asarray([f"p{int(x) % 4}" for x in pk], dtype=object)
    bk = np.concatenate([np.arange(250), rng.integers(0, 250, B - 250)])
    bw = rng.integers(0, 50, B)
    probe = jm.SideRows(
        n=N,
        key=jm._dict_encode(pk, DataType.LONG),
        cols={
            "v": jm._dict_encode(pv, DataType.LONG),
            "g": jm._dict_encode(pg, DataType.STRING),
        },
    )
    build = jm.SideRows(
        n=B,
        key=jm._dict_encode(bk, DataType.LONG),
        cols={"d.w": jm._dict_encode(bw, DataType.LONG)},
    )
    ub = np.arange(250)
    build_u = jm.SideRows(
        n=250,
        key=jm._dict_encode(ub, DataType.LONG),
        cols={
            "d.w": jm._dict_encode(rng.integers(0, 50, 250), DataType.LONG),
            "d.cat": jm._dict_encode(
                np.asarray([f"c{k % 6}" for k in ub], dtype=object), DataType.STRING
            ),
        },
    )
    # string join keys exercise the shared-vocabulary id space
    spk = np.asarray([f"k{int(x)}" for x in pk], dtype=object)
    sbk = np.asarray([f"k{int(x)}" for x in ub], dtype=object)
    probe_s = jm.SideRows(
        n=N,
        key=jm._dict_encode(spk, DataType.STRING),
        cols={"v": jm._dict_encode(pv, DataType.LONG)},
    )
    build_s = jm.SideRows(
        n=250,
        key=jm._dict_encode(sbk, DataType.STRING),
        cols={"d.w": jm._dict_encode(rng.integers(0, 50, 250), DataType.LONG)},
    )

    ex = QueryExecutor()
    cases = [
        (
            "SELECT count(*), sum(f.v), sum(d.w), avg(f.v), min(d.w), "
            "max(f.v), minmaxrange(d.w) FROM f JOIN d ON f.k = d.k",
            build,
            probe,
        ),
        (
            "SELECT sum(f.v), count(*) FROM f JOIN d ON f.k = d.k GROUP BY f.g",
            build,
            probe,
        ),
        (
            "SELECT sum(f.v), min(d.w) FROM f JOIN d ON f.k = d.k "
            "GROUP BY d.cat, f.g",
            build_u,
            probe,
        ),
        (
            "SELECT count(*), sum(f.v) FROM f JOIN d ON f.k = d.k",
            build_s,
            probe_s,
        ),
    ]

    def norm(r):
        if r.groups is not None:
            return {k: [p.finalize() for p in v] for k, v in r.groups.items()}
        return [p.finalize() for p in (r.aggregations or [])]

    for pql, b, p in cases:
        req = parse_pql(pql)
        dev = ex.execute_join(req, b, p)
        assert "deviceBytes" in dev.cost, f"device path not taken for {pql}"
        host = jm.host_join(req, b, p)
        assert norm(dev) == norm(host), pql
        assert dev.num_docs_scanned == host.num_docs_scanned
        assert dev.cost.get("buildRows") == b.n
        assert dev.cost.get("probeRows") == p.n
    assert ex.healing_stats()["hostFailovers"] == 0


def test_shuffle_partitions_preserve_join_and_balance_skew():
    rng = np.random.default_rng(7)
    # zipf s=1.2 on the join key — the acceptance distribution
    zk = (np.minimum(rng.zipf(1.2, 30000), 400) - 1).astype(np.int64)
    probe = jm.SideRows(
        n=zk.size,
        key=jm._dict_encode(zk, DataType.LONG),
        cols={"v": jm._dict_encode(rng.integers(0, 10, zk.size), DataType.LONG)},
    )
    build = jm.SideRows(
        n=400,
        key=jm._dict_encode(np.arange(400), DataType.LONG),
        cols={"d.w": jm._dict_encode(np.arange(400) % 7, DataType.LONG)},
    )
    req = parse_pql("SELECT count(*), sum(f.v) FROM f JOIN d ON f.k = d.k")
    full = jm.host_join(req, build, probe)

    def run(split):
        owners, n_heavy = jm.plan_shuffle_partitions(
            build, probe, 4, split_heavy=split
        )
        parts = []
        sizes = []
        for b_idx, p_idx in owners:
            b_sub, p_sub = jm.side_take(build, b_idx), jm.side_take(probe, p_idx)
            sizes.append(p_sub.nbytes() + b_sub.nbytes())
            parts.append(jm.host_join(req, b_sub, p_sub))
        merged = parts[0]
        for p in parts[1:]:
            merged.merge(p)
        return merged, sizes, n_heavy

    merged, sizes, n_heavy = run(split=True)
    # inner-join correctness is partition-invariant
    assert [p.finalize() for p in merged.aggregations] == [
        p.finalize() for p in full.aggregations
    ]
    assert n_heavy > 0
    ratio = max(sizes) / (sum(sizes) / len(sizes))
    assert ratio <= 2.0, sizes
    _m2, sizes_ns, _h = run(split=False)
    ratio_ns = max(sizes_ns) / (sum(sizes_ns) / len(sizes_ns))
    assert ratio <= ratio_ns  # splitting never worsens balance


# ---------------------------------------------------------------------------
# cluster end-to-end
# ---------------------------------------------------------------------------

NPART = 4


def _fact_schema(name):
    return Schema(
        name,
        dimensions=[
            FieldSpec("k", DataType.INT, FieldType.DIMENSION),
            FieldSpec("grp", DataType.STRING, FieldType.DIMENSION),
        ],
        metrics=[FieldSpec("v", DataType.INT, FieldType.METRIC)],
    )


def _dim_schema(name):
    return Schema(
        name,
        dimensions=[
            FieldSpec("k", DataType.INT, FieldType.DIMENSION),
            FieldSpec("cat", DataType.STRING, FieldType.DIMENSION),
        ],
        metrics=[FieldSpec("w", DataType.INT, FieldType.METRIC)],
    )


def _make_rows(seed=3, n=1500, keys=60):
    rng = np.random.default_rng(seed)
    fact = [
        {"k": int(k), "grp": f"g{int(k) % 3}", "v": int(v)}
        for k, v in zip(rng.integers(0, keys, n), rng.integers(0, 100, n))
    ]
    dim = [{"k": k, "cat": f"c{k % 5}", "w": (k * 3) % 41} for k in range(keys)]
    return fact, dim


def _oracle(fact, dim):
    import collections

    dmap = collections.defaultdict(list)
    for d in dim:
        dmap[d["k"]].append(d)
    return [(f, d) for f in fact for d in dmap.get(f["k"], [])]


@pytest.fixture(scope="module")
def join_cluster():
    cl = InProcessCluster(num_servers=2)
    fact, dim = _make_rows()
    part = PartitionConfig(column="k", num_partitions=NPART)
    cl.add_offline_table(
        _fact_schema("factT"), table_name="factT", replication=2, partitioning=part
    )
    cl.add_offline_table(
        _dim_schema("dimT"), table_name="dimT", replication=2, partitioning=part
    )
    fs, ds = _fact_schema("factT"), _dim_schema("dimT")
    for p in range(NPART):
        cl.upload(
            "factT_OFFLINE",
            build_segment(
                fs,
                [r for r in fact if r["k"] % NPART == p],
                "factT_OFFLINE",
                segment_name=f"factT_{p}_p{p}",
            ),
        )
        cl.upload(
            "dimT_OFFLINE",
            build_segment(
                ds,
                [r for r in dim if r["k"] % NPART == p],
                "dimT_OFFLINE",
                segment_name=f"dimT_{p}_p{p}",
            ),
        )
    yield cl, fact, dim
    cl.stop()


_STRATS = ("colocated", "broadcast", "shuffle")


def _result_payload(resp) -> str:
    """Result sections only: work accounting is strategy-dependent by
    construction (the PR 3 heal contract), results are not."""
    keep = ("aggregationResults", "selectionResults", "exceptions",
            "partialResponse", "planDigest")
    return json.dumps(
        {k: v for k, v in resp.to_json().items() if k in keep}, sort_keys=True
    )


def test_all_strategies_end_to_end_byte_identical(join_cluster):
    cl, fact, dim = join_cluster
    joined = _oracle(fact, dim)
    exp = [len(joined), float(sum(f["v"] for f, _ in joined)),
           float(sum(d["w"] for _, d in joined))]
    q = "SELECT count(*), sum(f.v), sum(d.w) FROM factT f JOIN dimT d ON f.k = d.k"
    payloads = set()
    for strat in _STRATS:
        resp = cl.broker.handle_pql(q, debug_options={"joinStrategy": strat})
        assert not resp.exceptions, (strat, resp.exceptions)
        got = [a.value for a in resp.aggregation_results]
        assert [got[0], float(got[1]), float(got[2])] == exp, strat
        payloads.add(_result_payload(resp))
        # join cost keys are additive and present
        assert resp.cost.get("buildRows", 0) > 0
        assert resp.cost.get("probeRows", 0) > 0
        if strat == "shuffle":
            assert resp.cost.get("shuffleBytes", 0) > 0
        if strat == "broadcast":
            assert resp.cost.get("broadcastBytes", 0) > 0
    # forced-host reference produces the same payload (debugOptions ride
    # the literal digest, not the shape, so planDigest matches too)
    import os

    os.environ["PINOT_TPU_JOIN_DEVICE"] = "0"
    try:
        for strat in _STRATS:
            resp = cl.broker.handle_pql(q, debug_options={"joinStrategy": strat})
            assert not resp.exceptions
            payloads.add(_result_payload(resp))
    finally:
        os.environ.pop("PINOT_TPU_JOIN_DEVICE")
    assert len(payloads) == 1, payloads


def test_join_cost_vector_broker_equals_sum_of_servers(join_cluster):
    """The additive-cost invariant extends to joins: the broker's merged
    vector equals the key-wise sum of every server reply's vector, over
    every phase of the most phase-heavy strategy (shuffle)."""
    cl, _f, _d = join_cluster

    class _Spy:
        def __init__(self, inner):
            self.inner = inner
            self.replies = []

        def request(self, address, payload, timeout=15.0):
            reply = self.inner.request(address, payload, timeout)
            self.replies.append(reply)
            return reply

        def __getattr__(self, name):
            return getattr(self.inner, name)

    spy = _Spy(cl.broker.transport)
    cl.broker.transport = spy
    try:
        resp = cl.broker.handle_pql(
            "SELECT sum(f.v), count(*) FROM factT f JOIN dimT d ON f.k = d.k "
            "WHERE d.cat IN ('c1','c3') GROUP BY d.cat",
            debug_options={"joinStrategy": "shuffle"},
        )
        assert not resp.exceptions, resp.exceptions
        summed: dict = {}
        docs = 0
        for raw in spy.replies:
            part = deserialize_result(raw)
            docs += part.num_docs_scanned
            for k, v in part.cost.items():
                summed[k] = summed.get(k, 0) + v
        assert resp.num_docs_scanned == docs
        for k in set(summed) | set(resp.cost):
            assert resp.cost.get(k, 0) == pytest.approx(summed.get(k, 0)), k
    finally:
        cl.broker.transport = spy.inner


def test_join_group_by_having_order_and_selection(join_cluster):
    cl, fact, dim = join_cluster
    joined = _oracle(fact, dim)
    # group-by with HAVING, identical across strategies
    q = (
        "SELECT sum(f.v), count(*) FROM factT f JOIN dimT d ON f.k = d.k "
        "WHERE f.v > 20 GROUP BY d.cat HAVING count(*) > 10 TOP 5"
    )
    seen = {
        _result_payload(cl.broker.handle_pql(q, debug_options={"joinStrategy": s}))
        for s in _STRATS
    }
    assert len(seen) == 1
    # selection join with order/limit (host tier)
    qsel = (
        "SELECT f.v, d.w FROM factT f JOIN dimT d ON f.k = d.k "
        "WHERE d.cat = 'c2' ORDER BY f.v DESC LIMIT 5"
    )
    top_v = sorted(
        (f["v"] for f, d in joined if d["cat"] == "c2"), reverse=True
    )[:5]
    for s in _STRATS:
        resp = cl.broker.handle_pql(qsel, debug_options={"joinStrategy": s})
        assert not resp.exceptions, (s, resp.exceptions)
        assert resp.selection_results.columns == ["v", "dimT.w"]
        # sort-key ties admit any row order (strategies partition rows
        # differently, like routing draws do for scans) — the ordered
        # sort-column values are the deterministic contract
        assert [int(r[0]) for r in resp.selection_results.rows] == top_v


def test_join_key_referenced_as_value_column(join_cluster):
    """sum/group over the join key itself: the key doubles as a value
    column and must be read ONCE per segment (regression: duplicated
    extraction doubled host results and crashed the device packing)."""
    cl, fact, dim = join_cluster
    joined = _oracle(fact, dim)
    q = "SELECT count(*), sum(f.k) FROM factT f JOIN dimT d ON f.k = d.k"
    for strat in _STRATS:
        resp = cl.broker.handle_pql(q, debug_options={"joinStrategy": strat})
        assert not resp.exceptions, (strat, resp.exceptions)
        vals = [a.value for a in resp.aggregation_results]
        assert int(vals[0]) == len(joined), strat
        assert float(vals[1]) == float(sum(f["k"] for f, _ in joined)), strat


def test_join_empty_filtered_side_returns_empty_not_type_error(join_cluster):
    """A right-side filter matching nothing yields an empty inner join
    (count 0), never a spurious key-type validation error from the
    empty-extract placeholder (regression)."""
    cl, _f, _d = join_cluster
    for strat in _STRATS:
        resp = cl.broker.handle_pql(
            "SELECT count(*) FROM factT f JOIN dimT d ON f.k = d.k "
            "WHERE d.cat = 'nomatch'",
            debug_options={"joinStrategy": strat},
        )
        assert not resp.exceptions, (strat, resp.exceptions)
        assert int(resp.aggregation_results[0].value) == 0


def test_bogus_join_strategy_is_typed_4xx(join_cluster):
    cl, _f, _d = join_cluster
    resp = cl.broker.handle_pql(
        "SELECT count(*) FROM factT f JOIN dimT d ON f.k = d.k",
        debug_options={"joinStrategy": "bogus"},
    )
    assert [e.error_code for e in resp.exceptions] == [ErrorCode.QUERY_VALIDATION]


def test_join_validation_errors_are_typed_4xx(join_cluster):
    cl, _f, _d = join_cluster
    # mixed-side OR
    resp = cl.broker.handle_pql(
        "SELECT count(*) FROM factT f JOIN dimT d ON f.k = d.k "
        "WHERE f.v = 1 OR d.cat = 'c1'"
    )
    assert [e.error_code for e in resp.exceptions] == [ErrorCode.QUERY_VALIDATION]
    # unknown right table
    resp = cl.broker.handle_pql(
        "SELECT count(*) FROM factT f JOIN nosuch d ON f.k = d.k"
    )
    assert [e.error_code for e in resp.exceptions] == [ErrorCode.QUERY_VALIDATION]
    # forcing colocated where ineligible (partition column mismatch)
    resp = cl.broker.handle_pql(
        "SELECT count(*) FROM factT f JOIN dimT d ON f.v = d.k",
        debug_options={"joinStrategy": "colocated"},
    )
    assert [e.error_code for e in resp.exceptions] == [ErrorCode.QUERY_VALIDATION]


def test_join_explain_strategy_and_digest_match_execution(join_cluster):
    cl, _f, _d = join_cluster
    q = "SELECT count(*), sum(f.v) FROM factT f JOIN dimT d ON f.k = d.k"
    executed = cl.broker.handle_pql(q)
    assert not executed.exceptions
    plan = cl.broker.handle_pql("EXPLAIN " + q)
    node = plan.explain["join"]
    # the partition-aligned tables pick colocated, EXPLAIN and real
    # execution agree, and the plan digest matches exactly
    assert node["strategy"] == "colocated"
    assert node["colocated"]["eligible"] is True
    assert plan.explain["planDigest"] == executed.plan_digest
    analyze = cl.broker.handle_pql("EXPLAIN ANALYZE " + q)
    actual = analyze.explain["join"]["actual"]
    assert actual["strategy"] == "colocated"
    assert actual["buildRows"] > 0 and actual["probeRows"] > 0
    # forced shuffle: EXPLAIN names it, ANALYZE carries the split info
    analyze = cl.broker.handle_pql(
        "EXPLAIN ANALYZE " + q, debug_options={"joinStrategy": "shuffle"}
    )
    actual = analyze.explain["join"]["actual"]
    assert actual["strategy"] == "shuffle"
    assert actual["shuffleBytes"] > 0
    assert "heavyHitterSplits" in actual
    # explain_dump renders the join node
    from pinot_tpu.tools.explain_dump import render_explain

    text = render_explain(analyze.to_json())
    assert "join: shuffle" in text and "colocated:" in text


def test_join_shapes_reach_planstats(join_cluster):
    cl, _f, _d = join_cluster
    q = "SELECT max(f.v) FROM factT f JOIN dimT d ON f.k = d.k"
    resp = cl.broker.handle_pql(q)
    assert not resp.exceptions
    top = cl.broker.planstats.top(50, by="count")
    entry = next(e for e in top if e["digest"] == resp.plan_digest)
    assert "join dimT" in entry["summary"]


def test_join_excluded_from_micro_batching(join_cluster):
    """ISSUE 14 guard: join dispatches never enter the PR 13 batching
    tier — no batchHits on any join response, no batched launches on
    the lanes beyond what scans formed."""
    cl, _f, _d = join_cluster
    before = [
        (s.lanes.stats()["batchLaunches"] if s.lanes else 0) for s in cl.servers
    ]
    for t in (5, 15, 25, 35):
        resp = cl.broker.handle_pql(
            f"SELECT sum(f.v) FROM factT f JOIN dimT d ON f.k = d.k "
            f"WHERE f.v > {t}"
        )
        assert not resp.exceptions
        assert "batchHits" not in resp.cost
    after = [
        (s.lanes.stats()["batchLaunches"] if s.lanes else 0) for s in cl.servers
    ]
    assert after == before


def test_join_traces_show_exchange_phases(join_cluster):
    cl, _f, _d = join_cluster
    resp = cl.broker.handle_pql(
        "SELECT count(*) FROM factT f JOIN dimT d ON f.k = d.k",
        trace=True,
        debug_options={"joinStrategy": "shuffle"},
    )
    from pinot_tpu.tools.trace_dump import render_waterfall

    text = render_waterfall(resp.trace_info)
    for span in ("joinPlan", "joinBuildExtract", "joinProbeExtract",
                 "joinShuffleExec", "joinExec"):
        assert span in text, span


# ---------------------------------------------------------------------------
# failover + healing (chaos)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.parametrize("strategy", ["broadcast", "shuffle"])
def test_join_survives_replica_failure(strategy, tmp_path):
    """Replication 2: one server's transport dies mid-fleet; every
    strategy still answers exactly (failover to the live replica — for
    shuffle, owner re-dispatch onto the remaining owners)."""
    cl = InProcessCluster(num_servers=2, data_dir=str(tmp_path))
    try:
        fact, dim = _make_rows(seed=9, n=600, keys=30)
        cl.add_offline_table(_fact_schema("fA"), table_name="fA", replication=2)
        cl.add_offline_table(_dim_schema("dA"), table_name="dA", replication=2)
        cl.upload("fA_OFFLINE", build_segment(_fact_schema("fA"), fact, "fA_OFFLINE", segment_name="fA_0"))
        cl.upload("dA_OFFLINE", build_segment(_dim_schema("dA"), dim, "dA_OFFLINE", segment_name="dA_0"))
        q = "SELECT count(*), sum(f.v) FROM fA f JOIN dA d ON f.k = d.k"
        ok = cl.broker.handle_pql(q, debug_options={"joinStrategy": strategy})
        assert not ok.exceptions, ok.exceptions
        expected = _result_payload(ok)

        # sever server0's transport: every request to it now fails
        dead = cl.servers[0]
        cl.transport.register(
            (dead.name, 0),
            lambda payload: (_ for _ in ()).throw(ConnectionError("severed")),
        )
        resp = cl.broker.handle_pql(q, debug_options={"joinStrategy": strategy})
        assert not resp.exceptions, (strategy, resp.exceptions)
        assert not resp.partial_response
        assert _result_payload(resp) == expected
    finally:
        cl.stop()


@pytest.mark.chaos
def test_poisoned_join_plan_heals_to_host(tmp_path):
    """A join plan that deterministically fails on device quarantines
    and serves from the exact host join — byte-identical, transparent,
    exactly like a poisoned scan (shared heal counters + poison map)."""
    from pinot_tpu.common.faults import DeviceFaultInjector
    from pinot_tpu.server.instance import ServerInstance
    from pinot_tpu.server.starter import ServerStarter
    from pinot_tpu.controller.controller import Controller
    from pinot_tpu.broker.broker import BrokerRequestHandler
    from pinot_tpu.broker.starter import BrokerStarter
    from pinot_tpu.transport.local import LocalTransport

    controller = Controller(str(tmp_path))
    transport = LocalTransport()
    injector = DeviceFaultInjector(seed=1)
    server = ServerInstance("s0", device_fault_injector=injector)
    starter = ServerStarter(server, controller.resources)
    starter.start()
    transport.register(("s0", 0), server.handle_request)
    broker = BrokerRequestHandler(transport, {"s0": ("s0", 0)}, name="jb")
    BrokerStarter(broker, controller.resources).start()
    try:
        fact, dim = _make_rows(seed=2, n=500, keys=25)
        controller.add_schema(_fact_schema("fP"))
        controller.add_schema(_dim_schema("dP"))
        from pinot_tpu.common.tableconfig import TableConfig

        controller.add_table(TableConfig(table_name="fP", table_type="OFFLINE"))
        controller.add_table(TableConfig(table_name="dP", table_type="OFFLINE"))
        controller.upload_segment(
            "fP_OFFLINE", build_segment(_fact_schema("fP"), fact, "fP_OFFLINE", segment_name="fP_0")
        )
        controller.upload_segment(
            "dP_OFFLINE", build_segment(_dim_schema("dP"), dim, "dP_OFFLINE", segment_name="dP_0")
        )
        q = "SELECT count(*), sum(f.v) FROM fP f JOIN dP d ON f.k = d.k"
        healthy = broker.handle_pql(q, debug_options={"joinStrategy": "broadcast"})
        assert not healthy.exceptions, healthy.exceptions
        assert "deviceBytes" in healthy.cost  # device path proven

        # the next device launch fails DETERMINISTICALLY (non-retryable:
        # the executor quarantines the join plan without a device retry)
        injector.fail_next(1, retryable=False)
        resp = broker.handle_pql(q, debug_options={"joinStrategy": "broadcast"})
        assert not resp.exceptions, resp.exceptions
        assert _result_payload(resp) == _result_payload(healthy)
        heal = server.executor.healing_stats()
        assert heal["hostFailovers"] >= 1
        assert heal["poisonedPlans"] >= 1
        # quarantined: the next query skips the device outright
        resp2 = broker.handle_pql(q, debug_options={"joinStrategy": "broadcast"})
        assert not resp2.exceptions
        assert _result_payload(resp2) == _result_payload(healthy)
        assert server.executor.healing_stats()["poisonSkips"] >= 1
    finally:
        broker.shutdown()
        server.shutdown()
        controller.stop()


# ---------------------------------------------------------------------------
# zipf skew acceptance (chaos tier)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_zipf_shuffle_join_balances_heavy_hitters(tmp_path):
    """ISSUE 14 acceptance: a zipf s=1.2 shuffle join completes with no
    single server receiving >2x the mean shuffle bytes, the split is
    visible in metrics + EXPLAIN, and disabling the split degrades
    balance (proving the mechanism, not luck)."""
    import os

    cl = InProcessCluster(num_servers=4)
    try:
        rng = np.random.default_rng(21)
        keys = (np.minimum(rng.zipf(1.2, 12000), 300) - 1).astype(int)
        fact = [
            {"k": int(k), "grp": "g", "v": int(v)}
            for k, v in zip(keys, rng.integers(0, 50, keys.size))
        ]
        dim = [{"k": k, "cat": f"c{k % 5}", "w": k % 17} for k in range(300)]
        cl.add_offline_table(_fact_schema("fZ"), table_name="fZ", replication=1)
        cl.add_offline_table(_dim_schema("dZ"), table_name="dZ", replication=4)
        fs = _fact_schema("fZ")
        for i in range(4):
            cl.upload(
                "fZ_OFFLINE",
                build_segment(
                    fs, fact[i::4], "fZ_OFFLINE", segment_name=f"fZ_{i}"
                ),
            )
        cl.upload(
            "dZ_OFFLINE",
            build_segment(_dim_schema("dZ"), dim, "dZ_OFFLINE", segment_name="dZ_0"),
        )
        q = "SELECT count(*), sum(f.v) FROM fZ f JOIN dZ d ON f.k = d.k"
        joined = _oracle(fact, dim)
        before_splits = cl.broker.metrics.meter("join.heavyHitterSplits").count
        resp = cl.broker.handle_pql(
            "EXPLAIN ANALYZE " + q, debug_options={"joinStrategy": "shuffle"}
        )
        assert not resp.exceptions, resp.exceptions
        # exact answer under the skewed exchange
        assert resp.num_docs_scanned >= len(joined)  # joined + extraction scans
        vals = [a.value for a in resp.aggregation_results]
        assert int(vals[0]) == len(joined)
        assert float(vals[1]) == float(sum(f["v"] for f, _ in joined))
        actual = resp.explain["join"]["actual"]
        assert actual["heavyHitterSplits"] > 0
        assert (
            cl.broker.metrics.meter("join.heavyHitterSplits").count
            > before_splits
        )
        per = actual["shuffleBytesPerServer"]
        assert len(per) == 4
        mean = sum(per.values()) / len(per)
        assert max(per.values()) <= 2.0 * mean, per
        # mechanism check: with splitting disabled the hot owner is
        # strictly worse than with it on
        os.environ["PINOT_TPU_JOIN_SPLIT"] = "0"
        try:
            resp_ns = cl.broker.handle_pql(
                "EXPLAIN ANALYZE " + q, debug_options={"joinStrategy": "shuffle"}
            )
            per_ns = resp_ns.explain["join"]["actual"]["shuffleBytesPerServer"]
            mean_ns = sum(per_ns.values()) / len(per_ns)
            assert resp_ns.explain["join"]["actual"]["heavyHitterSplits"] == 0
            assert max(per.values()) / mean < max(per_ns.values()) / mean_ns
        finally:
            os.environ.pop("PINOT_TPU_JOIN_SPLIT")
    finally:
        cl.stop()


# ---------------------------------------------------------------------------
# result-cache interop guard
# ---------------------------------------------------------------------------


def test_colocated_join_result_cache_keys_both_tables(tmp_path, monkeypatch):
    monkeypatch.setenv("PINOT_TPU_RESULT_CACHE", "1")
    cl = InProcessCluster(num_servers=1, data_dir=str(tmp_path))
    try:
        fact, dim = _make_rows(seed=4, n=400, keys=20)
        part = PartitionConfig(column="k", num_partitions=1)
        cl.add_offline_table(
            _fact_schema("fC"), table_name="fC", replication=1, partitioning=part
        )
        cl.add_offline_table(
            _dim_schema("dC"), table_name="dC", replication=1, partitioning=part
        )
        cl.upload("fC_OFFLINE", build_segment(_fact_schema("fC"), fact, "fC_OFFLINE", segment_name="fC_0_p0"))
        cl.upload("dC_OFFLINE", build_segment(_dim_schema("dC"), dim, "dC_OFFLINE", segment_name="dC_0_p0"))
        q = "SELECT count(*), sum(f.v) FROM fC f JOIN dC d ON f.k = d.k"
        r1 = cl.broker.handle_pql(q)
        assert not r1.exceptions and "rescacheHits" not in r1.cost
        r2 = cl.broker.handle_pql(q)
        # hit: zero device/host work, identical payload
        assert r2.cost == {"rescacheHits": 1}, r2.cost
        assert _result_payload(r2) == _result_payload(r1)
        # an ingest/segment change on the BUILD side invalidates: the
        # next query re-executes against the grown build side (upload
        # through the controller so routing learns the new segment)
        evictions_before = (
            cl.servers[0].metrics.meter("rescache.staleEvictions").count
        )
        dim2 = dim + [{"k": 5, "cat": "c0", "w": 40}]
        cl.upload(
            "dC_OFFLINE",
            build_segment(_dim_schema("dC"), dim2[-1:], "dC_OFFLINE", segment_name="dC_1_p0"),
        )
        assert (
            cl.servers[0].metrics.meter("rescache.staleEvictions").count
            > evictions_before
        )
        r3 = cl.broker.handle_pql(q)
        assert not r3.exceptions
        assert r3.cost != {"rescacheHits": 1}
        exp = len(_oracle(fact, dim2))
        assert int(r3.aggregation_results[0].value) == exp
        # broadcast/shuffle joins never cache server-side
        r4 = cl.broker.handle_pql(q, debug_options={"joinStrategy": "broadcast"})
        r5 = cl.broker.handle_pql(q, debug_options={"joinStrategy": "broadcast"})
        assert not r5.exceptions and "rescacheHits" not in r5.cost
    finally:
        cl.stop()


# ---------------------------------------------------------------------------
# networked broker -> server path
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_join_strategies_over_networked_cluster(tmp_path):
    """All three strategies end-to-end over REAL protocol endpoints
    (HTTP registration/heartbeats, TCP scatter) — the acceptance's
    networked broker->server path, partitioning propagated through the
    clusterstate poll."""
    from pinot_tpu.common.tableconfig import TableConfig
    from pinot_tpu.tools.cluster_harness import NetworkedCluster

    cl = NetworkedCluster(num_servers=2, data_dir=str(tmp_path))
    try:
        fact, dim = _make_rows(seed=6, n=500, keys=24)
        part = PartitionConfig(column="k", num_partitions=2)
        cl.controller.add_schema(_fact_schema("fN"))
        cl.controller.add_schema(_dim_schema("dN"))
        fphys = cl.controller.add_table(
            TableConfig(table_name="fN", table_type="OFFLINE", replication=2,
                        partitioning=part)
        )
        dphys = cl.controller.add_table(
            TableConfig(table_name="dN", table_type="OFFLINE", replication=2,
                        partitioning=part)
        )
        for p in range(2):
            cl.controller.upload_segment(
                fphys,
                build_segment(
                    _fact_schema("fN"),
                    [r for r in fact if r["k"] % 2 == p],
                    fphys,
                    segment_name=f"fN_{p}_p{p}",
                ),
            )
            cl.controller.upload_segment(
                dphys,
                build_segment(
                    _dim_schema("dN"),
                    [r for r in dim if r["k"] % 2 == p],
                    dphys,
                    segment_name=f"dN_{p}_p{p}",
                ),
            )
        joined = _oracle(fact, dim)
        q = "SELECT count(*), sum(f.v) FROM fN f JOIN dN d ON f.k = d.k"

        def serving():
            r = cl.query(q)
            return not r.exceptions and int(
                r.aggregation_results[0].value
            ) == len(joined)

        cl.wait(serving, what="join serving over the network")
        payloads = set()
        for strat in _STRATS:
            r = cl.broker.handle_pql(q, debug_options={"joinStrategy": strat})
            assert not r.exceptions, (strat, r.exceptions)
            assert int(r.aggregation_results[0].value) == len(joined)
            payloads.add(_result_payload(r))
        assert len(payloads) == 1
        # partitioning reached the networked broker via the poll
        assert cl.broker.joinplan.partitions.get("fN") == ("k", 2)
    finally:
        cl.stop()
