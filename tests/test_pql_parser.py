"""PQL parser tests (grammar semantics of the reference PQL2.g4)."""
import pytest

from pinot_tpu.common.request import FilterOperator
from pinot_tpu.pql import parse_pql, PqlParseError, optimize_request


def test_simple_selection():
    req = parse_pql("SELECT * FROM myTable")
    assert req.table_name == "myTable"
    assert req.is_selection
    assert req.selection.columns == ["*"]
    assert req.selection.size == 10  # reference default LIMIT 10


def test_selection_with_columns_limit_offset():
    req = parse_pql("select colA, colB from t limit 20, 30")
    assert req.selection.columns == ["colA", "colB"]
    assert req.selection.offset == 20
    assert req.selection.size == 30


def test_selection_order_by():
    req = parse_pql("SELECT a FROM t ORDER BY b DESC, c LIMIT 5")
    s = req.selection
    assert [(x.column, x.ascending) for x in s.sorts] == [("b", False), ("c", True)]
    assert s.size == 5


def test_aggregation():
    req = parse_pql("SELECT count(*), sum(runs), avg(hits) FROM baseball")
    assert [a.function for a in req.aggregations] == ["count", "sum", "avg"]
    assert [a.column for a in req.aggregations] == ["*", "runs", "hits"]
    assert req.aggregations[0].display_name == "count_star"
    assert req.aggregations[1].display_name == "sum_runs"


def test_group_by_top():
    req = parse_pql("SELECT sum(runs) FROM baseball GROUP BY playerName TOP 5")
    assert req.is_group_by
    assert req.group_by.columns == ["playerName"]
    assert req.group_by.top_n == 5


def test_group_by_default_top():
    req = parse_pql("SELECT sum(x) FROM t GROUP BY a, b")
    assert req.group_by.top_n == 10


def test_where_equality_and_in():
    req = parse_pql("SELECT count(*) FROM t WHERE a = 'x' AND b IN (1, 2, 3)")
    f = req.filter
    assert f.operator == FilterOperator.AND
    eq, inp = f.children
    assert eq.operator == FilterOperator.EQUALITY and eq.column == "a" and eq.values == ["x"]
    assert inp.operator == FilterOperator.IN and inp.values == ["1", "2", "3"]


def test_where_not_in_and_neq():
    req = parse_pql("SELECT count(*) FROM t WHERE a NOT IN ('x','y') AND b <> 5")
    ni, ne = req.filter.children
    assert ni.operator == FilterOperator.NOT_IN
    assert ne.operator == FilterOperator.NOT and ne.values == ["5"]


def test_where_range_between():
    req = parse_pql("SELECT count(*) FROM t WHERE x BETWEEN 10 AND 20")
    f = req.filter
    assert f.operator == FilterOperator.RANGE
    assert f.range_spec.lower == "10" and f.range_spec.upper == "20"
    assert f.range_spec.include_lower and f.range_spec.include_upper


def test_where_range_comparisons():
    req = parse_pql("SELECT count(*) FROM t WHERE x > 5 AND x <= 10")
    lo, hi = req.filter.children
    assert lo.range_spec.lower == "5" and not lo.range_spec.include_lower
    assert hi.range_spec.upper == "10" and hi.range_spec.include_upper


def test_and_binds_tighter_than_or():
    req = parse_pql("SELECT count(*) FROM t WHERE a = 1 OR b = 2 AND c = 3")
    f = req.filter
    assert f.operator == FilterOperator.OR
    assert f.children[0].operator == FilterOperator.EQUALITY
    assert f.children[1].operator == FilterOperator.AND


def test_parens():
    req = parse_pql("SELECT count(*) FROM t WHERE (a = 1 OR b = 2) AND c = 3")
    f = req.filter
    assert f.operator == FilterOperator.AND
    assert f.children[0].operator == FilterOperator.OR


def test_regexp_like():
    req = parse_pql("SELECT count(*) FROM t WHERE regexp_like(name, 'foo.*')")
    assert req.filter.operator == FilterOperator.REGEX
    assert req.filter.values == ["foo.*"]


def test_string_literals_quotes():
    req = parse_pql("SELECT count(*) FROM t WHERE a = 'it''s' OR b = \"x\"")
    assert req.filter.children[0].values == ["it's"]
    assert req.filter.children[1].values == ["x"]


def test_mixed_agg_and_column_rejected():
    with pytest.raises(PqlParseError):
        parse_pql("SELECT a, sum(b) FROM t")


def test_unknown_agg_rejected():
    with pytest.raises(PqlParseError):
        parse_pql("SELECT frobnicate(a) FROM t")


def test_having():
    req = parse_pql("SELECT sum(a) FROM t GROUP BY b HAVING sum(a) > 100")
    assert req.having is not None
    assert req.having.function == "sum" and req.having.operator == ">" and req.having.value == 100.0


def test_optimizer_or_eq_to_in():
    req = parse_pql("SELECT count(*) FROM t WHERE a = 1 OR a = 2 OR a = 3")
    optimize_request(req)
    assert req.filter.operator == FilterOperator.IN
    assert sorted(req.filter.values) == ["1", "2", "3"]


def test_optimizer_flatten():
    req = parse_pql("SELECT count(*) FROM t WHERE (a = 1 AND (b = 2 AND c = 3))")
    optimize_request(req)
    assert req.filter.operator == FilterOperator.AND
    assert len(req.filter.children) == 3


def test_mv_aggregations():
    req = parse_pql("SELECT sumMV(vals), countMV(vals) FROM t")
    assert req.aggregations[0].function == "summv"
    assert req.aggregations[0].is_mv and req.aggregations[0].base_function == "sum"


def test_trailing_semicolon_and_case():
    req = parse_pql("select SUM(x) from T where Y = 'z' group by Z top 3;")
    assert req.group_by.top_n == 3


def test_optimization_flags():
    """Per-query optimizer toggles via debugOptions optimizationFlags
    (OptimizationFlags.java: '+' enables only those listed, '-' disables
    that one, mixing is an error)."""
    import pytest

    from pinot_tpu.pql.optimizer import OptimizationFlags, optimize_request

    pql = "SELECT count(*) FROM t WHERE (a = '1' OR a = '2') AND (b = 'x' AND c = 'y')"

    # default: OR-of-equalities collapses to IN
    req = optimize_request(parse_pql(pql))
    ops = {leaf.operator for leaf in _leaves(req.filter)}
    from pinot_tpu.common.request import FilterOperator

    assert FilterOperator.IN in ops

    # disabling the IN-clause rewrite keeps the OR of equalities
    req = parse_pql(pql)
    req.debug_options = {"optimizationFlags": "-multipleOrEqualitiesToInClause"}
    req = optimize_request(req)
    ops = {leaf.operator for leaf in _leaves(req.filter)}
    assert FilterOperator.IN not in ops

    # '+' form enables only the listed optimization
    req = parse_pql(pql)
    req.debug_options = {"optimizationFlags": "+flattenNestedPredicates"}
    req = optimize_request(req)
    ops = {leaf.operator for leaf in _leaves(req.filter)}
    assert FilterOperator.IN not in ops

    # mixing + and - is rejected, as in the reference
    with pytest.raises(ValueError):
        OptimizationFlags.from_debug_options({"optimizationFlags": "+a,-b"})
    # missing prefix is rejected
    with pytest.raises(ValueError):
        OptimizationFlags.from_debug_options({"optimizationFlags": "noprefix"})


def _leaves(tree):
    if tree is None:
        return
    if tree.is_leaf:
        yield tree
        return
    for c in tree.children:
        yield from _leaves(c)


def test_having_must_name_selected_aggregation():
    import pytest

    from pinot_tpu.pql import PqlParseError, optimize_request, parse_pql

    with pytest.raises(PqlParseError, match="not\\s+in the SELECT"):
        optimize_request(
            parse_pql("SELECT sum(a) FROM t GROUP BY b HAVING count(*) > 5")
        )
    # matching spec passes through
    optimize_request(parse_pql("SELECT sum(a) FROM t GROUP BY b HAVING sum(a) > 5"))
