"""Realtime ingestion tests: mutable segments, LLC consume/commit FSM,
rollover, offset checkpointing, validation repair, hybrid federation."""
import pytest

from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema, TimeFieldSpec
from pinot_tpu.pql import parse_pql
from pinot_tpu.realtime.llc import (
    RESP_CATCH_UP,
    RESP_COMMIT,
    RESP_HOLD,
    RESP_KEEP,
    make_segment_name,
    parse_segment_name,
)
from pinot_tpu.realtime.mutable import MutableSegment
from pinot_tpu.realtime.stream import FileBasedStreamProvider, MemoryStreamProvider
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.tools.cluster_harness import InProcessCluster
from pinot_tpu.tools.scan_engine import ScanQueryProcessor
from pinot_tpu.engine.executor import QueryExecutor
from pinot_tpu.engine.reduce import reduce_to_response


def rsvp_schema():
    """meetupRsvp-style schema (RealtimeQuickStart analog)."""
    return Schema(
        "meetupRsvp",
        dimensions=[
            FieldSpec("venue_name", DataType.STRING),
            FieldSpec("event_name", DataType.STRING),
        ],
        metrics=[FieldSpec("rsvp_count", DataType.INT, FieldType.METRIC)],
        time_field=TimeFieldSpec("mtime", DataType.LONG, time_unit="MILLISECONDS"),
    )


def make_row(i):
    return {
        "venue_name": f"venue{i % 5}",
        "event_name": f"event{i % 3}",
        "rsvp_count": i % 7,
        "mtime": 1_000_000 + i,
    }


# ---------------------------------------------------------- mutable
def test_mutable_segment_snapshot_queries():
    schema = rsvp_schema()
    seg = MutableSegment(schema, "m0", "rt")
    rows = [make_row(i) for i in range(100)]
    for r in rows:
        seg.index(r)

    snap = seg.snapshot()
    assert snap.num_docs == 100
    # snapshot is cached until the watermark moves
    assert seg.snapshot() is snap
    seg.index(make_row(100))
    snap2 = seg.snapshot()
    assert snap2 is not snap and snap2.num_docs == 101

    # query the snapshot through the engine, compare vs oracle
    oracle = ScanQueryProcessor(schema, rows + [make_row(100)])
    for pql in [
        "SELECT count(*) FROM rt WHERE venue_name = 'venue1'",
        "SELECT sum(rsvp_count) FROM rt GROUP BY event_name",
        "SELECT max(mtime) FROM rt",
    ]:
        req = parse_pql(pql)
        got = reduce_to_response(req, [QueryExecutor().execute([seg.snapshot()], req)])
        want = oracle.execute(parse_pql(pql))
        assert got.to_json()["aggregationResults"] == want.to_json()["aggregationResults"]


def test_segment_name_roundtrip():
    name = make_segment_name("rt_REALTIME", 3, 7)
    assert parse_segment_name(name) == ("rt_REALTIME", 3, 7)


# ---------------------------------------------------------- llc flow
def test_consume_query_commit_rollover(tmp_path):
    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path))
    schema = rsvp_schema()
    stream = MemoryStreamProvider(num_partitions=1)
    physical = cluster.add_realtime_table(schema, stream, rows_per_segment=50)

    for i in range(120):
        stream.produce(make_row(i))

    seg0 = make_segment_name(physical, 0, 0)
    consumers = cluster.controller.realtime_manager.consumers_of(seg0)
    assert len(consumers) == 1
    dm = consumers[0]

    # consume a partial batch: rows visible to queries immediately
    dm.consume_step(max_rows=30)
    resp = cluster.query("SELECT count(*) FROM meetupRsvp")
    assert resp.num_docs_scanned == 30

    # hit the threshold -> commit -> rollover to seq 1
    dm.consume_step(max_rows=1000)
    assert dm.threshold_reached
    assert dm.try_commit() == RESP_KEEP

    ideal = cluster.controller.resources.get_ideal_state(physical)
    assert ideal[seg0] == {"server0": "ONLINE"}
    seg1 = make_segment_name(physical, 0, 1)
    assert ideal[seg1] == {"server0": "CONSUMING"}

    # committed segment checkpointed exact offsets
    info = cluster.controller.resources.get_segment_metadata(physical, seg0)
    assert info["metadata"].custom["startOffset"] == 0
    assert info["metadata"].custom["endOffset"] == 50

    # new consumer picks up from offset 50
    dm1 = cluster.controller.realtime_manager.consumers_of(seg1)[0]
    assert dm1.offset == 50
    dm1.consume_step(max_rows=1000)
    assert dm1.try_commit() == RESP_KEEP  # second segment seals at 100

    seg2 = make_segment_name(physical, 0, 2)
    dm2 = cluster.controller.realtime_manager.consumers_of(seg2)[0]
    dm2.consume_step(max_rows=1000)  # 20 rows, under threshold

    # total rows: 2 sealed segments (100) + consuming (20)
    resp = cluster.query("SELECT count(*) FROM meetupRsvp")
    assert resp.num_docs_scanned == 120

    # aggregate correctness across sealed + consuming
    oracle = ScanQueryProcessor(schema, [make_row(i) for i in range(120)])
    got = cluster.query("SELECT sum(rsvp_count) FROM meetupRsvp GROUP BY venue_name")
    want = oracle.execute(parse_pql("SELECT sum(rsvp_count) FROM meetupRsvp GROUP BY venue_name"))
    assert got.to_json()["aggregationResults"] == want.to_json()["aggregationResults"]


def test_replicated_consumers_catch_up(tmp_path):
    cluster = InProcessCluster(num_servers=2, data_dir=str(tmp_path))
    schema = rsvp_schema()
    stream = MemoryStreamProvider(num_partitions=1)
    physical = cluster.add_realtime_table(
        schema, stream, rows_per_segment=40, replication=2
    )
    for i in range(60):
        stream.produce(make_row(i))

    seg0 = make_segment_name(physical, 0, 0)
    dms = cluster.controller.realtime_manager.consumers_of(seg0)
    assert len(dms) == 2
    fast, slow = dms

    fast.consume_step(max_rows=40)
    slow.consume_step(max_rows=25)  # laggard

    # laggard reports first: HOLD (not all replicas reported)
    assert slow.try_commit() == RESP_HOLD
    # fast replica reports at 40: committer decided = fast -> COMMIT path runs
    assert fast.try_commit() == RESP_KEEP
    # laggard now catches up to the committed offset and keeps/downloads
    resp = slow.try_commit()
    assert resp in ("KEEP", "DISCARD", "CATCH_UP", "HOLD")

    # both replicas now ONLINE on the sealed segment
    view = cluster.controller.resources.get_external_view(physical)
    assert view[seg0] == {"server0": "ONLINE", "server1": "ONLINE"}
    # query still counts each row once (routing picks one replica)
    assert cluster.query("SELECT count(*) FROM meetupRsvp").num_docs_scanned >= 40


def test_validation_recreates_consuming(tmp_path):
    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path))
    schema = rsvp_schema()
    stream = MemoryStreamProvider(num_partitions=1)
    physical = cluster.add_realtime_table(schema, stream, rows_per_segment=10)
    for i in range(10):
        stream.produce(make_row(i))

    seg0 = make_segment_name(physical, 0, 0)
    dm = cluster.controller.realtime_manager.consumers_of(seg0)[0]
    dm.consume_step(max_rows=100)
    assert dm.try_commit() == RESP_KEEP

    # simulate loss of the seq-1 consuming segment (controller crash analog)
    seg1 = make_segment_name(physical, 0, 1)
    cluster.controller.resources.delete_segment(physical, seg1)
    assert seg1 not in cluster.controller.resources.get_ideal_state(physical)

    cluster.controller.validation_manager.run_once()
    ideal = cluster.controller.resources.get_ideal_state(physical)
    # recreated at the next seq after the last COMMITTED one (seq 0) -> seq 1
    assert seg1 in ideal and ideal[seg1]["server0"] == "CONSUMING"
    dm2 = cluster.controller.realtime_manager.consumers_of(seg1)[0]
    assert dm2.offset == 10  # resumes from the committed end offset


def test_file_stream_provider(tmp_path):
    import json

    p = tmp_path / "part0.jsonl"
    p.write_text("\n".join(json.dumps(make_row(i)) for i in range(25)))
    stream = FileBasedStreamProvider([str(p)])
    assert stream.partition_count() == 1
    assert stream.latest_offset(0) == 25
    rows, nxt = stream.fetch(0, 10, 10)
    assert len(rows) == 10 and nxt == 20
    rows, nxt = stream.fetch(0, 20, 10)
    assert len(rows) == 5 and nxt == 25


def test_multi_partition(tmp_path):
    cluster = InProcessCluster(num_servers=2, data_dir=str(tmp_path))
    schema = rsvp_schema()
    stream = MemoryStreamProvider(num_partitions=2)
    physical = cluster.add_realtime_table(schema, stream, rows_per_segment=1000)
    for i in range(30):
        stream.produce(make_row(i), partition=i % 2)

    for p in range(2):
        seg = make_segment_name(physical, p, 0)
        for dm in cluster.controller.realtime_manager.consumers_of(seg):
            dm.consume_step(max_rows=100)
    assert cluster.query("SELECT count(*) FROM meetupRsvp").num_docs_scanned == 30


# ---------------------------------------------------------- hybrid
def test_hybrid_time_boundary(tmp_path):
    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path))
    schema = rsvp_schema()

    # offline side: times 1_000_000..1_000_049
    offline_physical = cluster.add_offline_table(schema, table_name="meetupRsvp")
    offline_rows = [make_row(i) for i in range(50)]
    cluster.upload(offline_physical, build_segment(schema, offline_rows, offline_physical, "off0"))

    # realtime side overlaps: times 1_000_030..1_000_079 (30..79)
    stream = MemoryStreamProvider(num_partitions=1)
    rt_physical = cluster.add_realtime_table(schema, stream, rows_per_segment=1000)
    rt_rows = [make_row(i) for i in range(30, 80)]
    for r in rt_rows:
        stream.produce(r)
    seg0 = make_segment_name(rt_physical, 0, 0)
    cluster.controller.realtime_manager.consumers_of(seg0)[0].consume_step(max_rows=100)

    # federated query: boundary = offline max time (1_000_049);
    # offline answers <= boundary, realtime answers > boundary
    resp = cluster.query("SELECT count(*) FROM meetupRsvp")
    assert resp.num_docs_scanned == 80  # 0..79 counted exactly once
    assert not resp.exceptions

    resp = cluster.query("SELECT max(mtime) FROM meetupRsvp")
    assert resp.aggregation_results[0].value == 1_000_079.0


def test_index_batch_dirty_row_is_atomic():
    """Regression: a dirty value mid-batch (producer garbage a
    DataType.convert rejects) must not misalign columns — encode
    happens before any row array mutates, so the whole batch rejects
    and a corrected retry lands cleanly."""
    import pytest

    schema = rsvp_schema()
    seg = MutableSegment(schema, "atom", "t")
    seg.index_batch([make_row(i) for i in range(10)])
    bad = [make_row(10), {**make_row(11), "rsvp_count": "not-an-int"}]
    with pytest.raises(Exception):
        seg.index_batch(bad)
    assert seg.num_docs == 10
    seg.index_batch([make_row(10), make_row(11)])
    assert seg.num_docs == 12
    snap = seg.snapshot()
    assert snap.num_docs == 12
    # every column aligned: spot-check the last row round-trips
    row = snap.row(11)
    assert row["rsvp_count"] == make_row(11)["rsvp_count"]
    assert row["venue_name"] == make_row(11)["venue_name"]


def test_flaky_consumer_ingests_exactly_once(tmp_path):
    """A stream provider that fails 60% of fetches and returns short
    batches must not lose or duplicate rows: the consume/commit cycle
    retries until every segment seals at exact offsets (the
    FlakyConsumerRealtimeClusterIntegrationTest analog)."""
    from pinot_tpu.realtime.stream import FlakyStreamProvider

    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path))
    schema = rsvp_schema()
    inner = MemoryStreamProvider(num_partitions=1)
    stream = FlakyStreamProvider(inner, fail_rate=0.6, seed=42)
    physical = cluster.add_realtime_table(schema, stream, rows_per_segment=50)

    total = 173
    for i in range(total):
        inner.produce(make_row(i))

    # drive consumption with retry-on-failure, as the production
    # network consume loop does (server/network_starter.py _run)
    seq = 0
    attempts = 0
    while attempts < 4000:
        attempts += 1
        seg = make_segment_name(physical, 0, seq)
        dms = cluster.controller.realtime_manager.consumers_of(seg)
        if not dms:
            break
        dm = dms[0]
        try:
            got = dm.consume_step(max_rows=64)
        except RuntimeError:
            continue  # injected failure: retry, offsets unchanged
        if dm.threshold_reached:
            dm.try_commit()
            seq += 1
        elif got == 0:
            break
    assert stream.failures > 5  # the injection actually engaged

    # exactly-once: every row present once, sealed offsets contiguous
    resp = cluster.query("SELECT count(*) FROM meetupRsvp")
    assert resp.num_docs_scanned == total
    got = cluster.query("SELECT sum(rsvp_count) FROM meetupRsvp")
    oracle = ScanQueryProcessor(schema, [make_row(i) for i in range(total)])
    want = oracle.execute(parse_pql("SELECT sum(rsvp_count) FROM meetupRsvp"))
    assert got.to_json()["aggregationResults"] == want.to_json()["aggregationResults"]
    end = 0
    for s in range(seq):
        info = cluster.controller.resources.get_segment_metadata(
            physical, make_segment_name(physical, 0, s)
        )
        assert info["metadata"].custom["startOffset"] == end
        end = info["metadata"].custom["endOffset"]


def test_index_batch_nested_list_sv_value_is_atomic():
    """Regression: equal-length LIST values in an SV numeric column
    build a 2-D array that must be rejected in the ENCODE phase (the
    vectorized fast path), not explode in commit after other columns
    already mutated."""
    import pytest

    from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema

    schema = Schema(
        "t",
        dimensions=[
            FieldSpec("mv", DataType.INT_ARRAY, single_value=False),
            FieldSpec("a", DataType.INT),
        ],
    )
    seg = MutableSegment(schema, "nested", "t")
    with pytest.raises(Exception):
        seg.index_batch([{"mv": [1], "a": [1, 2]}, {"mv": [2], "a": [3, 4]}])
    assert seg.num_docs == 0
    seg.index_batch([{"mv": [9], "a": 7}])
    snap = seg.snapshot()
    assert snap.row(0) == {"mv": [9], "a": 7}


def test_index_batch_nan_dict_cardinality_stable():
    """Regression: NaN ingest must key the dictionary identically
    whether a batch takes the vectorized or the per-value path."""
    from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema

    schema = Schema(
        "t", metrics=[FieldSpec("m", DataType.DOUBLE, FieldType.METRIC)]
    )
    nan = float("nan")
    seg_fast = MutableSegment(schema, "f", "t")
    seg_fast.index_batch([{"m": nan}, {"m": nan}])  # no None: fast path eligible
    seg_slow = MutableSegment(schema, "s", "t")
    seg_slow.index_batch([{"m": nan}, {"m": nan}, {"m": None}])  # fallback loop
    card_fast = len(seg_fast._columns["m"].id_to_value)
    ids_slow = seg_slow._columns["m"].ids[:2].tolist()
    # both paths must key the two NaNs the same way
    assert card_fast == len(set(ids_slow))


# ------------------------------------------- columnar mode detection
def _columnar_dm(stream):
    from pinot_tpu.realtime.llc import RealtimeSegmentDataManager

    return RealtimeSegmentDataManager(
        None, None, "rt_REALTIME", "rt__0__0__t", rsvp_schema(), stream, 0, 0, 1000
    )


def _block(n, start=0):
    import numpy as np

    return {
        "venue_name": np.array([f"venue{i % 5}" for i in range(start, start + n)]),
        "event_name": np.array([f"event{i % 3}" for i in range(start, start + n)]),
        "rsvp_count": np.arange(start, start + n, dtype=np.int64) % 7,
        "mtime": np.arange(1_000_000 + start, 1_000_000 + start + n, dtype=np.int64),
    }


def test_columnar_transient_error_does_not_latch_row_mode():
    """Regression (llc.py _fetch_and_index): a transient transport error
    on the FIRST columnar fetch must re-raise — the mode is still
    unknown.  The old code latched _columnar=False, permanently wedging
    ingest on columnar partitions (whose row fetches the broker rejects
    forever) until a restart."""

    class FailOnceStream:
        def __init__(self):
            self.transport_failures = 1
            self.row_fetches = 0

        def fetch_columns(self, partition, offset):
            if self.transport_failures:
                self.transport_failures -= 1
                raise OSError("connection reset by peer")
            return _block(10), 10, offset + 10

        def fetch(self, partition, offset, max_rows):
            self.row_fetches += 1
            return [], offset

    stream = FailOnceStream()
    dm = _columnar_dm(stream)
    with pytest.raises(OSError):
        dm.consume_step()
    assert dm._columnar is None  # mode still unknown, nothing latched
    assert stream.row_fetches == 0  # never fell through to the row path
    assert dm.consume_step() == 10  # plain retry next step recovers
    assert dm._columnar is True and dm.offset == 10


def test_columnar_transient_runtime_error_unknown_mode_reraises():
    """A non-definitive RuntimeError (bad reply, truncated frame) while
    the mode is unknown re-raises too — only the broker's typed verdict
    may latch."""

    class BadReplyOnceStream:
        def __init__(self):
            self.bad = 1

        def fetch_columns(self, partition, offset):
            if self.bad:
                self.bad -= 1
                raise RuntimeError("stream broker: bad reply")
            return _block(4), 4, offset + 4

        def fetch(self, partition, offset, max_rows):
            raise AssertionError("row path must not engage")

    dm = _columnar_dm(BadReplyOnceStream())
    with pytest.raises(RuntimeError, match="bad reply"):
        dm.consume_step()
    assert dm._columnar is None
    assert dm.consume_step() == 4
    assert dm._columnar is True


def test_columnar_definitive_row_mode_latches():
    """The broker's typed row-mode rejection IS definitive: latch row
    mode and consume via the row path from then on."""

    class RowModeStream:
        def __init__(self):
            self.columnar_attempts = 0

        def fetch_columns(self, partition, offset):
            self.columnar_attempts += 1
            raise RuntimeError("stream broker: row-mode partition")

        def fetch(self, partition, offset, max_rows):
            rows = [make_row(i) for i in range(offset, min(offset + max_rows, 5))]
            return rows, offset + len(rows)

    stream = RowModeStream()
    dm = _columnar_dm(stream)
    assert dm.consume_step() == 5
    assert dm._columnar is False
    dm.consume_step()
    assert stream.columnar_attempts == 1  # latched: no more fetchc probes


def test_columnar_transport_error_on_known_columnar_reraises():
    """Once KNOWN columnar, transport errors keep re-raising (retryable)
    rather than flipping to the row path."""

    class FlakyColumnarStream:
        def __init__(self):
            self.calls = 0

        def fetch_columns(self, partition, offset):
            self.calls += 1
            if self.calls == 2:
                raise OSError("tunnel hiccup")
            return _block(3, start=offset), 3, offset + 3

        def fetch(self, partition, offset, max_rows):
            raise AssertionError("row path must not engage")

    dm = _columnar_dm(FlakyColumnarStream())
    assert dm.consume_step() == 3
    assert dm._columnar is True
    with pytest.raises(OSError):
        dm.consume_step()
    assert dm._columnar is True  # still columnar
    assert dm.consume_step() == 3  # recovers at the same offset
    assert dm.offset == 6
