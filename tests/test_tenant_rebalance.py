"""Tenant management, table rebalance, storage-quota enforcement.

Covers the reference's PinotTenantRestletResource tagging flow,
RebalanceTableCommand / Helix auto-rebalance, and the storage quota
checks validated at table/segment CRUD time (SURVEY §2.4 controller,
§3.5 "validate tenants/quota").
"""
import pytest

from pinot_tpu.common.tableconfig import QuotaConfig, TableConfig
from pinot_tpu.pql import parse_pql
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.tools.cluster_harness import InProcessCluster
from pinot_tpu.tools.datagen import make_test_schema, random_rows
from pinot_tpu.tools.scan_engine import ScanQueryProcessor


def test_quota_config_roundtrip():
    cfg = TableConfig(
        table_name="t",
        broker_tenant="brTen",
        server_tenant="srvTen",
        quota=QuotaConfig(storage="128M", max_queries_per_second=5.0),
    )
    back = TableConfig.from_json(cfg.to_json())
    assert back.broker_tenant == "brTen"
    assert back.server_tenant == "srvTen"
    assert back.quota.storage == "128M"
    assert back.quota.max_queries_per_second == 5.0
    assert back.quota.storage_bytes() == 128 * 2**20
    assert QuotaConfig(storage="2G").storage_bytes() == 2 * 2**30
    assert QuotaConfig(storage="1024").storage_bytes() == 1024
    assert QuotaConfig().storage_bytes() is None
    with pytest.raises(ValueError):
        QuotaConfig(storage="lots").storage_bytes()


def test_tenant_create_and_table_validation(tmp_path):
    cluster = InProcessCluster(num_servers=3, data_dir=str(tmp_path))
    schema = make_test_schema(with_mv=False)
    cluster.controller.add_schema(schema)
    res = cluster.controller.resources

    tagged = res.create_tenant("analyticsTenant", "server", 2)
    assert len(tagged) == 2
    assert res.tenant_instances("analyticsTenant", "server") == tagged
    assert set(res.list_tenants()["analyticsTenant"]) == set(tagged)

    # only one untagged server left; a 2-instance tenant must fail
    with pytest.raises(RuntimeError):
        res.create_tenant("otherTenant", "server", 2)

    # table on a tenant with no members is rejected at creation
    bad = TableConfig(table_name=schema.schema_name, server_tenant="ghostTenant")
    with pytest.raises(ValueError):
        cluster.controller.add_table(bad)

    # table on the real tenant: segments land only on tenant servers
    cfg = TableConfig(
        table_name=schema.schema_name, server_tenant="analyticsTenant", replication=2
    )
    physical = cluster.controller.add_table(cfg)
    rows = random_rows(schema, 120, seed=7)
    cluster.upload(physical, build_segment(schema, rows, physical, "t1"))
    ideal = res.get_ideal_state(physical)
    assert set(ideal["t1"]) == set(tagged)
    assert cluster.query("SELECT count(*) FROM testTable").num_docs_scanned == 120
    cluster.stop()


def test_rebalance_moves_segments_to_new_server(tmp_path):
    cluster = InProcessCluster(num_servers=2, data_dir=str(tmp_path))
    schema = make_test_schema(with_mv=False)
    physical = cluster.add_offline_table(schema, replication=1)
    rows = random_rows(schema, 100, seed=11)
    for i in range(6):
        cluster.upload(physical, build_segment(schema, rows[: 50 + i], physical, f"seg{i}"))

    res = cluster.controller.resources
    before = res.get_ideal_state(physical)
    assert all("server2" not in r for r in before.values())

    cluster.add_server("server2")
    dry = cluster.controller.rebalance_table(physical, dry_run=True)
    assert dry["dryRun"] and dry["segmentsMoved"] > 0
    # dry run changed nothing
    assert res.get_ideal_state(physical) == before

    result = cluster.controller.rebalance_table(physical)
    assert result["segmentsMoved"] > 0
    after = res.get_ideal_state(physical)
    counts = {}
    for replicas in after.values():
        for srv in replicas:
            counts[srv] = counts.get(srv, 0) + 1
    assert counts == {"server0": 2, "server1": 2, "server2": 2}
    # external view converged to the new ideal state
    assert res.get_external_view(physical) == after

    # queries still return complete, correct results after the moves
    oracle = ScanQueryProcessor(schema, [])
    total = sum(len(rows[: 50 + i]) for i in range(6))
    resp = cluster.query("SELECT count(*) FROM testTable")
    assert resp.num_docs_scanned == total
    assert not resp.exceptions

    # second rebalance is a no-op (already balanced)
    again = cluster.controller.rebalance_table(physical)
    assert again["segmentsMoved"] == 0
    cluster.stop()


def test_rebalance_after_server_death(tmp_path):
    cluster = InProcessCluster(num_servers=3, data_dir=str(tmp_path))
    schema = make_test_schema(with_mv=False)
    physical = cluster.add_offline_table(schema, replication=2)
    rows = random_rows(schema, 90, seed=13)
    for i in range(3):
        cluster.upload(physical, build_segment(schema, rows, physical, f"s{i}"))

    res = cluster.controller.resources
    res.set_instance_alive("server1", False)
    result = cluster.controller.rebalance_table(physical)
    after = res.get_ideal_state(physical)
    # every segment keeps 2 replicas, none on the dead server
    for seg, replicas in after.items():
        assert len(replicas) == 2
        assert "server1" not in replicas
    resp = cluster.query("SELECT count(*) FROM testTable")
    assert resp.num_docs_scanned == 270 and not resp.exceptions
    cluster.stop()


def test_tenant_rebalance_rest_endpoints(tmp_path):
    import json
    import urllib.request

    from pinot_tpu.controller.controller import ControllerHttpServer

    cluster = InProcessCluster(num_servers=2, data_dir=str(tmp_path))
    schema = make_test_schema(with_mv=False)
    physical = cluster.add_offline_table(schema)
    rows = random_rows(schema, 60, seed=19)
    cluster.upload(physical, build_segment(schema, rows, physical, "r1"))
    cluster.upload(physical, build_segment(schema, rows, physical, "r2"))

    http = ControllerHttpServer(cluster.controller)
    http.start()
    base = f"http://127.0.0.1:{http.port}"
    try:
        req = urllib.request.Request(
            base + "/tenants",
            data=json.dumps({"name": "restTenant", "role": "server", "count": 1}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=5) as r:
            out = json.loads(r.read())
        assert out["status"] == "ok" and len(out["instances"]) == 1

        with urllib.request.urlopen(base + "/tenants", timeout=5) as r:
            assert "restTenant" in json.loads(r.read())["tenants"]
        with urllib.request.urlopen(base + "/tenants/restTenant", timeout=5) as r:
            assert json.loads(r.read())["ServerInstances"] == out["instances"]

        req = urllib.request.Request(
            base + f"/tables/{physical}/rebalance?dryRun=true", data=b"{}"
        )
        with urllib.request.urlopen(req, timeout=5) as r:
            assert json.loads(r.read())["dryRun"] is True

        with urllib.request.urlopen(base + f"/tables/{physical}/size", timeout=5) as r:
            assert json.loads(r.read())["reportedSizeInBytes"] > 0
    finally:
        http.stop()
        cluster.stop()


def test_storage_quota_rejects_upload(tmp_path):
    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path))
    schema = make_test_schema(with_mv=False)
    physical = cluster.add_offline_table(
        schema, quota=QuotaConfig(storage="5K")
    )
    rows = random_rows(schema, 400, seed=17)
    cluster.upload(physical, build_segment(schema, rows[:40], physical, "small"))
    with pytest.raises(ValueError, match="storage quota"):
        cluster.upload(physical, build_segment(schema, rows, physical, "big"))
    # rejected segment left no trace: not stored, not assigned
    assert not cluster.controller.store.exists(physical, "big")
    assert "big" not in cluster.controller.resources.segments_of(physical)
    # cluster still serves the accepted segment
    assert cluster.query("SELECT count(*) FROM testTable").num_docs_scanned == 40

    # a REFRESH that would breach the quota is rejected before the store
    # is touched: the previous durable copy survives
    before = cluster.controller.store.segment_size_bytes(physical, "small")
    with pytest.raises(ValueError, match="storage quota"):
        cluster.upload(physical, build_segment(schema, rows, physical, "small"))
    assert cluster.controller.store.segment_size_bytes(physical, "small") == before
    assert cluster.query("SELECT count(*) FROM testTable").num_docs_scanned == 40
    cluster.stop()
