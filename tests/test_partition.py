"""Network-partition tolerance tests (ISSUE 9).

Chaos acceptance (``-m chaos``, tier-1, same scenario code as the
``cluster_harness`` CLI):

- ``partition-server``: a server severed from the controller for longer
  than its lease loses NO queries; its replicas move only AFTER the
  lease window (never on a missed heartbeat) and it rejoins cleanly.
- ``partition-controller``: the controller cut off from every role —
  the data plane rides it out on versioned snapshots, nothing moves,
  everything re-admits on heal.
- ``asymmetric-partition``: one-way reply loss on the realtime commit
  plane — the victim self-fences write authority while the controller
  still sees it alive; exactly one committed segment, replicas
  byte-identical, zero lost/duplicated rows.
- ``split-brain``: a zombie controller's every durable write is
  typed-rejected (``StaleEpochError``); the live controller converges.

Plus unit coverage: link injector semantics, serving-lease state
machine, property-store epoch fencing, gateway lease grants, the
stabilizer's lease fence, committer failover in the completion FSM,
and the RemoteConsumer freeze-and-retry contract.
"""
import threading
import time

import pytest

from pinot_tpu.common.faults import (
    CONTROLLER_LINK,
    LinkFaultTransport,
    NetworkFaultInjector,
    PartitionedLinkError,
)
from pinot_tpu.common.fencing import ServingLease, StaleEpochError
from pinot_tpu.controller.property_store import PropertyStore
from pinot_tpu.server.instance import ServerInstance
from pinot_tpu.tools.cluster_harness import (
    InProcessCluster,
    run_asymmetric_partition_scenario,
    run_partition_controller_scenario,
    run_partition_server_scenario,
    run_split_brain_scenario,
)
from pinot_tpu.transport.local import LocalTransport
from pinot_tpu.transport.tcp import TransportError


# ------------------------------------------------------------------
# chaos acceptance — the same scenario code the CLI runs
# ------------------------------------------------------------------
@pytest.mark.chaos
def test_partition_server_acceptance(tmp_path):
    out = run_partition_server_scenario(data_dir=str(tmp_path))
    assert out["failedQueries"] == 0, out["failures"]
    # replicas held through the lease window, moved only after it
    assert out["heldThroughLeaseWindow"], out
    assert not out["movedOnFirstMissedHeartbeat"], out
    assert out["leaseDeferrals"] > 0, out
    assert out["victimSelfFenced"], out
    assert out["replicationRestored"], out
    assert out["noDuplicateReplicas"], out
    assert out["victimReadmitted"], out
    assert out["finalComplete"] and out["finalDocs"] == out["expectedDocs"]


@pytest.mark.chaos
def test_partition_controller_acceptance(tmp_path):
    out = run_partition_controller_scenario(data_dir=str(tmp_path))
    assert out["failedQueries"] == 0, out["failures"]
    assert out["idealUnchangedDuringOutage"], out
    assert out["idealUnchangedAfterHeal"], out
    assert out["finalComplete"] and out["finalDocs"] == out["expectedDocs"]


@pytest.mark.chaos
def test_asymmetric_partition_acceptance(tmp_path):
    out = run_asymmetric_partition_scenario(data_dir=str(tmp_path))
    assert out["failedQueries"] == 0, out
    assert out["victimSelfFenced"], out
    assert out["controllerSawVictimAlive"], out
    assert out["noReplicaMovement"], out
    assert out["committedByteIdentical"], out
    assert out["finalDocs"] == out["expectedDocs"], out


@pytest.mark.chaos
def test_split_brain_acceptance(tmp_path):
    out = run_split_brain_scenario(data_dir=str(tmp_path))
    assert out["failedQueries"] == 0, out
    assert out["allStaleWritesRejected"], out["staleRejections"]
    assert out["durableStoreUnchangedByZombie"], out
    assert out["liveControllerConverged"], out
    assert out["epochB"] > out["epochA"]


# ------------------------------------------------------------------
# NetworkFaultInjector semantics
# ------------------------------------------------------------------
def test_injector_cut_drops_request_before_delivery():
    inj = NetworkFaultInjector()
    calls = []
    inj.cut("a", "b")
    with pytest.raises(PartitionedLinkError):
        inj.call("a", "b", lambda: calls.append(1))
    assert calls == []  # never delivered
    # the reverse RPC delivers (request rides b->a, which is open) but
    # its reply rides the cut a->b direction: physical one-way semantics
    delivered = []
    with pytest.raises(PartitionedLinkError):
        inj.call("b", "a", lambda: delivered.append(1))
    assert delivered == [1]
    inj.heal("a", "b")
    assert inj.call("a", "b", lambda: "ok") == "ok"
    assert inj.call("b", "a", lambda: "ok") == "ok"


def test_injector_one_way_cut_delivers_then_loses_reply():
    """Cutting only dst->src models the asymmetric partition: the
    request EXECUTES at the destination, the caller still errors."""
    inj = NetworkFaultInjector()
    inj.cut("b", "a")  # replies b->a lost
    delivered = []
    with pytest.raises(PartitionedLinkError):
        inj.call("a", "b", lambda: delivered.append(1))
    assert delivered == [1]  # side effects happened
    assert [e.outcome for e in inj.events_for("a", "b")] == ["replyDropped"]


def test_injector_duplicate_and_flaky_and_partition():
    inj = NetworkFaultInjector(seed=7)
    inj.set_link("a", "b", duplicate=True)
    n = [0]

    def fn():
        n[0] += 1
        return n[0]

    assert inj.call("a", "b", fn) == 2  # delivered twice, second reply
    assert n[0] == 2

    inj.heal()
    inj.set_link("a", "b", error_rate=1.0)
    with pytest.raises(PartitionedLinkError):
        inj.call("a", "b", lambda: "ok")

    inj.heal()
    inj.partition("a", "b")
    for src, dst in (("a", "b"), ("b", "a")):
        with pytest.raises(PartitionedLinkError):
            inj.call(src, dst, lambda: "ok")
    inj.heal("a")  # heal everything touching a
    assert inj.call("a", "b", lambda: "ok") == "ok"


def test_link_fault_transport_over_local_transport():
    transport = LocalTransport()
    transport.register(("s0", 0), lambda payload: b"pong")
    inj = NetworkFaultInjector()
    linked = LinkFaultTransport(transport, inj, src="brk")
    assert linked.request(("s0", 0), b"ping") == b"pong"
    inj.cut("brk", "s0")
    with pytest.raises(TransportError):
        linked.request(("s0", 0), b"ping")
    assert [e.outcome for e in inj.events_for("brk", "s0")] == ["ok", "dropped"]


def test_gateway_edge_injection_and_netfaults_attribution():
    """The controller-edge hook (for harnesses that cannot wire client
    processes): a cut server->controller link drops heartbeats at the
    gateway, and the fault lands on the consulted role's netfaults.*
    series."""
    from pinot_tpu.controller.network import ParticipantGateway
    from pinot_tpu.controller.resource_manager import ClusterResourceManager
    from pinot_tpu.utils.metrics import ControllerMetrics

    inj = NetworkFaultInjector()
    metrics = ControllerMetrics("controller")
    gw = ParticipantGateway(
        ClusterResourceManager(), metrics=metrics, epoch=1, fault_injector=inj
    )
    assert gw.register({"name": "s1", "role": "server"})["status"] == "ok"
    inj.cut("s1", CONTROLLER_LINK)
    with pytest.raises(PartitionedLinkError):
        gw.heartbeat("s1")
    assert metrics.meter("netfaults.dropped").count == 1
    inj.heal()
    assert gw.heartbeat("s1")["status"] == "ok"


# ------------------------------------------------------------------
# ServingLease state machine
# ------------------------------------------------------------------
def test_lease_unleased_means_implicit_authority():
    lease = ServingLease()
    assert lease.held() and not lease.granted
    assert lease.remaining_s() == float("inf")
    assert lease.epoch == -1


def test_lease_renew_expire_renew_cycle():
    clock = [100.0]
    lease = ServingLease(clock=lambda: clock[0])
    lease.renew({"epoch": 3, "durationS": 2.0})
    assert lease.held() and lease.granted and lease.epoch == 3
    assert lease.remaining_s() == pytest.approx(2.0)
    clock[0] = 101.9
    assert lease.held()
    clock[0] = 102.1  # past the window: write authority gone
    assert not lease.held()
    assert lease.remaining_s() == 0.0
    lease.renew({"epoch": 4, "durationS": 2.0})
    assert lease.held() and lease.epoch == 4
    # a legacy controller reply without a lease block changes nothing
    lease.renew(None)
    assert lease.held()


def test_lease_metrics_and_snapshot():
    from pinot_tpu.utils.metrics import ServerMetrics

    clock = [0.0]
    metrics = ServerMetrics("srvX")
    lease = ServingLease(clock=lambda: clock[0], metrics=metrics)
    assert metrics.gauge("lease.held").value == 1  # unleased = authority
    lease.renew({"epoch": 1, "durationS": 1.0})
    assert metrics.meter("lease.renewals").count == 1
    clock[0] = 2.0
    assert not lease.held()
    assert metrics.meter("lease.expiries").count == 1
    assert not lease.held()  # expiry metered once, not per poll
    assert metrics.meter("lease.expiries").count == 1
    snap = lease.snapshot()
    assert snap == {
        "granted": True, "held": False, "epoch": 1, "remainingS": 0.0
    }


# ------------------------------------------------------------------
# property-store epoch fencing
# ------------------------------------------------------------------
def test_property_store_epoch_fence(tmp_path):
    a = PropertyStore(str(tmp_path))
    assert a.stored_epoch() == 0
    assert a.claim_epoch() == 1
    a.put("tables", "t1", {"x": 1})

    b = PropertyStore(str(tmp_path))
    assert b.claim_epoch() == 2
    # the old writer is fenced from every mutation...
    with pytest.raises(StaleEpochError) as ei:
        a.put("tables", "t1", {"x": 2})
    assert ei.value.stale == 1 and ei.value.current == 2
    with pytest.raises(StaleEpochError):
        a.delete("tables", "t1")
    with pytest.raises(StaleEpochError):
        a.delete_namespace("tables")
    # ...but reads still work (a zombie may observe, never mutate)
    assert a.get("tables", "t1") == {"x": 1}
    # the live writer is unaffected
    b.put("tables", "t1", {"x": 3})
    assert b.get("tables", "t1") == {"x": 3}
    # an unfenced store (no claim) keeps working — bare/test usage
    c = PropertyStore(str(tmp_path / "other"))
    c.put("tables", "t", {"ok": True})


# ------------------------------------------------------------------
# gateway lease grants + stabilizer lease fence
# ------------------------------------------------------------------
def test_gateway_grants_lease_on_register_and_heartbeat():
    from pinot_tpu.controller.network import ParticipantGateway
    from pinot_tpu.controller.resource_manager import ClusterResourceManager

    clock = [50.0]
    res = ClusterResourceManager()
    gw = ParticipantGateway(
        res, epoch=7, lease_s=3.0, clock=lambda: clock[0]
    )
    out = gw.register({"name": "s1", "role": "server"})
    assert out["lease"] == {"epoch": 7, "durationS": 3.0}
    assert res.instances["s1"].lease_until == pytest.approx(53.0)
    assert gw.server_lease_valid("s1")

    clock[0] = 52.0
    out = gw.heartbeat("s1")
    assert out["lease"]["epoch"] == 7
    assert res.instances["s1"].lease_until == pytest.approx(55.0)

    clock[0] = 55.5  # lease ran out: confirmed-dead territory
    assert not gw.server_lease_valid("s1")
    # an instance that never heartbeat (in-process) keeps authority
    res.register_instance(
        __import__(
            "pinot_tpu.controller.resource_manager",
            fromlist=["InstanceState"],
        ).InstanceState("local0", role="server")
    )
    assert gw.server_lease_valid("local0")
    assert not gw.server_lease_valid("ghost")  # unknown: no authority


def test_stabilizer_lease_fence_defers_until_lease_expiry(tmp_path):
    """A dead-looking server whose serving lease has not expired may be
    alive-but-partitioned: nothing moves until the lease window closes
    (even with a zero grace window)."""
    from pinot_tpu.controller.stabilizer import SelfStabilizer
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.tools.datagen import make_test_schema, random_rows

    cluster = InProcessCluster(num_servers=3, data_dir=str(tmp_path))
    schema = make_test_schema(with_mv=False)
    physical = cluster.add_offline_table(schema, replication=2)
    rows = random_rows(schema, 60, seed=3)
    for i in range(3):
        cluster.upload(physical, build_segment(schema, rows, physical, f"g{i}"))
    res = cluster.controller.resources
    clock = [200.0]
    st = SelfStabilizer(res, grace_s=0.0, now=lambda: clock[0])
    before = res.get_ideal_state(physical)

    # server0 held a lease until T=210 when it went dark
    res.instances["server0"].lease_until = 210.0
    res.set_instance_alive("server0", False)
    st.run_once()
    assert res.get_ideal_state(physical) == before  # lease fence held
    assert st.metrics.meter("stabilizer.leaseDeferrals").count == 1
    clock[0] = 209.9
    st.run_once()
    assert res.get_ideal_state(physical) == before

    clock[0] = 210.1  # lease expired: confirmed dead, movement allowed
    st.run_once()
    ideal = res.get_ideal_state(physical)
    for seg, replicas in ideal.items():
        assert len([s for s in replicas if s != "server0"]) == 2
    cluster.stop()


# ------------------------------------------------------------------
# committer failover in the completion FSM (satellite)
# ------------------------------------------------------------------
def _rt_cluster(tmp_path, replication=2):
    from pinot_tpu.common.schema import (
        DataType, FieldSpec, FieldType, Schema, TimeFieldSpec,
    )
    from pinot_tpu.realtime.stream import MemoryStreamProvider

    cluster = InProcessCluster(num_servers=2, data_dir=str(tmp_path))
    schema = Schema(
        "meetupRsvp",
        dimensions=[FieldSpec("venue_name", DataType.STRING)],
        metrics=[FieldSpec("rsvp_count", DataType.INT, FieldType.METRIC)],
        time_field=TimeFieldSpec("mtime", DataType.LONG, time_unit="MILLISECONDS"),
    )
    stream = MemoryStreamProvider(num_partitions=1)
    physical = cluster.add_realtime_table(
        schema, stream, rows_per_segment=50, replication=replication
    )
    for i in range(50):
        stream.produce(
            {"venue_name": f"v{i % 3}", "rsvp_count": i % 5, "mtime": 10_000 + i}
        )
    return cluster, physical, stream


def test_committer_partitioned_mid_upload_fails_over(tmp_path):
    """Acceptance (c): committer elected, partitioned away mid-upload,
    lease expires -> a caught-up replica is re-elected and commits;
    the old committer's late segmentCommit is rejected by the lease/
    leadership fence; exactly one committed copy, byte-identical on
    every replica, zero lost or duplicated rows."""
    from pinot_tpu.realtime.llc import make_segment_name

    cluster, physical, stream = _rt_cluster(tmp_path)
    rm = cluster.controller.realtime_manager
    completion = rm.completion
    res = cluster.controller.resources
    seg0 = make_segment_name(physical, 0, 0)
    dms = {dm.server.name: dm for dm in rm.consumers_of(seg0)}
    assert set(dms) == {"server0", "server1"}
    for dm in dms.values():
        dm.consume_step(max_rows=1000)
        assert dm.offset == 50

    # lease plane: both replicas leased, then the elected committer's
    # lease expires (it is partitioned away)
    leases = {"server0": True, "server1": True}
    completion.lease_checker = lambda s: leases[s]

    # both report; max-offset tie -> name order picks server1
    resp, _ = completion.segment_consumed(seg0, "server0", 50)
    assert resp == "HOLD"
    resp, _ = completion.segment_consumed(seg0, "server1", 50)
    assert resp == "COMMIT"  # server1 elected, told to upload...

    # ...and vanishes mid-upload: its lease expires before the bytes land
    leases["server1"] = False
    committed_late = dms["server1"].mutable.to_committed_segment()

    # the surviving replica's next round re-elects it
    resp, _ = completion.segment_consumed(seg0, "server0", 50)
    assert resp == "COMMIT"
    meters = cluster.controller.metrics
    assert meters.meter("fence.committerReElections").count == 1
    committed = dms["server0"].mutable.to_committed_segment()
    assert completion.segment_commit(seg0, "server0", committed) == "KEEP"

    # the old committer's LATE upload bounces off the fence
    assert completion.segment_commit(seg0, "server1", committed_late) == "NOT_LEADER"
    assert meters.meter("fence.leaseRejections").count == 1
    # ... and its next consumed round learns the final verdict (KEEP:
    # it consumed exactly the committed range)
    resp, target = completion.segment_consumed(seg0, "server1", 50)
    assert resp == "KEEP" and target == 50

    # exactly one committed copy at the committed offset
    info = res.get_segment_metadata(physical, seg0)
    assert info["metadata"].custom.get("endOffset") == 50
    ideal = res.get_ideal_state(physical)
    assert all(st == "ONLINE" for st in ideal[seg0].values())
    # replicas serve byte-identical committed bytes
    crcs = set()
    for server in cluster.servers:
        tdm = server.data_manager.table(physical)
        acquired = tdm.acquire_segments([seg0])
        try:
            crcs.update(d.segment.metadata.crc for d in acquired)
        finally:
            tdm.release_segments(acquired)
    assert len(crcs) == 1
    # zero lost, zero duplicated rows vs consumed offsets
    result = cluster.query("SELECT count(*) FROM meetupRsvp")
    assert result.num_docs_scanned == 50 and not result.exceptions
    cluster.stop()


def test_committer_stall_reelects_despite_valid_controller_side_lease(tmp_path):
    """ONE-WAY partition on the commit plane: the victim committer's
    heartbeats still reach the controller (its controller-side lease
    keeps renewing) while its self-fenced commit plane goes silent —
    lease validity alone cannot detect this.  The commit-stall window
    re-elects a caught-up replica, and the old committer's late upload
    is answered idempotently (no double commit)."""
    from pinot_tpu.realtime.llc import make_segment_name

    cluster, physical, stream = _rt_cluster(tmp_path)
    rm = cluster.controller.realtime_manager
    completion = rm.completion
    seg0 = make_segment_name(physical, 0, 0)
    dms = {dm.server.name: dm for dm in rm.consumers_of(seg0)}
    for dm in dms.values():
        dm.consume_step(max_rows=1000)
        assert dm.offset == 50

    # the controller-side lease plane sees BOTH replicas alive forever
    completion.lease_checker = lambda s: True
    fake_now = [1000.0]
    completion.clock = lambda: fake_now[0]

    resp, _ = completion.segment_consumed(seg0, "server0", 50)
    assert resp == "HOLD"
    resp, _ = completion.segment_consumed(seg0, "server1", 50)
    assert resp == "COMMIT"  # server1 elected committer
    late = dms["server1"].mutable.to_committed_segment()

    # server1 goes protocol-silent.  Inside the stall window the
    # survivor just holds...
    fake_now[0] += completion.commit_stall_ms / 1000.0 / 2.0
    resp, _ = completion.segment_consumed(seg0, "server0", 50)
    assert resp == "HOLD"
    # ...past it, the survivor is re-elected and commits
    fake_now[0] += completion.commit_stall_ms / 1000.0
    resp, _ = completion.segment_consumed(seg0, "server0", 50)
    assert resp == "COMMIT"
    meters = cluster.controller.metrics
    assert meters.meter("fence.committerReElections").count == 1
    committed = dms["server0"].mutable.to_committed_segment()
    assert completion.segment_commit(seg0, "server0", committed) == "KEEP"

    # the old committer's late upload cannot double-commit: it lands on
    # the COMMITTED short-circuit (its lease is still valid, and it
    # consumed exactly the committed range, so KEEP is the idempotent
    # duplicate-upload answer) — persisted exactly once
    assert completion.segment_commit(seg0, "server1", late) == "KEEP"
    assert meters.meter("segmentCommits").count == 1
    result = cluster.query("SELECT count(*) FROM meetupRsvp")
    assert result.num_docs_scanned == 50 and not result.exceptions
    cluster.stop()


def test_completion_epoch_fence_rejects_stale_epochs(tmp_path):
    """Commit-plane calls carrying the WRONG incarnation's lease epoch
    raise the typed StaleEpochError (both too-old and too-new: a zombie
    controller must not act on its successor's committers either)."""
    from pinot_tpu.realtime.llc import make_segment_name

    cluster, physical, stream = _rt_cluster(tmp_path, replication=1)
    completion = cluster.controller.realtime_manager.completion
    seg0 = make_segment_name(physical, 0, 0)
    current = cluster.controller.epoch

    with pytest.raises(StaleEpochError):
        completion.segment_consumed(seg0, "server0", 50, epoch=current - 1)
    with pytest.raises(StaleEpochError):
        completion.segment_consumed(seg0, "server0", 50, epoch=current + 1)
    with pytest.raises(StaleEpochError):
        completion.segment_commit(seg0, "server0", None, epoch=current - 1)
    assert (
        cluster.controller.metrics.meter("fence.staleEpochRejections").count == 3
    )
    # current epoch and epoch-less legacy callers pass the fence
    resp, _ = completion.segment_consumed(seg0, "server0", 10, epoch=current)
    assert resp in ("HOLD", "CATCH_UP", "COMMIT")
    resp, _ = completion.segment_consumed(seg0, "server0", 10)
    assert resp in ("HOLD", "CATCH_UP", "COMMIT")
    cluster.stop()


def test_inprocess_try_commit_freezes_without_lease(tmp_path):
    """The in-process consumer's write path honors the lease fence too:
    an expired lease freezes try_commit (HOLD, offset intact)."""
    from pinot_tpu.realtime.llc import make_segment_name

    cluster, physical, stream = _rt_cluster(tmp_path, replication=1)
    rm = cluster.controller.realtime_manager
    seg0 = make_segment_name(physical, 0, 0)
    dm = rm.consumers_of(seg0)[0]
    dm.consume_step(max_rows=1000)
    server = dm.server

    clock = [0.0]
    server.lease = ServingLease(clock=lambda: clock[0])
    server.lease.renew({"epoch": cluster.controller.epoch, "durationS": 1.0})
    clock[0] = 5.0  # expired: no write authority
    assert dm.try_commit() == "HOLD"
    assert dm.offset == 50  # frozen, not reset
    blocked = server.metrics.meter("lease.blockedCommits").count
    assert blocked == 1

    clock[0] = 5.5
    server.lease.renew({"epoch": cluster.controller.epoch, "durationS": 10.0})
    assert dm.try_commit() == "KEEP"  # committed once authority returned
    cluster.stop()


# ------------------------------------------------------------------
# RemoteConsumer freeze-and-retry (satellite)
# ------------------------------------------------------------------
class _StubStarter:
    """Just enough NetworkedServerStarter surface for a RemoteConsumer."""

    def __init__(self, name="srvX"):
        self.name = name
        self.server = ServerInstance(name)
        self.posts = []
        self.fail_posts = False
        self.post_reply = {"response": "HOLD", "targetOffset": None}

    def _post(self, path, payload):
        self.posts.append((path, payload))
        if self.fail_posts:
            raise OSError("connection refused")
        return dict(self.post_reply)

    def upload_segment_bytes(self, path, segment):
        raise OSError("connection refused")


def _remote_consumer(starter):
    from pinot_tpu.server.network_starter import RemoteConsumer

    schema_json = {
        "schemaName": "t",
        "dimensionFieldSpecs": [{"name": "d", "dataType": "STRING"}],
        "metricFieldSpecs": [{"name": "m", "dataType": "INT"}],
    }
    msg = {
        "streamDescriptor": {"type": "memory", "partitions": 1},
        "schemaJson": schema_json,
        "partition": 0,
        "startOffset": 17,
        "rowsPerSegment": 100,
    }
    return RemoteConsumer(starter, "t_REALTIME", "t_REALTIME__0__0", msg,
                          poll_interval_s=0.01)


def test_remote_consumer_freezes_on_unreachable_controller():
    """Controller unreachability mid-protocol = freeze-and-retry: the
    round returns False, the offset is untouched, the backoff escalates
    with full jitter, and a later success resets it."""
    starter = _StubStarter()
    consumer = _remote_consumer(starter)
    consumer.stop()  # no thread: we drive rounds by hand

    starter.fail_posts = True
    t0 = time.monotonic()
    assert consumer._completion_round() is False
    assert consumer._completion_round() is False
    assert consumer.offset == 17  # frozen
    assert consumer._ctrl_backoff.failures == 2
    assert time.monotonic() - t0 < 5.0  # jittered, bounded waits

    starter.fail_posts = False
    assert consumer._completion_round() is False  # HOLD reply
    assert consumer._ctrl_backoff.failures == 0  # reset on success
    # the protocol payload carries the server's lease epoch slot
    assert starter.posts[-1][1]["segment"] == "t_REALTIME__0__0"
    assert "epoch" in starter.posts[-1][1]
    starter.server.shutdown()


def test_remote_consumer_commit_unreachable_freezes_not_fails():
    """A commit upload that cannot reach the controller freezes the
    round (the copy may have landed with only the reply lost — the next
    segmentConsumed resolves it idempotently)."""
    starter = _StubStarter()
    consumer = _remote_consumer(starter)
    consumer.stop()
    starter.post_reply = {"response": "COMMIT", "targetOffset": 17}
    assert consumer._completion_round() is False  # upload raised -> frozen
    assert consumer.offset == 17
    assert consumer._ctrl_backoff.failures >= 1
    starter.server.shutdown()


def test_remote_consumer_lease_expiry_blocks_round():
    starter = _StubStarter()
    consumer = _remote_consumer(starter)
    consumer.stop()
    clock = [0.0]
    starter.server.lease = ServingLease(clock=lambda: clock[0])
    starter.server.lease.renew({"epoch": 5, "durationS": 1.0})
    clock[0] = 2.0  # expired
    assert consumer._completion_round() is False
    assert starter.posts == []  # never reached the controller
    clock[0] = 2.5
    starter.server.lease.renew({"epoch": 6, "durationS": 5.0})
    assert consumer._completion_round() is False  # HOLD reply flows again
    assert starter.posts[-1][1]["epoch"] == 6
    starter.server.shutdown()


# ------------------------------------------------------------------
# broker snapshot hold (all-dead snapshots are suspect)
# ------------------------------------------------------------------
def test_broker_holds_routing_on_all_dead_snapshot():
    """A snapshot claiming EVERY server is dead is indistinguishable
    from the controller having been the partitioned one (post-heal,
    the fleet's heartbeats may simply not have landed yet): the broker
    keeps its last routing and refetches until servers reappear."""
    from pinot_tpu.broker.network_starter import NetworkedBrokerStarter

    starter = NetworkedBrokerStarter("http://127.0.0.1:9")  # never polled
    h = starter.handler
    base = {
        "epoch": "1", "drainingServers": [], "quotas": {},
        "timeBoundaries": {},
    }
    starter._apply_state(
        dict(
            base, version=5, servers={"s0": ["127.0.0.1", 1234]},
            deadServers=[], tables={"t_OFFLINE": {"seg0": {"s0": "ONLINE"}}},
        )
    )
    assert starter._version == 5 and "t_OFFLINE" in h.routing.tables()

    starter._apply_state(
        dict(
            base, version=6, servers={}, deadServers=["s0"],
            tables={"t_OFFLINE": {"seg0": {}}},
        )
    )
    assert starter._version == 5  # held: version NOT advanced
    assert "t_OFFLINE" in h.routing.tables()  # routing intact
    assert h.metrics.meter("controller.allDeadSnapshotsHeld").count == 1

    # a snapshot with live servers applies normally again
    starter._apply_state(
        dict(
            base, version=7, servers={"s0": ["127.0.0.1", 1234]},
            deadServers=[], tables={"t_OFFLINE": {"seg0": {"s0": "ONLINE"}}},
        )
    )
    assert starter._version == 7


# ------------------------------------------------------------------
# jittered backoff helper
# ------------------------------------------------------------------
def test_full_jitter_backoff_escalates_and_resets():
    from pinot_tpu.utils.retry import FullJitterBackoff

    b = FullJitterBackoff(initial_s=0.1, cap_s=1.0, seed=42)
    delays = [b.next_delay() for _ in range(8)]
    assert all(0.0 <= d <= 1.0 for d in delays)
    assert b.failures == 8
    # the window is capped
    assert max(delays) <= 1.0
    b.reset()
    assert b.failures == 0
    assert b.next_delay() <= 0.1  # back to the fast first retry
