"""Bit-sliced filter/aggregate tier (engine/bitsliced.py, r17):
encode/decode round-trips, kernel vs numpy oracle, tier selection +
EXPLAIN honesty, env-tunable crossovers, and end-to-end bit-exactness
against the scan tier."""
import numpy as np
import pytest

from pinot_tpu.engine.packing import (
    bit_width,
    bitslice_decode,
    bitslice_encode,
    integral_dictionary_values,
)

jax = pytest.importorskip("jax")


# ------------------------------------------------------- encode/decode
def _roundtrip(values, width, n_rows=None):
    n = len(values) if n_rows is None else n_rows
    n_words = (max(n, 1) + 31) // 32
    planes = bitslice_encode(np.asarray(values), width, n_words)
    assert planes.shape == (width, n_words) and planes.dtype == np.uint32
    out = bitslice_decode(planes, len(values))
    np.testing.assert_array_equal(out, np.asarray(values, dtype=np.int64))
    return planes


def test_roundtrip_widths_and_word_edges():
    rng = np.random.default_rng(3)
    for width in (1, 2, 5, 12, 31, 32):
        hi = (1 << width) - 1 if width < 32 else (1 << 32) - 1
        # non-multiple-of-32 row counts cross word boundaries
        for n in (1, 31, 32, 33, 97):
            vals = rng.integers(0, hi, size=n, endpoint=True, dtype=np.uint64)
            _roundtrip(vals.astype(np.int64), width)


def test_roundtrip_extremes_width1_width32():
    _roundtrip([0, 1, 1, 0, 1], 1)
    hi = (1 << 32) - 1
    planes = _roundtrip([0, hi, 12345, hi - 1], 32)
    assert planes.shape[0] == 32


def test_encode_out_of_range_raises():
    with pytest.raises(ValueError):
        bitslice_encode(np.array([4]), width=2, n_words=1)
    with pytest.raises(ValueError):
        bitslice_encode(np.array([-1]), width=4, n_words=1)


def test_signed_values_roundtrip_via_offset():
    # signed domains are encoded as offsets from the per-segment min
    # (StagedColumn.bsiv_min) — the encoder itself is unsigned
    vals = np.array([-7, -3, 0, 12, 40], dtype=np.int64)
    off = vals - vals.min()
    width = bit_width(int(off.max()))
    planes = bitslice_encode(off, width, 1)
    back = bitslice_decode(planes, len(vals)) + vals.min()
    np.testing.assert_array_equal(back, vals)


def test_bit_width():
    assert bit_width(0) == 1
    assert bit_width(1) == 1
    assert bit_width(2) == 2
    assert bit_width(255) == 8
    assert bit_width(256) == 9


def test_integral_dictionary_values():
    ok = integral_dictionary_values(np.array([1.0, 50.0, 3.0]))
    assert ok is not None and ok.dtype == np.int64
    np.testing.assert_array_equal(ok, [1, 50, 3])
    assert integral_dictionary_values(np.array([1.5, 2.0])) is None
    assert integral_dictionary_values(np.array([np.nan, 1.0])) is None
    assert integral_dictionary_values(np.array([2.0**53, 1.0])) is None
    assert integral_dictionary_values(np.array(["a", "b"])) is None
    ints = integral_dictionary_values(np.array([3, 9], dtype=np.int32))
    np.testing.assert_array_equal(ints, [3, 9])


# ------------------------------------------------- kernel vs numpy oracle
def _encode_seg(ids, n_pad, width):
    return bitslice_encode(ids, width, n_pad // 32)


def test_kernel_matches_numpy_oracle():
    """Interval/points/negated-points leaves under an AND/OR tree with
    fused count/sum/min/max, across segments with UNEVEN doc counts
    (the validity mask must clip padding rows)."""
    from pinot_tpu.engine.kernel import make_packed_bitsliced_kernel

    rng = np.random.default_rng(11)
    n_pad, width, vwidth = 1024, 5, 6
    docs = [1000, 737]  # second segment ends mid-word
    ids = [rng.integers(0, 32, size=n_pad).astype(np.int64) for _ in docs]
    vals = [(i * 2) % 61 for i in ids]  # integral "values" per dict id

    spec = (
        (("interval", "c", width, 0), ("points", "c", width, 4)),
        ("or", ("leaf", 0), ("leaf", 1)),
        (("c", vwidth),),
        (("c", width, True), ("c", width, False)),
    )
    kern = make_packed_bitsliced_kernel(spec)

    segs = {
        "nd": np.array(docs, dtype=np.int32),
        "p:c": np.stack([_encode_seg(i, n_pad, width) for i in ids]),
        "v:c": np.stack([_encode_seg(v, n_pad, vwidth) for v in vals]),
    }
    q = {
        # kernel bounds are half-open [lo, hi): 3 <= id <= 9
        "bounds:0": np.array([[3, 10]] * 2, dtype=np.int32),
        "pts:1": np.array([[20, 25, -1, -1]] * 2, dtype=np.int32),
    }
    outs = kern(segs, q)

    for s, nd in enumerate(docs):
        i, v = ids[s][:nd], np.asarray(vals[s][:nd])
        m = ((i >= 3) & (i <= 9)) | np.isin(i, [20, 25])
        assert int(outs["count"][s]) == int(m.sum())
        got_sum = sum(
            (1 << b) * int(outs["psum:c"][s][b]) for b in range(vwidth)
        )
        assert got_sum == int(v[m].sum())
        if m.any():
            assert int(outs["ext:mx:c"][s]) == int(i[m].max())
            assert int(outs["ext:mn:c"][s]) == int(i[m].min())


def test_kernel_negated_points_and_full_interval():
    from pinot_tpu.engine.kernel import make_packed_bitsliced_kernel

    rng = np.random.default_rng(5)
    n_pad, width = 1024, 4
    nd = 990
    ids = rng.integers(0, 16, size=n_pad).astype(np.int64)
    spec = (
        (("points_none", "c", width, 2),),
        ("leaf", 0),
        (),
        (),
    )
    kern = make_packed_bitsliced_kernel(spec)
    segs = {
        "nd": np.array([nd], dtype=np.int32),
        "p:c": _encode_seg(ids, n_pad, width)[None],
    }
    q = {"pts:0": np.array([[7, 9]], dtype=np.int32)}
    outs = kern(segs, q)
    ref = int((~np.isin(ids[:nd], [7, 9])).sum())
    assert int(outs["count"][0]) == ref

    # hi >= 2^width must select every live row, not wrap
    spec2 = ((("interval", "c", width, 0),), ("leaf", 0), (), ())
    kern2 = make_packed_bitsliced_kernel(spec2)
    q2 = {"bounds:0": np.array([[0, 1 << width]], dtype=np.int32)}
    outs2 = kern2(segs, q2)
    assert int(outs2["count"][0]) == nd


# ----------------------------------------------- end-to-end + selection
@pytest.fixture(scope="module")
def lineitem():
    from pinot_tpu.engine.executor import QueryExecutor
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment

    segs = [
        synthetic_lineitem_segment(20000, seed=7, name="bsl0"),
        synthetic_lineitem_segment(15000, seed=11, name="bsl1"),
    ]
    return QueryExecutor(), segs


def _run(ex, segs, pql):
    from pinot_tpu.engine.reduce import reduce_to_response
    from pinot_tpu.pql import parse_pql, optimize_request

    req = optimize_request(parse_pql(pql))
    res = ex.execute(segs, req)
    return res, reduce_to_response(req, [res])


BIT_EXACT_CASES = [
    "SELECT sum(l_quantity), count(*), min(l_quantity), max(l_quantity), "
    "avg(l_quantity) FROM lineitem WHERE l_extendedprice BETWEEN 10000 AND 50000",
    "SELECT count(*), sum(l_quantity) FROM lineitem "
    "WHERE l_quantity IN (5, 10, 15) AND l_extendedprice > 30000",
    "SELECT count(*) FROM lineitem "
    "WHERE l_quantity NOT IN (1, 2) OR l_extendedprice < 20000",
    "SELECT min(l_extendedprice), max(l_extendedprice) FROM lineitem "
    "WHERE l_quantity = 25",
]


@pytest.mark.parametrize("pql", BIT_EXACT_CASES)
def test_bit_exact_vs_scan(lineitem, monkeypatch, pql):
    """The fused path must return byte-identical answers to the scan
    tier — fused SUM in exact integer arithmetic, extremes round-
    tripped through the device value dtype."""
    ex, segs = lineitem
    monkeypatch.setenv("PINOT_TPU_BITSLICED", "force")
    res, resp = _run(ex, segs, pql)
    assert res.cost.get("segmentsBitsliced") == len(segs), res.cost
    monkeypatch.setenv("PINOT_TPU_BITSLICED", "0")
    res2, resp2 = _run(ex, segs, pql)
    assert not res2.cost.get("segmentsBitsliced"), res2.cost
    assert [a.value for a in resp.aggregation_results] == [
        a.value for a in resp2.aggregation_results
    ]


def test_empty_match_and_disable(lineitem, monkeypatch):
    ex, segs = lineitem
    monkeypatch.setenv("PINOT_TPU_BITSLICED", "force")
    # a 0-match filter is legitimately postings turf; pin it off so the
    # empty-bitmap edge (garbage extreme ids, zero psum) is exercised
    monkeypatch.setenv("PINOT_TPU_INVINDEX", "0")
    pql = (
        "SELECT count(*), sum(l_quantity), min(l_quantity) FROM lineitem "
        "WHERE l_extendedprice < 0"
    )
    res, resp = _run(ex, segs, pql)
    assert res.cost.get("segmentsBitsliced") == len(segs)
    vals = [a.value for a in resp.aggregation_results]
    monkeypatch.setenv("PINOT_TPU_BITSLICED", "0")
    _, resp2 = _run(ex, segs, pql)
    assert vals == [a.value for a in resp2.aggregation_results]


def test_restaging_after_segment_set_change(lineitem, monkeypatch):
    """Staging-token participation: adding a segment (or reloading one
    under a fresh token) re-stages the bit planes and the answers
    track the new data — no stale-plane serving."""
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment

    ex, segs = lineitem
    monkeypatch.setenv("PINOT_TPU_BITSLICED", "force")
    pql = "SELECT count(*) FROM lineitem WHERE l_quantity > 10"
    res1, resp1 = _run(ex, segs[:1], pql)
    assert res1.cost.get("segmentsBitsliced") == 1
    # grow the serving set past the staged watermark
    res2, resp2 = _run(ex, segs, pql)
    assert res2.cost.get("segmentsBitsliced") == 2
    assert resp2.aggregation_results[0].value > resp1.aggregation_results[0].value
    # a RE-LOADED twin (same name, fresh staging token, different rows)
    # must not alias the old planes
    twin = synthetic_lineitem_segment(9000, seed=23, name="bsl0")
    res3, resp3 = _run(ex, [twin], pql)
    assert res3.cost.get("segmentsBitsliced") == 1
    monkeypatch.setenv("PINOT_TPU_BITSLICED", "0")
    _, ref3 = _run(ex, [twin], pql)
    assert resp3.aggregation_results[0].value == ref3.aggregation_results[0].value


def test_ineligible_shapes_fall_through(lineitem, monkeypatch):
    """force skips the cost model, never structural eligibility:
    group-by, selection, and unfiltered queries serve from the other
    tiers."""
    ex, segs = lineitem
    monkeypatch.setenv("PINOT_TPU_BITSLICED", "force")
    for pql in (
        "SELECT count(*) FROM lineitem",  # no filter
        "SELECT sum(l_quantity) FROM lineitem WHERE l_quantity > 5 "
        "GROUP BY l_returnflag",
        "SELECT l_quantity FROM lineitem WHERE l_quantity > 5 LIMIT 3",
    ):
        res, _ = _run(ex, segs, pql)
        assert not res.cost.get("segmentsBitsliced"), (pql, res.cost)


def test_cost_model_and_knobs(lineitem, monkeypatch):
    """Auto mode takes the tier exactly when the cost model picks it,
    and the PINOT_TPU_TIER_COST_* knobs move the crossover."""
    ex, segs = lineitem
    pql = (
        "SELECT sum(l_quantity), count(*) FROM lineitem "
        "WHERE l_extendedprice BETWEEN 10000 AND 60000"
    )
    monkeypatch.delenv("PINOT_TPU_BITSLICED", raising=False)
    res, _ = _run(ex, segs, pql)
    assert res.cost.get("segmentsBitsliced") == len(segs), res.cost
    # price the plane pass absurdly high: the model must hand the
    # query back to the scan
    monkeypatch.setenv("PINOT_TPU_TIER_COST_BSI_NS_PER_ROW_PER_PLANE", "1000")
    res2, _ = _run(ex, segs, pql)
    assert not res2.cost.get("segmentsBitsliced"), res2.cost


def test_tiercost_env_knobs_defaults_unchanged(monkeypatch):
    from pinot_tpu.engine import tiercost

    monkeypatch.delenv("PINOT_TPU_TIER_COST_POSTINGS_MATCH_FRACTION", raising=False)
    # the default reproduces the historical total_docs // 64 exactly
    for n in (0, 63, 64, 6400, 16_777_216):
        assert tiercost.postings_max_matches(n) == n // 64
    monkeypatch.setenv("PINOT_TPU_TIER_COST_POSTINGS_MATCH_FRACTION", "0.5")
    assert tiercost.postings_max_matches(100) == 50
    monkeypatch.setenv("PINOT_TPU_TIER_COST_BSI_MAX_PLANES", "3")
    assert tiercost.bsi_max_planes() == 3


def test_explain_reports_bitsliced_tier(monkeypatch):
    """EXPLAIN must say 'bitsliced' exactly when the executor would
    take it, with plane counts + fused-agg flags, and launch nothing."""
    from pinot_tpu.tools.cluster_harness import single_server_broker
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment

    segs = [synthetic_lineitem_segment(20000, seed=3, name="bsix0")]
    broker = single_server_broker("lineitem", segs)
    monkeypatch.delenv("PINOT_TPU_BITSLICED", raising=False)
    pql = (
        "EXPLAIN SELECT sum(l_quantity), count(*) FROM lineitem "
        "WHERE l_extendedprice BETWEEN 10000 AND 60000"
    )
    resp = broker.handle_pql(pql)
    assert not resp.exceptions, resp.exceptions
    node = resp.to_json()["explain"]["servers"][0]
    tiers = {s["segment"]: s for s in node["segments"]}
    seg = tiers["bsix0"]
    assert seg["tier"] == "bitsliced", seg
    assert seg["planes"] > 0 and seg["planeCounts"]
    assert any(a.startswith("sum") for a in seg["fusedAggs"])
    assert node["tierCounts"].get("segmentsBitsliced") == 1

    # flip the cost model off: EXPLAIN must agree with the executor
    monkeypatch.setenv("PINOT_TPU_BITSLICED", "0")
    resp2 = broker.handle_pql(pql)
    node2 = resp2.to_json()["explain"]["servers"][0]
    assert all(s["tier"] != "bitsliced" for s in node2["segments"])
    broker.local_servers[0].shutdown()


def test_batched_bsi_dispatches_match_serial(monkeypatch):
    """Lane micro-batching on the bit-sliced tier (r18): same-spec
    distinct-literal BSI queries queued on a blocked lane gather into
    one batched plane launch, and every member's payload is identical
    to the serial (no-lane) executor's — the counters prove real
    batches formed on the BSI path, not the scan tier."""
    import json
    import threading
    import time

    from pinot_tpu.tools.cluster_harness import single_server_broker
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment

    monkeypatch.setenv("PINOT_TPU_BITSLICED", "force")
    segs = [
        synthetic_lineitem_segment(8000, seed=7, name="bbat0"),
        synthetic_lineitem_segment(6000, seed=11, name="bbat1"),
    ]
    serial = single_server_broker("lineitem", segs, pipeline=False)
    pipelined = single_server_broker("lineitem", segs, pipeline=True)

    def payload(resp):
        return json.dumps(
            {
                k: v
                for k, v in resp.to_json().items()
                if k not in ("timeUsedMs", "requestId", "cost")
            },
            sort_keys=True,
        )

    queries = [
        "SELECT count(*), sum(l_quantity) FROM lineitem "
        f"WHERE l_extendedprice BETWEEN 10000 AND {t}"
        for t in (30000, 35000, 40000, 45000)
    ]
    # warm staging + plane compile so formation isn't skewed by a cold
    # compile holding the lane
    r = pipelined.handle_pql(queries[0])
    assert not r.exceptions, r.exceptions
    assert r.cost.get("segmentsBitsliced") == len(segs), r.cost

    server = pipelined.local_servers[0]
    gate = threading.Event()
    server.lane.submit(("blocker", time.monotonic()), lambda: gate.wait(15))
    time.sleep(0.05)
    results = {}
    errs = []

    def run(q):
        try:
            results[q] = pipelined.handle_pql(q)
        except Exception as e:  # pragma: no cover - fail loudly below
            errs.append((q, e))

    threads = [threading.Thread(target=run, args=(q,)) for q in queries]
    for t in threads:
        t.start()
    time.sleep(0.8)  # let every PREP finish and queue on the lane
    gate.set()
    for t in threads:
        t.join()
    assert not errs, errs[:1]

    stats = server.lane.stats()
    assert stats["batchLaunches"] >= 1, stats
    assert stats["batchedQueries"] >= 2, stats
    batched_hits = 0
    for q in queries:
        resp = results[q]
        assert not resp.exceptions, (q, resp.exceptions)
        # every member really served from the bit-sliced tier
        assert resp.cost.get("segmentsBitsliced") == len(segs), (q, resp.cost)
        assert payload(serial.handle_pql(q)) == payload(resp), q
        batched_hits += int(resp.cost.get("batchHits", 0))
    assert batched_hits >= 2  # the differential exercised real batches
    serial.local_servers[0].shutdown()
    pipelined.local_servers[0].shutdown()
