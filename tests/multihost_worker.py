"""Worker for the true multi-process multi-host test
(tests/test_multihost_process.py): each OS process is one "host" of a
2-host CPU cluster.

Run as: python tests/multihost_worker.py <coordinator> <num_procs> <pid>

Brings up jax's distributed runtime (the real multi-host wiring:
coordinator service, process ids, global device view), builds the
2-D (hosts, chips) mesh with ``make_multihost_mesh`` — the SAME
function a real TPU pod slice uses — and executes the production
sharded query kernel over it, printing this process's view of the
globally-reduced result.
"""
import os
import sys

# 4 virtual CPU devices per process -> 8 global across 2 processes;
# gloo backs the cross-process CPU collectives
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
)
os.environ.setdefault("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    coordinator, num_procs, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    jax.distributed.initialize(
        coordinator_address=coordinator, num_processes=num_procs, process_id=pid
    )
    assert jax.process_count() == num_procs, jax.process_count()
    assert jax.local_device_count() == 4
    assert jax.device_count() == 4 * num_procs

    import numpy as np

    from pinot_tpu.engine.context import get_table_context
    from pinot_tpu.engine.device import segment_arrays, stage_segments, to_device_inputs
    from pinot_tpu.engine.plan import build_query_inputs, build_static_plan
    from pinot_tpu.parallel.multichip import SEGMENT_AXIS, make_sharded_table_kernel
    from pinot_tpu.parallel.multihost import (
        HOST_AXIS,
        flatten_to_segment_mesh,
        make_multihost_mesh,
    )
    from pinot_tpu.pql import optimize_request, parse_pql
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment

    mesh = make_multihost_mesh()
    assert mesh.axis_names == (HOST_AXIS, SEGMENT_AXIS)
    assert mesh.devices.shape == (num_procs, 4), mesh.devices.shape

    # every process builds the same 8 tiny segments (deterministic
    # seeds); the segment axis shards across ALL devices of BOTH
    # processes, so the psum merge crosses the process boundary (the
    # DCN hop on a real slice)
    segments = [
        synthetic_lineitem_segment(512, seed=100 + i, name=f"mh{i}") for i in range(8)
    ]
    request = optimize_request(
        parse_pql(
            "SELECT sum(l_quantity), count(*) FROM lineitem "
            "WHERE l_shipdate <= '1998-09-02' GROUP BY l_returnflag TOP 10"
        )
    )
    ctx = get_table_context(segments)
    needed = sorted(set(request.referenced_columns()))
    staged = stage_segments(segments, needed, gfwd_columns=("l_returnflag",), ctx=ctx)
    plan = build_static_plan(request, ctx, staged)
    q = to_device_inputs(build_query_inputs(request, plan, ctx, staged))
    seg = segment_arrays(staged, needed)

    kernel = make_sharded_table_kernel(plan, flatten_to_segment_mesh(mesh))
    outs = kernel(seg, q)
    total = float(np.asarray(jax.device_get(outs["num_docs"])).sum())
    print(f"RESULT pid={pid} num_docs={total}", flush=True)


if __name__ == "__main__":
    main()
