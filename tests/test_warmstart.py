"""Warm-start resilience (ISSUE 16): persistent compile cache, fleet
plan prewarming, and readiness-gated movement.

Tier-1 guards: the plan ledger classifies restarts honestly (a corrupt
or alien entry is a MISS, never a crash, and every topology axis —
jax version, platform, device count/kind, x64 — separates cache keys);
a fresh server over a warm cache serves its first query as
``compile.persistentHit`` with ``compile.cold == 0``; the prewarm
worker compiles the fleet's hot shapes on its background thread without
ever blocking the serving path; the stabilizer defers trims while the
surviving cover is still warming (bounded by the prewarm timeout); the
broker deprioritizes — never excludes — warming replicas; and the
``rolling-restart-warm`` chaos scenario holds the whole story end to
end (zero failed queries, zero cold compiles on restarted servers).
"""
import json
import os
import threading
import time

import pytest

from pinot_tpu.broker.health import ServerHealthTracker
from pinot_tpu.broker.routing import RoutingTableProvider
from pinot_tpu.controller.resource_manager import ClusterResourceManager
from pinot_tpu.controller.stabilizer import SelfStabilizer
from pinot_tpu.engine import compilecache
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.tools.cluster_harness import (
    run_rolling_restart_warm_scenario,
    single_server_broker,
)
from pinot_tpu.tools.datagen import make_test_schema, random_rows

PQL = "SELECT sum(metInt), count(*) FROM warmT GROUP BY dimStr TOP 5"


@pytest.fixture
def cache_isolation():
    """Persistent-cache tests re-point jax's global compilation-cache
    config; restore it (and the module's idempotence guard) so the rest
    of the suite keeps its default no-cache behavior."""
    import jax

    prev = jax.config.jax_compilation_cache_dir
    compilecache._reset_for_tests()
    yield
    compilecache._reset_for_tests()
    try:
        jax.config.update("jax_compilation_cache_dir", prev)
    except Exception:
        pass


def _meter(server, name):
    snap = server.metrics.snapshot()["meters"]
    return int(snap.get(name, {}).get("count", 0))


def _build_segments(seed=11, num=2, rows_per=60):
    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, rows_per * num, seed=seed)
    return [
        build_segment(
            schema, rows[i * rows_per : (i + 1) * rows_per], "warmT", f"seg{i}"
        )
        for i in range(num)
    ]


# ------------------------------------------------------------------
# plan ledger: cache-key safety
# ------------------------------------------------------------------
def test_ledger_hit_and_every_corruption_is_a_miss(tmp_path):
    """record -> known roundtrip; every damaged-entry mode is a MISS,
    never an exception — the ledger is advisory accounting only."""
    root = str(tmp_path)
    fp = compilecache.topology_fingerprint()
    assert compilecache.record_plan("d1a2b3c4", fp, root=root)
    assert compilecache.known_plan("d1a2b3c4", fp, root=root)
    # unknown digest / wrong fingerprint: plain misses
    assert not compilecache.known_plan("eeeeeeee", fp, root=root)
    assert not compilecache.known_plan("d1a2b3c4", "0" * 16, root=root)
    assert not compilecache.known_plan("", fp, root=root)

    # corrupt the entry in place: not JSON at all
    path = compilecache._plan_path(root, "d1a2b3c4", fp)
    with open(path, "w") as f:
        f.write("\x00garbage not json")
    assert not compilecache.known_plan("d1a2b3c4", fp, root=root)

    # valid JSON, wrong shape (a list, not a dict)
    with open(path, "w") as f:
        json.dump(["alien"], f)
    assert not compilecache.known_plan("d1a2b3c4", fp, root=root)

    # alien entry: a file whose recorded digest/fingerprint disagree
    # with its filename (e.g. copied from another cache root)
    with open(path, "w") as f:
        json.dump({"digest": "other", "fingerprint": fp}, f)
    assert not compilecache.known_plan("d1a2b3c4", fp, root=root)
    with open(path, "w") as f:
        json.dump({"digest": "d1a2b3c4", "fingerprint": "alienfp"}, f)
    assert not compilecache.known_plan("d1a2b3c4", fp, root=root)

    # truncated (crash mid-write without the atomic rename)
    with open(path, "w") as f:
        f.write('{"digest": "d1a2b')
    assert not compilecache.known_plan("d1a2b3c4", fp, root=root)

    # a healthy re-record repairs the entry
    assert compilecache.record_plan("d1a2b3c4", fp, root=root)
    assert compilecache.known_plan("d1a2b3c4", fp, root=root)

    # a hostile digest cannot escape the ledger directory
    evil = compilecache._plan_path(root, "../../escape", fp)
    assert evil.startswith(os.path.join(root, "plans"))


def test_fingerprint_every_axis_separates_keys():
    """jax version, platform, device count, device kind, and x64 each
    change the fingerprint — a cache written on a different mesh or jax
    build can miss, never poison."""
    base = compilecache.topology_fingerprint()
    assert base == compilecache.topology_fingerprint()  # stable
    variants = [
        compilecache.topology_fingerprint(jax_version="99.99.99"),
        compilecache.topology_fingerprint(platform="tpu"),
        compilecache.topology_fingerprint(device_count=1024),
        compilecache.topology_fingerprint(device_kind="TPU v9"),
        compilecache.topology_fingerprint(x64=not True),
    ]
    # x64 override must actually differ from the session default
    variants[-1] = compilecache.topology_fingerprint(
        x64=not __import__("jax").config.jax_enable_x64
    )
    assert all(v != base for v in variants), variants
    assert len(set(variants)) == len(variants)  # axes don't collide

    # a plan recorded under one topology is unknown under another
    fp_a = compilecache.topology_fingerprint(device_count=8)
    fp_b = compilecache.topology_fingerprint(device_count=16)
    import tempfile

    with tempfile.TemporaryDirectory() as root:
        assert compilecache.record_plan("abcd1234", fp_a, root=root)
        assert compilecache.known_plan("abcd1234", fp_a, root=root)
        assert not compilecache.known_plan("abcd1234", fp_b, root=root)


def test_cache_disabled_without_env(monkeypatch):
    monkeypatch.delenv("PINOT_TPU_COMPILE_CACHE_DIR", raising=False)
    assert compilecache.cache_root() is None
    assert not compilecache.enabled()
    assert compilecache.configure_jax_cache() is None
    assert not compilecache.record_plan("d1")
    assert not compilecache.known_plan("d1")


# ------------------------------------------------------------------
# compile accounting across a restart
# ------------------------------------------------------------------
def test_persistent_hit_classification_across_restart(
    tmp_path, monkeypatch, cache_isolation
):
    """Server generation 1 compiles cold (``persistentMiss``); a fresh
    server over the same cache root classifies its first launch
    ``persistentHit`` with ``compile.cold == 0``, and EXPLAIN reports
    the r16 compile states (cold -> persistent -> warm) along the way."""
    monkeypatch.setenv("PINOT_TPU_COMPILE_CACHE_DIR", str(tmp_path))

    broker1 = single_server_broker("warmT", _build_segments(), pipeline=True)
    s1 = broker1.local_servers[0]
    try:
        pre = broker1.handle_pql("EXPLAIN " + PQL)
        assert pre.explain["servers"][0]["device"]["compile"]["state"] == "cold"
        resp = broker1.handle_pql(PQL)
        assert not resp.exceptions, resp.exceptions
        assert _meter(s1, "compile.cold") == 1
        assert _meter(s1, "compile.persistentMiss") == 1
        assert _meter(s1, "compile.persistentHit") == 0
    finally:
        s1.shutdown()

    # "restart": a genuinely fresh instance — empty lane compile
    # registries — sharing only the on-disk cache root
    broker2 = single_server_broker("warmT", _build_segments(), pipeline=True)
    s2 = broker2.local_servers[0]
    try:
        pre = broker2.handle_pql("EXPLAIN " + PQL)
        comp = pre.explain["servers"][0]["device"]["compile"]
        assert comp["state"] == "persistent", comp  # ledger-proven warm
        resp = broker2.handle_pql(PQL)
        assert not resp.exceptions, resp.exceptions
        assert _meter(s2, "compile.cold") == 0
        assert _meter(s2, "compile.persistentHit") == 1
        assert _meter(s2, "compile.persistentMiss") == 0
        post = broker2.handle_pql("EXPLAIN " + PQL)
        assert (
            post.explain["servers"][0]["device"]["compile"]["state"] == "warm"
        )
    finally:
        s2.shutdown()


# ------------------------------------------------------------------
# prewarm worker
# ------------------------------------------------------------------
def test_prewarm_compiles_ahead_and_reports_readiness(
    tmp_path, monkeypatch, cache_isolation
):
    """The worker replays the fleet workload feed through phantom
    staging BEFORE any query: the first serving query is classified
    ``compile.prewarmed`` (never cold), and the warming flag flips
    synchronously on request and clears when the pass drains."""
    monkeypatch.setenv("PINOT_TPU_COMPILE_CACHE_DIR", str(tmp_path))

    # generation 1 records the workload shape the fleet feed serves
    broker1 = single_server_broker("warmT", _build_segments(), pipeline=True)
    s1 = broker1.local_servers[0]
    try:
        resp = broker1.handle_pql(PQL)
        assert not resp.exceptions, resp.exceptions
        entries = broker1.workload_snapshot(top=8)["topByCount"]
        assert entries and entries[0]["exemplarPql"]
    finally:
        s1.shutdown()

    broker2 = single_server_broker("warmT", _build_segments(), pipeline=True)
    s2 = broker2.local_servers[0]
    try:
        assert not s2.prewarm.enabled  # no feed wired yet: always ready
        s2.prewarm.workload_source = lambda tables, n: entries
        s2.prewarm.request_prewarm("warmT")
        assert s2.prewarm.warming  # synchronous flip: heartbeats see it
        deadline = time.monotonic() + 30.0
        while s2.prewarm.warming and time.monotonic() < deadline:
            time.sleep(0.005)
        assert not s2.prewarm.warming, s2.prewarm.state()
        assert _meter(s2, "prewarm.compiled") >= 1
        assert _meter(s2, "compile.prewarmed") >= 1
        assert _meter(s2, "compile.cold") == 0
        assert _meter(s2, "prewarm.failed") == 0
        # EXPLAIN reports HOW the executable arrived before it serves
        pre = broker2.handle_pql("EXPLAIN " + PQL)
        comp = pre.explain["servers"][0]["device"]["compile"]
        assert comp["state"] == "prewarmed", comp
        # first serving query: the executable is already resident
        resp = broker2.handle_pql(PQL)
        assert not resp.exceptions, resp.exceptions
        assert _meter(s2, "compile.cold") == 0
        assert _meter(s2, "compile.warm") >= 1
        st = s2.prewarm.state()
        assert st["ready"] and st["compiled"] >= 1
    finally:
        s2.shutdown()


def test_prewarm_never_blocks_serving():
    """A pass parked inside the workload fetch must not delay a live
    query: prewarm work happens strictly on the background thread."""
    broker = single_server_broker("warmT", _build_segments(), pipeline=True)
    server = broker.local_servers[0]
    entered = threading.Event()
    release = threading.Event()

    def stalled_source(tables, n):
        entered.set()
        release.wait(timeout=10.0)
        return []

    try:
        server.prewarm.workload_source = stalled_source
        server.prewarm.request_prewarm()
        assert entered.wait(timeout=5.0)
        # the worker is wedged mid-pass; serving proceeds regardless
        resp = broker.handle_pql(PQL)
        assert not resp.exceptions, resp.exceptions
        assert server.prewarm.warming  # still mid-pass the whole time
    finally:
        release.set()
        server.shutdown()
    assert not server.prewarm.warming  # stop() clears the flag


def test_prewarm_disabled_without_feed_or_topk():
    """No workload source (plain in-process instances) or top_k == 0
    means the worker never starts and the server is simply ready."""
    broker = single_server_broker("warmT", _build_segments(), pipeline=True)
    server = broker.local_servers[0]
    try:
        assert not server.prewarm.enabled
        server.prewarm.request_prewarm("warmT")
        assert not server.prewarm.warming
        assert server.prewarm._thread is None  # nothing ever spawned
        server.prewarm.workload_source = lambda tables, n: []
        server.prewarm.top_k = 0
        assert not server.prewarm.enabled
        server.prewarm.request_prewarm("warmT")
        assert not server.prewarm.warming
        assert server.prewarm.state()["ready"]
    finally:
        server.shutdown()


# ------------------------------------------------------------------
# readiness-gated movement
# ------------------------------------------------------------------
def test_trim_defers_for_warming_cover_then_times_out():
    """``_destinations_ready``: a trim waits while the surviving cover
    is still prewarming — ``rebalanceTrimDeferred`` in the event ring,
    ``rebalance.prewarmDeferrals`` marked — and proceeds anyway past
    the bounded prewarm window (``rebalancePrewarmTimeout``)."""
    clock = [100.0]
    st = SelfStabilizer(ClusterResourceManager(), grace_s=5.0, now=lambda: clock[0])
    st.prewarm_timeout_s = 10.0
    warming = {"serverB"}
    st.readiness_fn = lambda s: s not in warming
    serving = ["serverA", "serverB"]

    # everyone ready: trim proceeds, no wait recorded
    assert st._destinations_ready("t_OFFLINE", "s0", serving, 1)
    assert not st._warm_waits

    # victim A leaves only cover B, which is warming: defer
    assert not st._destinations_ready(
        "t_OFFLINE", "s0", serving, 1, victim="serverA", dst="serverB"
    )
    ev = st.events()[-1]
    assert ev["event"] == "rebalanceTrimDeferred"
    assert ev["server"] == "serverA" and ev["dst"] == "serverB"
    assert ev["reason"] == "destination warming"
    assert st.metrics.meter("rebalance.prewarmDeferrals").count == 1
    assert ("t_OFFLINE", "s0") in st._warm_waits

    # still inside the window: keeps deferring
    clock[0] = 105.0
    assert not st._destinations_ready(
        "t_OFFLINE", "s0", serving, 1, victim="serverA", dst="serverB"
    )
    assert st.metrics.meter("rebalance.prewarmDeferrals").count == 2

    # destination finishes warming: trim proceeds and the wait clears
    warming.clear()
    assert st._destinations_ready(
        "t_OFFLINE", "s0", serving, 1, victim="serverA", dst="serverB"
    )
    assert not st._warm_waits

    # a wedged prewarm cannot pin the surplus replica forever: the
    # deferral is bounded by the prewarm window
    warming.add("serverB")
    clock[0] = 200.0
    assert not st._destinations_ready(
        "t_OFFLINE", "s0", serving, 1, victim="serverA", dst="serverB"
    )
    clock[0] = 211.0  # past prewarm_timeout_s
    assert st._destinations_ready(
        "t_OFFLINE", "s0", serving, 1, victim="serverA", dst="serverB"
    )
    assert st.events()[-1]["event"] == "rebalancePrewarmTimeout"
    assert not st._warm_waits  # timeout clears the clock too

    # a broken readiness probe must never freeze movement
    def boom(server):
        raise RuntimeError("probe down")

    st.readiness_fn = boom
    assert st._destinations_ready(
        "t_OFFLINE", "s0", serving, 1, victim="serverA"
    )

    # no probe wired (pre-r16 clusters): everyone is ready
    st.readiness_fn = None
    assert st._ready("anything")


# ------------------------------------------------------------------
# broker routing: deprioritize, never exclude
# ------------------------------------------------------------------
def test_routing_deprioritizes_warming_replica():
    provider = RoutingTableProvider(num_tables=4)
    segments = [f"seg{i}" for i in range(4)]
    view = {seg: {"s1": "ONLINE", "s2": "ONLINE"} for seg in segments}
    provider.update("t_OFFLINE", view)
    health = ServerHealthTracker()

    # s1 warming: every segment re-routes onto the ready replica
    health.set_warming("s1", True)
    for _ in range(10):
        rt = provider.find_servers("t_OFFLINE", health=health)
        assert set(rt) == {"s2"}, rt
        assert sorted(sum(rt.values(), [])) == segments

    # warming cleared (e.g. heartbeat reports ready): s1 serves again
    health.set_warming("s1", False)
    seen = set()
    for _ in range(40):
        seen.update(provider.find_servers("t_OFFLINE", health=health))
    assert seen == {"s1", "s2"}

    # a warming replica that is all that is left still serves —
    # deprioritized is never excluded
    sole = {seg: {"s1": "ONLINE"} for seg in segments}
    provider.update("sole_OFFLINE", sole)
    health.set_warming("s1", True)
    rt = provider.find_servers("sole_OFFLINE", health=health)
    assert set(rt) == {"s1"}
    assert sorted(sum(rt.values(), [])) == segments

    # the wholesale clusterstate refresh path drives the same flag
    health.set_warming_servers({"s2"})
    assert health.is_warming("s2") and not health.is_warming("s1")
    assert health.warming_servers() == {"s2"}


# ------------------------------------------------------------------
# chaos acceptance — the same scenario code the CLI runs
# ------------------------------------------------------------------
@pytest.mark.chaos
def test_rolling_restart_warm_acceptance(tmp_path, cache_isolation):
    out = run_rolling_restart_warm_scenario(
        data_dir=str(tmp_path / "data"), cache_dir=str(tmp_path / "cache")
    )
    assert out["failedQueries"] == 0, out.get("failures")
    # the warm-start bar: every restarted server came up with ZERO cold
    # compiles — its first launches were persistent-cache or prewarm
    assert out["coldCompilesOnRestarted"] == 0, out["servers"]
    assert out["warmStartsOnRestarted"] >= 1, out["servers"]
    # movement provably waited on warming destinations
    assert out["trimDeferrals"] >= 1, out
    assert out["prewarmDeferralMeter"] >= out["trimDeferrals"]
    assert out["prewarmTimeouts"] == 0, out
    # prewarm never entered a serving lane on the restarted servers
    assert out["laneWatchdogClean"], out["servers"]
    assert out["p99Bounded"], (out["rollP99Ms"], out["p99LimitMs"])
    assert out["noSegmentLoss"] and out["finalComplete"], out
