"""Sort-dedup exact-distinct device path (StaticAgg.sort_pairs):
high-cardinality ``distinctcount`` stays on device via a global
(group, valueId) pair sort instead of the dense [capacity, gcard_pad]
holder or the host fallback.

Reference parity: the map-based group-by storage the reference switches
to beyond the dense array key space
(``DefaultGroupKeyGenerator.java:60-63``), re-designed for TPU — sorts
are vectorizable where hash maps are not (VERDICT r2 #3)."""
import json

import numpy as np
import pytest

from pinot_tpu.engine import config
from pinot_tpu.engine.context import get_table_context
from pinot_tpu.engine.device import clear_staging_cache, stage_segments
from pinot_tpu.engine.executor import QueryExecutor
from pinot_tpu.engine.plan import build_static_plan
from pinot_tpu.engine.reduce import reduce_to_response
from pinot_tpu.pql import optimize_request, parse_pql
from pinot_tpu.tools.datagen import lineitem_schema, synthetic_lineitem_segment
from pinot_tpu.tools.scan_engine import ScanQueryProcessor

STRIP = (
    "timeUsedMs",
    "cost",
    "numEntriesScannedInFilter",
    "numEntriesScannedPostFilter",
    "numSegmentsQueried",
    "numServersQueried",
    "numServersResponded",
    "numDocsScanned",
)


def _norm(resp):
    j = resp.to_json()
    for k in STRIP:
        j.pop(k, None)
    return json.dumps(j, sort_keys=True, default=str)


@pytest.fixture(scope="module")
def cluster():
    segs = [
        synthetic_lineitem_segment(15000, seed=23 + i, name=f"ds{i}") for i in range(3)
    ]
    rows = [r for s in segs for r in s.rows()]
    return segs, ScanQueryProcessor(lineitem_schema(), rows)


@pytest.fixture(autouse=True)
def small_dense_cap(monkeypatch):
    # l_extendedprice has ~16k global cardinality; force it past the
    # dense-state budget so the sort-dedup path engages
    monkeypatch.setattr(config, "MAX_VALUE_STATE", 1 << 10)
    # keep the selective-predicate host path out of the way: these
    # tests pin the DEVICE kernel path
    monkeypatch.setenv("PINOT_TPU_INVINDEX", "0")


def test_plan_selects_sort_pairs(cluster):
    segs, _ = cluster
    req = optimize_request(
        parse_pql(
            "SELECT distinctcount(l_extendedprice) FROM lineitem "
            "GROUP BY l_returnflag TOP 10"
        )
    )
    ctx = get_table_context(segs)
    staged = stage_segments(segs, sorted(req.referenced_columns()), ctx=ctx)
    plan = build_static_plan(req, ctx, staged)
    assert plan.on_device
    assert plan.aggs[0].sort_pairs


QUERIES = [
    "SELECT distinctcount(l_extendedprice) FROM lineitem GROUP BY l_returnflag TOP 10",
    "SELECT distinctcount(l_extendedprice) FROM lineitem",
    # exact percentile through the same pair-sort machinery (run-length
    # counts): any cardinality stays on device
    "SELECT percentile50(l_extendedprice), percentile95(l_extendedprice) FROM lineitem",
    "SELECT percentile90(l_extendedprice) FROM lineitem GROUP BY l_returnflag TOP 10",
    "SELECT percentile50(l_extendedprice), distinctcount(l_extendedprice) FROM lineitem "
    "WHERE l_shipmode = 'RAIL' GROUP BY l_linestatus TOP 10",
    "SELECT distinctcount(l_extendedprice), count(*) FROM lineitem "
    "WHERE l_shipmode IN ('RAIL','FOB') GROUP BY l_linestatus TOP 10",
    "SELECT distinctcount(l_extendedprice), sum(l_quantity) FROM lineitem "
    "GROUP BY l_returnflag, l_linestatus TOP 10",
    "SELECT distinctcount(l_extendedprice) FROM lineitem WHERE l_shipdate > '1998-10-01'",
]


def test_sort_path_matches_oracle(cluster):
    segs, oracle = cluster
    ex = QueryExecutor()
    for q in QUERIES:
        req = optimize_request(parse_pql(q))
        req2 = optimize_request(parse_pql(q))
        got = reduce_to_response(req, [ex.execute(segs, req)])
        want = oracle.execute(req2)
        assert _norm(got) == _norm(want), q


def test_cross_server_merge_stays_exact(cluster):
    """Partials from two executors over disjoint segment sets merge to
    the same exact distinct counts (DistinctPartial set semantics ride
    the pair buffers)."""
    segs, oracle = cluster
    q = (
        "SELECT distinctcount(l_extendedprice) FROM lineitem "
        "GROUP BY l_returnflag TOP 10"
    )
    req = optimize_request(parse_pql(q))
    ex = QueryExecutor()
    parts = [ex.execute(segs[:2], req), ex.execute(segs[2:], req)]
    got = reduce_to_response(req, parts)
    want = oracle.execute(optimize_request(parse_pql(q)))
    assert _norm(got) == _norm(want)


def test_overflow_falls_back_to_host(cluster, monkeypatch):
    from pinot_tpu.engine import kernel as kernel_mod

    segs, oracle = cluster
    monkeypatch.setattr(config, "DISTINCT_PAIR_CAP", 64)  # << unique pairs
    kernel_mod.make_table_kernel.cache_clear()
    kernel_mod.make_packed_table_kernel.cache_clear()
    try:
        # the filter keeps the query off the plan-time guaranteed-
        # overflow skip, so this exercises the RUNTIME overflow
        # detection (device pairs buffer too small -> host re-run)
        q = (
            "SELECT distinctcount(l_extendedprice) FROM lineitem "
            "WHERE l_shipdate > '1993-01-01' GROUP BY l_returnflag TOP 10"
        )
        req = optimize_request(parse_pql(q))
        ctx = get_table_context(segs)
        staged = stage_segments(segs, sorted(req.referenced_columns()), ctx=ctx)
        assert build_static_plan(req, ctx, staged).on_device
        got = reduce_to_response(req, [QueryExecutor().execute(segs, req)])
        want = oracle.execute(optimize_request(parse_pql(q)))
        assert _norm(got) == _norm(want)
    finally:
        kernel_mod.make_table_kernel.cache_clear()
        kernel_mod.make_packed_table_kernel.cache_clear()
        clear_staging_cache()


def test_guaranteed_overflow_skips_device(cluster, monkeypatch):
    """With no filter and global cardinality beyond the pair buffer,
    every dictionary value lands in >= 1 pair — the device sort is
    doomed, so the planner goes straight to the host path (the r4
    north-star capture burned 32 minutes on the staged+compiled+sorted
    device attempt before falling back)."""
    segs, oracle = cluster
    monkeypatch.setattr(config, "DISTINCT_PAIR_CAP", 64)
    q = "SELECT distinctcount(l_extendedprice) FROM lineitem GROUP BY l_returnflag TOP 10"
    req = optimize_request(parse_pql(q))
    ctx = get_table_context(segs)
    staged = stage_segments(segs, sorted(req.referenced_columns()), ctx=ctx)
    plan = build_static_plan(req, ctx, staged)
    assert not plan.on_device
    got = reduce_to_response(req, [QueryExecutor().execute(segs, req)])
    want = oracle.execute(optimize_request(parse_pql(q)))
    assert _norm(got) == _norm(want)


def test_trim_path_uses_pair_counts(cluster):
    """>100 groups engages trim ordering, which reads the per-slot
    distinct counts off the pair buffer (_PairsState.counts)."""
    segs, oracle = cluster
    for q in (
        "SELECT distinctcount(l_extendedprice) FROM lineitem GROUP BY l_shipdate TOP 5",
        "SELECT percentile50(l_extendedprice) FROM lineitem GROUP BY l_shipdate TOP 5",
    ):
        req = optimize_request(parse_pql(q))
        got = reduce_to_response(req, [QueryExecutor().execute(segs, req)])
        want = oracle.execute(optimize_request(parse_pql(q)))
        assert _norm(got) == _norm(want), q


def test_mv_sort_pairs_matches_oracle(monkeypatch):
    """MV distinctcount through the pair-emission path (per-entry
    expansion, dedup across repeated values within a row)."""
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.tools.datagen import make_test_schema, random_rows

    schema = make_test_schema(with_mv=True)
    rows = random_rows(schema, 4000, seed=9)
    segs = [
        build_segment(schema, rows[:2000], "testTable", "mv0"),
        build_segment(schema, rows[2000:], "testTable", "mv1"),
    ]
    oracle = ScanQueryProcessor(schema, rows)
    # force the sort path for the MV column's cardinality too
    monkeypatch.setattr(config, "MAX_VALUE_STATE", 1)
    for q in [
        "SELECT distinctcountmv(dimIntMV) FROM testTable",
        "SELECT distinctcountmv(dimIntMV) FROM testTable GROUP BY dimStr TOP 10",
        "SELECT distinctcountmv(dimStrMV), count(*) FROM testTable "
        "WHERE dimInt > 300 GROUP BY dimStr TOP 10",
    ]:
        req = optimize_request(parse_pql(q))
        plan_probe = optimize_request(parse_pql(q))
        got = reduce_to_response(req, [QueryExecutor().execute(segs, req)])
        want = oracle.execute(plan_probe)
        assert _norm(got) == _norm(want), q


def test_sort_pairs_through_block_skip_kernel(cluster, monkeypatch):
    """Zone-map block path + sort-pairs distinct/percentile compose:
    pairs emit from the gathered candidate blocks only."""
    monkeypatch.setenv("PINOT_TPU_ZONE_BLOCK", "1024")
    segs, oracle = cluster
    q = (
        "SELECT distinctcount(l_extendedprice), percentile50(l_extendedprice) "
        "FROM lineitem WHERE l_shipdate <= '1992-02-01'"
    )
    req = optimize_request(parse_pql(q))
    part = QueryExecutor().execute(segs, req)
    total = sum(s.num_docs for s in segs)
    # the block path engaged: filter scan cost is O(candidate rows)
    assert part.num_entries_scanned_in_filter < total / 2
    got = reduce_to_response(req, [part])
    want = oracle.execute(optimize_request(parse_pql(q)))
    assert _norm(got) == _norm(want)


def test_sort_pairs_on_mesh_matches_oracle(cluster):
    """The distinct-pairs collective: per-chip compacted buffers
    all_gather and re-merge across the mesh (counts of pairs seen on
    several chips sum) — high-cardinality exact distinct/percentile no
    longer drops to the host under a mesh."""
    import jax

    from pinot_tpu.parallel.multichip import default_mesh

    segs, oracle = cluster
    mesh = default_mesh(jax.devices()[:4])
    ex = QueryExecutor(mesh=mesh)
    for q in (
        "SELECT distinctcount(l_extendedprice) FROM lineitem GROUP BY l_returnflag TOP 10",
        "SELECT percentile50(l_extendedprice), count(*) FROM lineitem "
        "GROUP BY l_linestatus TOP 10",
        "SELECT distinctcount(l_extendedprice) FROM lineitem",
    ):
        req = optimize_request(parse_pql(q))
        got = reduce_to_response(req, [ex.execute(segs, req)])
        want = oracle.execute(optimize_request(parse_pql(q)))
        assert _norm(got) == _norm(want), q


def test_mesh_overflow_forces_host_fallback(cluster, monkeypatch):
    """A chip overflowing its pair buffer must poison the merged
    n_unique so the executor drops to the exact host path instead of
    silently losing pairs."""
    import jax

    from pinot_tpu.engine import kernel as kernel_mod
    from pinot_tpu.parallel.multichip import default_mesh

    segs, oracle = cluster
    monkeypatch.setattr(config, "DISTINCT_PAIR_CAP", 64)
    kernel_mod.make_table_kernel.cache_clear()
    kernel_mod.make_packed_table_kernel.cache_clear()
    try:
        mesh = default_mesh(jax.devices()[:4])
        q = "SELECT distinctcount(l_extendedprice) FROM lineitem GROUP BY l_returnflag TOP 10"
        req = optimize_request(parse_pql(q))
        got = reduce_to_response(req, [QueryExecutor(mesh=mesh).execute(segs, req)])
        want = oracle.execute(optimize_request(parse_pql(q)))
        assert _norm(got) == _norm(want)
    finally:
        kernel_mod.make_table_kernel.cache_clear()
        kernel_mod.make_packed_table_kernel.cache_clear()
        clear_staging_cache()


def test_grouped_hll_sort_pairs(cluster, monkeypatch):
    """Grouped HLL past the dense budget rides the same pair-sort
    machinery ((slot, bucket*64+rho) gids) instead of host-falling-back;
    registers reconstruct exactly at finalize so estimates match the
    oracle bit for bit."""
    segs, oracle = cluster
    monkeypatch.setattr(config, "MAX_VALUE_STATE", 1)  # force sort for HLL too
    for q in (
        "SELECT distinctcounthll(l_extendedprice) FROM lineitem GROUP BY l_returnflag TOP 10",
        "SELECT fasthll(l_shipdate), count(*) FROM lineitem GROUP BY l_shipdate TOP 5",
    ):
        req = optimize_request(parse_pql(q))
        got = reduce_to_response(req, [QueryExecutor().execute(segs, req)])
        want = oracle.execute(optimize_request(parse_pql(q)))
        assert _norm(got) == _norm(want), q


def test_forced_host_is_subset_of_plan_decision(cluster):
    """plan_forced_host must NEVER claim host for a query the full plan
    would run on device (it may be narrower — it sees less than the
    planner — but a false positive silently degrades device queries to
    the host path).  Swept over capacity/overflow/filter combinations
    with shrunken caps so every branch fires."""
    from pinot_tpu.engine.plan import plan_forced_host

    segs, _ = cluster
    ctx = get_table_context(segs)
    queries = [
        "SELECT count(*) FROM lineitem GROUP BY l_returnflag TOP 10",
        "SELECT count(*) FROM lineitem GROUP BY l_extendedprice TOP 10",
        "SELECT distinctcount(l_extendedprice) FROM lineitem GROUP BY l_returnflag TOP 10",
        "SELECT distinctcount(l_extendedprice) FROM lineitem "
        "WHERE l_shipdate > '1993-01-01' GROUP BY l_returnflag TOP 10",
        "SELECT distinctcount(l_extendedprice) FROM lineitem",
        "SELECT percentile50(l_extendedprice) FROM lineitem GROUP BY l_shipmode TOP 5",
        "SELECT sum(l_quantity) FROM lineitem",
    ]
    for cap_name, cap_val in [
        (None, None),
        ("MAX_GROUP_CAPACITY", 100),
        ("DISTINCT_PAIR_CAP", 64),
        ("MAX_VALUE_STATE", 256),
    ]:
        # a PRIVATE patcher per case: the shared function-scoped
        # monkeypatch also carries the module's autouse cap shrink,
        # which an undo() would unwind
        with pytest.MonkeyPatch.context() as mp:
            if cap_name is not None:
                mp.setattr(config, cap_name, cap_val)
            forced_seen = 0
            for q in queries:
                req = optimize_request(parse_pql(q))
                forced = plan_forced_host(req, ctx)
                staged = stage_segments(segs, sorted(req.referenced_columns()), ctx=ctx)
                plan = build_static_plan(req, ctx, staged)
                if forced:
                    forced_seen += 1
                    assert not plan.on_device, (cap_name, q)
        if cap_name in ("MAX_GROUP_CAPACITY", "DISTINCT_PAIR_CAP"):
            assert forced_seen > 0, f"{cap_name} shrink should force some hosts"
