"""Zone-map block skipping (engine/zonemap.py + block-gather kernel).

Reference capability: index-based skipping for selective queries
(``SortedInvertedIndexBasedFilterOperator.java``,
``BitmapInvertedIndexReader.java:28``) — here per-block dictId min/max
zones prune blocks host-side before the device gather.
"""
import json

import numpy as np
import pytest

from pinot_tpu.engine import zonemap
from pinot_tpu.engine.executor import QueryExecutor
from pinot_tpu.engine.plan import build_query_inputs, build_static_plan
from pinot_tpu.engine.context import get_table_context
from pinot_tpu.engine.device import stage_segments
from pinot_tpu.engine.reduce import reduce_to_response
from pinot_tpu.pql import optimize_request, parse_pql
from pinot_tpu.tools.datagen import lineitem_schema, synthetic_lineitem_segment
from pinot_tpu.tools.scan_engine import ScanQueryProcessor

BLOCK = 1024

QUERIES = [
    # clustered-date interval: one candidate block per segment
    "SELECT sum(l_quantity), count(*) FROM lineitem WHERE l_shipdate <= '1992-02-01' GROUP BY l_returnflag TOP 10",
    # point lookup on the clustered column
    "SELECT sum(l_extendedprice) FROM lineitem WHERE l_shipdate = '1995-06-14'",
    # AND with an unclustered match-table leaf
    "SELECT count(*) FROM lineitem WHERE l_shipmode IN ('RAIL','FOB') AND l_shipdate BETWEEN '1993-01-01' AND '1993-03-01'",
    # empty candidate set (date past the data)
    "SELECT max(l_discount) FROM lineitem WHERE l_shipdate > '1998-11-30'",
    # OR of two clustered ranges
    "SELECT count(*) FROM lineitem WHERE l_shipdate <= '1992-02-01' OR l_shipdate > '1998-10-01'",
    # IN points on the clustered column
    "SELECT sum(l_tax) FROM lineitem WHERE l_shipdate IN ('1994-01-05','1997-03-22')",
    # selection + order-by through the block path (docid remapping)
    "SELECT l_shipdate, l_quantity FROM lineitem WHERE l_shipdate = '1995-06-14' ORDER BY l_quantity DESC LIMIT 5",
    # NOT IN stays correct (conservative candidacy)
    "SELECT count(*) FROM lineitem WHERE l_shipdate NOT IN ('1995-06-14') AND l_shipdate BETWEEN '1995-06-01' AND '1995-06-30'",
]

STRIP = (
    "timeUsedMs",
    "cost",
    "numEntriesScannedInFilter",
    "numEntriesScannedPostFilter",
    "numSegmentsQueried",
    "numServersQueried",
    "numServersResponded",
)


@pytest.fixture(scope="module")
def cluster(monkeypatch_module=None):
    segs = [
        synthetic_lineitem_segment(20000, seed=7 + i, name=f"li{i}") for i in range(3)
    ]
    rows = [r for s in segs for r in s.rows()]
    oracle = ScanQueryProcessor(lineitem_schema(), rows)
    return segs, oracle


@pytest.fixture(autouse=True)
def small_zone_block(monkeypatch):
    monkeypatch.setenv("PINOT_TPU_ZONE_BLOCK", str(BLOCK))
    # these tests exercise the zone-map BLOCK path; the postings fast
    # path (engine/invindex_path.py) would swallow the selective
    # queries first
    monkeypatch.setenv("PINOT_TPU_INVINDEX", "0")


def _norm(resp):
    j = resp.to_json()
    for k in STRIP:
        j.pop(k, None)
    return json.dumps(j, sort_keys=True, default=str)


def test_block_path_matches_oracle(cluster):
    segs, oracle = cluster
    ex = QueryExecutor()
    for q in QUERIES:
        req = optimize_request(parse_pql(q))
        req2 = optimize_request(parse_pql(q))
        got = reduce_to_response(req, [ex.execute(segs, req)])
        want = oracle.execute(req2)
        assert _norm(got) == _norm(want), q


def test_selective_query_scans_candidate_blocks_only(cluster):
    segs, _ = cluster
    ex = QueryExecutor()
    req = optimize_request(
        parse_pql("SELECT sum(l_extendedprice) FROM lineitem WHERE l_shipdate = '1995-06-14'")
    )
    part = ex.execute(segs, req)
    # clustered dates: the one matching block per segment, not the table
    assert part.num_entries_scanned_in_filter <= 2 * BLOCK * len(segs)
    total = sum(s.num_docs for s in segs)
    assert part.num_entries_scanned_in_filter < total / 4


def test_zone_map_disabled_full_scan(cluster, monkeypatch):
    segs, oracle = cluster
    monkeypatch.setenv("PINOT_TPU_ZONEMAP", "0")
    ex = QueryExecutor()
    q = "SELECT sum(l_extendedprice) FROM lineitem WHERE l_shipdate = '1995-06-14'"
    req = optimize_request(parse_pql(q))
    req2 = optimize_request(parse_pql(q))
    got = reduce_to_response(req, [ex.execute(segs, req)])
    assert _norm(got) == _norm(oracle.execute(req2))


def test_candidate_blocks_conservative(cluster):
    """Every row the kernel would match must live in a candidate block."""
    segs, _ = cluster
    q = "SELECT count(*) FROM lineitem WHERE l_shipdate BETWEEN '1994-03-01' AND '1994-04-15'"
    req = optimize_request(parse_pql(q))
    ctx = get_table_context(segs)
    staged = stage_segments(segs, sorted(req.referenced_columns()), ctx=ctx)
    plan = build_static_plan(req, ctx, staged)
    q_np = build_query_inputs(req, plan, ctx, staged)
    cand = zonemap.candidate_blocks(plan, q_np, segs, staged.n_pad, block=BLOCK)
    assert cand is not None
    for si, seg in enumerate(segs):
        col = seg.column("l_shipdate")
        d = col.dictionary
        lo, hi = q_np["bounds"][0][si]
        match_rows = np.nonzero((col.fwd >= lo) & (col.fwd < hi))[0]
        for doc in match_rows:
            assert cand[si][doc // BLOCK], (si, doc)


def test_zones_cached_per_segment(cluster):
    segs, _ = cluster
    z1 = zonemap.column_zones(segs[0], "l_shipdate", BLOCK)
    z2 = zonemap.column_zones(segs[0], "l_shipdate", BLOCK)
    assert z1 is z2
    zmin, zmax = z1
    assert (zmin <= zmax).all()
    # clustered column: zones are narrow
    assert (zmax - zmin).mean() < segs[0].column("l_shipdate").metadata.cardinality / 8


def test_randomized_differential_through_block_path(monkeypatch):
    """Randomized PQL differential vs the scan oracle with the zone
    block small enough that the block-gather kernel engages on most
    filtered queries — the QueryGenerator net over the new path."""
    monkeypatch.setenv("PINOT_TPU_ZONE_BLOCK", "256")
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.tools.datagen import make_test_schema, random_rows
    from pinot_tpu.tools.query_gen import QueryGenerator
    from tests.test_engine import _values_close

    schema = make_test_schema()
    rows = random_rows(schema, 1500, seed=77, cardinality=10)
    # sort by a dimension so zones are selective for some columns
    rows.sort(key=lambda r: (r["dimStr"], r["dimInt"]))
    chunk = len(rows) // 3
    segs = [
        build_segment(schema, rows[i * chunk : (i + 1) * chunk if i < 2 else len(rows)],
                      "testTable", f"zseg{i}")
        for i in range(3)
    ]
    oracle = ScanQueryProcessor(schema, rows)
    gen = QueryGenerator(schema, rows, seed=99)
    ex = QueryExecutor()
    def canon(resp):
        # group order among EQUAL aggregate values is unspecified (both
        # engines sort by value; tie-break differs) — canonicalize
        for agg in resp.get("aggregationResults") or []:
            if "groupByResult" in agg:
                agg["groupByResult"].sort(key=lambda e: (str(e["value"]), e["group"]))
        return resp

    mismatches = []
    for _ in range(40):
        pql = gen.next_query()
        req = optimize_request(parse_pql(pql))
        req2 = optimize_request(parse_pql(pql))
        got = reduce_to_response(req, [ex.execute(segs, req)]).to_json()
        want = oracle.execute(req2).to_json()
        for k in STRIP:
            got.pop(k, None)
            want.pop(k, None)
        if not _values_close(canon(got), canon(want)):
            mismatches.append((pql, got, want))
    assert not mismatches, json.dumps(mismatches[0], default=str)[:3000]


def test_block_path_on_8_device_mesh(cluster):
    """Zone-map skipping composes with the sharded multi-chip kernel:
    block ids shard over the segment axis (parallel/multichip.py)."""
    from pinot_tpu.parallel import default_mesh

    segs, oracle = cluster
    total = sum(s.num_docs for s in segs)
    ex = QueryExecutor(mesh=default_mesh())
    for q in QUERIES:
        req = optimize_request(parse_pql(q))
        req2 = optimize_request(parse_pql(q))
        part = ex.execute(segs, req)
        got = reduce_to_response(req, [part])
        want = oracle.execute(req2)
        assert _norm(got) == _norm(want), q
    # the selective point query must actually have taken the skipping
    # path on the mesh, not fallen back to the full sharded scan
    req = optimize_request(
        parse_pql("SELECT sum(l_extendedprice) FROM lineitem WHERE l_shipdate = '1995-06-14'")
    )
    part = ex.execute(segs, req)
    assert part.num_entries_scanned_in_filter < total / 4


def test_docrange_classification_and_fallback(cluster):
    """RANGE/EQ on a column sorted in every segment classifies as a
    doc-interval predicate (no column read); a mixed table where one
    segment is unsorted falls back to the dictId-interval kind."""
    from pinot_tpu.engine.plan import build_static_plan
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment

    segs, _ = cluster

    def kinds(segments, pql):
        req = optimize_request(parse_pql(pql))
        ctx = get_table_context(segments)
        staged = stage_segments(segments, sorted(req.referenced_columns()), ctx=ctx)
        plan = build_static_plan(req, ctx, staged)
        return [l.eval_kind for l in plan.leaves]

    assert kinds(segs, "SELECT count(*) FROM lineitem WHERE l_shipdate <= '1995-01-01'") == ["docrange"]
    assert kinds(segs, "SELECT count(*) FROM lineitem WHERE l_shipdate = '1995-06-14'") == ["docrange"]
    # unsorted column: stays a dictId interval
    assert kinds(segs, "SELECT count(*) FROM lineitem WHERE l_quantity > 25") == ["interval"]
    # IN with several points is not contiguous: stays points
    assert kinds(
        segs, "SELECT count(*) FROM lineitem WHERE l_shipdate IN ('1994-01-05','1997-03-22')"
    ) == ["points"]

    # mixed sortedness across segments: fall back
    unsorted = synthetic_lineitem_segment(5000, seed=99, name="unsorted")
    object.__setattr__(unsorted.column("l_shipdate").metadata, "is_sorted", False)
    mixed = list(segs) + [unsorted]
    assert kinds(mixed, "SELECT count(*) FROM lineitem WHERE l_shipdate <= '1995-01-01'") == ["interval"]


def test_docrange_column_not_staged(cluster):
    """A column used only by docrange predicates never reaches device
    memory: the kernel compares row ids against host-computed bounds."""
    from pinot_tpu.engine.device import clear_staging_cache, _stage_cache

    segs, oracle = cluster
    clear_staging_cache()
    ex = QueryExecutor()
    q = "SELECT sum(l_quantity) FROM lineitem WHERE l_shipdate <= '1994-01-01'"
    req = optimize_request(parse_pql(q))
    req2 = optimize_request(parse_pql(q))
    got = reduce_to_response(req, [ex.execute(segs, req)])
    assert _norm(got) == _norm(oracle.execute(req2))
    staged_cols = {c for st in _stage_cache.values() for c in st.columns}
    assert "l_shipdate" not in staged_cols
    assert "l_quantity" in staged_cols
    clear_staging_cache()


def test_zone_maps_persisted_in_segment_file(tmp_path, monkeypatch):
    """write_segment stores per-block zones; read_segment preloads them
    so the first selective query does no O(n) zone scan."""
    from pinot_tpu.segment.format import read_segment, write_segment

    monkeypatch.setenv("PINOT_TPU_ZONE_BLOCK", "512")
    seg = synthetic_lineitem_segment(5000, seed=5, name="zp")
    d = write_segment(seg, str(tmp_path / "zp"))
    loaded = read_segment(str(tmp_path / "zp"))
    cache = getattr(loaded, "_zone_cache", {})
    assert ("l_shipdate", 512) in cache
    zmin, zmax = cache[("l_shipdate", 512)]
    ref_min, ref_max = zonemap.column_zones(seg, "l_shipdate", 512)
    np.testing.assert_array_equal(zmin, ref_min)
    np.testing.assert_array_equal(zmax, ref_max)
    # column_zones on the loaded segment returns the preloaded arrays
    got = zonemap.column_zones(loaded, "l_shipdate", 512)
    assert got[0] is zmin


def test_persisted_zones_reblock_to_coarser(tmp_path, monkeypatch):
    """Zones persisted at a fine write-time block derive coarser query
    blocks by grouped min/max — no column rescan."""
    from pinot_tpu.segment.format import read_segment, write_segment

    monkeypatch.setenv("PINOT_TPU_ZONE_BLOCK", "256")
    seg = synthetic_lineitem_segment(5000, seed=5, name="zr")
    write_segment(seg, str(tmp_path / "zr"))
    loaded = read_segment(str(tmp_path / "zr"))
    loaded.columns["l_shipdate"] = loaded.columns["l_shipdate"].__class__(
        metadata=loaded.column("l_shipdate").metadata,
        dictionary=loaded.column("l_shipdate").dictionary,
        fwd=None,  # prove the derivation never touches the column
    )
    monkeypatch.setenv("PINOT_TPU_ZONE_BLOCK", "1024")
    got = zonemap.column_zones(loaded, "l_shipdate", 1024)
    want = zonemap.column_zones(seg, "l_shipdate", 1024)
    np.testing.assert_array_equal(got[0], want[0])
    np.testing.assert_array_equal(got[1], want[1])


def test_selection_limit_beyond_candidate_window(cluster):
    """Regression (ADVICE r2): a selective filter with one candidate
    block but LIMIT+OFFSET > block rows must not feed top_k a k larger
    than the gathered view — the candidate window grows (or the plan
    falls back to the full scan) and results still match the oracle."""
    segs, oracle = cluster
    ex = QueryExecutor()
    q = (
        "SELECT l_shipdate, l_quantity FROM lineitem "
        "WHERE l_shipdate = '1995-06-14' "
        f"ORDER BY l_quantity DESC LIMIT {BLOCK + 200}"
    )
    req = optimize_request(parse_pql(q))
    req2 = optimize_request(parse_pql(q))
    got = reduce_to_response(req, [ex.execute(segs, req)])
    assert _norm(got) == _norm(oracle.execute(req2))


def test_runs_leaf_through_block_path(cluster):
    """Regression: a 'runs' eval-kind leaf (>16-value IN list) must
    compute real zone candidacy — treating it as a table leaf read the
    all-False dummy and pruned EVERY block (empty results)."""
    segs, oracle = cluster
    d = segs[0].column("l_shipdate").dictionary
    vals = ", ".join(repr(d.get(i)) for i in range(0, 60, 3))  # 20 points
    q = f"SELECT count(*), sum(l_quantity) FROM lineitem WHERE l_shipdate IN ({vals})"
    req = optimize_request(parse_pql(q))
    from pinot_tpu.engine.plan import build_static_plan

    ctx = get_table_context(segs)
    staged = stage_segments(segs, sorted(req.referenced_columns()), ctx=ctx)
    plan = build_static_plan(req, ctx, staged)
    assert plan.leaves[0].eval_kind == "runs"
    got = reduce_to_response(req, [QueryExecutor().execute(segs, req)])
    want = oracle.execute(optimize_request(parse_pql(q)))
    assert _norm(got) == _norm(want)
    # sanity: the query matches something (the bug returned zero rows)
    assert int(got.to_json()["aggregationResults"][0]["value"]) > 0
