"""Pallas fused-kernel tests (interpret mode on CPU; real-chip lowering
is validated when TPU hardware is attached)."""
import numpy as np
import pytest

import jax.numpy as jnp

from pinot_tpu.engine.pallas_kernels import PALLAS_AVAILABLE, fused_filtered_groupby_sums


@pytest.mark.skipif(not PALLAS_AVAILABLE, reason="pallas not importable")
def test_fused_groupby_matches_numpy():
    rng = np.random.default_rng(0)
    n = 5000
    card_f, card_g, card_v = 7, 6, 50
    filter_fwd = rng.integers(0, card_f, n).astype(np.int32)
    match = np.zeros(card_f, dtype=bool)
    match[[1, 3, 4]] = True
    valid = np.ones(n, dtype=bool)
    valid[-13:] = False
    keys = rng.integers(0, card_g, n).astype(np.int32)
    v_fwd = rng.integers(0, card_v, n).astype(np.int32)
    v_dict = np.round(rng.uniform(0, 100, card_v), 2)

    docs, count, (sums,) = fused_filtered_groupby_sums(
        jnp.asarray(filter_fwd),
        jnp.asarray(match),
        jnp.asarray(valid),
        jnp.asarray(keys),
        [jnp.asarray(v_fwd)],
        [jnp.asarray(v_dict)],
        capacity=card_g,
        interpret=True,
    )

    mask = match[filter_fwd] & valid
    np.testing.assert_allclose(float(docs), mask.sum())
    want_count = np.bincount(keys[mask], minlength=card_g)
    np.testing.assert_allclose(np.asarray(count), want_count, rtol=1e-6)
    vals = v_dict[v_fwd]
    want_sums = np.bincount(keys[mask], weights=vals[mask], minlength=card_g)
    np.testing.assert_allclose(np.asarray(sums), want_sums, rtol=1e-5)


@pytest.mark.skipif(not PALLAS_AVAILABLE, reason="pallas not importable")
def test_fused_groupby_multi_value_columns():
    rng = np.random.default_rng(3)
    n = 1000
    keys = rng.integers(0, 4, n).astype(np.int32)
    filter_fwd = np.zeros(n, dtype=np.int32)
    match = np.ones(1, dtype=bool)
    valid = np.ones(n, dtype=bool)
    fwds = [rng.integers(0, 10, n).astype(np.int32) for _ in range(3)]
    dicts = [np.arange(10, dtype=np.float64) * (i + 1) for i in range(3)]

    docs, count, sums = fused_filtered_groupby_sums(
        jnp.asarray(filter_fwd),
        jnp.asarray(match),
        jnp.asarray(valid),
        jnp.asarray(keys),
        [jnp.asarray(f) for f in fwds],
        [jnp.asarray(d) for d in dicts],
        capacity=4,
        interpret=True,
    )
    assert float(docs) == n
    np.testing.assert_allclose(np.asarray(count), np.bincount(keys, minlength=4))
    for i in range(3):
        want = np.bincount(keys, weights=dicts[i][fwds[i]], minlength=4)
        np.testing.assert_allclose(np.asarray(sums[i]), want, rtol=1e-5)


@pytest.mark.skipif(not PALLAS_AVAILABLE, reason="pallas not importable")
def test_value_state_counts_pallas_matches_xla():
    """The Pallas occupancy histogram (VMEM-resident accumulator)
    matches the XLA factored contraction bit-for-bit, for K both a
    multiple of 128 and not, under direct and vmapped use (the kernel
    runs inside the vmapped per-segment program)."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from pinot_tpu.engine.kernel import (
        _value_state_counts,
        _value_state_counts_pallas,
    )

    rng = np.random.default_rng(12)
    for K in (16384, 300):
        n = 6000
        idx_np = rng.integers(0, K, size=n).astype(np.int32)
        idx_np[rng.random(n) < 0.05] = K  # dropped sentinel entries
        idx = jnp.asarray(idx_np)
        a = np.asarray(_value_state_counts(idx, K))
        b = np.asarray(_value_state_counts_pallas(idx, K))
        assert a.shape == b.shape == (K,)
        assert np.array_equal(a, b), K
        # ground truth
        want = np.bincount(idx_np[idx_np < K], minlength=K)
        assert np.array_equal(a, want.astype(a.dtype))

    K = 1024
    batch = jnp.asarray(rng.integers(0, K, size=(3, 4096)).astype(np.int32))
    va = np.asarray(jax.vmap(lambda i: _value_state_counts(i, K))(batch))
    vb = np.asarray(jax.vmap(lambda i: _value_state_counts_pallas(i, K))(batch))
    assert np.array_equal(va, vb)
