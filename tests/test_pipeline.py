"""Serving-pipeline tests: DeviceLane unit behavior (coalescing,
deadline shed, error fan-out, close), scheduler interaction when the
LANE (not the worker pool) is the bottleneck, and the pipelined-vs-
serial differential on the full broker path."""
import json
import threading
import time

import pytest

from pinot_tpu.engine.dispatch import DeviceLane, LaneClosedError
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.server.scheduler import (
    QueryAbandonedError,
    QueryScheduler,
    SchedulerSaturatedError,
)
from pinot_tpu.tools.datagen import make_test_schema, random_rows
from pinot_tpu.utils.metrics import ServerMetrics


# -- DeviceLane units --------------------------------------------------


def test_lane_dispatches_and_delivers():
    lane = DeviceLane()
    t = lane.submit("k", lambda: 41 + 1)
    assert t.result(time.monotonic() + 5) == 42
    assert lane.dispatch_count == 1
    assert lane.coalesce_hits == 0


def test_lane_coalesces_identical_queued_dispatches():
    """Waiters keyed identically behind a busy lane ride ONE launch."""
    lane = DeviceLane(metrics=ServerMetrics("t"))
    gate = threading.Event()
    launches = []

    def slow():
        gate.wait(5)
        launches.append("slow")
        return "slow-out"

    def fast():
        launches.append("fast")
        return "fast-out"

    t_block = lane.submit("blocker", slow)  # occupies the lane thread
    time.sleep(0.05)  # let the lane pick it up
    tickets = [lane.submit("same", fast) for _ in range(5)]
    other = lane.submit("different", fast)
    gate.set()
    deadline = time.monotonic() + 5
    assert t_block.result(deadline) == "slow-out"
    assert [t.result(deadline) for t in tickets] == ["fast-out"] * 5
    assert other.result(deadline) == "fast-out"
    # 5 identical submits -> 1 launch; the different key launches alone
    assert launches.count("fast") == 2
    assert lane.coalesce_hits == 4
    assert lane.stats()["coalesceHits"] == 4


def test_lane_no_result_caching_after_completion():
    """A submit AFTER an identical dispatch finished re-launches: the
    lane coalesces in-flight work, it is not a result cache."""
    lane = DeviceLane()
    calls = []
    fn = lambda: calls.append(1) or len(calls)
    t1 = lane.submit("k", fn)
    assert t1.result(time.monotonic() + 5) == 1
    # plain python values have no pending device buffers -> closed
    t2 = lane.submit("k", fn)
    assert t2.result(time.monotonic() + 5) == 2
    assert lane.dispatch_count == 2


def test_lane_deadline_shed_while_queued():
    """A waiter whose deadline drains in the lane queue sheds with
    QueryAbandonedError and its dispatch never launches."""
    lane = DeviceLane(metrics=ServerMetrics("t"))
    gate = threading.Event()
    launched = []

    lane.submit("blocker", lambda: gate.wait(5))
    time.sleep(0.05)
    doomed = lane.submit(
        "doomed", lambda: launched.append(1), deadline=time.monotonic() + 0.01
    )
    time.sleep(0.05)  # the deadline expires while 'blocker' holds the lane
    gate.set()
    with pytest.raises(QueryAbandonedError):
        doomed.result(time.monotonic() + 5)
    time.sleep(0.1)
    assert launched == []  # shed before launch, not after
    assert lane.shed_count == 1


def test_lane_mixed_deadline_waiters_still_serve_live_ones():
    """When only SOME coalesced waiters expired, the dispatch still runs
    for the rest."""
    lane = DeviceLane()
    gate = threading.Event()
    lane.submit("blocker", lambda: gate.wait(5))
    time.sleep(0.05)
    dead = lane.submit("k", lambda: "v", deadline=time.monotonic() + 0.01)
    live = lane.submit("k", lambda: "v", deadline=time.monotonic() + 30)
    time.sleep(0.05)
    gate.set()
    with pytest.raises(QueryAbandonedError):
        dead.result(time.monotonic() + 5)
    assert live.result(time.monotonic() + 5) == "v"


def test_lane_error_fans_out_to_all_waiters():
    """Launch failures reach every coalesced waiter as the TYPED
    DeviceExecutionError (lane supervision contract), carrying the raw
    cause; a deterministic error classifies as poison."""
    from pinot_tpu.engine.dispatch import DeviceExecutionError

    lane = DeviceLane()
    gate = threading.Event()

    def boom():
        gate.wait(5)
        raise ValueError("kernel exploded")

    lane.submit("blocker", lambda: gate.wait(5))
    time.sleep(0.05)
    tickets = [lane.submit("bad", boom) for _ in range(3)]
    gate.set()
    for t in tickets:
        with pytest.raises(DeviceExecutionError, match="kernel exploded") as ei:
            t.result(time.monotonic() + 5)
        assert isinstance(ei.value.cause, ValueError)
        assert ei.value.retryable is False  # deterministic -> poison
    assert lane.device_failure_count == 1  # one launch, fanned out
    # an error never stays coalescible: the next submit re-launches
    ok = lane.submit("bad", lambda: "fine")
    assert ok.result(time.monotonic() + 5) == "fine"


def test_lane_close_fails_queued_and_rejects_new():
    lane = DeviceLane()
    gate = threading.Event()
    lane.submit("blocker", lambda: gate.wait(5))
    time.sleep(0.05)
    queued = lane.submit("q", lambda: "never")
    lane.close()
    lane.close()  # idempotent
    gate.set()
    with pytest.raises(LaneClosedError):
        queued.result(time.monotonic() + 5)
    with pytest.raises(LaneClosedError):
        lane.submit("x", lambda: 1)


def test_lane_result_honors_caller_deadline():
    lane = DeviceLane()
    gate = threading.Event()
    lane.submit("blocker", lambda: gate.wait(5))
    time.sleep(0.05)
    slow = lane.submit("s", lambda: "late")
    with pytest.raises(TimeoutError):
        slow.result(time.monotonic() + 0.05)
    gate.set()


# -- scheduler x lane interaction -------------------------------------


def test_saturation_shed_when_lane_is_bottleneck():
    """With the device lane wedged, workers pile up blocked on tickets,
    the pending queue fills, and NEW submits shed with the saturation
    error — the overload policy holds no matter which stage binds."""
    lane = DeviceLane()
    sched = QueryScheduler(num_workers=2, max_pending=3)
    gate = threading.Event()
    lane.submit("blocker", lambda: gate.wait(10))
    time.sleep(0.05)

    def query(i):
        ticket = lane.submit(f"q{i}", lambda: i)  # distinct keys: no coalesce
        return ticket.result(time.monotonic() + 10)

    futs = [sched.submit(lambda i=i: query(i)) for i in range(3)]
    time.sleep(0.1)  # two workers blocked in the lane, one queued
    with pytest.raises(SchedulerSaturatedError):
        sched.submit(lambda: query(99))
    assert sched.shed_count == 1
    gate.set()
    assert sorted(f.result(timeout=10) for f in futs) == [0, 1, 2]
    sched.shutdown()
    lane.close()


def test_deadline_abandonment_with_lane_bottleneck():
    """Deadline expiry while BLOCKED BEHIND the lane (not the worker
    queue) still surfaces as abandonment/timeout, and the lane sheds the
    expired waiter instead of executing it."""
    lane = DeviceLane()
    sched = QueryScheduler(num_workers=1, max_pending=4)
    gate = threading.Event()
    executed = []
    lane.submit("blocker", lambda: gate.wait(10))
    time.sleep(0.05)

    deadline = time.monotonic() + 0.2

    def query():
        if time.monotonic() >= deadline:
            raise QueryAbandonedError("expired pre-lane")
        ticket = lane.submit("q", lambda: executed.append(1), deadline=deadline)
        return ticket.result(deadline)

    fut = sched.submit(query)
    with pytest.raises((QueryAbandonedError, TimeoutError)):
        fut.result(timeout=10)
    gate.set()
    time.sleep(0.1)
    assert executed == []  # never ran device work for the dead query
    sched.shutdown()
    lane.close()


# -- full-path differential -------------------------------------------


def _payload(resp) -> str:
    # cost excluded like timeUsedMs: it records HOW the path executed
    # (coalesce hits, device ms), which differs serial vs pipelined;
    # freshnessMs is wall-clock-relative staleness, never payload
    return json.dumps(
        {k: v for k, v in resp.to_json().items()
         if k not in ("timeUsedMs", "requestId", "cost", "freshnessMs")},
        sort_keys=True,
    )


@pytest.fixture(scope="module")
def differential_stack():
    from pinot_tpu.tools.cluster_harness import single_server_broker

    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 4000, seed=9)
    segs = [
        build_segment(schema, rows[:2000], "testTable", "pseg0"),
        build_segment(schema, rows[2000:], "testTable", "pseg1"),
    ]
    serial = single_server_broker("testTable", segs, pipeline=False)
    pipelined = single_server_broker("testTable", segs, pipeline=True)
    return serial, pipelined


DIFF_QUERIES = [
    "SELECT count(*) FROM testTable",
    "SELECT sum(metInt), min(metFloat), max(metInt) FROM testTable WHERE dimInt > 50",
    "SELECT sum(metInt) FROM testTable GROUP BY dimStr TOP 5",
    "SELECT distinctcount(dimInt) FROM testTable GROUP BY dimStr TOP 5",
    "SELECT dimStr, metInt FROM testTable ORDER BY metInt DESC LIMIT 7",
]


def test_pipelined_matches_serial_payloads(differential_stack):
    serial, pipelined = differential_stack
    for pql in DIFF_QUERIES:
        a = serial.handle_pql(pql)
        b = pipelined.handle_pql(pql)
        assert not a.exceptions and not b.exceptions, (pql, a.exceptions, b.exceptions)
        assert _payload(a) == _payload(b), pql


def test_coalesced_waiters_get_independent_correct_results(differential_stack):
    """Concurrent identical queries through the pipelined broker: every
    waiter's payload equals the serial path's, and the lane actually
    coalesced (same results from FEWER dispatches)."""
    serial, pipelined = differential_stack
    pql = DIFF_QUERIES[2]
    want = _payload(serial.handle_pql(pql))
    server = pipelined.local_servers[0]
    base_hits = server.lane.coalesce_hits

    payloads = []
    errs = []
    lock = threading.Lock()

    def hit():
        for _ in range(8):
            resp = pipelined.handle_pql(pql)
            with lock:
                if resp.exceptions:
                    errs.append(resp.exceptions)
                else:
                    payloads.append(_payload(resp))

    threads = [threading.Thread(target=hit) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs[:1]
    assert len(payloads) == 64
    assert set(payloads) == {want}
    assert server.lane.coalesce_hits > base_hits  # dispatches were shared


def test_status_surface_exposes_pipeline_counters(differential_stack):
    _, pipelined = differential_stack
    status = pipelined.local_servers[0].status()
    assert status["lane"] is not None
    for key in ("depth", "dispatches", "coalesceHits", "shed"):
        assert key in status["lane"]
    assert "pending" in status["scheduler"]
    timers = status["metrics"]["timers"]
    assert "phase.laneWait" in timers and "phase.laneDispatch" in timers
