"""TPU engine tests: sentinel golden values + differential vs the scan
oracle (the QueriesSentinelTest / H2-differential analogs, SURVEY §4)."""
import json
import math

import pytest

from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.engine.executor import QueryExecutor
from pinot_tpu.engine.reduce import reduce_to_response
from pinot_tpu.pql import parse_pql, optimize_request
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.tools.datagen import make_test_schema, random_rows
from pinot_tpu.tools.query_gen import QueryGenerator
from pinot_tpu.tools.scan_engine import ScanQueryProcessor

SCHEMA = Schema(
    "t",
    dimensions=[
        FieldSpec("city", DataType.STRING),
        FieldSpec("tags", DataType.STRING_ARRAY, single_value=False),
    ],
    metrics=[
        FieldSpec("sales", DataType.INT, FieldType.METRIC),
        FieldSpec("price", DataType.DOUBLE, FieldType.METRIC),
    ],
)

ROWS = [
    {"city": "sf", "tags": ["a", "b"], "sales": 10, "price": 1.5},
    {"city": "sf", "tags": ["b"], "sales": 20, "price": 2.5},
    {"city": "ny", "tags": ["a"], "sales": 30, "price": 3.5},
    {"city": "la", "tags": ["c", "a"], "sales": 40, "price": 4.5},
    {"city": "ny", "tags": ["b", "c"], "sales": 50, "price": 5.5},
]

SEGMENT = build_segment(SCHEMA, ROWS, "t", "s0")
EXECUTOR = QueryExecutor()


def run_engine(pql, segments=None):
    req = optimize_request(parse_pql(pql))
    res = EXECUTOR.execute(segments or [SEGMENT], req)
    return reduce_to_response(req, [res])


def agg_values(resp):
    return [a.value for a in resp.aggregation_results]


# ------------------------------------------------------------- sentinels
def test_count_star():
    assert agg_values(run_engine("SELECT count(*) FROM t")) == [5]


def test_basic_aggs():
    resp = run_engine(
        "SELECT sum(sales), min(sales), max(sales), avg(sales), minmaxrange(sales) FROM t"
    )
    assert agg_values(resp) == [150.0, 10.0, 50.0, 30.0, 40.0]


def test_filters():
    assert agg_values(run_engine("SELECT count(*) FROM t WHERE city = 'sf'")) == [2]
    assert agg_values(run_engine("SELECT count(*) FROM t WHERE city IN ('sf','ny')")) == [4]
    assert agg_values(run_engine("SELECT count(*) FROM t WHERE sales > 20")) == [3]
    assert agg_values(run_engine("SELECT count(*) FROM t WHERE sales BETWEEN 20 AND 40")) == [3]
    assert agg_values(run_engine("SELECT count(*) FROM t WHERE city <> 'sf'")) == [3]
    assert agg_values(run_engine("SELECT count(*) FROM t WHERE city NOT IN ('sf','la')")) == [2]
    assert agg_values(
        run_engine("SELECT count(*) FROM t WHERE city = 'sf' OR sales = 40")
    ) == [3]


def test_mv_filters():
    assert agg_values(run_engine("SELECT count(*) FROM t WHERE tags = 'a'")) == [3]
    assert agg_values(run_engine("SELECT count(*) FROM t WHERE tags <> 'a'")) == [2]


def test_regex_filter():
    assert agg_values(run_engine("SELECT count(*) FROM t WHERE regexp_like(city, '^s')")) == [2]


def test_distinct_and_hll():
    assert agg_values(run_engine("SELECT distinctcount(city) FROM t")) == [3]
    assert agg_values(run_engine("SELECT distinctcountmv(tags) FROM t")) == [3]
    assert agg_values(run_engine("SELECT distinctcounthll(sales) FROM t")) == [5]


def test_percentiles():
    assert agg_values(run_engine("SELECT percentile50(sales) FROM t")) == [30.0]
    assert agg_values(run_engine("SELECT percentile90(sales) FROM t")) == [50.0]


def test_group_by():
    resp = run_engine("SELECT sum(sales) FROM t GROUP BY city TOP 2")
    gr = resp.aggregation_results[0].group_by_result
    assert [(g.group, g.value) for g in gr] == [(["ny"], 80.0), (["la"], 40.0)]


def test_group_by_min_asc():
    resp = run_engine("SELECT min(sales) FROM t GROUP BY city")
    gr = resp.aggregation_results[0].group_by_result
    assert [(g.group[0], g.value) for g in gr] == [("sf", 10.0), ("ny", 30.0), ("la", 40.0)]


def test_group_by_mv():
    resp = run_engine("SELECT count(*) FROM t GROUP BY tags")
    gr = {g.group[0]: g.value for g in resp.aggregation_results[0].group_by_result}
    assert gr == {"a": 3, "b": 3, "c": 2}


def test_group_by_multi():
    resp = run_engine("SELECT sum(sales) FROM t GROUP BY city, tags TOP 100")
    gr = {tuple(g.group): g.value for g in resp.aggregation_results[0].group_by_result}
    assert gr[("sf", "b")] == 30.0
    assert gr[("ny", "c")] == 50.0


def test_mv_aggregation():
    assert agg_values(run_engine("SELECT countmv(tags) FROM t")) == [8]


def test_selection():
    resp = run_engine("SELECT city, sales FROM t LIMIT 3")
    assert resp.selection_results.rows == [["sf", 10], ["sf", 20], ["ny", 30]]


def test_selection_order_by():
    resp = run_engine("SELECT city FROM t ORDER BY sales DESC LIMIT 2")
    assert resp.selection_results.rows == [["ny"], ["la"]]


def test_selection_star():
    resp = run_engine("SELECT * FROM t LIMIT 1")
    assert resp.selection_results.columns == ["city", "tags", "sales", "price"]


def test_empty_filter_result():
    resp = run_engine("SELECT count(*), sum(sales) FROM t WHERE city = 'zz'")
    assert agg_values(resp) == [0, 0.0]


def test_stats():
    resp = run_engine("SELECT count(*) FROM t WHERE city = 'sf'")
    assert resp.num_docs_scanned == 2
    assert resp.total_docs == 5
    assert resp.num_segments_queried == 1


# ------------------------------------------------- differential vs oracle
def _norm(resp):
    # cost carries wall-clock ms (path-dependent): never bit-identical
    return json.dumps(
        {k: v for k, v in resp.to_json().items() if k != "cost"}, sort_keys=True
    )


def _values_close(a, b, tol=1e-6):
    if isinstance(a, dict) and isinstance(b, dict):
        return set(a) == set(b) and all(_values_close(a[k], b[k], tol) for k in a)
    if isinstance(a, list) and isinstance(b, list):
        return len(a) == len(b) and all(_values_close(x, y, tol) for x, y in zip(a, b))
    if isinstance(a, str) and isinstance(b, str):
        try:
            fa, fb = float(a), float(b)
            if math.isinf(fa) or math.isinf(fb):
                return fa == fb
            return abs(fa - fb) <= tol * max(1.0, abs(fa), abs(fb))
        except ValueError:
            return a == b
    return a == b


def _run_differential(num_segments, seed, num_queries=40):
    schema = make_test_schema()
    rows = random_rows(schema, 1200, seed=seed, cardinality=15)
    if num_segments == 1:
        segments = [build_segment(schema, rows, "testTable", "seg0")]
    else:
        chunk = len(rows) // num_segments
        segments = [
            build_segment(
                schema,
                rows[i * chunk : (i + 1) * chunk if i < num_segments - 1 else len(rows)],
                "testTable",
                f"seg{i}",
            )
            for i in range(num_segments)
        ]
    oracle = ScanQueryProcessor(schema, rows)
    gen = QueryGenerator(schema, rows, seed=seed)
    mismatches = []
    for qi in range(num_queries):
        pql = gen.next_query()
        req_e = optimize_request(parse_pql(pql))
        req_o = optimize_request(parse_pql(pql))
        got = reduce_to_response(req_e, [EXECUTOR.execute(segments, req_e)])
        want = oracle.execute(req_o)
        gj, wj = got.to_json(), want.to_json()
        for k in ("timeUsedMs", "cost", "numEntriesScannedInFilter", "numEntriesScannedPostFilter",
                  "numSegmentsQueried", "numServersQueried", "numServersResponded"):
            gj.pop(k, None)
            wj.pop(k, None)
        if not _values_close(gj, wj):
            mismatches.append((pql, gj, wj))
    assert not mismatches, f"{len(mismatches)} mismatches; first: " + json.dumps(
        mismatches[0], indent=2, default=str
    )[:4000]


def test_differential_single_segment():
    _run_differential(1, seed=11)


def test_differential_multi_segment():
    _run_differential(3, seed=23)


def test_differential_more_queries():
    _run_differential(2, seed=47, num_queries=60)


def test_runs_eval_kind_regex_and_large_in():
    """Table-kind leaves with few dictId runs evaluate as interval
    unions (plan eval_kind 'runs'): regex on ordered values, >16-value
    IN lists, and their negations match the oracle."""
    from pinot_tpu.engine.context import get_table_context
    from pinot_tpu.engine.device import stage_segments
    from pinot_tpu.engine.plan import build_static_plan

    schema = make_test_schema(with_mv=True)
    rows = random_rows(schema, 3000, seed=31, cardinality=60)
    segs = [
        build_segment(schema, rows[:1500], "testTable", "r0"),
        build_segment(schema, rows[1500:], "testTable", "r1"),
    ]
    oracle = ScanQueryProcessor(schema, rows)
    in_vals = ", ".join(str(v) for v in range(0, 40))  # 40 points > _MAX_POINTS
    queries = [
        f"SELECT count(*), sum(metInt) FROM testTable WHERE dimInt IN ({in_vals})",
        f"SELECT count(*) FROM testTable WHERE dimInt NOT IN ({in_vals})",
        "SELECT count(*) FROM testTable WHERE REGEXP_LIKE(dimStr, 's1.*')",
        f"SELECT count(*) FROM testTable WHERE dimIntMV IN ({in_vals})",
    ]
    for pql in queries:
        req = optimize_request(parse_pql(pql))
        req2 = optimize_request(parse_pql(pql))
        got = reduce_to_response(req, [EXECUTOR.execute(segs, req)])
        want = oracle.execute(req2)
        gj, wj = got.to_json(), want.to_json()
        for k in ("timeUsedMs", "cost", "numEntriesScannedInFilter", "numEntriesScannedPostFilter",
                  "numSegmentsQueried", "numServersQueried", "numServersResponded"):
            gj.pop(k, None)
            wj.pop(k, None)
        assert _values_close(gj, wj), (pql, gj, wj)

    # the plan actually selected the runs kind for the big IN list
    req = optimize_request(parse_pql(queries[0]))
    ctx = get_table_context(segs)
    staged = stage_segments(segs, sorted(req.referenced_columns()), ctx=ctx)
    plan = build_static_plan(req, ctx, staged)
    kinds = {l.eval_kind for l in plan.leaves}
    assert "runs" in kinds, kinds


def test_matmul_holder_paths_forced(monkeypatch):
    """The MXU one-hot paths (fused group contraction + combined-key
    dense presence/hist holders) are off on the CPU backend by default;
    force them on so CPU CI locks their correctness against the oracle
    (they are the production TPU paths)."""
    monkeypatch.setenv("PINOT_TPU_GROUPBY_MATMUL", "1")
    schema = make_test_schema(with_mv=True)
    rows = random_rows(schema, 2500, seed=55, cardinality=30)
    segs = [
        build_segment(schema, rows[:1250], "testTable", "mm0"),
        build_segment(schema, rows[1250:], "testTable", "mm1"),
    ]
    oracle = ScanQueryProcessor(schema, rows)
    for pql in [
        "SELECT sum(metInt), count(*), avg(metFloat) FROM testTable GROUP BY dimStr TOP 10",
        "SELECT distinctcount(dimInt) FROM testTable GROUP BY dimStr TOP 10",
        "SELECT percentile90(metInt) FROM testTable GROUP BY dimStr TOP 10",
        "SELECT distinctcount(dimInt), percentile50(metInt) FROM testTable",
        "SELECT distinctcountmv(dimIntMV) FROM testTable GROUP BY dimStr TOP 10",
        "SELECT distinctcount(dimLong) FROM testTable WHERE dimInt > 400 GROUP BY dimStr TOP 10",
        "SELECT distinctcounthll(dimLong), fasthll(dimInt) FROM testTable",
        "SELECT distinctcounthllmv(dimIntMV) FROM testTable WHERE dimInt <= 700",
    ]:
        req = optimize_request(parse_pql(pql))
        req2 = optimize_request(parse_pql(pql))
        got = reduce_to_response(req, [EXECUTOR.execute(segs, req)])
        want = oracle.execute(req2)
        gj, wj = got.to_json(), want.to_json()
        for k in ("timeUsedMs", "cost", "numEntriesScannedInFilter", "numEntriesScannedPostFilter",
                  "numSegmentsQueried", "numServersQueried", "numServersResponded"):
            gj.pop(k, None)
            wj.pop(k, None)
        assert _values_close(gj, wj), (pql, gj, wj)


def test_grouped_hll_mxu_contraction(monkeypatch):
    """The grouped-HLL occupancy contraction (small group spaces) vs
    the oracle — the cap is raised and kernel caches cleared so the
    branch PROVABLY executes (the default gate admits capacity <= 16)."""
    from pinot_tpu.engine import kernel as kernel_mod

    monkeypatch.setenv("PINOT_TPU_GROUPBY_MATMUL", "1")
    monkeypatch.setattr(kernel_mod, "_MATMUL_HLL_CAP", 1 << 24)
    kernel_mod.make_table_kernel.cache_clear()
    kernel_mod.make_packed_table_kernel.cache_clear()
    try:
        schema = make_test_schema(with_mv=True)
        rows = random_rows(schema, 600, seed=66, cardinality=5)
        segs = [
            build_segment(schema, rows[:300], "testTable", "hm0"),
            build_segment(schema, rows[300:], "testTable", "hm1"),
        ]
        oracle = ScanQueryProcessor(schema, rows)
        for pql in [
            "SELECT distinctcounthll(dimLong) FROM testTable GROUP BY dimStr TOP 15",
            "SELECT fasthllmv(dimIntMV), count(*) FROM testTable GROUP BY dimStr TOP 15",
        ]:
            req = optimize_request(parse_pql(pql))
            req2 = optimize_request(parse_pql(pql))
            got = reduce_to_response(req, [EXECUTOR.execute(segs, req)])
            want = oracle.execute(req2)
            gj, wj = got.to_json(), want.to_json()
            for k in ("timeUsedMs", "cost", "numEntriesScannedInFilter", "numEntriesScannedPostFilter",
                      "numSegmentsQueried", "numServersQueried", "numServersResponded"):
                gj.pop(k, None)
                wj.pop(k, None)
            assert _values_close(gj, wj), (pql, gj, wj)
    finally:
        kernel_mod.make_table_kernel.cache_clear()
        kernel_mod.make_packed_table_kernel.cache_clear()
        from pinot_tpu.engine.device import clear_staging_cache

        clear_staging_cache()


def test_regex_table_cache_and_qinput_cache(monkeypatch):
    """Repeated regex queries scan the dictionary once (plan._regex_tables
    LRU) and repeated identical queries reuse device-resident inputs
    (executor query-input cache) — both per-query upload/scan costs are
    paid once on a served workload."""
    from pinot_tpu.engine import plan as plan_mod

    plan_mod._regex_tables.clear()
    calls = {"n": 0}
    real = plan_mod.match_table

    def counting(leaf, d, card_pad):
        calls["n"] += 1
        return real(leaf, d, card_pad)

    monkeypatch.setattr(plan_mod, "match_table", counting)
    ex = QueryExecutor()
    req = optimize_request(parse_pql("SELECT count(*) FROM t WHERE regexp_like(city, '^s')"))
    r1 = ex.execute([SEGMENT], req)
    first = calls["n"]
    assert first >= 1
    r2 = ex.execute([SEGMENT], req)
    assert calls["n"] == first  # second query: all regex tables cached
    assert reduce_to_response(req, [r1]).aggregation_results[0].value == \
        reduce_to_response(req, [r2]).aggregation_results[0].value == 2

    # the device-input cache is populated and keyed by plan+content
    assert len(ex._qinput_cache) >= 1


def test_having_engine_sentinel():
    """Direct engine+reduce HAVING: groups failing the predicate drop
    from every agg list (SQL semantics), exact sentinel values."""
    resp = run_engine(
        "SELECT sum(sales), count(*) FROM t GROUP BY city HAVING sum(sales) > 35 TOP 10"
    )
    by_city = {
        tuple(g.group)[0]: (g.value, None)
        for g in resp.aggregation_results[0].group_by_result
    }
    # sums: sf=30, ny=80, la=40 -> only ny and la pass
    assert set(by_city) == {"ny", "la"}
    counts = {
        tuple(g.group)[0]: g.value
        for g in resp.aggregation_results[1].group_by_result
    }
    assert set(counts) == {"ny", "la"}  # count list filtered too
    assert float(counts["ny"]) == 2 and float(counts["la"]) == 1


def test_grouped_hll_three_lowerings_bit_identical(monkeypatch):
    """The grouped-HLL matmul / packed-sort / scatter lowerings must be
    interchangeable: same registers, same estimates, byte-identical
    responses (the sort path's searchsorted run-max extraction is the
    round-5 replacement for scatter-max; the matmul occupancy is the
    small-capacity fast path)."""
    from pinot_tpu.engine import kernel as kernel_mod
    from pinot_tpu.engine.device import clear_staging_cache

    schema = make_test_schema(with_mv=True)
    rows = random_rows(schema, 3000, seed=77, cardinality=40)
    segs = [
        build_segment(schema, rows[:1500], "testTable", "hl0"),
        build_segment(schema, rows[1500:], "testTable", "hl1"),
    ]
    pqls = [
        "SELECT distinctcounthll(dimLong) FROM testTable GROUP BY dimStr TOP 10",
        "SELECT fasthll(dimLong), count(*) FROM testTable "
        "GROUP BY dimStr, dimInt TOP 12",
    ]
    variants = {
        # (GROUPBY_MATMUL, _MATMUL_HLL_CAP, _HLL_SORT_CAP) -> path
        # 1<<25 covers BOTH queries' K = capacity * 16384 (the two-dim
        # group space is 40*39=1560 -> K ~= 25.6M) so the matmul
        # variant genuinely takes the matmul lowering for each
        "matmul": ("1", 1 << 25, 1 << 16),
        "sort": ("0", 1 << 18, 1 << 16),
        "scatter": ("0", 1 << 18, 0),
    }
    results = {}
    try:
        for name, (mm, hll_cap, sort_cap) in variants.items():
            monkeypatch.setenv("PINOT_TPU_GROUPBY_MATMUL", mm)
            monkeypatch.setattr(kernel_mod, "_MATMUL_HLL_CAP", hll_cap)
            monkeypatch.setattr(kernel_mod, "_HLL_SORT_CAP", sort_cap)
            kernel_mod.make_table_kernel.cache_clear()
            kernel_mod.make_packed_table_kernel.cache_clear()
            clear_staging_cache()
            out = []
            for q in pqls:
                req = optimize_request(parse_pql(q))
                resp = reduce_to_response(req, [QueryExecutor().execute(segs, req)])
                assert not resp.exceptions, (name, q, resp.exceptions)
                out.append(_norm(resp))
            results[name] = out
    finally:
        kernel_mod.make_table_kernel.cache_clear()
        kernel_mod.make_packed_table_kernel.cache_clear()
        clear_staging_cache()
    assert results["matmul"] == results["sort"] == results["scatter"]
