"""Server self-healing tests: device-lane supervision (typed errors,
watchdog restart, re-driven queue), transparent host failover with the
poison quarantine, deterministic device chaos (seeded
DeviceFaultInjector), and segment integrity (CRC verification at fetch
/ load / add time, quarantine + re-fetch from the controller copy with
the partialResponse contract served mid-recovery)."""
import json
import os
import threading
import time

import pytest

from pinot_tpu.common.faults import DeviceFaultInjector
from pinot_tpu.engine.dispatch import (
    DeviceExecutionError,
    DeviceLane,
    classify_device_error,
)
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.tools.datagen import make_test_schema, random_rows
from pinot_tpu.tools.cluster_harness import single_server_broker

TABLE = "healTable"


# excluded from the byte-identity check: wall time and the
# entries-scanned WORK accounting (they describe how a path executed —
# a host fallback scans different entry counts than the device kernel
# by construction).  Results, docs scanned, and the degradation
# contract fields all must match exactly.
_PATH_DEPENDENT = {
    "timeUsedMs",
    "requestId",  # broker-assigned per query, never payload
    "numEntriesScannedInFilter",
    "numEntriesScannedPostFilter",
    "cost",  # cost vector describes HOW a path executed (device vs host
    # ms, serving tier) — path-dependent by construction
    "freshnessMs",  # wall-clock-relative event-time staleness, not payload
}


def _payload(resp) -> str:
    return json.dumps(
        {k: v for k, v in resp.to_json().items() if k not in _PATH_DEPENDENT},
        sort_keys=True,
    )


# -- error classification ---------------------------------------------


def test_classify_device_error_retryable_vs_poison():
    transient = classify_device_error(RuntimeError("RESOURCE_EXHAUSTED: hbm oom"))
    assert transient.retryable is True
    poison = classify_device_error(TypeError("lowering failed for shape (3,)"))
    assert poison.retryable is False
    assert isinstance(poison.cause, TypeError)
    # idempotent: an already-typed error passes through untouched
    again = classify_device_error(poison)
    assert again is poison


# -- lane watchdog / restart units ------------------------------------


def test_lane_watchdog_restarts_wedged_lane_and_redrives_queue():
    """A launch wedged past the stall timeout: waiters get the typed
    stall error, the lane respawns, and dispatches still QUEUED behind
    the wedge run to completion on the new thread."""
    lane = DeviceLane(stall_timeout_s=0.15)
    gate = threading.Event()

    def wedge():
        gate.wait(10)
        return "late"

    stuck = lane.submit("wedge", wedge)
    time.sleep(0.05)  # lane thread inside the wedge
    behind = lane.submit("behind", lambda: "ok")
    with pytest.raises(DeviceExecutionError) as ei:
        stuck.result(time.monotonic() + 5)
    assert ei.value.stalled and ei.value.retryable is False
    # the queued dispatch was re-driven by the respawned lane thread
    assert behind.result(time.monotonic() + 5) == "ok"
    assert lane.restart_count == 1
    assert lane.device_failure_count >= 1
    assert lane.stats()["restarts"] == 1
    gate.set()  # unwedge the abandoned thread
    time.sleep(0.05)
    lane.close()


def test_lane_stale_completion_discarded_after_restart():
    """The abandoned thread's eventual return value must be dropped: a
    fresh identical submit re-launches instead of seeing stale state."""
    lane = DeviceLane(stall_timeout_s=0.1)
    gate = threading.Event()
    calls = []

    def wedge():
        gate.wait(10)
        calls.append("wedge")
        return "stale-value"

    stuck = lane.submit("k", wedge)
    with pytest.raises(DeviceExecutionError):
        stuck.result(time.monotonic() + 5)
    gate.set()  # old thread completes NOW, after the restart
    time.sleep(0.2)
    assert lane.stale_completions == 1
    fresh = lane.submit("k", lambda: "fresh")
    assert fresh.result(time.monotonic() + 5) == "fresh"
    assert calls == ["wedge"]
    lane.close()


def test_lane_injector_raises_typed_faults():
    inj = DeviceFaultInjector(seed=3)
    lane = DeviceLane(fault_injector=inj)
    inj.fail_next(1, retryable=True)
    bad = lane.submit("a", lambda: 1)
    with pytest.raises(DeviceExecutionError) as ei:
        bad.result(time.monotonic() + 5)
    assert ei.value.retryable is True
    ok = lane.submit("a", lambda: 2)  # injector healed after one
    assert ok.result(time.monotonic() + 5) == 2
    assert [r.outcome for r in inj.launches] == ["fail_next", "ok"]
    lane.close()


# -- full-path failover (chaos tier) ----------------------------------


@pytest.fixture()
def heal_stack():
    """One pipelined server + broker with a seeded device fault
    injector and a fast lane watchdog, plus a serial (device-healthy)
    twin for byte-identical reference payloads."""
    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 3000, seed=21)
    segs = [
        build_segment(schema, rows[:1500], TABLE, "h0"),
        build_segment(schema, rows[1500:], TABLE, "h1"),
    ]
    inj = DeviceFaultInjector(seed=11)
    broker = single_server_broker(
        TABLE,
        segs,
        pipeline=True,
        device_fault_injector=inj,
        lane_stall_timeout_s=0.2,
    )
    reference = single_server_broker(TABLE, segs, pipeline=False)
    yield broker, reference, inj
    broker.local_servers[0].shutdown()
    reference.local_servers[0].shutdown()


CHAOS_QUERIES = [
    "SELECT count(*) FROM healTable",
    "SELECT sum(metInt), min(metFloat), max(metInt) FROM healTable WHERE dimInt > 50",
    "SELECT sum(metInt) FROM healTable GROUP BY dimStr TOP 5",
    "SELECT distinctcount(dimInt) FROM healTable GROUP BY dimStr TOP 5",
    "SELECT dimStr, metInt FROM healTable ORDER BY metInt DESC LIMIT 7",
    # scalar distinct + percentile with a filter: exercises the host
    # fallback's ROW-WISE accumulator path under failover (regression:
    # it used to build mergeable partials and crash on .add)
    "SELECT distinctcount(dimInt), percentile50(metInt) FROM healTable WHERE metInt > 100",
]


@pytest.mark.chaos
def test_transient_device_failure_heals_with_one_device_retry(heal_stack):
    broker, reference, inj = heal_stack
    pql = CHAOS_QUERIES[1]
    want = _payload(reference.handle_pql(pql))
    inj.fail_next(1, retryable=True)
    resp = broker.handle_pql(pql)
    assert not resp.exceptions
    assert _payload(resp) == want
    heal = broker.local_servers[0].status()["selfHealing"]
    assert heal["deviceFailures"] >= 1
    assert heal["deviceRetries"] >= 1
    assert heal["hostFailovers"] == 0  # the device retry was enough


@pytest.mark.chaos
def test_poisoned_plan_serves_byte_identical_via_host_failover(heal_stack):
    """Acceptance (a): a poisoned plan keeps answering, byte-identical
    to the healthy device run, and repeat offenders skip the device."""
    broker, reference, inj = heal_stack
    pql = CHAOS_QUERIES[2]
    want = _payload(reference.handle_pql(pql))
    healthy = broker.handle_pql(pql)
    assert _payload(healthy) == want
    digest = inj.launches[-1].digest
    assert digest is not None

    inj.poison_plan(digest)
    poisoned = broker.handle_pql(pql)
    assert not poisoned.exceptions
    assert _payload(poisoned) == want  # host failover, same bytes
    server = broker.local_servers[0]
    heal = server.status()["selfHealing"]
    assert heal["deviceFailures"] >= 1
    assert heal["hostFailovers"] >= 1
    assert heal["poisonedPlans"] >= 1

    # quarantined now: the next repeat goes straight to host — the
    # injector must see NO new launch for this digest
    launches_before = len(inj.launches)
    again = broker.handle_pql(pql)
    assert _payload(again) == want
    assert len(inj.launches) == launches_before
    assert server.status()["selfHealing"]["poisonSkips"] >= 1

    # other plans still run on device
    other = broker.handle_pql(CHAOS_QUERIES[0])
    assert not other.exceptions
    assert len(inj.launches) > launches_before


@pytest.mark.chaos
def test_stalled_dispatch_restarts_lane_and_still_answers(heal_stack):
    """Acceptance (b): a wedged kernel launch trips the watchdog; the
    stalled query fails over to host (answered, not errored), and a
    query queued behind the wedge is re-driven on device."""
    broker, reference, inj = heal_stack
    stall_pql = CHAOS_QUERIES[3]
    behind_pql = CHAOS_QUERIES[0]
    want_stall = _payload(reference.handle_pql(stall_pql))
    want_behind = _payload(reference.handle_pql(behind_pql))

    inj.stall_next(1, stall_s=1.0)  # >> lane stall timeout (0.2s)
    results = {}

    def run(name, pql):
        results[name] = broker.handle_pql(pql)

    t1 = threading.Thread(target=run, args=("stalled", stall_pql))
    t1.start()
    time.sleep(0.08)  # stalled launch occupies the lane thread
    t2 = threading.Thread(target=run, args=("behind", behind_pql))
    t2.start()
    t1.join(30)
    t2.join(30)
    assert not results["stalled"].exceptions
    assert not results["behind"].exceptions
    assert _payload(results["stalled"]) == want_stall  # host failover
    assert _payload(results["behind"]) == want_behind
    server = broker.local_servers[0]
    heal = server.status()["selfHealing"]
    assert heal["laneRestarts"] >= 1
    assert heal["hostFailovers"] >= 1
    assert server.lane.restart_count >= 1


@pytest.mark.chaos
def test_alloc_failure_heals_as_resource_exhausted(heal_stack):
    """A device allocation failure (injected RESOURCE_EXHAUSTED) is a
    distinct heal class: demote-and-retry answers on DEVICE with
    byte-identical results — no host failover, no plan poisoning."""
    broker, reference, inj = heal_stack
    pql = CHAOS_QUERIES[1]
    want = _payload(reference.handle_pql(pql))
    server = broker.local_servers[0]
    heal0 = dict(server.status()["selfHealing"])

    inj.alloc_fail_next(1)
    resp = broker.handle_pql(pql)
    assert not resp.exceptions
    assert _payload(resp) == want
    assert "alloc_fail" in [r.outcome for r in inj.launches]

    heal = server.status()["selfHealing"]
    assert heal["resourceExhausted"] >= heal0["resourceExhausted"] + 1
    assert heal["deviceFailures"] >= heal0["deviceFailures"] + 1
    # OOM never poisons and never leaves the device
    assert heal["hostFailovers"] == heal0["hostFailovers"]
    assert heal["poisonedPlans"] == heal0["poisonedPlans"]

    # the healed plan keeps serving on device afterwards
    again = broker.handle_pql(pql)
    assert _payload(again) == want
    assert inj.launches[-1].outcome == "ok"


@pytest.mark.chaos
def test_coalesced_waiters_all_get_failover_result(heal_stack):
    """Acceptance (c): waiters coalesced onto a failing dispatch all
    receive the failover RESULT — never the raw device exception."""
    broker, reference, inj = heal_stack
    pql = CHAOS_QUERIES[2]
    want = _payload(reference.handle_pql(pql))
    server = broker.local_servers[0]

    # warm both plans so PREP is milliseconds and submits overlap
    assert _payload(broker.handle_pql(pql)) == want
    broker.handle_pql(CHAOS_QUERIES[0])

    # wedge the lane briefly (below the watchdog timeout) so identical
    # queries pile up + coalesce behind the blocker...
    inj.stall_next(1, stall_s=0.15)
    base_hits = server.lane.coalesce_hits

    blocker_done = []

    def blocker():
        blocker_done.append(broker.handle_pql(CHAOS_QUERIES[0]))

    tb = threading.Thread(target=blocker)
    tb.start()
    time.sleep(0.05)  # blocker's launch is stalling inside the lane
    # ...then fail their one shared launch hard (non-retryable)
    inj.fail_next(99, retryable=False)

    payloads, errors = [], []
    lock = threading.Lock()

    def hit():
        resp = broker.handle_pql(pql)
        with lock:
            if resp.exceptions:
                errors.append(resp.exceptions)
            else:
                payloads.append(_payload(resp))

    threads = [threading.Thread(target=hit) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    tb.join(30)
    inj.heal()
    assert not errors, errors[:1]
    assert len(payloads) == 6 and set(payloads) == {want}
    assert server.lane.coalesce_hits > base_hits  # they really coalesced
    heal = server.status()["selfHealing"]
    # every waiter was answered off-device: via explicit host failover
    # or, once the plan was quarantined, the poison skip
    assert heal["hostFailovers"] + heal["poisonSkips"] >= len(payloads)
    assert heal["hostFailovers"] >= 1


@pytest.mark.chaos
def test_seeded_device_chaos_run_completes_clean(heal_stack):
    """Acceptance sweep: a seeded chaos schedule (poison + stall +
    fail_next) over the query ladder finishes with ZERO failed queries,
    every payload byte-identical to the healthy run, and every
    self-healing counter that was exercised nonzero."""
    broker, reference, inj = heal_stack
    server = broker.local_servers[0]
    want = {pql: _payload(reference.handle_pql(pql)) for pql in CHAOS_QUERIES}

    # healthy warmup (also records plan digests per query)
    digests = {}
    for pql in CHAOS_QUERIES:
        resp = broker.handle_pql(pql)
        assert not resp.exceptions
        assert _payload(resp) == want[pql]
        if inj.launches and inj.launches[-1].digest is not None:
            digests[pql] = inj.launches[-1].digest

    # chaos schedule: poison the group-by plan AND the scalar-distinct
    # plan (row-wise host fallback), stall one launch (lane restart),
    # sprinkle transient failures over the rest
    inj.poison_plan(digests[CHAOS_QUERIES[2]])
    inj.poison_plan(digests[CHAOS_QUERIES[5]])
    inj.stall_next(1, stall_s=1.0)
    failed = 0
    for round_no in range(3):
        for pql in CHAOS_QUERIES:
            resp = broker.handle_pql(pql)
            if resp.exceptions:
                failed += 1
            else:
                assert _payload(resp) == want[pql], pql
        inj.fail_next(1, retryable=True)
    assert failed == 0

    heal = server.status()["selfHealing"]
    assert heal["deviceFailures"] >= 2  # stall + fail_next + poison hits
    assert heal["hostFailovers"] >= 1
    assert heal["laneRestarts"] >= 1
    assert heal["poisonedPlans"] >= 1
    assert heal["poisonSkips"] >= 1
    assert heal["deviceRetries"] >= 1
    # the status surface exposes the full counter contract
    for key in (
        "deviceFailures", "deviceRetries", "hostFailovers", "poisonSkips",
        "poisonedPlans", "laneRestarts", "crcFailures", "quarantinedSegments",
    ):
        assert key in heal, key


# -- segment integrity -------------------------------------------------


def _write_store_segment(tmp_path, seg):
    from pinot_tpu.segment.format import write_segment

    d = tmp_path / "store" / seg.segment_name
    write_segment(seg, str(d))
    return d


def _corrupt_segment_file(path):
    """Flip bytes in the buffer region (past the JSON header) so the
    file still parses but the column data no longer matches the CRC."""
    with open(path, "r+b") as f:
        data = f.read()
        hlen = int.from_bytes(data[8:16], "little")
        pos = 16 + hlen + max(0, (len(data) - 16 - hlen) // 2)
        f.seek(pos)
        chunk = data[pos : pos + 8]
        f.write(bytes((~b) & 0xFF for b in chunk))


def test_verify_crc_on_add_rejects_corrupt_segment():
    import numpy as np

    from pinot_tpu.segment.format import SegmentIntegrityError
    from pinot_tpu.server.instance import ServerInstance

    schema = make_test_schema(with_mv=False)
    seg = build_segment(schema, random_rows(schema, 200, seed=5), TABLE, "bad0")
    col = next(iter(seg.columns.values()))
    col.fwd = np.ascontiguousarray(col.fwd[::-1])  # silent bit-rot analog
    server = ServerInstance("intsrv", pipeline=False)
    with pytest.raises(SegmentIntegrityError):
        server.add_segment(TABLE, seg, verify_crc=True)
    assert server.status()["selfHealing"]["crcFailures"] == 1
    tdm = server.data_manager.table(TABLE)
    assert tdm is None or "bad0" not in tdm.segment_names()
    server.shutdown()


def test_fetch_with_expected_crc_rejects_corrupt_copy(tmp_path):
    from pinot_tpu.segment.fetcher import DEFAULT_FACTORY
    from pinot_tpu.segment.format import SEGMENT_FILE_NAME, SegmentIntegrityError

    schema = make_test_schema(with_mv=False)
    seg = build_segment(schema, random_rows(schema, 200, seed=6), TABLE, "f0")
    d = _write_store_segment(tmp_path, seg)
    _corrupt_segment_file(d / SEGMENT_FILE_NAME)
    dest = tmp_path / "local" / SEGMENT_FILE_NAME
    with pytest.raises(SegmentIntegrityError):
        DEFAULT_FACTORY.fetch(
            "file://" + str(d), str(dest), expected_crc=seg.metadata.crc
        )
    assert not dest.exists()  # nothing corrupt ever lands at the dest
    assert not (tmp_path / "local").joinpath(SEGMENT_FILE_NAME + ".verify").exists()


@pytest.mark.chaos
def test_corrupt_local_segment_quarantined_refetched_and_serving(tmp_path):
    """Acceptance: a committed segment whose LOCAL copy rots on disk is
    quarantined at load time and re-fetched from the controller copy;
    a query answered mid-recovery carries partialResponse=true +
    numSegmentsUnserved, and serving is fully restored by the reload —
    all inside this test."""
    from pinot_tpu.broker.broker import BrokerRequestHandler
    from pinot_tpu.broker.routing import RoutingTableProvider
    from pinot_tpu.controller.resource_manager import ClusterResourceManager
    from pinot_tpu.segment import fetcher as fetcher_mod
    from pinot_tpu.segment.format import SEGMENT_FILE_NAME
    from pinot_tpu.server.instance import ServerInstance
    from pinot_tpu.server.starter import ServerStarter
    from pinot_tpu.transport.local import LocalTransport

    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 400, seed=17)
    segs = {
        "q0": build_segment(schema, rows[:200], TABLE, "q0"),
        "q1": build_segment(schema, rows[200:], TABLE, "q1"),
    }
    stores = {n: _write_store_segment(tmp_path, s) for n, s in segs.items()}

    server = ServerInstance("intsrv2", pipeline=False)
    starter = ServerStarter(
        server, ClusterResourceManager(), data_dir=str(tmp_path / "server-data")
    )
    transport = LocalTransport()
    transport.register(("intsrv2", 0), server.handle_request)
    routing = RoutingTableProvider()
    routing.update(
        TABLE, {"q0": {"intsrv2": "ONLINE"}, "q1": {"intsrv2": "ONLINE"}}
    )
    broker = BrokerRequestHandler(
        transport, {"intsrv2": ("intsrv2", 0)}, routing=routing, timeout_ms=30_000
    )

    def load(name):
        return starter._load(
            TABLE,
            name,
            {
                "metadata": segs[name].metadata,
                "downloadUri": "file://" + str(stores[name]),
            },
        )

    assert load("q0") and load("q1")
    resp = broker.handle_pql("SELECT count(*) FROM healTable")
    assert resp.num_docs_scanned == 400 and not resp.partial_response

    # rot the LOCAL copy of q1 on disk, then simulate a server restart
    # (fresh instance + starter over the same data_dir)
    local_q1 = os.path.join(str(tmp_path / "server-data"), TABLE, "q1")
    _corrupt_segment_file(os.path.join(local_q1, SEGMENT_FILE_NAME))
    server.shutdown()

    server2 = ServerInstance("intsrv2", pipeline=False)
    starter2 = ServerStarter(
        server2, ClusterResourceManager(), data_dir=str(tmp_path / "server-data")
    )
    transport.register(("intsrv2", 0), server2.handle_request)

    def load2(name):
        return starter2._load(
            TABLE,
            name,
            {
                "metadata": segs[name].metadata,
                "downloadUri": "file://" + str(stores[name]),
            },
        )

    assert load2("q0")

    # hook the re-fetch: mid-recovery (q1 quarantined, clean copy not
    # yet down) a query must serve the degraded-but-honest contract
    mid_recovery = {}
    real_fetch = fetcher_mod.DEFAULT_FACTORY.fetch

    def spying_fetch(uri, dest_path, expected_crc=None, **kwargs):
        if "q1" in uri and "mid" not in mid_recovery:
            mid_recovery["mid"] = broker.handle_pql(
                "SELECT count(*) FROM healTable"
            )
        return real_fetch(uri, dest_path, expected_crc=expected_crc, **kwargs)

    fetcher_mod.DEFAULT_FACTORY.fetch = spying_fetch
    try:
        assert load2("q1")  # quarantine -> re-fetch -> verified load
    finally:
        fetcher_mod.DEFAULT_FACTORY.fetch = real_fetch

    mid = mid_recovery["mid"]
    assert mid.partial_response is True
    assert mid.num_segments_unserved == 1
    assert mid.num_docs_scanned == 200  # q0 still answered
    assert any(e.error_code == 230 for e in mid.exceptions)

    # recovery complete: full serving restored, quarantine dir kept
    resp = broker.handle_pql("SELECT count(*) FROM healTable")
    assert resp.num_docs_scanned == 400
    assert resp.partial_response is False and not resp.exceptions
    heal = server2.status()["selfHealing"]
    assert heal["crcFailures"] >= 1
    assert heal["quarantinedSegments"] >= 1
    parent = os.path.dirname(local_q1)
    assert any(".quarantined." in n for n in os.listdir(parent))
    server2.shutdown()


def test_corrupt_source_copy_stays_unserved(tmp_path):
    """When the CONTROLLER copy itself is bad, the re-fetch round must
    not loop forever or serve corrupt data: the segment stays out of
    serving after one quarantine + failed re-fetch."""
    from pinot_tpu.controller.resource_manager import ClusterResourceManager
    from pinot_tpu.segment.format import SEGMENT_FILE_NAME
    from pinot_tpu.server.instance import ServerInstance
    from pinot_tpu.server.starter import ServerStarter

    schema = make_test_schema(with_mv=False)
    seg = build_segment(schema, random_rows(schema, 200, seed=8), TABLE, "s0")
    store = _write_store_segment(tmp_path, seg)
    _corrupt_segment_file(store / SEGMENT_FILE_NAME)

    server = ServerInstance("intsrv3", pipeline=False)
    starter = ServerStarter(
        server, ClusterResourceManager(), data_dir=str(tmp_path / "sd")
    )
    ok = starter._load(
        TABLE,
        "s0",
        {"metadata": seg.metadata, "downloadUri": "file://" + str(store)},
    )
    assert ok is False
    tdm = server.data_manager.table(TABLE)
    assert tdm is None or "s0" not in tdm.segment_names()
    heal = server.status()["selfHealing"]
    assert heal["crcFailures"] >= 1
    # the verified fetch never landed a copy, so there was nothing to
    # impound: no quarantine count for the fetch-refused incident
    assert heal["quarantinedSegments"] == 0
    server.shutdown()


def test_stale_source_copy_not_counted_as_corruption(tmp_path):
    """Replication lag: the ideal state asks for a NEWER CRC than the
    controller store currently serves.  The load must fail softly —
    unserved, retried later — with NO corruption counters and NO
    quarantine of an intact (just old) copy."""
    from pinot_tpu.controller.resource_manager import ClusterResourceManager
    from pinot_tpu.segment.format import write_segment
    from pinot_tpu.server.instance import ServerInstance
    from pinot_tpu.server.starter import ServerStarter

    schema = make_test_schema(with_mv=False)
    v1 = build_segment(schema, random_rows(schema, 100, seed=40), TABLE, "st0")
    v2 = build_segment(schema, random_rows(schema, 150, seed=41), TABLE, "st0")
    store = tmp_path / "store" / "st0"
    write_segment(v1, str(store))  # store still serves v1...

    server = ServerInstance("intsrv5", pipeline=False)
    starter = ServerStarter(
        server, ClusterResourceManager(), data_dir=str(tmp_path / "sd")
    )
    ok = starter._load(  # ...while the ideal state already names v2
        TABLE,
        "st0",
        {"metadata": v2.metadata, "downloadUri": "file://" + str(store)},
    )
    assert ok is False
    heal = server.status()["selfHealing"]
    assert heal["crcFailures"] == 0
    assert heal["quarantinedSegments"] == 0
    server.shutdown()


def test_stale_local_copy_refreshed_without_quarantine(tmp_path):
    """A segment REFRESH (ideal-state CRC moved) must not read as
    corruption: the intact old local copy is silently replaced — no
    crcFailures, no quarantine dir, new data serving."""
    from pinot_tpu.controller.resource_manager import ClusterResourceManager
    from pinot_tpu.segment.format import write_segment
    from pinot_tpu.server.instance import ServerInstance
    from pinot_tpu.server.starter import ServerStarter

    schema = make_test_schema(with_mv=False)
    v1 = build_segment(schema, random_rows(schema, 100, seed=30), TABLE, "r0")
    v2 = build_segment(schema, random_rows(schema, 150, seed=31), TABLE, "r0")
    assert v1.metadata.crc != v2.metadata.crc
    store = tmp_path / "store" / "r0"
    write_segment(v1, str(store))

    server = ServerInstance("intsrv4", pipeline=False)
    starter = ServerStarter(
        server, ClusterResourceManager(), data_dir=str(tmp_path / "sd")
    )
    info = lambda seg: {
        "metadata": seg.metadata, "downloadUri": "file://" + str(store)
    }
    assert starter._load(TABLE, "r0", info(v1))

    write_segment(v2, str(store))  # controller refreshed the segment
    assert starter._load(TABLE, "r0", info(v2))
    tdm = server.data_manager.table(TABLE)
    sdm = tdm.acquire_segments(["r0"])[0]
    try:
        assert sdm.segment.num_docs == 150  # the NEW copy serves
    finally:
        tdm.release_segments([sdm])
    heal = server.status()["selfHealing"]
    assert heal["crcFailures"] == 0
    assert heal["quarantinedSegments"] == 0
    assert not any(
        ".quarantined." in n for n in os.listdir(tmp_path / "sd" / TABLE)
    )
    server.shutdown()
