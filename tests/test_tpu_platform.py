"""On-device TPU-platform correctness gate (VERDICT r1 #6).

Run with::

    PINOT_TPU_TESTS=tpu python -m pytest tests/ -m tpu -q

All other test files run on the virtual CPU mesh in float64; this file
runs the engine on the REAL chip in its production float32 config and
asserts device results match the host oracle within accumulation
tolerance — the check that catches f32 drift at scale, which the
CPU/x64 suite cannot.
"""
import json
import os

import numpy as np
import pytest

pytestmark = pytest.mark.tpu

if os.environ.get("PINOT_TPU_TESTS") != "tpu":
    pytest.skip(
        "TPU gate runs via PINOT_TPU_TESTS=tpu pytest -m tpu", allow_module_level=True
    )

import jax

if jax.devices()[0].platform == "cpu":
    pytest.skip("no TPU device attached", allow_module_level=True)

from pinot_tpu.engine.executor import QueryExecutor
from pinot_tpu.engine.reduce import reduce_to_response
from pinot_tpu.pql import optimize_request, parse_pql
from pinot_tpu.tools.datagen import lineitem_schema, synthetic_lineitem_segment
from pinot_tpu.tools.scan_engine import ScanQueryProcessor

ROWS_PER_SEGMENT = int(os.environ.get("PINOT_TPU_GATE_ROWS", "250000"))
NUM_SEGMENTS = 3
RTOL = 1e-4  # f32 pairwise-tree accumulation over ~1M rows


@pytest.fixture(scope="module")
def cluster():
    segs = [
        synthetic_lineitem_segment(ROWS_PER_SEGMENT, seed=41 + i, name=f"tli{i}")
        for i in range(NUM_SEGMENTS)
    ]
    rows = [r for s in segs for r in s.rows()]
    oracle = ScanQueryProcessor(lineitem_schema(), rows)
    return segs, oracle


QUERIES = [
    "SELECT count(*) FROM lineitem",
    "SELECT sum(l_quantity), sum(l_extendedprice), min(l_discount), max(l_tax), avg(l_quantity) FROM lineitem",
    "SELECT sum(l_quantity), count(*) FROM lineitem WHERE l_shipdate <= '1998-09-02' GROUP BY l_returnflag, l_linestatus TOP 10",
    "SELECT sum(l_extendedprice) FROM lineitem WHERE l_shipmode IN ('RAIL','FOB') GROUP BY l_shipmode TOP 10",
    "SELECT count(*) FROM lineitem WHERE l_shipdate BETWEEN '1994-01-01' AND '1994-06-30'",
    "SELECT distinctcount(l_shipmode), percentile50(l_quantity) FROM lineitem",
    "SELECT distinctcounthll(l_shipdate) FROM lineitem",
    "SELECT minmaxrange(l_extendedprice) FROM lineitem GROUP BY l_returnflag TOP 10",
    # selective point query: exercises the zone-map block path on-device
    "SELECT sum(l_extendedprice), count(*) FROM lineitem WHERE l_shipdate = '1995-06-14'",
]


def _close(a, b, rtol):
    if isinstance(a, dict) and isinstance(b, dict):
        return set(a) == set(b) and all(_close(a[k], b[k], rtol) for k in a)
    if isinstance(a, list) and isinstance(b, list):
        return len(a) == len(b) and all(_close(x, y, rtol) for x, y in zip(a, b))
    if isinstance(a, str) and isinstance(b, str):
        try:
            fa, fb = float(a), float(b)
        except ValueError:
            return a == b
        return abs(fa - fb) <= rtol * max(1.0, abs(fa), abs(fb))
    return a == b


@pytest.mark.parametrize("pql", QUERIES)
def test_device_matches_oracle_f32(cluster, pql):
    segs, oracle = cluster
    req = optimize_request(parse_pql(pql))
    req2 = optimize_request(parse_pql(pql))
    got = reduce_to_response(req, [QueryExecutor().execute(segs, req)]).to_json()
    want = oracle.execute(req2).to_json()
    # HLL is an estimator: identical registers either way, compare exact
    rtol = RTOL
    assert _close(got["aggregationResults"], want["aggregationResults"], rtol), (
        pql,
        json.dumps(got["aggregationResults"], default=str)[:500],
        json.dumps(want["aggregationResults"], default=str)[:500],
    )


def test_single_chip_mesh_shard_map(cluster):
    """The shard_map collective path on the real chip (mesh size 1 —
    the degenerate but on-device case of the multichip program)."""
    from pinot_tpu.parallel.multichip import default_mesh

    segs, oracle = cluster
    mesh = default_mesh(jax.devices()[:1])
    pql = "SELECT sum(l_quantity) FROM lineitem GROUP BY l_returnflag TOP 10"
    req = optimize_request(parse_pql(pql))
    req2 = optimize_request(parse_pql(pql))
    got = reduce_to_response(req, [QueryExecutor(mesh=mesh).execute(segs, req)]).to_json()
    want = oracle.execute(req2).to_json()
    assert _close(got["aggregationResults"], want["aggregationResults"], RTOL)


def test_selection_order_by_on_device(cluster):
    segs, oracle = cluster
    pql = "SELECT l_shipdate, l_quantity FROM lineitem ORDER BY l_quantity DESC, l_shipdate LIMIT 10"
    req = optimize_request(parse_pql(pql))
    req2 = optimize_request(parse_pql(pql))
    got = reduce_to_response(req, [QueryExecutor().execute(segs, req)]).to_json()
    want = oracle.execute(req2).to_json()
    assert got["selectionResults"] == want["selectionResults"]


def test_sum_accumulation_at_bench_scale():
    """f32 accumulation drift at the north-star scale (VERDICT r2 #6):
    SUM/AVG and the group-by matmul SUM over >=100M rows vs an EXACT
    f64 oracle computed from dictionary bincounts (sum = sum_d count_d
    * value_d — no row scan, so the oracle itself carries no float
    error).  The reference aggregates in double everywhere
    (DoubleAggregationResultHolder); rtol here states how close the
    f32 device path gets at scale."""
    rows_per = int(os.environ.get("PINOT_TPU_SCALE_ROWS", str(8_388_608)))
    nseg = int(os.environ.get("PINOT_TPU_SCALE_SEGMENTS", "16"))
    RTOL_SCALE = 1e-5

    segs = [
        synthetic_lineitem_segment(rows_per, seed=61 + i, name=f"sc{i}")
        for i in range(nseg)
    ]
    # exact per-returnflag and total sums of l_extendedprice in f64
    total_sum = 0.0
    total_cnt = 0
    group_sums: dict = {}
    for s in segs:
        price = s.column("l_extendedprice")
        rf = s.column("l_returnflag")
        vals = np.asarray(price.dictionary.values, dtype=np.float64)
        card = price.dictionary.cardinality
        combined = rf.fwd.astype(np.int64) * card + price.fwd
        counts = np.bincount(
            combined, minlength=rf.dictionary.cardinality * card
        ).reshape(rf.dictionary.cardinality, card)
        per_rf = counts @ vals
        for local_id in range(rf.dictionary.cardinality):
            key = str(rf.dictionary.get(local_id))
            group_sums[key] = group_sums.get(key, 0.0) + float(per_rf[local_id])
        total_sum += float(per_rf.sum())
        total_cnt += s.num_docs
    assert total_cnt == rows_per * nseg

    ex = QueryExecutor()
    req = optimize_request(
        parse_pql(
            "SELECT sum(l_extendedprice), avg(l_extendedprice), count(*) FROM lineitem"
        )
    )
    got = reduce_to_response(req, [ex.execute(segs, req)]).to_json()
    g = got["aggregationResults"]
    # count rides the same f32 accumulation: exact only while partial
    # sums stay under 2^24, tolerance-bound like the sums otherwise
    assert abs(float(g[2]["value"]) - total_cnt) <= RTOL_SCALE * total_cnt
    gsum, gavg = float(g[0]["value"]), float(g[1]["value"])
    assert abs(gsum - total_sum) <= RTOL_SCALE * abs(total_sum), (
        "scalar SUM drift", gsum, total_sum, abs(gsum - total_sum) / abs(total_sum),
    )
    want_avg = total_sum / total_cnt
    assert abs(gavg - want_avg) <= RTOL_SCALE * abs(want_avg)

    # group-by path: the one-hot MATMUL accumulation (MXU) at scale
    req2 = optimize_request(
        parse_pql(
            "SELECT sum(l_extendedprice) FROM lineitem GROUP BY l_returnflag TOP 10"
        )
    )
    got2 = reduce_to_response(req2, [ex.execute(segs, req2)]).to_json()
    rows = got2["aggregationResults"][0]["groupByResult"]
    assert len(rows) == len(group_sums)
    for row in rows:
        key = row["group"][0]
        want = group_sums[key]
        have = float(row["value"])
        assert abs(have - want) <= RTOL_SCALE * abs(want), (
            "group SUM drift", key, have, want, abs(have - want) / abs(want),
        )


def test_sort_pairs_distinct_on_device(cluster, monkeypatch):
    """High-cardinality exact distinct/percentile through the on-chip
    sort-dedup path (pair lexsort + stable compaction on the REAL
    chip's sort implementation); distinct counts are exact integers, so
    no float tolerance applies."""
    from pinot_tpu.engine import config as cfg
    from pinot_tpu.engine import kernel as kernel_mod

    segs, oracle = cluster
    monkeypatch.setattr(cfg, "MAX_VALUE_STATE", 1 << 10)
    monkeypatch.setenv("PINOT_TPU_INVINDEX", "0")
    kernel_mod.make_table_kernel.cache_clear()
    kernel_mod.make_packed_table_kernel.cache_clear()
    try:
        for pql in (
            "SELECT distinctcount(l_extendedprice) FROM lineitem GROUP BY l_returnflag TOP 10",
            "SELECT percentile50(l_extendedprice) FROM lineitem",
        ):
            req = optimize_request(parse_pql(pql))
            req2 = optimize_request(parse_pql(pql))
            got = reduce_to_response(req, [QueryExecutor().execute(segs, req)]).to_json()
            want = oracle.execute(req2).to_json()
            assert _close(got["aggregationResults"], want["aggregationResults"], RTOL), (
                pql,
                json.dumps(got["aggregationResults"], default=str)[:400],
                json.dumps(want["aggregationResults"], default=str)[:400],
            )
    finally:
        kernel_mod.make_table_kernel.cache_clear()
        kernel_mod.make_packed_table_kernel.cache_clear()


def test_repeated_query_uses_input_cache_on_device(cluster):
    """A repeated identical query reuses device-resident inputs (the
    q-input LRU) and MUST return bit-identical results — validates the
    cache keying on the real chip where the upload it skips is a full
    tunnel round trip."""
    segs, _ = cluster
    ex = QueryExecutor()
    pql = (
        "SELECT sum(l_quantity), count(*) FROM lineitem "
        "WHERE l_shipdate <= '1998-09-02' GROUP BY l_returnflag TOP 10"
    )
    req = optimize_request(parse_pql(pql))
    first = reduce_to_response(req, [ex.execute(segs, req)]).to_json()
    assert len(ex._qinput_cache) >= 1  # populated by the first run
    second = reduce_to_response(req, [ex.execute(segs, req)]).to_json()
    assert first["aggregationResults"] == second["aggregationResults"]
    # a DIFFERENT literal must miss the cache and answer differently
    req3 = optimize_request(parse_pql(pql.replace("1998-09-02", "1994-01-01")))
    third = reduce_to_response(req3, [ex.execute(segs, req3)]).to_json()
    assert third["aggregationResults"] != first["aggregationResults"]
