"""On-device TPU-platform correctness gate (VERDICT r1 #6).

Run with::

    PINOT_TPU_TESTS=tpu python -m pytest tests/ -m tpu -q

All other test files run on the virtual CPU mesh in float64; this file
runs the engine on the REAL chip in its production float32 config and
asserts device results match the host oracle within accumulation
tolerance — the check that catches f32 drift at scale, which the
CPU/x64 suite cannot.
"""
import json
import os

import numpy as np
import pytest

pytestmark = pytest.mark.tpu

if os.environ.get("PINOT_TPU_TESTS") != "tpu":
    pytest.skip(
        "TPU gate runs via PINOT_TPU_TESTS=tpu pytest -m tpu", allow_module_level=True
    )

import jax

if jax.devices()[0].platform == "cpu":
    pytest.skip("no TPU device attached", allow_module_level=True)

from pinot_tpu.engine.executor import QueryExecutor
from pinot_tpu.engine.reduce import reduce_to_response
from pinot_tpu.pql import optimize_request, parse_pql
from pinot_tpu.tools.datagen import lineitem_schema, synthetic_lineitem_segment
from pinot_tpu.tools.scan_engine import ScanQueryProcessor

ROWS_PER_SEGMENT = int(os.environ.get("PINOT_TPU_GATE_ROWS", "250000"))
NUM_SEGMENTS = 3
RTOL = 1e-4  # f32 pairwise-tree accumulation over ~1M rows


@pytest.fixture(scope="module")
def cluster():
    segs = [
        synthetic_lineitem_segment(ROWS_PER_SEGMENT, seed=41 + i, name=f"tli{i}")
        for i in range(NUM_SEGMENTS)
    ]
    rows = [r for s in segs for r in s.rows()]
    oracle = ScanQueryProcessor(lineitem_schema(), rows)
    return segs, oracle


QUERIES = [
    "SELECT count(*) FROM lineitem",
    "SELECT sum(l_quantity), sum(l_extendedprice), min(l_discount), max(l_tax), avg(l_quantity) FROM lineitem",
    "SELECT sum(l_quantity), count(*) FROM lineitem WHERE l_shipdate <= '1998-09-02' GROUP BY l_returnflag, l_linestatus TOP 10",
    "SELECT sum(l_extendedprice) FROM lineitem WHERE l_shipmode IN ('RAIL','FOB') GROUP BY l_shipmode TOP 10",
    "SELECT count(*) FROM lineitem WHERE l_shipdate BETWEEN '1994-01-01' AND '1994-06-30'",
    "SELECT distinctcount(l_shipmode), percentile50(l_quantity) FROM lineitem",
    "SELECT distinctcounthll(l_shipdate) FROM lineitem",
    "SELECT minmaxrange(l_extendedprice) FROM lineitem GROUP BY l_returnflag TOP 10",
    # selective point query: exercises the zone-map block path on-device
    "SELECT sum(l_extendedprice), count(*) FROM lineitem WHERE l_shipdate = '1995-06-14'",
]


def _close(a, b, rtol):
    if isinstance(a, dict) and isinstance(b, dict):
        return set(a) == set(b) and all(_close(a[k], b[k], rtol) for k in a)
    if isinstance(a, list) and isinstance(b, list):
        return len(a) == len(b) and all(_close(x, y, rtol) for x, y in zip(a, b))
    if isinstance(a, str) and isinstance(b, str):
        try:
            fa, fb = float(a), float(b)
        except ValueError:
            return a == b
        return abs(fa - fb) <= rtol * max(1.0, abs(fa), abs(fb))
    return a == b


@pytest.mark.parametrize("pql", QUERIES)
def test_device_matches_oracle_f32(cluster, pql):
    segs, oracle = cluster
    req = optimize_request(parse_pql(pql))
    req2 = optimize_request(parse_pql(pql))
    got = reduce_to_response(req, [QueryExecutor().execute(segs, req)]).to_json()
    want = oracle.execute(req2).to_json()
    # HLL is an estimator: identical registers either way, compare exact
    rtol = RTOL
    assert _close(got["aggregationResults"], want["aggregationResults"], rtol), (
        pql,
        json.dumps(got["aggregationResults"], default=str)[:500],
        json.dumps(want["aggregationResults"], default=str)[:500],
    )


def test_single_chip_mesh_shard_map(cluster):
    """The shard_map collective path on the real chip (mesh size 1 —
    the degenerate but on-device case of the multichip program)."""
    from pinot_tpu.parallel.multichip import default_mesh

    segs, oracle = cluster
    mesh = default_mesh(jax.devices()[:1])
    pql = "SELECT sum(l_quantity) FROM lineitem GROUP BY l_returnflag TOP 10"
    req = optimize_request(parse_pql(pql))
    req2 = optimize_request(parse_pql(pql))
    got = reduce_to_response(req, [QueryExecutor(mesh=mesh).execute(segs, req)]).to_json()
    want = oracle.execute(req2).to_json()
    assert _close(got["aggregationResults"], want["aggregationResults"], RTOL)


def test_selection_order_by_on_device(cluster):
    segs, oracle = cluster
    pql = "SELECT l_shipdate, l_quantity FROM lineitem ORDER BY l_quantity DESC, l_shipdate LIMIT 10"
    req = optimize_request(parse_pql(pql))
    req2 = optimize_request(parse_pql(pql))
    got = reduce_to_response(req, [QueryExecutor().execute(segs, req)]).to_json()
    want = oracle.execute(req2).to_json()
    assert got["selectionResults"] == want["selectionResults"]
