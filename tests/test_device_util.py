"""Device utilization & profiling plane (PR 10): per-plan roofline
accounting, lane occupancy, transfer counters, and the on-demand
profiler bracket.

Tier-1 guards: the lane launch path performs ZERO occupancy-related
allocations while no sampler runs (the PR 4 zero-alloc trace-guard
analog), the static XLA cost analysis degrades to None — never an
exception — on backends that report nothing, /debug/plans' roofline is
computed from the SAME wall time the phase timers report, occupancy
reads 0 on an idle lane, the profiler endpoint honors ref-count +
auto-stop semantics, and the controller /debug/utilization rollup
equals the per-server snapshots it fetched."""
import itertools
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pinot_tpu.segment.builder import build_segment
from pinot_tpu.tools.cluster_harness import InProcessCluster, single_server_broker
from pinot_tpu.tools.datagen import make_test_schema, random_rows

# unique segment names per fixture instantiation: the HBM ledger and
# staging cache are process-global and key by segment name
_SEQ = itertools.count()


def _mk_broker(pipeline=True, rows_n=1200, table="utilTable"):
    n = next(_SEQ)
    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, rows_n, seed=23)
    half = rows_n // 2
    segs = [
        build_segment(schema, rows[:half], table, f"du{n}a"),
        build_segment(schema, rows[half:], table, f"du{n}b"),
    ]
    return single_server_broker(table, segs, pipeline=pipeline)


@pytest.fixture()
def util_broker():
    broker = _mk_broker()
    yield broker
    broker.local_servers[0].shutdown()


# ------------------------------------------------------ transfer stats
def test_transfer_stats_accumulate_and_ignore_nonpositive():
    from pinot_tpu.engine.device import TransferStats

    ts = TransferStats()
    ts.record_h2d(100)
    ts.record_h2d(0)
    ts.record_h2d(-5)
    ts.record_d2h(40)
    snap = ts.snapshot()
    # process identity rides every snapshot so fleet rollups can dedupe
    # co-resident servers' shared counters
    assert isinstance(snap.pop("processToken"), str)
    assert snap == {
        "h2dBytes": 100,
        "h2dTransfers": 1,
        "d2hBytes": 40,
        "d2hTransfers": 1,
    }


def test_device_query_counts_d2h_transfer_bytes(util_broker):
    from pinot_tpu.engine.device import TRANSFERS

    before = TRANSFERS.snapshot()
    resp = util_broker.handle_pql("SELECT sum(metInt) FROM utilTable")
    assert not resp.exceptions
    after = TRANSFERS.snapshot()
    # the packed result fetch is a real D2H transfer
    assert after["d2hBytes"] > before["d2hBytes"]
    assert after["d2hTransfers"] > before["d2hTransfers"]


# --------------------------------------------------- static cost analysis
def test_normalize_cost_analysis_none_and_partial():
    """The CPU-backend contract: None / empty / partial / list-shaped
    analysis outputs all degrade gracefully, never raise."""
    from pinot_tpu.engine.packing import _normalize_cost_analysis as norm

    assert norm(None) is None
    assert norm({}) is None
    assert norm([]) is None
    assert norm("nope") is None
    assert norm({"utilization": 0.5}) is None  # no usable keys
    # partial dict: flops without bytes (and vice versa) both survive
    assert norm({"flops": 10.0}) == {"flops": 10.0}
    assert norm({"bytes accessed": 64}) == {"bytesAccessed": 64.0}
    # older backends wrap the dict in a list
    assert norm([{"flops": 3, "bytes accessed": 9}]) == {
        "flops": 3.0,
        "bytesAccessed": 9.0,
    }
    # negative / junk values are dropped, not propagated
    assert norm({"flops": -1, "bytes accessed": "junk"}) is None


def test_kernel_cost_analysis_graceful_fallbacks(monkeypatch):
    from pinot_tpu.engine.packing import kernel_cost_analysis

    # no .lower on the kernel: nothing to analyze
    assert kernel_cost_analysis(lambda x: x, (1,)) is None

    # a lower() that raises degrades to None, never an exception
    class _Boom:
        def lower(self, *a):
            raise RuntimeError("no AOT path")

    assert kernel_cost_analysis(_Boom(), (1,)) is None

    # explicit opt-out
    monkeypatch.setenv("PINOT_TPU_COST_ANALYSIS", "off")
    import jax

    k = jax.jit(lambda x: x * 2.0)
    assert kernel_cost_analysis(k, (np.ones(8),)) is None
    monkeypatch.delenv("PINOT_TPU_COST_ANALYSIS")

    # the real CPU path: either a usable dict or the explicit None
    out = kernel_cost_analysis(k, (np.ones(8),))
    if out is not None:
        assert out["source"] in ("lowered", "compiled")
        assert set(out) <= {"flops", "bytesAccessed", "peakMemoryBytes", "source"}


def test_explain_compile_block_carries_cost_analysis(util_broker):
    """Acceptance: EXPLAIN's compile block carries static flops/bytes
    once the async analysis lands, or the explicit 'unavailable' —
    never a silent absence."""
    broker = util_broker
    server = broker.local_servers[0]
    pql = "SELECT sum(metInt) FROM utilTable WHERE dimInt > 40"

    cold = broker.handle_pql("EXPLAIN " + pql)
    dev = cold.explain["servers"][0]["device"]
    assert dev["compile"]["state"] == "cold"
    assert dev["compile"]["costAnalysis"] == "unavailable"

    assert not broker.handle_pql(pql).exceptions
    digest = dev["planDigest"]
    deadline = time.time() + 15
    while time.time() < deadline:
        ci = server.lane.compile_info(digest)
        assert ci is not None
        if "costAnalysis" in ci:
            break
        time.sleep(0.05)
    warm = broker.handle_pql("EXPLAIN " + pql)
    ca = warm.explain["servers"][0]["device"]["compile"]["costAnalysis"]
    # the tri-state contract: a dict with the static estimates, or the
    # explicit string states — "pending" only while the helper runs
    if isinstance(ca, dict):
        assert ("flops" in ca) or ("bytesAccessed" in ca)
    else:
        assert ca in ("unavailable", "pending")


# ----------------------------------------------------------- occupancy
def test_occupancy_idle_reads_zero_then_busy_positive(util_broker):
    broker = util_broker
    server = broker.local_servers[0]
    # idle lane, fresh gauge window: both gauges read 0
    gauges = server.metrics.snapshot()["gauges"]
    assert gauges["device.util.busyFraction"] == 0.0
    assert gauges["device.util.avgQueueDepth"] == 0.0

    for _ in range(3):
        assert not broker.handle_pql(
            "SELECT sum(metInt) FROM utilTable WHERE dimInt > 10"
        ).exceptions
    # a fresh reader's first window spans lane construction -> now and
    # must see the launches that just happened
    occ = server.lane.occupancy_read("test-busy")
    assert occ["busyFraction"] > 0.0
    assert 0.0 <= occ["busyFraction"] <= 1.0
    assert occ["depth"] == 0 and occ["inflight"] == 0
    # same reader, idle interval: the next window reads 0 again
    time.sleep(0.05)
    assert server.lane.occupancy_read("test-busy")["busyFraction"] == 0.0


def test_occupancy_zero_allocations_without_sampler(util_broker):
    """Zero-overhead contract (the PR 4 SPAN_ALLOCATIONS analog): with
    no sampler running, serving queries performs no occupancy-related
    allocations on the launch path."""
    import pinot_tpu.engine.dispatch as dispatch_mod

    broker = util_broker
    broker.handle_pql("SELECT count(*) FROM utilTable")  # warm
    before = dispatch_mod.OCCUPANCY_ALLOCATIONS
    for _ in range(5):
        assert not broker.handle_pql("SELECT count(*) FROM utilTable").exceptions
    assert dispatch_mod.OCCUPANCY_ALLOCATIONS == before, (
        "occupancy sampling allocated during serving with no sampler running"
    )


def test_serial_server_has_no_lane_occupancy():
    broker = _mk_broker(pipeline=False, rows_n=600)
    server = broker.local_servers[0]
    try:
        assert server.lane is None and server.occupancy_sampler is None
        gauges = server.metrics.snapshot()["gauges"]
        assert gauges["device.util.busyFraction"] == 0
        dev = server.device_utilization()
        assert dev["occupancy"] is None and "sampler" not in dev
    finally:
        server.shutdown()


def test_occupancy_sampler_lifecycle(util_broker):
    """start/stop idempotency + ring accumulation; the conftest
    thread-leak guard proves the sampler thread dies with the lane."""
    from pinot_tpu.engine.dispatch import OccupancySampler

    server = util_broker.local_servers[0]
    sampler = OccupancySampler(server.lane, interval_s=0.03)
    assert not sampler.running
    sampler.stop()  # stop before start: no-op
    sampler.start()
    sampler.start()  # idempotent join
    assert sampler.running
    deadline = time.time() + 5
    while sampler.samples_taken < 3 and time.time() < deadline:
        time.sleep(0.02)
    sampler.stop()
    assert not sampler.running
    taken = sampler.samples_taken
    assert taken >= 3
    snap = sampler.snapshot()
    assert snap["samplesTaken"] == taken and not snap["running"]
    for s in snap["samples"]:
        assert {"ts", "busyFraction", "avgQueueDepth", "depth"} == set(s)
        assert s["busyFraction"] == 0.0  # idle lane throughout
    time.sleep(0.1)
    assert sampler.samples_taken == taken  # really stopped
    # restart works after a stop
    sampler.start()
    assert sampler.running
    sampler.stop()


def test_occupancy_sampler_refuses_closed_lane():
    from pinot_tpu.engine.dispatch import OccupancySampler

    broker = _mk_broker(rows_n=400)
    server = broker.local_servers[0]
    sampler = OccupancySampler(server.lane, interval_s=0.03)
    server.shutdown()
    sampler.start()  # closed lane: must not spin up a thread
    assert not sampler.running


# ------------------------------------------------------------ profiler
class _FakeTrace:
    def __init__(self, fail_start=False):
        self.starts = []
        self.stops = 0
        self.fail_start = fail_start

    def start(self, d):
        if self.fail_start:
            raise RuntimeError("backend says no")
        self.starts.append(d)

    def stop(self):
        self.stops += 1

    @property
    def api(self):
        return (self.start, self.stop)


def test_profiler_refcount_shares_one_capture(tmp_path):
    from pinot_tpu.server.profiler import DeviceProfiler

    fake = _FakeTrace()
    prof = DeviceProfiler(base_dir=str(tmp_path), trace_api=fake.api)
    s1 = prof.start()
    s2 = prof.start()  # joins: jax allows ONE active trace per process
    assert len(fake.starts) == 1
    assert s1["active"] and s2["refCount"] == 2
    assert s2["dir"] == s1["dir"]
    mid = prof.stop()
    assert mid["active"] and mid["refCount"] == 1 and fake.stops == 0
    done = prof.stop()
    assert not done["active"] and fake.stops == 1
    # idempotent stop on an inactive profiler (retry after timeout)
    again = prof.stop()
    assert not again["active"] and again["refCount"] == 0 and fake.stops == 1
    # a fresh capture starts cleanly afterwards
    prof.start()
    assert len(fake.starts) == 2
    prof.shutdown()
    assert fake.stops == 2


def test_profiler_auto_stop_force_stops_despite_refcount(tmp_path):
    from pinot_tpu.server.profiler import DeviceProfiler

    fake = _FakeTrace()
    prof = DeviceProfiler(base_dir=str(tmp_path), trace_api=fake.api)
    prof.start(timeout_s=0.15)
    prof.start(timeout_s=0.15)  # refcount 2: auto-stop must still fire
    deadline = time.time() + 5
    while prof.snapshot()["active"] and time.time() < deadline:
        time.sleep(0.02)
    snap = prof.snapshot()
    assert not snap["active"] and snap["refCount"] == 0
    assert snap["autoStops"] == 1 and fake.stops == 1


def test_profiler_bounded_captures_and_unavailable(tmp_path):
    from pinot_tpu.server.profiler import (
        DeviceProfiler,
        ProfilerUnavailableError,
    )

    fake = _FakeTrace()
    prof = DeviceProfiler(
        base_dir=str(tmp_path), trace_api=fake.api, max_captures=2
    )
    for _ in range(4):
        prof.start()
        prof.stop()
    assert len(prof.snapshot()["captures"]) <= 2  # oldest pruned

    broken = DeviceProfiler(
        base_dir=str(tmp_path / "b"), trace_api=_FakeTrace(fail_start=True).api
    )
    with pytest.raises(ProfilerUnavailableError):
        broken.start()
    # the failed start left no active capture behind
    assert not broken.snapshot()["active"]


def test_profiler_endpoints_and_sampler_bracket(util_broker, tmp_path):
    """POST /debug/profile/start|stop semantics over the admin surface:
    200 start/stop with the occupancy sampler bracketed to the capture,
    and the typed 404 when the backend has no profiler."""
    from pinot_tpu.server.network_starter import ServerAdminHttpServer
    from pinot_tpu.server.profiler import DeviceProfiler

    server = util_broker.local_servers[0]
    fake = _FakeTrace()
    server.profiler = DeviceProfiler(base_dir=str(tmp_path), trace_api=fake.api)
    server.profiler.on_capture_end = server.occupancy_sampler.stop
    admin = ServerAdminHttpServer(server)
    admin.start()

    def post(path, body=b"{}"):
        req = urllib.request.Request(
            admin.url + path, data=body, method="POST"
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    try:
        code, snap = post("/debug/profile/start")
        assert code == 200 and snap["active"] and snap["refCount"] == 1
        assert server.occupancy_sampler.running  # bracketed capture
        with urllib.request.urlopen(
            admin.url + "/debug/profile", timeout=10
        ) as r:
            assert json.loads(r.read())["active"]
        # /debug/device reports the live profiler + sampler state
        with urllib.request.urlopen(
            admin.url + "/debug/device", timeout=10
        ) as r:
            dev = json.loads(r.read())
        assert dev["profiler"]["active"] and dev["sampler"]["running"]

        code, snap = post("/debug/profile/stop")
        assert code == 200 and not snap["active"]
        deadline = time.time() + 5
        while server.occupancy_sampler.running and time.time() < deadline:
            time.sleep(0.02)
        assert not server.occupancy_sampler.running  # parked with capture

        # bad JSON body is a 400, not a stack trace
        code, err = post("/debug/profile/start", body=b"{nope")
        assert code == 400

        # no usable profiler backend: typed 404
        server.profiler._trace_api = _FakeTrace(fail_start=True).api
        code, err = post("/debug/profile/start")
        assert code == 404
        assert err["errorType"] == "ProfilerUnavailableError"
    finally:
        admin.stop()


# ----------------------------------------------------- platform peaks
def test_platform_peaks_unknown_cpu_and_env_override(monkeypatch):
    from pinot_tpu.utils.platform import platform_peaks

    out = platform_peaks(refresh=True)
    # CPU test mesh: no declared peak — the roofline must say
    # "unavailable", not invent a number
    assert out["peakFlopsPerSec"] is None and out["peakBytesPerSec"] is None
    assert out["platform"] == "cpu"

    monkeypatch.setenv("PINOT_TPU_PEAK_FLOPS", "2e12")
    monkeypatch.setenv("PINOT_TPU_PEAK_HBM_BPS", "8e11")
    env_out = platform_peaks(refresh=True)
    assert env_out["source"] == "env"
    assert env_out["peakFlopsPerSec"] == 2e12
    assert env_out["peakBytesPerSec"] == 8e11

    # junk overrides must not break metric scrapes
    monkeypatch.setenv("PINOT_TPU_PEAK_FLOPS", "banana")
    junk = platform_peaks(refresh=True)
    assert junk["peakFlopsPerSec"] != "banana"
    monkeypatch.delenv("PINOT_TPU_PEAK_FLOPS")
    monkeypatch.delenv("PINOT_TPU_PEAK_HBM_BPS")
    platform_peaks(refresh=True)  # restore the cached no-env state


# ------------------------------------------------- roofline consistency
def test_plan_roofline_consistent_with_phase_timers(util_broker):
    """Acceptance: /debug/plans' roofline entry is computed from the
    SAME wall time the phase timers / cost vector report — achieved
    bytes/s == deviceBytes / sum(per-response deviceMs) exactly."""
    broker = util_broker
    server = broker.local_servers[0]
    pql = "SELECT sum(metInt) FROM utilTable WHERE dimInt > 20"
    want_ms = 0.0
    want_bytes = 0
    for _ in range(4):
        resp = broker.handle_pql(pql)
        assert not resp.exceptions
        want_ms += float(resp.cost["deviceMs"])
        want_bytes += int(resp.cost["deviceBytes"])
    assert want_ms > 0 and want_bytes > 0

    snap = server.plan_stats.snapshot(top=10)
    [plan] = [p for p in snap["plans"] if p["count"] == 4]
    roof = plan["roofline"]
    assert roof["deviceMs"] == pytest.approx(want_ms, abs=0.01)
    assert roof["deviceBytes"] == want_bytes
    assert roof["achievedBytesPerSec"] == pytest.approx(
        want_bytes * 1000.0 / roof["deviceMs"], rel=1e-6
    )
    # CPU mesh declares no peak: explicit None, not a fake fraction
    assert roof["rooflineFraction"] is None
    # the per-tier latency window matches the execution count
    assert plan["tierLatencyMs"]["device"]["samples"] == 4
    assert plan["tierLatencyMs"]["host"]["samples"] == 0
    # and the server-wide recent window saw the same traffic
    recent = server.device_utilization()["recent"]
    assert recent["queries"] >= 4
    assert recent["deviceBytes"] >= want_bytes


def test_roofline_fractions_against_declared_peaks(monkeypatch, util_broker):
    """With peaks declared (env escape hatch), the roofline fraction is
    the best-utilized resource's achieved/peak ratio."""
    monkeypatch.setenv("PINOT_TPU_PEAK_FLOPS", "1e15")
    monkeypatch.setenv("PINOT_TPU_PEAK_HBM_BPS", "1e12")
    broker = util_broker
    server = broker.local_servers[0]
    for _ in range(2):
        assert not broker.handle_pql(
            "SELECT max(metFloat) FROM utilTable WHERE dimInt > 30"
        ).exceptions
    [plan] = server.plan_stats.snapshot(top=10)["plans"]
    roof = plan["roofline"]
    assert roof["bandwidthFraction"] == pytest.approx(
        roof["achievedBytesPerSec"] / 1e12, abs=1e-6
    )
    fractions = [roof["bandwidthFraction"]]
    if "flopsFraction" in roof:
        fractions.append(roof["flopsFraction"])
    assert roof["rooflineFraction"] == pytest.approx(max(fractions), abs=1e-6)
    recent = server.device_utilization()["recent"]
    assert recent["rooflineFraction"] is not None


def test_host_path_latency_attributed_per_digest(util_broker):
    """The host tier records per-digest execution time too — a mixed
    workload's /debug/plans carries comparable latency on BOTH tiers."""
    broker = util_broker
    server = broker.local_servers[0]
    # postings path serves host-side; the range scan serves on device
    host_pql = "SELECT avg(metFloat) FROM utilTable WHERE dimStr = 'a'"
    dev_pql = "SELECT sum(metInt) FROM utilTable WHERE dimInt > 40"
    for _ in range(2):
        assert not broker.handle_pql(host_pql).exceptions
        assert not broker.handle_pql(dev_pql).exceptions
    by_summary = {
        p["summary"]: p for p in server.plan_stats.snapshot(top=10)["plans"]
    }
    host_plan = next(
        p for s, p in by_summary.items() if "dimStr:EQUALITY" in s
    )
    dev_plan = next(p for s, p in by_summary.items() if "dimInt:RANGE" in s)
    assert host_plan["tierLatencyMs"]["host"]["samples"] == 2
    assert host_plan["tierLatencyMs"]["host"]["p95Ms"] > 0
    assert host_plan["tierLatencyMs"]["device"]["samples"] == 0
    assert host_plan["roofline"] is None  # never ran on device
    assert dev_plan["tierLatencyMs"]["device"]["samples"] == 2
    assert dev_plan["tierLatencyMs"]["host"]["samples"] == 0
    assert dev_plan["roofline"] is not None


def test_status_device_section(util_broker):
    server = util_broker.local_servers[0]
    dev = util_broker.local_servers[0].status()["device"]
    assert {"platform", "occupancy", "transfers", "recent", "profiler"} <= set(
        dev
    )
    assert dev["occupancy"]["busyFraction"] >= 0.0
    assert not dev["profiler"]["active"]
    # the device.util.* series are pre-registered at construction
    gauges = server.metrics.snapshot()["gauges"]
    for name in (
        "device.util.busyFraction",
        "device.util.avgQueueDepth",
        "device.util.h2dBytes",
        "device.util.d2hBytes",
        "device.util.achievedBytesPerSec",
        "device.util.achievedFlopsPerSec",
        "device.util.rooflineFraction",
        "profile.active",
    ):
        assert name in gauges, name


# ------------------------------------------------- controller rollup
def test_controller_utilization_rollup_and_dashboard(tmp_path):
    """Acceptance: /debug/utilization's totals equal the per-server
    snapshots it includes verbatim; unreachable servers degrade to a
    named entry; the dashboard page renders the rollup."""
    from pinot_tpu.controller.controller import (
        ControllerHttpServer,
        collect_utilization,
    )
    from pinot_tpu.controller.resource_manager import InstanceState
    from pinot_tpu.server.network_starter import ServerAdminHttpServer

    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path), http=True)
    admin = None
    http = None
    try:
        schema = make_test_schema(with_mv=False)
        physical = cluster.add_offline_table(schema)
        rows = random_rows(schema, 800, seed=31)
        cluster.upload(
            physical, build_segment(schema, rows, physical, "util0")
        )
        for _ in range(3):
            assert not cluster.query(
                "SELECT sum(metInt) FROM testTable WHERE dimInt > 5"
            ).exceptions

        admin = ServerAdminHttpServer(cluster.servers[0])
        admin.start()
        cluster.controller.resources.instances["server0"].url = admin.url
        # a registered-but-dead admin surface must degrade, not fail
        cluster.controller.resources.register_instance(
            InstanceState(name="ghost", role="server", url="http://127.0.0.1:9")
        )

        util = collect_utilization(cluster.controller, timeout_s=5.0)
        assert "ghost" in util["unreachable"]
        dev = util["servers"]["server0"]["device"]
        # totals are computed from EXACTLY the snapshots included
        assert util["totals"]["h2dBytes"] == dev["transfers"]["h2dBytes"]
        assert util["totals"]["d2hBytes"] == dev["transfers"]["d2hBytes"]
        assert util["totals"]["deviceMs"] == dev["recent"]["deviceMs"]
        assert util["totals"]["deviceBytes"] == dev["recent"]["deviceBytes"]
        assert util["totals"]["queries"] == dev["recent"]["queries"] >= 3
        assert util["totals"]["achievedBytesPerSec"] == pytest.approx(
            dev["recent"]["deviceBytes"] * 1000.0 / dev["recent"]["deviceMs"],
            rel=1e-6,
        )
        assert util["occupancy"]["servers"] == 1
        assert util["occupancy"]["meanBusyFraction"] == pytest.approx(
            dev["occupancy"]["busyFraction"], abs=1e-9
        )
        assert util["profilesActive"] == 0
        plans = util["underutilizedPlans"]
        assert plans and plans[0]["server"] == "server0"
        assert {"digest", "deviceMs", "achievedBytesPerSec",
                "rooflineFraction"} <= set(plans[0])

        http = ControllerHttpServer(cluster.controller)
        http.start()
        base = f"http://127.0.0.1:{http.port}"
        with urllib.request.urlopen(
            base + "/debug/utilization", timeout=10
        ) as r:
            over = json.loads(r.read())
        assert "server0" in over["servers"] and "ghost" in over["unreachable"]
        with urllib.request.urlopen(
            base + "/dashboard/utilization", timeout=10
        ) as r:
            page = r.read().decode()
        assert "Device utilization" in page and "server0" in page
        assert "unreachable" in page  # the partial-rollup banner
    finally:
        if http is not None:
            http.stop()
        if admin is not None:
            admin.stop()
        cluster.stop()


# ------------------------------------------------------ perf gate
def _serving_doc():
    import os

    from pinot_tpu.tools.perf_gate import load_bench

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return load_bench(os.path.join(repo, "SERVING_UTIL_r10.json"))


def test_perf_gate_serving_identical_run_passes():
    from pinot_tpu.tools.perf_gate import compare

    base = _serving_doc()
    out = compare(base, json.loads(json.dumps(base)))
    assert out["verdict"] == "pass"
    assert out["compared"] >= 6
    paths = {m["metric"] for m in out["metrics"]}
    assert "utilization.pipelined.achievedBytesPerSec" in paths
    assert "utilization.pipelined.busyFraction" in paths


def test_perf_gate_serving_direction_aware_fail():
    from pinot_tpu.tools.perf_gate import compare

    base = _serving_doc()
    cur = json.loads(json.dumps(base))
    # bandwidth collapse: an order of magnitude under the band
    cur["utilization"]["pipelined"]["achievedBytesPerSec"] = (
        base["utilization"]["pipelined"]["achievedBytesPerSec"] * 0.1
    )
    out = compare(base, cur)
    assert out["verdict"] == "fail"
    bad = [m for m in out["metrics"] if not m["ok"]]
    assert [m["metric"] for m in bad] == [
        "utilization.pipelined.achievedBytesPerSec"
    ]
    # higher-is-better: the same magnitude UP is not a regression
    cur["utilization"]["pipelined"]["achievedBytesPerSec"] = (
        base["utilization"]["pipelined"]["achievedBytesPerSec"] * 10
    )
    assert compare(base, cur)["verdict"] == "pass"


def test_perf_gate_serving_config_and_kind_mismatch_skip():
    import os

    from pinot_tpu.tools.perf_gate import compare, load_bench

    base = _serving_doc()
    cur = json.loads(json.dumps(base))
    cur["num_segments"] = base["num_segments"] + 7
    out = compare(base, cur)
    assert out["verdict"] == "skipped"
    assert "num_segments" in out["configMismatch"]

    # mixed kinds (default bench vs serving mode): nothing to compare
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    default_doc = load_bench(os.path.join(repo, "BENCH_r05.json"))
    out2 = compare(default_doc, base)
    assert out2["verdict"] == "skipped"
    assert "kind" in out2["reason"]


# ------------------------------------------------------ explain_dump
def test_explain_dump_renders_cost_analysis_and_roofline():
    from pinot_tpu.tools.explain_dump import (
        render_cost_analysis,
        render_roofline,
    )

    dev = {
        "compile": {
            "state": "warm",
            "costAnalysis": {
                "flops": 2.5e9,
                "bytesAccessed": 1.5e6,
                "source": "lowered",
            },
        }
    }
    out = render_cost_analysis(dev)
    assert "est flops=2.50G" in out and "est bytes=1.50M" in out
    assert "(lowered)" in out
    assert render_cost_analysis(
        {"compile": {"costAnalysis": "unavailable"}}
    ).strip() == "cost-analysis: unavailable"
    assert render_cost_analysis({"compile": {}}) == ""

    est = {
        "roofline": {
            "achievedBytesPerSec": 3.2e9,
            "achievedFlopsPerSec": 1.1e12,
            "rooflineFraction": 0.125,
        }
    }
    line = render_roofline(est)
    assert "achieved=3.20GB/s" in line and "1.10TFLOP/s" in line
    assert "roofline=12.50%" in line
    nopeak = render_roofline({"roofline": {"achievedBytesPerSec": 1.0,
                                           "rooflineFraction": None}})
    assert "n/a (no peak declared)" in nopeak
    assert render_roofline({}) == ""


def test_explain_dump_footer_on_executed_shape(util_broker):
    """End-to-end: once a shape has executed, EXPLAIN's history
    estimate carries the roofline and the renderer shows it."""
    from pinot_tpu.tools.explain_dump import render_explain

    broker = util_broker
    pql = "SELECT sum(metInt) FROM utilTable WHERE dimInt > 60"
    for _ in range(2):
        assert not broker.handle_pql(pql).exceptions
    plan = broker.handle_pql("EXPLAIN " + pql)
    out = render_explain(plan.to_json())
    assert "utilization: achieved=" in out
    assert "roofline=n/a (no peak declared)" in out  # CPU mesh
    assert "cost-analysis:" in out
