"""Elastic fleet breadth (ISSUE 15): partition-parallel ingest,
proactive skew-aware rebalancing, zero-downtime movement at 100+ tables.

Chaos acceptance (``-m chaos``, tier-1): the full ``elastic-fleet``
harness scenario — 100+ tables under mixed ingest+query closed-loop
load sustain a forced skew-triggered live rebalance AND a mid-rebalance
controller restart with zero failed queries, zero lost/duplicate rows,
and exactly one committed copy per sequence.

Plus unit coverage: the IngestConsumerPool scheduler (bounded workers,
done-removal, error parking, kick, live resize), the rebalance
planner's hysteresis / make-before-break ordering / ERROR-destination
abort / cost-rate weighting / disable switch, per-partition lag-gauge
continuity across segment rollover and pool resize (satellite 1),
drain racing a CONSUMING-segment handoff (satellite 3), and the
version-keyed cluster-state snapshot cache (control-plane scale).
"""
import threading
import time

import pytest

from pinot_tpu.common.tableconfig import TableConfig
from pinot_tpu.controller.network import ParticipantGateway
from pinot_tpu.controller.resource_manager import (
    ClusterResourceManager,
    InstanceState,
    Participant,
)
from pinot_tpu.controller.stabilizer import SelfStabilizer
from pinot_tpu.realtime.llc import make_segment_name
from pinot_tpu.realtime.pool import IngestConsumerPool
from pinot_tpu.realtime.stream import MemoryStreamProvider
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.segment.immutable import SegmentMetadata
from pinot_tpu.tools.cluster_harness import (
    InProcessCluster,
    run_elastic_fleet_scenario,
)
from pinot_tpu.tools.datagen import make_test_schema, random_rows
from pinot_tpu.utils.metrics import ControllerMetrics


# ------------------------------------------------------------------
# chaos acceptance — the same scenario code the CLI runs
# ------------------------------------------------------------------
@pytest.mark.chaos
def test_elastic_fleet_acceptance(tmp_path):
    out = run_elastic_fleet_scenario(data_dir=str(tmp_path))
    assert out["failedQueries"] == 0, out.get("failures")
    assert out["tables"] >= 100
    assert out["okQueries"] > 0
    assert out["coverageNeverLost"]
    # the restart genuinely interrupted an in-flight rebalance
    assert out["movesStartedBeforeRestart"] > 0
    assert out["pendingMovesAtRestart"] > 0 or out["surplusReplicasAtRestart"] > 0
    assert out["movesCompletedAfterRestart"] > 0
    # zero lost/duplicate rows, exactly one committed copy per sequence
    assert out["rtRowsServed"] == [out["rtRowsExpected"]] * len(out["rtRowsServed"])
    assert out["oneCommittedCopyPerSequence"]
    assert out["finalImbalanceRatio"] < out["skewRatioThreshold"]


def test_elastic_fleet_smoke(tmp_path):
    """Scaled-down tier-1 smoke of the same scenario path (16 tables)."""
    out = run_elastic_fleet_scenario(num_tables=16, data_dir=str(tmp_path))
    assert out["failedQueries"] == 0, out.get("failures")
    assert out["oneCommittedCopyPerSequence"]
    assert out["coverageNeverLost"]


# ------------------------------------------------------------------
# IngestConsumerPool scheduler
# ------------------------------------------------------------------
class _ScriptedConsumer:
    """step() pops scripted return values; records who ran it."""

    def __init__(self, script):
        self.script = list(script)
        self.calls = 0
        self.threads = set()

    def step(self):
        self.calls += 1
        self.threads.add(threading.current_thread().name)
        if not self.script:
            return None
        out = self.script.pop(0)
        if out == "raise":
            raise RuntimeError("scripted failure")
        return out


def _wait(cond, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


def test_pool_runs_consumers_and_removes_done():
    pool = IngestConsumerPool(workers=2, name="t1")
    a = _ScriptedConsumer([0.0, 0.0, None])
    b = _ScriptedConsumer([0.0, None])
    pool.add(a, key="a")
    pool.add(b, key="b")
    assert _wait(lambda: not pool.snapshot()["consumers"])
    assert a.calls == 3 and b.calls == 2
    assert pool.snapshot()["steps"] == 5
    pool.stop()


def test_pool_bounded_workers():
    """More consumers than workers: everything still runs, on at most
    ``workers`` distinct threads."""
    pool = IngestConsumerPool(workers=2, name="t2")
    consumers = [_ScriptedConsumer([0.0, None]) for _ in range(8)]
    for i, c in enumerate(consumers):
        pool.add(c, key=i)
    assert _wait(lambda: not pool.snapshot()["consumers"])
    threads = set().union(*(c.threads for c in consumers))
    assert len(threads) <= 2
    assert all(c.calls == 2 for c in consumers)
    pool.stop()


def test_pool_error_parks_consumer_not_worker():
    """A raising consumer is parked with a backoff; the OTHER consumer
    keeps stepping on the shared workers."""
    pool = IngestConsumerPool(workers=1, name="t3")
    bad = _ScriptedConsumer(["raise", None])
    good = _ScriptedConsumer([0.0] * 5 + [None])
    pool.add(bad, key="bad")
    pool.add(good, key="good")
    assert _wait(lambda: good.calls == 6)
    assert pool.snapshot()["errors"] == 1
    pool.kick()  # pull `bad` out of its error park immediately
    assert _wait(lambda: not pool.snapshot()["consumers"])
    pool.stop()


def test_pool_parked_consumer_costs_nothing_until_eligible():
    pool = IngestConsumerPool(workers=1, name="t4")
    slow = _ScriptedConsumer([30.0, None])  # parks itself for 30s
    pool.add(slow, key="slow")
    assert _wait(lambda: slow.calls == 1)
    time.sleep(0.15)
    assert slow.calls == 1  # still parked
    pool.kick()
    assert _wait(lambda: slow.calls == 2)
    pool.stop()


def test_pool_live_resize():
    pool = IngestConsumerPool(workers=1, name="t5")
    c = _ScriptedConsumer([0.05] * 40 + [None])
    pool.add(c, key="c")
    assert _wait(lambda: c.calls >= 2)
    pool.resize(3)
    assert pool.snapshot()["workers"] == 3
    pool.resize(1)
    assert pool.snapshot()["workers"] == 1
    assert _wait(lambda: c.calls >= 5)  # still being driven after shrink
    pool.stop()
    # leak guard: stopped pool's workers exit (asserted by conftest too)
    from pinot_tpu.realtime.pool import leaked_pool_threads

    assert leaked_pool_threads(grace_s=2.0) == []


# ------------------------------------------------------------------
# rebalance planner units (make-before-break over raw resources)
# ------------------------------------------------------------------
def _planner_rig(cold_participant_result=True):
    """Two servers, two 100-doc segments pinned on srvA: ratio 2.0
    (a single-segment skew is unmovable by design — the half-gap rule
    refuses moves that would only invert the imbalance).
    ``cold_participant_result``: what srvB's transition executor
    returns (True=ONLINE now, None=pending, False=ERROR)."""
    res = ClusterResourceManager()
    log = []

    def exec_a(table, seg, target, info):
        log.append(("srvA", seg, target))
        return True

    def exec_b(table, seg, target, info):
        log.append(("srvB", seg, target))
        return cold_participant_result

    res.register_instance(InstanceState("srvA", role="server"), Participant("srvA", exec_a))
    res.register_instance(InstanceState("srvB", role="server"), Participant("srvB", exec_b))
    res.add_table(TableConfig(table_name="t", table_type="OFFLINE", replication=1))
    for name in ("s0", "s1"):
        meta = SegmentMetadata(segment_name=name, table_name="t_OFFLINE", num_docs=100)
        res.add_segment("t_OFFLINE", meta, {"dir": "/nope"}, servers=["srvA"])
    st = SelfStabilizer(res, grace_s=0.0)
    st.rebalance_skew_ratio = 1.5
    st.rebalance_hysteresis = 2
    st.rebalance_max_moves = 2
    return res, st, log


def _moved_segment(res):
    """The (single) segment currently holding a surplus replica."""
    ideal = res.get_ideal_state("t_OFFLINE")
    moved = [s for s, r in ideal.items() if len(r) > 1]
    assert len(moved) == 1, ideal
    return moved[0]


def test_rebalance_hysteresis_defers_then_moves():
    res, st, log = _planner_rig()
    st.run_once()  # evaluation 1: skewed, deferred
    assert st.metrics.meter("rebalance.skewDeferrals").count == 1
    assert st.metrics.meter("rebalance.movesStarted").count == 0
    assert all(
        r == {"srvA": "ONLINE"}
        for r in res.get_ideal_state("t_OFFLINE").values()
    )
    st.run_once()  # evaluation 2: hysteresis satisfied -> phase 1
    assert st.metrics.meter("rebalance.movesStarted").count == 1
    # make-before-break: BOTH replicas in the ideal state now
    moved = _moved_segment(res)
    assert set(res.get_ideal_state("t_OFFLINE")[moved]) == {"srvA", "srvB"}
    assert ("srvB", moved, "ONLINE") in log
    st.run_once()  # phase 2: view shows srvB ONLINE -> src trimmed
    assert st.metrics.meter("rebalance.movesCompleted").count == 1
    ideal = res.get_ideal_state("t_OFFLINE")
    assert set(ideal[moved]) == {"srvB"}
    # balanced now: one segment per server, no further moves
    st.run_once()
    assert st.metrics.meter("rebalance.movesStarted").count == 1
    # the event ring distinguishes rebalance moves from heal moves
    classes = {e["event"]: e["class"] for e in st.events()}
    assert classes["rebalanceMoveStarted"] == "rebalance"
    assert classes["rebalanceMoveCompleted"] == "rebalance"


def test_rebalance_never_breaks_coverage_while_destination_pending():
    """With the destination transition PENDING (remote participant),
    the source replica must survive every round until the external
    view proves the new copy serves."""
    res, st, log = _planner_rig(cold_participant_result=None)
    st.run_once()
    st.run_once()  # phase 1: srvB added, view entry OFFLINE (pending)
    moved = _moved_segment(res)
    assert set(res.get_ideal_state("t_OFFLINE")[moved]) == {"srvA", "srvB"}
    for _ in range(3):
        st.run_once()  # trim must WAIT: srvB never reported ONLINE
        assert set(res.get_ideal_state("t_OFFLINE")[moved]) == {"srvA", "srvB"}
    assert st.metrics.meter("rebalance.movesCompleted").count == 0
    # the current-state report lands (the ack): NOW the trim may run
    res.report_state("srvB", "t_OFFLINE", moved, "ONLINE")
    st.run_once()
    assert set(res.get_ideal_state("t_OFFLINE")[moved]) == {"srvB"}
    assert st.metrics.meter("rebalance.movesCompleted").count == 1


def test_rebalance_error_destination_aborts_move():
    """A destination that fails its load (ERROR in the view) is dropped
    instead of the source — the move aborts, coverage holds."""
    res, st, log = _planner_rig(cold_participant_result=False)
    st.run_once()
    st.run_once()  # phase 1: add fails on srvB -> view ERROR
    moved = _moved_segment(res)
    assert set(res.get_ideal_state("t_OFFLINE")[moved]) == {"srvA", "srvB"}
    st.run_once()  # abort: drop the ERROR destination
    assert set(res.get_ideal_state("t_OFFLINE")[moved]) == {"srvA"}
    assert st.metrics.meter("rebalance.movesAborted").count == 1
    assert st.metrics.meter("rebalance.movesCompleted").count == 0


def test_rebalance_disabled_switch():
    res, st, log = _planner_rig()
    st.rebalance_enabled = False
    for _ in range(4):
        st.run_once()
    assert st.metrics.meter("rebalance.evaluations").count == 0
    assert all(
        r == {"srvA": "ONLINE"}
        for r in res.get_ideal_state("t_OFFLINE").values()
    )
    # the kill switch freezes phase 2 too: an existing surplus (e.g.
    # an in-flight move interrupted by the operator flipping the
    # switch) must NOT keep being trimmed
    res.add_segment_replica("t_OFFLINE", "s0", "srvB")
    for _ in range(2):
        st.run_once()
    assert set(res.get_ideal_state("t_OFFLINE")["s0"]) == {"srvA", "srvB"}
    assert st.metrics.meter("rebalance.movesCompleted").count == 0
    # re-enabling completes the move from derived state
    st.rebalance_enabled = True
    st.run_once()
    assert len(res.get_ideal_state("t_OFFLINE")["s0"]) == 1
    assert st.metrics.meter("rebalance.movesCompleted").count == 1


def test_rebalance_cost_rate_weights_hot_table_first(tmp_path):
    """Two equal-doc tables concentrated on server0; the cost-rate
    provider names one as the hot query tenant — the planner's first
    moves spread THAT table's segments."""
    cluster = InProcessCluster(num_servers=2, data_dir=str(tmp_path))
    res = cluster.controller.resources
    st = cluster.controller.stabilizer
    st.grace_s = 0.0
    st.rebalance_skew_ratio = 1.2
    st.rebalance_hysteresis = 1
    st.rebalance_max_moves = 1
    st.cost_rate_fn = lambda: {"hotq": 10.0, "coldq": 0.0}
    st.busy_fn = None
    schema_h = make_test_schema(with_mv=False)
    schema_h.schema_name = "hotq"
    schema_c = make_test_schema(with_mv=False)
    schema_c.schema_name = "coldq"
    rows = random_rows(schema_h, 50, seed=3)
    import os as _os

    for schema, prefix in ((schema_h, "h"), (schema_c, "c")):
        physical = cluster.add_offline_table(schema, replication=1)
        for i in range(2):
            seg = build_segment(schema, rows, physical, f"{prefix}{i}")
            path = cluster.controller.store.save(physical, seg)
            res.add_segment(
                physical, seg.metadata,
                {"dir": path, "downloadUri": "file://" + _os.path.abspath(path)},
                servers=["server0"],
            )
    st.run_once()
    started = [e for e in st.events() if e["event"] == "rebalanceMoveStarted"]
    assert started and started[0]["table"] == "hotq_OFFLINE"
    cluster.stop()


# ------------------------------------------------------------------
# satellite 1: per-partition lag gauges across rollover / pool resize
# ------------------------------------------------------------------
def test_lag_gauges_continuous_across_rollover_and_resize(tmp_path):
    """Multi-consumer case: two partitions on one server, pool-driven.
    Rolling partition 0 to its next sequence re-registers the SAME
    ``ingest.lag.<table>.p0`` series bound to the successor's probe;
    the predecessor's detach (equality-guarded) must not clear it, and
    partition 1's series must be untouched.  A pool resize changes
    worker count only — every gauge binding survives."""
    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path))
    rm = cluster.controller.realtime_manager
    pool = IngestConsumerPool(workers=2, name="lagtest")
    rm.ingest_pool = pool
    try:
        schema = make_test_schema(with_mv=False)
        schema.schema_name = "lagT"
        stream = MemoryStreamProvider(num_partitions=2)
        physical = cluster.add_realtime_table(
            schema, stream, rows_per_segment=50
        )
        rows = random_rows(schema, 70, seed=5)
        for row in rows:
            stream.produce(row, partition=0)  # 70 rows: one roll + 20
        for row in rows[:30]:
            stream.produce(row, partition=1)  # 30 rows: no roll

        server = cluster.servers[0]
        seg01 = make_segment_name(physical, 0, 1)

        def rolled():
            dms = rm.consumers_of(seg01)
            return bool(dms) and dms[0].offset == 70

        assert _wait(rolled, timeout_s=15.0), "partition 0 did not roll"
        # mid-test resize: gauges must survive a live worker change
        pool.resize(1)
        pool.resize(3)

        dms1 = rm.consumers_of(make_segment_name(physical, 1, 0))
        assert _wait(lambda: dms1[0].offset == 30, timeout_s=10.0)

        g0 = server.metrics.gauge(f"ingest.lag.{physical}.p0")
        g1 = server.metrics.gauge(f"ingest.lag.{physical}.p1")
        successor = rm.consumers_of(seg01)[0]
        # the p0 series is bound to the SUCCESSOR's probe (not cleared,
        # not the predecessor's frozen offset)
        assert g0._fn is successor._lag_probe
        assert g1._fn is dms1[0]._lag_probe
        assert g0.value == 0 and g1.value == 0
        # a late duplicate detach from the (already stopped) first
        # consumer must be a no-op thanks to the equality guard
        stopped = [
            dm
            for dm in [successor]
            if False
        ]
        seg00 = make_segment_name(physical, 0, 0)
        # the seq-0 consumer was stopped + deregistered at commit; its
        # stop() is idempotent and must not clobber the live series
        assert rm.consumers_of(seg00) == []
        g0_before = g0._fn
        # simulate the stale detach directly: clear_fn with a foreign
        # probe is the exact call path RemoteConsumer/DM stop() takes
        g0.clear_fn(lambda: 999)
        assert g0._fn is g0_before

        resp = cluster.query("SELECT count(*) FROM lagT")
        assert resp.num_docs_scanned == 100 and not resp.exceptions
    finally:
        pool.stop()
        cluster.stop()


# ------------------------------------------------------------------
# satellite 3: drain racing a CONSUMING-segment handoff
# ------------------------------------------------------------------
def test_drain_races_consuming_handoff_zero_loss(tmp_path):
    """Draining the server holding the ONLY consumer for a partition
    must re-create the consumer on a live server at the last COMMITTED
    offset: uncommitted rows re-consume from the stream (zero lost,
    zero duplicate), and the drain completes."""
    cluster = InProcessCluster(num_servers=2, data_dir=str(tmp_path))
    rm = cluster.controller.realtime_manager
    res = cluster.controller.resources
    try:
        schema = make_test_schema(with_mv=False)
        schema.schema_name = "drainRace"
        stream = MemoryStreamProvider(num_partitions=1)
        physical = cluster.add_realtime_table(
            schema, stream, rows_per_segment=50
        )
        for row in random_rows(schema, 70, seed=9):
            stream.produce(row)

        seg0 = make_segment_name(physical, 0, 0)
        dm = rm.consumers_of(seg0)[0]
        dm.consume_step(max_rows=1000)
        assert dm.try_commit() == "KEEP"  # committed at offset 50

        seg1 = make_segment_name(physical, 0, 1)
        holder = next(iter(res.get_ideal_state(physical)[seg1]))
        dm1 = next(c for c in rm.consumers_of(seg1) if c.server.name == holder)
        dm1.consume_step(max_rows=20)  # 20 UNCOMMITTED rows (50..69)

        # the race: drain lands while the consumer holds uncommitted
        # rows — no grace for operator intent, handoff this round
        cluster.controller.drain_instance(holder)
        st = cluster.controller.stabilizer
        st.grace_s = 0.0
        st.run_once()
        st.run_once()

        ideal = res.get_ideal_state(physical)
        assert seg1 in ideal
        new_holder = next(iter(ideal[seg1]))
        assert new_holder != holder
        assert ideal[seg1][new_holder] == "CONSUMING"
        new_dm = rm.consumers_of(seg1)
        assert len(new_dm) == 1 and new_dm[0].server.name == new_holder
        assert new_dm[0].offset == 50  # committed offset, NOT the lost 70

        # drain completes: nothing (committed or consuming) left behind
        st.run_once()
        status = cluster.controller.drain_status(holder)
        assert status["drained"], status

        new_dm[0].consume_step(max_rows=100)  # re-consume the 20 rows
        resp = cluster.query("SELECT count(*) FROM drainRace")
        assert resp.num_docs_scanned == 70 and not resp.exceptions
        assert resp.partial_response is False
    finally:
        cluster.stop()


# ------------------------------------------------------------------
# satellite 5: the ingest-ladder perf-gate wiring (direction-aware,
# config-mismatch SKIP) against the committed INGEST_r15.json
# ------------------------------------------------------------------
def test_perf_gate_ingest_ladder_kind():
    import copy

    from pinot_tpu.tools.perf_gate import compare, load_bench

    doc = load_bench("INGEST_r15.json")
    out = compare(doc, doc)
    assert out["verdict"] == "pass", out
    assert out["compared"] >= 8
    # the committed capture itself carries the arc's acceptance: the
    # parallel aggregate beats the INGEST_r5 single-consumer LLC
    # ceiling by well over 1.5x
    assert doc["vs_r5_single_consumer_ceiling"] >= 1.5

    # a parallel-scaling collapse (partition-parallel ingest silently
    # serialized) must FAIL the gate
    cur = copy.deepcopy(doc)
    cur["parallel_vs_single"] = doc["parallel_vs_single"] * 0.4
    cur["vs_r5_single_consumer_ceiling"] = 1.0
    out = compare(doc, cur)
    assert out["verdict"] == "fail"
    failed = {m["metric"] for m in out["metrics"] if not m["ok"]}
    assert "parallel_vs_single" in failed
    assert "vs_r5_single_consumer_ceiling" in failed

    # a slower lag drain past the band fails too (direction-aware)
    cur = copy.deepcopy(doc)
    cur["ladder"]["c2"]["lag_drain_s"] = doc["ladder"]["c2"]["lag_drain_s"] * 10
    assert compare(doc, cur)["verdict"] == "fail"

    # ladders from a different-sized host are not comparable: SKIP
    cur = copy.deepcopy(doc)
    cur["cpu_cores"] = 96
    out = compare(doc, cur)
    assert out["verdict"] == "skipped"
    assert "cpu_cores" in out["configMismatch"]


# ------------------------------------------------------------------
# control-plane scale: version-keyed cluster-state snapshot cache
# ------------------------------------------------------------------
def test_clusterstate_snapshot_cached_per_version():
    res = ClusterResourceManager()
    res.register_instance(
        InstanceState("srv0", role="server", addr=("127.0.0.1", 9000))
    )
    res.add_table(TableConfig(table_name="t", table_type="OFFLINE", replication=1))
    metrics = ControllerMetrics("controller")
    gw = ParticipantGateway(res, metrics=metrics)

    first = gw.cluster_state()
    second = gw.cluster_state()
    assert second is first  # served from the cache, no rebuild
    assert metrics.meter("clusterStateCacheHits").count == 1
    assert metrics.meter("clusterStatePolls").count == 2

    res.bump_version()  # any change invalidates by version key
    third = gw.cluster_state()
    assert third is not first
    assert third["version"] > first["version"]
    assert metrics.meter("clusterStateCacheHits").count == 1
    # and the new snapshot is cached in turn
    assert gw.cluster_state() is third
    assert metrics.meter("clusterStateCacheHits").count == 2
