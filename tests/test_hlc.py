"""HLC (high-level consumer) ingestion mode: one consumer-group member
per server, broker-coordinated partition rebalance, server-owned
segments that seal and roll locally.

Reference: ``HLRealtimeSegmentDataManager.java:54`` +
``KafkaHighLevelConsumerStreamProvider.java`` (consumer groups replace
controller-coordinated per-partition offsets)."""
import json
import signal
import socket

import pytest

from pinot_tpu.common.tableconfig import StreamConfig, TableConfig
from pinot_tpu.realtime.netstream import NetworkStreamProvider, StreamBrokerServer
from pinot_tpu.tools.datagen import make_test_schema
from tests.test_network_cluster import _get, _post_json, _spawn, _wait_for

TABLE = "hlcTable"
PHYSICAL = "hlcTable_REALTIME"


def _row(i):
    return {
        "dimStr": f"v{i % 5}",
        "dimInt": i % 7,
        "dimLong": i,
        "metInt": i,
        "metFloat": 0.5 * i,
        "metDouble": 0.25 * i,
        "daysSinceEpoch": 17000 + i,
    }


@pytest.mark.slow
def test_hlc_group_consumption_seal_roll_and_failover(tmp_path):
    schema = make_test_schema(with_mv=False)
    schema.schema_name = TABLE

    procs = []
    sb = StreamBrokerServer(log_dir=str(tmp_path / "streamlog"))
    sb.start()
    try:
        host, port = sb.address
        producer = NetworkStreamProvider(host, port, "hltopic")
        producer.create_topic(4)

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ctrl_port = s.getsockname()[1]
        s.close()
        ctrl_proc, ctrl_url = _spawn(
            ["StartController", "-port", str(ctrl_port),
             "-data-dir", str(tmp_path / "store"), "-heartbeat-timeout", "3.0"]
        )
        procs.append(ctrl_proc)
        srv_procs = {}
        for name in ("h0", "h1"):
            p, _ = _spawn(
                ["StartServer", "-controller", ctrl_url, "-name", name,
                 "-data-dir", str(tmp_path / f"cache_{name}")]
            )
            procs.append(p)
            srv_procs[name] = p
        broker_proc, broker_url = _spawn(["StartBroker", "-controller", ctrl_url, "-port", "0"])
        procs.append(broker_proc)

        _post_json(ctrl_url + "/schemas", schema.to_json())
        config = TableConfig(
            table_name=TABLE,
            table_type="REALTIME",
            stream=StreamConfig(
                stream_type="network",
                topic="hltopic",
                rows_per_segment=50,
                consumer_type="highlevel",
                properties={"host": host, "port": port},
            ),
        )
        _post_json(ctrl_url + "/tables", config.to_json())

        def _query(pql):
            return _post_json(broker_url + "/query", {"pql": pql})

        def _count_is(n):
            def check():
                resp = _query(f"SELECT count(*) FROM {TABLE}")
                return not resp.get("exceptions") and resp.get("numDocsScanned") == n
            return check

        # wait for BOTH members before producing: a lone member would
        # legitimately drain the whole backlog first (assignments are
        # correct either way; this keeps the scenario deterministic)
        from pinot_tpu.realtime.netstream import HLConsumer

        probe = HLConsumer(host, port, "hltopic", PHYSICAL, "probe")

        def _group_formed():
            d = probe.describe_group()
            return len(d["members"]) == 2 and not d["syncPending"]

        _wait_for(_group_formed, timeout=60, what="both servers in the group")

        for i in range(60):
            producer.produce(_row(i), partition=i % 4)
        _wait_for(_count_is(60), timeout=90, what="60 rows via both group members")

        resp = _query(f"SELECT sum(metInt) FROM {TABLE}")
        assert float(resp["aggregationResults"][0]["value"]) == sum(range(60))

        # kill one member before it seals: the group rebalances and the
        # survivor re-consumes the dead member's partitions from the
        # committed offsets (at-least-once, converging to exactly the
        # produced rows once the dead server drops out of routing)
        srv_procs["h1"].send_signal(signal.SIGKILL)
        srv_procs["h1"].wait(timeout=10)
        for i in range(60, 120):
            producer.produce(_row(i), partition=i % 4)
        _wait_for(_count_is(120), timeout=120, what="120 rows after failover rebalance")

        # the survivor has consumed >= 100 rows: its segment sealed,
        # uploaded pinned to it, and consumption rolled to seq 1+
        def _sealed_segment_online():
            view = _get(ctrl_url + f"/tables/{PHYSICAL}/externalview")
            return any(st == "ONLINE" for reps in view.values() for st in reps.values())

        _wait_for(_sealed_segment_online, timeout=60, what="sealed HLC segment ONLINE")
        resp = _query(f"SELECT sum(metInt) FROM {TABLE}")
        assert not resp.get("exceptions"), resp
        assert float(resp["aggregationResults"][0]["value"]) == sum(range(120))

        # group offsets are checkpointed in the stream broker
        committed = probe.committed_offsets()
        assert committed and sum(committed.values()) >= 50
    finally:
        for p in procs:
            try:
                p.kill()
            except Exception:
                pass
        sb.stop()


def test_ensure_hlc_consumers_resumes_owned_idx_mid_roll():
    """Regression (ADVICE r2): the seal-and-roll window — the sealed
    upload flips the server's segment entry ONLINE before the roll
    registers the successor.  ``ensure_hlc_consumers`` running in that
    window must continue the server's own idx at the next sequence (the
    name the server's roll will also register, so both dedupe), not open
    a phantom CONSUMING segment at a fresh idx that no consumer serves."""
    from pinot_tpu.controller.resource_manager import (
        CONSUMING,
        ONLINE,
        ClusterResourceManager,
        InstanceState,
    )
    from pinot_tpu.realtime.llc import RealtimeSegmentManager, make_segment_name
    from pinot_tpu.realtime.stream import MemoryStreamProvider

    rm = ClusterResourceManager()
    rm.register_instance(InstanceState(name="srvA", role="server"))
    schema = make_test_schema(with_mv=False)
    schema.schema_name = "hlcRace"
    config = TableConfig(
        table_name="hlcRace",
        table_type="REALTIME",
        stream=StreamConfig(stream_type="memory", topic="t", consumer_type="highlevel"),
    )
    mgr = RealtimeSegmentManager(rm, store=None)
    physical = mgr.setup_table(config, schema, MemoryStreamProvider(2))

    seg0 = make_segment_name(physical, 0, 0)
    ideal = rm.get_ideal_state(physical)
    assert ideal.get(seg0) == {"srvA": CONSUMING}

    # simulate the mid-roll window: sealed upload replaced the entry
    # (ONLINE, still pinned to srvA); the roll has NOT registered seq 1
    with rm._lock:
        rm.ideal_states[physical][seg0] = {"srvA": ONLINE}

    mgr.ensure_hlc_consumers(physical)
    ideal = rm.get_ideal_state(physical)
    seg1 = make_segment_name(physical, 0, 1)
    assert ideal.get(seg1) == {"srvA": CONSUMING}, ideal
    # no phantom fresh-idx segment
    assert set(ideal) == {seg0, seg1}, ideal

    # the server's own roll for the same name dedupes controller-side
    mgr.register_hlc_roll(physical, "srvA", 0, 1)
    assert set(rm.get_ideal_state(physical)) == {seg0, seg1}
