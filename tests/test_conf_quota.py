"""Config system + query quota tests."""
import time

from pinot_tpu.common.conf import BrokerConf, ControllerConf, ServerConf, parse_properties
from pinot_tpu.common.tableconfig import QuotaConfig
from pinot_tpu.broker.quota import QueryQuotaManager
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.tools.cluster_harness import InProcessCluster
from pinot_tpu.tools.datagen import make_test_schema, random_rows


def test_parse_properties():
    props = parse_properties(
        """
        # comment
        pinot.server.netty.port=9999
        pinot.server.query.executor.timeout.ms = 5000
        controller.port=9001
        """
    )
    assert props["pinot.server.netty.port"] == "9999"
    assert props["pinot.server.query.executor.timeout.ms"] == "5000"


def test_conf_from_dict():
    conf = ServerConf.from_dict(
        {"pinot.server.netty.port": "9999", "pinot.server.query.executor.timeout.ms": "5000"}
    )
    assert conf.netty_port == 9999
    assert conf.query_executor_timeout_ms == 5000
    assert conf.instance_id == "server0"  # default preserved

    b = BrokerConf.from_dict({"pinot.broker.timeout.ms": "2000"})
    assert b.timeout_ms == 2000
    c = ControllerConf.from_dict({"controller.port": "9001"})
    assert c.port == 9001


def test_broker_resilience_conf_maps_to_handler():
    """pinot.broker.* resilience keys flow from properties text into the
    scatter-gather layer's knobs and the circuit breaker."""
    from pinot_tpu.broker.broker import BrokerRequestHandler
    from pinot_tpu.transport.local import LocalTransport

    conf = BrokerConf.from_dict(
        parse_properties(
            """
            pinot.broker.retry.attempts=5
            pinot.broker.retry.backoff.ms=7
            pinot.broker.hedge.delay.ms=120
            pinot.broker.health.failure.threshold=2
            pinot.broker.health.penalty.ms=900
            """
        )
    )
    handler = BrokerRequestHandler.from_conf(LocalTransport(), {}, conf)
    assert handler.retry_attempts == 5
    assert handler.retry_backoff_ms == 7.0
    assert handler.hedge_delay_ms == 120.0
    assert handler.health.failure_threshold == 2
    assert handler.health.penalty_ms == 900.0


def test_quota_headroom():
    qm = QueryQuotaManager()
    assert qm.headroom("unlimited") == 1.0
    qm.set_quota("t", 2.0)
    assert qm.headroom("t") == 1.0  # full bucket
    qm.allow("t")
    qm.allow("t")
    assert qm.headroom("t") < 0.5  # drained (refills over time)


def test_token_bucket_quota():
    qm = QueryQuotaManager()
    qm.set_quota("t", 2.0)  # 2 qps, burst 2
    assert qm.allow("t")
    assert qm.allow("t")
    assert not qm.allow("t")  # bucket drained
    assert qm.allow("other")  # unlimited table unaffected
    qm.set_quota("t", None)
    assert qm.allow("t")


def test_token_bucket_fractional_qps():
    """A sub-1.0 quota must admit its steady rate: capacity stays 1.0
    (one whole query spendable) and refill accrues at the fractional
    rate — 0.5 qps admits exactly one query per two seconds."""
    from pinot_tpu.broker.quota import _TokenBucket

    b = _TokenBucket(0.5)
    assert b.capacity == 1.0
    assert b.try_acquire()  # the seeded token
    assert not b.try_acquire()  # drained
    # one second later: half a token — still not enough
    b.last -= 1.0
    assert not b.try_acquire()
    # two seconds after the drain: a full token accrued
    b.last -= 1.5
    assert b.try_acquire()


def test_token_bucket_burst_capacity():
    from pinot_tpu.broker.quota import _TokenBucket

    b = _TokenBucket(2.0, burst=5.0)
    assert b.capacity == 5.0
    for _ in range(5):
        assert b.try_acquire()  # full burst spendable at once
    assert not b.try_acquire()
    # refill still runs at qps (not burst): 1s -> 2 tokens, never past cap
    b.last -= 1.0
    assert b.try_acquire() and b.try_acquire()
    assert not b.try_acquire()
    b.last -= 60.0
    assert b.headroom() == 1.0  # refill capped at burst


def test_token_bucket_sub_one_burst_cannot_block_table():
    """Regression: a misconfigured burst < 1.0 must not set capacity
    below one whole token (acquire costs 1.0) — that would shed 100%
    of the table's queries forever."""
    from pinot_tpu.broker.quota import _TokenBucket

    b = _TokenBucket(4.0, burst=0.5)
    assert b.capacity == 1.0
    assert b.try_acquire()
    b2 = _TokenBucket(4.0)
    b2.reconfigure(4.0, burst=0.25)
    assert b2.capacity == 1.0 and b2.try_acquire()


def test_token_bucket_reconfigure_preserves_tokens():
    """A quota UPDATE (cluster-state re-notify) must not refill a
    drained bucket — only capacity/rate change, spent tokens stay
    spent (clamped when the new capacity is smaller)."""
    from pinot_tpu.broker.quota import _TokenBucket

    b = _TokenBucket(2.0)  # capacity 2
    assert b.try_acquire() and b.try_acquire()
    b.reconfigure(10.0)
    assert not b.try_acquire()  # still drained: no refill on update
    assert b.qps == 10.0
    # shrink below current tokens: clamped to the new capacity
    b2 = _TokenBucket(4.0, burst=8.0)
    b2.reconfigure(1.0)
    assert b2.tokens == 1.0 == b2.capacity


def test_quota_manager_set_quota_idempotent_no_refill():
    qm = QueryQuotaManager()
    qm.set_quota("t", 2.0)
    assert qm.allow("t") and qm.allow("t") and not qm.allow("t")
    qm.set_quota("t", 2.0)  # unchanged re-notify: same bucket, no refill
    assert not qm.allow("t")
    qm.set_quota("t", 5.0)  # update: reconfigure in place, no refill
    assert not qm.allow("t")
    assert qm.tables() == ["t"]
    qm.set_quota("t", None)  # removal clears the bucket
    assert qm.allow("t") and qm.tables() == []


def test_quota_headroom_edges():
    qm = QueryQuotaManager()
    qm.set_quota("t", 1.0)
    assert qm.headroom("t") == 1.0
    qm.allow("t")
    assert qm.headroom("t") < 0.1  # fully drained (modulo refill)
    qm.set_quota("b", 2.0, burst=10.0)
    qm.allow("b")
    assert 0.85 < qm.headroom("b") < 0.95  # ~9/10 of the burst left


def test_networked_quota_propagation_update_and_removal():
    """Regression (ISSUE 7 satellite): a table-config quota UPDATE
    reaches a running networked broker on its next cluster-state poll
    without refilling the bucket, and a quota REMOVAL clears the
    bucket instead of leaving a stale limiter behind."""
    from pinot_tpu.broker.network_starter import NetworkedBrokerStarter

    starter = NetworkedBrokerStarter("http://127.0.0.1:9")  # never polled
    try:
        quota = starter.handler.quota

        def snap(version, quotas):
            return {
                "version": version,
                "epoch": "e1",
                "servers": {},
                "tables": {},
                "quotas": quotas,
            }

        starter._apply_state(
            snap(1, {"T_OFFLINE": {"rawName": "T", "maxQueriesPerSecond": 2.0}})
        )
        assert quota.allow("T") and quota.allow("T") and not quota.allow("T")

        # identical snapshot re-applied (poll after an unrelated version
        # bump): the drained bucket must NOT refill
        starter._apply_state(
            snap(2, {"T_OFFLINE": {"rawName": "T", "maxQueriesPerSecond": 2.0}})
        )
        assert not quota.allow("T")

        # quota UPDATE lands on the next poll (tokens preserved)
        starter._apply_state(
            snap(
                3,
                {
                    "T_OFFLINE": {
                        "rawName": "T",
                        "maxQueriesPerSecond": 50.0,
                        "burstQueries": 60.0,
                    }
                },
            )
        )
        assert not quota.allow("T")  # still drained right after the update

        # quota REMOVAL clears the bucket entirely
        starter._apply_state(snap(4, {}))
        assert quota.allow("T") and quota.tables() == []
    finally:
        starter.http._httpd.server_close()


def test_quota_live_update_reaches_inprocess_broker(tmp_path):
    """update_table_quota: the operator-facing live path — a running
    in-process broker enforces the new rate on the next query, and a
    removal stops enforcement."""
    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path))
    schema = make_test_schema(with_mv=False)
    physical = cluster.add_offline_table(schema)
    cluster.upload(
        physical,
        build_segment(schema, random_rows(schema, 10, seed=1), physical, "q1"),
    )
    assert not cluster.query("SELECT count(*) FROM testTable").exceptions

    cluster.controller.resources.update_table_quota(physical, 1.0)
    ok = cluster.query("SELECT count(*) FROM testTable")
    assert not ok.exceptions
    limited = cluster.query("SELECT count(*) FROM testTable")
    assert limited.exceptions and limited.exceptions[0].error_code == 429

    cluster.controller.resources.update_table_quota(physical, None)
    cleared = cluster.query("SELECT count(*) FROM testTable")
    assert not cleared.exceptions
    cluster.stop()


def test_quota_enforced_end_to_end(tmp_path):
    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path))
    schema = make_test_schema(with_mv=False)
    physical = cluster.add_offline_table(schema)
    # set a tiny quota on the table config and re-notify brokers
    cluster.controller.resources.table_configs[physical].quota = QuotaConfig(
        max_queries_per_second=1.0
    )
    cluster.upload(physical, build_segment(schema, random_rows(schema, 10, seed=1), physical, "q1"))

    ok = cluster.query("SELECT count(*) FROM testTable")
    assert not ok.exceptions
    # immediately again: bucket (capacity 1) is empty
    limited = cluster.query("SELECT count(*) FROM testTable")
    assert limited.exceptions and limited.exceptions[0].error_code == 429
