"""Config system + query quota tests."""
import time

from pinot_tpu.common.conf import BrokerConf, ControllerConf, ServerConf, parse_properties
from pinot_tpu.common.tableconfig import QuotaConfig
from pinot_tpu.broker.quota import QueryQuotaManager
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.tools.cluster_harness import InProcessCluster
from pinot_tpu.tools.datagen import make_test_schema, random_rows


def test_parse_properties():
    props = parse_properties(
        """
        # comment
        pinot.server.netty.port=9999
        pinot.server.query.executor.timeout.ms = 5000
        controller.port=9001
        """
    )
    assert props["pinot.server.netty.port"] == "9999"
    assert props["pinot.server.query.executor.timeout.ms"] == "5000"


def test_conf_from_dict():
    conf = ServerConf.from_dict(
        {"pinot.server.netty.port": "9999", "pinot.server.query.executor.timeout.ms": "5000"}
    )
    assert conf.netty_port == 9999
    assert conf.query_executor_timeout_ms == 5000
    assert conf.instance_id == "server0"  # default preserved

    b = BrokerConf.from_dict({"pinot.broker.timeout.ms": "2000"})
    assert b.timeout_ms == 2000
    c = ControllerConf.from_dict({"controller.port": "9001"})
    assert c.port == 9001


def test_broker_resilience_conf_maps_to_handler():
    """pinot.broker.* resilience keys flow from properties text into the
    scatter-gather layer's knobs and the circuit breaker."""
    from pinot_tpu.broker.broker import BrokerRequestHandler
    from pinot_tpu.transport.local import LocalTransport

    conf = BrokerConf.from_dict(
        parse_properties(
            """
            pinot.broker.retry.attempts=5
            pinot.broker.retry.backoff.ms=7
            pinot.broker.hedge.delay.ms=120
            pinot.broker.health.failure.threshold=2
            pinot.broker.health.penalty.ms=900
            """
        )
    )
    handler = BrokerRequestHandler.from_conf(LocalTransport(), {}, conf)
    assert handler.retry_attempts == 5
    assert handler.retry_backoff_ms == 7.0
    assert handler.hedge_delay_ms == 120.0
    assert handler.health.failure_threshold == 2
    assert handler.health.penalty_ms == 900.0


def test_quota_headroom():
    qm = QueryQuotaManager()
    assert qm.headroom("unlimited") == 1.0
    qm.set_quota("t", 2.0)
    assert qm.headroom("t") == 1.0  # full bucket
    qm.allow("t")
    qm.allow("t")
    assert qm.headroom("t") < 0.5  # drained (refills over time)


def test_token_bucket_quota():
    qm = QueryQuotaManager()
    qm.set_quota("t", 2.0)  # 2 qps, burst 2
    assert qm.allow("t")
    assert qm.allow("t")
    assert not qm.allow("t")  # bucket drained
    assert qm.allow("other")  # unlimited table unaffected
    qm.set_quota("t", None)
    assert qm.allow("t")


def test_quota_enforced_end_to_end(tmp_path):
    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path))
    schema = make_test_schema(with_mv=False)
    physical = cluster.add_offline_table(schema)
    # set a tiny quota on the table config and re-notify brokers
    cluster.controller.resources.table_configs[physical].quota = QuotaConfig(
        max_queries_per_second=1.0
    )
    cluster.upload(physical, build_segment(schema, random_rows(schema, 10, seed=1), physical, "q1"))

    ok = cluster.query("SELECT count(*) FROM testTable")
    assert not ok.exceptions
    # immediately again: bucket (capacity 1) is empty
    limited = cluster.query("SELECT count(*) FROM testTable")
    assert limited.exceptions and limited.exceptions[0].error_code == 429
