"""Inverted-index postings + selective-query fast path
(segment/invindex.py + engine/invindex_path.py).

Reference capability: ``BitmapInvertedIndexReader.java:28`` +
``BitmapBasedFilterOperator.java:34`` — O(matches) selective predicates
independent of doc order (the case zone maps cannot prune: values
shuffled across blocks)."""
import json

import numpy as np
import pytest

from pinot_tpu.engine.executor import QueryExecutor
from pinot_tpu.engine.context import get_table_context
from pinot_tpu.engine.invindex_path import try_index_path
from pinot_tpu.engine.reduce import reduce_to_response
from pinot_tpu.pql import optimize_request, parse_pql
from pinot_tpu.segment.invindex import InvertedIndex, inverted_index
from pinot_tpu.tools.datagen import lineitem_schema, synthetic_lineitem_segment
from pinot_tpu.tools.scan_engine import ScanQueryProcessor

STRIP = (
    "timeUsedMs",
    "cost",
    "numEntriesScannedInFilter",
    "numEntriesScannedPostFilter",
    "numSegmentsQueried",
    "numServersQueried",
    "numServersResponded",
    "numDocsScanned",
)


def _norm(resp):
    j = resp.to_json()
    for k in STRIP:
        j.pop(k, None)
    return json.dumps(j, sort_keys=True, default=str)


@pytest.fixture(scope="module")
def cluster():
    segs = [
        synthetic_lineitem_segment(20000, seed=17 + i, name=f"ii{i}") for i in range(3)
    ]
    rows = [r for s in segs for r in s.rows()]
    return segs, ScanQueryProcessor(lineitem_schema(), rows)


# -- postings unit level ------------------------------------------------


def test_build_sv_round_trip():
    fwd = np.array([3, 1, 3, 0, 1, 3], dtype=np.int32)
    idx = InvertedIndex.build_sv(fwd, 4)
    assert idx.rows[idx.offsets[3] : idx.offsets[4]].tolist() == [0, 2, 5]
    assert idx.rows[idx.offsets[1] : idx.offsets[2]].tolist() == [1, 4]
    assert idx.rows[idx.offsets[2] : idx.offsets[3]].tolist() == []
    # a dictId range is one contiguous slice
    t = np.zeros(4, bool)
    t[1:3] = True
    assert idx.slices_for_table(t) == [(1, 3)]
    assert sorted(idx.resolve_table(t).tolist()) == [1, 4]


def test_build_mv_any_semantics():
    # rows: 0 -> [1, 2]; 1 -> []; 2 -> [2]
    mv_values = np.array([1, 2, 2], dtype=np.int32)
    mv_offsets = np.array([0, 2, 2, 3], dtype=np.int64)
    idx = InvertedIndex.build_mv(mv_values, mv_offsets, 3)
    t = np.zeros(3, bool)
    t[2] = True
    assert sorted(idx.resolve_table(t).tolist()) == [0, 2]
    # a doc matching SEVERAL predicate values resolves ONCE (regression:
    # per-(doc,value) postings must dedupe or aggregations double-count)
    t2 = np.ones(3, bool)
    assert idx.resolve_table(t2).tolist() == [0, 2]


def test_index_cached_on_segment(cluster):
    segs, _ = cluster
    a = inverted_index(segs[0], "l_extendedprice")
    b = inverted_index(segs[0], "l_extendedprice")
    assert a is b
    col = segs[0].column("l_extendedprice")
    # postings invert the forward index exactly
    d = np.random.default_rng(3).integers(0, col.dictionary.cardinality, 5)
    for dict_id in d:
        t = np.zeros(col.dictionary.cardinality, bool)
        t[dict_id] = True
        want = np.nonzero(np.asarray(col.fwd) == dict_id)[0]
        np.testing.assert_array_equal(a.resolve_table(t), want)


# -- fast path vs oracle ------------------------------------------------

SELECTIVE_QUERIES = [
    # point lookup on the SHUFFLED high-card column (zone maps can't
    # prune this; the reference answers it from the inverted index)
    "SELECT count(*) FROM lineitem WHERE l_extendedprice = {p0}",
    "SELECT sum(l_quantity), avg(l_tax) FROM lineitem WHERE l_extendedprice = {p0}",
    "SELECT min(l_quantity), max(l_quantity) FROM lineitem WHERE l_extendedprice IN ({p0}, {p1})",
    # AND residuals on the matched subset
    "SELECT count(*) FROM lineitem WHERE l_extendedprice = {p0} AND l_returnflag = 'R'",
    "SELECT sum(l_discount) FROM lineitem WHERE l_extendedprice = {p0} AND l_shipmode NOT IN ('RAIL')",
    # group-by and selection through the same path
    "SELECT sum(l_quantity) FROM lineitem WHERE l_extendedprice = {p0} GROUP BY l_returnflag TOP 10",
    "SELECT l_returnflag, l_quantity FROM lineitem WHERE l_extendedprice = {p0} ORDER BY l_quantity DESC LIMIT 5",
]


def _pvals(segs):
    d = segs[0].column("l_extendedprice").dictionary
    return repr(d.get(100)), repr(d.get(2000))


def test_index_path_matches_oracle(cluster):
    segs, oracle = cluster
    p0, p1 = _pvals(segs)
    ex = QueryExecutor()
    for q in SELECTIVE_QUERIES:
        pql = q.format(p0=p0, p1=p1)
        req = optimize_request(parse_pql(pql))
        req2 = optimize_request(parse_pql(pql))
        got = reduce_to_response(req, [ex.execute(segs, req)])
        want = oracle.execute(req2)
        assert _norm(got) == _norm(want), pql


def test_index_path_engages_and_is_o_matches(cluster):
    segs, _ = cluster
    p0, _ = _pvals(segs)
    req = optimize_request(
        parse_pql(f"SELECT count(*) FROM lineitem WHERE l_extendedprice = {p0}")
    )
    ctx = get_table_context(segs)
    total = sum(s.num_docs for s in segs)
    res = try_index_path(req, list(segs), ctx, total, None)
    assert res is not None
    # filter cost is O(postings), nowhere near the table
    assert res.num_entries_scanned_in_filter < total / 100


def test_unselective_predicate_stays_on_device(cluster):
    segs, _ = cluster
    # 20% of rows: must NOT take the needle path
    req = optimize_request(
        parse_pql("SELECT count(*) FROM lineitem WHERE l_returnflag = 'R'")
    )
    ctx = get_table_context(segs)
    total = sum(s.num_docs for s in segs)
    assert try_index_path(req, list(segs), ctx, total, None) is None


def test_kill_switch(cluster, monkeypatch):
    segs, _ = cluster
    monkeypatch.setenv("PINOT_TPU_INVINDEX", "0")
    p0, _ = _pvals(segs)
    req = optimize_request(
        parse_pql(f"SELECT count(*) FROM lineitem WHERE l_extendedprice = {p0}")
    )
    ctx = get_table_context(segs)
    assert try_index_path(req, list(segs), ctx, 1, None) is None


def test_threshold_bail(cluster, monkeypatch):
    segs, _ = cluster
    monkeypatch.setenv("PINOT_TPU_INDEX_MAX_MATCHES", "1")
    p0, _ = _pvals(segs)
    req = optimize_request(
        parse_pql(f"SELECT count(*) FROM lineitem WHERE l_extendedprice = {p0}")
    )
    ctx = get_table_context(segs)
    total = sum(s.num_docs for s in segs)
    assert try_index_path(req, list(segs), ctx, total, None) is None


def test_configured_inverted_index_columns_warm_at_load(tmp_path):
    """invertedIndexColumns table config (IndexingConfig parity): the
    server pre-builds configured postings at segment load instead of on
    the first needle query."""
    from pinot_tpu.common.tableconfig import IndexingConfig
    from pinot_tpu.tools.cluster_harness import InProcessCluster

    cluster = InProcessCluster(num_servers=1)
    physical = cluster.add_offline_table(
        lineitem_schema(),
        "lineitem",
        indexing=IndexingConfig(inverted_index_columns=["l_extendedprice"]),
    )
    seg = synthetic_lineitem_segment(5000, seed=5, name="warm0")
    cluster.controller.upload_segment(physical, seg)
    tdm = cluster.servers[0].data_manager.table(physical)
    acquired = tdm.acquire_segments(tdm.segment_names())
    try:
        seg_loaded = acquired[0].query_view()
        cache = getattr(seg_loaded, "_inv_cache", {})
        assert "l_extendedprice" in cache, "postings not warmed at load"
    finally:
        tdm.release_segments(acquired)


# -- compressed containers (VERDICT r3 #6) ------------------------------


def test_compressed_blocks_roundtrip_clustered():
    """A sorted (clustered) column: postings are consecutive runs ->
    run containers; decode must be exact and memory far below raw."""
    n = 50_000
    fwd = np.sort(np.random.default_rng(3).integers(0, 100, n)).astype(np.int32)
    raw = InvertedIndex.build_sv(fwd, 100, compress=False)
    comp = InvertedIndex.build_sv(fwd, 100, compress=True)
    np.testing.assert_array_equal(raw.rows, comp.rows)
    t = np.zeros(100, bool)
    t[17] = True
    t[40:60] = True
    np.testing.assert_array_equal(raw.resolve_table(t), comp.resolve_table(t))
    # clustered postings collapse to run containers: >=20x cut on the
    # posting body (offsets overhead excluded by using a small card)
    assert comp.nbytes * 20 <= raw.nbytes, (comp.nbytes, raw.nbytes)


def test_compressed_blocks_roundtrip_shuffled():
    """Shuffled high-cardinality column: packed containers at
    ceil(log2(num_docs)) bits; decode exact, strictly below raw int32."""
    n = 40_000
    rng = np.random.default_rng(4)
    fwd = rng.integers(0, 7000, n).astype(np.int32)
    raw = InvertedIndex.build_sv(fwd, 7000, compress=False)
    comp = InvertedIndex.build_sv(fwd, 7000, compress=True)
    np.testing.assert_array_equal(raw.rows, comp.rows)
    for d in (0, 1234, 6999):
        t = np.zeros(7000, bool)
        t[d] = True
        np.testing.assert_array_equal(raw.resolve_table(t), comp.resolve_table(t))
    # 16 bits vs 32 on the body (40k docs): about 2x minus offsets
    body_raw = raw.nbytes - raw.offsets.nbytes
    body_comp = comp.nbytes - comp.offsets.nbytes
    assert body_comp * 1.9 <= body_raw, (body_comp, body_raw)


def test_compressed_mv_roundtrip():
    mv_offsets = np.arange(0, 3 * 9001, 3, dtype=np.int32)  # 9000 docs x 3 values
    rng = np.random.default_rng(5)
    mv_values = rng.integers(0, 50, mv_offsets[-1]).astype(np.int32)
    raw = InvertedIndex.build_mv(mv_values, mv_offsets, 50, compress=False)
    comp = InvertedIndex.build_mv(mv_values, mv_offsets, 50, compress=True)
    t = np.zeros(50, bool)
    t[7] = True
    t[31] = True
    np.testing.assert_array_equal(raw.resolve_table(t), comp.resolve_table(t))


def test_postings_budget_refusal_and_release(monkeypatch):
    """Over-budget builds are refused (engine falls back to scan) and
    unloading a segment returns its bytes to the budget."""
    from pinot_tpu.segment import invindex as ii
    from pinot_tpu.server.datamanager import SegmentDataManager

    seg = synthetic_lineitem_segment(3000, seed=31, name="bud0")
    monkeypatch.setattr(ii, "_postings_bytes", 0)
    monkeypatch.setenv("PINOT_TPU_INVINDEX_BUDGET_BYTES", "64")  # tiny
    assert inverted_index(seg, "l_extendedprice") is None
    cache = getattr(seg, "_inv_cache")
    refusal = cache["l_extendedprice"]
    assert refusal[0] == "refused"  # cached: no per-query rebuild
    assert inverted_index(seg, "l_extendedprice") is None
    assert cache["l_extendedprice"] is refusal  # same epoch: not retried

    seg2 = synthetic_lineitem_segment(3000, seed=32, name="bud1")
    monkeypatch.setenv("PINOT_TPU_INVINDEX_BUDGET_BYTES", str(64 << 20))
    idx = inverted_index(seg2, "l_extendedprice")
    assert idx is not None
    assert ii.postings_bytes_in_use() >= idx.nbytes
    sdm = SegmentDataManager(seg2)
    assert sdm.release() == 0  # owner ref dropped -> postings freed
    assert ii.postings_bytes_in_use() == 0

    # the release bumped the epoch: the earlier refusal re-evaluates and
    # (budget is now ample) the index builds
    assert inverted_index(seg, "l_extendedprice") is not None


def test_concurrent_index_builds_account_once(monkeypatch):
    """Race regression: concurrent cold builds of the same (segment,
    column) must account postings bytes exactly once — double-counting
    would eventually refuse all future builds."""
    import threading

    from pinot_tpu.segment import invindex as ii

    seg = synthetic_lineitem_segment(20000, seed=44, name="race0")
    monkeypatch.setattr(ii, "_postings_bytes", 0)
    monkeypatch.setenv("PINOT_TPU_INVINDEX_BUDGET_BYTES", str(64 << 20))
    results = []
    barrier = threading.Barrier(8)

    def hit():
        barrier.wait()
        results.append(inverted_index(seg, "l_extendedprice"))

    threads = [threading.Thread(target=hit) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(r is not None for r in results)
    cached = getattr(seg, "_inv_cache")["l_extendedprice"]
    assert all(r is cached for r in results)  # one winning index
    assert ii.postings_bytes_in_use() == cached.nbytes  # accounted ONCE
