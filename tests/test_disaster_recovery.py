"""Disaster-recovery plane (ISSUE 20): journaled metadata, torn-write
crash battery, deep-store scrubbing, and full cluster restore.

The reference survives a controller loss because metadata lives in
ZooKeeper's transaction log + snapshots and segments in the deep store.
Our analogs — the CRC-framed ``MetadataJournal`` behind the
``PropertyStore`` and the ``tools/backup.py`` archive path — must keep
the same promises:

- a crash at ANY byte offset of a journal append or record write is
  recoverable (torn tail truncated, never fatal);
- replay is idempotent across a crash between snapshot and log
  truncation;
- a garbled record file heals from the journal (or surfaces as a typed
  ``CorruptRecordError`` with the damage quarantined aside);
- a backup taken while serving restores byte-for-byte, with epoch
  fencing still rejecting pre-disaster zombie writers;
- a corrupt deep-store copy is detected and re-replicated from a live
  server (scrubber), and CRC-failing fetches report the store suspect.
"""
from __future__ import annotations

import json
import os
import shutil
import tarfile
import threading

import pytest

from pinot_tpu.controller.journal import MetadataJournal, apply_op
from pinot_tpu.controller.property_store import CorruptRecordError, PropertyStore

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


# ------------------------------------------------------------ journal


def test_journal_append_recover_roundtrip(tmp_path):
    j = MetadataJournal(str(tmp_path / "j"), fsync=False)
    assert j.recover() == {}
    j.append({"op": "put", "ns": "tables", "key": "t1", "record": {"a": 1}})
    j.append({"op": "put", "ns": "tables", "key": "t2", "record": {"b": 2}})
    j.append({"op": "delete", "ns": "tables", "key": "t1"})
    j.append({"op": "put", "ns": "cluster", "key": "epoch", "record": {"epoch": 3}})
    j.close()

    j2 = MetadataJournal(str(tmp_path / "j"), fsync=False)
    state = j2.recover()
    assert state == {
        "tables": {"t2": {"b": 2}},
        "cluster": {"epoch": {"epoch": 3}},
    }
    assert j2.seq == 4  # appends continue past the recovered seq
    assert j2.append({"op": "delete", "ns": "cluster", "key": "epoch"}) == 5


def test_journal_torn_tail_battery(tmp_path):
    """Truncate the log at EVERY byte offset: recovery must never raise
    and must yield exactly the ops whose frames survived whole."""
    j = MetadataJournal(str(tmp_path / "j"), fsync=False)
    frame_ends = []
    for i in range(5):
        j.append({"op": "put", "ns": "ns", "key": f"k{i}", "record": {"v": i}})
        j.close()  # flush the fd so the size below is the true frame end
        frame_ends.append(os.path.getsize(j.log_path))
    full = open(j.log_path, "rb").read()

    for cut in range(len(full) + 1):
        d = tmp_path / f"cut{cut}"
        jdir = d / "j"
        os.makedirs(jdir)
        with open(jdir / "journal.log", "wb") as f:
            f.write(full[:cut])
        state = MetadataJournal(str(jdir), fsync=False).recover()
        whole = sum(1 for end in frame_ends if end <= cut)
        assert state.get("ns", {}) == {
            f"k{i}": {"v": i} for i in range(whole)
        }, f"cut at {cut}"
        # the torn remainder was truncated off, so a SECOND recovery
        # sees a clean log ending at the last whole frame
        assert os.path.getsize(jdir / "journal.log") == (
            frame_ends[whole - 1] if whole else 0
        )


def test_journal_garbage_tail_and_bit_flip(tmp_path):
    """Non-truncation damage: flipped bytes inside the last frame, or
    pure garbage appended — replay stops at the last good frame."""
    j = MetadataJournal(str(tmp_path / "j"), fsync=False)
    j.append({"op": "put", "ns": "ns", "key": "good", "record": {"v": 1}})
    j.close()
    keep = os.path.getsize(j.log_path)
    j2 = MetadataJournal(str(tmp_path / "j"), fsync=False)
    j2.recover()
    j2.append({"op": "put", "ns": "ns", "key": "bad", "record": {"v": 2}})
    j2.close()
    with open(j2.log_path, "r+b") as f:  # flip a payload byte of frame 2
        f.seek(keep + 10)
        b = f.read(1)
        f.seek(keep + 10)
        f.write(bytes([b[0] ^ 0xFF]))
    state = MetadataJournal(str(tmp_path / "j"), fsync=False).recover()
    assert state == {"ns": {"good": {"v": 1}}}

    with open(tmp_path / "j" / "journal.log", "ab") as f:
        f.write(b"\xff" * 37)  # garbage tail (absurd length word)
    state = MetadataJournal(str(tmp_path / "j"), fsync=False).recover()
    assert state == {"ns": {"good": {"v": 1}}}


def test_journal_snapshot_replay_idempotent_across_crash(tmp_path):
    """Crash between the snapshot replace and the log truncate: the
    snapshot says seq N while the log still holds frames 1..N — replay
    must skip them (seq <= snapshot.seq), not double-apply."""
    j = MetadataJournal(str(tmp_path / "j"), fsync=False)
    for i in range(3):
        j.append({"op": "put", "ns": "ns", "key": f"k{i}", "record": {"v": i}})
    j.append({"op": "delete", "ns": "ns", "key": "k0"})
    j.close()
    log_bytes = open(j.log_path, "rb").read()
    j2 = MetadataJournal(str(tmp_path / "j"), fsync=False)
    state = j2.recover()
    j2.write_snapshot(state)
    # simulate the crash: the pre-snapshot log reappears in full
    with open(j2.log_path, "wb") as f:
        f.write(log_bytes)
    recovered = MetadataJournal(str(tmp_path / "j"), fsync=False).recover()
    assert recovered == state == {"ns": {"k1": {"v": 1}, "k2": {"v": 2}}}
    # delete of k0 replayed on top of a snapshot that already folded it
    # in would be a no-op; a REPLAYED put of k0 would be the bug
    assert "k0" not in recovered["ns"]


def test_journal_corrupt_snapshot_quarantined(tmp_path):
    events = []
    j = MetadataJournal(str(tmp_path / "j"), fsync=False, on_event=events.append)
    j.append({"op": "put", "ns": "ns", "key": "k", "record": {"v": 9}})
    j.close()
    with open(j.snapshot_path, "w") as f:
        f.write("{not json")
    state = MetadataJournal(
        str(tmp_path / "j"), fsync=False, on_event=events.append
    ).recover()
    assert state == {"ns": {"k": {"v": 9}}}  # journal alone recovers
    assert "corruptSnapshot" in events
    assert any(".corrupt." in fn for fn in os.listdir(tmp_path / "j"))


# ----------------------------------------------------- property store


def test_property_store_kill_restart_mid_write(tmp_path):
    """Crash-at-every-offset at the PropertyStore level: commit some
    puts, tear the journal tail at arbitrary points, reopen — every
    committed record must come back, reads must never crash."""
    d = str(tmp_path / "ps")
    ps = PropertyStore(d)
    for i in range(6):
        ps.put("tables", f"t{i}", {"i": i})
    ps.delete("tables", "t0")
    ps.close()
    log = os.path.join(d, ".journal", "journal.log")
    full_size = os.path.getsize(log)

    for cut in range(0, full_size + 1, max(1, full_size // 23)):
        d2 = str(tmp_path / f"ps_cut{cut}")
        shutil.copytree(d, d2)
        with open(os.path.join(d2, ".journal", "journal.log"), "r+b") as f:
            f.truncate(cut)
        ps2 = PropertyStore(d2)
        # mirror files survive the torn journal, so every committed
        # record is still readable whatever the cut
        for i in range(1, 6):
            assert ps2.get("tables", f"t{i}") == {"i": i}
        ps2.close()


def test_record_corruption_heals_from_journal(tmp_path):
    ps = PropertyStore(str(tmp_path / "ps"))
    ps.put("schemas", "s1", {"cols": [1, 2, 3]})
    path = ps._path("schemas", "s1")
    with open(path, "w") as f:
        f.write('{"cols": [1,')  # torn mirror write
    assert ps.get("schemas", "s1") == {"cols": [1, 2, 3]}  # healed
    assert json.load(open(path)) == {"cols": [1, 2, 3]}  # rewritten
    ns_dir = os.path.dirname(path)
    assert any(".corrupt." in fn for fn in os.listdir(ns_dir))  # quarantined
    assert ps.metrics.meter("durability.recordsHealed").count >= 1
    assert ps.metrics.meter("durability.corruptRecords").count >= 1
    # a DELETED mirror file also heals (restore path)
    os.unlink(path)
    assert ps.get("schemas", "s1") == {"cols": [1, 2, 3]}
    ps.close()


def test_unjournaled_corrupt_record_raises_typed_error(tmp_path):
    ps = PropertyStore(str(tmp_path / "ps"))
    ps.put("tables", "anchor", {"x": 1})  # materialize the ns dir
    rogue = os.path.join(os.path.dirname(ps._path("tables", "anchor")), "rogue.json")
    with open(rogue, "w") as f:
        f.write("not json at all")
    with pytest.raises(CorruptRecordError) as ei:
        ps.get("tables", "rogue")
    assert ei.value.namespace == "tables" and ei.value.key == "rogue"
    assert not os.path.exists(rogue)  # quarantined aside, not left in place
    ns_dir = os.path.dirname(rogue)
    assert any(fn.startswith("rogue.json.corrupt.") for fn in os.listdir(ns_dir))
    assert "rogue" not in ps.list_keys("tables")
    ps.close()


def test_snapshot_while_mutating_consistent(tmp_path):
    """snapshot_now racing a writer thread: a reopened store must see
    every record the writer committed, with no torn/partial state."""
    d = str(tmp_path / "ps")
    ps = PropertyStore(d)
    stop = threading.Event()
    written = []

    def writer():
        i = 0
        while not stop.is_set():
            ps.put("segments/t", f"seg{i}", {"n": i})
            written.append(i)
            i += 1

    t = threading.Thread(target=writer)
    t.start()
    try:
        for _ in range(12):
            ps.snapshot_now()
    finally:
        stop.set()
        t.join()
    ps.close()
    ps2 = PropertyStore(d)
    for i in written:
        assert ps2.get("segments/t", f"seg{i}") == {"n": i}
    ps2.close()


def test_epoch_claims_journaled_mirror_loss_survivable(tmp_path):
    """Wipe every mirror file (keep only the journal): a reopened store
    recovers records AND the epoch, so fencing still rejects the old
    incarnation — the restore-from-journal invariant."""
    from pinot_tpu.common.fencing import StaleEpochError

    d = str(tmp_path / "ps")
    ps_a = PropertyStore(d)
    assert ps_a.claim_epoch() == 1
    ps_a.put("tables", "t", {"kept": True})
    ps_a.snapshot_now()
    ps_a.put("tables", "t2", {"post-snapshot": True})
    # destroy every record mirror; only .journal survives
    for entry in os.listdir(d):
        if entry in (".journal", ".fence.lock"):
            continue
        full = os.path.join(d, entry)
        shutil.rmtree(full) if os.path.isdir(full) else os.unlink(full)

    ps_b = PropertyStore(d)
    assert ps_b.get("tables", "t") == {"kept": True}
    assert ps_b.get("tables", "t2") == {"post-snapshot": True}
    assert ps_b.stored_epoch() == 1
    assert ps_b.claim_epoch() == 2
    with pytest.raises(StaleEpochError):
        ps_a.put("tables", "zombie", {"x": 1})
    ps_a.close()
    ps_b.close()


# ---------------------------------------------------- backup/restore


def _populated_data_dir(root):
    from pinot_tpu.controller.store import SegmentStore
    from pinot_tpu.segment.format import write_segment
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment

    data_dir = os.path.join(root, "cluster")
    ps = PropertyStore(os.path.join(data_dir, "property_store"))
    ps.claim_epoch()
    ps.put("schemas", "s", {"dims": ["a"]})
    ps.put("tables", "t_OFFLINE", {"replication": 2})
    ps.put("idealstates", "t_OFFLINE", {"seg0": {"server0": "ONLINE"}})
    ps.put("segments/t_OFFLINE", "seg0", {"crc": 123})
    store = SegmentStore(os.path.join(data_dir, "segments"))
    seg = synthetic_lineitem_segment(200, seed=7, name="seg0")
    write_segment(seg, store.segment_dir("t_OFFLINE", "seg0"))
    return data_dir, ps, store


def test_backup_restore_roundtrip_equality(tmp_path):
    from pinot_tpu.tools.backup import create_backup, restore_backup

    data_dir, ps, store = _populated_data_dir(str(tmp_path))
    archive = str(tmp_path / "b.tar.gz")
    info = create_backup(data_dir, archive)
    assert info["segments"] == 1 and info["epoch"] == 1
    assert os.path.exists(archive)

    # restore into a SECOND data dir that has only the deep store
    # (archive + deep store alone rebuild the cluster)
    data_dir2 = str(tmp_path / "cluster2")
    shutil.copytree(
        os.path.join(data_dir, "segments"), os.path.join(data_dir2, "segments")
    )
    out = restore_backup(archive, data_dir2)
    assert out["restored"] and out["segmentsVerified"] == 1
    assert out["segmentsMissing"] == [] and out["segmentsCorrupt"] == []
    ps2 = PropertyStore(os.path.join(data_dir2, "property_store"))
    for ns, key in (
        ("schemas", "s"),
        ("tables", "t_OFFLINE"),
        ("idealstates", "t_OFFLINE"),
        ("segments/t_OFFLINE", "seg0"),
    ):
        assert ps2.get(ns, key) == ps.get(ns, key), (ns, key)
    assert ps2.stored_epoch() == 1  # fencing token restored
    ps.close()
    ps2.close()


def test_restore_refuses_nonempty_and_reports_damage(tmp_path):
    from pinot_tpu.segment.format import SEGMENT_FILE_NAME
    from pinot_tpu.tools.backup import create_backup, restore_backup

    data_dir, ps, store = _populated_data_dir(str(tmp_path))
    archive = str(tmp_path / "b.tar.gz")
    create_backup(data_dir, archive)
    ps.close()
    with pytest.raises(FileExistsError):
        restore_backup(archive, data_dir)  # live store present, no overwrite
    # damage the deep store, then restore with overwrite: damage is
    # REPORTED (scrubber's job to heal), never fatal
    seg_path = store.segment_file_path("t_OFFLINE", "seg0")
    with open(seg_path, "r+b") as f:
        f.seek(-8, os.SEEK_END)
        f.write(b"\x00" * 8)
    out = restore_backup(archive, data_dir, overwrite=True)
    assert out["segmentsCorrupt"] == ["t_OFFLINE/seg0"]
    os.unlink(seg_path)
    out = restore_backup(archive, data_dir, overwrite=True)
    assert out["segmentsMissing"] == ["t_OFFLINE/seg0"]
    assert SEGMENT_FILE_NAME  # silence linters about the unused import


def test_restore_rejects_traversal_archive(tmp_path):
    from pinot_tpu.tools.backup import restore_backup

    evil = str(tmp_path / "evil.tar.gz")
    payload = tmp_path / "x"
    payload.write_text("boom")
    with tarfile.open(evil, "w:gz") as tar:
        tar.add(str(payload), arcname="../../escape")
    with pytest.raises(ValueError, match="unsafe archive member"):
        restore_backup(evil, str(tmp_path / "out"))


# -------------------------------------------- scrubbing & suspects


class _NoTableResources:
    def tables(self):
        return []

    def get_ideal_state(self, table):
        return {}

    def get_segment_metadata(self, table, segment):
        return {}


def test_scrubber_budget_denied_requeues_suspect(tmp_path):
    from pinot_tpu.controller.managers import DeepStoreScrubber
    from pinot_tpu.utils.audit import SamplerBudget

    scrub = DeepStoreScrubber(
        _NoTableResources(), store=None, budget=SamplerBudget(per_s=0.0)
    )
    scrub.report_suspect("t", "seg0", source="server1")
    scrub.run_once()
    snap = scrub.snapshot()
    assert snap["budgetDenied"] == 1
    assert snap["copiesChecked"] == 0
    # the server-reported suspect was requeued, not dropped
    assert snap["suspectsPending"] == 1


def test_scrubber_detects_and_repairs_from_donor(tmp_path):
    """Unit twin of the harness scrub leg: seed rot into the store
    copy; the scrubber detects it and re-replicates verified bytes via
    ``copy_fn`` from a 'server' holding a good copy."""
    from pinot_tpu.controller.managers import DeepStoreScrubber
    from pinot_tpu.controller.store import SegmentStore
    from pinot_tpu.segment.format import SEGMENT_FILE_NAME, write_segment
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment
    from pinot_tpu.utils.audit import SamplerBudget

    store = SegmentStore(str(tmp_path / "segments"))
    seg = synthetic_lineitem_segment(300, seed=11, name="seg0")
    # stamp a verifiable byte-level claim (the builder/commit path does
    # this; synthetic segments skip it and would pass CRC trivially)
    seg.metadata.custom["dataCrc"] = True
    seg.metadata.crc = seg.compute_crc()
    write_segment(seg, store.segment_dir("t_OFFLINE", "seg0"))
    good_bytes = open(store.segment_file_path("t_OFFLINE", "seg0"), "rb").read()
    with open(store.segment_file_path("t_OFFLINE", "seg0"), "r+b") as f:
        f.seek(-16, os.SEEK_END)
        f.write(b"\xde\xad\xbe\xef" * 4)

    class _Resources(_NoTableResources):
        def tables(self):
            return ["t_OFFLINE"]

        def get_ideal_state(self, table):
            return {"seg0": {"server0": "ONLINE"}}

        def get_external_view(self, table):
            return {"seg0": {"server0": "ONLINE"}}

        def instances_snapshot(self):
            class _I:
                name, url, role, alive = "server0", "inproc://server0", "server", True

            return [_I()]

    scrub = DeepStoreScrubber(
        _Resources(),
        store,
        budget=SamplerBudget(per_s=1000.0, burst=100.0),
        copy_fn=lambda name, url, table, segment: good_bytes,
    )
    scrub.run_once()
    snap = scrub.snapshot()
    assert snap["corruptCopies"] == 1 and snap["repairs"] == 1, snap
    assert snap["evidence"][0]["repairedFrom"] == "server0"
    store.verify_copy("t_OFFLINE", "seg0")  # healed copy passes CRC
    assert SEGMENT_FILE_NAME


def test_fetch_failing_crc_reports_store_suspect(tmp_path):
    from pinot_tpu.segment.fetcher import SegmentFetcherFactory
    from pinot_tpu.segment.format import SegmentIntegrityError

    src = tmp_path / "rotten"
    src.write_bytes(b"this is not a segment file")
    fired = []
    with pytest.raises(SegmentIntegrityError):
        SegmentFetcherFactory().fetch(
            str(src),
            str(tmp_path / "dest.pnt"),
            expected_crc=42,
            suspect_cb=lambda uri, exc: fired.append((uri, exc)),
        )
    assert fired and fired[0][0] == str(src)
    assert isinstance(fired[0][1], SegmentIntegrityError)
    assert not os.path.exists(tmp_path / "dest.pnt")  # bad bytes not installed


# ------------------------------------------------- perf gate (dr kind)


def _dr_doc():
    return {
        "metric": "dr_restore_first_query_s",
        "platform": "cpu",
        "num_segments": 6,
        "clients": 3,
        "value": 0.3,
        "backup": {"backupSeconds": 0.05},
        "restore": {"restoreToFirstQuerySeconds": 0.3, "byteIdentical": True},
        "scrub": {"okQpsRatio": 1.0, "detected": True, "repaired": True},
    }


def test_perf_gate_dr_kind():
    from pinot_tpu.tools.perf_gate import _doc_kind, compare

    base = _dr_doc()
    assert _doc_kind(base) == "dr"
    assert compare(base, json.loads(json.dumps(base)))["verdict"] == "pass"

    broken = _dr_doc()
    broken["restore"]["byteIdentical"] = False
    broken["scrub"]["repaired"] = False
    out = compare(base, broken)
    assert out["verdict"] == "fail"
    failed = {m["metric"] for m in out["metrics"] if not m["ok"]}
    assert failed == {"restore.byteIdentical", "scrub.repaired"}

    slow = _dr_doc()
    slow["value"] = slow["restore"]["restoreToFirstQuerySeconds"] = 30.0
    assert compare(base, slow)["verdict"] == "fail"  # order-of-magnitude rot

    other_kind = dict(_dr_doc(), metric="audit_overhead_ratio")
    assert compare(base, other_kind)["verdict"] == "skipped"


def test_committed_dr_artifact_gates_itself():
    from pinot_tpu.tools.perf_gate import compare, load_bench

    path = os.path.join(os.path.dirname(__file__), "..", "DR_r20.json")
    doc = load_bench(path)
    out = compare(doc, json.loads(json.dumps(doc)))
    assert out["verdict"] == "pass" and out["compared"] >= 7


# --------------------------------------------------- chaos twin (e2e)


def test_disaster_recovery_scenario_chaos_twin(tmp_path):
    """Tier-1 twin of ``--scenario disaster-recovery``: consistent
    online backup under load, seeded store-copy rot scrubbed + repaired
    from a live server, then the property store DESTROYED mid-load and
    the cluster restored from archive + deep store — byte-identical
    answers, drain flag + fencing preserved, realtime resumes from the
    committed offset with zero lost/duplicate rows, ZERO failed
    queries throughout."""
    from pinot_tpu.tools.cluster_harness import run_disaster_recovery_scenario

    res = run_disaster_recovery_scenario(
        window_s=0.3, data_dir=str(tmp_path)
    )
    assert res["failedQueries"] == 0, res
    assert res["restore"]["byteIdentical"]
    assert res["restore"]["drainFlagPreserved"]
    assert res["restore"]["fencingPreserved"]
    assert res["restore"]["rtCommittedPreserved"] and res["restore"]["rtResumed"]
    assert res["scrub"]["detected"] and res["scrub"]["repaired"]
    assert res["restore"]["restoreToFirstQuerySeconds"] < 30.0
