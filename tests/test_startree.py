"""Star-tree tests: build, eligibility, traversal correctness vs oracle,
docs-scanned reduction, persistence, executor routing
(the StarTreeClusterIntegrationTest analog: star-tree answers must equal
non-star-tree answers)."""
import numpy as np
import pytest

from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.engine.executor import QueryExecutor
from pinot_tpu.engine.reduce import reduce_to_response
from pinot_tpu.pql import optimize_request, parse_pql
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.segment.format import read_segment, write_segment
from pinot_tpu.startree import (
    STAR,
    StarTreeBuilderConfig,
    build_star_tree,
    execute_star_tree,
    is_fit_for_star_tree,
)
from pinot_tpu.tools.datagen import random_rows
from pinot_tpu.tools.scan_engine import ScanQueryProcessor

SCHEMA = Schema(
    "st",
    dimensions=[
        FieldSpec("d1", DataType.STRING),
        FieldSpec("d2", DataType.STRING),
        FieldSpec("d3", DataType.INT),
    ],
    metrics=[
        FieldSpec("m1", DataType.INT, FieldType.METRIC),
        FieldSpec("m2", DataType.DOUBLE, FieldType.METRIC),
    ],
)


@pytest.fixture(scope="module")
def data():
    rows = random_rows(SCHEMA, 2000, seed=31, cardinality=8)
    seg = build_segment(SCHEMA, rows, "st", "stseg")
    build_star_tree(seg, SCHEMA, StarTreeBuilderConfig(max_leaf_records=10))
    oracle = ScanQueryProcessor(SCHEMA, rows)
    return rows, seg, oracle


STAR_QUERIES = [
    "SELECT sum(m1), sum(m2) FROM st",
    "SELECT count(*) FROM st",
    "SELECT sum(m1) FROM st WHERE d1 = '{d1v}'",
    "SELECT sum(m2), count(*) FROM st WHERE d1 = '{d1v}' AND d2 = '{d2v}'",
    "SELECT sum(m1) FROM st WHERE d1 IN ('{d1v}', '{d1w}')",
    "SELECT sum(m1) FROM st GROUP BY d2 TOP 50",
    "SELECT count(*), avg(m2) FROM st WHERE d2 = '{d2v}' GROUP BY d1 TOP 50",
    "SELECT sum(m1) FROM st GROUP BY d1, d2 TOP 1000",
    # RANGE on split dimensions routes to the cube (contiguous dictId
    # interval; StarTreeIndexOperator.java:53 mixed-filter parity)
    "SELECT sum(m1), count(*) FROM st WHERE d3 <= '{d3v}'",
    "SELECT sum(m2) FROM st WHERE d1 = '{d1v}' AND d3 > '{d3v}'",
    "SELECT count(*) FROM st WHERE d3 BETWEEN '{d3v}' AND '{d3w}' GROUP BY d1 TOP 50",
]


def _fill(q, rows):
    d3s = sorted(r["d3"] for r in rows)
    return q.format(
        d1v=rows[0]["d1"],
        d1w=rows[1]["d1"],
        d2v=rows[0]["d2"],
        d3v=d3s[len(d3s) // 3],
        d3w=d3s[2 * len(d3s) // 3],
    )


def _agg_close(a, b, tol=1e-6):
    """Numeric-tolerant compare: star-tree pre-sums in a different order,
    so the last float digit can differ."""
    if isinstance(a, dict) and isinstance(b, dict):
        return set(a) == set(b) and all(_agg_close(a[k], b[k], tol) for k in a)
    if isinstance(a, list) and isinstance(b, list):
        return len(a) == len(b) and all(_agg_close(x, y, tol) for x, y in zip(a, b))
    if isinstance(a, str) and isinstance(b, str):
        try:
            fa, fb = float(a), float(b)
            return abs(fa - fb) <= tol * max(1.0, abs(fa), abs(fb))
        except ValueError:
            return a == b
    return a == b


@pytest.mark.parametrize("template", STAR_QUERIES)
def test_star_tree_matches_oracle(data, template):
    rows, seg, oracle = data
    pql = _fill(template, rows)
    req = optimize_request(parse_pql(pql))
    assert is_fit_for_star_tree(req, seg), pql
    got = reduce_to_response(req, [execute_star_tree(seg, req)]).to_json()
    want = oracle.execute(optimize_request(parse_pql(pql))).to_json()
    assert _agg_close(got["aggregationResults"], want["aggregationResults"]), pql


def test_docs_scanned_collapses(data):
    rows, seg, _ = data
    req = parse_pql("SELECT sum(m1), sum(m2) FROM st")
    res = execute_star_tree(seg, req)
    # full-table SUM scans the fully-starred rows, not 2000 docs
    assert res.num_docs_scanned < 50
    assert res.total_docs == 2000


def test_not_eligible_falls_back(data):
    rows, seg, oracle = data
    # min / distinct / OR-shaped queries are not star-tree eligible
    # (ranges on split dims now are)
    for pql in [
        "SELECT min(m1) FROM st",
        "SELECT distinctcount(d1) FROM st",
        "SELECT sum(m1) FROM st WHERE d1 = 'x' OR d2 = 'y'",
    ]:
        req = optimize_request(parse_pql(pql))
        assert not is_fit_for_star_tree(req, seg), pql


def test_executor_routes_star_and_normal(data):
    rows, seg, oracle = data
    ex = QueryExecutor()
    # eligible -> star path (few docs scanned)
    req = parse_pql("SELECT sum(m1) FROM st")
    resp = reduce_to_response(req, [ex.execute([seg], req)])
    assert resp.num_docs_scanned < 50
    want = oracle.execute(parse_pql("SELECT sum(m1) FROM st"))
    assert resp.aggregation_results[0].value == want.aggregation_results[0].value

    # ineligible -> normal engine path (scans everything), still correct
    req2 = parse_pql("SELECT min(m1) FROM st")
    resp2 = reduce_to_response(req2, [ex.execute([seg], req2)])
    assert resp2.num_docs_scanned == 2000
    want2 = oracle.execute(parse_pql("SELECT min(m1) FROM st"))
    assert resp2.aggregation_results[0].value == want2.aggregation_results[0].value


def test_mixed_segments_merge(data):
    """One segment with star-tree + one without: partials must merge."""
    rows, seg, oracle = data
    rows2 = random_rows(SCHEMA, 500, seed=77, cardinality=8)
    seg2 = build_segment(SCHEMA, rows2, "st", "plain")  # no star tree
    ex = QueryExecutor()
    req = parse_pql("SELECT sum(m1), count(*) FROM st")
    resp = reduce_to_response(req, [ex.execute([seg, seg2], req)])
    both = ScanQueryProcessor(SCHEMA, rows + rows2)
    want = both.execute(parse_pql("SELECT sum(m1), count(*) FROM st"))
    assert resp.to_json()["aggregationResults"] == want.to_json()["aggregationResults"]
    assert resp.total_docs == 2500


def test_persistence_roundtrip(data, tmp_path):
    rows, seg, oracle = data
    write_segment(seg, str(tmp_path / "stseg"))
    loaded = read_segment(str(tmp_path / "stseg"))
    st = loaded.star_tree
    assert st.split_order == seg.star_tree.split_order
    np.testing.assert_array_equal(st.dims, seg.star_tree.dims)
    np.testing.assert_array_equal(st.counts, seg.star_tree.counts)

    pql = "SELECT sum(m1) FROM st GROUP BY d1 TOP 100"
    req = parse_pql(pql)
    got = reduce_to_response(req, [execute_star_tree(loaded, req)]).to_json()
    want = oracle.execute(parse_pql(pql)).to_json()
    assert got["aggregationResults"] == want["aggregationResults"]


def test_star_sentinel_rows_exist(data):
    _, seg, _ = data
    # star rows exist at the first split level and cover the whole table
    st = seg.star_tree
    level0_star = st.dims[:, 0] == STAR
    assert level0_star.sum() >= 1
    # the root's star child subtree aggregates every raw doc exactly once
    star_root = st.root.star_child
    assert star_root is not None
    assert st.counts[star_root.start : star_root.end].sum() == 2000


def test_builder_config_skip_star(data):
    rows, _, _ = data
    seg = build_segment(SCHEMA, rows, "st", "skipseg")
    build_star_tree(
        seg, SCHEMA, StarTreeBuilderConfig(max_leaf_records=10, skip_star_for_dims=["d1"])
    )
    lvl = seg.star_tree.split_order.index("d1")
    assert not np.any(seg.star_tree.dims[:, lvl] == STAR)


def test_hll_in_star_tree(tmp_path):
    """distinctcounthll answered from the cube's pre-merged registers
    (the HllConfig derived-column capability)."""
    schema = Schema(
        "sth",
        dimensions=[
            FieldSpec("dim", DataType.STRING),
            FieldSpec("member", DataType.INT),  # high-card counted column
        ],
        metrics=[FieldSpec("m", DataType.INT, FieldType.METRIC)],
    )
    rows = random_rows(schema, 3000, seed=5, cardinality=400)
    seg = build_segment(schema, rows, "sth", "hllseg")
    build_star_tree(
        seg,
        schema,
        StarTreeBuilderConfig(max_leaf_records=5, hll_columns=["member"]),
    )
    oracle = ScanQueryProcessor(schema, rows)
    ex = QueryExecutor()

    for pql in [
        "SELECT distinctcounthll(member) FROM sth",
        f"SELECT fasthll(member) FROM sth WHERE dim = '{rows[0]['dim']}'",
        "SELECT distinctcounthll(member), count(*) FROM sth GROUP BY dim TOP 100",
    ]:
        req = optimize_request(parse_pql(pql))
        assert is_fit_for_star_tree(req, seg), pql
        got = reduce_to_response(req, [execute_star_tree(seg, req)]).to_json()
        want = oracle.execute(optimize_request(parse_pql(pql))).to_json()
        assert got["aggregationResults"] == want["aggregationResults"], pql

    # full-table HLL comes from few pre-agg rows, not 3000 docs
    req = parse_pql("SELECT distinctcounthll(member) FROM sth")
    assert execute_star_tree(seg, req).num_docs_scanned < 100

    # persists + reloads
    write_segment(seg, str(tmp_path / "hllseg"))
    loaded = read_segment(str(tmp_path / "hllseg"))
    req = parse_pql("SELECT distinctcounthll(member) FROM sth")
    a = reduce_to_response(req, [execute_star_tree(loaded, req)]).to_json()
    b = oracle.execute(parse_pql("SELECT distinctcounthll(member) FROM sth")).to_json()
    assert a["aggregationResults"] == b["aggregationResults"]


def test_adevents_hll_cube_groupby_matches_engine():
    """The north-star HLL group-by answered from the star-tree cube
    (campaign split, HLL(user_id) pre-agg): identical to the engine
    path, independent of row count (NORTHSTAR_HLL.json startree
    entry)."""
    import json

    from pinot_tpu.startree.builder import StarTreeBuilderConfig, build_star_tree
    from pinot_tpu.tools.cluster_harness import single_server_broker
    from pinot_tpu.tools.datagen import adevents_schema, synthetic_adevents_segment

    segs = [
        synthetic_adevents_segment(
            60_000, seed=23 + i, name=f"ad{i}", user_card=5000, campaign_card=32
        )
        for i in range(2)
    ]
    cfg = StarTreeBuilderConfig(
        split_order=["campaign_id", "site_id"],
        hll_columns=["user_id"],
        max_leaf_records=16,
    )
    for s in segs:
        build_star_tree(s, adevents_schema(), cfg)
    broker = single_server_broker("adevents", segs)
    pql = "SELECT distinctcounthll(user_id), count(*) FROM adevents GROUP BY campaign_id TOP 5"
    with_tree = broker.handle_pql(pql)
    assert not with_tree.exceptions, with_tree.exceptions
    assert with_tree.num_docs_scanned < 120_000  # pre-agg rows, not raw rows
    for s in segs:
        s.star_tree = None
    engine = broker.handle_pql(pql)
    assert json.dumps(with_tree.to_json()["aggregationResults"], sort_keys=True) == \
        json.dumps(engine.to_json()["aggregationResults"], sort_keys=True)
