"""Tools tests: quickstarts, client library, query runner, admin
CreateSegment/ShowSegment, controller segment upload over HTTP."""
import json
import urllib.request

import pytest

from pinot_tpu.api.client import Connection, ConnectionFactory, PinotClientError
from pinot_tpu.broker.broker import BrokerHttpServer
from pinot_tpu.controller.controller import ControllerHttpServer
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.segment.format import SEGMENT_FILE_NAME, write_segment
from pinot_tpu.tools.datagen import baseball_rows, baseball_schema, make_test_schema, random_rows
from pinot_tpu.tools.query_runner import QueryRunner
from pinot_tpu.tools.quickstart import run_offline_quickstart, run_realtime_quickstart


def test_offline_quickstart():
    cluster = run_offline_quickstart(num_rows=2000, num_segments=3, verbose=False)
    resp = cluster.query("SELECT count(*) FROM baseballStats")
    assert resp.num_docs_scanned == 2000
    resp = cluster.query("SELECT sum(runs) FROM baseballStats GROUP BY playerName TOP 5")
    assert len(resp.aggregation_results[0].group_by_result) == 5
    cluster.stop()


def test_offline_quickstart_startree():
    cluster = run_offline_quickstart(num_rows=2000, num_segments=2, startree=True, verbose=False)
    resp = cluster.query("SELECT sum(runs), count(*) FROM baseballStats")
    assert int(resp.aggregation_results[1].value) == 2000
    # star-tree answers from pre-agg rows, far fewer than 2000
    assert resp.num_docs_scanned < 1000
    cluster.stop()


def test_realtime_quickstart():
    cluster = run_realtime_quickstart(num_events=1200, verbose=False)
    resp = cluster.query("SELECT count(*) FROM meetupRsvp")
    assert resp.num_docs_scanned == 1200
    cluster.stop()


def test_client_library():
    cluster = run_offline_quickstart(num_rows=500, num_segments=1, http=True, verbose=False)
    try:
        conn = ConnectionFactory.from_host_list([f"http://127.0.0.1:{cluster.http.port}"])
        rg = conn.execute("SELECT count(*) FROM baseballStats")
        rs = rg.get_result_set(0)
        assert rs.get_int(0) == 500
        assert rg.execution_stats["numDocsScanned"] == 500

        rg = conn.execute("SELECT sum(runs) FROM baseballStats GROUP BY teamID TOP 3")
        rs = rg.get_result_set(0)
        assert rs.kind == "groupby"
        assert rs.get_row_count() == 3
        assert len(rs.get_group_key(0)) == 1

        rg = conn.execute("SELECT playerName, runs FROM baseballStats LIMIT 4")
        rs = rg.get_result_set(0)
        assert rs.kind == "selection"
        assert rs.get_row_count() == 4
        assert rs.get_column_names() == ["playerName", "runs"]

        stmt = conn.prepare_statement("SELECT count(*) FROM baseballStats WHERE teamID = ?")
        stmt.set_string(0, "BOS")
        rg2 = stmt.execute()
        assert rg2.get_result_set(0).get_int(0) > 0
    finally:
        cluster.stop()


def test_query_runner_modes():
    calls = []

    def fake_query(pql):
        calls.append(pql)

    runner = QueryRunner(fake_query)
    rep = runner.single_thread(["q1", "q2"], rounds=3)
    assert rep.num_queries == 6 and rep.qps > 0
    rep = runner.multi_threads(["q1", "q2", "q3"], num_threads=2, rounds=2)
    assert rep.num_queries == 6
    assert rep.to_json()["p99Ms"] >= 0


def test_serving_curve_smoke():
    """The QPS-ladder serving-curve tool runs the mixed workload through
    a real broker and reports per-step latency + shed counts."""
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment
    from pinot_tpu.tools.serving_curve import run_curve

    segs = [synthetic_lineitem_segment(20000, seed=5, name="sc0")]
    doc = run_curve(segs, [4.0], duration_s=1.5)
    assert len(doc["steps"]) == 1
    step = doc["steps"][0]
    assert step["queries"] > 0
    assert step["errors"] == 0
    assert step["p99_ms"] >= step["p50_ms"] > 0


def test_serving_curve_two_tenant_smoke():
    """The two-tenant ladder drives tenant A past its quota while
    tenant B's closed loop stays clean, and records per-tenant shed /
    quota counters per step."""
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment
    from pinot_tpu.tools.serving_curve import run_two_tenant_ladder

    seg_a = [synthetic_lineitem_segment(15000, seed=6, name="ta0")]
    seg_b = [synthetic_lineitem_segment(15000, seed=7, name="tb0")]
    doc = run_two_tenant_ladder(
        seg_a, seg_b, [40.0], duration_s=1.5, quota_qps=4.0
    )
    assert len(doc["steps"]) == 1
    step = doc["steps"][0]
    assert step["a_offered_multiple"] == 10.0
    assert step["a_quota_rejects"] > 0  # A's overflow shed at the quota
    assert step["a_errors"] == 0  # ...and ONLY with typed errors
    assert step["b_errors"] == 0  # B untouched by A's flood
    assert step["b_p99_ms"] >= step["b_p50_ms"] > 0
    assert step["admission_sheds"]["shedQuota"] == step["a_quota_rejects"]


def test_admin_create_and_show_segment(tmp_path, capsys):
    from pinot_tpu.tools.admin import main

    schema = make_test_schema(with_mv=False)
    schema_file = tmp_path / "schema.json"
    schema_file.write_text(json.dumps(schema.to_json()))
    data_file = tmp_path / "data.jsonl"
    rows = random_rows(schema, 50, seed=1)
    data_file.write_text("\n".join(json.dumps(r) for r in rows))

    out_dir = tmp_path / "seg_out"
    main([
        "CreateSegment",
        "-schema-file", str(schema_file),
        "-data-file", str(data_file),
        "-table", "t",
        "-segment-name", "cli_seg",
        "-out-dir", str(out_dir),
    ])
    captured = capsys.readouterr()
    assert "50 docs" in captured.out

    main(["ShowSegment", "-segment-dir", str(out_dir)])
    captured = capsys.readouterr()
    assert '"segmentName": "cli_seg"' in captured.out


def test_http_segment_upload(tmp_path):
    from pinot_tpu.tools.cluster_harness import InProcessCluster

    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path / "ctrl"))
    schema = make_test_schema(with_mv=False)
    physical = cluster.add_offline_table(schema)
    http = ControllerHttpServer(cluster.controller)
    http.start()
    try:
        seg = build_segment(schema, random_rows(schema, 120, seed=3), physical, "up1")
        seg_dir = tmp_path / "up1"
        write_segment(seg, str(seg_dir))
        data = (seg_dir / SEGMENT_FILE_NAME).read_bytes()
        req = urllib.request.Request(
            f"http://127.0.0.1:{http.port}/segments/{physical}",
            data=data,
            headers={"Content-Type": "application/octet-stream"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        assert out["status"] == "ok" and out["servers"]
        assert cluster.query("SELECT count(*) FROM testTable").num_docs_scanned == 120
    finally:
        http.stop()
        cluster.stop()


def test_segment_converters_roundtrip(tmp_path):
    """Export a segment to CSV/JSONL and rebuild an identical segment
    from the export (the pinot-tools segment-converter contract)."""
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.segment.readers import read_csv, read_jsonl
    from pinot_tpu.tools.converters import segment_to_csv, segment_to_jsonl

    schema = make_test_schema(with_mv=True)
    rows = random_rows(schema, 150, seed=5)
    seg = build_segment(schema, rows, "t_OFFLINE", "conv")

    jl = str(tmp_path / "out.jsonl")
    assert segment_to_jsonl(seg, jl) == 150
    back = read_jsonl(jl, schema)
    seg2 = build_segment(schema, back, "t_OFFLINE", "conv2")
    assert seg2.num_docs == seg.num_docs
    assert seg2.rows() == seg.rows()

    cv = str(tmp_path / "out.csv")
    assert segment_to_csv(seg, cv) == 150
    back_csv = read_csv(cv, schema)
    seg3 = build_segment(schema, back_csv, "t_OFFLINE", "conv3")
    assert seg3.rows() == seg.rows()


def test_star_tree_viewer(tmp_path):
    from pinot_tpu.startree.builder import StarTreeBuilderConfig
    from pinot_tpu.tools.converters import star_tree_summary

    schema = baseball_schema()
    rows = baseball_rows(500, seed=9)
    seg = build_segment(
        schema, rows, "bb_OFFLINE", "st1",
        startree_config=StarTreeBuilderConfig(max_leaf_records=50),
    )
    summary = star_tree_summary(seg)
    assert summary["hasStarTree"]
    assert summary["splitOrder"]
    assert summary["numAggRecords"] > 0
    assert summary["numStarNodes"] > 0
    assert summary["numLeaves"] > 0
    assert summary["nodes"][0]["path"] == "(root)"
    # a plain segment reports no star tree
    plain = build_segment(schema, rows, "bb_OFFLINE", "plain1")
    assert star_tree_summary(plain) == {"hasStarTree": False}


def test_admin_convert_and_generate(tmp_path, capsys):
    from pinot_tpu.segment.format import write_segment
    from pinot_tpu.tools.admin import main as admin_main

    schema = make_test_schema(with_mv=False)
    schema_file = tmp_path / "schema.json"
    schema_file.write_text(json.dumps(schema.to_json()))

    out_data = tmp_path / "gen.jsonl"
    admin_main([
        "GenerateData", "-schema-file", str(schema_file),
        "-num-rows", "120", "-out-file", str(out_data),
    ])
    assert len(out_data.read_text().splitlines()) == 120

    seg_dir = tmp_path / "seg"
    admin_main([
        "CreateSegment", "-schema-file", str(schema_file),
        "-data-file", str(out_data), "-table", "testTable_OFFLINE",
        "-segment-name", "g1", "-out-dir", str(seg_dir),
    ])
    out_csv = tmp_path / "export.csv"
    admin_main([
        "ConvertSegment", "-segment-dir", str(seg_dir),
        "-format", "csv", "-out-file", str(out_csv),
    ])
    assert "exported 120 rows" in capsys.readouterr().out
    assert len(out_csv.read_text().splitlines()) == 121  # header + rows


def test_hybrid_quickstart():
    """Offline history + realtime tail on ONE logical table: the time
    boundary federates so overlap rows count exactly once
    (HybridQuickstart.java analog)."""
    from pinot_tpu.tools.quickstart import run_hybrid_quickstart

    cluster = run_hybrid_quickstart(num_offline=600, num_realtime=300, verbose=False)
    resp = cluster.query("SELECT count(*) FROM meetupRsvp")
    assert not resp.exceptions
    # 600 offline + 300 realtime past the boundary; the 100-row overlap
    # ingested on the realtime side is excluded by the boundary filter
    assert resp.num_docs_scanned == 900
    resp = cluster.query("SELECT sum(rsvp_count) FROM meetupRsvp GROUP BY group_city TOP 3")
    assert not resp.exceptions and resp.to_json()["aggregationResults"][0]["groupByResult"]


def test_filter_matrix_smoke():
    """The selectivity x clustering x path matrix runs all four tiers
    per cell, forces the postings path, and labels zonemap/bitsliced
    fallthrough so neither tier is credited with a scan's win."""
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment
    from pinot_tpu.tools.filter_matrix import PATHS, run_matrix

    segs = [synthetic_lineitem_segment(30000, seed=7, name="fm0")]
    doc = run_matrix(segs, reps=3)
    assert len(doc["matrix"]) == 10
    tiers = tuple(PATHS)
    assert tiers == ("invindex", "zonemap", "bitsliced", "fullscan")
    for row in doc["matrix"]:
        for path in tiers:
            assert row[f"{path}_p50_ms"] > 0
        assert isinstance(row["zonemap_engaged"], bool)
        assert isinstance(row["bitsliced_engaged"], bool)
        assert row["winner"] in tiers
        if row["winner"] == "zonemap":
            assert row["zonemap_engaged"]
        if row["winner"] == "bitsliced":
            assert row["bitsliced_engaged"]
    # the shuffled fusable cells really engage the bit-sliced kernels
    assert any(
        r["bitsliced_engaged"] for r in doc["matrix"] if r["shape"] == "shuffled"
    )
    assert set(doc["tier_wins"]) == set(tiers)
    assert sum(doc["tier_wins"].values()) == len(doc["matrix"])
    assert "bitsliced_midsel_wins" in doc and "num_segments" in doc
