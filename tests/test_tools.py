"""Tools tests: quickstarts, client library, query runner, admin
CreateSegment/ShowSegment, controller segment upload over HTTP."""
import json
import urllib.request

import pytest

from pinot_tpu.api.client import Connection, ConnectionFactory, PinotClientError
from pinot_tpu.broker.broker import BrokerHttpServer
from pinot_tpu.controller.controller import ControllerHttpServer
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.segment.format import SEGMENT_FILE_NAME, write_segment
from pinot_tpu.tools.datagen import baseball_rows, baseball_schema, make_test_schema, random_rows
from pinot_tpu.tools.query_runner import QueryRunner
from pinot_tpu.tools.quickstart import run_offline_quickstart, run_realtime_quickstart


def test_offline_quickstart():
    cluster = run_offline_quickstart(num_rows=2000, num_segments=3, verbose=False)
    resp = cluster.query("SELECT count(*) FROM baseballStats")
    assert resp.num_docs_scanned == 2000
    resp = cluster.query("SELECT sum(runs) FROM baseballStats GROUP BY playerName TOP 5")
    assert len(resp.aggregation_results[0].group_by_result) == 5
    cluster.stop()


def test_offline_quickstart_startree():
    cluster = run_offline_quickstart(num_rows=2000, num_segments=2, startree=True, verbose=False)
    resp = cluster.query("SELECT sum(runs), count(*) FROM baseballStats")
    assert int(resp.aggregation_results[1].value) == 2000
    # star-tree answers from pre-agg rows, far fewer than 2000
    assert resp.num_docs_scanned < 1000
    cluster.stop()


def test_realtime_quickstart():
    cluster = run_realtime_quickstart(num_events=1200, verbose=False)
    resp = cluster.query("SELECT count(*) FROM meetupRsvp")
    assert resp.num_docs_scanned == 1200
    cluster.stop()


def test_client_library():
    cluster = run_offline_quickstart(num_rows=500, num_segments=1, http=True, verbose=False)
    try:
        conn = ConnectionFactory.from_host_list([f"http://127.0.0.1:{cluster.http.port}"])
        rg = conn.execute("SELECT count(*) FROM baseballStats")
        rs = rg.get_result_set(0)
        assert rs.get_int(0) == 500
        assert rg.execution_stats["numDocsScanned"] == 500

        rg = conn.execute("SELECT sum(runs) FROM baseballStats GROUP BY teamID TOP 3")
        rs = rg.get_result_set(0)
        assert rs.kind == "groupby"
        assert rs.get_row_count() == 3
        assert len(rs.get_group_key(0)) == 1

        rg = conn.execute("SELECT playerName, runs FROM baseballStats LIMIT 4")
        rs = rg.get_result_set(0)
        assert rs.kind == "selection"
        assert rs.get_row_count() == 4
        assert rs.get_column_names() == ["playerName", "runs"]

        stmt = conn.prepare_statement("SELECT count(*) FROM baseballStats WHERE teamID = ?")
        stmt.set_string(0, "BOS")
        rg2 = stmt.execute()
        assert rg2.get_result_set(0).get_int(0) > 0
    finally:
        cluster.stop()


def test_query_runner_modes():
    calls = []

    def fake_query(pql):
        calls.append(pql)

    runner = QueryRunner(fake_query)
    rep = runner.single_thread(["q1", "q2"], rounds=3)
    assert rep.num_queries == 6 and rep.qps > 0
    rep = runner.multi_threads(["q1", "q2", "q3"], num_threads=2, rounds=2)
    assert rep.num_queries == 6
    assert rep.to_json()["p99Ms"] >= 0


def test_admin_create_and_show_segment(tmp_path, capsys):
    from pinot_tpu.tools.admin import main

    schema = make_test_schema(with_mv=False)
    schema_file = tmp_path / "schema.json"
    schema_file.write_text(json.dumps(schema.to_json()))
    data_file = tmp_path / "data.jsonl"
    rows = random_rows(schema, 50, seed=1)
    data_file.write_text("\n".join(json.dumps(r) for r in rows))

    out_dir = tmp_path / "seg_out"
    main([
        "CreateSegment",
        "-schema-file", str(schema_file),
        "-data-file", str(data_file),
        "-table", "t",
        "-segment-name", "cli_seg",
        "-out-dir", str(out_dir),
    ])
    captured = capsys.readouterr()
    assert "50 docs" in captured.out

    main(["ShowSegment", "-segment-dir", str(out_dir)])
    captured = capsys.readouterr()
    assert '"segmentName": "cli_seg"' in captured.out


def test_http_segment_upload(tmp_path):
    from pinot_tpu.tools.cluster_harness import InProcessCluster

    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path / "ctrl"))
    schema = make_test_schema(with_mv=False)
    physical = cluster.add_offline_table(schema)
    http = ControllerHttpServer(cluster.controller)
    http.start()
    try:
        seg = build_segment(schema, random_rows(schema, 120, seed=3), physical, "up1")
        seg_dir = tmp_path / "up1"
        write_segment(seg, str(seg_dir))
        data = (seg_dir / SEGMENT_FILE_NAME).read_bytes()
        req = urllib.request.Request(
            f"http://127.0.0.1:{http.port}/segments/{physical}",
            data=data,
            headers={"Content-Type": "application/octet-stream"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        assert out["status"] == "ok" and out["servers"]
        assert cluster.query("SELECT count(*) FROM testTable").num_docs_scanned == 120
    finally:
        http.stop()
        cluster.stop()
