"""Chaos test: real OS server processes, killed with POSIX signals.

The ChaosMonkeyIntegrationTest analog (``ChaosMonkeyIntegrationTest.java:41``,
kill via signals :76, consistency assertion :206): queries must degrade
to partial results with exceptions while a server is dead, and recover
fully once it restarts.
"""
import os
import signal
import subprocess
import sys
import time

import pytest

from pinot_tpu.broker.broker import BrokerRequestHandler
from pinot_tpu.broker.routing import RoutingTableProvider
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.segment.format import write_segment
from pinot_tpu.tools.datagen import make_test_schema, random_rows
from pinot_tpu.transport.tcp import TcpTransport

TABLE = "chaosTable_OFFLINE"


def _spawn_server(name, table, seg_dirs, repo_root):
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""  # no TPU tunnel in child processes
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "pinot_tpu.tools.run_server",
            "--name", name,
            "--table", table,
            "--segments", *seg_dirs,
        ],
        cwd=repo_root,
        env=env,
        stdout=subprocess.PIPE,
        text=True,
    )
    deadline = time.time() + 60
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line.startswith("READY"):
            return proc, int(line.split()[1])
    proc.kill()
    raise RuntimeError(f"server {name} did not become ready")


@pytest.mark.slow
def test_kill_and_restart_server(tmp_path):
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 400, seed=13)

    seg_dirs = {}
    for i, name in enumerate(["c0", "c1"]):
        seg = build_segment(schema, rows[i * 200 : (i + 1) * 200], TABLE, name)
        d = str(tmp_path / name)
        write_segment(seg, d)
        seg_dirs[name] = d

    procs = {}
    ports = {}
    try:
        procs["sA"], ports["sA"] = _spawn_server("sA", TABLE, [seg_dirs["c0"]], repo_root)
        procs["sB"], ports["sB"] = _spawn_server("sB", TABLE, [seg_dirs["c1"]], repo_root)

        routing = RoutingTableProvider()
        routing.update(TABLE, {"c0": {"sA": "ONLINE"}, "c1": {"sB": "ONLINE"}})
        broker = BrokerRequestHandler(
            TcpTransport(),
            {"sA": ("127.0.0.1", ports["sA"]), "sB": ("127.0.0.1", ports["sB"])},
            routing=routing,
            timeout_ms=30_000,
        )

        resp = broker.handle_pql("SELECT count(*) FROM chaosTable")
        assert resp.num_docs_scanned == 400
        assert not resp.exceptions

        # SIGKILL one server: partial results + an exception, no hang
        procs["sB"].send_signal(signal.SIGKILL)
        procs["sB"].wait(timeout=10)
        broker2 = BrokerRequestHandler(  # fresh transport (no pooled dead socket)
            TcpTransport(),
            {"sA": ("127.0.0.1", ports["sA"]), "sB": ("127.0.0.1", ports["sB"])},
            routing=routing,
            timeout_ms=8_000,
        )
        resp = broker2.handle_pql("SELECT count(*) FROM chaosTable")
        assert resp.num_docs_scanned == 200
        assert len(resp.exceptions) == 1
        assert resp.num_servers_responded == 1

        # restart on a fresh port; routing repoints; full recovery
        procs["sB2"], new_port = _spawn_server("sB", TABLE, [seg_dirs["c1"]], repo_root)
        broker2.set_server_address("sB", ("127.0.0.1", new_port))
        resp = broker2.handle_pql("SELECT count(*) FROM chaosTable")
        assert resp.num_docs_scanned == 400
        assert not resp.exceptions
    finally:
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()
