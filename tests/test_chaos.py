"""Chaos tests: fault-injected scatter-gather plus real killed processes.

Two tiers:

- Deterministic fault injection (``-m chaos``, fast, in tier-1):
  ``FaultInjectingTransport`` over in-process servers exercises replica
  failover, hedged requests, the circuit breaker, partial-response
  accounting, and deadline propagation without sleeping through real
  heartbeat windows or spawning processes.
- The ChaosMonkeyIntegrationTest analog (slow, opt-in): real OS server
  processes killed with POSIX signals (``ChaosMonkeyIntegrationTest.
  java:41``, kill via signals :76, consistency assertion :206).
"""
import os
import signal
import subprocess
import sys
import time

import pytest

from pinot_tpu.broker.broker import BrokerRequestHandler
from pinot_tpu.broker.health import ServerHealthTracker
from pinot_tpu.broker.routing import RoutingTableProvider
from pinot_tpu.common.faults import FaultInjectingTransport
from pinot_tpu.common.response import ErrorCode
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.segment.format import write_segment
from pinot_tpu.server.instance import ServerInstance
from pinot_tpu.tools.datagen import make_test_schema, random_rows
from pinot_tpu.transport.local import LocalTransport
from pinot_tpu.transport.tcp import TcpTransport

TABLE = "chaosTable_OFFLINE"
ADDR_A = ("sA", 0)
ADDR_B = ("sB", 0)


def _two_replica_cluster(**broker_kwargs):
    """Two in-process servers, each holding BOTH segments (replication
    2), behind a fault-injecting transport.  400 rows total."""
    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 400, seed=13)
    segs = {
        "c0": build_segment(schema, rows[:200], TABLE, "c0"),
        "c1": build_segment(schema, rows[200:], TABLE, "c1"),
    }
    transport = FaultInjectingTransport(LocalTransport(), seed=7)
    addresses = {"sA": ADDR_A, "sB": ADDR_B}
    for name, addr in addresses.items():
        inst = ServerInstance(name)
        for seg in segs.values():
            inst.add_segment(TABLE, seg)
        transport.inner.register(addr, inst.handle_request)
    routing = RoutingTableProvider(num_tables=1)
    routing.update(
        TABLE,
        {
            "c0": {"sA": "ONLINE", "sB": "ONLINE"},
            "c1": {"sA": "ONLINE", "sB": "ONLINE"},
        },
    )
    broker_kwargs.setdefault("timeout_ms", 10_000)
    broker_kwargs.setdefault("retry_backoff_ms", 1.0)
    broker = BrokerRequestHandler(transport, addresses, routing=routing, **broker_kwargs)
    return broker, transport


# ------------------------------------------------------- failover
@pytest.mark.chaos
def test_one_dead_replica_failover_completes():
    """Acceptance: killing one replica of a 2-replica table still yields
    a COMPLETE response — the dead server's segment set re-issues to the
    surviving replica instead of degrading the query."""
    broker, transport = _two_replica_cluster()
    transport.set_fault(ADDR_A, down=True)
    resp = broker.handle_pql("SELECT count(*) FROM chaosTable")
    assert resp.num_docs_scanned == 400
    assert resp.partial_response is False
    assert resp.num_segments_unserved == 0
    # recovered-by-failover attempts do NOT surface client exceptions
    assert not resp.exceptions
    assert resp.num_servers_responded == 1  # only sB answered
    # sA absorbed at least one failed attempt before the failover
    assert any(c.outcome != "ok" for c in transport.calls_to(ADDR_A)) or (
        transport.calls_to(ADDR_A) == []
    )


@pytest.mark.chaos
def test_all_replicas_dead_partial_within_deadline():
    """Acceptance: with every replica dead the query returns WITHIN the
    deadline, flagged partial, with the unserved-segment count."""
    broker, transport = _two_replica_cluster(timeout_ms=2_000)
    transport.set_fault(ADDR_A, down=True)
    transport.set_fault(ADDR_B, down=True)
    t0 = time.monotonic()
    resp = broker.handle_pql("SELECT count(*) FROM chaosTable")
    elapsed = time.monotonic() - t0
    assert elapsed < 2.0  # transport errors are instant; no deadline wait
    assert resp.partial_response is True
    assert resp.num_segments_unserved == 2
    assert resp.exceptions  # the failures are reported, not hidden
    assert resp.num_docs_scanned == 0
    assert resp.num_servers_responded == 0


@pytest.mark.chaos
def test_blackholed_replica_fails_over_within_deadline():
    """A server that accepts the request and never replies (no RST,
    just silence) must not burn the whole deadline: with an untried
    replica available the attempt is capped at half the remaining
    budget, surfaces as a transport timeout, and fails over in time."""
    broker, transport = _two_replica_cluster(timeout_ms=2_000)
    broker.routing.update(TABLE, {"c0": {"sA": "ONLINE", "sB": "ONLINE"}})
    primary = next(iter(broker.routing.find_servers(TABLE)))
    black_addr = ADDR_A if primary == "sA" else ADDR_B
    transport.set_fault(black_addr, blackhole=True)
    t0 = time.monotonic()
    resp = broker.handle_pql("SELECT count(*) FROM chaosTable")
    elapsed = time.monotonic() - t0
    assert resp.num_docs_scanned == 200  # complete, via the live replica
    assert resp.partial_response is False
    assert not resp.exceptions
    assert elapsed < 1.9  # failover happened BEFORE the 2s deadline


@pytest.mark.chaos
def test_transient_blip_heals_via_failover():
    """A single transient transport failure costs a retry, not data."""
    broker, transport = _two_replica_cluster()
    transport.set_fault(ADDR_A, fail_next=1)
    transport.set_fault(ADDR_B, fail_next=1)
    resp = broker.handle_pql("SELECT count(*) FROM chaosTable")
    assert resp.num_docs_scanned == 400
    assert resp.partial_response is False
    assert not resp.exceptions


@pytest.mark.chaos
def test_saturated_server_reply_fails_over():
    """A typed 210 (scheduler saturated) reply is retryable: the broker
    re-issues the segment set on the replica instead of surfacing it."""
    from pinot_tpu.common.datatable import serialize_result
    from pinot_tpu.engine.results import IntermediateResult

    broker, transport = _two_replica_cluster()

    def saturated(_payload: bytes) -> bytes:
        return serialize_result(
            IntermediateResult(
                exceptions=[(ErrorCode.SERVER_SCHEDULER_DOWN, "saturated")]
            )
        )

    transport.inner.register(ADDR_A, saturated)
    resp = broker.handle_pql("SELECT count(*) FROM chaosTable")
    assert resp.num_docs_scanned == 400
    assert resp.partial_response is False
    assert not resp.exceptions


# ------------------------------------------------------- hedging
@pytest.mark.chaos
def test_slow_server_hedge_wins_under_deadline():
    """Acceptance: a straggler replica triggers a hedged request to the
    other replica; the fast reply wins well before the straggler (and
    far before the query deadline)."""
    broker, transport = _two_replica_cluster(
        timeout_ms=10_000, hedge_delay_ms=50.0
    )
    # single segment so the whole query is one hedgeable batch
    broker.routing.update(TABLE, {"c0": {"sA": "ONLINE", "sB": "ONLINE"}})
    primary = next(iter(broker.routing.find_servers(TABLE)))
    slow_addr, fast_addr = (ADDR_A, ADDR_B) if primary == "sA" else (ADDR_B, ADDR_A)
    transport.set_fault(slow_addr, delay_s=2.0)
    t0 = time.monotonic()
    resp = broker.handle_pql("SELECT count(*) FROM chaosTable")
    elapsed = time.monotonic() - t0
    assert resp.num_docs_scanned == 200  # segment c0 only
    assert resp.partial_response is False
    assert resp.num_hedges >= 1
    assert elapsed < 1.5  # hedge beat the 2s straggler
    assert transport.calls_to(fast_addr)  # the hedge actually went out


@pytest.mark.chaos
def test_hedge_skipped_near_quota():
    """Hedging amplifies load; a table brushing its QPS quota must not
    double its own traffic."""
    broker, transport = _two_replica_cluster(
        timeout_ms=3_000, hedge_delay_ms=10.0, hedge_min_quota_headroom=2.0
    )
    # headroom is at most 1.0 < 2.0, so hedging is always suppressed
    transport.set_fault(ADDR_A, delay_s=0.3)
    transport.set_fault(ADDR_B, delay_s=0.3)
    resp = broker.handle_pql("SELECT count(*) FROM chaosTable")
    assert resp.num_docs_scanned == 400
    assert resp.num_hedges == 0


# ------------------------------------------------------- circuit breaker
@pytest.mark.chaos
def test_circuit_breaker_open_probe_close():
    clock = [0.0]
    h = ServerHealthTracker(failure_threshold=3, penalty_ms=1_000, clock=lambda: clock[0])
    for _ in range(2):
        h.record_failure("s1")
    assert h.is_healthy("s1")  # below threshold
    h.record_failure("s1")
    assert h.state_of("s1") == "OPEN"
    assert not h.is_healthy("s1")
    assert not h.allow_request("s1")
    clock[0] = 1.1  # past the penalty window -> HALF_OPEN, one probe
    assert h.allow_request("s1") is True
    assert h.allow_request("s1") is False  # second concurrent probe refused
    h.record_success("s1")
    assert h.state_of("s1") == "CLOSED"
    # a failed probe re-opens with a fresh window
    for _ in range(3):
        h.record_failure("s1")
    clock[0] = 2.3
    assert h.allow_request("s1") is True
    h.record_failure("s1")
    assert h.state_of("s1") == "OPEN"
    assert not h.allow_request("s1")


@pytest.mark.chaos
def test_probe_claim_is_a_lease_not_a_permanent_mark():
    """A half-open probe whose holder vanished (attempt cancelled at
    query end, reply never read) must not quarantine the server forever:
    the claim expires after one penalty window."""
    clock = [0.0]
    h = ServerHealthTracker(failure_threshold=1, penalty_ms=1_000, clock=lambda: clock[0])
    h.record_failure("s1")  # OPEN at t=0
    clock[0] = 1.1
    assert h.allow_request("s1") is True  # probe claimed...
    assert h.is_healthy("s1") is False  # ...others steered away
    # holder never reports back; lease expires one penalty window later
    clock[0] = 2.2
    assert h.is_healthy("s1") is True
    assert h.allow_request("s1") is True  # a fresh probe may go out


@pytest.mark.chaos
def test_routing_prefers_healthy_replicas():
    h = ServerHealthTracker(failure_threshold=1, penalty_ms=60_000)
    routing = RoutingTableProvider(num_tables=4)
    routing.update(
        TABLE,
        {
            "c0": {"sA": "ONLINE", "sB": "ONLINE"},
            "c1": {"sA": "ONLINE", "sB": "ONLINE"},
        },
    )
    h.record_failure("sA")  # penalty box
    for _ in range(20):
        cover = routing.find_servers(TABLE, health=h)
        assert set(cover) == {"sB"}, cover
    # alternates excludes the tried server even when unhealthy ones remain
    assignment, unserved = routing.alternates(TABLE, ["c0"], {"sB"}, health=h)
    assert assignment == {"sA": ["c0"]} and unserved == []
    assignment, unserved = routing.alternates(TABLE, ["c0"], {"sA", "sB"})
    assert assignment == {} and unserved == ["c0"]


@pytest.mark.chaos
def test_controller_death_event_reaches_health_tracker():
    """Heartbeat-miss -> set_instance_alive(False) must reach the broker
    circuit breaker through the SAME event that rebuilds routing."""
    from pinot_tpu.broker.starter import BrokerStarter
    from pinot_tpu.controller.resource_manager import ClusterResourceManager

    resources = ClusterResourceManager()
    transport = LocalTransport()
    broker = BrokerRequestHandler(transport, {})
    starter = BrokerStarter(broker, resources)
    starter.start()
    from pinot_tpu.controller.resource_manager import InstanceState

    resources.register_instance(InstanceState("sX", role="server"))
    resources.set_instance_alive("sX", False)
    assert broker.health.state_of("sX") == "OPEN"
    resources.set_instance_alive("sX", True)
    assert broker.health.state_of("sX") == "CLOSED"


# ------------------------------------------------------- deadline + validation
@pytest.mark.chaos
def test_scheduler_sheds_expired_deadline_work():
    """Deadline propagation: a query whose broker-sent budget expired
    while queued is abandoned at dequeue, never executed."""
    from pinot_tpu.server.scheduler import QueryAbandonedError, QueryScheduler

    sched = QueryScheduler(num_workers=1)
    ran = []
    with pytest.raises(QueryAbandonedError):
        sched.run(lambda: ran.append(1), timeout_s=10.0, deadline=time.monotonic() - 0.001)
    assert ran == []
    assert sched.abandoned_count == 1
    sched.shutdown()


@pytest.mark.chaos
def test_invalid_timeout_override_rejected():
    broker, _ = _two_replica_cluster()
    for bad in (-5, 0, float("nan")):
        resp = broker.handle_pql("SELECT count(*) FROM chaosTable", timeout_ms=bad)
        assert resp.exceptions
        assert resp.exceptions[0].error_code == ErrorCode.QUERY_VALIDATION
    # valid override still works
    resp = broker.handle_pql("SELECT count(*) FROM chaosTable", timeout_ms=5_000)
    assert not resp.exceptions and resp.num_docs_scanned == 400


@pytest.mark.chaos
@pytest.mark.slow
def test_flaky_link_soak():
    """Soak-style (opt-in via -m slow): a 50%-lossy link to one replica
    must not lose a single query — failover absorbs every seeded fault,
    and the circuit breaker steers steady-state traffic to the clean
    replica after enough consecutive failures."""
    broker, transport = _two_replica_cluster(retry_attempts=3)
    transport.set_fault(ADDR_A, error_rate=0.5)
    for _ in range(50):
        resp = broker.handle_pql("SELECT count(*) FROM chaosTable")
        assert resp.num_docs_scanned == 400
        assert resp.partial_response is False


@pytest.mark.chaos
def test_parse_timeout_contract():
    from pinot_tpu.broker.broker import InvalidTimeoutError, _parse_timeout

    assert _parse_timeout(None) is None
    assert _parse_timeout("") is None
    assert _parse_timeout("1500") == 1500.0
    assert _parse_timeout(250) == 250.0
    for junk in ("abc", "-1", "0", True, False, "inf", "nan", -3, 0):
        with pytest.raises(InvalidTimeoutError):
            _parse_timeout(junk)


def _spawn_server(name, table, seg_dirs, repo_root):
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""  # no TPU tunnel in child processes
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "pinot_tpu.tools.run_server",
            "--name", name,
            "--table", table,
            "--segments", *seg_dirs,
        ],
        cwd=repo_root,
        env=env,
        stdout=subprocess.PIPE,
        text=True,
    )
    deadline = time.time() + 60
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line.startswith("READY"):
            return proc, int(line.split()[1])
    proc.kill()
    raise RuntimeError(f"server {name} did not become ready")


@pytest.mark.chaos
@pytest.mark.slow
def test_kill_and_restart_server(tmp_path):
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, 400, seed=13)

    seg_dirs = {}
    for i, name in enumerate(["c0", "c1"]):
        seg = build_segment(schema, rows[i * 200 : (i + 1) * 200], TABLE, name)
        d = str(tmp_path / name)
        write_segment(seg, d)
        seg_dirs[name] = d

    procs = {}
    ports = {}
    try:
        procs["sA"], ports["sA"] = _spawn_server("sA", TABLE, [seg_dirs["c0"]], repo_root)
        procs["sB"], ports["sB"] = _spawn_server("sB", TABLE, [seg_dirs["c1"]], repo_root)

        routing = RoutingTableProvider()
        routing.update(TABLE, {"c0": {"sA": "ONLINE"}, "c1": {"sB": "ONLINE"}})
        broker = BrokerRequestHandler(
            TcpTransport(),
            {"sA": ("127.0.0.1", ports["sA"]), "sB": ("127.0.0.1", ports["sB"])},
            routing=routing,
            timeout_ms=30_000,
        )

        resp = broker.handle_pql("SELECT count(*) FROM chaosTable")
        assert resp.num_docs_scanned == 400
        assert not resp.exceptions

        # SIGKILL one server: partial results + an exception, no hang
        procs["sB"].send_signal(signal.SIGKILL)
        procs["sB"].wait(timeout=10)
        broker2 = BrokerRequestHandler(  # fresh transport (no pooled dead socket)
            TcpTransport(),
            {"sA": ("127.0.0.1", ports["sA"]), "sB": ("127.0.0.1", ports["sB"])},
            routing=routing,
            timeout_ms=8_000,
        )
        resp = broker2.handle_pql("SELECT count(*) FROM chaosTable")
        assert resp.num_docs_scanned == 200
        assert len(resp.exceptions) == 1
        assert resp.num_servers_responded == 1

        # restart on a fresh port; routing repoints; full recovery
        procs["sB2"], new_port = _spawn_server("sB", TABLE, [seg_dirs["c1"]], repo_root)
        broker2.set_server_address("sB", ("127.0.0.1", new_port))
        resp = broker2.handle_pql("SELECT count(*) FROM chaosTable")
        assert resp.num_docs_scanned == 400
        assert not resp.exceptions
    finally:
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()
