"""Pluggable segment fetchers (segment/fetcher.py):
SegmentFetcherFactory scheme dispatch, http retries, WebHDFS protocol
shape, custom-scheme registration, and the server load path resolving
a downloadUri (SegmentFetcherFactory.java + WebHdfsV1Client.java)."""
import http.server
import os
import threading

import pytest

from pinot_tpu.segment.fetcher import (
    HttpSegmentFetcher,
    LocalFileSegmentFetcher,
    SegmentFetcher,
    SegmentFetcherFactory,
    WebHdfsSegmentFetcher,
)


@pytest.fixture()
def http_server(tmp_path):
    state = {"fail_next": 0, "webhdfs_opens": []}

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if state["fail_next"] > 0:
                state["fail_next"] -= 1
                self.send_error(503)
                return
            if self.path.startswith("/webhdfs/v1/"):
                state["webhdfs_opens"].append(self.path)
                assert self.path.endswith("?op=OPEN")
                body = b"webhdfs-bytes"
            else:
                body = b"http-bytes:" + self.path.encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield srv.server_address, state
    finally:
        srv.shutdown()


def test_local_fetcher_variants(tmp_path):
    src = tmp_path / "seg.bin"
    src.write_bytes(b"segment-bytes")
    f = LocalFileSegmentFetcher()
    f.fetch(str(src), str(tmp_path / "out1"))
    f.fetch("file://" + str(src), str(tmp_path / "out2"))
    assert (tmp_path / "out1").read_bytes() == b"segment-bytes"
    assert (tmp_path / "out2").read_bytes() == b"segment-bytes"
    # a segment DIRECTORY resolves to its segment file
    from pinot_tpu.segment.format import SEGMENT_FILE_NAME

    d = tmp_path / "segdir"
    d.mkdir()
    (d / SEGMENT_FILE_NAME).write_bytes(b"dir-bytes")
    f.fetch("file://" + str(d), str(tmp_path / "out3"))
    assert (tmp_path / "out3").read_bytes() == b"dir-bytes"


def test_http_fetcher_with_retry(http_server, tmp_path):
    (host, port), state = http_server
    state["fail_next"] = 2  # two 503s, third attempt lands
    f = HttpSegmentFetcher(attempts=3)
    dest = tmp_path / "got"
    f.fetch(f"http://{host}:{port}/t/s/file", str(dest))
    assert dest.read_bytes() == b"http-bytes:/t/s/file"


def test_webhdfs_fetcher_protocol(http_server, tmp_path):
    (host, port), state = http_server
    dest = tmp_path / "got"
    WebHdfsSegmentFetcher().fetch(f"hdfs://{host}:{port}/data/seg1", str(dest))
    assert dest.read_bytes() == b"webhdfs-bytes"
    assert state["webhdfs_opens"] == ["/webhdfs/v1/data/seg1?op=OPEN"]


def test_factory_dispatch_and_register(http_server, tmp_path):
    (host, port), _ = http_server
    fac = SegmentFetcherFactory()
    src = tmp_path / "s"
    src.write_bytes(b"x")
    fac.fetch("file://" + str(src), str(tmp_path / "o1"))
    fac.fetch(f"http://{host}:{port}/x", str(tmp_path / "o2"))
    assert (tmp_path / "o2").read_bytes() == b"http-bytes:/x"

    class BlobFetcher(SegmentFetcher):
        def fetch(self, uri, dest_path):
            with open(dest_path, "wb") as f:
                f.write(b"blob:" + uri.encode())

    fac.register("s3", BlobFetcher())
    fac.fetch("s3://bucket/key", str(tmp_path / "o3"))
    assert (tmp_path / "o3").read_bytes() == b"blob:s3://bucket/key"

    with pytest.raises(ValueError, match="no segment fetcher"):
        fac.fetch("ftp://nope/x", str(tmp_path / "o4"))


def test_server_load_resolves_download_uri(tmp_path):
    """In-process server load path with ONLY a downloadUri (no local
    dir): the factory fetches and the segment serves queries."""
    from pinot_tpu.controller.resource_manager import ClusterResourceManager
    from pinot_tpu.segment.format import SEGMENT_FILE_NAME, write_segment
    from pinot_tpu.server.instance import ServerInstance
    from pinot_tpu.server.starter import ServerStarter
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment

    seg = synthetic_lineitem_segment(500, seed=3, name="fseg")
    d = tmp_path / "store"
    write_segment(seg, str(d))

    rm = ClusterResourceManager()
    server = ServerInstance("fsrv")
    starter = ServerStarter(server, rm)
    ok = starter._load(
        "lineitem",
        "fseg",
        {"metadata": seg.metadata, "downloadUri": "file://" + str(d)},
    )
    assert ok
    tdm = server.data_manager.table("lineitem")
    assert tdm is not None and "fseg" in tdm.segment_names()


def test_http_download_truncation_cleans_part_and_retries(tmp_path):
    """A connection cut mid-body must not leave a truncated file (or a
    stale .part) behind: the attempt fails the length check, cleans up,
    and the retry can land a full copy."""
    import http.server
    import threading as _threading

    state = {"truncate_next": 0}

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = b"0123456789" * 100
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if state["truncate_next"] > 0:
                state["truncate_next"] -= 1
                self.wfile.write(body[: len(body) // 2])
                self.wfile.flush()
                self.connection.close()  # cut mid-stream
            else:
                self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = _threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        host, port = srv.server_address
        dest = tmp_path / "seg"
        state["truncate_next"] = 1
        HttpSegmentFetcher(attempts=3).fetch(f"http://{host}:{port}/s", str(dest))
        assert dest.read_bytes() == b"0123456789" * 100  # retry healed it
        assert not (tmp_path / "seg.part").exists()

        # every attempt truncated -> typed retry failure, no leftovers
        from pinot_tpu.utils.retry import RetryError

        state["truncate_next"] = 99
        with pytest.raises(RetryError):
            HttpSegmentFetcher(attempts=2).fetch(
                f"http://{host}:{port}/s", str(tmp_path / "seg2")
            )
        assert not (tmp_path / "seg2").exists()
        assert not (tmp_path / "seg2.part").exists()
    finally:
        srv.shutdown()


def test_exponential_backoff_full_jitter():
    """Full jitter: delays draw uniformly from [0, cap], deterministic
    per seed, and do NOT re-synchronize retrying replicas (plain
    exponential backoff fires every replica at the same instants)."""
    from pinot_tpu.utils.retry import ExponentialBackoffRetryPolicy

    plain = ExponentialBackoffRetryPolicy(5, 0.2)
    assert [plain.delay_s(i) for i in range(3)] == [0.2, 0.4, 0.8]

    j1 = ExponentialBackoffRetryPolicy(5, 0.2, jitter=True, seed=42)
    j2 = ExponentialBackoffRetryPolicy(5, 0.2, jitter=True, seed=42)
    j3 = ExponentialBackoffRetryPolicy(5, 0.2, jitter=True, seed=43)
    d1 = [j1.delay_s(i) for i in range(8)]
    d2 = [j2.delay_s(i) for i in range(8)]
    d3 = [j3.delay_s(i) for i in range(8)]
    assert d1 == d2  # deterministic per seed
    assert d1 != d3  # different replicas spread out
    for i, d in enumerate(d1):
        assert 0.0 <= d <= 0.2 * 2**i
