"""Tiered staged-table residency (engine/residency.py, ISSUE 18):
snapshot/restore byte identity, ledger-exact tier transitions, pin
refcounts vs the victim picker, warm -> cold spill and reload, and the
entry-cap demotion racing concurrent staging."""
import os
import threading

import numpy as np
import pytest

from pinot_tpu.engine import device as device_mod
from pinot_tpu.engine.device import (
    _ROLE_ATTRS,
    LEDGER,
    clear_staging_cache,
    get_staged,
)
from pinot_tpu.engine.residency import (
    RESIDENCY,
    restore_staged,
    snapshot_staged,
)
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.tools.datagen import make_test_schema, random_rows

SCHEMA = make_test_schema(with_mv=True)
COLS = ["dimStr", "dimInt", "metInt", "metDouble", "dimIntMV"]


def _make_segs(table: str, n: int = 200, seed: int = 5):
    rows = random_rows(SCHEMA, n, seed=seed)
    return [
        build_segment(SCHEMA, rows[: n // 2], table, f"{table}a"),
        build_segment(SCHEMA, rows[n // 2 :], table, f"{table}b"),
    ]


@pytest.fixture(autouse=True)
def _clean_tiers():
    clear_staging_cache()
    yield
    for k in ("PINOT_TPU_HBM_CAP_BYTES", "PINOT_TPU_HOST_CAP_BYTES",
              "PINOT_TPU_STAGE_CACHE_ENTRIES"):
        os.environ.pop(k, None)
    clear_staging_cache()


def _arrays_of(st):
    """Every device array of a StagedTable as numpy, keyed by
    (column index, role attr) — the byte-identity comparison set."""
    out = {}
    for name, col in st.columns.items():
        for attr, _ in _ROLE_ATTRS:
            arr = getattr(col, attr, None)
            if arr is not None:
                out[(name, attr)] = np.asarray(arr)
    out[("nd", "num_docs_arr")] = np.asarray(st.num_docs_arr)
    return out


def test_snapshot_restore_round_trip_is_byte_identical():
    segs = _make_segs("rtrip")
    st = get_staged(segs, COLS, raw_columns=["metDouble"])
    before = _arrays_of(st)
    snap, nbytes = snapshot_staged(st)
    assert nbytes > 0
    restored = restore_staged(snap)
    after = _arrays_of(restored)
    assert sorted(before) == sorted(after)
    for k in before:
        assert np.array_equal(before[k], after[k]), k
    # alias safety: promotion mints a NEW process-unique token
    assert restored.token != st.token
    # packed metadata survives (names, pads, cardinalities)
    assert sorted(st.columns) == sorted(restored.columns)
    for name, a in st.columns.items():
        b = restored.columns[name]
        assert (a.stored_type, a.single_value, a.cards) == (
            b.stored_type, b.single_value, b.cards
        )


def test_demote_promote_keeps_ledger_exact():
    segs = _make_segs("ledg")
    st = get_staged(segs, COLS)
    hot_bytes = LEDGER.total_bytes()
    assert hot_bytes > 0
    key = RESIDENCY._token_keys[st.token]
    os.environ["PINOT_TPU_HBM_CAP_BYTES"] = "1"
    freed = RESIDENCY.enforce()
    assert freed > 0
    # demotion IS a ledger drop: hot bytes return to zero while the
    # warm snapshot holds the payload
    assert LEDGER.total_bytes() == 0
    assert RESIDENCY.warm_bytes() > 0
    assert key not in device_mod._stage_cache
    os.environ.pop("PINOT_TPU_HBM_CAP_BYTES")
    # promotion re-registers the same footprint
    st2 = get_staged(segs, COLS)
    assert st2.token != st.token
    assert LEDGER.total_bytes() == hot_bytes
    assert RESIDENCY.counter("promotions") == 1
    assert RESIDENCY.counter("demotions") == 1


def test_promoted_arrays_match_fresh_staging():
    segs = _make_segs("prom")
    st = get_staged(segs, COLS, raw_columns=["metInt"])
    want = _arrays_of(st)
    os.environ["PINOT_TPU_HBM_CAP_BYTES"] = "1"
    RESIDENCY.enforce()
    os.environ.pop("PINOT_TPU_HBM_CAP_BYTES")
    got = _arrays_of(get_staged(segs, COLS, raw_columns=["metInt"]))
    assert sorted(want) == sorted(got)
    for k in want:
        assert np.array_equal(want[k], got[k]), k


def test_warm_spills_cold_and_reloads():
    segs = _make_segs("cold")
    st = get_staged(segs, COLS)
    want = _arrays_of(st)
    os.environ["PINOT_TPU_HBM_CAP_BYTES"] = "1"
    os.environ["PINOT_TPU_HOST_CAP_BYTES"] = "1"
    RESIDENCY.enforce()
    assert RESIDENCY.counter("coldDemotions") == 1
    assert RESIDENCY.cold_bytes() > 0
    assert RESIDENCY.warm_bytes() == 0
    os.environ.pop("PINOT_TPU_HBM_CAP_BYTES")
    os.environ.pop("PINOT_TPU_HOST_CAP_BYTES")
    got = _arrays_of(get_staged(segs, COLS))
    assert RESIDENCY.counter("coldLoads") == 1
    assert RESIDENCY.counter("promotions") == 1
    for k in want:
        assert np.array_equal(want[k], got[k]), k
    # byte identity across ALL THREE states (hot -> warm -> cold ->
    # hot) is the zero-re-encode contract


def test_pin_blocks_demotion_until_unpin():
    segs = _make_segs("pin")
    st = get_staged(segs, COLS, pin=True)
    os.environ["PINOT_TPU_HBM_CAP_BYTES"] = "1"
    assert RESIDENCY.enforce() == 0  # pinned: not a victim
    assert LEDGER.total_bytes() > 0
    RESIDENCY.unpin(st.token)
    assert RESIDENCY.enforce() > 0
    assert LEDGER.total_bytes() == 0


def test_pin_refcount_survives_nested_queries():
    segs = _make_segs("ref")
    st = get_staged(segs, COLS, pin=True)
    get_staged(segs, COLS, pin=True)  # same key: second in-flight query
    assert RESIDENCY.pin_count(st.token) == 2
    RESIDENCY.unpin(st.token)
    os.environ["PINOT_TPU_HBM_CAP_BYTES"] = "1"
    assert RESIDENCY.enforce() == 0  # still one holder
    RESIDENCY.unpin(st.token)
    assert RESIDENCY.enforce() > 0
    assert RESIDENCY.pin_count(st.token) == 0


def test_entry_cap_demotes_coldest_not_clears_all():
    os.environ["PINOT_TPU_STAGE_CACHE_ENTRIES"] = "2"
    all_segs = [_make_segs(f"cap{i}", n=60, seed=i) for i in range(4)]
    for segs in all_segs:
        get_staged(segs, COLS)
    # cache bounded, nothing lost: overflow went warm, not dropped
    assert len(device_mod._stage_cache) <= 2
    assert RESIDENCY.counter("demotions") >= 2
    snap = RESIDENCY.snapshot()
    assert snap["hotTables"] + snap["warmTables"] + snap["coldTables"] == 4


def test_concurrent_staging_races_entry_cap_eviction():
    """Threads staging distinct tables under a tiny entry cap while
    re-promoting each other's victims: every get_staged must return a
    correct pinned table (pin taken inside the key lock), and the
    refcounts must drain to zero."""
    os.environ["PINOT_TPU_STAGE_CACHE_ENTRIES"] = "2"
    tables = [_make_segs(f"race{i}", n=60, seed=10 + i) for i in range(5)]
    want_nd = [
        sum(s.metadata.num_docs for s in segs) for segs in tables
    ]
    errors = []

    def worker(idx: int) -> None:
        try:
            for round_ in range(8):
                segs = tables[(idx + round_) % len(tables)]
                st = get_staged(segs, COLS, pin=True)
                try:
                    nd = int(np.asarray(st.num_docs_arr).sum())
                    expect = want_nd[(idx + round_) % len(tables)]
                    if nd != expect:
                        errors.append(f"docs {nd} != {expect}")
                finally:
                    RESIDENCY.unpin(st.token)
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors[:4]
    snap = RESIDENCY.snapshot()
    assert snap["pinnedTokens"] == 0
    assert len(device_mod._stage_cache) <= 2 + 4  # cap + in-flight pins


def test_clear_staging_cache_resets_all_tiers():
    segs = _make_segs("clr")
    get_staged(segs, COLS)
    os.environ["PINOT_TPU_HBM_CAP_BYTES"] = "1"
    RESIDENCY.enforce()
    assert RESIDENCY.warm_bytes() > 0
    clear_staging_cache()
    snap = RESIDENCY.snapshot()
    assert snap["hotTables"] == snap["warmTables"] == snap["coldTables"] == 0
    # a retained warm copy would silently turn the next stage into a
    # promotion — clear means clear
    os.environ.pop("PINOT_TPU_HBM_CAP_BYTES")
    get_staged(segs, COLS)
    assert RESIDENCY.counter("promotions") == 0


def test_drop_segment_drops_every_tier():
    segs = _make_segs("dropseg")
    get_staged(segs, COLS)
    os.environ["PINOT_TPU_HBM_CAP_BYTES"] = "1"
    RESIDENCY.enforce()
    os.environ.pop("PINOT_TPU_HBM_CAP_BYTES")
    assert RESIDENCY.drop_segment(segs[0].segment_name) == 1
    assert RESIDENCY.warm_bytes() == 0
    # the quarantine path: a later re-stage starts from source
    get_staged(segs, COLS)
    assert RESIDENCY.counter("promotions") == 0
