"""Fast serving-curve smoke (tier-1, -m bench_smoke): bench.py's
concurrent serving mode end-to-end at tiny scale — closed-loop clients
at concurrency 8 over the pipelined and serial paths.  Guards the PR-2
tentpole invariants in CI: the device lane actually coalesces identical
dispatches under concurrency, and pipelined results never diverge from
the serial path.  (The full-scale bench smoke stays ``slow``.)"""
import json
import os
import subprocess
import sys

import pytest


@pytest.mark.bench_smoke
def test_serving_curve_smoke():
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PINOT_TPU_BENCH_FORCE_CPU="1",
        PINOT_TPU_BENCH_MODE="serving",
        PINOT_TPU_BENCH_SEGMENTS="1",
        PINOT_TPU_BENCH_ROWS_PER_SEGMENT="60000",
        PINOT_TPU_BENCH_SERVE_CLIENTS="8",
        PINOT_TPU_BENCH_SERVE_DURATION_S="1.5",
    )
    out = subprocess.run(
        [sys.executable, "bench.py"],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out.stdout)
    assert doc["metric"] == "serving_closed_loop_qps_pipelined_vs_serial"

    # the pipelined lane must have coalesced identical dispatches under
    # 8 closed-loop clients of a repeated shape
    lane = doc["modes"]["pipelined"]["lane"]
    assert lane is not None and lane["coalesceHits"] > 0, lane
    assert lane["dispatches"] > 0
    # the serial mode must really be serial (no lane)
    assert doc["modes"]["serial"]["lane"] is None

    # no result divergence between the two execution paths
    assert doc["differential"]["identical_payloads"], doc["differential"]

    # utilization plane (PR 10): the pipelined lane's occupancy window
    # covers the measured ladder and must be busy under 8 closed-loop
    # clients; the D2H counter saw the result fetches; the CPU mesh
    # declares no peak so the roofline fraction is the explicit null
    util = doc["utilization"]["pipelined"]
    assert util["busyFraction"] > 0, util
    assert util["achievedBytesPerSec"] > 0 and util["d2hBytes"] > 0
    assert util["rooflineFraction"] is None
    # the serial mode has no lane, hence no occupancy fields — but its
    # device path still reports achieved bandwidth
    assert "busyFraction" not in doc["utilization"]["serial"]
    assert doc["utilization"]["serial"]["achievedBytesPerSec"] > 0

    # every curve step completed queries without errors
    for mode in ("serial", "pipelined"):
        for steps in doc["modes"][mode]["curves"].values():
            for step in steps:
                assert step["errors"] == 0, (mode, step)
                assert step["queries"] > 0, (mode, step)
