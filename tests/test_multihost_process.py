"""True multi-process multi-host execution (VERDICT r2 #7): two OS
processes bring up ``jax.distributed.initialize`` (coordinator, process
ids, global device view — the real multi-host runtime wiring, not mesh
reshaping), build the 2-D (hosts, chips) mesh with
``make_multihost_mesh``, and run the production sharded query kernel
through a collective that crosses the process boundary.

Reference analog: the multi-server in-process cluster harness
(``pinot-integration-tests/.../ClusterTest.java:62``) — here at the
SPMD layer.  Skips when the CPU cross-process collective backend
(gloo) is unavailable in this jax build; the wiring under test is
real either way."""
import os
import socket
import subprocess
import sys

import pytest

WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_distributed_mesh():
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    # the worker pins its own platform/device-count flags; scrub any
    # conftest-inherited backend state
    env.pop("XLA_FLAGS", None)
    env["PINOT_TPU_TESTS"] = ""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(WORKER)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coordinator, "2", str(pid)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(WORKER))),
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost workers timed out")

    for rc, out, err in outs:
        if rc != 0:
            low = (err or "").lower()
            if "gloo" in low or "collectives" in low or "cross-host" in low or "unimplemented" in low:
                pytest.skip(f"CPU cross-process collectives unavailable: {err[-400:]}")
            pytest.fail(f"worker failed rc={rc}\nstdout={out}\nstderr={err[-2000:]}")

    # both processes observe the SAME globally-reduced count: 8
    # segments x 512 rows, filter matches everything
    results = [
        line for rc, out, _ in outs for line in out.splitlines() if line.startswith("RESULT")
    ]
    assert len(results) == 2, results
    vals = {line.split("num_docs=")[1] for line in results}
    assert vals == {"4096.0"}, results


SERVE_WORKER = os.path.join(os.path.dirname(__file__), "multihost_serve_worker.py")


@pytest.mark.slow
def test_broker_pql_through_multihost_mesh():
    """End-to-end PQL answered by a multi-host mesh (VERDICT r3 #7):
    a real BrokerRequestHandler scatter-gathers to the LEAD host of a
    2-process (hosts, chips) mesh-serving group; the lead fans the
    query to the follower so both enter the sharded kernel's
    cross-process collectives, and the broker merges the one reply."""
    import time

    coordinator = f"127.0.0.1:{_free_port()}"
    lead_port, follower_port = _free_port(), _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PINOT_TPU_TESTS"] = ""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(SERVE_WORKER)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    args = {
        0: [coordinator, "2", "0", str(lead_port), str(follower_port)],
        1: [coordinator, "2", "1", str(follower_port)],
    }
    # stdout/stderr go to FILES: a chatty worker blocking on a full
    # stderr pipe would deadlock the readiness loop below
    import tempfile

    logdir = tempfile.mkdtemp(prefix="meshserve_")
    outs = [open(os.path.join(logdir, f"w{pid}.out"), "w+") for pid in (0, 1)]
    errs = [open(os.path.join(logdir, f"w{pid}.err"), "w+") for pid in (0, 1)]
    procs = [
        subprocess.Popen(
            [sys.executable, SERVE_WORKER, *args[pid]],
            stdout=outs[pid],
            stderr=errs[pid],
            text=True,
            env=env,
            cwd=repo_root,
        )
        for pid in (0, 1)
    ]

    def read(f):
        f.flush()
        f.seek(0)
        return f.read()

    try:
        # wait for both hosts to report SERVING (coordinator + mesh up)
        deadline = time.time() + 240
        serving = set()
        while len(serving) < 2 and time.time() < deadline:
            for i, p in enumerate(procs):
                if i in serving:
                    continue
                if p.poll() is not None:
                    err = read(errs[i])
                    low = err.lower()
                    if "gloo" in low or "collectives" in low or "unimplemented" in low:
                        pytest.skip(f"CPU cross-process collectives unavailable: {err[-300:]}")
                    pytest.fail(f"worker {i} died rc={p.returncode}\n{err[-2000:]}")
                if "SERVING" in read(outs[i]):
                    serving.add(i)
            time.sleep(0.2)
        assert len(serving) == 2, "mesh hosts did not come up in time"

        from pinot_tpu.broker.broker import BrokerRequestHandler
        from pinot_tpu.broker.routing import RoutingTableProvider
        from pinot_tpu.transport.tcp import TcpTransport

        routing = RoutingTableProvider()
        routing.update(
            "lineitem", {f"mh{i}": {"meshhost0": "ONLINE"} for i in range(8)}
        )
        broker = BrokerRequestHandler(
            TcpTransport(),
            {"meshhost0": ("127.0.0.1", lead_port)},
            routing=routing,
            timeout_ms=240_000.0,
        )
        resp = broker.handle_pql(
            "SELECT sum(l_quantity), count(*) FROM lineitem "
            "WHERE l_shipdate <= '1998-09-02' GROUP BY l_returnflag TOP 10"
        )
        assert not resp.exceptions, resp.exceptions
        assert resp.num_docs_scanned == 4096  # all 8 x 512 rows, via the mesh
        counts = {
            tuple(g.group): g.value
            for g in resp.aggregation_results[1].group_by_result
        }
        assert sum(counts.values()) == 4096
        # second query exercises steady-state ordering across processes
        resp2 = broker.handle_pql("SELECT count(*) FROM lineitem")
        assert not resp2.exceptions, resp2.exceptions
        assert resp2.aggregation_results[0].value == 4096.0

        # follower death: the lead's liveness preflight must fail the
        # query fast (error response) instead of wedging the collective
        procs[1].terminate()
        try:
            procs[1].wait(timeout=10)
        except subprocess.TimeoutExpired:
            procs[1].kill()  # CPU-only worker: SIGKILL is safe
            procs[1].wait(timeout=10)
        t0 = time.time()
        resp3 = broker.handle_pql("SELECT count(*) FROM lineitem")
        assert resp3.exceptions, "dead follower must surface as a query error"
        assert "unreachable" in resp3.exceptions[0].message
        assert time.time() - t0 < 60, "follower-down detection took too long"
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
        for f in outs + errs:
            f.close()


@pytest.mark.slow
def test_mesh_follower_death_between_preflight_and_collective():
    """The HARD failure window (r4 VERDICT #7): the follower answers the
    lead's liveness ping, then dies on query receipt — after preflight,
    before collective entry.  The lead's forward-grace watch must (1)
    fail THIS query with a typed error instead of entering the doomed
    psum barrier, and (2) mark the group degraded so every later query
    errors fast until the group is restarted."""
    import time

    coordinator = f"127.0.0.1:{_free_port()}"
    lead_port, follower_port = _free_port(), _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PINOT_TPU_TESTS"] = ""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(SERVE_WORKER)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    follower_env = dict(env)
    follower_env["PINOT_TPU_MESH_TEST_EXIT_ON_QUERY"] = "1"
    args = {
        0: [coordinator, "2", "0", str(lead_port), str(follower_port)],
        1: [coordinator, "2", "1", str(follower_port)],
    }
    import tempfile

    logdir = tempfile.mkdtemp(prefix="meshdeath_")
    outs = [open(os.path.join(logdir, f"w{pid}.out"), "w+") for pid in (0, 1)]
    errs = [open(os.path.join(logdir, f"w{pid}.err"), "w+") for pid in (0, 1)]
    procs = [
        subprocess.Popen(
            [sys.executable, SERVE_WORKER, *args[pid]],
            stdout=outs[pid],
            stderr=errs[pid],
            text=True,
            env=env if pid == 0 else follower_env,
            cwd=repo_root,
        )
        for pid in (0, 1)
    ]

    def read(f):
        f.flush()
        f.seek(0)
        return f.read()

    try:
        deadline = time.time() + 240
        serving = set()
        while len(serving) < 2 and time.time() < deadline:
            for i, p in enumerate(procs):
                if i in serving:
                    continue
                if p.poll() is not None:
                    err = read(errs[i])
                    low = err.lower()
                    if "gloo" in low or "collectives" in low or "unimplemented" in low:
                        pytest.skip(f"CPU cross-process collectives unavailable: {err[-300:]}")
                    pytest.fail(f"worker {i} died rc={p.returncode}\n{err[-2000:]}")
                if "SERVING" in read(outs[i]):
                    serving.add(i)
            time.sleep(0.2)
        assert len(serving) == 2, "mesh hosts did not come up in time"

        from pinot_tpu.broker.broker import BrokerRequestHandler
        from pinot_tpu.broker.routing import RoutingTableProvider
        from pinot_tpu.transport.tcp import TcpTransport

        routing = RoutingTableProvider()
        routing.update(
            "lineitem", {f"mh{i}": {"meshhost0": "ONLINE"} for i in range(8)}
        )
        broker = BrokerRequestHandler(
            TcpTransport(),
            {"meshhost0": ("127.0.0.1", lead_port)},
            routing=routing,
            timeout_ms=240_000.0,
        )
        # the follower pings PONG (alive), then _exit(17)s on the query
        t0 = time.time()
        resp = broker.handle_pql("SELECT count(*) FROM lineitem")
        took = time.time() - t0
        assert resp.exceptions, "mid-query follower death must error, not hang"
        assert "between preflight and collective entry" in resp.exceptions[0].message
        assert took < 60, f"mid-query death detection took {took:.0f}s"
        try:
            rc = procs[1].wait(timeout=10)
        except subprocess.TimeoutExpired:
            rc = None
        assert rc == 17, f"follower should have exited via the hook (rc={rc})"

        # the group is now degraded: every subsequent query errors FAST
        t0 = time.time()
        resp2 = broker.handle_pql("SELECT count(*) FROM lineitem")
        assert resp2.exceptions
        assert "degraded" in resp2.exceptions[0].message
        assert time.time() - t0 < 15, "degraded replies must be immediate"
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
        for f in outs + errs:
            f.close()
