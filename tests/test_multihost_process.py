"""True multi-process multi-host execution (VERDICT r2 #7): two OS
processes bring up ``jax.distributed.initialize`` (coordinator, process
ids, global device view — the real multi-host runtime wiring, not mesh
reshaping), build the 2-D (hosts, chips) mesh with
``make_multihost_mesh``, and run the production sharded query kernel
through a collective that crosses the process boundary.

Reference analog: the multi-server in-process cluster harness
(``pinot-integration-tests/.../ClusterTest.java:62``) — here at the
SPMD layer.  Skips when the CPU cross-process collective backend
(gloo) is unavailable in this jax build; the wiring under test is
real either way."""
import os
import socket
import subprocess
import sys

import pytest

WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_distributed_mesh():
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    # the worker pins its own platform/device-count flags; scrub any
    # conftest-inherited backend state
    env.pop("XLA_FLAGS", None)
    env["PINOT_TPU_TESTS"] = ""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(WORKER)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coordinator, "2", str(pid)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(WORKER))),
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost workers timed out")

    for rc, out, err in outs:
        if rc != 0:
            low = (err or "").lower()
            if "gloo" in low or "collectives" in low or "cross-host" in low or "unimplemented" in low:
                pytest.skip(f"CPU cross-process collectives unavailable: {err[-400:]}")
            pytest.fail(f"worker failed rc={rc}\nstdout={out}\nstderr={err[-2000:]}")

    # both processes observe the SAME globally-reduced count: 8
    # segments x 512 rows, filter matches everything
    results = [
        line for rc, out, _ in outs for line in out.splitlines() if line.startswith("RESULT")
    ]
    assert len(results) == 2, results
    vals = {line.split("num_docs=")[1] for line in results}
    assert vals == {"4096.0"}, results
