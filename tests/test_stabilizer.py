"""Cluster self-stabilization tests.

Chaos acceptance (``-m chaos``, deterministic, tier-1): killing a
server under closed-loop load loses zero queries and replication is
restored within 2 stabilizer rounds; a drain-based rolling restart of
every server completes with zero failed queries and zero permanent
segment loss; a controller killed and restarted mid-stabilization
resumes idempotently and converges to the same ideal state.

Plus unit coverage: grace-window deferral, skew-aware (doc-weighted)
re-replication placement, consuming-segment handoff at the committed
offset, drain REST endpoints, heartbeat flap hysteresis, periodic-
manager stop/failure accounting, and RetentionManager /
SegmentStatusChecker run_once edge cases.
"""
import os
import threading
import time

import pytest

from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema, TimeFieldSpec
from pinot_tpu.controller.controller import Controller, ControllerHttpServer
from pinot_tpu.controller.managers import (
    RetentionManager,
    SegmentStatusChecker,
    _PeriodicManager,
)
from pinot_tpu.controller.network import ParticipantGateway
from pinot_tpu.controller.resource_manager import ClusterResourceManager, InstanceState
from pinot_tpu.controller.stabilizer import SelfStabilizer
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.server.instance import ServerInstance
from pinot_tpu.server.starter import ServerStarter
from pinot_tpu.tools.cluster_harness import (
    InProcessCluster,
    run_drain_scenario,
    run_kill_server_scenario,
    run_rolling_restart_scenario,
)
from pinot_tpu.tools.datagen import make_test_schema, random_rows


# ------------------------------------------------------------------
# chaos acceptance — the same scenario code the CLI runs
# ------------------------------------------------------------------
@pytest.mark.chaos
def test_kill_server_acceptance(tmp_path):
    out = run_kill_server_scenario(data_dir=str(tmp_path))
    assert out["failedQueries"] == 0, out["failures"]
    assert out["replicationRestored"], out
    assert out["viewConverged"], out
    assert out["finalComplete"] and out["finalDocs"] == out["expectedDocs"]
    assert out["stabilizer"]["stabilizer.replicasAdded"]["count"] > 0
    assert out["stabilizer"]["stabilizer.replicasDropped"]["count"] > 0


@pytest.mark.chaos
def test_drain_acceptance(tmp_path):
    out = run_drain_scenario(data_dir=str(tmp_path))
    assert out["failedQueries"] == 0, out["failures"]
    assert out["drainStatus"]["drained"] and out["drainStatus"]["draining"]
    assert out["onExcluded"] == 0  # nothing left on the drained server
    assert out["finalComplete"] and out["finalDocs"] == out["expectedDocs"]


@pytest.mark.chaos
def test_rolling_restart_acceptance(tmp_path):
    out = run_rolling_restart_scenario(data_dir=str(tmp_path))
    assert out["failedQueries"] == 0, out["failures"]
    assert out["noSegmentLoss"], out
    assert out["viewConverged"], out


# ------------------------------------------------------------------
# grace window + placement
# ------------------------------------------------------------------
def _offline_cluster(tmp_path, num_servers=3, replication=2, segments=3, docs=60):
    cluster = InProcessCluster(num_servers=num_servers, data_dir=str(tmp_path))
    schema = make_test_schema(with_mv=False)
    physical = cluster.add_offline_table(schema, replication=replication)
    rows = random_rows(schema, docs, seed=3)
    for i in range(segments):
        cluster.upload(physical, build_segment(schema, rows, physical, f"g{i}"))
    return cluster, physical


def test_grace_window_defers_movement(tmp_path):
    """A dead server inside the grace window triggers NO data movement
    (a GC pause / rolling bounce must not cause a mass copy); once the
    window passes, re-replication proceeds."""
    cluster, physical = _offline_cluster(tmp_path)
    res = cluster.controller.resources
    clock = [100.0]
    st = SelfStabilizer(res, grace_s=10.0, now=lambda: clock[0])
    before = res.get_ideal_state(physical)

    res.set_instance_alive("server0", False)
    st.run_once()
    assert res.get_ideal_state(physical) == before  # deferred
    assert st.metrics.meter("stabilizer.graceDeferrals").count == 1  # per server
    assert st.metrics.gauge("stabilizer.deadServers").value == 1

    # a recovery inside the window resets the death clock
    res.set_instance_alive("server0", True)
    clock[0] = 105.0
    st.run_once()
    assert st.metrics.gauge("stabilizer.deadServers").value == 0
    res.set_instance_alive("server0", False)
    clock[0] = 109.0  # only 4s into the NEW window
    st.run_once()
    assert res.get_ideal_state(physical) == before

    clock[0] = 125.0  # past the window: act
    st.run_once()
    ideal = res.get_ideal_state(physical)
    for seg, replicas in ideal.items():
        assert len([s for s in replicas if s != "server0"]) == 2
    st.run_once()  # cleanup round drops the dead replicas
    ideal = res.get_ideal_state(physical)
    assert all("server0" not in r for r in ideal.values())
    cluster.stop()


def test_skew_aware_replacement_placement(tmp_path):
    """Re-replication load-balances by DOCS, not segment count: one huge
    segment plus three small ones re-replicate onto the two survivors
    with the big one alone on its server (PIM-tree-style skew
    resistance)."""
    cluster = InProcessCluster(num_servers=3, data_dir=str(tmp_path))
    schema = make_test_schema(with_mv=False)
    physical = cluster.add_offline_table(schema, replication=1)
    res = cluster.controller.resources
    rows = random_rows(schema, 200, seed=9)
    for name, n in (("big", 200), ("t1", 10), ("t2", 10), ("t3", 10)):
        seg = build_segment(schema, rows[:n], physical, name)
        path = cluster.controller.store.save(physical, seg)
        res.add_segment(
            physical, seg.metadata,
            {"dir": path, "downloadUri": "file://" + os.path.abspath(path)},
            servers=["server0"],
        )
    res.set_instance_alive("server0", False)
    st = cluster.controller.stabilizer
    st.grace_s = 0.0
    st.run_once()
    st.run_once()
    ideal = res.get_ideal_state(physical)
    by_server = {}
    for seg, replicas in ideal.items():
        for s in replicas:
            by_server.setdefault(s, set()).add(seg)
    assert "server0" not in by_server
    # the 200-doc segment sits alone; the three 10-doc ones share a host
    big_host = next(s for s, segs in by_server.items() if "big" in segs)
    assert by_server[big_host] == {"big"}
    other = next(s for s in by_server if s != big_host)
    assert by_server[other] == {"t1", "t2", "t3"}
    # queries serve the full data from the rebuilt placement
    resp = cluster.query("SELECT count(*) FROM testTable")
    assert resp.num_docs_scanned == 230 and not resp.exceptions
    cluster.stop()


# ------------------------------------------------------------------
# consuming-segment handoff
# ------------------------------------------------------------------
def _rt_schema():
    return Schema(
        "meetupRsvp",
        dimensions=[FieldSpec("venue_name", DataType.STRING)],
        metrics=[FieldSpec("rsvp_count", DataType.INT, FieldType.METRIC)],
        time_field=TimeFieldSpec("mtime", DataType.LONG, time_unit="MILLISECONDS"),
    )


def test_consuming_handoff_resumes_at_committed_offset(tmp_path):
    """Killing the server that hosts a CONSUMING segment retires it and
    re-creates it on a live server resuming from the COMMITTED offset
    (uncommitted rows re-consume from the stream — at-least-once, no
    double count, no loss)."""
    from pinot_tpu.realtime.llc import make_segment_name
    from pinot_tpu.realtime.stream import MemoryStreamProvider

    cluster = InProcessCluster(num_servers=2, data_dir=str(tmp_path))
    schema = _rt_schema()
    stream = MemoryStreamProvider(num_partitions=1)
    physical = cluster.add_realtime_table(schema, stream, rows_per_segment=50)
    for i in range(70):
        stream.produce({"venue_name": f"v{i % 3}", "rsvp_count": i % 5, "mtime": 10_000 + i})

    rm = cluster.controller.realtime_manager
    res = cluster.controller.resources
    seg0 = make_segment_name(physical, 0, 0)
    dm = rm.consumers_of(seg0)[0]
    dm.consume_step(max_rows=1000)
    assert dm.try_commit() == "KEEP"  # seg0 committed at offset 50

    seg1 = make_segment_name(physical, 0, 1)
    holder = next(iter(res.get_ideal_state(physical)[seg1]))
    dm1 = next(c for c in rm.consumers_of(seg1) if c.server.name == holder)
    dm1.consume_step(max_rows=20)  # 20 UNCOMMITTED rows at offsets 50..69

    res.set_instance_alive(holder, False)
    st = cluster.controller.stabilizer
    st.grace_s = 0.0
    st.run_once()  # retire + recreate consuming, re-replicate seg0
    st.run_once()

    ideal = res.get_ideal_state(physical)
    assert seg1 in ideal
    new_holder = next(iter(ideal[seg1]))
    assert new_holder != holder
    assert ideal[seg1][new_holder] == "CONSUMING"
    assert st.metrics.meter("stabilizer.consumingReassigned").count == 1
    new_dm = rm.consumers_of(seg1)
    assert len(new_dm) == 1 and new_dm[0].server.name == new_holder
    assert new_dm[0].offset == 50  # committed offset, NOT the lost 70

    new_dm[0].consume_step(max_rows=20)  # re-consume the 20 lost rows
    resp = cluster.query("SELECT count(*) FROM meetupRsvp")
    assert resp.num_docs_scanned == 70 and not resp.exceptions
    assert resp.partial_response is False
    cluster.stop()


def test_drain_sheds_replicated_consuming_replica(tmp_path):
    """Draining a server that holds one replica of a still-consuming
    segment must complete: the draining replica is shed (the healthy
    holder keeps consuming; the next sequence reopens at full
    replication on commit) instead of wedging drained=false forever."""
    from pinot_tpu.realtime.llc import make_segment_name
    from pinot_tpu.realtime.stream import MemoryStreamProvider

    cluster = InProcessCluster(num_servers=2, data_dir=str(tmp_path))
    schema = _rt_schema()
    stream = MemoryStreamProvider(num_partitions=1)
    physical = cluster.add_realtime_table(
        schema, stream, rows_per_segment=50, replication=2
    )
    seg0 = make_segment_name(physical, 0, 0)
    rm = cluster.controller.realtime_manager
    res = cluster.controller.resources
    assert set(res.get_ideal_state(physical)[seg0]) == {"server0", "server1"}

    cluster.controller.drain_instance("server0")
    st = cluster.controller.stabilizer
    st.grace_s = 0.0
    st.run_once()
    assert cluster.controller.drain_status("server0")["drained"]
    ideal = res.get_ideal_state(physical)
    assert ideal[seg0] == {"server1": "CONSUMING"}
    # server0's consumer is released; server1's keeps consuming
    holders = {dm.server.name for dm in rm.consumers_of(seg0)}
    assert holders == {"server1"}
    cluster.stop()


# ------------------------------------------------------------------
# drain endpoints
# ------------------------------------------------------------------
def test_drain_endpoints_http(tmp_path):
    import json
    import urllib.request

    cluster, physical = _offline_cluster(tmp_path)
    cluster.controller.stabilizer.grace_s = 0.0
    http = ControllerHttpServer(cluster.controller)
    http.start()
    base = f"http://127.0.0.1:{http.port}"

    def post(path):
        req = urllib.request.Request(base + path, data=b"{}")
        with urllib.request.urlopen(req, timeout=5) as r:
            return json.loads(r.read())

    def get(path):
        with urllib.request.urlopen(base + path, timeout=5) as r:
            return json.loads(r.read())

    try:
        out = post("/instances/server0/drain")
        assert out["draining"] and out["remainingSegments"] > 0 and not out["drained"]
        # draining server drops out of NEW routing covers immediately
        cover = cluster.broker.routing.find_servers(physical)
        assert "server0" not in cover
        # the clusterstate lists it as DRAINING (deliberate), not dead
        state = get("/clusterstate")
        assert "server0" in state["drainingServers"]
        assert "server0" not in state["deadServers"]
        assert all(
            "server0" not in replicas
            for replicas in state["tables"][physical].values()
        )

        cluster.controller.stabilizer.run_once()
        cluster.controller.stabilizer.run_once()
        out = get("/instances/server0/drain")
        assert out["drained"] and out["remainingSegments"] == 0

        out = post("/instances/server0/undrain")
        assert not out["draining"]
        # stabilizer events + metrics ride the debug surface
        dbg = get("/debug/stabilizer")
        assert any(e["event"] == "replicaAdded" for e in dbg["events"])
        assert dbg["metrics"]["meters"]["stabilizer.replicasDropped"]["count"] > 0

        resp = cluster.query("SELECT count(*) FROM testTable")
        assert not resp.exceptions and resp.partial_response is False

        # a typo'd name must 404, never report drained=true to a
        # rolling-restart loop about to bounce the real server
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as ei:
            get("/instances/serverO/drain")
        assert ei.value.code == 404
    finally:
        http.stop()
        cluster.stop()


# ------------------------------------------------------------------
# heartbeat flap hysteresis
# ------------------------------------------------------------------
def test_flap_hysteresis_holds_readmit():
    from pinot_tpu.utils.metrics import ControllerMetrics

    res = ClusterResourceManager()
    clock = [0.0]
    metrics = ControllerMetrics("controller")
    gw = ParticipantGateway(
        res, metrics=metrics, flap_window_s=60.0, flap_threshold=3,
        flap_hold_base_s=5.0, clock=lambda: clock[0],
    )
    gw.register({"name": "s1", "role": "server"})

    # three dead->alive cycles inside the window: admitted (metered)
    for t in (1.0, 2.0, 3.0):
        res.set_instance_alive("s1", False)
        clock[0] = t
        out = gw.heartbeat("s1")
        assert out["status"] == "ok"
        assert res.instances["s1"].alive
    assert metrics.meter("gateway.flaps").count == 2  # cycles beyond the first

    # the fourth revive attempt is HELD with an escalating window
    res.set_instance_alive("s1", False)
    clock[0] = 4.0
    out = gw.heartbeat("s1")
    assert out["status"] == "held" and out["holdSeconds"] == pytest.approx(5.0)
    assert not res.instances["s1"].alive
    clock[0] = 6.0  # still inside the hold
    assert gw.heartbeat("s1")["status"] == "held"

    # re-REGISTERING does not bypass the gate either
    clock[0] = 7.0
    out = gw.register({"name": "s1", "role": "server"})
    assert out["status"] == "held"
    assert not res.instances["s1"].alive

    # a further attempt after the hold ESCALATES it (2x per extra flap)
    clock[0] = 10.0
    out = gw.heartbeat("s1")
    assert out["status"] == "held" and out["holdSeconds"] == pytest.approx(10.0)

    # once the flap window drains, the instance is re-admitted
    clock[0] = 80.0
    out = gw.heartbeat("s1")
    assert out["status"] == "ok"
    assert res.instances["s1"].alive
    gw.stop()


# ------------------------------------------------------------------
# controller crash recovery
# ------------------------------------------------------------------
def _expected_ideal_after_kill(tmp_path, victim="server0"):
    """The UNINTERRUPTED reference run: same cluster build, kill, two
    stabilizer rounds — placement is deterministic, so this is the
    fixpoint an interrupted run must also reach."""
    cluster, physical = _offline_cluster(tmp_path, segments=4)
    res = cluster.controller.resources
    res.set_instance_alive(victim, False)
    st = cluster.controller.stabilizer
    st.grace_s = 0.0
    st.run_once()
    st.run_once()
    ideal = res.get_ideal_state(physical)
    cluster.stop()
    return physical, ideal


def test_controller_restart_mid_stabilization(tmp_path):
    """Kill a controller between the stabilizer's add phase and its
    cleanup phase: the recovered controller replays the partially-
    applied plan from the property store and converges to the SAME
    ideal state as an uninterrupted run — idempotently (a further round
    changes nothing, and every server holds exactly its ideal-state
    segments: no duplicate transitions)."""
    physical, expected = _expected_ideal_after_kill(tmp_path / "ref")

    data_dir = str(tmp_path / "live")
    cluster, _ = _offline_cluster(tmp_path / "live", segments=4)
    res = cluster.controller.resources
    res.set_instance_alive("server0", False)
    st = cluster.controller.stabilizer
    st.grace_s = 0.0
    st.run_once()  # ADD phase applied; dead replicas not yet dropped
    mid = res.get_ideal_state(physical)
    assert any("server0" in r for r in mid.values())  # plan half-applied
    cluster.stop()  # controller "crashes" here

    ctrl2 = Controller(data_dir)
    ctrl2.stabilizer.grace_s = 0.0
    # the surviving servers re-register with the recovered controller
    # (server0 never comes back); registration replays their ideal-state
    # transitions from the recovered property store
    servers = {}
    for name in ("server1", "server2"):
        s = ServerInstance(name)
        ServerStarter(s, ctrl2.resources).start()
        servers[name] = s
    ctrl2.stabilizer.run_once()
    ctrl2.stabilizer.run_once()

    ideal = ctrl2.resources.get_ideal_state(physical)
    assert ideal == expected  # same fixpoint as the uninterrupted run
    assert ctrl2.resources.get_external_view(physical) == ideal
    # idempotent: another round is a no-op
    ctrl2.stabilizer.run_once()
    assert ctrl2.resources.get_ideal_state(physical) == ideal
    # no duplicate/ghost replicas on the servers themselves
    for name, s in servers.items():
        want = sorted(seg for seg, r in ideal.items() if name in r)
        assert sorted(s.data_manager.table(physical).segment_names()) == want
    ctrl2.stop()


def test_drain_flag_survives_controller_restart(tmp_path):
    data_dir = str(tmp_path)
    cluster, physical = _offline_cluster(tmp_path, num_servers=2, replication=1)
    cluster.controller.drain_instance("server0")
    assert cluster.controller.resources.instances["server0"].draining
    cluster.stop()

    ctrl2 = Controller(data_dir)
    # recovered BEFORE the instance re-registers
    assert ctrl2.drain_status("server0")["draining"]
    # re-registration does not launder the drain away
    s0 = ServerInstance("server0")
    ServerStarter(s0, ctrl2.resources).start()
    assert ctrl2.resources.instances["server0"].draining
    # only an explicit undrain clears it — durably
    ctrl2.undrain_instance("server0")
    assert not ctrl2.resources.instances["server0"].draining
    ctrl2.stop()
    ctrl3 = Controller(data_dir)
    assert not ctrl3.drain_status("server0")["draining"]
    ctrl3.stop()


# ------------------------------------------------------------------
# periodic-manager lifecycle + failure accounting
# ------------------------------------------------------------------
def test_manager_stop_joins_worker_thread():
    class _Tick(_PeriodicManager):
        def __init__(self):
            super().__init__(0.005)
            self.runs = 0

        def run_once(self):
            self.runs += 1

    m = _Tick()
    m.start()
    deadline = time.monotonic() + 5
    while m.runs == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    m.stop()
    assert m._thread is not None and not m._thread.is_alive()
    assert m.runs >= 1


def test_manager_run_failures_are_metered():
    class _Boom(_PeriodicManager):
        def run_once(self):
            raise RuntimeError("boom")

    m = _Boom(0.005)
    m.start()
    meter = m.metrics.meter("manager._Boom.failures")
    deadline = time.monotonic() + 5
    while meter.count < 2 and time.monotonic() < deadline:
        time.sleep(0.005)
    m.stop()
    assert meter.count >= 2  # counted, not only logged


def test_validation_manager_autowired_to_realtime(tmp_path):
    ctrl = Controller(str(tmp_path))
    assert ctrl.validation_manager.realtime_manager is ctrl.realtime_manager
    ctrl.stop()


# ------------------------------------------------------------------
# RetentionManager / SegmentStatusChecker run_once edge cases
# ------------------------------------------------------------------
def _retention_fixture(tmp_path, retention_value):
    from pinot_tpu.common.tableconfig import RetentionConfig, TableConfig

    cluster = InProcessCluster(num_servers=1, data_dir=str(tmp_path))
    schema = Schema(
        "rt",
        metrics=[FieldSpec("m", DataType.INT, FieldType.METRIC)],
        time_field=TimeFieldSpec("days", DataType.INT, time_unit="DAYS"),
    )
    cluster.controller.add_schema(schema)
    physical = cluster.controller.add_table(
        TableConfig(
            table_name="rt",
            retention=RetentionConfig(
                retention_time_unit="DAYS", retention_time_value=retention_value
            ),
        )
    )
    return cluster, schema, physical


def test_retention_zero_and_negative_config_never_deletes(tmp_path):
    for i, value in enumerate((0, -5)):
        cluster, schema, physical = _retention_fixture(tmp_path / str(i), value)
        ancient = build_segment(schema, [{"m": 1, "days": 1}], physical, "ancient")
        cluster.upload(physical, ancient)
        cluster.controller.retention_manager.run_once()
        assert cluster.controller.resources.segments_of(physical) == ["ancient"]
        cluster.stop()


def test_retention_skips_segment_without_metadata(tmp_path):
    cluster, schema, physical = _retention_fixture(tmp_path, 30)
    res = cluster.controller.resources
    with res._lock:  # a ghost ideal-state entry with no metadata record
        res.ideal_states[physical]["ghost"] = {"server0": "ONLINE"}
    cluster.controller.retention_manager.run_once()  # must not raise
    assert "ghost" in res.segments_of(physical)
    cluster.controller.status_checker.run_once()  # nor the checker
    snap = cluster.controller.status_checker.metrics.snapshot()
    assert snap["gauges"][f"{physical}.segmentCount"] == 1
    cluster.stop()


def test_retention_and_status_on_empty_table(tmp_path):
    cluster, schema, physical = _retention_fixture(tmp_path, 30)
    cluster.controller.retention_manager.run_once()
    cluster.controller.status_checker.run_once()
    snap = cluster.controller.status_checker.metrics.snapshot()
    assert snap["gauges"][f"{physical}.percentSegmentsAvailable"] == 100.0
    assert snap["gauges"][f"{physical}.segmentCount"] == 0
    cluster.stop()


def test_retention_tolerates_deletion_racing_snapshot(tmp_path, monkeypatch):
    """A segment deleted between the ``segments_of`` snapshot and the
    per-segment metadata fetch is skipped, not crashed on."""
    cluster, schema, physical = _retention_fixture(tmp_path, 30)
    now_days = int(time.time() // 86400)
    cluster.upload(
        physical, build_segment(schema, [{"m": 1, "days": now_days - 100}], physical, "old")
    )
    cluster.upload(
        physical, build_segment(schema, [{"m": 2, "days": now_days}], physical, "fresh")
    )
    res = cluster.controller.resources
    orig = res.segments_of

    def racy(table):
        segs = orig(table)
        if "old" in segs:  # concurrent delete AFTER the snapshot
            cluster.controller.delete_segment(physical, "old")
        return segs

    monkeypatch.setattr(res, "segments_of", racy)
    cluster.controller.retention_manager.run_once()  # must not raise
    monkeypatch.undo()
    assert res.segments_of(physical) == ["fresh"]
    cluster.stop()


def test_status_checker_counts_missing_view_replicas(tmp_path):
    cluster, physical = _offline_cluster(
        tmp_path, num_servers=1, replication=1, segments=2
    )
    res = cluster.controller.resources
    with res._lock:  # one replica silently vanishes from the view
        res.external_views[physical]["g0"].clear()
    cluster.controller.status_checker.run_once()
    snap = cluster.controller.status_checker.metrics.snapshot()
    assert snap["gauges"][f"{physical}.percentSegmentsAvailable"] == 50.0
    cluster.stop()
