"""Benchmark: rows scanned/sec on a TPC-H-Q1-shaped query (BASELINE.md).

The reference's stored numbers (contrib/pinot-benchmark, BASELINE.md):
full-scan SUM queries on 6M-row lineitem run at ~14.2M rows/s in the
single config (422 ms for Q0, broker-reported timeUsedMs).  The north
star is rows-scanned/sec/chip on a Q1-shaped filtered group-by at 100M+
rows, plus p99 group-by latency < 50 ms through the broker.

Two measurements, both reported:

1. **Kernel throughput** (headline): staged segments, compiled query
   kernel, steady-state marginal-batch timing (time batches of M_large
   and M_small back-to-back dispatches and divide the difference by
   M_large - M_small).  This subtracts the fixed host<->device
   round-trip latency — on a tunneled chip that RTT swamps device time
   and is an artifact of this environment, not the design.  It is the
   closest analog of the reference's broker-reported server execution
   time (which also excludes client RTT).
2. **Broker end-to-end p50/p99** (detail): the same query through the
   full broker path (parse -> route -> scatter -> kernel -> reduce ->
   JSON) on an in-process cluster, client-observed wall time per query.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "rows/s", "vs_baseline": N}
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

BASELINE_ROWS_PER_SEC = 14_200_000.0  # BASELINE.md: 6,001,215 rows / 0.422 s
TPU_CAPTURE_REF = "BENCH_TPU_CAPTURES_r5.json"  # committed on-chip record

Q1_PQL = (
    "SELECT sum(l_quantity), sum(l_extendedprice), sum(l_discount), count(*) "
    "FROM lineitem WHERE l_shipdate <= '1998-09-02' "
    "GROUP BY l_returnflag, l_linestatus TOP 10"
)


def _build_segments(num_segments: int, rows_per_segment: int):
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment

    return [
        synthetic_lineitem_segment(rows_per_segment, seed=11 + i, name=f"li{i}")
        for i in range(num_segments)
    ]


def _kernel_rows_per_sec(segments, iters: int):
    """Steady-state device throughput via marginal-batch timing.
    Returns (rows_per_sec, per_query_ms, e2e_dispatch_ms)."""
    from pinot_tpu.engine.context import get_table_context
    from pinot_tpu.engine.device import segment_arrays, stage_segments
    from pinot_tpu.engine.kernel import make_table_kernel
    from pinot_tpu.engine.plan import build_query_inputs, build_static_plan
    from pinot_tpu.pql import optimize_request, parse_pql

    request = optimize_request(parse_pql(Q1_PQL))
    ctx = get_table_context(segments)
    needed = sorted(set(request.referenced_columns()))
    # agg inputs stage as raw float32 streams on TPU (dict gathers
    # serialize — 159x slower on v5e, see engine/config.py raw_card_min);
    # this mirrors what executor._role_columns stages for the broker path
    from pinot_tpu.engine.config import raw_card_min

    agg_cols = ("l_quantity", "l_extendedprice", "l_discount")
    raw_cols = tuple(
        c
        for c in agg_cols
        if max(s.column(c).metadata.cardinality for s in segments) > raw_card_min()
    )
    staged = stage_segments(
        segments,
        needed,
        raw_columns=raw_cols,
        gfwd_columns=("l_returnflag", "l_linestatus"),
        ctx=ctx,
    )
    plan = build_static_plan(request, ctx, staged)
    assert plan.on_device, "bench query must run on device"
    q_np = build_query_inputs(request, plan, ctx, staged)

    from pinot_tpu.engine.device import to_device_inputs

    q_inputs = to_device_inputs(q_np)
    seg_arrays = segment_arrays(staged, needed)
    kernel = make_table_kernel(plan)
    total_rows = sum(s.num_docs for s in segments)

    def fetch(outs):
        # pull one scalar leaf to the host: executions are FIFO on the
        # device stream, so this proves every dispatched query finished
        leaf = next(iter(outs.values()))
        while isinstance(leaf, (tuple, list)):
            leaf = leaf[0]
        np.asarray(leaf)

    def run_batch(m: int) -> float:
        t0 = time.perf_counter()
        outs = None
        for _ in range(m):
            outs = kernel(seg_arrays, q_inputs)
        fetch(outs)
        return time.perf_counter() - t0

    fetch(kernel(seg_arrays, q_inputs))  # compile
    run_batch(5)  # warm the dispatch pipeline past tunnel cold-start

    m_small, m_large = 5, 5 + iters
    diffs = []
    e2e = []
    for _ in range(3):
        t_large = run_batch(m_large)
        t_small = run_batch(m_small)
        diffs.append((t_large - t_small) / (m_large - m_small))
        e2e.append(t_large / m_large)
    median = max(sorted(diffs)[len(diffs) // 2], 1e-6)
    e2e_ms = sorted(e2e)[len(e2e) // 2] * 1000
    return total_rows / median, median * 1000, e2e_ms


def _broker_latencies(segments, queries_per_round: int = 40):
    """p50/p99 of the Q1 query through the full broker path (parse ->
    route -> scatter -> vmapped kernel -> reduce), client-observed."""
    from pinot_tpu.tools.cluster_harness import single_server_broker
    from pinot_tpu.tools.query_runner import QueryRunner

    # the 600s default timeout covers the first broker-path query's
    # ~1GB column staging over the tunnel + compile; the serving
    # default (15s) is for steady state
    broker = single_server_broker("lineitem", segments)

    def run(pql: str) -> None:
        resp = broker.handle_pql(pql)
        assert not resp.exceptions, resp.exceptions

    runner = QueryRunner(run)
    runner.single_thread([Q1_PQL], rounds=3)  # warm: stage + compile
    report = runner.single_thread([Q1_PQL] * queries_per_round, rounds=1)

    # Selective point queries (~0.05% of rows): three engine paths ----
    #  - invindex: host postings, O(matches), doc-order independent
    #    (engine/invindex_path.py — BitmapBasedFilterOperator analog)
    #  - zonemap: device block-gather, needs clustered values
    #  - fullscan: the device scan kernel
    # The clustered date column exercises all three; the SHUFFLED
    # high-cardinality l_extendedprice column is the case zone maps
    # cannot prune (VERDICT r2 #2) — the postings path must hold there.
    sel_clustered = (
        "SELECT sum(l_extendedprice), count(*) FROM lineitem "
        "WHERE l_shipdate = '1995-06-14'"
    )
    d_price = segments[0].column("l_extendedprice").dictionary
    pv = d_price.get(d_price.cardinality // 2)
    sel_shuffled = (
        f"SELECT sum(l_quantity), count(*) FROM lineitem "
        f"WHERE l_extendedprice = {pv!r}"
    )
    # every row pins BOTH flags explicitly so ambient env can't
    # mislabel a path; prior values are restored afterwards
    matrix = [
        ("clustered", sel_clustered, "invindex", "1", "0"),
        ("clustered", sel_clustered, "zonemap", "0", "1"),
        ("clustered", sel_clustered, "fullscan", "0", "0"),
        ("shuffled", sel_shuffled, "invindex", "1", "0"),
        ("shuffled", sel_shuffled, "fullscan", "0", "0"),
    ]
    flags = ("PINOT_TPU_INVINDEX", "PINOT_TPU_ZONEMAP")
    saved = {k: os.environ.get(k) for k in flags}
    selective = {}
    try:
        for shape, pql, label, inv, zm in matrix:
            os.environ["PINOT_TPU_INVINDEX"] = inv
            os.environ["PINOT_TPU_ZONEMAP"] = zm
            runner.single_thread([pql], rounds=3)  # warm + compile
            r = runner.single_thread([pql] * 20, rounds=1)
            selective[f"sel_{shape}_p50_ms_{label}"] = r.to_json()["p50Ms"]
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    # the other BASELINE.md workload shapes through the broker:
    # Q6 (IN + range filter group-by) and the HLL distinct group-by
    extra_shapes = {
        "q6": (
            "SELECT sum(l_extendedprice) FROM lineitem "
            "WHERE l_shipmode IN ('RAIL','FOB') AND "
            "l_receiptdate BETWEEN '1997-01-01' AND '1997-12-31' "
            "GROUP BY l_shipmode TOP 10"
        ),
        "hll_groupby": (
            "SELECT distinctcounthll(l_shipdate) FROM lineitem "
            "GROUP BY l_returnflag TOP 10"
        ),
    }
    for label, pql in extra_shapes.items():
        runner.single_thread([pql], rounds=3)  # warm + compile
        r = runner.single_thread([pql] * 10, rounds=1)
        selective[f"{label}_p50_ms"] = r.to_json()["p50Ms"]
    return report, selective


def _closed_loop(broker, queries, clients: int, duration_s: float) -> dict:
    """N closed-loop clients: each keeps exactly one query in flight for
    ``duration_s`` (the saturation-throughput measurement — open-loop
    target-QPS ladders live in tools/serving_curve.py).  Queries beyond
    a list cycle per-client with a stagger so mixed workloads interleave
    across clients."""
    import threading

    lat = []
    errors = [0]
    lock = threading.Lock()
    stop = time.perf_counter() + duration_s

    def client(ci: int) -> None:
        i = ci  # stagger so concurrent clients mix shapes
        while time.perf_counter() < stop:
            q = queries[i % len(queries)]
            i += 1
            t0 = time.perf_counter()
            resp = broker.handle_pql(q)
            ms = (time.perf_counter() - t0) * 1000.0
            with lock:
                lat.append(ms)
                if resp.exceptions:
                    errors[0] += 1

    threads = [threading.Thread(target=client, args=(ci,)) for ci in range(clients)]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    lat.sort()

    def pct(p: float) -> float:
        return lat[min(int(len(lat) * p / 100.0), len(lat) - 1)] if lat else 0.0

    return {
        "clients": clients,
        "queries": len(lat),
        "qps": round(len(lat) / wall, 1),
        # throughput of SUCCESSFUL queries only: a broker shedding 429s
        # answers in microseconds, so counting sheds as served traffic
        # can inflate "qps" by 50x+ while the cluster does no work
        "ok_qps": round((len(lat) - errors[0]) / wall, 1),
        "p50_ms": round(pct(50), 3),
        "p99_ms": round(pct(99), 3),
        "errors": errors[0],
    }


def _strip_timing(resp) -> str:
    """Canonical BrokerResponse payload for differential comparison:
    everything except the wall-clock field, the broker-assigned
    per-query requestId, the cost vector (path-dependent by
    construction: serial vs pipelined time device work differently and
    coalesce hits only exist pipelined), and the event-time freshness
    stamp (wall-clock-relative by definition — two executions of the
    same query legitimately observe different staleness)."""
    return json.dumps(
        {k: v for k, v in resp.to_json().items()
         if k not in ("timeUsedMs", "requestId", "cost", "freshnessMs")},
        sort_keys=True,
    )


def _literal_mix(segments):
    """Same-shape distinct-literal queries — the cross-query batching
    workload (ISSUE 13): every client cycles ONE plan shape per family
    with literals spread across the data, so the lane's micro-batching
    tier sees distinct dispatches that share a StaticPlan and stacks
    them into one vmapped launch.  Two families: the Q1 group-by at
    six shipdate cutoffs (clustered column — low cutoffs may take the
    zone-map block path instead, which is the honest mix), and a
    scalar-agg filter over the SHUFFLED l_quantity column (zone maps
    cannot prune it, so it always rides the batchable full scan)."""
    d = segments[0].column("l_shipdate").dictionary
    qs = []
    for f in (0.25, 0.4, 0.55, 0.7, 0.85, 0.95):
        cutoff = d.get(int((d.cardinality - 1) * f))
        qs.append(
            "SELECT sum(l_quantity), sum(l_extendedprice), count(*) "
            f"FROM lineitem WHERE l_shipdate <= {cutoff!r} "
            "GROUP BY l_returnflag, l_linestatus TOP 10"
        )
    for t in (5, 15, 25, 35, 45):
        qs.append(
            "SELECT sum(l_extendedprice), count(*) FROM lineitem "
            f"WHERE l_quantity > {t}"
        )
    return qs


def _join_main() -> None:
    """Distributed-join mode (PINOT_TPU_BENCH_MODE=join, ISSUE 14):
    closed-loop QPS ladder over the three join strategies x uniform vs
    zipf-skewed join keys, a byte-identity differential holding every
    strategy (device AND host-reference execution) to one payload, and
    the shuffle skew-balance measurement (max owner exchange bytes /
    mean, split on vs off) that the perf gate bounds at <= 2x."""
    import json as _json

    import numpy as np

    import jax

    # x64 so the differential compares exact aggregation payloads
    # across device/host and all three strategies (the tier-1 suite
    # holds the same contract)
    jax.config.update("jax_enable_x64", True)

    from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema
    from pinot_tpu.common.tableconfig import PartitionConfig
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.tools.cluster_harness import InProcessCluster

    platform = jax.devices()[0].platform
    fact_rows = int(os.environ.get("PINOT_TPU_BENCH_JOIN_FACT_ROWS", "40000"))
    dim_rows = int(os.environ.get("PINOT_TPU_BENCH_JOIN_DIM_ROWS", "2000"))
    num_segments = int(os.environ.get("PINOT_TPU_BENCH_JOIN_SEGMENTS", "4"))
    duration_s = float(os.environ.get("PINOT_TPU_BENCH_JOIN_S", "2.0"))
    clients = int(os.environ.get("PINOT_TPU_BENCH_JOIN_CLIENTS", "4"))
    zipf_s = 1.2

    rng = np.random.default_rng(14)
    fact_schema = lambda name: Schema(  # noqa: E731
        name,
        dimensions=[FieldSpec("k", DataType.INT, FieldType.DIMENSION)],
        metrics=[FieldSpec("v", DataType.INT, FieldType.METRIC)],
    )
    dim_schema = Schema(
        "dimB",
        dimensions=[
            FieldSpec("k", DataType.INT, FieldType.DIMENSION),
            FieldSpec("cat", DataType.STRING, FieldType.DIMENSION),
        ],
        metrics=[FieldSpec("w", DataType.INT, FieldType.METRIC)],
    )

    uni_keys = rng.integers(0, dim_rows, fact_rows)
    zipf_keys = np.minimum(rng.zipf(zipf_s, fact_rows), dim_rows) - 1
    vals = rng.integers(0, 1000, fact_rows)

    # 4 servers: the shuffle skew measurement needs enough owners for a
    # hash hot-spot to exist at all (2 owners bound max/mean at 2.0 by
    # construction); the dim table replicates everywhere so colocated
    # eligibility survives arbitrary fact placement
    n_servers = int(os.environ.get("PINOT_TPU_BENCH_JOIN_SERVERS", "4"))
    cluster = InProcessCluster(num_servers=n_servers)
    try:
        part = PartitionConfig(column="k", num_partitions=num_segments)
        for name, keys in (("factUni", uni_keys), ("factZipf", zipf_keys)):
            schema = fact_schema(name)
            cluster.add_offline_table(
                schema, table_name=name, replication=2, partitioning=part
            )
            for p in range(num_segments):
                sel = keys % num_segments == p
                rows = [
                    {"k": int(k), "v": int(v)}
                    for k, v in zip(keys[sel], vals[sel])
                ]
                cluster.upload(
                    f"{name}_OFFLINE",
                    build_segment(
                        schema, rows, f"{name}_OFFLINE", segment_name=f"{name}_{p}_p{p}"
                    ),
                )
        cluster.add_offline_table(
            dim_schema, table_name="dimB", replication=n_servers, partitioning=part
        )
        for p in range(num_segments):
            rows = [
                {"k": k, "cat": f"c{k % 23}", "w": (k * 7) % 501}
                for k in range(dim_rows)
                if k % num_segments == p
            ]
            cluster.upload(
                "dimB_OFFLINE",
                build_segment(
                    dim_schema, rows, "dimB_OFFLINE", segment_name=f"dimB_{p}_p{p}"
                ),
            )

        def q(table):
            return (
                "SELECT count(*), sum(f.v), sum(d.w) "
                f"FROM {table} f JOIN dimB d ON f.k = d.k"
            )

        diff_queries = [
            q("factUni"),
            "SELECT sum(f.v), count(*) FROM factUni f JOIN dimB d "
            "ON f.k = d.k WHERE f.v > 500 GROUP BY d.cat TOP 8",
            "SELECT min(d.w), max(f.v), avg(f.v) FROM factZipf f "
            "JOIN dimB d ON f.k = d.k WHERE d.cat IN ('c1','c2','c3')",
        ]

        # ---- byte-identity differential: every strategy, device and
        # host-reference execution, must produce ONE result payload.
        # Work-accounting fields are strategy-dependent by construction
        # (a shuffle scans extraction rows a colocated join never
        # ships; covers differ per routing draw) — the PR 3 self-heal
        # contract: result fields exact, accounting path-dependent.
        _ACCOUNTING = (
            "timeUsedMs", "requestId", "cost", "numDocsScanned",
            "numEntriesScannedInFilter", "numEntriesScannedPostFilter",
            "totalDocs", "numSegmentsQueried", "numServersQueried",
            "numServersResponded", "numRetries", "numHedges",
        )

        def _strip_join(resp) -> str:
            return json.dumps(
                {
                    k: v
                    for k, v in resp.to_json().items()
                    if k not in _ACCOUNTING
                },
                sort_keys=True,
            )

        payloads = {}
        for strategy in ("colocated", "broadcast", "shuffle"):
            for device in ("1", "0"):
                os.environ["PINOT_TPU_JOIN_STRATEGY"] = strategy
                os.environ["PINOT_TPU_JOIN_DEVICE"] = device
                for i, pql in enumerate(diff_queries):
                    resp = cluster.broker.handle_pql(pql)
                    assert not resp.exceptions, (strategy, device, resp.exceptions)
                    payloads.setdefault(i, set()).add(_strip_join(resp))
        identical = all(len(v) == 1 for v in payloads.values())
        os.environ.pop("PINOT_TPU_JOIN_DEVICE", None)

        # ---- QPS ladder ---------------------------------------------
        qps: dict = {}
        for strategy in ("colocated", "broadcast", "shuffle"):
            os.environ["PINOT_TPU_JOIN_STRATEGY"] = strategy
            qps[strategy] = {}
            for dist, table in (("uniform", "factUni"), ("zipf", "factZipf")):
                cluster.broker.handle_pql(q(table))  # warm kernels
                summary = _closed_loop(
                    cluster.broker, [q(table)], clients, duration_s
                )
                qps[strategy][dist] = summary["ok_qps"]
                qps[f"{strategy}_p50_ms_{dist}"] = summary["p50_ms"]

        # ---- shuffle skew balance (zipf keys) -----------------------
        os.environ["PINOT_TPU_JOIN_STRATEGY"] = "shuffle"
        skew: dict = {}
        for split, label in (("1", "Split"), ("0", "NoSplit")):
            os.environ["PINOT_TPU_JOIN_SPLIT"] = split
            resp = cluster.broker.handle_pql("EXPLAIN ANALYZE " + q("factZipf"))
            actual = (resp.explain or {}).get("join", {}).get("actual", {})
            per = actual.get("shuffleBytesPerServer") or {}
            mean = sum(per.values()) / max(1, len(per))
            skew[f"balanceRatio{label}"] = (
                round(max(per.values()) / mean, 3) if mean else 0.0
            )
            if label == "Split":
                skew["heavyHitterSplits"] = int(
                    actual.get("heavyHitterSplits") or 0
                )
        os.environ.pop("PINOT_TPU_JOIN_SPLIT", None)
        os.environ.pop("PINOT_TPU_JOIN_STRATEGY", None)

        doc = {
            "metric": "join_qps",
            "value": qps["shuffle"]["uniform"],
            "unit": "queries/s",
            "config": {
                "fact_rows": fact_rows,
                "dim_rows": dim_rows,
                "num_segments": num_segments,
                "n_servers": n_servers,
                "clients": clients,
                "zipf_s": zipf_s,
                "platform": platform,
            },
            "qps": {
                s: {d: qps[s][d] for d in ("uniform", "zipf")}
                for s in ("colocated", "broadcast", "shuffle")
            },
            "latency_p50_ms": {
                k: v for k, v in qps.items() if isinstance(v, float)
            },
            "differential": {
                "identical": 1.0 if identical else 0.0,
                "queries": len(diff_queries),
                "variants": 6,
            },
            "skew": skew,
        }
        print(_json.dumps(doc, indent=1))
    finally:
        cluster.stop()


def _serving_main() -> None:
    """Concurrent serving-curve mode (PINOT_TPU_BENCH_MODE=serving):
    closed-loop client ladders (1..256 clients, ISSUE 13) over
    repeated-, mixed-, and literal-mix-shape workloads against the
    in-process broker path, across THREE execution configs — serial
    executor, pipelined (device lane + coalescing + cross-query
    micro-batching), and cached (pipelined + the ingest-aware result
    cache) — plus payload-differential checks across all of them.
    Prints ONE JSON document."""
    from pinot_tpu.tools.cluster_harness import single_server_broker
    from pinot_tpu.tools.serving_curve import mixed_workload

    num_segments = int(os.environ.get("PINOT_TPU_BENCH_SEGMENTS", "4"))
    rows_per_segment = int(os.environ.get("PINOT_TPU_BENCH_ROWS_PER_SEGMENT", "250000"))
    duration_s = float(os.environ.get("PINOT_TPU_BENCH_SERVE_DURATION_S", "6"))
    ladder = [
        int(c)
        for c in os.environ.get(
            "PINOT_TPU_BENCH_SERVE_CLIENTS", "1,4,8,16,64,256"
        ).split(",")
    ]

    segments = _build_segments(num_segments, rows_per_segment)
    queries_mixed = mixed_workload(segments)
    queries_literal = _literal_mix(segments)
    workloads = {
        "repeated_q1": [Q1_PQL],
        "mixed": queries_mixed,
        "literal_mix": queries_literal,
    }

    import jax

    doc = {
        "metric": "serving_closed_loop_qps_pipelined_vs_serial",
        "platform": jax.devices()[0].platform,
        "num_segments": num_segments,
        "total_rows": num_segments * rows_per_segment,
        "duration_s_per_step": duration_s,
        "workloads": "repeated_q1 = the Q1 group-by scan issued by every "
        "client; mixed = the four BASELINE.md shapes interleaved across "
        "clients (tools/serving_curve.py mixed_workload); literal_mix = "
        "same-plan distinct-literal ladders (the cross-query batching "
        "workload, ISSUE 13)",
        "modes": {},
    }
    brokers = {}
    doc["utilization"] = {}
    from pinot_tpu.engine.device import TRANSFERS

    mode_configs = (
        ("serial", False, False),
        ("pipelined", True, False),
        ("cached", True, True),
    )
    for mode, pipelined, cached in mode_configs:
        if cached:
            os.environ["PINOT_TPU_RESULT_CACHE"] = "1"
        try:
            broker = single_server_broker("lineitem", segments, pipeline=pipelined)
        finally:
            os.environ.pop("PINOT_TPU_RESULT_CACHE", None)
        brokers[mode] = broker
        server = broker.local_servers[0]
        # warm every shape (staging + compile) before any measurement
        for q in queries_mixed + queries_literal + [Q1_PQL]:
            for _ in range(2):
                resp = broker.handle_pql(q)
                assert not resp.exceptions, resp.exceptions
        if pipelined:
            # warm the BATCHED kernel buckets too: concurrent distinct-
            # literal bursts make the lane form batches, compiling the
            # vmapped pow2-size variants — otherwise their cold
            # compiles land inside the measured ladder (a ~30% dent on
            # the 2-core CPU box, steady state is at parity)
            import threading as _threading

            for _ in range(3):
                burst = [
                    _threading.Thread(target=broker.handle_pql, args=(q,))
                    for q in queries_literal
                ]
                for t in burst:
                    t.start()
                for t in burst:
                    t.join()
        # utilization plane (ISSUE 10): window the occupancy + transfer
        # + achieved-rate accounting to the MEASURED ladder — warmup
        # staging/compile must not inflate busy-fraction, bandwidth, or
        # roofline figures
        if server.lane is not None:
            server.lane.occupancy_read("bench")
        transfers0 = TRANSFERS.snapshot()
        ladder_t0 = time.monotonic()
        curves = {}
        for wname, qs in workloads.items():
            curves[wname] = [_closed_loop(broker, qs, c, duration_s) for c in ladder]
        occ = (
            server.lane.occupancy_read("bench")
            if server.lane is not None
            else None
        )
        transfers1 = TRANSFERS.snapshot()
        transfers = {
            k: transfers1[k] - v
            for k, v in transfers0.items()
            if isinstance(v, (int, float))  # skip processToken identity
        }
        device = server.device_utilization(roofline_since=ladder_t0)
        doc["modes"][mode] = {
            "curves": curves,
            "lane": None if server.lane is None else server.lane.stats(),
            "scheduler": server.scheduler.stats(),
            "rescache": server.result_cache.snapshot(),
            "device": {
                "occupancy": occ,
                "transfers": transfers,
                "recent": device.get("recent"),
                "platform": device.get("platform"),
            },
        }
        recent = device.get("recent") or {}
        doc["utilization"][mode] = {
            # flat paths for tools/perf_gate.py's serving spec bands
            **(
                {
                    "busyFraction": occ["busyFraction"],
                    "avgQueueDepth": occ["avgQueueDepth"],
                }
                if occ is not None
                else {}
            ),
            "achievedBytesPerSec": recent.get("achievedBytesPerSec", 0.0),
            "achievedFlopsPerSec": recent.get("achievedFlopsPerSec", 0.0),
            "rooflineFraction": recent.get("rooflineFraction"),
            "d2hBytes": transfers.get("d2hBytes", 0),
            "h2dBytes": transfers.get("h2dBytes", 0),
        }
        print(json.dumps({"mode_done": mode}), file=__import__("sys").stderr, flush=True)

    # saturation = best closed-loop ok-QPS across the ladder, per
    # workload (shed responses excluded — see _closed_loop)
    for wname in workloads:
        sat = {
            m: max(s["ok_qps"] for s in doc["modes"][m]["curves"][wname])
            for m in doc["modes"]
        }
        doc[f"saturation_qps_{wname}"] = sat
        doc[f"speedup_{wname}"] = round(sat["pipelined"] / max(sat["serial"], 1e-9), 2)
        doc[f"speedup_cached_{wname}"] = round(
            sat["cached"] / max(sat["serial"], 1e-9), 2
        )

    # cross-query batching + result-cache rollups (ISSUE 13 gate
    # surface).  Batching figures come from the PIPELINED mode (the
    # cached mode answers most repeats before the lane ever sees
    # them); cache figures from the CACHED mode.
    pipe_lane = doc["modes"]["pipelined"]["lane"] or {}
    # denominator: queries that actually EXECUTED (shed 429s at the
    # 64/256-client steps never reach the lane, so counting them would
    # understate occupancy by the shed rate)
    pipe_ok = sum(
        s["queries"] - s["errors"]
        for steps in doc["modes"]["pipelined"]["curves"].values()
        for s in steps
    )
    launches = pipe_lane.get("batchLaunches", 0)
    carried = pipe_lane.get("batchedQueries", 0)
    doc["batching"] = {
        "batchLaunches": launches,
        "batchedQueries": carried,
        "avgBatchSize": round(carried / launches, 3) if launches else 0.0,
        "batchedQueryFraction": (
            round(carried / pipe_ok, 4) if pipe_ok else 0.0
        ),
        "windowCloses": {
            "full": pipe_lane.get("batchWindowFull", 0),
            "timeout": pipe_lane.get("batchWindowTimeout", 0),
        },
        "note": "2-core CPU sim executes batch members serially inside "
        "one program, so batching is ~neutral for wall clock HERE "
        "(steady state measured at parity; the counters prove batches "
        "form) — the amortization win is accelerator-side, where "
        "per-launch dispatch/transfer overhead dominates",
    }
    rc = doc["modes"]["cached"]["rescache"]
    doc["rescache"] = {
        "hitRate": rc.get("hitRate", 0.0),
        "hits": rc.get("hits", 0),
        "misses": rc.get("misses", 0),
        "puts": rc.get("puts", 0),
        "staleEvictions": rc.get("staleEvictions", 0),
    }

    # equal-client-count acceptance view (ISSUE 13: ok-QPS vs the r11
    # baseline is compared AT THE SAME client count, not across ladder
    # maxima — the r11 ladder stopped at 16 clients)
    doc["ok_qps_at_16_clients"] = {}
    for wname in workloads:
        at16 = {}
        for m in doc["modes"]:
            step = next(
                (s for s in doc["modes"][m]["curves"][wname] if s["clients"] == 16),
                None,
            )
            if step is not None:
                at16[m] = step["ok_qps"]
        if at16:
            doc["ok_qps_at_16_clients"][wname] = at16

    # sampling-overhead spec (ISSUE 11): observability defaults
    # (always-on tail tracing + history recorder) vs sampling off
    # (PINOT_TPU_TAIL_TRACE=0, recorder stopped), on otherwise
    # IDENTICAL fresh brokers.  Two traps this measurement dodges:
    # both brokers start with the AIMD admission window pre-opened (a
    # fresh window ramping under a closed-loop flood sheds thousands
    # of instant 429s — admission behavior, not sampler overhead), and
    # the ratio uses ok_qps (a shed answers in microseconds, so raw
    # qps counts a storm of 429s as 50x+ "throughput").  An earlier
    # draft fell into both and measured a bogus 75x overhead.
    # tools/perf_gate.py gates the ratio: the always-on sampler must
    # stay within band of the sampling-off run forever.
    overhead_clients = ladder[-1]
    overhead_runs = {}
    for key in ("on", "off"):
        os.environ["PINOT_TPU_ADMISSION_WINDOW_INIT"] = str(
            max(64, 2 * overhead_clients)
        )
        if key == "off":
            os.environ["PINOT_TPU_TAIL_TRACE"] = "0"
        try:
            b = single_server_broker("lineitem", segments, pipeline=True)
        finally:
            os.environ.pop("PINOT_TPU_ADMISSION_WINDOW_INIT", None)
            os.environ.pop("PINOT_TPU_TAIL_TRACE", None)
        if key == "off":
            b.shutdown()  # stops the history recorder thread: fully dark
        for _ in range(2):  # warm staging + compile before measuring
            resp = b.handle_pql(Q1_PQL)
            assert not resp.exceptions, resp.exceptions
        overhead_runs[key] = _closed_loop(b, [Q1_PQL], overhead_clients, duration_s)
        if key == "on":
            b.shutdown()
    on_run, off_run = overhead_runs["on"], overhead_runs["off"]
    doc["sampling_overhead"] = {
        "clients": overhead_clients,
        "samplingOnQps": on_run["ok_qps"],
        "samplingOffQps": off_run["ok_qps"],
        "qpsRatio": round(on_run["ok_qps"] / max(off_run["ok_qps"], 1e-9), 4),
        "samplingOnP99Ms": on_run["p99_ms"],
        "samplingOffP99Ms": off_run["p99_ms"],
        "errors": {"on": on_run["errors"], "off": off_run["errors"]},
        "note": "ok-qps (shed/error responses excluded) on fresh identical "
        "brokers with the admission window pre-opened; on = defaults "
        "(always-on tail tracing + history recorder), off = "
        "PINOT_TPU_TAIL_TRACE=0 with the recorder stopped; pipelined "
        "repeated_q1 at the top ladder step",
    }

    # differential: serial, pipelined (batched), and cached must serve
    # byte-identical payloads (timing field excluded) for every
    # workload shape — and a REPEATED query against the cached broker
    # (a guaranteed cache hit) must still match the serial payload
    diffs = 0
    cache_hit_diffs = 0
    diff_queries = queries_mixed + queries_literal + [Q1_PQL]
    for q in diff_queries:
        a = _strip_timing(brokers["serial"].handle_pql(q))
        b = _strip_timing(brokers["pipelined"].handle_pql(q))
        c1 = brokers["cached"].handle_pql(q)
        c2 = brokers["cached"].handle_pql(q)  # second call: cache hit
        if len({a, b, _strip_timing(c1)}) != 1:
            diffs += 1
        if _strip_timing(c2) != a or not c2.cost.get("rescacheHits"):
            cache_hit_diffs += 1
    doc["differential"] = {
        "queries": len(diff_queries),
        "mismatches": diffs,
        "cache_hit_mismatches": cache_hit_diffs,
        "identical_payloads": diffs == 0 and cache_hit_diffs == 0,
        "note": "payload = BrokerResponse.to_json() minus "
        "timeUsedMs/requestId/cost, sorted keys, across "
        "serial/pipelined/cached; cache_hit rows re-query the cached "
        "broker and require a rescacheHits-marked identical payload",
    }
    print(json.dumps(doc, indent=1))


def _audit_main() -> None:
    """Audit-plane mode (PINOT_TPU_BENCH_MODE=audit, ISSUE 19): the two
    numbers the audit plane must keep honest forever.  (1) Overhead —
    closed-loop ok-QPS on two fresh identical brokers, audit defaults ON
    (shadow sampler + replica double-scatter at their shipped 1-in-N
    rates) vs audit fully OFF (PINOT_TPU_AUDIT_SAMPLE_N=0,
    PINOT_TPU_AUDIT_REPLICA_N=0); the sampling-overhead traps from
    serving mode apply verbatim (pre-opened admission window, ok-QPS
    ratio, never raw qps).  (2) Detection — the seeded wrong-answer
    scenario from tools/cluster_harness.py: arm a device-tier result
    corruption under load, measure how long the shadow auditor takes to
    flag + quarantine it.  Prints ONE JSON document (perf-gated by
    tools/perf_gate.py AUDIT_METRIC_SPECS against AUDIT_r19.json)."""
    from pinot_tpu.tools.cluster_harness import (
        run_audit_divergence_scenario,
        single_server_broker,
    )

    num_segments = int(os.environ.get("PINOT_TPU_BENCH_SEGMENTS", "4"))
    rows_per_segment = int(os.environ.get("PINOT_TPU_BENCH_ROWS_PER_SEGMENT", "250000"))
    duration_s = float(os.environ.get("PINOT_TPU_BENCH_AUDIT_DURATION_S", "6"))
    clients = int(os.environ.get("PINOT_TPU_BENCH_AUDIT_CLIENTS", "16"))

    segments = _build_segments(num_segments, rows_per_segment)

    import sys

    import jax

    doc = {
        "metric": "audit_overhead_ok_qps_ratio",
        "platform": jax.devices()[0].platform,
        "num_segments": num_segments,
        "total_rows": num_segments * rows_per_segment,
        "duration_s": duration_s,
        "clients": clients,
    }

    runs = {}
    for key in ("on", "off"):
        os.environ["PINOT_TPU_ADMISSION_WINDOW_INIT"] = str(max(64, 2 * clients))
        if key == "off":
            os.environ["PINOT_TPU_AUDIT_SAMPLE_N"] = "0"
            os.environ["PINOT_TPU_AUDIT_REPLICA_N"] = "0"
        try:
            b = single_server_broker("lineitem", segments, pipeline=True)
        finally:
            os.environ.pop("PINOT_TPU_ADMISSION_WINDOW_INIT", None)
            os.environ.pop("PINOT_TPU_AUDIT_SAMPLE_N", None)
            os.environ.pop("PINOT_TPU_AUDIT_REPLICA_N", None)
        for _ in range(2):  # warm staging + compile before measuring
            resp = b.handle_pql(Q1_PQL)
            assert not resp.exceptions, resp.exceptions
        runs[key] = _closed_loop(b, [Q1_PQL], clients, duration_s)
        server = b.local_servers[0]
        runs[key]["audit"] = server.auditor.snapshot()
        server.auditor.stop()
        b.shutdown()
        print(json.dumps({"mode_done": f"audit-overhead-{key}"}),
              file=sys.stderr, flush=True)
    on_run, off_run = runs["on"], runs["off"]
    ratio = round(on_run["ok_qps"] / max(off_run["ok_qps"], 1e-9), 4)
    doc["value"] = ratio
    doc["audit_overhead"] = {
        "auditOnQps": on_run["ok_qps"],
        "auditOffQps": off_run["ok_qps"],
        "okQpsRatio": ratio,
        "auditOnP99Ms": on_run["p99_ms"],
        "auditOffP99Ms": off_run["p99_ms"],
        "errors": {"on": on_run["errors"], "off": off_run["errors"]},
        "auditorOn": on_run["audit"],
        "note": "ok-qps (shed/error responses excluded) on fresh identical "
        "pipelined brokers with the admission window pre-opened; on = "
        "shipped audit defaults (shadow 1-in-64, replica 1-in-256, "
        "budgeted background oracle re-execution), off = both samplers "
        "disabled; repeated_q1 closed loop",
    }

    res = run_audit_divergence_scenario()
    print(json.dumps({"mode_done": "audit-divergence"}), file=sys.stderr, flush=True)
    doc["divergence"] = res
    doc["detect_ms"] = res.get("detectMs")
    doc["detected"] = 1 if res.get("detected") else 0
    doc["post_quarantine_mismatches"] = res.get("postQuarantineMismatches")
    doc["divergence_failed_queries"] = res.get("failedQueries")
    print(json.dumps(doc, indent=1))


def _multichip_main() -> None:
    """Mesh serving-ladder mode (PINOT_TPU_BENCH_MODE=multichip): the
    SAME broker-path workload served by three execution-plane configs
    over an N-device host (forced virtual CPU devices off-chip; the
    real slice on TPU):

      single_lane  one lane, one chip — the pre-mesh serving path
      sharded      one lane over ALL N chips (pure SPMD speedup:
                   segment axis sharded, psum merge over ICI)
      lane_group   max(2, N/4) lanes of N/lanes chips (2x4 on an
                   8-device host) — per-chip-group lanes, the
                   pod-serving configuration (per-lane utilization)

    Emits per-mode closed-loop ladders, scan-heavy rows/s, the
    sharded-vs-single speedup, per-lane utilization (busy fraction +
    achieved bytes/s per lane with sum-consistent rollups), and a
    byte-identity differential across all three configs.  Runs under
    x64 so the differential compares exact aggregation payloads (the
    tier-1 suite holds the same contract).  Prints ONE JSON document
    (metric prefix ``multichip_`` — tools/perf_gate.py gates it
    against the committed MULTICHIP_r06.json)."""
    import sys

    import jax

    jax.config.update("jax_enable_x64", True)
    from pinot_tpu.engine.mesh import build_topology
    from pinot_tpu.tools.cluster_harness import single_server_broker
    from pinot_tpu.tools.serving_curve import mixed_workload

    devices = jax.devices()
    n_dev = len(devices)
    num_segments = int(os.environ.get("PINOT_TPU_BENCH_SEGMENTS", str(max(8, n_dev))))
    rows_per_segment = int(
        os.environ.get("PINOT_TPU_BENCH_ROWS_PER_SEGMENT", "125000")
    )
    duration_s = float(os.environ.get("PINOT_TPU_BENCH_SERVE_DURATION_S", "4"))
    ladder = [
        int(c)
        for c in os.environ.get("PINOT_TPU_BENCH_SERVE_CLIENTS", "1,4").split(",")
    ]
    segments = _build_segments(num_segments, rows_per_segment)
    total_rows = num_segments * rows_per_segment
    queries_mixed = mixed_workload(segments)

    lanes = max(2, n_dev // 4)  # 8 devices -> 2 lanes of 4
    topologies = {
        "single_lane": None,  # trivial topology: the pre-mesh path
        "sharded": build_topology(devices, 1, n_dev),
        "lane_group": build_topology(devices, lanes, max(1, n_dev // lanes)),
    }
    doc = {
        "metric": "multichip_serving_ladder_rows_per_sec",
        "platform": devices[0].platform,
        "n_devices": n_dev,
        # informational, NOT a config key: on virtual CPU devices the
        # attainable sharded speedup is bounded by host cores, not
        # devices — a 2-core container cannot show the 8-chip win
        # (the committed ISSUE 12 acceptance figure is the on-chip /
        # many-core number; this artifact gates regressions, not the
        # absolute claim)
        "host_cpus": os.cpu_count(),
        "num_segments": num_segments,
        "total_rows": total_rows,
        "duration_s_per_step": duration_s,
        "modes": {},
        "utilization": {},
        "rows_per_sec": {},
    }
    brokers = {}
    for mode, topo in topologies.items():
        kwargs = {} if topo is None else {"topology": topo}
        broker = single_server_broker("lineitem", segments, **kwargs)
        brokers[mode] = broker
        server = broker.local_servers[0]
        for q in queries_mixed + [Q1_PQL]:  # warm staging + compile
            for _ in range(2):
                resp = broker.handle_pql(q)
                assert not resp.exceptions, resp.exceptions
        ladder_t0 = time.monotonic()
        # scan-heavy single-shape ladder: Q1 rows/s is the headline
        curves = [_closed_loop(broker, [Q1_PQL], c, duration_s) for c in ladder]
        best_qps = max(s["ok_qps"] for s in curves)
        du = server.device_utilization(roofline_since=ladder_t0)
        recent = du.get("recent") or {}
        util = {
            "busyFraction": (du.get("occupancy") or {}).get("busyFraction", 0.0),
            "achievedBytesPerSec": recent.get("achievedBytesPerSec", 0.0),
            "queries": recent.get("queries", 0),
        }
        if "lanes" in recent:
            util["lanes"] = [
                {
                    "achievedBytesPerSec": l["achievedBytesPerSec"],
                    "deviceBytes": l["deviceBytes"],
                    "queries": l["queries"],
                }
                for l in recent["lanes"]
            ]
            util["laneSumAchievedBytesPerSec"] = sum(
                l["achievedBytesPerSec"] for l in recent["lanes"]
            )
        occ = du.get("occupancy") or {}
        if "lanes" in occ:
            util["laneBusyFractions"] = [
                l["busyFraction"] for l in occ["lanes"]
            ]
        doc["modes"][mode] = {
            "mesh": server.topology.snapshot(),
            "curves": curves,
            "lane": server.lanes.stats() if server.lanes is not None else None,
        }
        doc["utilization"][mode] = util
        doc["rows_per_sec"][mode] = round(best_qps * total_rows, 1)
        print(json.dumps({"mode_done": mode}), file=sys.stderr, flush=True)

    doc["sharded_vs_single"] = round(
        doc["rows_per_sec"]["sharded"]
        / max(doc["rows_per_sec"]["single_lane"], 1e-9),
        3,
    )
    doc["lane_group_vs_single"] = round(
        doc["rows_per_sec"]["lane_group"]
        / max(doc["rows_per_sec"]["single_lane"], 1e-9),
        3,
    )

    # byte-identity differential across every execution-plane config:
    # the mesh must be invisible in payloads
    diffs = 0
    for q in queries_mixed + [Q1_PQL]:
        payloads = {m: _strip_timing(b.handle_pql(q)) for m, b in brokers.items()}
        if len(set(payloads.values())) != 1:
            diffs += 1
    doc["differential"] = {
        "queries": len(queries_mixed) + 1,
        "mismatches": diffs,
        "identical_payloads": diffs == 0,
        "note": "payload = BrokerResponse.to_json() minus "
        "timeUsedMs/requestId/cost, sorted keys, across "
        "single_lane/sharded/lane_group",
    }
    for b in brokers.values():
        b.local_servers[0].shutdown()
    print(json.dumps(doc, indent=1))


def _probe_tpu(timeout_s: float = 180.0) -> bool:
    """Subprocess backend probe (pinot_tpu.utils.platform.probe_device,
    the one shared implementation)."""
    from pinot_tpu.utils.platform import probe_device

    return probe_device(timeout_s)


def _arm_deadline():
    """The tunnel can wedge MID-run (after a healthy probe), hanging a
    device call forever inside C code; without this the driver's bench
    run records NOTHING.  A daemon TIMER THREAD (not SIGALRM — a Python
    signal handler only runs when the main thread returns to the
    interpreter loop, which a wedged C call never does; blocking device
    calls do release the GIL) prints an explicit degraded record and
    exits, so a wedge still leaves a parseable result line.  Returns
    the timer; call .cancel() once the measurement is done."""
    import threading

    deadline_s = int(os.environ.get("PINOT_TPU_BENCH_DEADLINE_S", "2400"))
    if deadline_s <= 0:
        return None

    def on_deadline():
        print(
            json.dumps(
                {
                    "metric": "tpch_q1_rows_scanned_per_sec_per_chip",
                    "value": 0.0,
                    "unit": "rows/s",
                    "vs_baseline": 0.0,
                    "degraded": True,
                    "tpu_capture_ref": TPU_CAPTURE_REF,
                    "detail": {"error": f"deadline {deadline_s}s exceeded (tunnel wedge?)"},
                },
            ),
            flush=True,
        )
        # nonzero so return-code automation can tell a wedged run from a
        # clean one (ADVICE r3); configurable for drivers that discard
        # stdout of nonzero-exit runs
        try:
            code = int(os.environ.get("PINOT_TPU_BENCH_DEGRADED_EXIT", "3"))
        except ValueError:
            code = 3  # a junk env value must not disarm the watchdog
        os._exit(code)

    timer = threading.Timer(deadline_s, on_deadline)
    timer.daemon = True
    timer.start()
    return timer


def main() -> None:
    deadline = _arm_deadline()
    mode = os.environ.get("PINOT_TPU_BENCH_MODE")
    # FORCE_CPU: deterministic CPU mode for the smoke test (short-
    # circuits past the tunnel probe and its timeout); otherwise a
    # failed probe falls back to CPU rather than hanging the run.
    # Multichip mode needs the virtual-device request BEFORE first
    # backend init (xla_force_host_platform_device_count).
    if os.environ.get("PINOT_TPU_BENCH_FORCE_CPU") == "1" or not _probe_tpu():
        from pinot_tpu.utils.platform import force_cpu_mesh

        force_cpu_mesh(
            int(os.environ.get("PINOT_TPU_BENCH_MESH_DEVICES", "8"))
            if mode == "multichip"
            else 1
        )

    if mode == "multichip":
        try:
            _multichip_main()
        finally:
            if deadline is not None:
                deadline.cancel()
        return

    if mode == "serving":
        try:
            _serving_main()
        finally:
            if deadline is not None:
                deadline.cancel()
        return

    if mode == "join":
        try:
            _join_main()
        finally:
            if deadline is not None:
                deadline.cancel()
        return

    if mode == "audit":
        try:
            _audit_main()
        finally:
            if deadline is not None:
                deadline.cancel()
        return

    import jax

    platform = jax.devices()[0].platform
    on_tpu = platform not in ("cpu",)

    num_segments = int(os.environ.get("PINOT_TPU_BENCH_SEGMENTS", "16" if on_tpu else "4"))
    rows_per_segment = int(
        os.environ.get(
            "PINOT_TPU_BENCH_ROWS_PER_SEGMENT", "8388608" if on_tpu else "250000"
        )
    )
    iters = int(os.environ.get("PINOT_TPU_BENCH_ITERS", "20"))
    total_rows = num_segments * rows_per_segment

    segments = _build_segments(num_segments, rows_per_segment)
    rows_per_sec, per_query_ms, e2e_ms = _kernel_rows_per_sec(segments, iters)
    import sys

    print(
        f"# kernel phase done: {rows_per_sec:,.0f} rows/s "
        f"({per_query_ms:.2f} ms/query device-marginal)",
        file=sys.stderr,
        flush=True,
    )
    broker_report, selective = _broker_latencies(segments)
    rj = broker_report.to_json()
    p50_s = max(broker_report.percentile(50), 1e-6) / 1000.0

    # vs_baseline compares like-for-like (ADVICE r1): the baseline is
    # the reference broker's reported query time, so the ratio uses our
    # broker-path p50 (true client-observed per-query latency); the
    # kernel marginal-batch ratio is reported alongside in detail.
    if deadline is not None:
        deadline.cancel()  # measurement done: the wedge deadline no longer applies
    print(
        json.dumps(
            {
                "metric": "tpch_q1_rows_scanned_per_sec_per_chip",
                "value": round(rows_per_sec, 1),
                "unit": "rows/s",
                "vs_baseline": round(total_rows / p50_s / BASELINE_ROWS_PER_SEC, 3),
                # the north-star target is an on-chip number (BASELINE.md
                # "on v5e-8"); a CPU fallback is an environment artifact
                # (tunnel down), not a measurement of the design — the
                # committed on-chip record lives in tpu_capture_ref
                "degraded": not on_tpu,
                **({"tpu_capture_ref": TPU_CAPTURE_REF} if not on_tpu else {}),
                "detail": {
                    "vs_baseline_kernel_marginal": round(
                        rows_per_sec / BASELINE_ROWS_PER_SEC, 3
                    ),
                    "platform": platform,
                    "total_rows": total_rows,
                    "num_segments": num_segments,
                    "per_query_ms": round(per_query_ms, 3),
                    "batch_amortized_ms": round(e2e_ms, 3),
                    "method": "marginal-batch (fixed RTT subtracted); "
                    "batch_amortized spreads one fetch RTT over the batch; "
                    "broker numbers are true per-query client-observed "
                    "latency incl. one tunnel RTT each",
                    "iters": iters,
                    "broker_p50_ms": rj["p50Ms"],
                    "broker_p99_ms": rj["p99Ms"],
                    "broker_rows_per_sec_p50": round(total_rows / p50_s, 1),
                    **selective,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
