"""Benchmark: rows scanned/sec on a TPC-H-Q1-shaped query (BASELINE.md).

The reference's stored numbers (contrib/pinot-benchmark, BASELINE.md):
full-scan SUM queries on 6M-row lineitem run at ~14.2M rows/s in the
single config (422 ms for Q0).  The north star is rows-scanned/sec/chip
on a Q1-shaped filtered group-by.

This harness stages synthetic lineitem segments into device memory and
times the compiled query kernel steady-state (post-compile) by the
marginal-batch method: time back-to-back batches of M_large and M_small
dispatches (each batch fetches its last result, and the device stream
is FIFO, so every dispatched query provably executed); the difference
divided by (M_large - M_small) is the sustained per-query device time
with the fixed host<->device round-trip latency subtracted out — on a
tunneled chip that latency otherwise swamps the device time.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "rows/s", "vs_baseline": N}
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_ROWS_PER_SEC = 14_200_000.0  # BASELINE.md: 6,001,215 rows / 0.422 s


def main() -> None:
    import jax

    platform = jax.devices()[0].platform
    on_tpu = platform not in ("cpu",)

    num_segments = int(os.environ.get("PINOT_TPU_BENCH_SEGMENTS", "4"))
    rows_per_segment = int(
        os.environ.get(
            "PINOT_TPU_BENCH_ROWS_PER_SEGMENT", "2000000" if on_tpu else "250000"
        )
    )
    iters = int(os.environ.get("PINOT_TPU_BENCH_ITERS", "20"))
    total_rows = num_segments * rows_per_segment

    from pinot_tpu.engine.context import get_table_context
    from pinot_tpu.engine.device import stage_segments
    from pinot_tpu.engine.executor import QueryExecutor
    from pinot_tpu.engine.kernel import make_table_kernel
    from pinot_tpu.engine.plan import build_query_inputs, build_static_plan
    from pinot_tpu.pql import optimize_request, parse_pql
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment

    segments = [
        synthetic_lineitem_segment(rows_per_segment, seed=11 + i, name=f"li{i}")
        for i in range(num_segments)
    ]

    # TPC-H Q1 shape: date-range filter, 2-col group-by, multiple SUMs
    pql = (
        "SELECT sum(l_quantity), sum(l_extendedprice), sum(l_discount), count(*) "
        "FROM lineitem WHERE l_shipdate <= '1998-09-02' "
        "GROUP BY l_returnflag, l_linestatus TOP 10"
    )
    request = optimize_request(parse_pql(pql))

    ctx = get_table_context(segments)
    needed = sorted(set(request.referenced_columns()))
    staged = stage_segments(
        segments,
        needed,
        raw_columns=("l_quantity", "l_extendedprice", "l_discount"),
        gfwd_columns=("l_returnflag", "l_linestatus"),
        ctx=ctx,
    )
    plan = build_static_plan(request, ctx, staged)
    assert plan.on_device, "bench query must run on device"
    q_np = build_query_inputs(request, plan, ctx, staged)

    import jax.numpy as jnp

    def conv(x):
        if isinstance(x, np.ndarray):
            return jnp.asarray(x)
        if isinstance(x, list):
            return [conv(v) for v in x]
        if isinstance(x, dict):
            return {k: conv(v) for k, v in x.items()}
        return x

    q_inputs = conv(q_np)
    seg_arrays = {"valid": staged.valid}
    for name in needed:
        col = staged.column(name)
        if col.fwd is not None:
            seg_arrays[f"{name}.fwd"] = col.fwd
        if col.dict_vals is not None:
            seg_arrays[f"{name}.dict"] = col.dict_vals
        if col.raw is not None:
            seg_arrays[f"{name}.raw"] = col.raw
        if col.gfwd is not None:
            seg_arrays[f"{name}.gfwd"] = col.gfwd

    kernel = make_table_kernel(plan)

    def fetch(outs):
        # pull one scalar leaf to the host: executions are FIFO on the
        # device stream, so this proves every dispatched query finished
        leaf = next(iter(outs.values()))
        while isinstance(leaf, (tuple, list)):
            leaf = leaf[0]
        np.asarray(leaf)

    def run_batch(m: int) -> float:
        t0 = time.perf_counter()
        outs = None
        for _ in range(m):
            outs = kernel(seg_arrays, q_inputs)
        fetch(outs)
        return time.perf_counter() - t0

    fetch(kernel(seg_arrays, q_inputs))  # compile
    run_batch(2)  # warm

    # Marginal per-query time from back-to-back batches: subtracting the
    # small batch removes the fixed host<->device round-trip latency
    # (which on a tunneled chip otherwise swamps the device time), so
    # the metric reflects sustained device throughput.
    m_small, m_large = 5, 5 + iters
    diffs = []
    for _ in range(3):
        t_large = run_batch(m_large)
        t_small = run_batch(m_small)
        diffs.append((t_large - t_small) / (m_large - m_small))
    median = max(sorted(diffs)[len(diffs) // 2], 1e-6)
    rows_per_sec = total_rows / median

    print(
        json.dumps(
            {
                "metric": "tpch_q1_rows_scanned_per_sec_per_chip",
                "value": round(rows_per_sec, 1),
                "unit": "rows/s",
                "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 3),
                "detail": {
                    "platform": platform,
                    "total_rows": total_rows,
                    "num_segments": num_segments,
                    "per_query_ms": round(median * 1000, 3),
                    "method": "marginal-batch (fixed RTT subtracted)",
                    "iters": iters,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
