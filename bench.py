"""Benchmark: rows scanned/sec on a TPC-H-Q1-shaped query (BASELINE.md).

The reference's stored numbers (contrib/pinot-benchmark, BASELINE.md):
full-scan SUM queries on 6M-row lineitem run at ~14.2M rows/s in the
single config (422 ms for Q0).  The north star is rows-scanned/sec/chip
on a Q1-shaped filtered group-by.

This harness stages synthetic lineitem segments into device memory and
times the compiled query kernel end-to-end (device compute + result
readback), steady-state (post-compile), median of N iterations.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "rows/s", "vs_baseline": N}
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_ROWS_PER_SEC = 14_200_000.0  # BASELINE.md: 6,001,215 rows / 0.422 s


def main() -> None:
    import jax

    platform = jax.devices()[0].platform
    on_tpu = platform not in ("cpu",)

    num_segments = int(os.environ.get("PINOT_TPU_BENCH_SEGMENTS", "4"))
    rows_per_segment = int(
        os.environ.get(
            "PINOT_TPU_BENCH_ROWS_PER_SEGMENT", "2000000" if on_tpu else "250000"
        )
    )
    iters = int(os.environ.get("PINOT_TPU_BENCH_ITERS", "20"))
    total_rows = num_segments * rows_per_segment

    from pinot_tpu.engine.context import get_table_context
    from pinot_tpu.engine.device import stage_segments
    from pinot_tpu.engine.executor import QueryExecutor
    from pinot_tpu.engine.kernel import make_table_kernel
    from pinot_tpu.engine.plan import build_query_inputs, build_static_plan
    from pinot_tpu.pql import optimize_request, parse_pql
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment

    segments = [
        synthetic_lineitem_segment(rows_per_segment, seed=11 + i, name=f"li{i}")
        for i in range(num_segments)
    ]

    # TPC-H Q1 shape: date-range filter, 2-col group-by, multiple SUMs
    pql = (
        "SELECT sum(l_quantity), sum(l_extendedprice), sum(l_discount), count(*) "
        "FROM lineitem WHERE l_shipdate <= '1998-09-02' "
        "GROUP BY l_returnflag, l_linestatus TOP 10"
    )
    request = optimize_request(parse_pql(pql))

    ctx = get_table_context(segments)
    needed = sorted(set(request.referenced_columns()))
    staged = stage_segments(segments, needed)
    plan = build_static_plan(request, ctx, staged)
    assert plan.on_device, "bench query must run on device"
    q_np = build_query_inputs(request, plan, ctx, staged)

    import jax.numpy as jnp

    def conv(x):
        if isinstance(x, np.ndarray):
            return jnp.asarray(x)
        if isinstance(x, list):
            return [conv(v) for v in x]
        if isinstance(x, dict):
            return {k: conv(v) for k, v in x.items()}
        return x

    q_inputs = conv(q_np)
    seg_arrays = {"valid": staged.valid}
    for name in needed:
        col = staged.column(name)
        if col.fwd is not None:
            seg_arrays[f"{name}.fwd"] = col.fwd
        if col.dict_vals is not None:
            seg_arrays[f"{name}.dict"] = col.dict_vals

    kernel = make_table_kernel(plan)

    def run_once():
        outs = kernel(seg_arrays, q_inputs)
        jax.block_until_ready(outs)
        return outs

    run_once()  # compile
    run_once()  # warm
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        run_once()
        times.append(time.perf_counter() - t0)
    median = sorted(times)[len(times) // 2]
    rows_per_sec = total_rows / median

    print(
        json.dumps(
            {
                "metric": "tpch_q1_rows_scanned_per_sec_per_chip",
                "value": round(rows_per_sec, 1),
                "unit": "rows/s",
                "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 3),
                "detail": {
                    "platform": platform,
                    "total_rows": total_rows,
                    "num_segments": num_segments,
                    "median_ms": round(median * 1000, 3),
                    "iters": iters,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
