// Native fixed-bit codec for dictId forward indexes.
//
// The reference packs dictIds with minimal bits in Java word-at-a-time
// readers/writers (pinot-core io/reader/impl/v1/FixedBitSingleValueReader.java,
// io/writer/impl/FixedBitSingleValueWriter.java). This is the native
// equivalent used at segment write/load time: LSB-first bit stream,
// bit i of the stream lives at (bytes[i>>3] >> (i&7)) & 1 — matching
// pinot_tpu/segment/bitpack.py's numpy fallback format exactly.
//
// Build: make -C native   (g++ -O3 -shared -fPIC)
#include <cstdint>
#include <cstring>

extern "C" {

// values[n] with values < 2^nbits  ->  out[ceil(n*nbits/8)] (zeroed by caller)
void pinot_pack_bits(const int32_t* values, int64_t n, int nbits, uint8_t* out) {
    uint64_t acc = 0;   // bit accumulator
    int filled = 0;     // bits currently in acc
    int64_t out_pos = 0;
    for (int64_t i = 0; i < n; ++i) {
        acc |= (static_cast<uint64_t>(static_cast<uint32_t>(values[i])) &
                ((nbits == 64) ? ~0ULL : ((1ULL << nbits) - 1))) << filled;
        filled += nbits;
        while (filled >= 8) {
            out[out_pos++] = static_cast<uint8_t>(acc & 0xFF);
            acc >>= 8;
            filled -= 8;
        }
    }
    if (filled > 0) {
        out[out_pos++] = static_cast<uint8_t>(acc & 0xFF);
    }
}

// packed bytes -> out[n] int32
void pinot_unpack_bits(const uint8_t* packed, int64_t n, int nbits, int32_t* out) {
    uint64_t acc = 0;
    int filled = 0;
    int64_t in_pos = 0;
    const uint64_t mask = (nbits == 64) ? ~0ULL : ((1ULL << nbits) - 1);
    for (int64_t i = 0; i < n; ++i) {
        while (filled < nbits) {
            acc |= static_cast<uint64_t>(packed[in_pos++]) << filled;
            filled += 8;
        }
        out[i] = static_cast<int32_t>(acc & mask);
        acc >>= nbits;
        filled -= nbits;
    }
}

}  // extern "C"
