// Native CSV ingest: parse a delimiter-separated body straight into
// columnar arrays, one pass, no per-row Python objects.
//
// The reference reads ingest files row-at-a-time through Java record
// readers (pinot-core data/readers/CSVRecordReader.java) feeding the
// two-pass segment builder. Here the hot path is columnar from the
// start: numeric cells are parsed to int64/double in place, string
// cells are recorded as (offset,length) slices into the file buffer
// and materialized lazily by the Python side.
//
// Scope: the fast path handles unquoted CSV only (no '"' anywhere in
// the buffer — the caller checks and falls back to Python's csv module
// otherwise), LF or CRLF line endings, missing trailing cells filled
// with per-column defaults, blank lines skipped.
//
// Build: make -C native
#include <charconv>
#include <cstdint>
#include <cstring>

namespace {

// Parse one numeric cell [s, e). Empty -> default. Integer columns fall
// back to double-then-truncate (the int(float(x)) coercion the Python
// DataType.convert applies). Returns false on unparseable garbage.
bool parse_i64(const char* s, const char* e, int64_t def, int64_t* out) {
    if (s == e) { *out = def; return true; }  // truly empty -> default
    while (s < e && (*s == ' ' || *s == '\t')) ++s;
    while (e > s && (e[-1] == ' ' || e[-1] == '\t')) --e;
    if (s == e) return false;  // whitespace-only: python raises, so fall back
    auto r = std::from_chars(s, e, *out);
    if (r.ec == std::errc() && r.ptr == e) return true;
    double d;
    auto rd = std::from_chars(s, e, d);
    if (rd.ec == std::errc() && rd.ptr == e && d == d &&
        d >= -9.2e18 && d <= 9.2e18) {
        *out = static_cast<int64_t>(d);
        return true;
    }
    return false;  // NaN / out-of-range -> caller falls back (loud python error)
}

bool parse_f64(const char* s, const char* e, double def, double* out) {
    if (s == e) { *out = def; return true; }  // truly empty -> default
    while (s < e && (*s == ' ' || *s == '\t')) ++s;
    while (e > s && (e[-1] == ' ' || e[-1] == '\t')) --e;
    if (s == e) return false;  // whitespace-only: python raises, so fall back
    auto r = std::from_chars(s, e, *out);
    return r.ec == std::errc() && r.ptr == e;
}

}  // namespace

extern "C" {

// types[c]: 0 = int64, 1 = double, 2 = raw slice (strings / MV cells),
// 3 = skip (tokenized but nothing recorded — non-schema columns).
// Parsing starts at buf[start] (the caller points this past the header
// line so the file buffer is never copied). Recorded slice offsets are
// absolute into buf. i64_outs[c] / f64_outs[c]: preallocated [max_rows]
// when types[c] selects them, else may be null. str_offs[c]:
// preallocated [2*max_rows] (offset,length pairs) when types[c]==2.
// Returns rows parsed; -1 = row wider than ncols; -2 = bad numeric cell.
int64_t pinot_csv_parse(const char* buf, int64_t len, int64_t start,
                        char delim, int ncols,
                        const int8_t* types, const int64_t* i64_def,
                        const double* f64_def, int64_t max_rows,
                        int64_t* const* i64_outs, double* const* f64_outs,
                        int64_t* const* str_offs) {
    int64_t row = 0;
    int64_t pos = start;
    while (pos < len && row < max_rows) {
        // locate end of line
        const char* nl = static_cast<const char*>(
            memchr(buf + pos, '\n', static_cast<size_t>(len - pos)));
        int64_t line_end = nl ? (nl - buf) : len;
        int64_t next = nl ? line_end + 1 : len;
        if (line_end > pos && buf[line_end - 1] == '\r') --line_end;  // CRLF
        if (line_end == pos) { pos = next; continue; }  // blank line

        int col = 0;
        int64_t cell_start = pos;
        for (int64_t i = pos; i <= line_end; ++i) {
            if (i < line_end && buf[i] != delim) continue;
            if (col >= ncols) return -1;
            const char* cs = buf + cell_start;
            const char* ce = buf + i;
            switch (types[col]) {
                case 0:
                    if (!parse_i64(cs, ce, i64_def[col], &i64_outs[col][row]))
                        return -2;
                    break;
                case 1:
                    if (!parse_f64(cs, ce, f64_def[col], &f64_outs[col][row]))
                        return -2;
                    break;
                case 2:
                    str_offs[col][2 * row] = cell_start;
                    str_offs[col][2 * row + 1] = i - cell_start;
                    break;
                default:  // 3: skip
                    break;
            }
            ++col;
            cell_start = i + 1;
        }
        // missing trailing cells -> defaults / empty slices
        for (; col < ncols; ++col) {
            switch (types[col]) {
                case 0: i64_outs[col][row] = i64_def[col]; break;
                case 1: f64_outs[col][row] = f64_def[col]; break;
                case 2:
                    str_offs[col][2 * row] = line_end;
                    str_offs[col][2 * row + 1] = 0;
                    break;
                default:
                    break;
            }
        }
        ++row;
        pos = next;
    }
    return row;
}

}  // extern "C"
