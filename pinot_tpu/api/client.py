"""Client library: connections, result sets, prepared statements.

The pinot-api equivalent (``pinot-api/.../client/Connection.java``,
``ConnectionFactory.java``, ``ResultSetGroup``): connect to one or more
brokers (static list, or dynamically from a controller's table list —
the ExternalViewReader analog), round-robin broker selection per query,
typed accessors over the JSON response.
"""
from __future__ import annotations

import json
import random
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional, Sequence


class PinotClientError(Exception):
    pass


class ResultSet:
    """One aggregation / group-by / selection result block."""

    def __init__(self, block: Dict[str, Any], kind: str) -> None:
        self._block = block
        self.kind = kind  # "aggregation" | "groupby" | "selection"

    # -- selection / tabular ------------------------------------------
    def get_column_names(self) -> List[str]:
        if self.kind == "selection":
            return list(self._block.get("columns", []))
        if self.kind == "groupby":
            return list(self._block.get("groupByColumns", [])) + [self._block.get("function", "value")]
        return [self._block.get("function", "value")]

    def get_row_count(self) -> int:
        if self.kind == "selection":
            return len(self._block.get("results", []))
        if self.kind == "groupby":
            return len(self._block.get("groupByResult", []))
        return 1

    def get_column_count(self) -> int:
        return len(self.get_column_names())

    def get(self, row: int, col: int = 0) -> Any:
        if self.kind == "selection":
            return self._block["results"][row][col]
        if self.kind == "groupby":
            entry = self._block["groupByResult"][row]
            groups = entry["group"]
            if col < len(groups):
                return groups[col]
            return entry["value"]
        return self._block.get("value")

    def get_string(self, row: int, col: int = 0) -> str:
        return str(self.get(row, col))

    def get_int(self, row: int, col: int = 0) -> int:
        return int(float(self.get(row, col)))

    def get_double(self, row: int, col: int = 0) -> float:
        return float(self.get(row, col))

    # group-by helpers (reference ResultSet.getGroupKeyString)
    def get_group_key(self, row: int) -> List[str]:
        if self.kind != "groupby":
            raise PinotClientError("not a group-by result")
        return list(self._block["groupByResult"][row]["group"])


class ResultSetGroup:
    def __init__(self, response: Dict[str, Any]) -> None:
        self.response = response
        self._sets: List[ResultSet] = []
        if "selectionResults" in response:
            self._sets.append(ResultSet(response["selectionResults"], "selection"))
        for block in response.get("aggregationResults", []):
            kind = "groupby" if "groupByResult" in block else "aggregation"
            self._sets.append(ResultSet(block, kind))

    @property
    def result_set_count(self) -> int:
        return len(self._sets)

    def get_result_set(self, index: int) -> ResultSet:
        return self._sets[index]

    @property
    def exceptions(self) -> List[Dict[str, Any]]:
        return self.response.get("exceptions", [])

    @property
    def execution_stats(self) -> Dict[str, Any]:
        return {
            k: self.response.get(k)
            for k in ("numDocsScanned", "totalDocs", "timeUsedMs", "numServersQueried", "numServersResponded")
        }


class Connection:
    def __init__(self, broker_urls: Sequence[str], timeout_s: float = 60.0) -> None:
        if not broker_urls:
            raise PinotClientError("no brokers")
        self.broker_urls = [u.rstrip("/") for u in broker_urls]
        self.timeout_s = timeout_s
        self._rng = random.Random()

    def execute(
        self, pql: str, trace: bool = False, timeout_ms: Optional[float] = None
    ) -> ResultSetGroup:
        """``timeout_ms`` shortens this query's broker budget (clamped
        server-side to the broker's configured ceiling)."""
        url = self._rng.choice(self.broker_urls) + "/query"
        request_body: Dict[str, Any] = {"pql": pql, "trace": trace}
        if timeout_ms is not None:
            request_body["timeoutMs"] = timeout_ms
        body = json.dumps(request_body).encode("utf-8")
        req = urllib.request.Request(url, data=body, headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                payload = json.loads(r.read())
        except OSError as e:
            raise PinotClientError(f"broker {url}: {e}") from e
        return ResultSetGroup(payload)

    def prepare_statement(self, pql_template: str) -> "PreparedStatement":
        return PreparedStatement(self, pql_template)


class PreparedStatement:
    """``?``-placeholder statement (reference PreparedStatement)."""

    def __init__(self, connection: Connection, template: str) -> None:
        self.connection = connection
        self.template = template
        self._values: Dict[int, str] = {}

    def set_string(self, index: int, value: str) -> None:
        escaped = value.replace("'", "''")
        self._values[index] = f"'{escaped}'"

    def set_int(self, index: int, value: int) -> None:
        self._values[index] = str(int(value))

    def set_double(self, index: int, value: float) -> None:
        self._values[index] = repr(float(value))

    def execute(self) -> ResultSetGroup:
        parts = self.template.split("?")
        if len(parts) - 1 != len(self._values):
            raise PinotClientError("not all placeholders bound")
        out = parts[0]
        for i in range(1, len(parts)):
            out += self._values[i - 1] + parts[i]
        return self.connection.execute(out)


class ConnectionFactory:
    """``fromHostList`` / ``fromController`` (DynamicBrokerSelector analog:
    the controller's broker list plays ZK's role)."""

    @staticmethod
    def from_host_list(broker_urls: Sequence[str]) -> Connection:
        return Connection(broker_urls)

    @staticmethod
    def from_controller(controller_url: str) -> Connection:
        url = controller_url.rstrip("/") + "/brokers"
        try:
            with urllib.request.urlopen(url, timeout=10) as r:
                payload = json.loads(r.read())
            brokers = payload.get("brokers", [])
        except OSError as e:
            raise PinotClientError(f"controller {controller_url}: {e}") from e
        if not brokers:
            raise PinotClientError("controller reports no brokers")
        return Connection(brokers)
