from pinot_tpu.api.client import Connection, ConnectionFactory, ResultSetGroup

__all__ = ["Connection", "ConnectionFactory", "ResultSetGroup"]
