"""Warm-restart bench: the persistent-compile-cache + prewarm story in
numbers (ISSUE 16), producing one perf-gateable JSON document.

Three phases, each a FRESH python process (the jit/XLA executable
caches are process-local, so an in-process "restart" would overstate
warmth) sharing one persistent compile cache directory:

- ``cold``    — empty cache: first-query pays the genuine XLA compile
                (``compile.cold`` = shapes), then a steady closed loop
                measures the warmed p50.  Writes the broker's top-K
                workload snapshot (the prewarm feed) for phase 3.
- ``restart`` — same cache, fresh process, NO prewarm: the first query
                re-traces against the persistent cache
                (``compile.persistentHit``, ``compile.cold == 0``).
- ``prewarm`` — same cache, fresh process: the worker replays the
                phase-1 workload snapshot through
                ``build_prewarm_spec`` BEFORE any query, so the first
                serving query is ``compile.prewarmed``-backed.

The document's headline ``value`` is the prewarmed first-query latency;
``cold_free_restart`` is 1.0 only when BOTH restart phases kept
``compile.cold`` at zero (the gate's exact bar).  On a real TPU the
cold compile is ~25s and the warm-restart first query is re-trace-only,
so the first-query-over-steady ratio collapses toward 1; CPU test runs
keep the same mechanism at millisecond scale.

Usage:
  PINOT_TPU_COMPILE_CACHE_DIR is managed internally; just run
  python -m pinot_tpu.tools.restart_bench > RESTART_r16.json
  python -m pinot_tpu.tools.perf_gate RESTART_r16.json --baseline RESTART_r16.json
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional

TABLE = "restartT"
PQL = f"SELECT sum(metInt), count(*) FROM {TABLE} GROUP BY dimStr TOP 5"
ROWS_PER_SEGMENT = 120
NUM_SEGMENTS = 4


def _build_broker():
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.tools.datagen import make_test_schema, random_rows
    from pinot_tpu.tools.cluster_harness import single_server_broker

    schema = make_test_schema(with_mv=False)
    rows = random_rows(schema, ROWS_PER_SEGMENT * NUM_SEGMENTS, seed=11)
    segs = [
        build_segment(
            schema,
            rows[i * ROWS_PER_SEGMENT : (i + 1) * ROWS_PER_SEGMENT],
            TABLE,
            f"seg{i}",
        )
        for i in range(NUM_SEGMENTS)
    ]
    return single_server_broker(TABLE, segs, pipeline=True)


def _meters(server) -> Dict[str, int]:
    snap = server.metrics.snapshot()["meters"]
    return {
        name: int(snap.get(name, {}).get("count", 0))
        for name in (
            "compile.cold",
            "compile.warm",
            "compile.persistentHit",
            "compile.persistentMiss",
            "compile.prewarmed",
            "prewarm.compiled",
            "prewarm.failed",
        )
    }


def run_phase(phase: str, workload_path: Optional[str], steady_n: int) -> Dict[str, Any]:
    broker = _build_broker()
    server = broker.local_servers[0]
    try:
        if phase == "prewarm":
            with open(workload_path) as f:
                entries = json.load(f)
            server.prewarm.workload_source = lambda tables, n: entries
            server.prewarm.request_prewarm(TABLE)
            deadline = time.monotonic() + 30.0
            while server.prewarm.warming and time.monotonic() < deadline:
                time.sleep(0.005)
            assert not server.prewarm.warming, "prewarm never finished"
        t0 = time.perf_counter()
        resp = broker.handle_pql(PQL)
        first_ms = (time.perf_counter() - t0) * 1000.0
        assert not resp.exceptions, resp.exceptions
        lat: List[float] = []
        for _ in range(steady_n):
            t0 = time.perf_counter()
            resp = broker.handle_pql(PQL)
            lat.append((time.perf_counter() - t0) * 1000.0)
            assert not resp.exceptions, resp.exceptions
        out = {
            "phase": phase,
            "firstQueryMs": round(first_ms, 3),
            "steadyP50Ms": round(statistics.median(lat), 3),
            "meters": _meters(server),
        }
        if phase == "cold" and workload_path:
            snapshot = broker.workload_snapshot(top=8)["topByCount"]
            with open(workload_path, "w") as f:
                json.dump(snapshot, f)
        return out
    finally:
        server.prewarm.stop()
        server.shutdown()


def _spawn_phase(
    phase: str, cache_dir: str, workload_path: str, steady_n: int
) -> Dict[str, Any]:
    env = dict(os.environ)
    env["PINOT_TPU_COMPILE_CACHE_DIR"] = cache_dir
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "pinot_tpu.tools.restart_bench",
            "--phase",
            phase,
            "--workload",
            workload_path,
            "--steady-n",
            str(steady_n),
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"phase {phase} failed:\n{proc.stdout}\n{proc.stderr}"
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="pinot_tpu-restart-bench")
    p.add_argument("--phase", choices=["cold", "restart", "prewarm"])
    p.add_argument("--workload", default=None)
    p.add_argument("--steady-n", type=int, default=40)
    p.add_argument("--cache-dir", default=None)
    args = p.parse_args(argv)

    if args.phase:
        out = run_phase(args.phase, args.workload, args.steady_n)
        print(json.dumps(out))
        return 0

    import jax

    cache_dir = args.cache_dir or tempfile.mkdtemp(prefix="pinot_tpu_restart_")
    workload_path = os.path.join(cache_dir, "workload.json")
    cold = _spawn_phase("cold", cache_dir, workload_path, args.steady_n)
    restart = _spawn_phase("restart", cache_dir, workload_path, args.steady_n)
    prewarm = _spawn_phase("prewarm", cache_dir, workload_path, args.steady_n)

    cold_free = float(
        restart["meters"]["compile.cold"] == 0
        and prewarm["meters"]["compile.cold"] == 0
        and prewarm["meters"]["compile.prewarmed"] >= 1
        and restart["meters"]["compile.persistentHit"] >= 1
    )
    steady_p50 = prewarm["steadyP50Ms"]
    doc = {
        "metric": "restart_warm_first_query_ms",
        "value": prewarm["firstQueryMs"],
        "unit": "ms",
        "bench": "warm_restart_persistent_cache_prewarm",
        "platform": jax.devices()[0].platform,
        "total_rows": ROWS_PER_SEGMENT * NUM_SEGMENTS,
        "num_segments": NUM_SEGMENTS,
        "pql": PQL,
        "cold": cold,
        "restart": restart,
        "prewarm": prewarm,
        "cold_first_query_ms": cold["firstQueryMs"],
        "restart_first_query_ms": restart["firstQueryMs"],
        "steady_p50_ms": steady_p50,
        # structural ratios the gate bands: how much of the cold cliff
        # the persistent cache alone recovers, how much prewarm
        # recovers on top, and the first-query multiple of steady p50
        "restart_over_cold": round(
            restart["firstQueryMs"] / max(cold["firstQueryMs"], 1e-9), 4
        ),
        "prewarm_over_cold": round(
            prewarm["firstQueryMs"] / max(cold["firstQueryMs"], 1e-9), 4
        ),
        "first_query_over_steady_p50": round(
            prewarm["firstQueryMs"] / max(steady_p50, 1e-9), 4
        ),
        "cold_free_restart": cold_free,
    }
    print(json.dumps(doc, indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
