"""Perf regression gate: fresh ``bench.py`` JSON vs a committed record.

``bench.py`` prints one JSON document per run and the repo commits the
round captures (``BENCH_r05.json`` & friends).  This gate compares a
fresh run against a committed baseline with per-metric tolerance bands,
so a perf regression fails CI instead of silently landing:

- higher-is-better metrics (rows/s throughput) must stay above
  ``baseline * min_ratio``;
- lower-is-better metrics (latencies, per-query ms) must stay below
  ``baseline * max_ratio``.

Bands are deliberately wide (CI machines are noisy; the committed
captures come from dedicated runs) — the gate catches the 2x cliff a
bad merge introduces, not 5% jitter.  ``PINOT_TPU_PERF_GATE_SCALE``
(or ``--tolerance-scale``) widens every band multiplicatively for even
noisier environments.

Runs are only comparable at the same workload size: when the two
documents disagree on ``total_rows`` / ``num_segments`` / ``platform``
the gate SKIPS (exit 0, verdict "skipped") rather than comparing apples
to oranges — pass ``--allow-config-mismatch`` to force the comparison
anyway (ratio semantics survive a platform change poorly; use only for
exploration).

Serving-mode documents (``PINOT_TPU_BENCH_MODE=serving``) gate their
own namespace — saturation QPS across serial/pipelined/cached configs,
the ISSUE 10 utilization fields (lane busy-fraction, achieved device
bytes/s, D2H volume), the ISSUE 11 sampling-overhead ratio (QPS with
the always-on tail sampler vs sampling off), and the ISSUE 13 batching
occupancy + result-cache hit rate against the committed
``SERVING_BATCH_r13.json`` — with the same direction-aware bands and
config-mismatch SKIP.  Multichip-mode documents
(``PINOT_TPU_BENCH_MODE=multichip``, the mesh execution plane) gate
per-config rows/s, the sharded-vs-single speedup, and per-lane
achieved bandwidth against the committed ``MULTICHIP_r06.json``.
Mixed kinds (default vs serving vs multichip) skip outright.

Usage:
  python -m pinot_tpu.tools.perf_gate current.json [--baseline BENCH_r05.json]
  python bench.py > /tmp/fresh.json && \
      python -m pinot_tpu.tools.perf_gate /tmp/fresh.json

Exit codes: 0 pass/skip, 1 regression, 2 input error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

# metric path -> (direction, default band).  direction "higher": value
# must be >= baseline * band (band < 1).  direction "lower": value must
# be <= baseline * band (band > 1).
METRIC_SPECS: Dict[str, Tuple[str, float]] = {
    "value": ("higher", 0.40),  # headline rows/s
    "detail.vs_baseline_kernel_marginal": ("higher", 0.40),
    "detail.per_query_ms": ("lower", 2.5),
    "detail.batch_amortized_ms": ("lower", 2.5),
    "detail.broker_p50_ms": ("lower", 2.5),
    "detail.broker_p99_ms": ("lower", 3.0),
    "detail.broker_rows_per_sec_p50": ("higher", 0.40),
    "detail.sel_clustered_p50_ms_invindex": ("lower", 3.0),
    "detail.sel_clustered_p50_ms_zonemap": ("lower", 3.0),
    "detail.sel_clustered_p50_ms_fullscan": ("lower", 3.0),
    "detail.sel_shuffled_p50_ms_invindex": ("lower", 3.0),
    "detail.sel_shuffled_p50_ms_fullscan": ("lower", 3.0),
    "detail.q6_p50_ms": ("lower", 3.0),
    "detail.hll_groupby_p50_ms": ("lower", 3.0),
}

# config keys that must match for latency/throughput numbers to be
# comparable at all
CONFIG_KEYS = ("detail.total_rows", "detail.num_segments", "detail.platform")

# serving-mode documents (PINOT_TPU_BENCH_MODE=serving) carry their own
# metric namespace: saturation QPS + the utilization-plane fields
# (ISSUE 10 — lane occupancy and achieved bandwidth are the gated
# substrate for the throughput arc).  Occupancy/bandwidth bands are
# wide: closed-loop QPS on shared CI boxes swings, and these gate the
# 2x cliff (a lane suddenly idle, a bandwidth collapse), not jitter.
SERVING_METRIC_SPECS: Dict[str, Tuple[str, float]] = {
    "saturation_qps_repeated_q1.pipelined": ("higher", 0.40),
    "saturation_qps_repeated_q1.serial": ("higher", 0.40),
    "saturation_qps_mixed.pipelined": ("higher", 0.40),
    "saturation_qps_mixed.serial": ("higher", 0.40),
    "speedup_repeated_q1": ("higher", 0.50),
    "utilization.pipelined.busyFraction": ("higher", 0.30),
    "utilization.pipelined.achievedBytesPerSec": ("higher", 0.30),
    "utilization.serial.achievedBytesPerSec": ("higher", 0.30),
    "utilization.pipelined.d2hBytes": ("higher", 0.30),
    # sampling-overhead spec (ISSUE 11): qpsRatio = saturation QPS with
    # the always-on tail sampler + history recorder at defaults over
    # the same run with sampling off.  Near 1.0 by construction; the
    # band catches the sampler growing a real per-query cost (a ratio
    # collapse), not closed-loop jitter.  The absolute on-QPS also
    # rides the standard saturation band.
    "sampling_overhead.qpsRatio": ("higher", 0.60),
    "sampling_overhead.samplingOnQps": ("higher", 0.40),
    # cross-query batching + result cache (ISSUE 13): the batched
    # fraction and average batch size prove batches actually form on
    # the literal-mix ladder (a collapse means the tier silently
    # disengaged), the cache hit rate proves the ingest-aware cache
    # still serves repeats, and the cached-config ok-QPS rides the
    # same saturation bands as the other configs.  All absent in
    # pre-r13 baselines — the gate skips absent metrics.
    "saturation_qps_repeated_q1.cached": ("higher", 0.40),
    "saturation_qps_mixed.cached": ("higher", 0.40),
    "saturation_qps_literal_mix.cached": ("higher", 0.40),
    "saturation_qps_literal_mix.pipelined": ("higher", 0.40),
    "saturation_qps_literal_mix.serial": ("higher", 0.40),
    "batching.avgBatchSize": ("higher", 0.50),
    "batching.batchedQueryFraction": ("higher", 0.50),
    "rescache.hitRate": ("higher", 0.50),
}

SERVING_CONFIG_KEYS = ("total_rows", "num_segments", "platform")

SERVING_DEFAULT_BASELINE = "SERVING_BATCH_r13.json"

# multichip-mode documents (PINOT_TPU_BENCH_MODE=multichip, the mesh
# execution plane): per-execution-config scan-heavy rows/s, the
# sharded-vs-single speedup (the ISSUE 12 acceptance is >= 3x on an
# 8-device host — the band fails the gate if a merge collapses it
# below ~2.1x of the committed capture), and per-lane utilization.
# Direction-aware with the same config-mismatch SKIP as every kind.
MULTICHIP_METRIC_SPECS: Dict[str, Tuple[str, float]] = {
    "rows_per_sec.single_lane": ("higher", 0.40),
    "rows_per_sec.sharded": ("higher", 0.40),
    "rows_per_sec.lane_group": ("higher", 0.40),
    "sharded_vs_single": ("higher", 0.70),
    "lane_group_vs_single": ("higher", 0.60),
    "utilization.sharded.achievedBytesPerSec": ("higher", 0.30),
    "utilization.lane_group.achievedBytesPerSec": ("higher", 0.30),
}

MULTICHIP_CONFIG_KEYS = ("total_rows", "num_segments", "n_devices", "platform")

MULTICHIP_DEFAULT_BASELINE = "MULTICHIP_r06.json"

# join-mode documents (PINOT_TPU_BENCH_MODE=join, ISSUE 14): per-
# strategy closed-loop QPS over uniform and zipf-skewed keys, plus the
# two structural invariants the gate must never let collapse — the
# byte-identity differential against the host-reference join
# (identical == 1.0, exact) and the shuffle skew balance (max owner
# bytes / mean <= 2.0 under zipf with splitting on).
JOIN_METRIC_SPECS: Dict[str, Tuple[str, float]] = {
    "qps.colocated.uniform": ("higher", 0.40),
    "qps.broadcast.uniform": ("higher", 0.40),
    "qps.shuffle.uniform": ("higher", 0.40),
    "qps.shuffle.zipf": ("higher", 0.40),
    "differential.identical": ("higher", 1.0),
    "skew.balanceRatioSplit": ("lower", 1.30),
    "skew.heavyHitterSplits": ("higher", 1.0),
}

JOIN_CONFIG_KEYS = ("fact_rows", "dim_rows", "num_segments", "platform")

JOIN_DEFAULT_BASELINE = "JOIN_r14.json"

# ingest-mode documents (tools/ingest_bench.py --ladder, ISSUE 15): the
# partition-parallel consumer ladder.  Per-rung aggregate rows/s plus
# the two structural ratios — parallel_vs_single (same-host scaling; a
# collapse means partition-parallel ingest silently serialized) and
# vs_r5_single_consumer_ceiling (the arc's acceptance: aggregate must
# stay >= 1.5x the committed INGEST_r5 single-consumer LLC ceiling —
# the band is 1.5 / the committed INGEST_r15 capture's 2.531, so the
# gate floor sits exactly ON the acceptance bar).  Lag drains gate
# lower-is-better.  cpu_cores is a config key: ladder numbers are
# only comparable on an identically-sized host (config-mismatch SKIP).
INGEST_METRIC_SPECS: Dict[str, Tuple[str, float]] = {
    "value": ("higher", 0.40),
    "single_consumer_rows_per_sec": ("higher", 0.40),
    "ladder.c1.rows_per_sec": ("higher", 0.40),
    "ladder.c2.rows_per_sec": ("higher", 0.40),
    "ladder.c4.rows_per_sec": ("higher", 0.40),
    "ladder.c2.lag_drain_s": ("lower", 2.5),
    "ladder.c4.lag_drain_s": ("lower", 2.5),
    "parallel_vs_single": ("higher", 0.60),
    "vs_r5_single_consumer_ceiling": ("higher", 0.593),
}

INGEST_CONFIG_KEYS = (
    "partitions", "rows_per_partition", "cpu_cores", "platform",
)

INGEST_DEFAULT_BASELINE = "INGEST_r15.json"

# restart-mode documents (tools/restart_bench.py, ISSUE 16): the
# warm-restart story.  ``cold_free_restart`` is the exact structural
# bar — 1.0 only when both restart phases kept ``compile.cold`` at
# zero AND classified their first launches (persistentHit / prewarmed)
# — any cold compile on a restart fails the gate outright.  The ratio
# metrics band the recovered fraction of the cold cliff: the
# persistent cache alone must keep the first query under ~72% of cold,
# prewarming under ~2x its committed fraction (~5% of cold on the CPU
# capture; on a real TPU the cold side is ~25s so these ratios
# collapse toward zero).  first_query_over_steady_p50 rides a relative
# band: CPU steady p50 is broker overhead (~2ms) so the re-trace
# constant dominates the toy-scale ratio; the band catches it
# regressing toward the cold multiple (~180x), not jitter.
RESTART_METRIC_SPECS: Dict[str, Tuple[str, float]] = {
    "value": ("lower", 2.5),
    "cold_first_query_ms": ("lower", 2.5),
    "restart_first_query_ms": ("lower", 2.5),
    "steady_p50_ms": ("lower", 2.5),
    "restart_over_cold": ("lower", 1.6),
    "prewarm_over_cold": ("lower", 2.0),
    "first_query_over_steady_p50": ("lower", 2.0),
    "cold_free_restart": ("higher", 1.0),
}

RESTART_CONFIG_KEYS = ("total_rows", "num_segments", "platform")

RESTART_DEFAULT_BASELINE = "RESTART_r16.json"

# filter-matrix documents (tools/filter_matrix.py, ISSUE 17): the
# four-tier win map.  These are structural counts, not latencies: each
# tier must keep winning its region of the (selectivity, clustering)
# plane.  The 0.5 band on integer win counts means "keep at least half
# your cells, and never drop to zero when the baseline had any" — a
# tier's entire region collapsing (the bit-sliced tier silently
# disengaging, postings losing the needle cells) fails the gate, while
# a single boundary cell flapping between adjacent tiers does not.
# ``bitsliced_midsel_wins`` / ``value`` is the r17 acceptance bar: the
# bit-sliced tier must keep winning a shuffled mid-selectivity range
# cell (baseline >= 1, so the 0.5 band floors current at >= 1).
FILTERMATRIX_METRIC_SPECS: Dict[str, Tuple[str, float]] = {
    "value": ("higher", 0.5),
    "bitsliced_midsel_wins": ("higher", 0.5),
    "tier_wins.invindex": ("higher", 0.5),
    "tier_wins.zonemap": ("higher", 0.5),
    "tier_wins.bitsliced": ("higher", 0.5),
    "tier_wins.fullscan": ("higher", 0.5),
}

FILTERMATRIX_CONFIG_KEYS = ("total_rows", "num_segments", "platform")

FILTERMATRIX_DEFAULT_BASELINE = "FILTER_MATRIX_CPU_r17.json"

# tiered-residency documents (tools/cluster_harness.py hbm-pressure,
# ISSUE 18): the memory-pressure resilience story.  ``value`` /
# ``addressable_over_cap`` is the oversubscription factor the scenario
# actually sustained (addressable staged bytes over the HBM cap —
# ~8x by construction; shrinking means the scenario stopped proving
# pressure).  ``demotions`` / ``promotions`` / ``cold_loads`` are
# structural: the tiers must visibly CYCLE under the sweep (a silent
# residency manager that never demotes would pass a latency-only
# gate while the OOM heal path rots untested).  The hot-set latency
# bars ride wide bands — the hot table's closed loop runs concurrently
# with cold-table staging churn on a shared CPU box, so only an
# order-of-magnitude regression (hot set no longer protected by heat
# scoring) should fail the gate.
TIERED_METRIC_SPECS: Dict[str, Tuple[str, float]] = {
    "value": ("higher", 0.8),
    "addressable_over_cap": ("higher", 0.8),
    "hot_p99_ms": ("lower", 4.0),
    "hot_p99_over_baseline": ("lower", 4.0),
    "demotions": ("higher", 0.5),
    "promotions": ("higher", 0.5),
    "cold_loads": ("higher", 0.5),
}

TIERED_CONFIG_KEYS = ("num_tables", "platform")

TIERED_DEFAULT_BASELINE = "TIERED_r18.json"

# audit-plane documents (PINOT_TPU_BENCH_MODE=audit, ISSUE 19): the two
# promises the correctness/freshness audit plane must keep forever.
# ``value`` / ``audit_overhead.okQpsRatio`` is serving ok-QPS with the
# shipped audit defaults ON over audit fully OFF — the background
# shadow oracle + replica double-scatter must cost <= ~5% of serving
# throughput (baseline ratio ~1.0, band 0.95 floors it near 0.95; ratio
# is fresh-broker/pre-opened-window ok-QPS, same traps as the serving
# sampling_overhead spec).  ``detect_ms`` bounds how long the shadow
# auditor takes to flag + quarantine a seeded device-tier wrong answer
# under closed-loop load (milliseconds on the in-process harness; the
# wide band gates order-of-magnitude rot, not scheduler jitter).
# ``detected`` is structural: the seeded corruption must ALWAYS be
# caught — a gate run where it slipped through fails outright.
AUDIT_METRIC_SPECS: Dict[str, Tuple[str, float]] = {
    "value": ("higher", 0.95),
    "audit_overhead.okQpsRatio": ("higher", 0.95),
    "audit_overhead.auditOnQps": ("higher", 0.40),
    "detect_ms": ("lower", 50.0),
    "detected": ("higher", 1.0),
    "divergence.divergences": ("higher", 0.5),
}

AUDIT_CONFIG_KEYS = ("total_rows", "num_segments", "clients", "platform")

AUDIT_DEFAULT_BASELINE = "AUDIT_r19.json"


# disaster-recovery documents (cluster_harness --scenario
# disaster-recovery, ISSUE 20): the durability plane's forever
# promises.  Wall-clock rows (backup under load, restore-to-first-
# successful-query) get wide bands — they gate order-of-magnitude rot
# on the tiny harness cluster, not scheduler jitter.  The structural
# rows are absolute: restored answers must stay byte-identical to the
# pre-disaster payloads, and the scrubber must ALWAYS detect and
# repair the seeded corrupt store copy.  ``scrub.okQpsRatio`` is
# serving ok-QPS while a scrub round runs over the pre-scrub baseline
# window (clamped at 1.0) — scrubbing must never cost more than ~5%
# of serving throughput.
DR_METRIC_SPECS: Dict[str, Tuple[str, float]] = {
    "value": ("lower", 5.0),
    "backup.backupSeconds": ("lower", 5.0),
    "restore.restoreToFirstQuerySeconds": ("lower", 5.0),
    "restore.byteIdentical": ("higher", 1.0),
    "scrub.okQpsRatio": ("higher", 0.95),
    "scrub.detected": ("higher", 1.0),
    "scrub.repaired": ("higher", 1.0),
}

DR_CONFIG_KEYS = ("num_segments", "clients", "platform")

DR_DEFAULT_BASELINE = "DR_r20.json"


def _is_serving(doc: Dict[str, Any]) -> bool:
    return str(doc.get("metric", "")).startswith("serving_")


def _doc_kind(doc: Dict[str, Any]) -> str:
    metric = str(doc.get("metric", ""))
    if metric.startswith("serving_"):
        return "serving"
    if metric.startswith("multichip_"):
        return "multichip"
    if metric.startswith("join_"):
        return "join"
    if metric.startswith("ingest_"):
        return "ingest"
    if metric.startswith("restart_"):
        return "restart"
    if metric.startswith("filtermatrix_"):
        return "filtermatrix"
    if metric.startswith("tiered_"):
        return "tiered"
    if metric.startswith("audit_"):
        return "audit"
    if metric.startswith("dr_"):
        return "dr"
    return "default"


def _specs_for(doc: Dict[str, Any]):
    """(metric specs, config keys) for a bench document's kind."""
    kind = _doc_kind(doc)
    if kind == "serving":
        return SERVING_METRIC_SPECS, SERVING_CONFIG_KEYS
    if kind == "multichip":
        return MULTICHIP_METRIC_SPECS, MULTICHIP_CONFIG_KEYS
    if kind == "join":
        return JOIN_METRIC_SPECS, JOIN_CONFIG_KEYS
    if kind == "ingest":
        return INGEST_METRIC_SPECS, INGEST_CONFIG_KEYS
    if kind == "restart":
        return RESTART_METRIC_SPECS, RESTART_CONFIG_KEYS
    if kind == "filtermatrix":
        return FILTERMATRIX_METRIC_SPECS, FILTERMATRIX_CONFIG_KEYS
    if kind == "tiered":
        return TIERED_METRIC_SPECS, TIERED_CONFIG_KEYS
    if kind == "audit":
        return AUDIT_METRIC_SPECS, AUDIT_CONFIG_KEYS
    if kind == "dr":
        return DR_METRIC_SPECS, DR_CONFIG_KEYS
    return METRIC_SPECS, CONFIG_KEYS


def _get(doc: Dict[str, Any], path: str) -> Any:
    cur: Any = doc
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def load_bench(source) -> Dict[str, Any]:
    """A bench document from a dict, a path, or ``-`` (stdin).  Accepts
    both the raw ``bench.py`` output line and the committed capture
    wrapper (``{"parsed": {...}}``, the driver's record format); for a
    multi-line file the LAST JSON-parseable line wins (bench.py logs
    progress lines to stderr but belt-and-braces here)."""
    if isinstance(source, dict):
        doc = source
    else:
        text = sys.stdin.read() if source == "-" else open(source).read()
        try:
            doc = json.loads(text)
        except json.JSONDecodeError:
            doc = None
            for line in text.strip().splitlines():
                line = line.strip()
                if line.startswith("{"):
                    try:
                        doc = json.loads(line)
                    except json.JSONDecodeError:
                        continue
            if doc is None:
                raise
    if "parsed" in doc and isinstance(doc["parsed"], dict):
        doc = doc["parsed"]
    if doc.get("metric") is None:
        raise ValueError("not a bench.py document (no 'metric' field)")
    return doc


def compare(
    baseline: Dict[str, Any],
    current: Dict[str, Any],
    tolerance_scale: float = 1.0,
    allow_config_mismatch: bool = False,
) -> Dict[str, Any]:
    """Gate verdict: ``{"verdict": "pass"|"fail"|"skipped", ...}`` with
    one row per compared metric.  Pure — unit-testable without files.
    The spec set follows the document kind (default bench vs serving
    mode); mismatched kinds skip — there is nothing to compare."""
    if _doc_kind(baseline) != _doc_kind(current):
        return {
            "verdict": "skipped",
            "reason": "bench document kinds differ "
            "(default vs serving vs multichip mode)",
            "configMismatch": {
                "metric": {
                    "baseline": baseline.get("metric"),
                    "current": current.get("metric"),
                }
            },
            "metrics": [],
        }
    specs, config_keys = _specs_for(current)
    mismatches = {
        k: {"baseline": _get(baseline, k), "current": _get(current, k)}
        for k in config_keys
        if _get(baseline, k) != _get(current, k)
    }
    if mismatches and not allow_config_mismatch:
        return {
            "verdict": "skipped",
            "reason": "workload config mismatch (different scale/platform "
            "runs are not comparable)",
            "configMismatch": mismatches,
            "metrics": [],
        }
    rows: List[Dict[str, Any]] = []
    failures = 0
    for path, (direction, band) in specs.items():
        b, c = _get(baseline, path), _get(current, path)
        if not isinstance(b, (int, float)) or not isinstance(c, (int, float)):
            continue  # metric absent in one doc: nothing to gate
        if b <= 0:
            continue
        if direction == "higher":
            limit = b * band / tolerance_scale
            ok = c >= limit
        else:
            limit = b * band * tolerance_scale
            ok = c <= limit
        if not ok:
            failures += 1
        rows.append(
            {
                "metric": path,
                "direction": direction,
                "baseline": b,
                "current": c,
                "limit": round(limit, 4),
                "ratio": round(c / b, 4),
                "ok": ok,
            }
        )
    return {
        "verdict": "fail" if failures else "pass",
        "failures": failures,
        "compared": len(rows),
        "toleranceScale": tolerance_scale,
        **({"configMismatch": mismatches} if mismatches else {}),
        "metrics": rows,
    }


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="pinot_tpu-perf-gate", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("current", help="fresh bench.py JSON (file or - for stdin)")
    p.add_argument(
        "--baseline",
        default=None,
        help="committed capture to gate against (default BENCH_r05.json, "
        f"{SERVING_DEFAULT_BASELINE} for a serving-mode document, or "
        f"{MULTICHIP_DEFAULT_BASELINE} for a multichip-mode document)",
    )
    p.add_argument(
        "--tolerance-scale",
        type=float,
        default=float(os.environ.get("PINOT_TPU_PERF_GATE_SCALE", "1.0")),
        help="widen every band multiplicatively (noisy CI)",
    )
    p.add_argument(
        "--allow-config-mismatch",
        action="store_true",
        help="compare even when workload size/platform differ",
    )
    args = p.parse_args(argv)
    try:
        current = load_bench(args.current)
        baseline_path = args.baseline
        if baseline_path is None:
            # default baseline follows the current document's kind
            baseline_path = {
                "serving": SERVING_DEFAULT_BASELINE,
                "multichip": MULTICHIP_DEFAULT_BASELINE,
                "join": JOIN_DEFAULT_BASELINE,
                "ingest": INGEST_DEFAULT_BASELINE,
                "restart": RESTART_DEFAULT_BASELINE,
                "filtermatrix": FILTERMATRIX_DEFAULT_BASELINE,
                "tiered": TIERED_DEFAULT_BASELINE,
                "audit": AUDIT_DEFAULT_BASELINE,
                "dr": DR_DEFAULT_BASELINE,
            }.get(_doc_kind(current), "BENCH_r05.json")
        baseline = load_bench(baseline_path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(json.dumps({"verdict": "error", "error": str(e)}), file=sys.stderr)
        return 2
    out = compare(
        baseline,
        current,
        tolerance_scale=max(args.tolerance_scale, 1e-9),
        allow_config_mismatch=args.allow_config_mismatch,
    )
    print(json.dumps(out, indent=1))
    return 1 if out["verdict"] == "fail" else 0


if __name__ == "__main__":
    raise SystemExit(main())
