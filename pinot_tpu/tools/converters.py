"""Segment export converters and star-tree inspection.

The reference ships segment converters (pinot-tools
``tools/segment/converter/`` — segment -> CSV/JSON/Avro) and a
``StarTreeIndexViewer``.  Same capabilities here: rows are rebuilt from
the columnar data (dictionary decode through the forward index) and
written back out as CSV, JSONL, or Avro object containers
(``pinot_tpu.segment.avro`` — pure-Python container codec, no library
needed).
"""
from __future__ import annotations

import csv
import json
from typing import Any, Dict, List, Optional

from pinot_tpu.segment.format import read_segment
from pinot_tpu.segment.immutable import ImmutableSegment


def _load(segment_or_dir) -> ImmutableSegment:
    if isinstance(segment_or_dir, ImmutableSegment):
        return segment_or_dir
    return read_segment(segment_or_dir)


def segment_to_jsonl(segment_or_dir, out_path: str) -> int:
    """Export every row of a segment as JSON lines; returns row count."""
    seg = _load(segment_or_dir)
    n = 0
    with open(out_path, "w") as f:
        for row in seg.rows():
            f.write(json.dumps(row, default=_json_default) + "\n")
            n += 1
    return n


def segment_to_csv(segment_or_dir, out_path: str) -> int:
    """Export every row of a segment as CSV (MV cells join on ';', the
    reference CSV reader's default multi-value delimiter)."""
    seg = _load(segment_or_dir)
    cols = list(seg.metadata.columns)
    n = 0
    with open(out_path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(cols)
        for row in seg.rows():
            w.writerow(
                [
                    ";".join(str(x) for x in row[c]) if isinstance(row[c], list) else row[c]
                    for c in cols
                ]
            )
            n += 1
    return n


def segment_to_avro(segment_or_dir, out_path: str, codec: str = "deflate") -> int:
    """Export every row of a segment as an Avro object container file
    (segment->Avro converter parity; schema derived from the segment)."""
    from pinot_tpu.common.schema import FieldSpec, Schema
    from pinot_tpu.segment.avro import pinot_to_avro_schema, write_avro

    seg = _load(segment_or_dir)
    specs = [
        FieldSpec(name, meta.data_type, meta.field_type, single_value=meta.single_value)
        for name, meta in seg.metadata.columns.items()
    ]
    schema = Schema(seg.metadata.table_name, dimensions=specs)
    avro_schema = pinot_to_avro_schema(schema)
    rows = [{k: _py(v) for k, v in row.items()} for row in seg.rows()]
    write_avro(out_path, avro_schema, rows, codec=codec)
    return len(rows)


def _np_scalar(v: Any) -> Optional[Any]:
    """numpy scalar -> plain Python, or None if not a numpy scalar."""
    import numpy as np

    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        return float(v)
    return None


def _py(v: Any):
    """Values (incl. lists) -> plain Python for the Avro encoder."""
    if isinstance(v, list):
        return [_py(x) for x in v]
    s = _np_scalar(v)
    return v if s is None else s


def _json_default(v: Any):
    s = _np_scalar(v)
    return str(v) if s is None else s


def star_tree_summary(segment_or_dir, max_nodes: int = 50) -> Dict[str, Any]:
    """StarTreeIndexViewer analog: tree shape + a bounded node dump +
    cube statistics, as a JSON-friendly dict."""
    seg = _load(segment_or_dir)
    st = getattr(seg, "star_tree", None)
    if st is None:
        return {"hasStarTree": False}

    nodes: List[Dict[str, Any]] = []
    depth_counts: Dict[int, int] = {}
    leaf_count = 0
    star_count = 0

    def walk(node, depth: int, path: List[str], is_star: bool) -> None:
        nonlocal leaf_count, star_count
        depth_counts[depth] = depth_counts.get(depth, 0) + 1
        if is_star:
            star_count += 1
        if node.is_leaf:
            leaf_count += 1
        if len(nodes) < max_nodes:
            nodes.append(
                {
                    "depth": depth,
                    "path": " / ".join(path) if path else "(root)",
                    "star": is_star,
                    "leaf": node.is_leaf,
                    "level": int(node.level),
                    "aggRange": [int(node.start), int(node.end)],
                }
            )
        for val, child in sorted(node.children.items()):
            walk(child, depth + 1, path + [str(val)], False)
        if node.star_child is not None:
            walk(node.star_child, depth + 1, path + ["*"], True)

    walk(st.root, 0, [], False)
    return {
        "hasStarTree": True,
        "splitOrder": list(st.split_order),
        "metricColumns": list(st.metric_columns),
        "hllColumns": list(st.hll_columns),
        "numAggRecords": st.num_records,
        "maxLeafRecords": st.max_leaf_records,
        "numLeaves": leaf_count,
        "numStarNodes": star_count,
        "nodesPerDepth": {str(k): v for k, v in sorted(depth_counts.items())},
        "nodes": nodes,
    }
