"""Standalone server process: load segments from disk, serve TCP queries.

Used by the chaos test (ChaosMonkeyIntegrationTest.java:41 analog —
real OS processes killed with POSIX signals) and by manual multi-process
deployments.

Usage: python -m pinot_tpu.tools.run_server --name s0 --port 0 \
          --table myTable_OFFLINE --segments /path/seg1 /path/seg2
Prints "READY <port>" on stdout once serving.
"""
from __future__ import annotations

import argparse
import sys
import time


def main(argv=None) -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--name", default="server0")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--table", required=True)
    p.add_argument("--segments", nargs="*", default=[])
    args = p.parse_args(argv)

    from pinot_tpu.segment.format import read_segment
    from pinot_tpu.server.instance import ServerInstance
    from pinot_tpu.transport.tcp import TcpServer

    server = ServerInstance(args.name)
    for seg_dir in args.segments:
        server.add_segment(args.table, read_segment(seg_dir))

    tcp = TcpServer(server.handle_request, port=args.port)
    tcp.start()
    print(f"READY {tcp.port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        tcp.stop()


if __name__ == "__main__":
    main()
