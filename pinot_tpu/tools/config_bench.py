"""BASELINE.json workload-config benchmarks 1, 2, and 5 — the three
configs without committed on-chip numbers (Q1/Q6 live in bench.py,
the 1B HLL ladder in tools/hll_northstar.py):

1. baseballStats offline group-by: SUM(runs) GROUP BY playerName
   (quick-start-offline shape at bench scale).
2. baseballStats star-tree cube: the same aggregations answered from
   the pre-aggregated cube (startree/operator.py) vs the raw scan —
   the reference's StarTreeIndexOperator speedup, re-measured here.
3. meetupRsvp realtime: ingest rate into a mutable segment plus a
   windowed COUNT group-by over the live consuming snapshot.

Prints one JSON object; run on-chip via tools/tpu_work_queue.sh or
directly.  Reference harness analog: PerfBenchmarkDriver +
BenchmarkQueryEngine (pinot-perf).
"""
from __future__ import annotations

import json
import time

import numpy as np


def _broker_for(table: str, segments):
    from pinot_tpu.tools.cluster_harness import single_server_broker

    return single_server_broker(table, segments)


def _p50(broker, pql: str, warm: int = 3, n: int = 15) -> float:
    for _ in range(warm):
        resp = broker.handle_pql(pql)
        assert not resp.exceptions, resp.exceptions
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        resp = broker.handle_pql(pql)
        times.append((time.perf_counter() - t0) * 1000)
        # a failed timed run returns fast and would publish a bogus
        # (low) p50 — errors must fail the bench, not flatter it
        assert not resp.exceptions, resp.exceptions
    times.sort()
    return round(times[len(times) // 2], 2)


def baseball_groupby(num_segments: int, rows_per_segment: int) -> dict:
    from pinot_tpu.tools.datagen import synthetic_baseball_segment

    segs = [
        synthetic_baseball_segment(rows_per_segment, seed=71 + i, name=f"bb{i}")
        for i in range(num_segments)
    ]
    broker = _broker_for("baseballStats", segs)
    total = num_segments * rows_per_segment
    pql = "SELECT sum(runs) FROM baseballStats GROUP BY playerName TOP 10"
    p50 = _p50(broker, pql)
    return {
        "config": "baseballStats_offline_groupby",
        "pql": pql,
        "total_rows": total,
        "p50_ms": p50,
        "rows_per_sec_p50": round(total / (p50 / 1000.0), 1),
        "multi_agg_p50_ms": _p50(
            broker,
            "SELECT sum(runs), sum(hits), sum(homeRuns), avg(atBats) "
            "FROM baseballStats GROUP BY playerName, league TOP 10",
        ),
    }


def startree_cube(rows: int) -> dict:
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.startree.builder import StarTreeBuilderConfig, build_star_tree
    from pinot_tpu.tools.datagen import baseball_rows, baseball_schema

    schema = baseball_schema()
    data = baseball_rows(rows, seed=9)
    seg = build_segment(schema, data, "baseballStats", "st0")
    t0 = time.perf_counter()
    build_star_tree(seg, schema, StarTreeBuilderConfig())
    build_s = round(time.perf_counter() - t0, 1)
    broker = _broker_for("baseballStats", [seg])
    pql = "SELECT sum(runs), count(*) FROM baseballStats GROUP BY teamID TOP 20"
    # routing is automatic when the tree exists (executor star routing);
    # the A/B detaches the tree for the scan side
    tree = seg.star_tree
    tree_p50 = _p50(broker, pql)
    seg.star_tree = None
    scan_p50 = _p50(broker, pql)
    seg.star_tree = tree
    return {
        "config": "baseballStats_startree_cube",
        "pql": pql,
        "rows": rows,
        "tree_build_s": build_s,
        "startree_p50_ms": tree_p50,
        "scan_p50_ms": scan_p50,
        "speedup": round(scan_p50 / max(tree_p50, 1e-3), 1),
    }


def realtime_windowed(rows: int) -> dict:
    from pinot_tpu.realtime.mutable import MutableSegment
    from pinot_tpu.tools.datagen import Row

    from pinot_tpu.common.schema import (
        DataType,
        FieldSpec,
        FieldType,
        Schema,
        TimeFieldSpec,
    )

    schema = Schema(
        "meetupRsvp",
        dimensions=[
            FieldSpec("venue_name", DataType.STRING),
            FieldSpec("event_name", DataType.STRING),
        ],
        metrics=[FieldSpec("rsvp_count", DataType.INT, FieldType.METRIC)],
        time_field=TimeFieldSpec("mtime", DataType.LONG, time_unit="MILLISECONDS"),
    )
    rng = np.random.default_rng(3)
    venues = [f"venue{i}" for i in range(50)]
    events = [f"event{i}" for i in range(20)]
    t_base = 1_700_000_000_000
    data: list[Row] = [
        {
            "venue_name": venues[int(v)],
            "event_name": events[int(e)],
            "rsvp_count": int(c),
            "mtime": t_base + int(i) * 100,
        }
        for i, (v, e, c) in enumerate(
            zip(
                rng.integers(0, 50, rows),
                rng.integers(0, 20, rows),
                rng.integers(1, 8, rows),
            )
        )
    ]
    seg = MutableSegment(schema, "rt0", "meetupRsvp")
    t0 = time.perf_counter()
    for i in range(0, rows, 2000):
        seg.index_batch(data[i : i + 2000])
    ingest_s = time.perf_counter() - t0

    broker = _broker_for("meetupRsvp", [seg])
    lo, hi = t_base + rows * 25, t_base + rows * 75  # middle half window
    pql = (
        f"SELECT count(*), sum(rsvp_count) FROM meetupRsvp "
        f"WHERE mtime BETWEEN {lo} AND {hi} GROUP BY venue_name TOP 10"
    )
    return {
        "config": "meetupRsvp_realtime_windowed_count",
        "pql": pql,
        "rows": rows,
        "ingest_rows_per_sec": round(rows / ingest_s, 1),
        "windowed_groupby_p50_ms": _p50(broker, pql),
    }


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("-bb-segments", type=int, default=8, dest="bb_segments")
    ap.add_argument("-bb-rows", type=int, default=8_388_608, dest="bb_rows")
    ap.add_argument("-st-rows", type=int, default=500_000, dest="st_rows")
    ap.add_argument("-rt-rows", type=int, default=2_000_000, dest="rt_rows")
    args = ap.parse_args()
    import jax

    out = {
        "platform": jax.devices()[0].platform,
        "baseball": baseball_groupby(args.bb_segments, args.bb_rows),
        "startree": startree_cube(args.st_rows),
        "realtime": realtime_windowed(args.rt_rows),
        # parallel N-partition consumer ingest (+ query-during-ingest):
        # tools/ingest_bench.py; the full-scale committed run lives in
        # INGEST_r5.json (solo 1.22M rows/s single-core via the
        # columnar stream path; aggregate is core-bound on this host)
        "parallel_ingest_ref": "INGEST_r5.json",
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
