"""North-star workload: high-cardinality distinctCountHLL group-by on
synthetic ad-events (BASELINE.json config 4; VERDICT r3 #4).

Measures, at a requested total row count:
- kernel-marginal rows/s (bench.py methodology: fixed dispatch RTT
  subtracted via marginal-batch timing);
- broker-path p50 over the full parse->route->kernel->reduce path;
- staged HBM bytes (the capacity accounting that locates the cliff);
- the >=2^20-group host-fallback path and the device sort-pairs exact
  distinct path, timed at the same scale.

Scale mechanics: ``distinct`` full segments are generated (high-card
user_id, partially overlapping across segments) and tiled to the
requested row count — host RAM stays O(distinct segments) while the
device sees the full stacked table.  Run sizes upward until staging or
the workspace exhausts HBM; the last fitting size plus the failure is
the documented capacity cliff.

Usage:
  python -m pinot_tpu.tools.hll_northstar -rows 536870912
  python -m pinot_tpu.tools.hll_northstar -rows 33554432 -paths  # aux paths too
"""
from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np

HLL_PQL = (
    "SELECT distinctcounthll(user_id) FROM adevents "
    "GROUP BY campaign_id TOP 10"
)


def staged_nbytes(staged) -> int:
    total = 0
    for sc in staged.columns.values():
        for arr in (sc.fwd, sc.mv, sc.mv_counts, sc.dict_vals, sc.raw, sc.gfwd,
                    sc.hll_bucket, sc.hll_rho, sc.mv_raw):
            if arr is not None:
                total += arr.nbytes
    return total


def _log(msg: str) -> None:
    import sys

    print(f"# {time.strftime('%H:%M:%S')} {msg}", file=sys.stderr, flush=True)


def run(total_rows: int, rows_per_segment: int, distinct: int, iters: int,
        aux_paths: bool) -> dict:
    from pinot_tpu.engine.context import get_table_context
    from pinot_tpu.engine.device import segment_arrays, stage_segments, to_device_inputs
    from pinot_tpu.engine.executor import QueryExecutor
    from pinot_tpu.engine.kernel import make_chunked_table_kernel
    from pinot_tpu.engine.plan import build_query_inputs, build_static_plan
    from pinot_tpu.engine.reduce import reduce_to_response
    from pinot_tpu.pql import optimize_request, parse_pql
    from pinot_tpu.tools.datagen import synthetic_adevents_segment, tile_segments

    n_segments = max(1, total_rows // rows_per_segment)
    t0 = time.perf_counter()
    distinct_segs = [
        synthetic_adevents_segment(rows_per_segment, seed=23 + i, name=f"ad{i}")
        for i in range(min(distinct, n_segments))
    ]
    segments = tile_segments(distinct_segs, n_segments)
    gen_s = time.perf_counter() - t0
    total_rows = sum(s.num_docs for s in segments)
    _log(f"datagen done ({gen_s:.0f}s, {n_segments} segments)")

    request = optimize_request(parse_pql(HLL_PQL))
    ctx = get_table_context(segments)
    needed = sorted(set(request.referenced_columns()))
    t0 = time.perf_counter()
    staged = stage_segments(
        segments,
        needed,
        gfwd_columns=("campaign_id",),
        hll_columns=("user_id",),
        ctx=ctx,
        skip_base_columns=("campaign_id", "user_id"),
    )
    stage_s = time.perf_counter() - t0
    hbm_bytes = staged_nbytes(staged)
    _log(f"staged ({stage_s:.0f}s, {hbm_bytes/(1<<30):.2f} GiB)")
    plan = build_static_plan(request, ctx, staged)
    assert plan.on_device, "north-star HLL group-by must stay on device"
    q_inputs = to_device_inputs(build_query_inputs(request, plan, ctx, staged))
    seg_arrays = segment_arrays(staged, needed)
    kernel = make_chunked_table_kernel(plan, n_segments, staged.n_pad)

    def fetch(outs):
        leaf = next(iter(outs.values()))
        while isinstance(leaf, (tuple, list)):
            leaf = leaf[0]
        np.asarray(leaf)

    def run_batch(m: int) -> float:
        t0 = time.perf_counter()
        outs = None
        for _ in range(m):
            outs = kernel(seg_arrays, q_inputs)
        fetch(outs)
        return time.perf_counter() - t0

    t0 = time.perf_counter()
    fetch(kernel(seg_arrays, q_inputs))  # compile
    compile_s = time.perf_counter() - t0
    _log(f"compiled ({compile_s:.0f}s); timing")
    # beyond ~10s/query the 3-repeat marginal-batch protocol outlasts
    # practical windows; one repeat of a leaner batch pair still
    # subtracts the fixed dispatch RTT (PINOT_TPU_NS_FAST=1)
    fast = os.environ.get("PINOT_TPU_NS_FAST") == "1"
    repeats, warm = (1, 1) if fast else (3, 3)
    run_batch(warm)
    m_small, m_large = (1, 1 + max(iters, 1)) if fast else (3, 3 + iters)
    diffs = []
    for _ in range(repeats):
        t_large = run_batch(m_large)
        t_small = run_batch(m_small)
        diffs.append((t_large - t_small) / (m_large - m_small))
    per_query_s = max(sorted(diffs)[len(diffs) // 2], 1e-9)

    out = {
        "workload": "adevents_hll_groupby",
        "pql": HLL_PQL,
        "total_rows": total_rows,
        "num_segments": n_segments,
        "distinct_segments": len(distinct_segs),
        "global_user_card": ctx.column("user_id").global_cardinality,
        "rows_per_sec": round(total_rows / per_query_s, 1),
        "per_query_ms": round(per_query_s * 1000, 3),
        "staged_hbm_bytes": hbm_bytes,
        "staged_hbm_gib": round(hbm_bytes / (1 << 30), 3),
        "datagen_s": round(gen_s, 1),
        "stage_s": round(stage_s, 1),
        "compile_s": round(compile_s, 1),
    }

    _log(f"kernel phase done: {out['rows_per_sec']:,.0f} rows/s")
    if aux_paths:
        # broker-path p50 on the same table (executor path end to end)
        ex = QueryExecutor()
        req = optimize_request(parse_pql(HLL_PQL))

        def one(r):
            return reduce_to_response(r, [ex.execute(segments, r)])

        one(req)
        times = []
        for _ in range(7):
            t0 = time.perf_counter()
            one(req)
            times.append((time.perf_counter() - t0) * 1000)
        out["executor_p50_ms"] = round(sorted(times)[len(times) // 2], 1)
        _log(f"executor p50 {out['executor_p50_ms']}ms; host-fallback next")

        # >=2^20-group HOST-FALLBACK path: group by the high-card column
        # itself (cap = global user card > MAX_GROUP_CAPACITY)
        req_hf = optimize_request(
            parse_pql(
                "SELECT count(*) FROM adevents GROUP BY user_id TOP 10"
            )
        )
        t0 = time.perf_counter()
        resp = one(req_hf)
        out["host_fallback_groups_s"] = round(time.perf_counter() - t0, 1)
        out["host_fallback_ok"] = not resp.exceptions
        _log(f"host fallback done ({out['host_fallback_groups_s']}s); sort-pairs next")

        # device SORT-PAIRS exact distinct at north-star cardinality
        req_sp = optimize_request(
            parse_pql(
                "SELECT distinctcount(user_id) FROM adevents "
                "GROUP BY site_id TOP 10"
            )
        )
        t0 = time.perf_counter()
        resp = one(req_sp)
        out["sort_pairs_distinct_s"] = round(time.perf_counter() - t0, 1)
        out["sort_pairs_ok"] = not resp.exceptions
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("-rows", type=int, default=134_217_728)
    ap.add_argument("-rows-per-segment", type=int, default=8_388_608, dest="rps")
    ap.add_argument("-distinct", type=int, default=4)
    ap.add_argument("-iters", type=int, default=10)
    ap.add_argument("-paths", action="store_true", help="also time host-fallback + sort-pairs + executor p50")
    ap.add_argument("-out", type=str, default="", help="also write the JSON document here")
    args = ap.parse_args()
    import jax

    result = run(args.rows, args.rps, args.distinct, args.iters, args.paths)
    result["platform"] = jax.devices()[0].platform
    text = json.dumps(result)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")


if __name__ == "__main__":
    main()
